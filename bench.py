"""Benchmark: aggregate BLS signature verification throughput per chip.

Workload (BASELINE.json north star): FastAggregateVerify over attestation
committees — the hot loop of process_attestation
(reference specs/phase0/beacon-chain.md:1742-1756, :719-735). A mainnet epoch
is 32 slots x 64 committees = 2048 aggregate verifications covering ~300k
attesting validators; the target is that epoch in < 2 s on a v5e-8, i.e.
~150k signatures/sec/pod = ~18.75k signatures/sec/chip.

`vs_baseline` is the ratio of measured signatures/sec/chip to the
single-chip north-star share (the reference publishes no numbers of its own
— BASELINE.md documents that absence).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env overrides: BENCH_N (verifications per batch), BENCH_K (signers per
committee), BENCH_REPS.
"""
import json
import os
import time


def main():
    n = int(os.environ.get("BENCH_N", "32"))
    k = int(os.environ.get("BENCH_K", "128"))
    reps = int(os.environ.get("BENCH_REPS", "2"))

    from consensus_specs_tpu.ops import bls_backend
    from consensus_specs_tpu.utils import bls

    privkeys = [i + 1 for i in range(k)]
    pubkeys = [bls.SkToPk(sk) for sk in privkeys]

    pubkey_sets, messages, signatures = [], [], []
    for i in range(n):
        msg = i.to_bytes(32, "little")
        sigs = [bls.Sign(sk, msg) for sk in privkeys]
        pubkey_sets.append(pubkeys)
        messages.append(msg)
        signatures.append(bls.Aggregate(sigs))

    # warmup: compiles the VM shape buckets (persistent-cached across runs)
    got = bls_backend.batch_fast_aggregate_verify(
        pubkey_sets[:1], messages[:1], signatures[:1]
    )
    assert bool(got[0]), "warmup verification failed"

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        got = bls_backend.batch_fast_aggregate_verify(
            pubkey_sets, messages, signatures
        )
        dt = time.perf_counter() - t0
        assert got.all(), "benchmark verification failed"
        best = min(best, dt)

    sigs_per_sec = (n * k) / best
    target_per_chip = 150_000 / 8  # north star: 300k sigs < 2 s on 8 chips
    print(
        json.dumps(
            {
                "metric": "aggregate BLS signatures verified/sec/chip",
                "value": round(sigs_per_sec, 2),
                "unit": "signatures/sec",
                "vs_baseline": round(sigs_per_sec / target_per_chip, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
