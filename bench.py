"""Benchmark: aggregate BLS signature verification throughput per chip.

Workload (BASELINE.json north star): FastAggregateVerify over attestation
committees — the hot loop of process_attestation
(reference specs/phase0/beacon-chain.md:1742-1756, :719-735). A mainnet epoch
is 32 slots x 64 committees = 2048 aggregate verifications covering ~300k
attesting validators; the target is that epoch in < 2 s on a v5e-8, i.e.
~150k signatures/sec/pod = ~18.75k signatures/sec/chip.

`vs_baseline` is the ratio of measured signatures/sec/chip to the
single-chip north-star share (the reference publishes no numbers of its own
— BASELINE.md documents that absence).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} (+ a
"platform" note, and an "error" key instead of a traceback on failure).

Robustness: the configured JAX platform (e.g. a TPU tunnel) may be
unreachable; a bench that dies with a traceback produces no signal at all.
So we probe backend initialization in a subprocess with a timeout first,
and fall back to CPU if the probe fails — a CPU number with a note beats
no number.

Env overrides: BENCH_N (verifications per batch), BENCH_K (signers per
committee), BENCH_REPS, BENCH_PROBE_TIMEOUT (seconds).
"""
import json
import os
import subprocess
import sys
import time


def _probe_backend(timeout: float) -> str | None:
    """Initialize the configured JAX backend in a throwaway subprocess.

    Returns the platform name on success, None on failure/timeout — without
    poisoning this process (a failed in-process init can leave jax wedged).
    """
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            capture_output=True,
            timeout=timeout,
            env=os.environ.copy(),
        )
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0:
        return None
    name = out.stdout.decode().strip().splitlines()
    return name[-1] if name else None


def _emit(value: float, vs_baseline: float, **extra) -> None:
    line = {
        "metric": "aggregate BLS signatures verified/sec/chip",
        "value": round(value, 2),
        "unit": "signatures/sec",
        "vs_baseline": round(vs_baseline, 4),
    }
    line.update(extra)
    print(json.dumps(line))


def main():
    n = int(os.environ.get("BENCH_N", "32"))
    k = int(os.environ.get("BENCH_K", "128"))
    reps = int(os.environ.get("BENCH_REPS", "2"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "180"))

    platform = _probe_backend(probe_timeout)
    if platform is None:
        # Configured backend (e.g. a TPU tunnel) failed to initialize within
        # the timeout; fall back to host CPU so the bench still reports.
        platform = f"cpu (fallback; {os.environ.get('JAX_PLATFORMS', 'default')!r} backend init failed)"
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()

    from consensus_specs_tpu.ops import bls_backend
    from consensus_specs_tpu.utils import bls

    privkeys = [i + 1 for i in range(k)]
    pubkeys = [bls.SkToPk(sk) for sk in privkeys]

    pubkey_sets, messages, signatures = [], [], []
    for i in range(n):
        msg = i.to_bytes(32, "little")
        sigs = [bls.Sign(sk, msg) for sk in privkeys]
        pubkey_sets.append(pubkeys)
        messages.append(msg)
        signatures.append(bls.Aggregate(sigs))

    # warmup: compiles the VM shape buckets (persisted via the XLA
    # compilation-cache dir configured above)
    got = bls_backend.batch_fast_aggregate_verify(
        pubkey_sets[:1], messages[:1], signatures[:1]
    )
    assert bool(got[0]), "warmup verification failed"

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        got = bls_backend.batch_fast_aggregate_verify(
            pubkey_sets, messages, signatures
        )
        dt = time.perf_counter() - t0
        assert got.all(), "benchmark verification failed"
        best = min(best, dt)

    sigs_per_sec = (n * k) / best
    target_per_chip = 150_000 / 8  # north star: 300k sigs < 2 s on 8 chips
    _emit(
        sigs_per_sec,
        sigs_per_sec / target_per_chip,
        platform=platform,
        n=n,
        k=k,
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # emit a parseable diagnostic, never a bare traceback
        import traceback

        tb = traceback.format_exc().strip().splitlines()
        _emit(0.0, 0.0, error=f"{type(e).__name__}: {e}", error_tail=tb[-3:])
        sys.exit(0)
