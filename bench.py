"""Benchmark: aggregate BLS signature verification throughput per chip.

Workload (BASELINE.json north star): FastAggregateVerify over attestation
committees — the hot loop of process_attestation
(reference specs/phase0/beacon-chain.md:1742-1756, :719-735). A mainnet epoch
is 32 slots x 64 committees = 2048 aggregate verifications covering ~300k
attesting validators; the target is that epoch in < 2 s on a v5e-8, i.e.
~150k signatures/sec/pod = ~18.75k signatures/sec/chip.

`vs_baseline` is the ratio of measured signatures/sec/chip to the
single-chip north-star share (the reference publishes no numbers of its own
— BASELINE.md documents that absence).

Prints ONE final JSON line: {"metric", "value", "unit", "vs_baseline"} (+ a
"platform" note, and an "error" key instead of a traceback on failure).

Robustness contract (see TPU_NOTES.md for the axon-tunnel failure history):
the configured JAX platform may hang at backend init for many minutes, OR
initialize fine and then fail at the first device op, OR die partway
through a granted window. So: the ENTIRE accelerator attempt runs in a
subprocess under a deadline, and the child prints a refreshed JSON line
after setup, after the (compile-inclusive) warmup, and after every rep with
stdout flushed — the parent takes the BEST-throughput parseable success
line from the child's output (_best_line), INCLUDING the partial output
recovered when the deadline kills it. Any attempt with no usable line falls back to an in-process CPU
run that always emits a number, with the accelerator failure attached as
"tpu_error".

Modes: the accelerator child runs TWO stages in its single process —
committee mode at the fixed shape N=32,K=128 FIRST (the only
configuration proven to fit compile + 3 reps inside a 420 s window,
TPU_NOTES.md round-3 entry), emitting its final line, THEN the full epoch
replay (BASELINE config #4 — the north-star workload) with per-rep
emission. A granted window therefore always records at least the
committee number; the parent reports the best-throughput line and
attaches each mode's best. The CPU fallback runs committee mode at the
same fixed shape so CPU numbers trend round-over-round. Env overrides
always win and collapse the child to a single stage: BENCH_MODE
("committee" | "epoch"), BENCH_N, BENCH_K, BENCH_REPS,
BENCH_PROBE_TIMEOUT (seconds for the whole accelerator attempt).

`--mode serve` is separate from the committee/epoch machinery: it drives a
synthetic Poisson gossip load (duplicate-heavy, with an injected backend
failure) through the streaming VerificationService
(consensus_specs_tpu/serve/) in-process on CPU, and its JSON line carries
sustained signatures/sec plus the serving numbers — batch occupancy, cache
hit rate, p50/p95/p99 submit->result latency, and the prep-vs-device time
split per flush (knobs: SERVE_* env vars, see serve/load.py). Add
`--trace out.json` to record per-request spans (queue-wait/prep/device/
combine/finalize) + VM program executions and export Chrome trace-event
JSON (device-occupancy and flight-recorder lanes included when those
planes are armed); `--flight out.jsonl` arms the flight recorder
(obs/flight.py) and dumps its structured-event journal after the run;
SERVE_METRICS_PORT=<port|0> additionally serves Prometheus `/metrics` +
`/snapshot` + `/healthz` (now SLO-state-bearing) + `/flightdump` during
the run (obs/).

`--mode serve --mesh N` runs the same serve load with the verify plane
sharded over N virtual CPU devices (CONSENSUS_SPECS_TPU_MESH; the
micro-batch's Miller loops and RLC chunk ladders ride the mesh batch
axis, the combine's product folds cross-replica via the Fq12 ppermute
butterfly, and the flush still pays ONE final exponentiation).
`--mode serve-mesh` is the scaling sweep: one `--mode serve --mesh d`
child per device count (SERVE_MESH_DEVICES, default 1,2,4,8), emitting a
`mesh` section — per-count sigs/sec, per-device occupancy, mesh
fallbacks, efficiency vs single-device — that tools/bench_compare.py
gates on ok-state round over round (`make serve-bench-mesh`).

`--mode serve-fleet` is the multi-process fleet sweep (ISSUE 11): one
`serve/fleet.FleetRouter` fleet of REAL worker processes per worker
count (SERVE_FLEET_WORKERS, default 1,2,4), each worker core-pinned and
warmed at exactly the flush shapes its consistent-hash share of the
stream produces; the `fleet` JSON section carries aggregate sigs/sec per
count plus the merged-scrape exactness property (merged /metrics ==
exact merge of per-worker snapshots) and is state-gated round over
round by tools/bench_compare.py ("FLEET ERRORED"). The parent pays the
jax import (ops/__init__ loads it eagerly) but never does device work
or compiles — those happen only in the core-pinned workers.

`--mode codec` is the prep-only microbenchmark: the batched input codec
(ops/codec.py) vs the per-item pure-Python prep path, items/sec over
CODEC_ITEMS items per kind — no pairings, just the front-door cost.

`--mode rlc` is the final-exp microbenchmark: per-item easy+hard
finalization vs the random-linear-combination combine
(bls_backend.batch_verify_rlc's core) on identical Miller outputs,
items/sec across N in {4,16,64,256} (RLC_BENCH_* env).

`--mode sim` is the adversarial multi-node network simulation
(consensus_specs_tpu/sim/): every named scenario class — partition/heal,
latency skew, lossy links, equivocating proposals, withheld-block
orphans, long-range reorg attempts, censored aggregates — runs N
independent HeadService+VerificationService nodes over a deterministic
discrete-event gossip fabric, and the JSON line reports the matrix:
per-scenario convergence through the differential gate (every honest
head bit-identical to spec.get_head on the union view), partition
heal-to-convergence latency, per-node heads/sec, and the fault mix
(CONSENSUS_SPECS_TPU_SIM_* env knobs; the `sim` section is gated round
over round by tools/bench_compare.py — a newly diverging scenario fails).

`--mode soak` is the long-horizon telemetry soak (ISSUE 19,
consensus_specs_tpu/bench/soak.py): a thousand-plus-slot simnet
scenario (periodic partitions over a linear canonical chain) replayed
against real verdict-mode fleet workers, with a per-node
chain/health.py ledger observing every slot past warm-up, a sim-clock
obs/timeseries.py store recording the full gauge history, and the
stitched cross-process Chrome trace dumped at the end. The JSON line's
value is simulated slots/sec of wall time; `vs_baseline` is 1.0 iff the
health gate (participation floor, bounded finality lag, zero
unexplained reorgs) held on every node; the `health` section is
state-gated round over round by tools/bench_compare.py ("HEALTH
DIVERGED"). CONSENSUS_SPECS_TPU_SOAK_* env knobs size it.

`--mode proofs` is the light-client read-path bench
(consensus_specs_tpu/bench/proofs.py): 10^4-10^6 simulated clients
replayed against the ProofService — R distinct per-slot proof artifacts
(finality branch + next-sync-committee branch + signed LightClientUpdate,
every one verified through spec.validate_light_client_update AND
is_valid_merkle_branch against an independently re-Merkleized root)
behind the content-addressed (slot, state_root) cache. The JSON line's
value is proofs/sec; `vs_baseline` is the steady-state cache hit rate
(the >= 0.99 acceptance bar); the `proofs` section is state-gated round
over round by tools/bench_compare.py ("PROOFS DIVERGED" when a
previously-verified shape stops verifying). CONSENSUS_SPECS_TPU_PROOF_*
env knobs size it.

`--mode head` is the chain-plane bench: a synthetic fork-and-gossip
replay (consensus_specs_tpu/bench/head_replay.py) through the
HeadService + proto-array vs the spec-store `get_head` recompute, at
growing block-tree sizes (HEAD_TREE_SIZES). The JSON line's value is
proto-array heads/sec at the largest tree; `vs_baseline` is the measured
speedup over the spec path divided by the 10x acceptance bar; per-tree
numbers ride `per_mode_best` as `head[<blocks>]` keys so
tools/bench_compare.py diffs them round over round. Fault injection
(invalid-signature + withheld-block deferred gossip) comes from
serve/load.py; SERVE_METRICS_PORT exposes /metrics mid-replay and the
line records the `chain.*` scrape.
"""
import json
import os
import subprocess
import sys
import time

_CHILD_FLAG = "CONSENSUS_SPECS_TPU_BENCH_CHILD"


def _emit(value: float, vs_baseline: float, **extra) -> None:
    line = {
        "metric": "aggregate BLS signatures verified/sec/chip",
        "value": round(value, 2),
        "unit": "signatures/sec",
        "vs_baseline": round(vs_baseline, 4),
    }
    line.update(extra)
    print(json.dumps(line), flush=True)


def _emit_result(result: dict) -> None:
    _emit(result.pop("value"), result.pop("vs_baseline"), **result)


def _workload_params(on_cpu: bool, override=None):
    # the CPU fallback runs committee mode at the FIXED comparable shape
    # (N=32, K=128 — one mainnet slot's worth of committee checks) so
    # round-over-round CPU numbers trend; the accelerator child runs the
    # full epoch replay. Env overrides always win.
    if override is not None:
        return override
    return (
        int(os.environ.get("BENCH_N", "32")),
        int(os.environ.get("BENCH_K", "128")),
        # CPU default bumped to 3 reps (round 5): with the XLA executable
        # cache warm a committee rep is ~13 s, so a median-of-3 costs
        # little and stabilizes the round-over-round fallback number
        int(os.environ.get("BENCH_REPS", "3" if on_cpu else "2")),
        os.environ.get("BENCH_MODE", "committee" if on_cpu else "epoch"),
    )


TARGET_PER_CHIP = 150_000 / 8  # north star: 300k sigs < 2 s on 8 chips

# the stage-0 liveness shape (tiny committee: a nonzero number lands within
# ~a minute of any grant) — ONE constant shared by every emission site AND
# _best_line's headline demotion, so resizing it cannot silently let its
# inflated per-sig rate shadow the comparable 32x128 number again
_WARMUP_SHAPE = (4, 8)
_WARMUP_OVERRIDE = _WARMUP_SHAPE + (1, "committee")


def _bench_env_overridden() -> bool:
    """True when the caller pinned any workload knob — quick-path
    substitutions must then step aside (env overrides always win)."""
    return any(
        os.environ.get(v) is not None
        for v in ("BENCH_N", "BENCH_K", "BENCH_REPS", "BENCH_MODE")
    )


def run_workload(emit_partial=None, override=None, child_quick=False) -> dict:
    """Run the configured workload on whatever platform jax resolves to.
    Returns the final result dict (not yet printed); ``emit_partial`` is
    called with in-progress result dicts as they improve.

    ``child_quick``: the deadline-guarded child sets this so that a machine
    whose DEFAULT backend resolves to plain CPU (no accelerator plugin)
    answers quickly with a small shape instead of burning the whole child
    deadline on the ~20-min comparable shape. Env overrides still win."""
    import jax

    from consensus_specs_tpu.obs import programs as obs_programs
    from consensus_specs_tpu.ops import profiling

    # each workload/stage starts from clean accumulators: the child runs
    # committee THEN epoch in one process, and without a reset the first
    # mode's latencies/gauges would bleed into the next mode's attached
    # profile summary. The vm-cache gauges are re-published afterwards —
    # their note_assembly source fires only once per program per process
    profiling.reset()
    obs_programs.export_gauges()

    platform = jax.default_backend()
    if child_quick and platform == "cpu" and not _bench_env_overridden():
        override = _WARMUP_OVERRIDE
    n, k, reps, mode = _workload_params(on_cpu=platform == "cpu", override=override)

    if mode == "epoch":
        from consensus_specs_tpu.bench.epoch_replay import run_epoch_replay

        return run_epoch_replay(emit_partial=emit_partial)

    from consensus_specs_tpu.ops import bls_backend
    from consensus_specs_tpu.utils import bls

    from consensus_specs_tpu.utils.bls12_381 import R

    privkeys = [i + 1 for i in range(k)]
    pubkeys = [bls.SkToPk(sk) for sk in privkeys]
    # an aggregate of same-message signatures equals one signature by the
    # summed secret key — setup is n signs, not n*k
    agg_sk = sum(privkeys) % R

    pubkey_sets, messages, signatures = [], [], []
    for i in range(n):
        msg = i.to_bytes(32, "little")
        pubkey_sets.append(pubkeys)
        messages.append(msg)
        signatures.append(bls.Sign(agg_sk, msg))

    def result(value, **extra):
        out = dict(
            value=value,
            vs_baseline=value / TARGET_PER_CHIP,
            platform=platform,
            mode="committee",
            n=n,
            k=k,
        )
        out.update(extra)
        return out

    # warmup: compiles the VM shape buckets (persisted via the XLA
    # compilation cache); its compile-inclusive timing is still a valid
    # lower bound worth having if the window dies before rep 1
    t0 = time.perf_counter()
    got = bls_backend.batch_fast_aggregate_verify(
        pubkey_sets, messages, signatures
    )
    warm = time.perf_counter() - t0
    assert got.all(), "warmup verification failed"
    if emit_partial is not None:
        emit_partial(result(n * k / warm, stage="warmup (compile-inclusive)"))

    times = []
    for r in range(reps):
        t0 = time.perf_counter()
        got = bls_backend.batch_fast_aggregate_verify(
            pubkey_sets, messages, signatures
        )
        dt = time.perf_counter() - t0
        assert got.all(), "benchmark verification failed"
        times.append(dt)
        if emit_partial is not None:
            emit_partial(
                result(n * k / min(times), stage=f"rep {r + 1}/{reps}")
            )
    # median of reps: stabler than min against one lucky/cold rep
    times.sort()
    best = times[len(times) // 2] if times else warm

    final = result(n * k / best)
    if profiling.enabled():  # dynamic check: env flips after import count
        final["profile"] = profiling.summary()
        # per-program provenance: steps/regs/assembly source for every VM
        # program this run resolved — plus the vmlint analysis stats
        # (max_live, critical path, classification) when a vm_analysis
        # pass ran in this process (obs/programs.note_analysis)
        final["programs"] = obs_programs.registry_snapshot()["programs"]
    return final


def _init_backend_with_watchdog(exit_fn=None) -> bool:
    """Initialize the JAX backend under a deadline (BENCH_INIT_DEADLINE,
    default 150 s) and return True when it resolved to plain CPU.

    The axon tunnel's dominant failure mode is a backend-init block that
    lasts 9-25+ minutes before hanging or erroring (TPU_NOTES.md), while
    every observed GRANT initialized within seconds — so waiting out a
    slow init only burns the harvest loop's sampling rate (and, under the
    driver's 420 s child deadline, the CPU-fallback budget). A daemon
    watchdog flushes a parseable error line and hard-exits the child if
    init overruns; a live grant proceeds in THIS process untouched."""
    import threading

    deadline = float(os.environ.get("BENCH_INIT_DEADLINE", "150"))
    if exit_fn is None:
        exit_fn = os._exit
    done = threading.Event()

    def watchdog():
        if not done.wait(deadline):
            _emit(
                0.0,
                0.0,
                error=(
                    f"backend init exceeded {deadline:.0f}s "
                    "(tunnel hang; grants initialize in seconds)"
                ),
            )
            sys.stdout.flush()
            exit_fn(3)

    threading.Thread(target=watchdog, daemon=True).start()
    try:
        import jax

        return jax.default_backend() == "cpu"
    finally:
        done.set()


def _shape_key(parsed: dict) -> str:
    """per_mode_best key: committee lines carry their (n, k) shape so the
    stage-0 tiny shape and the round-over-round comparable 32x128 shape
    never share a slot (ADVICE round 5: keying by mode alone let the
    warmup shape shadow the headline committee number)."""
    mode = parsed.get("mode", "committee")
    n, k = parsed.get("n"), parsed.get("k")
    if mode == "committee" and n and k:
        return f"committee[{n}x{k}]"
    return mode


def _is_warmup_shape(parsed: dict) -> bool:
    return (
        parsed.get("mode", "committee") == "committee"
        and (parsed.get("n"), parsed.get("k")) == _WARMUP_SHAPE
    )


def _best_line(stdout_bytes: bytes):
    """Best-throughput success JSON line in the child's output, or
    (None, last-error-string). The child emits staged lines (tiny
    liveness committee shape, the comparable committee shape, then
    epoch); lines within a stage improve monotonically, so max-value
    across the lines is the best achieved number — except the stage-0
    4x8 liveness shape, which only becomes the headline when NOTHING
    else landed (its tiny padded batch posts absurd per-sig rates that
    would otherwise bury the comparable numbers). Per-shape bests are
    attached so the record shows the committee AND epoch numbers, not
    just the winner."""
    err = None
    best = None
    best_warmup = None
    probes = {}
    mode_best = {}
    for line in stdout_bytes.decode(errors="replace").strip().splitlines():
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if "probe" in parsed:
            probes[parsed["probe"]] = {
                k: v for k, v in parsed.items() if k != "probe"
            }
        elif "error" in parsed:
            err = parsed["error"]
        elif parsed.get("value", 0) > 0:
            if _is_warmup_shape(parsed):
                if best_warmup is None or parsed["value"] > best_warmup["value"]:
                    best_warmup = parsed
            elif best is None or parsed["value"] > best["value"]:
                best = parsed
            key = _shape_key(parsed)
            if parsed["value"] > mode_best.get(key, 0.0):
                mode_best[key] = parsed["value"]
    if best is None:
        best = best_warmup  # only the liveness pre-pass landed
    if best is not None:
        best = dict(best)
        if len(mode_best) > 1:
            best["per_mode_best"] = {m: round(v, 2) for m, v in mode_best.items()}
        if probes:
            best["probes"] = probes
    return best, err


def _run_child_attempt(timeout: float):
    """Run this script as a child with the inherited (accelerator) platform.
    Returns (parsed JSON dict | None, failure reason | None). A deadline
    kill still yields whatever partial lines the child flushed."""
    env = os.environ.copy()
    env[_CHILD_FLAG] = "1"
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True,
            timeout=timeout,
            env=env,
        )
        stdout, stderr, rc = out.stdout, out.stderr, out.returncode
        timed_out = False
    except subprocess.TimeoutExpired as e:
        stdout = e.stdout or b""
        stderr = e.stderr or b""
        rc, timed_out = -1, True

    best, err = _best_line(stdout)
    if best is not None:
        if timed_out:
            best["note"] = (
                f"deadline ({timeout:.0f}s) hit; value is the best rep "
                "completed before the kill"
            )
        if err is not None:
            # a later stage errored AFTER this value landed (e.g. a rep's
            # verification assert) — surface it, never silently swallow
            best["error_after_partial"] = err[:300]
        return best, None
    if timed_out:
        return None, (
            f"accelerator attempt exceeded {timeout:.0f}s with no completed "
            "stage (backend-init hang, or setup/compile slower than the "
            "deadline)"
        )
    if err is not None:
        return None, err
    err_tail = stderr.decode(errors="replace").strip().splitlines()[-3:]
    return None, f"accelerator attempt rc={rc}: {' | '.join(err_tail)}"


def _cli_opt(name):
    """`<name> <v>` / `<name>=<v>` from argv."""
    argv = sys.argv[1:]
    for i, arg in enumerate(argv):
        if arg == name and i + 1 < len(argv):
            return argv[i + 1]
        if arg.startswith(name + "="):
            return arg.split("=", 1)[1]
    return None


def _cli_mode():
    """`--mode <m>` / `--mode=<m>` from argv."""
    return _cli_opt("--mode")


def main():
    if _cli_mode() == "serve":
        # streaming serve-plane bench, in-process and CPU-forced: the
        # deadline-guarded child exists because in-process accelerator
        # attempts can hang for minutes (TPU_NOTES.md), and the serve
        # line's value is the service-layer numbers (occupancy, cache hit
        # rate, latency percentiles) on a CPU-sized load — SERVE_* env
        # vars scale it up inside a granted window
        # `--trace out.json` turns on the span tracer for the whole run
        # and exports Chrome trace-event JSON (pipeline spans + VM program
        # executions + per-program registry) after the load completes
        # `--flight out.jsonl` arms the flight recorder for the run and
        # dumps its JSONL journal afterwards (the on-demand forensic dump;
        # the recorder also auto-dumps on a serve-plane fault)
        trace_path = _cli_opt("--trace")
        flight_path = _cli_opt("--flight")
        if trace_path:
            os.environ["CONSENSUS_SPECS_TPU_TRACE"] = "1"
        if flight_path:
            os.environ["CONSENSUS_SPECS_TPU_FLIGHT"] = "1"
        from consensus_specs_tpu.utils.jax_env import force_cpu

        # `--mesh N` shards the service's verify plane over N virtual CPU
        # devices (must be requested BEFORE backend init — XLA reads the
        # host-device-count flag once); the env makes the service's
        # construction-time mesh provider pick it up
        mesh_opt = _cli_opt("--mesh")
        if mesh_opt:
            os.environ["CONSENSUS_SPECS_TPU_MESH"] = mesh_opt
            force_cpu(n_devices=max(1, int(mesh_opt)))
        else:
            force_cpu()
        from consensus_specs_tpu.serve.load import run_serve_bench

        result = run_serve_bench()
        if trace_path:
            from consensus_specs_tpu.obs import tracing

            result["trace"] = tracing.dump_trace(trace_path)
            # monotone count (NOT the ring length): a scaled run traces
            # more requests than the ring retains spans for
            result["trace_requests"] = tracing.global_tracer().finished_total()
        if flight_path:
            from consensus_specs_tpu.obs import flight

            rec = flight.global_recorder()
            result["flight"] = rec.dump(flight_path, reason="bench_flight")
            result["flight_events"] = rec.counters()["events"]
        _emit_result(result)
        return

    if _cli_mode() == "serve-mesh":
        # mesh scaling sweep: one serve-bench child per device count (the
        # virtual-device count is frozen at backend init, so counts can't
        # share a process); the parent does no device work. The `mesh`
        # section is gated round-over-round by tools/bench_compare.py —
        # a device count that verified and now errors fails the round.
        from consensus_specs_tpu.serve.load import run_serve_mesh_sweep

        _emit_result(run_serve_mesh_sweep())
        return

    if _cli_mode() == "serve-fleet":
        # multi-process fleet scaling sweep (ISSUE 11): one FleetRouter
        # per worker count, real worker PROCESSES (each its own GIL/XLA
        # client), aggregate sigs/sec + the merged-scrape exactness
        # property in a `fleet` section gated state-wise by
        # tools/bench_compare.py. The parent imports jax (ops/__init__
        # is eager) but all device work happens in the workers.
        from consensus_specs_tpu.bench.fleet_sweep import run_fleet_bench

        _emit_result(run_fleet_bench())
        return

    if _cli_mode() == "codec":
        # prep-only microbench: batched input codec vs per-item host prep
        # (decode + subgroup + hash-to-G2, no pairings). CPU-forced — the
        # acceptance bar is the codec's host fallback beating the
        # per-item path on plain CPU; CODEC_ITEMS sizes the batch
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()
        from consensus_specs_tpu.bench.codec_prep import run_codec_bench

        _emit_result(run_codec_bench())
        return

    if _cli_mode() == "head":
        # chain-plane replay: proto-array vs spec-store get_head. CPU-
        # forced — the acceptance bar is the maintained pointer beating
        # the spec recompute >= 10x at the largest tree on plain CPU
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()
        from consensus_specs_tpu.bench.head_replay import run_head_bench

        _emit_result(run_head_bench())
        return

    if _cli_mode() == "sim":
        # adversarial multi-node simulation: N HeadService nodes over the
        # discrete-event gossip fabric, scenario matrix + convergence
        # gate. CPU-forced — the thing measured is the consensus plane
        # under network faults, not device math
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()
        from consensus_specs_tpu.bench.sim_matrix import run_sim_bench

        _emit_result(run_sim_bench())
        return

    if _cli_mode() == "proofs":
        # light-client read path (ISSUE 16): per-slot proof artifacts
        # served content-addressed to 10^4+ simulated clients, every one
        # verified (validate_light_client_update + is_valid_merkle_branch
        # against a re-Merkleized root). CPU-forced — the thing measured
        # is proof construction + cache economics, not device math. The
        # `proofs` section is state-gated round over round by
        # tools/bench_compare.py ("PROOFS DIVERGED").
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()
        from consensus_specs_tpu.bench.proofs import run_proofs_bench

        _emit_result(run_proofs_bench())
        return

    if _cli_mode() == "soak":
        # long-horizon telemetry soak (ISSUE 19): a thousand-plus-slot
        # simnet scenario against the real fleet deployment shape, a
        # per-node health ledger observing every slot, a sim-clock TSDB
        # recording the history, and the stitched cross-process Chrome
        # trace at the end. CPU-forced and crypto-free (verdict-mode
        # workers) — the thing measured is the telemetry plane and
        # fork-choice health over time, not device math. The `health`
        # section is state-gated round over round by
        # tools/bench_compare.py ("HEALTH DIVERGED" when a previously
        # green gate goes red).
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()
        from consensus_specs_tpu.bench.soak import run_soak_bench

        _emit_result(run_soak_bench())
        return

    if _cli_mode() == "merkle":
        # Merkleization plane race (ISSUE 18): the native batched
        # hash_tree_root path (one sha256_hash_many call per tree level,
        # incremental dirty-set re-roots) vs the pure-python oracle on
        # identical states — full-state cold root, per-block incremental
        # re-root, and the proof-world artifact build. CPU-forced — the
        # thing measured is the host Merkleization plane, not device
        # math. Every cell checks bit-identity; the `merkle` section is
        # state-gated round over round by tools/bench_compare.py
        # ("MERKLE DIVERGED" when a cell's roots stop matching).
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()
        from consensus_specs_tpu.bench.merkle import run_merkle_bench

        _emit_result(run_merkle_bench())
        return

    if _cli_mode() == "mainnet":
        # mainnet-scale workload replay (ISSUE 20): full mainnet-shape
        # slots over a synthetic million-validator registry —
        # mainnet-preset committee shuffling, hierarchical
        # aggregate-of-aggregates verification folding every committee
        # of a slot into ONE final exp, the bytes-budgeted pubkey plane
        # holding decompressed keys under RSS budget, a forced bad
        # committee localized by bisection, simnet's censored_aggregates
        # at mainnet committee fan-out through the strict convergence
        # gate, and committee-affinity fleet routing. CPU-forced; the
        # `mainnet` section is state-gated round over round by
        # tools/bench_compare.py ("MAINNET DIVERGED" — verdict identity
        # or a gate flipping ok True→False fails the round;
        # attestations/sec is report-only).
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()
        from consensus_specs_tpu.bench.mainnet import run_mainnet_bench

        _emit_result(run_mainnet_bench())
        return

    if _cli_mode() == "latency":
        # end-to-end gossip→head latency matrix (ISSUE 12): latency_skew
        # and lossy_links simnet scenarios, each under the classic
        # size-or-deadline flush, the slot-budget deadline scheduler, and
        # deadline+speculative head application — gossip_to_head_p99 per
        # scenario with the deadline-flush win quantified. CPU-forced —
        # the thing measured is flush scheduling and fork-choice latency,
        # not device math. The `latency` section is state-gated round
        # over round by tools/bench_compare.py ("LATENCY SLO VIOLATED").
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()
        from consensus_specs_tpu.bench.latency_pipeline import (
            run_latency_bench,
        )

        _emit_result(run_latency_bench())
        return

    if _cli_mode() == "vmexec":
        # VM execution-backend race (ISSUE 13): the scan interpreter vs
        # the fused straight-line lowering (ops/vm_compile.py) on
        # identical assembled programs, warm ms/row + trace/compile time
        # per (kind, rows) cell, bit-identity checked per cell.
        # CPU-forced; the `vmexec` section is state-gated round over
        # round by tools/bench_compare.py ("VMEXEC ERRORED" — a kind
        # losing its fused backend or the backends disagreeing bitwise
        # fails the round; ms/row movement is report-only). Running this
        # bench also persists each program's measured winner into its
        # .vm_cache plan — the verdict CONSENSUS_SPECS_TPU_VM_EXEC=auto
        # adopts for shapes a warm/pinned call has compiled.
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()
        from consensus_specs_tpu.bench.vmexec import run_vmexec_bench

        _emit_result(run_vmexec_bench())
        return

    if _cli_mode() == "finalexp":
        # hard-part microbench (ISSUE 10): host-oracle HHT vs the VM
        # hard-part variants (bit_serial, windowed, frobenius) at
        # pipelined rows {1,2,4,8}, plus the vmlint critical-path ratios
        # and the bucketed-vs-legacy assembler race on the chunk-16
        # rlc_combine. CPU-forced; the `finalexp` section is state-gated
        # round over round by tools/bench_compare.py (an errored variant
        # fails the round; a device cell slower than host is report-only)
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()
        from consensus_specs_tpu.bench.finalexp import run_finalexp_bench

        _emit_result(run_finalexp_bench())
        return

    if _cli_mode() == "rlc":
        # final-exp microbench: per-item easy+hard vs the RLC combine on
        # identical Miller outputs, items/sec across N in {4,16,64,256}.
        # CPU-forced — the acceptance bar is RLC beating the per-item
        # path at N >= 16 on plain CPU; RLC_BENCH_* env sizes it
        from consensus_specs_tpu.utils.jax_env import force_cpu

        force_cpu()
        from consensus_specs_tpu.bench.rlc_final import run_rlc_bench

        _emit_result(run_rlc_bench())
        return

    if os.environ.get(_CHILD_FLAG) == "1":
        # child: run on the inherited platform, flushing a refreshed JSON
        # line at every stage; a crash/device error becomes a JSON error
        # line for the parent to parse. Without an env override this runs
        # TWO stages in THIS process (a tunnel grant can evaporate between
        # process launches, TPU_NOTES.md round-4 entry): committee mode at
        # the window-proven fixed shape first, then the epoch workload.
        if _bench_env_overridden():
            try:
                result = run_workload(emit_partial=_emit_result, child_quick=True)
                _emit_result(result)
            except Exception as e:
                _emit(0.0, 0.0, error=f"{type(e).__name__}: {e}")
            return
        try:
            on_plain_cpu = _init_backend_with_watchdog()
        except Exception as e:
            _emit(0.0, 0.0, error=f"backend init {type(e).__name__}: {e}")
            return
        if on_plain_cpu:
            # no accelerator plugin resolved — answer fast so the parent's
            # deadline isn't burned on the ~20-min comparable CPU shape
            try:
                _emit_result(run_workload(override=_WARMUP_OVERRIDE))
            except Exception as e:
                _emit(0.0, 0.0, error=f"{type(e).__name__}: {e}")
            return
        for stage_override in (
            # stage 0: tiny shape — its small-bucket program compiles in
            # well under a minute, so a nonzero TPU number lands almost
            # immediately after any grant (the round-3 "compile + 3 reps
            # < 420 s" proof predates lane folding; the folded committee
            # program's TPU compile time is unmeasured)
            _WARMUP_OVERRIDE,
            (32, 128, 3, "committee"),  # the round-over-round fixed shape
            (0, 0, 1, "epoch"),  # north-star workload; per-rep emission
        ):
            try:
                _emit_result(
                    run_workload(emit_partial=_emit_result, override=stage_override)
                )
            except Exception as e:
                _emit(
                    0.0,
                    0.0,
                    error=f"{stage_override[3]} stage {type(e).__name__}: {e}",
                )
        # stage 3: the Pallas kernel A/Bs (SURVEY §7.3 risks #1-#2) in the
        # SAME process — the grant that landed the numbers above also
        # answers the kernel-dispatch questions: raw mont_mul vs the u64
        # lowering, then the whole-VM-program race across all three
        # dispatch modes. Failures are probe_error lines, never workload
        # errors.
        for probe_name, fn_name in (
            ("pallas_ab", "run_pallas_ab"),
            ("vm_step_ab", "run_step_ab"),
        ):
            try:
                # import inside the guard: an import-time failure must
                # also become a probe_error line, never a child crash
                from consensus_specs_tpu.bench import pallas_ab

                probe_fn = getattr(pallas_ab, fn_name)
                print(
                    json.dumps({"probe": probe_name, **probe_fn()}), flush=True
                )
            except Exception as e:
                print(
                    json.dumps(
                        {
                            "probe": probe_name,
                            "probe_error": f"{type(e).__name__}: {e}"[:300],
                        }
                    ),
                    flush=True,
                )
        return

    # Attempt the configured/default platform in a deadline-guarded child
    # unless CPU is explicitly forced. With JAX_PLATFORMS unset, a plugin
    # registered by sitecustomize may still be the default backend — the
    # child discovers it; a healthy CPU default also succeeds in the child.
    platform_env = os.environ.get("JAX_PLATFORMS", "")
    tpu_error = None
    if platform_env != "cpu":
        timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "420"))
        parsed, tpu_error = _run_child_attempt(timeout)
        if parsed is not None:
            print(json.dumps(parsed), flush=True)
            return

    # CPU fallback (or CPU-configured run): always emits a number. The
    # comparable committee shape takes ~10 min on a host core, so a tiny
    # liveness pre-pass (~30 s) lands a parseable line first — an external
    # deadline on bench.py itself then still leaves JSON on stdout — and
    # partial lines are flushed as the heavy run's reps complete.
    from consensus_specs_tpu.utils.jax_env import force_cpu

    force_cpu()
    _, _, _, mode = _workload_params(on_cpu=True)
    if mode == "committee" and not _bench_env_overridden():
        quick = run_workload(override=_WARMUP_OVERRIDE)
        quick["stage"] = ("fallback liveness pre-pass "
                          f"(n={_WARMUP_SHAPE[0]}, k={_WARMUP_SHAPE[1]})")
        if tpu_error is not None:
            quick["platform"] = "cpu (fallback)"
            quick["tpu_error"] = tpu_error[:500]
        _emit_result(quick)
    result = run_workload(emit_partial=_emit_result)
    if tpu_error is not None:
        result["platform"] = "cpu (fallback)"
        result["tpu_error"] = tpu_error[:500]
    _emit_result(result)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # emit a parseable diagnostic, never a bare traceback
        import traceback

        tb = traceback.format_exc().strip().splitlines()
        _emit(0.0, 0.0, error=f"{type(e).__name__}: {e}", error_tail=tb[-3:])
        sys.exit(0)
