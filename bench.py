"""Benchmark: aggregate BLS signature verification throughput per chip.

Workload (BASELINE.json north star): FastAggregateVerify over attestation
committees — the hot loop of process_attestation
(reference specs/phase0/beacon-chain.md:1742-1756, :719-735). A mainnet epoch
is 32 slots x 64 committees = 2048 aggregate verifications covering ~300k
attesting validators; the target is that epoch in < 2 s on a v5e-8, i.e.
~150k signatures/sec/pod = ~18.75k signatures/sec/chip.

`vs_baseline` is the ratio of measured signatures/sec/chip to the
single-chip north-star share (the reference publishes no numbers of its own
— BASELINE.md documents that absence).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} (+ a
"platform" note, and an "error" key instead of a traceback on failure).

Robustness contract (see TPU_NOTES.md for the axon-tunnel failure history):
the configured JAX platform may hang at backend init for many minutes, OR
initialize fine and then fail at the first device op ("TPU backend
setup/compile error"). Probe-then-run is not safe against the second mode,
so the ENTIRE accelerator attempt runs in a subprocess under a deadline;
any outcome other than a parseable success JSON (hang, crash, device error,
nonzero exit) falls back to an in-process CPU run that always emits a
number, with the accelerator failure attached as "tpu_error".

Env overrides: BENCH_N (verifications per batch), BENCH_K (signers per
committee), BENCH_REPS, BENCH_PROBE_TIMEOUT (seconds for the whole
accelerator attempt), BENCH_MODE ("committee" | "epoch").
"""
import json
import os
import subprocess
import sys
import time

_CHILD_FLAG = "CONSENSUS_SPECS_TPU_BENCH_CHILD"


def _emit(value: float, vs_baseline: float, **extra) -> None:
    line = {
        "metric": "aggregate BLS signatures verified/sec/chip",
        "value": round(value, 2),
        "unit": "signatures/sec",
        "vs_baseline": round(vs_baseline, 4),
    }
    line.update(extra)
    print(json.dumps(line))


def _workload_params(on_cpu: bool):
    # the CPU fallback keeps the workload SHAPE but shrinks the axes: the
    # full 32x128 committee batch takes tens of minutes through the scan VM
    # on a host core, which would blow any driver deadline without ever
    # emitting the JSON line (env overrides always win)
    return (
        int(os.environ.get("BENCH_N", "4" if on_cpu else "32")),
        int(os.environ.get("BENCH_K", "8" if on_cpu else "128")),
        int(os.environ.get("BENCH_REPS", "2" if on_cpu else "3")),
        os.environ.get("BENCH_MODE", "committee"),
    )


TARGET_PER_CHIP = 150_000 / 8  # north star: 300k sigs < 2 s on 8 chips


def run_workload() -> dict:
    """Run the configured workload on whatever platform jax resolves to.
    Returns the result dict (not yet printed)."""
    import jax

    platform = jax.default_backend()
    n, k, reps, mode = _workload_params(on_cpu=platform == "cpu")

    if mode == "epoch":
        from consensus_specs_tpu.bench.epoch_replay import run_epoch_replay

        return run_epoch_replay()

    from consensus_specs_tpu.ops import bls_backend
    from consensus_specs_tpu.utils import bls

    from consensus_specs_tpu.utils.bls12_381 import R

    privkeys = [i + 1 for i in range(k)]
    pubkeys = [bls.SkToPk(sk) for sk in privkeys]
    # an aggregate of same-message signatures equals one signature by the
    # summed secret key — setup is n signs, not n*k
    agg_sk = sum(privkeys) % R

    pubkey_sets, messages, signatures = [], [], []
    for i in range(n):
        msg = i.to_bytes(32, "little")
        pubkey_sets.append(pubkeys)
        messages.append(msg)
        signatures.append(bls.Sign(agg_sk, msg))

    # warmup: compiles the VM shape buckets (persisted via the XLA
    # compilation cache)
    got = bls_backend.batch_fast_aggregate_verify(
        pubkey_sets[:1], messages[:1], signatures[:1]
    )
    assert bool(got[0]), "warmup verification failed"

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        got = bls_backend.batch_fast_aggregate_verify(
            pubkey_sets, messages, signatures
        )
        dt = time.perf_counter() - t0
        assert got.all(), "benchmark verification failed"
        times.append(dt)
    # median of reps: stabler than min against one lucky/cold rep
    times.sort()
    best = times[len(times) // 2]

    sigs_per_sec = (n * k) / best
    result = dict(
        value=sigs_per_sec,
        vs_baseline=sigs_per_sec / TARGET_PER_CHIP,
        platform=platform,
        n=n,
        k=k,
    )
    if os.environ.get("CONSENSUS_SPECS_TPU_PROFILE") == "1":
        from consensus_specs_tpu.ops import profiling

        result["profile"] = profiling.summary()
    return result


def _run_child_attempt(timeout: float):
    """Run this script as a child with the inherited (accelerator) platform.
    Returns the parsed JSON dict on success, else (None, reason)."""
    env = os.environ.copy()
    env[_CHILD_FLAG] = "1"
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True,
            timeout=timeout,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None, (
            f"accelerator attempt exceeded {timeout:.0f}s "
            "(backend-init hang, or setup/compile slower than the deadline)"
        )
    tail_lines = out.stdout.decode(errors="replace").strip().splitlines()
    for line in reversed(tail_lines):
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if "error" in parsed:
            return None, parsed["error"]
        if parsed.get("value", 0) > 0:
            return parsed, None
    err_tail = out.stderr.decode(errors="replace").strip().splitlines()[-3:]
    return None, f"accelerator attempt rc={out.returncode}: {' | '.join(err_tail)}"


def main():
    if os.environ.get(_CHILD_FLAG) == "1":
        # child: run on the inherited platform; a crash/device error becomes
        # a JSON error line for the parent to parse
        try:
            result = run_workload()
            _emit(result.pop("value"), result.pop("vs_baseline"), **result)
        except Exception as e:
            _emit(0.0, 0.0, error=f"{type(e).__name__}: {e}")
        return

    # Attempt the configured/default platform in a deadline-guarded child
    # unless CPU is explicitly forced. With JAX_PLATFORMS unset, a plugin
    # registered by sitecustomize may still be the default backend — the
    # child discovers it; a healthy CPU default also succeeds in the child.
    platform_env = os.environ.get("JAX_PLATFORMS", "")
    tpu_error = None
    if platform_env != "cpu":
        timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "420"))
        parsed, tpu_error = _run_child_attempt(timeout)
        if parsed is not None:
            print(json.dumps(parsed))
            return

    # CPU fallback (or CPU-configured run): always emits a number
    from consensus_specs_tpu.utils.jax_env import force_cpu

    force_cpu()
    result = run_workload()
    if tpu_error is not None:
        result["platform"] = "cpu (fallback)"
        result["tpu_error"] = tpu_error[:500]
    _emit(result.pop("value"), result.pop("vs_baseline"), **result)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # emit a parseable diagnostic, never a bare traceback
        import traceback

        tb = traceback.format_exc().strip().splitlines()
        _emit(0.0, 0.0, error=f"{type(e).__name__}: {e}", error_tail=tb[-3:])
        sys.exit(0)
