/* Batched SHA-256 for SSZ merkleization — the native runtime component
 * backing hash_tree_root throughput (SURVEY.md §7.3 hard part #6; the
 * reference's native plane is its C BLS binding, utils/bls.py:17-22).
 *
 * API (ctypes, see consensus_specs_tpu/utils/native_sha256.py):
 *   void sha256_hash_pairs(const uint8_t* in, uint8_t* out, size_t n)
 *     - hashes n independent 64-byte messages (pairs of 32-byte tree nodes)
 *       into n 32-byte digests: one C call per MERKLE LAYER instead of one
 *       Python hashlib call per node pair. Every message is exactly one
 *       data block + one constant padding block, so the whole layer runs
 *       without branching or allocation.
 *   void sha256_hash_many(const uint8_t* in, const uint64_t* lens,
 *                         uint8_t* out, size_t n)
 *     - hashes n independent VARIABLE-length messages (concatenated in
 *       `in`, per-message byte lengths in `lens`) into n 32-byte digests:
 *       one C call per expand_message_xmd round for a whole hash-to-G2
 *       batch (the input codec plane, consensus_specs_tpu/ops/codec.py).
 *
 * Build: make native (gcc -O3 -fPIC -shared).
 */
#include <stdint.h>
#include <stddef.h>
#include <string.h>

static const uint32_t K[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,
    0x923f82a4,0xab1c5ed5,0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,
    0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,0xe49b69c1,0xefbe4786,
    0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,
    0x06ca6351,0x14292967,0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,
    0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,0xa2bfe8a1,0xa81a664b,
    0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,
    0x5b9cca4f,0x682e6ff3,0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,
    0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2
};

#define ROR(x,n) (((x) >> (n)) | ((x) << (32 - (n))))

static void compress(uint32_t state[8], const uint8_t block[64]) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)block[4*i] << 24) | ((uint32_t)block[4*i+1] << 16)
             | ((uint32_t)block[4*i+2] << 8) | block[4*i+3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = ROR(w[i-15], 7) ^ ROR(w[i-15], 18) ^ (w[i-15] >> 3);
        uint32_t s1 = ROR(w[i-2], 17) ^ ROR(w[i-2], 19) ^ (w[i-2] >> 10);
        w[i] = w[i-16] + s0 + w[i-7] + s1;
    }
    uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
    uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = ROR(e, 6) ^ ROR(e, 11) ^ ROR(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + K[i] + w[i];
        uint32_t S0 = ROR(a, 2) ^ ROR(a, 13) ^ ROR(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    state[0] += a; state[1] += b; state[2] += c; state[3] += d;
    state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

static const uint32_t IV[8] = {
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19
};

/* the padding block for a 64-byte message is constant: 0x80, zeros, and the
 * 512-bit length in the trailing 8 bytes */
static const uint8_t PAD64[64] = {
    0x80, 0, 0, 0, 0, 0, 0, 0,  0, 0, 0, 0, 0, 0, 0, 0,
    0,    0, 0, 0, 0, 0, 0, 0,  0, 0, 0, 0, 0, 0, 0, 0,
    0,    0, 0, 0, 0, 0, 0, 0,  0, 0, 0, 0, 0, 0, 0, 0,
    0,    0, 0, 0, 0, 0, 0, 0,  0, 0, 0, 0, 0, 0, 0x02, 0x00
};

static void sha256_64(const uint8_t in[64], uint8_t out[32]) {
    uint32_t st[8];
    memcpy(st, IV, sizeof st);
    compress(st, in);
    compress(st, PAD64);
    for (int i = 0; i < 8; i++) {
        out[4*i]   = (uint8_t)(st[i] >> 24);
        out[4*i+1] = (uint8_t)(st[i] >> 16);
        out[4*i+2] = (uint8_t)(st[i] >> 8);
        out[4*i+3] = (uint8_t)(st[i]);
    }
}

void sha256_hash_pairs(const uint8_t* in, uint8_t* out, size_t n) {
    for (size_t i = 0; i < n; i++)
        sha256_64(in + 64 * i, out + 32 * i);
}

static void sha256_any(const uint8_t* msg, size_t len, uint8_t* out) {
    uint32_t st[8];
    memcpy(st, IV, sizeof st);
    size_t full = len / 64;
    for (size_t b = 0; b < full; b++)
        compress(st, msg + 64 * b);
    size_t rem = len - 64 * full;
    uint8_t tail[128];
    memset(tail, 0, sizeof tail);
    memcpy(tail, msg + 64 * full, rem);
    tail[rem] = 0x80;
    size_t tlen = (rem + 9 <= 64) ? 64 : 128;
    uint64_t bitlen = (uint64_t)len * 8;
    for (int k = 0; k < 8; k++)
        tail[tlen - 1 - k] = (uint8_t)(bitlen >> (8 * k));
    compress(st, tail);
    if (tlen == 128)
        compress(st, tail + 64);
    for (int i = 0; i < 8; i++) {
        out[4*i]   = (uint8_t)(st[i] >> 24);
        out[4*i+1] = (uint8_t)(st[i] >> 16);
        out[4*i+2] = (uint8_t)(st[i] >> 8);
        out[4*i+3] = (uint8_t)(st[i]);
    }
}

void sha256_hash_many(const uint8_t* in, const uint64_t* lens,
                      uint8_t* out, size_t n) {
    size_t off = 0;
    for (size_t i = 0; i < n; i++) {
        size_t len = (size_t)lens[i];
        sha256_any(in + off, len, out + 32 * i);
        off += len;
    }
}
