/* Batched SHA-256 for SSZ merkleization — the native runtime component
 * backing hash_tree_root throughput (SURVEY.md §7.3 hard part #6; the
 * reference's native plane is its C BLS binding, utils/bls.py:17-22).
 *
 * API (ctypes, see consensus_specs_tpu/utils/native_sha256.py):
 *   void sha256_hash_pairs(const uint8_t* in, uint8_t* out, size_t n)
 *     - hashes n independent 64-byte messages (pairs of 32-byte tree nodes)
 *       into n 32-byte digests: one C call per MERKLE LAYER instead of one
 *       Python hashlib call per node pair. Every message is exactly one
 *       data block + one constant padding block, so the whole layer runs
 *       without branching or allocation.
 *   void sha256_hash_many(const uint8_t* in, const uint64_t* lens,
 *                         uint8_t* out, size_t n)
 *     - hashes n independent VARIABLE-length messages (concatenated in
 *       `in`, per-message byte lengths in `lens`) into n 32-byte digests:
 *       one C call per expand_message_xmd round for a whole hash-to-G2
 *       batch (the input codec plane, consensus_specs_tpu/ops/codec.py).
 *
 * Build: make native (gcc -O3 -fPIC -shared).
 */
#include <stdint.h>
#include <stddef.h>
#include <string.h>

static const uint32_t K[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,
    0x923f82a4,0xab1c5ed5,0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,
    0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,0xe49b69c1,0xefbe4786,
    0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,
    0x06ca6351,0x14292967,0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,
    0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,0xa2bfe8a1,0xa81a664b,
    0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,
    0x5b9cca4f,0x682e6ff3,0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,
    0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2
};

#define ROR(x,n) (((x) >> (n)) | ((x) << (32 - (n))))

static void compress(uint32_t state[8], const uint8_t block[64]) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)block[4*i] << 24) | ((uint32_t)block[4*i+1] << 16)
             | ((uint32_t)block[4*i+2] << 8) | block[4*i+3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = ROR(w[i-15], 7) ^ ROR(w[i-15], 18) ^ (w[i-15] >> 3);
        uint32_t s1 = ROR(w[i-2], 17) ^ ROR(w[i-2], 19) ^ (w[i-2] >> 10);
        w[i] = w[i-16] + s0 + w[i-7] + s1;
    }
    uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
    uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = ROR(e, 6) ^ ROR(e, 11) ^ ROR(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + K[i] + w[i];
        uint32_t S0 = ROR(a, 2) ^ ROR(a, 13) ^ ROR(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    state[0] += a; state[1] += b; state[2] += c; state[3] += d;
    state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

static const uint32_t IV[8] = {
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19
};

/* the padding block for a 64-byte message is constant: 0x80, zeros, and the
 * 512-bit length in the trailing 8 bytes */
static const uint8_t PAD64[64] = {
    0x80, 0, 0, 0, 0, 0, 0, 0,  0, 0, 0, 0, 0, 0, 0, 0,
    0,    0, 0, 0, 0, 0, 0, 0,  0, 0, 0, 0, 0, 0, 0, 0,
    0,    0, 0, 0, 0, 0, 0, 0,  0, 0, 0, 0, 0, 0, 0, 0,
    0,    0, 0, 0, 0, 0, 0, 0,  0, 0, 0, 0, 0, 0, 0x02, 0x00
};

/* ---- SHA-NI fast path ----------------------------------------------------
 * x86 SHA extensions run the whole compression in hardware (~4-8x over the
 * scalar rounds above). Compiled with a per-function target attribute so
 * the object still builds and loads on any x86-64 toolchain; selected at
 * runtime via cpuid, everything else falls back to the scalar path. */
#if defined(__x86_64__) || defined(_M_X64)
#define HAVE_SHA_NI_BUILD 1
#include <immintrin.h>

__attribute__((target("sha,sse4.1")))
static void compress_ni(uint32_t state[8], const uint8_t block[64]) {
    __m128i STATE0, STATE1, MSG, TMP, TMSG0, TMSG1, TMSG2, TMSG3;
    __m128i ABEF_SAVE, CDGH_SAVE;
    const __m128i MASK =
        _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);

    TMP = _mm_loadu_si128((const __m128i*)&state[0]);
    STATE1 = _mm_loadu_si128((const __m128i*)&state[4]);
    TMP = _mm_shuffle_epi32(TMP, 0xB1);          /* CDAB */
    STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);    /* EFGH */
    STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);    /* ABEF */
    STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0); /* CDGH */
    ABEF_SAVE = STATE0;
    CDGH_SAVE = STATE1;

    /* rounds 0-3 */
    TMSG0 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 0)), MASK);
    MSG = _mm_add_epi32(TMSG0,
        _mm_set_epi64x(0xE9B5DBA5B5C0FBCFULL, 0x71374491428A2F98ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

    /* rounds 4-7 */
    TMSG1 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 16)), MASK);
    MSG = _mm_add_epi32(TMSG1,
        _mm_set_epi64x(0xAB1C5ED5923F82A4ULL, 0x59F111F13956C25BULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG0 = _mm_sha256msg1_epu32(TMSG0, TMSG1);

    /* rounds 8-11 */
    TMSG2 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 32)), MASK);
    MSG = _mm_add_epi32(TMSG2,
        _mm_set_epi64x(0x550C7DC3243185BEULL, 0x12835B01D807AA98ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG1 = _mm_sha256msg1_epu32(TMSG1, TMSG2);

    /* rounds 12-15 */
    TMSG3 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 48)), MASK);
    MSG = _mm_add_epi32(TMSG3,
        _mm_set_epi64x(0xC19BF1749BDC06A7ULL, 0x80DEB1FE72BE5D74ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG3, TMSG2, 4);
    TMSG0 = _mm_add_epi32(TMSG0, TMP);
    TMSG0 = _mm_sha256msg2_epu32(TMSG0, TMSG3);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG2 = _mm_sha256msg1_epu32(TMSG2, TMSG3);

    /* rounds 16-19 */
    MSG = _mm_add_epi32(TMSG0,
        _mm_set_epi64x(0x240CA1CC0FC19DC6ULL, 0xEFBE4786E49B69C1ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG0, TMSG3, 4);
    TMSG1 = _mm_add_epi32(TMSG1, TMP);
    TMSG1 = _mm_sha256msg2_epu32(TMSG1, TMSG0);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG3 = _mm_sha256msg1_epu32(TMSG3, TMSG0);

    /* rounds 20-23 */
    MSG = _mm_add_epi32(TMSG1,
        _mm_set_epi64x(0x76F988DA5CB0A9DCULL, 0x4A7484AA2DE92C6FULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG1, TMSG0, 4);
    TMSG2 = _mm_add_epi32(TMSG2, TMP);
    TMSG2 = _mm_sha256msg2_epu32(TMSG2, TMSG1);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG0 = _mm_sha256msg1_epu32(TMSG0, TMSG1);

    /* rounds 24-27 */
    MSG = _mm_add_epi32(TMSG2,
        _mm_set_epi64x(0xBF597FC7B00327C8ULL, 0xA831C66D983E5152ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG2, TMSG1, 4);
    TMSG3 = _mm_add_epi32(TMSG3, TMP);
    TMSG3 = _mm_sha256msg2_epu32(TMSG3, TMSG2);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG1 = _mm_sha256msg1_epu32(TMSG1, TMSG2);

    /* rounds 28-31 */
    MSG = _mm_add_epi32(TMSG3,
        _mm_set_epi64x(0x1429296706CA6351ULL, 0xD5A79147C6E00BF3ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG3, TMSG2, 4);
    TMSG0 = _mm_add_epi32(TMSG0, TMP);
    TMSG0 = _mm_sha256msg2_epu32(TMSG0, TMSG3);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG2 = _mm_sha256msg1_epu32(TMSG2, TMSG3);

    /* rounds 32-35 */
    MSG = _mm_add_epi32(TMSG0,
        _mm_set_epi64x(0x53380D134D2C6DFCULL, 0x2E1B213827B70A85ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG0, TMSG3, 4);
    TMSG1 = _mm_add_epi32(TMSG1, TMP);
    TMSG1 = _mm_sha256msg2_epu32(TMSG1, TMSG0);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG3 = _mm_sha256msg1_epu32(TMSG3, TMSG0);

    /* rounds 36-39 */
    MSG = _mm_add_epi32(TMSG1,
        _mm_set_epi64x(0x92722C8581C2C92EULL, 0x766A0ABB650A7354ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG1, TMSG0, 4);
    TMSG2 = _mm_add_epi32(TMSG2, TMP);
    TMSG2 = _mm_sha256msg2_epu32(TMSG2, TMSG1);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG0 = _mm_sha256msg1_epu32(TMSG0, TMSG1);

    /* rounds 40-43 */
    MSG = _mm_add_epi32(TMSG2,
        _mm_set_epi64x(0xC76C51A3C24B8B70ULL, 0xA81A664BA2BFE8A1ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG2, TMSG1, 4);
    TMSG3 = _mm_add_epi32(TMSG3, TMP);
    TMSG3 = _mm_sha256msg2_epu32(TMSG3, TMSG2);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG1 = _mm_sha256msg1_epu32(TMSG1, TMSG2);

    /* rounds 44-47 */
    MSG = _mm_add_epi32(TMSG3,
        _mm_set_epi64x(0x106AA070F40E3585ULL, 0xD6990624D192E819ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG3, TMSG2, 4);
    TMSG0 = _mm_add_epi32(TMSG0, TMP);
    TMSG0 = _mm_sha256msg2_epu32(TMSG0, TMSG3);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG2 = _mm_sha256msg1_epu32(TMSG2, TMSG3);

    /* rounds 48-51 */
    MSG = _mm_add_epi32(TMSG0,
        _mm_set_epi64x(0x34B0BCB52748774CULL, 0x1E376C0819A4C116ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG0, TMSG3, 4);
    TMSG1 = _mm_add_epi32(TMSG1, TMP);
    TMSG1 = _mm_sha256msg2_epu32(TMSG1, TMSG0);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    TMSG3 = _mm_sha256msg1_epu32(TMSG3, TMSG0);

    /* rounds 52-55 */
    MSG = _mm_add_epi32(TMSG1,
        _mm_set_epi64x(0x682E6FF35B9CCA4FULL, 0x4ED8AA4A391C0CB3ULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG1, TMSG0, 4);
    TMSG2 = _mm_add_epi32(TMSG2, TMP);
    TMSG2 = _mm_sha256msg2_epu32(TMSG2, TMSG1);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

    /* rounds 56-59 */
    MSG = _mm_add_epi32(TMSG2,
        _mm_set_epi64x(0x8CC7020884C87814ULL, 0x78A5636F748F82EEULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(TMSG2, TMSG1, 4);
    TMSG3 = _mm_add_epi32(TMSG3, TMP);
    TMSG3 = _mm_sha256msg2_epu32(TMSG3, TMSG2);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

    /* rounds 60-63 */
    MSG = _mm_add_epi32(TMSG3,
        _mm_set_epi64x(0xC67178F2BEF9A3F7ULL, 0xA4506CEB90BEFFFAULL));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

    STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
    STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);
    TMP = _mm_shuffle_epi32(STATE0, 0x1B);       /* FEBA */
    STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);    /* DCHG */
    STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0); /* DCBA */
    STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);    /* HGFE */
    _mm_storeu_si128((__m128i*)&state[0], STATE0);
    _mm_storeu_si128((__m128i*)&state[4], STATE1);
}

__attribute__((target("sha,sse4.1")))
static void sha256_64_ni(const uint8_t in[64], uint8_t out[32]) {
    uint32_t st[8];
    memcpy(st, IV, sizeof st);
    compress_ni(st, in);
    compress_ni(st, PAD64);
    for (int i = 0; i < 8; i++) {
        out[4*i]   = (uint8_t)(st[i] >> 24);
        out[4*i+1] = (uint8_t)(st[i] >> 16);
        out[4*i+2] = (uint8_t)(st[i] >> 8);
        out[4*i+3] = (uint8_t)(st[i]);
    }
}
#endif /* __x86_64__ */

/* 0 = undetected, 1 = SHA-NI, -1 = scalar only */
static int sha_ni_state = 0;

#if defined(HAVE_SHA_NI_BUILD)
#include <cpuid.h>
#endif

static int use_sha_ni(void) {
    if (sha_ni_state == 0) {
#if defined(HAVE_SHA_NI_BUILD)
        unsigned a = 0, b = 0, c = 0, d = 0;
        int ok = 0;
        if (__get_cpuid_count(7, 0, &a, &b, &c, &d))
            ok = (b >> 29) & 1;                    /* CPUID.7.0:EBX.SHA */
        if (ok) {
            __cpuid(1, a, b, c, d);
            ok = (c >> 19) & 1;                    /* CPUID.1:ECX.SSE4.1 */
        }
        sha_ni_state = ok ? 1 : -1;
#else
        sha_ni_state = -1;
#endif
    }
    return sha_ni_state == 1;
}

static void sha256_64(const uint8_t in[64], uint8_t out[32]) {
    uint32_t st[8];
    memcpy(st, IV, sizeof st);
    compress(st, in);
    compress(st, PAD64);
    for (int i = 0; i < 8; i++) {
        out[4*i]   = (uint8_t)(st[i] >> 24);
        out[4*i+1] = (uint8_t)(st[i] >> 16);
        out[4*i+2] = (uint8_t)(st[i] >> 8);
        out[4*i+3] = (uint8_t)(st[i]);
    }
}

void sha256_hash_pairs(const uint8_t* in, uint8_t* out, size_t n) {
#if defined(HAVE_SHA_NI_BUILD)
    if (use_sha_ni()) {
        for (size_t i = 0; i < n; i++)
            sha256_64_ni(in + 64 * i, out + 32 * i);
        return;
    }
#endif
    for (size_t i = 0; i < n; i++)
        sha256_64(in + 64 * i, out + 32 * i);
}

static void sha256_any(const uint8_t* msg, size_t len, uint8_t* out) {
    uint32_t st[8];
    memcpy(st, IV, sizeof st);
    size_t full = len / 64;
    for (size_t b = 0; b < full; b++)
        compress(st, msg + 64 * b);
    size_t rem = len - 64 * full;
    uint8_t tail[128];
    memset(tail, 0, sizeof tail);
    memcpy(tail, msg + 64 * full, rem);
    tail[rem] = 0x80;
    size_t tlen = (rem + 9 <= 64) ? 64 : 128;
    uint64_t bitlen = (uint64_t)len * 8;
    for (int k = 0; k < 8; k++)
        tail[tlen - 1 - k] = (uint8_t)(bitlen >> (8 * k));
    compress(st, tail);
    if (tlen == 128)
        compress(st, tail + 64);
    for (int i = 0; i < 8; i++) {
        out[4*i]   = (uint8_t)(st[i] >> 24);
        out[4*i+1] = (uint8_t)(st[i] >> 16);
        out[4*i+2] = (uint8_t)(st[i] >> 8);
        out[4*i+3] = (uint8_t)(st[i]);
    }
}

void sha256_hash_many(const uint8_t* in, const uint64_t* lens,
                      uint8_t* out, size_t n) {
    size_t off = 0;
#if defined(HAVE_SHA_NI_BUILD)
    if (use_sha_ni()) {
        for (size_t i = 0; i < n; i++) {
            size_t len = (size_t)lens[i];
            if (len == 64) {
                sha256_64_ni(in + off, out + 32 * i);
            } else {
                sha256_any(in + off, len, out + 32 * i);
            }
            off += len;
        }
        return;
    }
#endif
    for (size_t i = 0; i < n; i++) {
        size_t len = (size_t)lens[i];
        sha256_any(in + off, len, out + 32 * i);
        off += len;
    }
}
