/* Bucketed list scheduling + liveness + LIFO linear-scan register
 * allocation for the field-ALU VM assembler (ops/vm.py).
 *
 * Semantics are IDENTICAL to the pure-Python path in Prog.assemble(),
 * which is itself gated bit-identical to the legacy reference scheduler
 * (tests/test_vm_scheduler.py): every ALU op lands on the first step >=
 * max(operand steps) + 1 whose unit has a free lane, lanes fill in op
 * creation order, registers are claimed most-recently-freed-first and
 * freed after each step's last use. The native kernel exists purely for
 * throughput — the ~1M ops/sec Python loops become ~30M+ ops/sec here,
 * so a .vm_cache-miss assembly of the chunk-16 rlc_combine is dominated
 * by IR extraction instead of scheduling.
 *
 * Build: make native (csrc/libvmsched.so); loaded via ctypes with a
 * pure-Python fallback when absent.
 *
 * kind[n]: -2 const, -1 input, 0 mul, 1 add, 2 sub
 * a[n], b[n]: operand op indices (const payloads sanitized to 0)
 * outs[n_out]: output op indices (live to n_steps + 1)
 * step/last_use/reg[n]: outputs
 * meta_out[2]: n_steps, alloc_regs (next_reg)
 * returns 0 on success, -1 on allocation failure
 */
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

int vm_schedule_alloc(int64_t n, const int64_t *kind, const int64_t *a,
                      const int64_t *b, int64_t w_mul, int64_t w_lin,
                      int64_t n_out, const int64_t *outs, int64_t *step,
                      int64_t *last_use, int64_t *reg, int64_t *meta_out)
{
    int64_t cap = n + 2;
    int64_t *fill[2], *nxt[2], ln[2] = {0, 0}, width[2];
    int64_t i, t, u, r, x, lnu, n_steps, next_reg, n_alu = 0;
    int rc = -1;

    width[0] = w_mul;
    width[1] = w_lin;
    fill[0] = malloc(cap * sizeof(int64_t));
    fill[1] = malloc(cap * sizeof(int64_t));
    nxt[0] = malloc(cap * sizeof(int64_t));
    nxt[1] = malloc(cap * sizeof(int64_t));
    if (!fill[0] || !fill[1] || !nxt[0] || !nxt[1])
        goto done_sched;

    /* 1) placement: per-unit lane-fill counters + union-find over steps
     * ("first step >= t with a free lane"; full steps point past
     * themselves, finds path-compress) */
    for (i = 0; i < n; i++) {
        int64_t k = kind[i];
        int64_t *f, *nx;
        if (k < 0) {
            step[i] = -1;
            continue;
        }
        n_alu++;
        {
            int64_t sa = step[a[i]], sb = step[b[i]];
            t = (sa >= sb ? sa : sb) + 1;
        }
        u = (k == 0) ? 0 : 1;
        f = fill[u];
        nx = nxt[u];
        lnu = ln[u];
        if (t >= lnu) {
            while (lnu <= t) {
                nx[lnu] = lnu;
                f[lnu] = 0;
                lnu++;
            }
            r = t;
        } else {
            r = t;
            x = nx[r];
            if (x != r) {
                /* walk to the root, then path-compress the chain */
                int64_t start = r;
                for (;;) {
                    r = x;
                    if (r == lnu) {
                        nx[lnu] = lnu;
                        f[lnu] = 0;
                        lnu++;
                        break;
                    }
                    x = nx[r];
                    if (x == r)
                        break;
                }
                x = start;
                while (nx[x] != r) {
                    int64_t nxx = nx[x];
                    nx[x] = r;
                    x = nxx;
                }
            }
        }
        ln[u] = lnu;
        if (++f[r] == width[u])
            nx[r] = r + 1;
        step[i] = r;
    }
    n_steps = ln[0] >= ln[1] ? ln[0] : ln[1];
    rc = 0;
done_sched:
    free(fill[0]);
    free(fill[1]);
    free(nxt[0]);
    free(nxt[1]);
    if (rc != 0)
        return rc;

    /* 2) liveness: last step at which each value is read */
    for (i = 0; i < n; i++)
        last_use[i] = -1;
    for (i = 0; i < n; i++) {
        if (kind[i] < 0)
            continue;
        t = step[i];
        if (last_use[a[i]] < t)
            last_use[a[i]] = t;
        if (last_use[b[i]] < t)
            last_use[b[i]] = t;
    }
    for (i = 0; i < n_out; i++)
        last_use[outs[i]] = n_steps + 1; /* live to the end */

    /* 3) allocation: creation-order ALU ops bucketed by step (counting
     * sort — stable, matching the by-step walk), LIFO free stack,
     * per-step expiry buckets sized by a last-use histogram */
    {
        int64_t nb = n_steps + 2;
        int64_t *bucket_off = calloc(nb + 1, sizeof(int64_t));
        int64_t *bucket = malloc((n_alu > 0 ? n_alu : 1) * sizeof(int64_t));
        int64_t *exp_off = calloc(nb + 1, sizeof(int64_t));
        int64_t *exp_fill, *expiry = NULL, *stack = NULL;
        int64_t sp = 0, e;

        rc = -1;
        exp_fill = calloc(nb, sizeof(int64_t));
        stack = malloc((n > 0 ? n : 1) * sizeof(int64_t));
        if (!bucket_off || !bucket || !exp_off || !exp_fill || !stack)
            goto done_alloc;

        for (i = 0; i < n; i++) {
            if (kind[i] >= 0)
                bucket_off[step[i] + 1]++;
            /* expiry histogram: every allocated value that is freed
             * lands in exactly one step bucket */
            e = last_use[i];
            if (kind[i] < 0) {
                if (e < 0)
                    continue; /* dead input/const: never freed */
            } else if (e < 0) {
                e = step[i]; /* dead value: freed right after its step */
            }
            if (e < nb)
                exp_off[e + 1]++;
        }
        for (i = 0; i < nb; i++) {
            bucket_off[i + 1] += bucket_off[i];
            exp_off[i + 1] += exp_off[i];
        }
        expiry = malloc((exp_off[nb] > 0 ? exp_off[nb] : 1)
                        * sizeof(int64_t));
        if (!expiry)
            goto done_alloc;
        {
            int64_t *bfill = calloc(nb, sizeof(int64_t));
            if (!bfill)
                goto done_alloc;
            for (i = 0; i < n; i++)
                if (kind[i] >= 0) {
                    t = step[i];
                    bucket[bucket_off[t] + bfill[t]++] = i;
                }
            free(bfill);
        }

        next_reg = 1;
        /* inputs and constants first, creation order */
        for (i = 0; i < n; i++) {
            if (kind[i] >= 0)
                continue;
            r = sp ? stack[--sp] : next_reg++;
            reg[i] = r;
            e = last_use[i];
            if (e >= 0 && e < nb)
                expiry[exp_off[e] + exp_fill[e]++] = r;
        }
        /* then step by step: allocate defs, free after last use */
        for (t = 0; t < n_steps; t++) {
            int64_t j;
            for (j = bucket_off[t]; j < bucket_off[t + 1]; j++) {
                int64_t op = bucket[j];
                r = sp ? stack[--sp] : next_reg++;
                reg[op] = r;
                e = last_use[op];
                if (e < 0)
                    e = t;
                if (e < nb)
                    expiry[exp_off[e] + exp_fill[e]++] = r;
            }
            for (j = exp_off[t]; j < exp_off[t] + exp_fill[t]; j++)
                stack[sp++] = expiry[j];
        }
        rc = 0;
done_alloc:
        free(bucket_off);
        free(bucket);
        free(exp_off);
        free(exp_fill);
        free(expiry);
        free(stack);
        if (rc != 0)
            return rc;
    }

    meta_out[0] = n_steps;
    meta_out[1] = next_reg;
    return 0;
}
