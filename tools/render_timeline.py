#!/usr/bin/env python
"""Render a soak time-series JSONL into one self-contained HTML/SVG
timeline (`make soak-bench` / the soak-smoke CI artifact).

Input is the artifact `obs/timeseries.py::dump_wire_jsonl` writes: one
header line (interval, levels, point count), then one line per
(resolution, point) with plain gauge values and histogram-delta
percentile summaries. Output is a single HTML file with inline SVG —
no JavaScript, no external assets, nothing to fetch: the file a CI run
attaches is the file a browser opens, offline, years later.

Panels group dynamically-labelled gauge families onto shared axes:
``health[n0].participation_rate`` and ``health[n3].participation_rate``
render as two series on one ``health.participation_rate`` panel, so a
single sick node shows up as the diverging line, which is the whole
point of recording per-node families side by side.

Usage:
  python tools/render_timeline.py soak_artifacts/soak_timeseries.jsonl \\
      -o soak_artifacts/soak_timeline.html \\
      [--match REGEX] [--resolution SECONDS]

``--match`` filters gauge labels (default: the consensus health family
plus the telemetry plane's own gauges); ``--resolution`` picks which
retention ring to plot (default: the finest present).
"""
import argparse
import html
import json
import os
import re
import sys

# distinguishable on white, colorblind-aware (Okabe-Ito)
PALETTE = ("#0072B2", "#D55E00", "#009E73", "#CC79A7",
           "#E69F00", "#56B4E9", "#F0E442", "#000000")

DEFAULT_MATCH = r"^health[\[.]|^timeseries\.|^process\."

_FAMILY_RE = re.compile(r"^([a-z_]+)\[([^\]]+)\]\.(.+)$")

PANEL_W, PANEL_H = 920, 170
MARGIN_L, MARGIN_R, MARGIN_T, MARGIN_B = 70, 160, 24, 22
PLOT_W = PANEL_W - MARGIN_L - MARGIN_R
PLOT_H = PANEL_H - MARGIN_T - MARGIN_B


def load_rows(path):
    """(header, rows) from one dump_wire_jsonl artifact."""
    header, rows = None, []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if header is None and "timeseries" in doc:
                header = doc
                continue
            if "resolution_s" in doc:
                rows.append(doc)
    return header or {}, rows


def split_label(label):
    """``health[n0].participation_rate`` -> ("health.participation_rate",
    "n0"); an unbracketed label is its own panel with one series."""
    m = _FAMILY_RE.match(label)
    if m:
        return f"{m.group(1)}.{m.group(3)}", m.group(2)
    return label, ""


def collect_panels(rows, match_re):
    """{panel: {series: [(t, value), ...]}} over the selected rows."""
    panels = {}
    for row in rows:
        t = float(row.get("t", 0.0))
        for label, value in row.get("gauges", {}).items():
            if not match_re.search(label):
                continue
            panel, series = split_label(label)
            panels.setdefault(panel, {}).setdefault(series, []).append(
                (t, float(value)))
    for series_map in panels.values():
        for pts in series_map.values():
            pts.sort()
    return panels


def _fmt(v):
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.4g}"


def render_panel(title, series_map):
    """One inline-SVG panel: every series as a polyline on shared axes."""
    all_pts = [p for pts in series_map.values() for p in pts]
    t_min = min(p[0] for p in all_pts)
    t_max = max(p[0] for p in all_pts)
    v_min = min(p[1] for p in all_pts)
    v_max = max(p[1] for p in all_pts)
    if t_max <= t_min:
        t_max = t_min + 1.0
    if v_max <= v_min:
        v_max = v_min + 1.0
    pad = (v_max - v_min) * 0.05
    v_min, v_max = v_min - pad, v_max + pad

    def sx(t):
        return MARGIN_L + (t - t_min) / (t_max - t_min) * PLOT_W

    def sy(v):
        return MARGIN_T + (1.0 - (v - v_min) / (v_max - v_min)) * PLOT_H

    out = [
        f'<svg viewBox="0 0 {PANEL_W} {PANEL_H}" width="{PANEL_W}" '
        f'height="{PANEL_H}" xmlns="http://www.w3.org/2000/svg" '
        f'role="img" aria-label="{html.escape(title, quote=True)}">',
        f'<text x="{MARGIN_L}" y="16" font-size="13" font-weight="bold" '
        f'font-family="monospace">{html.escape(title)}</text>',
        # plot frame + min/max gridlines
        f'<rect x="{MARGIN_L}" y="{MARGIN_T}" width="{PLOT_W}" '
        f'height="{PLOT_H}" fill="none" stroke="#ccc"/>',
    ]
    for frac in (0.25, 0.5, 0.75):
        y = MARGIN_T + PLOT_H * frac
        out.append(f'<line x1="{MARGIN_L}" y1="{y:.1f}" '
                   f'x2="{MARGIN_L + PLOT_W}" y2="{y:.1f}" '
                   f'stroke="#eee"/>')
    for v, anchor_y in ((v_max, MARGIN_T + 10),
                        (v_min, MARGIN_T + PLOT_H - 2)):
        out.append(f'<text x="{MARGIN_L - 6}" y="{anchor_y}" '
                   f'font-size="10" font-family="monospace" '
                   f'text-anchor="end">{_fmt(v)}</text>')
    for t, anchor in ((t_min, "start"), (t_max, "end")):
        out.append(f'<text x="{sx(t):.1f}" '
                   f'y="{MARGIN_T + PLOT_H + 14}" font-size="10" '
                   f'font-family="monospace" text-anchor="{anchor}">'
                   f't={_fmt(t)}s</text>')
    for i, (series, pts) in enumerate(sorted(series_map.items())):
        color = PALETTE[i % len(PALETTE)]
        coords = " ".join(f"{sx(t):.1f},{sy(v):.1f}" for t, v in pts)
        out.append(f'<polyline points="{coords}" fill="none" '
                   f'stroke="{color}" stroke-width="1.5"/>')
        last = pts[-1][1]
        ly = MARGIN_T + 12 + i * 14
        name = html.escape(series or title)
        out.append(f'<line x1="{MARGIN_L + PLOT_W + 8}" y1="{ly - 4}" '
                   f'x2="{MARGIN_L + PLOT_W + 24}" y2="{ly - 4}" '
                   f'stroke="{color}" stroke-width="2"/>')
        out.append(f'<text x="{MARGIN_L + PLOT_W + 28}" y="{ly}" '
                   f'font-size="10" font-family="monospace">'
                   f'{name} = {_fmt(last)}</text>')
    out.append("</svg>")
    return "\n".join(out)


def render_html(header, rows, match, resolution=None):
    match_re = re.compile(match)
    resolutions = sorted({float(r["resolution_s"]) for r in rows})
    if not resolutions:
        raise SystemExit("render_timeline: no points in the artifact")
    res = float(resolution) if resolution is not None else resolutions[0]
    selected = [r for r in rows if float(r["resolution_s"]) == res]
    if not selected:
        raise SystemExit(
            f"render_timeline: no points at resolution {res}s "
            f"(present: {resolutions})")
    panels = collect_panels(selected, match_re)
    parts = [
        "<!DOCTYPE html>",
        '<html><head><meta charset="utf-8">',
        "<title>telemetry soak timeline</title>",
        "<style>body{font-family:monospace;margin:24px;}"
        "svg{display:block;margin-bottom:10px;}</style>",
        "</head><body>",
        "<h2>telemetry soak timeline</h2>",
        f"<p>source interval {header.get('interval_s', '?')}s · "
        f"plotted resolution {_fmt(res)}s · "
        f"{len(selected)} points · retention rings "
        f"{[_fmt(r) for r in resolutions]} · "
        f"match <code>{html.escape(match)}</code></p>",
    ]
    if not panels:
        parts.append("<p><b>no gauge labels matched</b> — the soak ran "
                     "with the matched families disabled?</p>")
    for title in sorted(panels):
        parts.append(render_panel(title, panels[title]))
    parts.append("</body></html>")
    return "\n".join(parts) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="dump_wire_jsonl artifact to render")
    ap.add_argument("-o", "--out", default=None,
                    help="output HTML path (default: input with .html)")
    ap.add_argument("--match", default=DEFAULT_MATCH,
                    help="regex selecting gauge labels "
                         f"(default: {DEFAULT_MATCH!r})")
    ap.add_argument("--resolution", type=float, default=None,
                    help="retention ring to plot in seconds "
                         "(default: finest present)")
    args = ap.parse_args(argv)
    out = args.out or os.path.splitext(args.jsonl)[0] + ".html"
    header, rows = load_rows(args.jsonl)
    body = render_html(header, rows, args.match, args.resolution)
    with open(out, "w") as fh:
        fh.write(body)
    print(f"render_timeline: wrote {out} "
          f"({len(body)} bytes, {len(rows)} points read)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
