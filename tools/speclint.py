"""speclint — the repo's static-analysis gate (`make lint`).

Fills the role of the reference's flake8 + strict-mypy lint of the
GENERATED spec (reference Makefile:133-136, linter.ini) in an image that
ships neither tool (no installs allowed). Two layers:

1. SOURCE checks over every repo .py file (symtable-based, pyflakes-class)
   — the walk covers the package, tests/, tools/, bench.py and
   __graft_entry__.py, so the repo's own tooling is linted too:
   - undefined names: a symbol referenced in any scope that is neither
     local, nor enclosing, nor module-level, nor a builtin. This is the
     bug class that silently breaks exec-layered namespaces.
   - unused imports (module scope; `__init__.py` re-export modules and
     star-importing files are exempt, `# noqa` suppresses a line).
   - duplicate definitions (pyflakes F811): two `def`/`class` statements
     with the same name in the SAME statement body (module, class, or
     function) — the later silently shadows the earlier, the classic
     two-`def test_x` bug that makes a test never run. Branch-split
     redefinitions (if/else, try/except) live in different body lists and
     are not flagged; `@x.setter`-style attribute-decorated redefs are
     exempt.

2. BUILT-SPEC checks over every (fork, preset) module the builder emits —
   the analog of the reference type-checking its generated spec:
   - every name a spec function's code references (co_names, incl. nested
     code objects) must resolve in the built module or builtins: catches
     fork layering dropping a dependency;
   - every function annotation must resolve (typing.get_type_hints);
   - every SSZ container field type must be a real View class;
   - every direct call from a spec function to another function in the
     built namespace must BIND against the callee's signature (arity +
     keyword validity, inspect.signature.bind) — the cheapest meaningful
     slice of the reference's strict-mypy gate: a fork override that
     changes a helper's parameters breaks every stale call site at lint
     time, not at test-coverage mercy.

Exit status 0 = clean. Any finding prints `path:line: message` and fails.
"""
import ast
import builtins
import os
import sys
import symtable

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_BUILTINS = set(dir(builtins)) | {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__path__", "__class__",
}

SOURCE_ROOTS = ("consensus_specs_tpu", "tests", "tools")
SKIP_DIRS = {"__pycache__"}


def _py_files():
    for root in SOURCE_ROOTS:
        for dirpath, dirnames, files in os.walk(os.path.join(REPO, root)):
            dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)
    for f in ("bench.py", "__graft_entry__.py"):
        yield os.path.join(REPO, f)


def _noqa_lines(src: str):
    return {
        i + 1 for i, line in enumerate(src.splitlines()) if "noqa" in line
    }


def _walk_tables(table):
    yield table
    for child in table.get_children():
        yield from _walk_tables(child)


def _collect_defined_through(table, defined):
    """Names visible to children scopes: everything assigned/imported/
    parameter/function-or-class-defined in this table plus ancestors."""
    out = set(defined)
    for sym in table.get_symbols():
        if sym.is_assigned() or sym.is_imported() or sym.is_parameter() or sym.is_namespace():
            out.add(sym.get_name())
    return out


def check_duplicate_defs(tree, rel: str, noqa):
    """F811-class sweep: same-name `def`/`class` statements in one
    statement body. Bodies are scanned per-list, so `if`/`try` branch
    variants never collide; a redefinition whose decorator is an attribute
    access (`@prop.setter`, `@fn.register`) is the accumulator idiom and
    is exempt."""
    findings = []
    for node in ast.walk(tree):
        # every statement list is its own scan scope: body, else-branches
        # (If/For/While/Try orelse) and finally blocks — a dup WITHIN one
        # list shadows; defs split ACROSS lists are branch variants
        for body in (getattr(node, "body", None),
                     getattr(node, "orelse", None),
                     getattr(node, "finalbody", None)):
            if not isinstance(body, list):
                continue
            seen = {}
            for stmt in body:
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                    continue
                first = seen.get(stmt.name)
                # the accumulator idiom only: a decorator rooted at the
                # redefined name ITSELF (@x.setter / @x.register for def x).
                # Any other dotted decorator (@pytest.mark.slow, ...) is
                # not an exemption — two decorated test defs still shadow.
                self_decorated = any(
                    isinstance(d, ast.Attribute)
                    and isinstance(d.value, ast.Name)
                    and d.value.id == stmt.name
                    for d in stmt.decorator_list
                )
                if (first is not None and not self_decorated
                        and stmt.lineno not in noqa):
                    findings.append(
                        f"{rel}:{stmt.lineno}: duplicate definition of "
                        f"'{stmt.name}' (first defined at line {first}; "
                        "the later definition silently shadows it)"
                    )
                seen.setdefault(stmt.name, stmt.lineno)
    return findings


def check_source_file(path: str):
    findings = []
    src = open(path).read()
    rel = os.path.relpath(path, REPO)
    # specsrc files are exec-LAYERED into one namespace at build time, so
    # cross-file references are the design, not a bug; the built-spec layer
    # below is their real checker
    in_specsrc = rel.replace(os.sep, "/").startswith("consensus_specs_tpu/specsrc/")
    try:
        tree = ast.parse(src)
        top = symtable.symtable(src, rel, "exec")
    except SyntaxError as e:
        return [f"{rel}:{e.lineno}: syntax error: {e.msg}"]

    has_star = any(
        isinstance(n, ast.ImportFrom) and any(a.name == "*" for a in n.names)
        for n in ast.walk(tree)
    )
    noqa = _noqa_lines(src)

    # map name -> first use line (approximate, for reporting)
    use_lines = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            use_lines.setdefault(node.id, node.lineno)

    module_names = _collect_defined_through(top, set())

    # duplicate-definition sweep runs for EVERY file (specsrc included:
    # fork layering overrides across files by design, but a redefinition
    # within one module body is always a silent shadow)
    findings += check_duplicate_defs(tree, rel, noqa)

    if not has_star and not in_specsrc:
        # undefined-name sweep: FREE (global-implicit) symbols in any scope
        # must exist at module level or be builtins
        for table in _walk_tables(top):
            for sym in table.get_symbols():
                name = sym.get_name()
                if not sym.is_referenced():
                    continue
                if sym.is_local() or sym.is_parameter():
                    continue
                if sym.is_free():
                    continue  # closure binding: defined in an enclosing scope
                if name in module_names or name in _BUILTINS:
                    continue
                line = use_lines.get(name, 1)
                if line in noqa:
                    continue
                findings.append(
                    f"{rel}:{line}: undefined name '{name}' "
                    f"(scope {table.get_name()})"
                )

        # unused-import sweep: an imported name never LOADED anywhere in
        # the file (module scope or nested) and not re-exported via __all__
        if os.path.basename(path) != "__init__.py":
            exported = set()
            for n in ast.walk(tree):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Name) and t.id == "__all__":
                            exported = {
                                getattr(e, "value", None)
                                for e in getattr(n.value, "elts", [])
                            }
            for node in ast.walk(tree):
                if not isinstance(node, (ast.Import, ast.ImportFrom)):
                    continue
                if node.lineno in noqa:
                    continue
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    if name == "*" or name in use_lines or name in exported:
                        continue
                    if name == "annotations":  # from __future__
                        continue
                    findings.append(
                        f"{rel}:{node.lineno}: unused import '{name}'"
                    )
    return findings


# ---------------------------------------------------------------------------
# built-spec checks
# ---------------------------------------------------------------------------


def _function_names(fn):
    """All GLOBAL names a function's code loads (dis-level, so attribute
    accesses and locals are excluded), nested code objects included."""
    import dis

    out = set()
    stack = [fn.__code__]
    while stack:
        code = stack.pop()
        for ins in dis.get_instructions(code):
            if ins.opname in ("LOAD_GLOBAL", "STORE_GLOBAL", "DELETE_GLOBAL"):
                out.add(ins.argval)
        stack.extend(c for c in code.co_consts if hasattr(c, "co_names"))
    return out


def check_call_signatures(ns: dict, where: str):
    """For every function whose home namespace is ``ns``, parse its source
    and check each direct ``name(...)`` call whose callee resolves to a
    plain Python function in ``ns``: the written-out arguments must bind
    against the callee's signature. Call sites using *args/**kwargs, and
    callees that aren't plain functions (classes, builtins, SSZ types —
    different calling conventions), are skipped."""
    import inspect
    import textwrap

    findings = []
    for name in sorted(ns):
        fn = ns[name]
        if not (callable(fn) and hasattr(fn, "__code__")):
            continue
        if getattr(fn, "__globals__", None) is not ns:
            continue  # imported helper: its own module's lint covers it
        try:
            tree = ast.parse(textwrap.dedent(inspect.getsource(fn)))
        except (OSError, SyntaxError, TypeError):
            continue  # source not recoverable (exec'd without a file)
        local_names = set(fn.__code__.co_varnames)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
                continue
            if node.func.id in local_names:
                continue  # shadowed by a local: not the ns function
            callee = ns.get(node.func.id)
            if not inspect.isfunction(callee):
                continue
            if any(isinstance(a, ast.Starred) for a in node.args) or any(
                kw.arg is None for kw in node.keywords
            ):
                continue  # splatted call: arity unknowable statically
            try:
                inspect.signature(callee).bind(
                    *[None] * len(node.args),
                    **{kw.arg: None for kw in node.keywords},
                )
            except TypeError as e:
                findings.append(
                    f"{where}: {name} line {node.lineno}: call to "
                    f"{node.func.id}() does not bind: {e}"
                )
    return findings


def check_built_spec(fork: str, preset: str):
    import typing

    from consensus_specs_tpu.builder import build_spec_module
    from consensus_specs_tpu.utils.ssz.ssz_typing import Container, View

    findings = []
    mod = build_spec_module(fork, preset)
    ns = vars(mod)
    where = f"<built {fork}/{preset}>"

    for name in sorted(ns):
        obj = ns[name]
        if callable(obj) and hasattr(obj, "__code__"):
            if getattr(obj, "__globals__", None) is not ns:
                continue  # imported helper: resolves in its OWN module
            for ref in sorted(_function_names(obj)):
                if ref not in ns and ref not in _BUILTINS:
                    findings.append(
                        f"{where}: function {name} references undefined '{ref}'"
                    )
            try:
                typing.get_type_hints(obj, ns)
            except Exception as e:
                findings.append(
                    f"{where}: function {name} has unresolvable annotations: {e}"
                )
        elif isinstance(obj, type) and issubclass(obj, Container) and obj is not Container:
            for fname, ftyp in obj.fields().items():
                if not (isinstance(ftyp, type) and issubclass(ftyp, View)):
                    findings.append(
                        f"{where}: container {name}.{fname} has non-View type {ftyp!r}"
                    )
    findings += check_call_signatures(ns, where)
    return findings


def main() -> int:
    findings = []
    for path in _py_files():
        findings += check_source_file(path)

    if "--source-only" not in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
        from consensus_specs_tpu.builder import IMPLEMENTED_FORKS

        for fork in IMPLEMENTED_FORKS:
            for preset in ("minimal", "mainnet"):
                findings += check_built_spec(fork, preset)

    for f in findings:
        print(f)
    print(f"speclint: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
