#!/bin/bash
# Round-5 persistent TPU harvest loop. The bench child is now self-
# sufficient (bench.py): ONE process runs committee mode at the window-
# proven shape first, then the epoch workload with per-rep emission, then
# the Pallas-vs-u64 A/B — so a single tunnel grant answers everything and
# no second process launch is needed (grants evaporate between launches,
# TPU_NOTES.md round-4 entry). This loop just retries that child with a
# generous deadline and logs every line it flushes.
#
# Usage: tools/tpu_harvest_r5.sh [out.jsonl] — loops until killed.
OUT=${1:-/tmp/tpu_harvest_r5.jsonl}
cd "$(dirname "$0")/.." || exit 1
i=0
while true; do
  i=$((i + 1))
  echo "=== attempt $i $(date -u +%H:%M:%S) ===" >> "$OUT"
  CONSENSUS_SPECS_TPU_BENCH_CHILD=1 \
    timeout 1800 python bench.py >> "$OUT" 2>/dev/null
  echo "=== attempt $i end rc=$? $(date -u +%H:%M:%S) ===" >> "$OUT"
  sleep 10
done
