"""vmlint — the static-analysis gate for field-ALU VM programs (`make vmlint`).

Analyzes every program in the vmlib registry (Miller product, aggregate
verify, RLC combine, hard part, the subgroup-check ladders, the hash-to-G2
finish) at the production assembly shape through ops/vm_analysis.py:

- independently re-derives every value-magnitude bound and cross-checks the
  assembler's inline tracker (carry-safety of the 15-limb lanes);
- reports per-program register pressure and flags the live-range-outlier
  scheduler hazard (the PR 3 select-then-multiply register blowup class);
- reports the critical path / width profile / predicted runtime and the
  depth-bound vs width-bound classification ROADMAP item 5 plans against;
- reports the structural-dedup shape (ISSUE 15): distinct canonical
  chunk structures vs total chunks at the fused backend's period-aligned
  window, the dedup ratio, and the predicted cold XLA compile bill with
  and without dedup;
- gates against the committed VMLINT_BASELINE.json: any soundness error,
  any hazard, and any pressure/depth scalar grown past the tolerance fails.

Exit status 0 = clean. Usage:

    python tools/vmlint.py                  # full registry + baseline gate
    python tools/vmlint.py --tier1          # small-shape subset (fast)
    python tools/vmlint.py --update-baseline  # re-pin VMLINT_BASELINE.json
    python tools/vmlint.py --json out.json  # dump the full reports
    python tools/vmlint.py --no-gate        # reports only, no baseline diff

Program building + assembly dominate the run time (the bucketed scheduler
— ISSUE 10 — assembles at ~1-3M ops/sec, so building the IR is now the
bigger share); the full registry takes tens of seconds and rides
`make check`/CI, not tier-1 pytest (tests analyze the small subset).
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _fmt_line(r: dict) -> str:
    p, c = r["pressure"], r["cost"]
    s = r["structure"]
    # interp prediction: the 280 µs/step register-file model; fused: the
    # ISSUE 13 straight-line lowering model (real per-level widths +
    # per-level/per-chunk glue — ops/vm_analysis.py FUSED_COST_*);
    # structs: the ISSUE 15 dedup shape — distinct canonical chunk
    # structures / total chunks at the period-aligned window, and the
    # predicted cold XLA compile bill that buys (vs the per-chunk bill)
    return (
        f"{r['name']:<36} steps={p['sched_steps']:<6} "
        f"crit={c['critical_path']:<6} work={c['work_steps']:<5} "
        f"{c['classification']:<11} live={p['max_live']:<5} "
        f"regs={p['alloc_regs']:<5} mulutil={c['mul_utilization']:<7} "
        f"pred={c['predicted_row_s']:.2f}s/row "
        f"fused={c['predicted_fused_row_s']:.2f}s/row "
        f"structs={s['distinct_structs']}/{s['chunks']} "
        f"({s['dedup_ratio']}x, cold~{s['predicted_cold_s']:.0f}s"
        f"/{s['predicted_cold_nodedup_s']:.0f}s) "
        f"err={r['errors']} warn={r['warnings']}"
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tier1", action="store_true",
                    help="analyze only the small-shape tier-1 subset")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite VMLINT_BASELINE.json from this run "
                         "(full registry required)")
    ap.add_argument("--json", metavar="PATH",
                    help="dump the full report list as JSON")
    ap.add_argument("--no-gate", action="store_true",
                    help="skip the baseline comparison")
    args = ap.parse_args()

    from consensus_specs_tpu.ops import vm_analysis

    if args.update_baseline and args.tier1:
        ap.error("--update-baseline needs the full registry (drop --tier1)")

    reports = vm_analysis.run_registry(
        tier1_only=args.tier1,
        progress=lambda key: print(f"vmlint: analyzing {key} ...",
                                   flush=True),
    )
    print()
    for r in reports:
        print(_fmt_line(r))
        for f in r["findings"]:
            print(f"    [{f['severity']}] {f['rule']}: {f['detail']}")

    if args.json:
        with open(args.json, "w") as fh:
            json.dump(reports, fh, indent=1, sort_keys=True)
        print(f"\nvmlint: full reports -> {args.json}")

    if args.update_baseline:
        baseline = {r["name"]: vm_analysis.baseline_entry(r)
                    for r in reports}
        with open(vm_analysis.BASELINE_PATH, "w") as fh:
            json.dump(baseline, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"vmlint: baseline re-pinned -> {vm_analysis.BASELINE_PATH}")
        # still gate against the fresh baseline: soundness errors/hazards
        # must fail even while re-pinning the scalars

    failures = []
    if args.no_gate:
        failures = [
            f"{r['name']}: [{f['rule']}] {f['detail']}"
            for r in reports for f in r["findings"]
            if f["severity"] == "error"
        ]
    else:
        try:
            baseline = vm_analysis.load_baseline()
        except FileNotFoundError:
            print("vmlint: VMLINT_BASELINE.json missing — run "
                  "tools/vmlint.py --update-baseline and commit it")
            return 1
        # gate() iterates the analyzed reports, so a --tier1 run simply
        # checks the subset against its baseline entries
        failures = vm_analysis.gate(reports, baseline)

    print()
    for f in failures:
        print(f"vmlint FAIL: {f}")
    print(f"vmlint: {len(reports)} program(s), {len(failures)} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
