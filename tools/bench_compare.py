#!/usr/bin/env python
"""Round-over-round bench regression gate (`make bench-compare`).

The driver records one ``BENCH_r<NN>.json`` per round whose ``parsed``
block is the headline JSON line ``bench.py`` printed (best-throughput
stage, with ``per_mode_best`` attaching every (mode, shape) that landed).
This tool diffs the NEWEST round against the most recent previous round
that recorded a usable number and exits nonzero when any comparable
headline regressed more than the allowed fraction — a perf regression
becomes a visible check failure instead of a silently worse JSON artifact.

Comparability rules:
- values key by ``platform:shape`` — a CPU-fallback round must never be
  scored against a TPU window's number (the gap is ~10x and says nothing
  about the code); ``cpu (fallback)`` and ``cpu`` are the same platform.
- committee shapes carry their ``[NxK]`` (bench.py `_shape_key` rule: the
  4x8 liveness shape and the comparable 32x128 shape never share a slot).
- ``per_mode_best`` entries join the comparison under the parsed line's
  platform (they all came from the same child process).
- no common key between the rounds -> SKIP (exit 0, says so); a newest
  round with NO usable parsed value -> FAIL (a bench that stopped
  emitting numbers is itself a regression).

Threshold: ``--max-regression`` percent (default: env
``BENCH_COMPARE_MAX_REGRESSION`` or 30). CPU committee numbers jitter a
few percent round over round on shared hosts; 30% catches a lost
optimization without flapping on noise. Improvements never fail.

SLO gating: rounds that carry an ``slo`` section (the serve/head benches
emit one — per-objective ``ok`` + ``margin`` = objective/attained) are
gated on OBJECTIVE STATE, not on margin jitter: a previously-met
objective that the newest round VIOLATES fails the gate outright, while
margin movement within "met" is reported but never fails (CPU tail
latencies jitter far more than throughput means; the page-worthy event is
crossing the objective, and that is exactly what fails).

Simnet gating: rounds that carry a ``sim`` section (`bench.py --mode
sim` — per-scenario ``converged`` + ``heal_to_convergence_s``) follow
the same state-not-jitter rule: a scenario that converged in the
previous round and DIVERGES in the newest fails the gate outright
(differential convergence is a correctness claim, not a perf number);
heal-to-convergence latency movement is reported alongside but never
fails on its own.

Mesh gating: rounds that carry a ``mesh`` section (`bench.py --mode
serve-mesh` — per-device-count serve rows) gate on the same state rule:
a device count that VERIFIED in the previous round and ERRORS in the
newest fails the round outright (losing a working mesh size is a
correctness/availability regression), while per-count sigs/sec and the
scaling-efficiency ratio are report-only — CPU virtual devices
timeshare two host cores, so their scaling numbers say nothing until
real accelerator rounds.

Finalexp gating: rounds that carry a ``finalexp`` section (`bench.py
--mode finalexp` — per-(variant, rows) hard-part race cells) gate on the
same state rule: a variant cell that verified in the previous round and
ERRORS in the newest fails the round outright ("FINALEXP ERRORED",
mirror of MESH ERRORED — losing a working finalization variant is a
correctness/availability regression), while ms/row movement — including
a previously-winning device route going slower than host — is
report-only.

Vmexec gating: rounds that carry a ``vmexec`` section (`bench.py --mode
vmexec` — per-(kind, rows) interpreter-vs-fused execution race cells)
gate on the same state rule: a cell whose fused lowering ran AND matched
the interpreter bit for bit in the previous round and errors (or
mismatches) in the newest fails the round outright ("VMEXEC ERRORED",
mirror of FINALEXP ERRORED); the ms/row numbers are report-only.

Latency gating: rounds that carry a ``latency`` section (`bench.py
--mode latency` — per-scenario gossip→head rows under the adversarial
simnet runs) gate on the same state rule: a scenario whose deadline-mode
``gossip_to_head_p99`` met the declared objective (and converged) in the
previous round and violates it in the newest fails the round outright
("LATENCY SLO VIOLATED"); the p99 milliseconds are report-only.

Proofs gating: rounds that carry a ``proofs`` section (`bench.py --mode
proofs` — per-client-count light-client replay rows) gate on the same
state rule: a shape whose every served artifact VERIFIED (the spec's
``validate_light_client_update`` + ``is_valid_merkle_branch`` against an
independently re-Merkleized root) in the previous round and stops
verifying in the newest fails the round outright ("PROOFS DIVERGED",
mirror of SIM DIVERGED — a proof plane serving unverifiable bytes is a
correctness regression, not a perf number); proofs/sec, cache hit rate,
and p99 movement are report-only.

Merkle gating: rounds that carry a ``merkle`` section (`bench.py --mode
merkle` — native-vs-python Merkleization race cells) gate on the same
state rule: a cell whose native batched root was BIT-IDENTICAL to the
pure-python oracle in the previous round and diverges in the newest
fails the round outright ("MERKLE DIVERGED" — a hashing plane producing
wrong state roots is a consensus-correctness regression, not a perf
number); the cold/incremental/proof-world speedups and roots/sec are
report-only.

Mainnet gating: rounds that carry a ``mainnet`` section (`bench.py
--mode mainnet` — the mainnet-scale slot replay over the synthetic
million-validator registry) gate on the same state rule: a section
whose correctness claim held in the previous round (hierarchical
verdicts identical to the flat path under the memory budget, a planted
bad committee localized exactly by bisection, censored_aggregates
converging through the strict sim gate, committee affinity with zero
moves) and breaks in the newest fails the round outright ("MAINNET
DIVERGED" — verdict identity at scale is a consensus-correctness claim,
not a perf number); attestations/sec and RSS movement are report-only.

Health gating: rounds that carry a ``health`` section (`bench.py --mode
soak` — the long-horizon consensus health ledger) gate on the same
state rule: a soak whose gate (participation floor, bounded finality
lag, zero unexplained reorgs) held in the previous round and reports
diverged in the newest fails the round outright ("HEALTH DIVERGED" —
slow-burn consensus sickness is a correctness regression, not perf
jitter); participation movement within a green gate is report-only.

Output: the comparison table is also emitted as GitHub-flavored markdown
— appended to ``$GITHUB_STEP_SUMMARY`` when CI sets it, printed to stdout
otherwise — so the round-over-round numbers land on the workflow summary
page without artifact digging. The markdown additionally carries a
headline-trajectory section tracing each (platform, shape) headline
across EVERY recorded round, not just the newest pair.
"""
import argparse
import glob
import json
import os
import re
import sys

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def round_files(directory):
    """BENCH_r*.json paths sorted by round number."""
    found = []
    for path in glob.glob(os.path.join(directory, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if m:
            found.append((int(m.group(1)), path))
    return [p for _, p in sorted(found)]


def _platform(parsed):
    plat = str(parsed.get("platform", "unknown"))
    return "cpu" if plat.startswith("cpu") else plat


def _shape_key(parsed):
    mode = parsed.get("mode", "committee")
    n, k = parsed.get("n"), parsed.get("k")
    if mode == "committee" and n and k:
        return f"committee[{n}x{k}]"
    if mode == "head" and parsed.get("blocks"):
        # chain-plane lines key by tree size (bench.py --mode head emits
        # the same `head[<blocks>]` keys in per_mode_best): a 64-block
        # tree's heads/sec must never score against a 1024-block tree's
        return f"head[{parsed['blocks']}]"
    return str(mode)


def extract(doc):
    """{``platform:shape``: value} comparables from one round's JSON."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    try:
        value = float(parsed.get("value", 0))
    except (TypeError, ValueError):
        return {}
    if value <= 0:
        return {}
    plat = _platform(parsed)
    out = {f"{plat}:{_shape_key(parsed)}": value}
    per_mode = parsed.get("per_mode_best")
    if isinstance(per_mode, dict):
        for key, v in per_mode.items():
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
            if v > 0:
                # the headline's own slot keeps the (possibly higher)
                # parsed value
                out.setdefault(f"{plat}:{key}", v)
    return out


def extract_slo(doc):
    """{``platform:slo:<objective>``: {"ok", "margin"}} from one round's
    ``slo`` section (objectives with no traffic carry no margin and are
    skipped — nothing to gate)."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    section = parsed.get("slo")
    if not isinstance(section, dict):
        return {}
    plat = _platform(parsed)
    out = {}
    for name, row in sorted(section.items()):
        if not isinstance(row, dict) or row.get("n", 0) <= 0:
            continue
        try:
            margin = float(row.get("margin", 0.0))
        except (TypeError, ValueError):
            continue
        out[f"{plat}:slo:{name}"] = {
            "ok": bool(row.get("ok", False)),
            "margin": margin,
        }
    return out


def extract_sim(doc):
    """{``platform:sim:<scenario>``: {"converged", "heal_s"}} from one
    round's ``sim`` section (`bench.py --mode sim` scenario matrix)."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    section = parsed.get("sim")
    if not isinstance(section, dict):
        return {}
    plat = _platform(parsed)
    out = {}
    for name, row in sorted(section.items()):
        if not isinstance(row, dict):
            continue
        try:
            heal_s = float(row.get("heal_to_convergence_s", 0.0))
        except (TypeError, ValueError):
            continue
        out[f"{plat}:sim:{name}"] = {
            "converged": bool(row.get("converged", False)),
            "heal_s": heal_s,
        }
    return out


def extract_mesh(doc):
    """{``platform:mesh:<devices>``: {"ok", "sigs_per_sec", "efficiency"}}
    from one round's ``mesh`` section (`bench.py --mode serve-mesh`
    per-device-count rows; single `--mesh N` serve lines carry flat
    ``mesh_devices``/``mesh_fallbacks`` fields instead and are skipped)."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    section = parsed.get("mesh")
    if not isinstance(section, dict):
        return {}
    plat = _platform(parsed)
    out = {}
    for name, row in sorted(section.items()):
        if not isinstance(row, dict) or "ok" not in row:
            continue
        try:
            sigs = float(row.get("sigs_per_sec") or 0.0)
        except (TypeError, ValueError):
            sigs = 0.0
        out[f"{plat}:mesh:{name}"] = {
            "ok": bool(row.get("ok", False)),
            "sigs_per_sec": sigs,
            "efficiency": row.get("efficiency"),
        }
    return out


def extract_fleet(doc):
    """{``platform:fleet:<workers>``: {"ok", "sigs_per_sec"}} from one
    round's ``fleet`` section (`bench.py --mode serve-fleet` per-worker-
    count rows)."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    section = parsed.get("fleet")
    if not isinstance(section, dict):
        return {}
    plat = _platform(parsed)
    out = {}
    for name, row in sorted(section.items()):
        if not isinstance(row, dict) or "ok" not in row:
            continue
        try:
            sigs = float(row.get("sigs_per_sec") or 0.0)
        except (TypeError, ValueError):
            sigs = 0.0
        out[f"{plat}:fleet:{name}"] = {
            "ok": bool(row.get("ok", False)),
            "sigs_per_sec": sigs,
        }
    return out


def extract_latency(doc):
    """{``platform:latency:<scenario>``: {"ok", "p99_ms"}} from one
    round's ``latency`` section (`bench.py --mode latency` — per-scenario
    gossip→head rows: ``ok`` = converged AND the deadline-mode p99 met
    the declared gossip_to_head_p99 objective)."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    section = parsed.get("latency")
    if not isinstance(section, dict):
        return {}
    plat = _platform(parsed)
    out = {}
    for name, row in sorted(section.items()):
        if not isinstance(row, dict) or "ok" not in row:
            continue
        try:
            p99 = float(row.get("p99_ms") or 0.0)
        except (TypeError, ValueError):
            p99 = 0.0
        out[f"{plat}:latency:{name}"] = {
            "ok": bool(row.get("ok", False)),
            "p99_ms": p99,
        }
    return out


def extract_proofs(doc):
    """{``platform:proofs:<clients>``: {"ok", "proofs_per_sec",
    "hit_rate", "p99_ms"}} from one round's ``proofs`` section
    (`bench.py --mode proofs` light-client replay rows; ``ok`` = every
    served artifact verified end to end)."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    section = parsed.get("proofs")
    if not isinstance(section, dict):
        return {}
    plat = _platform(parsed)
    out = {}
    for name, row in sorted(section.items()):
        if not isinstance(row, dict) or "verified" not in row:
            continue
        try:
            pps = float(row.get("proofs_per_sec") or 0.0)
        except (TypeError, ValueError):
            pps = 0.0
        try:
            hit = float(row.get("hit_rate") or 0.0)
        except (TypeError, ValueError):
            hit = 0.0
        try:
            p99 = float(row.get("p99_ms") or 0.0)
        except (TypeError, ValueError):
            p99 = 0.0
        out[f"{plat}:proofs:{name}"] = {
            "ok": bool(row.get("verified", False)),
            "proofs_per_sec": pps,
            "hit_rate": hit,
            "p99_ms": p99,
        }
    return out


def extract_merkle(doc):
    """{``platform:merkle:<cell>``: {"ok", "speedup"}} from one round's
    ``merkle`` section (`bench.py --mode merkle` native-vs-python
    Merkleization race cells; ``ok`` = the two paths' roots are
    bit-identical). Speedups and roots/sec are report-only."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    section = parsed.get("merkle")
    if not isinstance(section, dict):
        return {}
    plat = _platform(parsed)
    out = {}
    for name, row in sorted(section.items()):
        if not isinstance(row, dict) or "ok" not in row:
            continue
        try:
            speedup = float(row.get("speedup") or 0.0)
        except (TypeError, ValueError):
            speedup = 0.0
        out[f"{plat}:merkle:{name}"] = {
            "ok": bool(row.get("ok", False)),
            "speedup": speedup,
        }
    return out


def extract_mainnet(doc):
    """{``platform:mainnet:<section>``: {"ok", "atts_per_sec"}} from one
    round's ``mainnet`` section (`bench.py --mode mainnet` mainnet-scale
    slot-replay sections; ``ok`` = the section's correctness claim held —
    hierarchical verdicts matching the flat/oracle path, bisection
    localizing the planted bad committee, the censored sim converging
    through the strict gate, committee affinity staying put).
    Attestations/sec and every other throughput figure are report-only."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    section = parsed.get("mainnet")
    if not isinstance(section, dict):
        return {}
    plat = _platform(parsed)
    out = {}
    for name, row in sorted(section.items()):
        if not isinstance(row, dict) or "ok" not in row:
            continue
        try:
            aps = float(row.get("atts_per_sec") or 0.0)
        except (TypeError, ValueError):
            aps = 0.0
        out[f"{plat}:mainnet:{name}"] = {
            "ok": bool(row.get("ok", False)),
            "atts_per_sec": aps,
        }
    return out


def extract_vmexec(doc):
    """{``platform:vmexec:<kind,rows>``: {"ok", "fused_ms_row",
    "interp_ms_row"}} from one round's ``vmexec`` section (`bench.py
    --mode vmexec` interpreter-vs-fused execution race cells)."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    section = parsed.get("vmexec")
    if not isinstance(section, dict):
        return {}
    plat = _platform(parsed)
    out = {}
    for name, row in sorted(section.items()):
        if not isinstance(row, dict) or "ok" not in row:
            continue
        try:
            fused = float(row.get("fused_ms_row") or 0.0)
        except (TypeError, ValueError):
            fused = 0.0
        try:
            interp = float(row.get("interp_ms_row") or 0.0)
        except (TypeError, ValueError):
            interp = 0.0
        out[f"{plat}:vmexec:{name}"] = {
            "ok": bool(row.get("ok", False)),
            "fused_ms_row": fused,
            "interp_ms_row": interp,
        }
    return out


def extract_finalexp(doc):
    """{``platform:finalexp:<variant,rows>``: {"ok", "ms_per_row"}} from
    one round's ``finalexp`` section (`bench.py --mode finalexp` hard-part
    race cells)."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    section = parsed.get("finalexp")
    if not isinstance(section, dict):
        return {}
    plat = _platform(parsed)
    out = {}
    for name, row in sorted(section.items()):
        if not isinstance(row, dict) or "ok" not in row:
            continue
        try:
            ms = float(row.get("ms_per_row") or 0.0)
        except (TypeError, ValueError):
            ms = 0.0
        out[f"{plat}:finalexp:{name}"] = {
            "ok": bool(row.get("ok", False)),
            "ms_per_row": ms,
        }
    return out


def extract_health(doc):
    """{``platform:health:<scope>``: {"ok", "participation_min",
    "unexplained_reorgs"}} from one round's ``health`` section
    (`bench.py --mode soak` — the consensus health ledger's gate verdict
    over the whole horizon, aggregate plus per node)."""
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict) or "error" in parsed:
        return {}
    section = parsed.get("health")
    if not isinstance(section, dict):
        return {}
    gate = section.get("gate")
    if not isinstance(gate, dict):
        return {}
    plat = _platform(parsed)
    out = {}

    def row(scope, ok, summary):
        if not isinstance(summary, dict):
            return
        try:
            pmin = float(summary.get("participation_min", 0.0))
            reorgs = int(summary.get("unexplained_reorgs", 0))
        except (TypeError, ValueError):
            return
        out[f"{plat}:health:{scope}"] = {
            "ok": bool(ok),
            "participation_min": pmin,
            "unexplained_reorgs": reorgs,
        }

    row("aggregate", gate.get("ok", False), section.get("aggregate"))
    per_node = section.get("per_node")
    if isinstance(per_node, dict):
        agg_ok = bool(gate.get("ok", False))
        for name, summary in sorted(per_node.items()):
            # per-node rows inherit the aggregate verdict (the gate
            # judges the worst case; a node's own numbers are the trend
            # detail) — their participation/reorg numbers still land in
            # the table for the trajectory read
            row(name, agg_ok, summary)
    return out


def headline_trajectory(files):
    """One line tracing the headline metric across EVERY recorded round
    (not just newest vs previous): ``r01 12.3 → r02 14.1 → …`` per
    (platform, shape) key that appears in two or more rounds. The pair
    diff answers "did this round regress"; this answers "where has this
    number been heading" — the soak's whole reason to exist, applied to
    the bench ledger itself."""
    series = {}
    order = []
    for path in files:
        m = _ROUND_RE.search(os.path.basename(path))
        label = f"r{m.group(1)}" if m else os.path.basename(path)
        try:
            vals = extract(_load(path))
        except (OSError, ValueError):
            continue
        for key, value in vals.items():
            series.setdefault(key, []).append((label, value))
        order.append(label)
    lines = []
    for key in sorted(series):
        points = series[key]
        if len(points) < 2:
            continue
        path_s = " → ".join(f"{label} {value:.4g}"
                            for label, value in points)
        first, last = points[0][1], points[-1][1]
        total = (last - first) / first if first else 0.0
        lines.append(f"`{key}`: {path_s} ({total:+.1%} over "
                     f"{len(points)} rounds)")
    return lines


def _load(path):
    with open(path) as fh:
        return json.load(fh)


def _emit_markdown(rows, prev_name, new_name, threshold_pct,
                   trajectory=()):
    """The comparison as a GitHub-flavored markdown table: appended to
    ``$GITHUB_STEP_SUMMARY`` when CI provides one, stdout otherwise.
    ``rows`` are (key, old, new, delta_frac|None, status) tuples;
    ``trajectory`` are preformatted headline-trajectory lines spanning
    every recorded round (``headline_trajectory``)."""
    lines = [
        f"### bench-compare: `{prev_name}` → `{new_name}` "
        f"(allowed regression {threshold_pct:.0f}%)",
        "",
        "| key | previous | newest | delta | status |",
        "|---|---:|---:|---:|---|",
    ]
    for key, old, new, delta, status in rows:
        delta_s = "—" if delta is None else f"{delta:+.1%}"
        lines.append(
            f"| `{key}` | {old} | {new} | {delta_s} | {status} |")
    if trajectory:
        lines += ["", "**Headline trajectory (all rounds):**", ""]
        lines += [f"- {t}" for t in trajectory]
    body = "\n".join(lines) + "\n"
    summary_path = os.environ.get("GITHUB_STEP_SUMMARY")
    if summary_path:
        with open(summary_path, "a") as fh:
            fh.write(body + "\n")
    else:
        print(body, end="")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--dir",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="directory holding the BENCH_r*.json rounds (default: repo root)",
    )
    ap.add_argument(
        "--max-regression",
        type=float,
        default=float(os.environ.get("BENCH_COMPARE_MAX_REGRESSION", "30")),
        help="allowed headline drop in percent before failing (default 30)",
    )
    args = ap.parse_args(argv)

    files = round_files(args.dir)
    if not files:
        print("bench-compare: SKIP — no BENCH_r*.json rounds found")
        return 0
    newest = files[-1]
    try:
        newest_doc = _load(newest)
        new_vals = extract(newest_doc)
        new_slo = extract_slo(newest_doc)
        new_sim = extract_sim(newest_doc)
        new_mesh = extract_mesh(newest_doc)
        new_fx = extract_finalexp(newest_doc)
        new_vx = extract_vmexec(newest_doc)
        new_fleet = extract_fleet(newest_doc)
        new_lat = extract_latency(newest_doc)
        new_proofs = extract_proofs(newest_doc)
        new_merkle = extract_merkle(newest_doc)
        new_health = extract_health(newest_doc)
        new_mainnet = extract_mainnet(newest_doc)
    except (OSError, ValueError) as e:
        print(f"bench-compare: FAIL — {os.path.basename(newest)} unreadable: {e}")
        return 1
    if not new_vals:
        print(
            f"bench-compare: FAIL — newest round {os.path.basename(newest)} "
            "recorded no usable parsed value (error line or value<=0)"
        )
        return 1
    if len(files) == 1:
        print("bench-compare: SKIP — only one round; nothing to compare")
        return 0

    prev_vals, prev_slo, prev_sim, prev_mesh = {}, {}, {}, {}
    prev_fx, prev_vx, prev_fleet, prev_lat = {}, {}, {}, {}
    prev_proofs, prev_merkle, prev_health, prev_path = {}, {}, {}, None
    prev_mainnet = {}
    for path in reversed(files[:-1]):
        try:
            doc = _load(path)
            prev_vals = extract(doc)
            prev_slo = extract_slo(doc)
            prev_sim = extract_sim(doc)
            prev_mesh = extract_mesh(doc)
            prev_fx = extract_finalexp(doc)
            prev_vx = extract_vmexec(doc)
            prev_fleet = extract_fleet(doc)
            prev_lat = extract_latency(doc)
            prev_proofs = extract_proofs(doc)
            prev_merkle = extract_merkle(doc)
            prev_health = extract_health(doc)
            prev_mainnet = extract_mainnet(doc)
        except (OSError, ValueError):
            prev_vals, prev_slo, prev_sim = {}, {}, {}
            prev_mesh, prev_fx, prev_vx = {}, {}, {}
            prev_fleet, prev_lat, prev_proofs = {}, {}, {}
            prev_merkle, prev_health, prev_mainnet = {}, {}, {}
        # an SLO-only or sim-only round (headline errored, objectives or
        # scenario matrix still recorded) is a usable baseline for its
        # state gate even with no throughput number
        if (prev_vals or prev_slo or prev_sim or prev_mesh or prev_fx
                or prev_vx or prev_fleet or prev_lat or prev_proofs
                or prev_merkle or prev_health or prev_mainnet):
            prev_path = path
            break
    if not (prev_vals or prev_slo or prev_sim or prev_mesh or prev_fx
            or prev_vx or prev_fleet or prev_lat or prev_proofs
            or prev_merkle or prev_health or prev_mainnet):
        print("bench-compare: SKIP — no earlier round recorded a usable value")
        return 0

    common = sorted(set(new_vals) & set(prev_vals))
    slo_common = sorted(set(new_slo) & set(prev_slo))
    sim_common = sorted(set(new_sim) & set(prev_sim))
    mesh_common = sorted(set(new_mesh) & set(prev_mesh))
    fx_common = sorted(set(new_fx) & set(prev_fx))
    vx_common = sorted(set(new_vx) & set(prev_vx))
    fleet_common = sorted(set(new_fleet) & set(prev_fleet))
    lat_common = sorted(set(new_lat) & set(prev_lat))
    proofs_common = sorted(set(new_proofs) & set(prev_proofs))
    merkle_common = sorted(set(new_merkle) & set(prev_merkle))
    health_common = sorted(set(new_health) & set(prev_health))
    mainnet_common = sorted(set(new_mainnet) & set(prev_mainnet))
    if (not common and not slo_common and not sim_common
            and not mesh_common and not fx_common and not vx_common
            and not fleet_common and not lat_common and not proofs_common
            and not merkle_common and not health_common
            and not mainnet_common):
        # SLO keys count as comparables too: two rounds that share no
        # throughput shape but both declare serve_p99 must still gate the
        # objective state, not skip past it
        print(
            "bench-compare: SKIP — no comparable (platform, shape) keys "
            f"between {os.path.basename(prev_path)} "
            f"({', '.join(sorted(prev_vals))}) and "
            f"{os.path.basename(newest)} ({', '.join(sorted(new_vals))})"
        )
        return 0

    threshold = args.max_regression / 100.0
    failures = []
    rows = []  # markdown table source
    print(
        f"bench-compare: {os.path.basename(prev_path)} -> "
        f"{os.path.basename(newest)} (allowed regression "
        f"{args.max_regression:.0f}%)"
    )
    for key in common:
        old, new = prev_vals[key], new_vals[key]
        delta = (new - old) / old
        marker = "  REGRESSION" if delta < -threshold else ""
        print(f"  {key}: {old:.2f} -> {new:.2f} ({delta:+.1%}){marker}")
        rows.append((key, f"{old:.2f}", f"{new:.2f}", delta,
                     "REGRESSION" if delta < -threshold else "ok"))
        if delta < -threshold:
            failures.append(key)

    # SLO state gate: a previously-met objective the newest round violates
    # fails outright; margin jitter within "met" is report-only (tail
    # latencies flap far more than throughput — the page-worthy event is
    # crossing the objective)
    for key in slo_common:
        old, new = prev_slo[key], new_slo[key]
        violated = old["ok"] and not new["ok"]
        status = "SLO VIOLATED" if violated else (
            "ok" if new["ok"] else "still violated")
        print(
            f"  {key}: margin {old['margin']:.2f} -> {new['margin']:.2f} "
            f"(ok: {old['ok']} -> {new['ok']}){'  ' + status if violated else ''}"
        )
        rows.append((key, f"{old['margin']:.2f}x", f"{new['margin']:.2f}x",
                     (new["margin"] - old["margin"]) / old["margin"]
                     if old["margin"] else None,
                     status))
        if violated:
            failures.append(key)

    # simnet convergence gate: same state-not-jitter rule as SLO — a
    # scenario that stops converging is a correctness regression and
    # fails outright; heal-latency movement is report-only
    for key in sim_common:
        old, new = prev_sim[key], new_sim[key]
        diverged = old["converged"] and not new["converged"]
        status = "SIM DIVERGED" if diverged else (
            "ok" if new["converged"] else "still diverged")
        print(
            f"  {key}: heal {old['heal_s']:.2f}s -> {new['heal_s']:.2f}s "
            f"(converged: {old['converged']} -> {new['converged']})"
            f"{'  ' + status if diverged else ''}"
        )
        rows.append((key, f"{old['heal_s']:.2f}s", f"{new['heal_s']:.2f}s",
                     (new["heal_s"] - old["heal_s"]) / old["heal_s"]
                     if old["heal_s"] else None,
                     status))
        if diverged:
            failures.append(key)

    # mesh state gate: a device count that verified last round and errors
    # now fails outright; sigs/sec + efficiency at each count are
    # report-only (CPU virtual devices cannot demonstrate real scaling)
    for key in mesh_common:
        old, new = prev_mesh[key], new_mesh[key]
        broke = old["ok"] and not new["ok"]
        status = "MESH ERRORED" if broke else (
            "ok" if new["ok"] else "still erroring")
        eff = new.get("efficiency")
        eff_s = f", efficiency {eff:.2f}" if isinstance(eff, float) else ""
        print(
            f"  {key}: {old['sigs_per_sec']:.2f} -> "
            f"{new['sigs_per_sec']:.2f} sigs/sec (ok: {old['ok']} -> "
            f"{new['ok']}{eff_s}){'  ' + status if broke else ''}"
        )
        rows.append((key, f"{old['sigs_per_sec']:.2f}",
                     f"{new['sigs_per_sec']:.2f}",
                     (new["sigs_per_sec"] - old["sigs_per_sec"])
                     / old["sigs_per_sec"]
                     if old["sigs_per_sec"] else None,
                     status))
        if broke:
            failures.append(key)

    # fleet state gate: a worker count that verified (correct verdicts +
    # exact merged scrape) last round and errors now fails outright —
    # "FLEET ERRORED", the mesh-gate mirror: losing a working fleet size
    # is an availability regression; per-count sigs/sec and the 2-worker
    # speedup are report-only (process scaling on the shared CI host
    # jitters like every other CPU number)
    for key in fleet_common:
        old, new = prev_fleet[key], new_fleet[key]
        broke = old["ok"] and not new["ok"]
        status = "FLEET ERRORED" if broke else (
            "ok" if new["ok"] else "still erroring")
        print(
            f"  {key}: {old['sigs_per_sec']:.2f} -> "
            f"{new['sigs_per_sec']:.2f} sigs/sec (ok: {old['ok']} -> "
            f"{new['ok']}){'  ' + status if broke else ''}"
        )
        rows.append((key, f"{old['sigs_per_sec']:.2f}",
                     f"{new['sigs_per_sec']:.2f}",
                     (new["sigs_per_sec"] - old["sigs_per_sec"])
                     / old["sigs_per_sec"]
                     if old["sigs_per_sec"] else None,
                     status))
        if broke:
            failures.append(key)

    # latency state gate (ISSUE 12): a scenario whose deadline-mode
    # gossip_to_head_p99 met the declared objective last round and
    # VIOLATES it (or stops converging / stops observing) now fails
    # outright — "LATENCY SLO VIOLATED", the SLO-state mirror for the
    # end-to-end plane; the p99 milliseconds themselves are report-only
    # (CPU tail latencies jitter, the page-worthy event is the crossing)
    for key in lat_common:
        old, new = prev_lat[key], new_lat[key]
        violated = old["ok"] and not new["ok"]
        status = "LATENCY SLO VIOLATED" if violated else (
            "ok" if new["ok"] else "still violated")
        print(
            f"  {key}: p99 {old['p99_ms']:.2f}ms -> {new['p99_ms']:.2f}ms "
            f"(ok: {old['ok']} -> {new['ok']})"
            f"{'  ' + status if violated else ''}"
        )
        rows.append((key, f"{old['p99_ms']:.2f}ms", f"{new['p99_ms']:.2f}ms",
                     (new["p99_ms"] - old["p99_ms"]) / old["p99_ms"]
                     if old["p99_ms"] else None,
                     status))
        if violated:
            failures.append(key)

    # proofs state gate (ISSUE 16): a light-client replay shape whose
    # every served artifact verified last round and stops verifying now
    # fails outright — "PROOFS DIVERGED", the sim-gate mirror for the
    # read path: a proof plane serving unverifiable bytes is a
    # correctness regression; proofs/sec, cache hit rate, and p99 are
    # report-only (CPU serve throughput jitters like every other number)
    for key in proofs_common:
        old, new = prev_proofs[key], new_proofs[key]
        diverged = old["ok"] and not new["ok"]
        status = "PROOFS DIVERGED" if diverged else (
            "ok" if new["ok"] else "still diverged")
        print(
            f"  {key}: {old['proofs_per_sec']:.2f} -> "
            f"{new['proofs_per_sec']:.2f} proofs/sec (hit "
            f"{old['hit_rate']:.4f} -> {new['hit_rate']:.4f}, p99 "
            f"{new['p99_ms']:.2f}ms; verified: {old['ok']} -> "
            f"{new['ok']}){'  ' + status if diverged else ''}"
        )
        rows.append((key, f"{old['proofs_per_sec']:.2f}",
                     f"{new['proofs_per_sec']:.2f}",
                     (new["proofs_per_sec"] - old["proofs_per_sec"])
                     / old["proofs_per_sec"]
                     if old["proofs_per_sec"] else None,
                     status))
        if diverged:
            failures.append(key)

    # merkle state gate (ISSUE 18): a Merkleization race cell whose
    # native and python roots were bit-identical last round and diverge
    # now fails outright — "MERKLE DIVERGED", the proofs-gate mirror for
    # the hashing plane: a native hash_tree_root that stops matching the
    # pure-python oracle is a consensus-correctness regression, not a
    # perf number; the speedup movement (cold, incremental, proof-world)
    # is report-only like every other CPU throughput figure
    for key in merkle_common:
        old, new = prev_merkle[key], new_merkle[key]
        diverged = old["ok"] and not new["ok"]
        status = "MERKLE DIVERGED" if diverged else (
            "ok" if new["ok"] else "still diverged")
        print(
            f"  {key}: {old['speedup']:.2f}x -> {new['speedup']:.2f}x "
            f"native speedup (bit-identical: {old['ok']} -> {new['ok']})"
            f"{'  ' + status if diverged else ''}"
        )
        rows.append((key, f"{old['speedup']:.2f}x", f"{new['speedup']:.2f}x",
                     (new["speedup"] - old["speedup"]) / old["speedup"]
                     if old["speedup"] else None,
                     status))
        if diverged:
            failures.append(key)

    # finalexp state gate: a hard-part variant cell that worked last round
    # and errors (or returns wrong verdicts) now fails outright — losing a
    # finalization variant is a correctness/availability regression; the
    # ms/row movement (including a device route losing to host) is
    # report-only, exactly like mesh sigs/sec
    for key in fx_common:
        old, new = prev_fx[key], new_fx[key]
        broke = old["ok"] and not new["ok"]
        status = "FINALEXP ERRORED" if broke else (
            "ok" if new["ok"] else "still erroring")
        print(
            f"  {key}: {old['ms_per_row']:.2f} -> {new['ms_per_row']:.2f} "
            f"ms/row (ok: {old['ok']} -> {new['ok']})"
            f"{'  ' + status if broke else ''}"
        )
        rows.append((key, f"{old['ms_per_row']:.2f}ms",
                     f"{new['ms_per_row']:.2f}ms",
                     (new["ms_per_row"] - old["ms_per_row"])
                     / old["ms_per_row"]
                     if old["ms_per_row"] else None,
                     status))
        if broke:
            failures.append(key)

    # vmexec state gate: an execution-backend race cell that was ok
    # (fused ran AND matched the interpreter bit for bit) last round and
    # errors or mismatches now fails outright — "VMEXEC ERRORED", the
    # finalexp-gate mirror for the lowering plane: losing the fused
    # backend (or bit-identity) on a program kind is a correctness/
    # availability regression; the ms/row movement either way is
    # report-only, exactly like finalexp ms/row
    for key in vx_common:
        old, new = prev_vx[key], new_vx[key]
        broke = old["ok"] and not new["ok"]
        status = "VMEXEC ERRORED" if broke else (
            "ok" if new["ok"] else "still erroring")
        print(
            f"  {key}: fused {old['fused_ms_row']:.2f} -> "
            f"{new['fused_ms_row']:.2f} ms/row (interp "
            f"{new['interp_ms_row']:.2f}; ok: {old['ok']} -> {new['ok']})"
            f"{'  ' + status if broke else ''}"
        )
        rows.append((key, f"{old['fused_ms_row']:.2f}ms",
                     f"{new['fused_ms_row']:.2f}ms",
                     (new["fused_ms_row"] - old["fused_ms_row"])
                     / old["fused_ms_row"]
                     if old["fused_ms_row"] else None,
                     status))
        if broke:
            failures.append(key)

    # consensus-health state gate (`bench.py --mode soak`): a soak whose
    # gate held in the previous round and reports DIVERGED now fails
    # outright — "HEALTH DIVERGED" (participation under the floor, a
    # finality-lag bound crossed, or reorgs outside declared disruption
    # windows are all slow-burn correctness regressions, not perf
    # jitter); participation movement within a green gate is report-only
    for key in health_common:
        old, new = prev_health[key], new_health[key]
        broke = old["ok"] and not new["ok"]
        status = "HEALTH DIVERGED" if broke else (
            "ok" if new["ok"] else "still diverged")
        print(
            f"  {key}: participation_min {old['participation_min']:.4f} -> "
            f"{new['participation_min']:.4f} (unexplained reorgs "
            f"{old['unexplained_reorgs']} -> {new['unexplained_reorgs']}; "
            f"ok: {old['ok']} -> {new['ok']})"
            f"{'  ' + status if broke else ''}"
        )
        rows.append((key, f"{old['participation_min']:.4f}",
                     f"{new['participation_min']:.4f}",
                     (new["participation_min"] - old["participation_min"])
                     / old["participation_min"]
                     if old["participation_min"] else None,
                     status))
        if broke:
            failures.append(key)

    # mainnet state gate (ISSUE 20): a mainnet-scale replay section whose
    # correctness claim held last round and breaks now fails outright —
    # "MAINNET DIVERGED". Each section's ok is a verdict-identity claim
    # (hierarchical fold matching the flat/oracle path under budget, the
    # planted bad committee localized exactly, censored_aggregates
    # converging through the strict sim gate, committee affinity with
    # zero moves) — losing any of them at million-validator shape is a
    # consensus-correctness regression; attestations/sec movement is
    # report-only like every other CPU throughput figure
    for key in mainnet_common:
        old, new = prev_mainnet[key], new_mainnet[key]
        diverged = old["ok"] and not new["ok"]
        status = "MAINNET DIVERGED" if diverged else (
            "ok" if new["ok"] else "still diverged")
        print(
            f"  {key}: {old['atts_per_sec']:.1f} -> "
            f"{new['atts_per_sec']:.1f} atts/sec "
            f"(ok: {old['ok']} -> {new['ok']})"
            f"{'  ' + status if diverged else ''}"
        )
        rows.append((key, f"{old['atts_per_sec']:.1f}",
                     f"{new['atts_per_sec']:.1f}",
                     (new["atts_per_sec"] - old["atts_per_sec"])
                     / old["atts_per_sec"]
                     if old["atts_per_sec"] else None,
                     status))
        if diverged:
            failures.append(key)

    _emit_markdown(rows, os.path.basename(prev_path),
                   os.path.basename(newest), args.max_regression,
                   trajectory=headline_trajectory(files))
    if failures:
        print(
            f"bench-compare: FAIL — regressed past the gate on: "
            f"{', '.join(failures)}"
        )
        return 1
    print(
        f"bench-compare: OK — {len(common)} comparable key(s) within "
        f"bounds" + (f", {len(slo_common)} SLO key(s) met"
                     if slo_common else "")
        + (f", {len(sim_common)} sim scenario(s) gated"
           if sim_common else "")
        + (f", {len(mesh_common)} mesh device count(s) gated"
           if mesh_common else "")
        + (f", {len(fx_common)} finalexp cell(s) gated"
           if fx_common else "")
        + (f", {len(vx_common)} vmexec cell(s) gated"
           if vx_common else "")
        + (f", {len(fleet_common)} fleet worker count(s) gated"
           if fleet_common else "")
        + (f", {len(lat_common)} latency scenario(s) gated"
           if lat_common else "")
        + (f", {len(proofs_common)} proof shape(s) gated"
           if proofs_common else "")
        + (f", {len(merkle_common)} merkle cell(s) gated"
           if merkle_common else "")
        + (f", {len(health_common)} health scope(s) gated"
           if health_common else "")
        + (f", {len(mainnet_common)} mainnet section(s) gated"
           if mainnet_common else "")
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
