"""Vector-tree sanity checker: validate an emitted test-vector tree's
layout and completeness (the consumer-side counterpart of gen_runner).

Checks, per the reference's <preset>/<fork>/<runner>/<handler>/<suite>/<case>
hierarchy (reference gen_helpers/gen_base/gen_runner.py:121-125):
- every case directory sits at exactly depth 6 and contains at least one
  part file (*.yaml / *.ssz_snappy);
- no INCOMPLETE sentinels remain (crash containment: a sentinel means the
  producing run died mid-case, gen_runner.py INCOMPLETE lifecycle);
- ssz_snappy parts decompress with the repo's own codec.

Usage: python tools/check_vectors.py VECTORS_DIR [--decode-sample N]
                                     [--report PATH]
Prints a per-runner case-count table and exits nonzero on any violation.
``--report`` additionally writes the table + verdict as a markdown file —
the committed, reproducible evidence of a sweep (`make sweep` regenerates
tree and report; round-4 verdict: vector evidence must persist in-repo).
"""
import argparse
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("vectors_dir")
    ap.add_argument("--decode-sample", type=int, default=25,
                    help="ssz_snappy parts to decompress as a spot check")
    ap.add_argument("--report", default=None,
                    help="also write the table + verdict as markdown here")
    args = ap.parse_args()
    root = args.vectors_dir

    incomplete = []
    empty_cases = []
    counts = {}  # (preset, fork, runner) -> cases
    snappy_parts = []

    for dirpath, dirnames, filenames in os.walk(root):
        rel = os.path.relpath(dirpath, root)
        parts = [] if rel == "." else rel.split(os.sep)
        if "INCOMPLETE" in filenames or "INCOMPLETE" in dirnames:
            incomplete.append(rel)
        if len(parts) == 6:  # a case dir
            data_files = [
                f for f in filenames
                if f.endswith((".yaml", ".ssz_snappy"))
            ]
            if not data_files:
                empty_cases.append(rel)
            key = tuple(parts[:3])
            counts[key] = counts.get(key, 0) + 1
            snappy_parts.extend(
                os.path.join(dirpath, f) for f in filenames
                if f.endswith(".ssz_snappy")
            )

    print(f"{'preset':<9} {'fork':<13} {'runner':<18} cases")
    for (preset, fork, runner), n in sorted(counts.items()):
        print(f"{preset:<9} {fork:<13} {runner:<18} {n}")
    total = sum(counts.values())
    print(f"total cases: {total}")

    ok = True
    if incomplete:
        ok = False
        print(f"FAIL: {len(incomplete)} INCOMPLETE sentinel(s), e.g. {incomplete[:3]}")
    if empty_cases:
        ok = False
        print(f"FAIL: {len(empty_cases)} case dir(s) with no parts, e.g. {empty_cases[:3]}")
    if total == 0:
        ok = False
        print("FAIL: no cases found")

    if snappy_parts and args.decode_sample:
        from consensus_specs_tpu.utils.snappy import decompress

        sample = random.Random(7).sample(
            snappy_parts, min(args.decode_sample, len(snappy_parts))
        )
        bad = 0
        for path in sample:
            try:
                with open(path, "rb") as f:
                    decompress(f.read())
            except Exception as e:
                bad += 1
                print(f"FAIL: {path}: {type(e).__name__}: {e}")
        print(f"ssz_snappy spot check: {len(sample) - bad}/{len(sample)} decode")
        ok = ok and bad == 0

    if args.report:
        lines = [
            "# Vector sweep report",
            "",
            f"Generated {time.strftime('%Y-%m-%d %H:%M UTC', time.gmtime())} "
            f"by `tools/check_vectors.py {root}` (regenerate: `make sweep`).",
            "",
            "| preset | fork | runner | cases |",
            "|---|---|---|---|",
        ]
        lines += [
            f"| {p} | {f} | {r} | {n} |"
            for (p, f, r), n in sorted(counts.items())
        ]
        lines += [
            "",
            f"- total cases: **{total}**",
            f"- INCOMPLETE sentinels: {len(incomplete)}",
            f"- empty case dirs: {len(empty_cases)}",
            f"- ssz_snappy parts: {len(snappy_parts)}",
            f"- verdict: **{'PASS' if ok else 'FAIL'}**",
            "",
        ]
        with open(args.report, "w") as f:
            f.write("\n".join(lines))
        print(f"report written: {args.report}")

    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
