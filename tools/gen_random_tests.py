"""Code-generate the `random` scenario-matrix test modules.

Role parity with the reference's random test codegen (reference
tests/generators/random/generate.py writes test_random.py files from a
scenario matrix because the test infra cannot synthesize pytest-visible
cases dynamically — same constraint here). Run from the repo root:

    python tools/gen_random_tests.py      # or: make generate_random_tests

Scenario vocabulary/matrix: consensus_specs_tpu/test/utils/scenario_matrix.py.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.test.utils.scenario_matrix import (  # noqa: E402
    scenario_matrix,
    scenario_name,
)

_HEADER = '''"""Code-generated randomized scenario-matrix tests — DO NOT EDIT.

Regenerate with `make generate_random_tests` (tools/gen_random_tests.py);
the vocabulary/matrix lives in test/utils/scenario_matrix.py. Mirrors the
reference's code-generated random suites (reference
tests/generators/random/generate.py)."""
from ...context import {fork_const}, spec_state_test, with_phases
from ...utils.scenario_matrix import run_matrix_scenario

'''

_CASE = '''
@with_phases([{fork_const}])
@spec_state_test
def test_{name}(spec, state):
    yield from run_matrix_scenario(
        spec, state,
        profile={profile!r}, timing={timing!r}, stressor={stressor!r},
        seed={seed},
    )

'''

_TARGETS = {
    "phase0": ("PHASE0", "consensus_specs_tpu/test/phase0/random/test_random_matrix.py"),
    "altair": ("ALTAIR", "consensus_specs_tpu/test/altair/random/test_random_matrix.py"),
}


def render(fork: str) -> str:
    fork_const, _ = _TARGETS[fork]
    parts = [_HEADER.format(fork_const=fork_const)]
    for i, (profile, timing, stressor) in enumerate(scenario_matrix()):
        parts.append(_CASE.format(
            fork_const=fork_const,
            name=scenario_name(profile, timing, stressor),
            profile=profile, timing=timing, stressor=stressor,
            # distinct deterministic seed per (fork, cell)
            seed=10_000 * (1 + list(_TARGETS).index(fork)) + i,
        ))
    return "".join(parts)


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for fork, (_, rel) in _TARGETS.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        init = os.path.join(os.path.dirname(path), "__init__.py")
        if not os.path.exists(init):
            open(init, "w").close()
        with open(path, "w") as f:
            f.write(render(fork))
        print(f"wrote {rel} ({len(scenario_matrix())} cases)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
