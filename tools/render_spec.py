"""Render the executable spec sources into the per-fork markdown document
set (docs/specs/<fork>/<doc>.md + index).

The reference's markdown under specs/ is simultaneously its spec SOURCE
and the client-team documentation; this repo authors the semantics as
Python (specsrc/, the SURVEY §7.2-sanctioned alternative), so the
human-readable document set is GENERATED from it instead: one markdown
document per specsrc module, with the module's section banners as
headings, constants grouped into tables-of-code, and every container and
function as an anchored, navigable block. `make docs` regenerates;
tests/test_render_spec.py checks the tree stays complete.
"""
import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SPECSRC = os.path.join(REPO, "consensus_specs_tpu", "specsrc")
OUT = os.path.join(REPO, "docs", "specs")

_TITLES = {
    "beacon_chain": "The Beacon Chain",
    "fork_choice": "Fork Choice",
    "validator": "Honest Validator",
    "p2p": "Networking (computable parts)",
    "client_settings": "Client Settings (TTD override)",
    "weak_subjectivity": "Weak Subjectivity",
    "fork": "Fork Transition",
    "bls": "BLS Extensions",
    "sync_protocol": "Light Client Sync Protocol",
    "das": "Data Availability Sampling",
    "custody_game": "Custody Game",
    "shard_transition": "Shard Transition",
}


def _sections(src: str):
    """(lineno, title) for every `# --- / # Title / # ---` banner."""
    lines = src.splitlines()
    out = []
    for i, line in enumerate(lines):
        if re.match(r"#\s*-{10,}", line) and i + 1 < len(lines):
            m = re.match(r"#\s+(.+)", lines[i + 1])
            if m and not re.match(r"-{5,}", m.group(1)):
                out.append((i + 1, m.group(1).strip()))
    return out


def _header_comment(src: str) -> str:
    out = []
    for line in src.splitlines():
        if re.match(r"#\s*-{10,}", line):
            break  # the first section banner ends the header
        if line.startswith("#"):
            out.append(line.lstrip("# ").rstrip())
        elif line.strip():
            break
    return "\n".join(out).strip()


def render_module(fork: str, name: str, src: str) -> str:
    tree = ast.parse(src)
    sections = _sections(src)
    title = _TITLES.get(name, name.replace("_", " ").title())

    md = [f"# {fork} — {title}", ""]
    header = _header_comment(src)
    if header:
        md += [header, ""]

    def section_for(lineno: int):
        current = None
        for sec_line, sec_title in sections:
            if sec_line < lineno:
                current = sec_title
            else:
                break
        return current

    emitted_sections = set()
    const_run = []  # accumulated top-level assignment source lines
    src_lines = src.splitlines()

    def flush_consts():
        if const_run:
            md.append("```python")
            md.extend(const_run)
            md.append("```")
            md.append("")
            const_run.clear()

    for node in tree.body:
        sec = section_for(node.lineno)
        if sec is not None and sec not in emitted_sections:
            flush_consts()
            emitted_sections.add(sec)
            md.append(f"## {sec}")
            md.append("")
        seg = src_lines[node.lineno - 1 : node.end_lineno]
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            const_run.extend(seg)
        elif isinstance(node, (ast.ClassDef, ast.FunctionDef)):
            flush_consts()
            kind = "class" if isinstance(node, ast.ClassDef) else "def"
            md.append(f"### `{node.name}`" + (" (container)" if kind == "class" else ""))
            md.append("")
            md.append("```python")
            md.extend(seg)
            md.append("```")
            md.append("")
    flush_consts()
    return "\n".join(md) + "\n"


def main() -> int:
    index = [
        "# Specification documents",
        "",
        "Generated from the executable spec sources (`consensus_specs_tpu/"
        "specsrc/`) by `make docs` — do not edit by hand; the Python IS the "
        "normative spec, these documents are its reviewable rendering.",
        "",
    ]
    total = 0
    for fork in sorted(os.listdir(SPECSRC)):
        fork_dir = os.path.join(SPECSRC, fork)
        if not os.path.isdir(fork_dir) or fork.startswith("__"):
            continue
        index.append(f"## {fork}")
        index.append("")
        out_dir = os.path.join(OUT, fork)
        os.makedirs(out_dir, exist_ok=True)
        for fn in sorted(os.listdir(fork_dir)):
            if not fn.endswith(".py") or fn.startswith("__"):
                continue
            name = fn[:-3]
            with open(os.path.join(fork_dir, fn)) as f:
                src = f.read()
            doc = render_module(fork, name, src)
            out_path = os.path.join(out_dir, f"{name}.md")
            with open(out_path, "w") as f:
                f.write(doc)
            rel = os.path.relpath(out_path, OUT)
            index.append(f"- [{_TITLES.get(name, name)}]({rel})")
            total += 1
        index.append("")
    with open(os.path.join(OUT, "index.md"), "w") as f:
        f.write("\n".join(index) + "\n")
    print(f"rendered {total} spec documents under {os.path.relpath(OUT, REPO)}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
