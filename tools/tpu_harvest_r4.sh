#!/bin/bash
# Round-4 persistent TPU harvest loop: retry the north-star epoch bench
# against the intermittent axon tunnel, TPU-child-only (no CPU-fallback
# burn — the fallback numbers are recorded separately and the host CPUs
# are needed for the build session running alongside).
#
# Every attempt invokes bench.py's deadline-guarded CHILD directly on the
# inherited (axon) platform: partial JSON lines flush after setup / warmup /
# every rep, so a window that dies mid-run still lands its best number in
# the log. After the FIRST successful epoch line, each later success also
# triggers one staged probe run (u64-vs-u32 ratio + Pallas A/B,
# tools/tpu_probe.py) to answer the representation questions in the same
# grant pattern.
#
# Usage: tools/tpu_harvest_r4.sh [out.jsonl] — loops until killed.
OUT=${1:-/tmp/tpu_harvest_r4.jsonl}
cd "$(dirname "$0")/.." || exit 1
i=0
while true; do
  i=$((i + 1))
  echo "=== attempt $i epoch $(date -u +%H:%M:%S) ===" >> "$OUT"
  ATT=$(mktemp)
  CONSENSUS_SPECS_TPU_BENCH_CHILD=1 BENCH_MODE=epoch \
    timeout 900 python bench.py > "$ATT" 2>/dev/null
  cat "$ATT" >> "$OUT"
  if grep -q '"platform": "axon"\|"platform": "tpu"' "$ATT"; then
    echo "=== attempt $i probe $(date -u +%H:%M:%S) ===" >> "$OUT"
    timeout 650 python tools/tpu_probe.py >> "$OUT" 2>&1
  fi
  rm -f "$ATT"
  sleep 10
done
