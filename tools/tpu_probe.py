import sys, time, faulthandler, os
"""Staged axon/TPU diagnostic: init -> u32 -> u64 -> mont_mul vs oracle.

See TPU_NOTES.md. Each stage prints latency or the failure; a watchdog dumps
the blocking stack and exits if any stage hangs >540s."""
log = open('/tmp/tpu_probe_evidence.txt', 'a', buffering=1)
def p(*a): print(*a, file=log); print(*a, flush=True)
p('=== probe start', time.strftime('%H:%M:%S'), 'JAX_PLATFORMS=', os.environ.get('JAX_PLATFORMS'))
def arm(seconds=540):
    # re-armed at each stage boundary: the deadline is per stage, not global
    faulthandler.dump_traceback_later(seconds, exit=True, file=log)
arm()
t0=time.time(); import jax; p('import jax %.1fs' % (time.time()-t0))
# mirror the env var into the live config: a bare env JAX_PLATFORMS=cpu
# does NOT stop jax from initializing every registered platform (the axon
# tunnel included) on the first device op — TPU_NOTES.md failure mode 4
_plat = os.environ.get('JAX_PLATFORMS')
if _plat:
    jax.config.update('jax_platforms', _plat)
t0=time.time()
try:
    d = jax.devices()
    p('devices %.1fs:' % (time.time()-t0), d)
except Exception as e:
    p('devices FAILED %.1fs: %r' % (time.time()-t0, e)); sys.exit(1)
import jax.numpy as jnp
arm()
for name, fn in [
    ('device_put_u32', lambda: jax.device_put(jnp.arange(8, dtype=jnp.uint32)).block_until_ready()),
    ('u32_mul', lambda: (jax.device_put(jnp.arange(8, dtype=jnp.uint32))**2).block_until_ready()),
]:
    t0=time.time()
    try:
        fn(); p('%s OK %.1fs' % (name, time.time()-t0))
    except Exception as e:
        p('%s FAILED %.1fs: %r' % (name, time.time()-t0, repr(e)[:300]))
jax.config.update('jax_enable_x64', True)
arm()
for name, fn in [
    ('device_put_u64', lambda: jax.device_put(jnp.arange(8, dtype=jnp.uint64)).block_until_ready()),
    ('u64_mulshift', lambda: ((jax.device_put(jnp.arange(8, dtype=jnp.uint64))*jnp.uint64(12345678901))>>jnp.uint64(28)).block_until_ready()),
]:
    t0=time.time()
    try:
        fn(); p('%s OK %.1fs' % (name, time.time()-t0))
    except Exception as e:
        p('%s FAILED %.1fs: %r' % (name, time.time()-t0, repr(e)[:300]))
# mont_mul primitive
arm()
t0=time.time()
try:
    sys.path.insert(0, '/root/repo')
    from consensus_specs_tpu.ops import fq
    import numpy as np
    a = fq.to_mont_int(0x1234567890abcdef); b = fq.to_mont_int(0xfedcba987654321)
    out = np.asarray(fq.mont_mul(a, b))
    got = fq.from_mont_limbs(out)
    # mont_mul(aR,bR)=abR and from_mont_limbs strips the R factor -> a*b
    want = (0x1234567890abcdef * 0xfedcba987654321) % fq.P
    p('mont_mul OK %.1fs match=%s' % (time.time()-t0, got == want))
except Exception as e:
    p('mont_mul FAILED %.1fs: %r' % (time.time()-t0, repr(e)[:400]))
# u64-vs-u32 representation shoot-out (SURVEY risk #1): batched mont_mul
# throughput of the production 15x28-bit/u64 path against the fq32
# 32x12-bit/u32 fallback, on whatever device granted
arm()
try:
    from consensus_specs_tpu.ops import fq32
    import numpy as np

    def bench_rep(mod, tag, batch=4096, iters=32):
        xs = [(i * 0x9E3779B97F4A7C15 + 1) % mod.P for i in range(batch)]
        a = np.stack([mod.to_mont_int(x) for x in xs])
        b = np.stack([mod.to_mont_int((x * 7 + 3) % mod.P) for x in xs])
        da, db = jax.device_put(a), jax.device_put(b)
        # baseline the raw representation, not fq.mont_mul's dispatcher —
        # under CONSENSUS_SPECS_TPU_PALLAS=1 the latter IS the Pallas kernel
        mm = getattr(mod, 'mont_mul_u64', mod.mont_mul)
        f = jax.jit(lambda u, v: mm(u, v))
        t0 = time.time(); f(da, db).block_until_ready()
        compile_s = time.time() - t0
        t0 = time.time()
        out = da
        for _ in range(iters):
            out = f(out, db)
        out.block_until_ready()
        dt = time.time() - t0
        rate = batch * iters / dt
        # correctness of the chained product on one lane
        got = mod.from_mont_limbs(np.asarray(out)[0])
        want = xs[0]
        for _ in range(iters):
            want = want * ((xs[0] * 7 + 3) % mod.P) % mod.P
        p('%s mont_mul %.0f mul/s (compile %.1fs, run %.2fs) match=%s'
          % (tag, rate, compile_s, dt, got == want))
        return rate

    r64 = bench_rep(fq, 'fq_u64')
    r32 = bench_rep(fq32, 'fq32_u32')
    p('representation ratio u32/u64 = %.2fx' % (r32 / r64))
except Exception as e:
    p('rep shootout FAILED: %r' % (repr(e)[:400]))
# Pallas mont_mul kernel (ops/pallas_fq.py): first Mosaic compile + A/B vs
# the jnp u64 lowering on the granted device. This measurement decides
# whether fq.mont_mul's CONSENSUS_SPECS_TPU_PALLAS dispatch defaults on.
arm()
try:
    from consensus_specs_tpu.ops import pallas_fq
    import numpy as np

    batch, iters = 4096, 32
    xs = [(i * 0x9E3779B97F4A7C15 + 1) % fq.P for i in range(batch)]
    a = np.stack([fq.to_mont_int(x) for x in xs])
    b = np.stack([fq.to_mont_int((x * 7 + 3) % fq.P) for x in xs])
    da, db = jax.device_put(a), jax.device_put(b)
    # jit-wrapped exactly like bench_rep's jnp baseline so the A/B compares
    # one compiled computation per iteration on both sides
    fp = jax.jit(pallas_fq.mont_mul)
    t0 = time.time()
    out = fp(da, db)
    out.block_until_ready()
    compile_s = time.time() - t0
    got = fq.from_mont_limbs(np.asarray(out)[0])
    want = xs[0] * ((xs[0] * 7 + 3) % fq.P) % fq.P
    t0 = time.time()
    o = da
    for _ in range(iters):
        o = fp(o, db)
    o.block_until_ready()
    dt = time.time() - t0
    # validate the CHAINED product too (kernel consuming its own loose
    # output), mirroring bench_rep — a single-call match is not enough to
    # promote the kernel
    chain_got = fq.from_mont_limbs(np.asarray(o)[0])
    chain_want = xs[0]
    for _ in range(iters):
        chain_want = chain_want * ((xs[0] * 7 + 3) % fq.P) % fq.P
    p('pallas_mont_mul %.0f mul/s (compile %.1fs, run %.2fs) match=%s chain_match=%s'
      % (batch * iters / dt, compile_s, dt, got == want, chain_got == chain_want))
except Exception as e:
    p('pallas_mont_mul FAILED: %r' % (repr(e)[:400]))
p('=== probe end', time.strftime('%H:%M:%S'))
