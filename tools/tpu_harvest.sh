#!/bin/bash
# Retry bench configs against the intermittent axon tunnel; append every
# emitted JSON line (TPU or fallback) to the results log. Meant to run in
# the background during a build session; safe to kill any time.
OUT=${1:-/tmp/tpu_harvest.jsonl}
ATTEMPTS=${2:-6}
cd "$(dirname "$0")/.." || exit 1
for i in $(seq 1 "$ATTEMPTS"); do
  echo "=== attempt $i committee $(date -u +%H:%M:%S) ===" >> "$OUT"
  BENCH_N=64 BENCH_K=128 BENCH_PROBE_TIMEOUT=420 timeout 560 python bench.py >> "$OUT" 2>> "$OUT"
  echo "=== attempt $i epoch $(date -u +%H:%M:%S) ===" >> "$OUT"
  BENCH_MODE=epoch BENCH_PROBE_TIMEOUT=900 timeout 1100 python bench.py >> "$OUT" 2>> "$OUT"
done
