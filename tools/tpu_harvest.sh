#!/bin/bash
# Retry bench configs against the intermittent axon tunnel; append every
# emitted JSON line (TPU or fallback) to the results log. Meant to run in
# the background during a build session; safe to kill any time.
# Epoch mode leads (the north-star workload, BASELINE config #4); the
# committee shape follows as the proven-to-fit-a-window config. Both rely
# on bench.py's per-stage partial emission so a window that dies mid-run
# still lands its best number.
OUT=${1:-/tmp/tpu_harvest.jsonl}
ATTEMPTS=${2:-6}
cd "$(dirname "$0")/.." || exit 1
for i in $(seq 1 "$ATTEMPTS"); do
  echo "=== attempt $i epoch $(date -u +%H:%M:%S) ===" >> "$OUT"
  BENCH_MODE=epoch BENCH_PROBE_TIMEOUT=900 timeout 1100 python bench.py >> "$OUT" 2>> "$OUT"
  # committee attempt: the outer timeout must cover the TPU deadline (420 s)
  # PLUS the fixed-shape N=32,K=128 CPU fallback (pre-pass + warmup + one
  # rep ~= 21 min); partial emission means even a kill still leaves the
  # liveness/warmup lines in the log
  echo "=== attempt $i committee $(date -u +%H:%M:%S) ===" >> "$OUT"
  BENCH_MODE=committee BENCH_PROBE_TIMEOUT=420 timeout 2100 python bench.py >> "$OUT" 2>> "$OUT"
done
