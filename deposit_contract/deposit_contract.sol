// SPDX-License-Identifier: CC0-1.0
pragma solidity 0.6.11;

// Own implementation of the eth2 deposit contract for this framework
// (capability parity with the reference's solidity_deposit_contract/
// deposit_contract.sol and specs/phase0/deposit-contract.md): a 32-depth
// incremental sha256 Merkle accumulator over DepositData leaves whose root
// the consensus spec checks with is_valid_merkle_branch
// (reference specs/phase0/beacon-chain.md:737-750, 1852-1860).

interface IDepositContract {
    event DepositEvent(
        bytes pubkey,
        bytes withdrawal_credentials,
        bytes amount,
        bytes signature,
        bytes index
    );

    function deposit(
        bytes calldata pubkey,
        bytes calldata withdrawal_credentials,
        bytes calldata signature,
        bytes32 deposit_data_root
    ) external payable;

    function get_deposit_root() external view returns (bytes32);

    function get_deposit_count() external view returns (bytes memory);
}

interface ERC165 {
    function supportsInterface(bytes4 interfaceId) external pure returns (bool);
}

contract DepositContract is IDepositContract, ERC165 {
    uint constant TREE_DEPTH = 32;
    // bounded strictly below 2**TREE_DEPTH so the length mix-in never wraps
    uint constant MAX_DEPOSITS = 2**TREE_DEPTH - 1;

    // branch[h] caches the left sibling pending at height h; only the
    // path of the NEXT insertion is stored — O(depth) state, O(depth) insert
    bytes32[TREE_DEPTH] branch;
    uint256 deposit_count;

    bytes32[TREE_DEPTH] zero_hashes;

    constructor() public {
        // zero_hashes[h] = root of an empty subtree of height h
        for (uint h = 0; h < TREE_DEPTH - 1; h++)
            zero_hashes[h + 1] = sha256(abi.encodePacked(zero_hashes[h], zero_hashes[h]));
    }

    function get_deposit_root() override external view returns (bytes32) {
        bytes32 node;
        uint size = deposit_count;
        for (uint h = 0; h < TREE_DEPTH; h++) {
            if ((size & 1) == 1)
                node = sha256(abi.encodePacked(branch[h], node));
            else
                node = sha256(abi.encodePacked(node, zero_hashes[h]));
            size /= 2;
        }
        // mix in the leaf count (SSZ List semantics)
        return sha256(abi.encodePacked(
            node,
            to_little_endian_64(uint64(deposit_count)),
            bytes24(0)
        ));
    }

    function get_deposit_count() override external view returns (bytes memory) {
        return to_little_endian_64(uint64(deposit_count));
    }

    function deposit(
        bytes calldata pubkey,
        bytes calldata withdrawal_credentials,
        bytes calldata signature,
        bytes32 deposit_data_root
    ) override external payable {
        require(pubkey.length == 48, "DepositContract: invalid pubkey length");
        require(withdrawal_credentials.length == 32,
            "DepositContract: invalid withdrawal_credentials length");
        require(signature.length == 96, "DepositContract: invalid signature length");

        require(msg.value >= 1 ether, "DepositContract: deposit value too low");
        require(msg.value % 1 gwei == 0,
            "DepositContract: deposit value not multiple of gwei");
        uint deposit_amount = msg.value / 1 gwei;
        require(deposit_amount <= type(uint64).max,
            "DepositContract: deposit value too high");

        emit DepositEvent(
            pubkey,
            withdrawal_credentials,
            to_little_endian_64(uint64(deposit_amount)),
            signature,
            to_little_endian_64(uint64(deposit_count))
        );

        // DepositData hash_tree_root, computed exactly as the SSZ spec does
        bytes32 pubkey_root = sha256(abi.encodePacked(pubkey, bytes16(0)));
        bytes32 signature_root = sha256(abi.encodePacked(
            sha256(abi.encodePacked(signature[:64])),
            sha256(abi.encodePacked(signature[64:], bytes32(0)))
        ));
        bytes32 node = sha256(abi.encodePacked(
            sha256(abi.encodePacked(pubkey_root, withdrawal_credentials)),
            sha256(abi.encodePacked(
                to_little_endian_64(uint64(deposit_amount)), bytes24(0), signature_root
            ))
        ));
        require(node == deposit_data_root,
            "DepositContract: reconstructed DepositData does not match supplied deposit_data_root");

        require(deposit_count < MAX_DEPOSITS, "DepositContract: merkle tree full");
        deposit_count += 1;

        // incremental insert: carry up until an empty (even) slot
        uint size = deposit_count;
        for (uint h = 0; h < TREE_DEPTH; h++) {
            if ((size & 1) == 1) {
                branch[h] = node;
                return;
            }
            node = sha256(abi.encodePacked(branch[h], node));
            size /= 2;
        }
        assert(false);
    }

    function supportsInterface(bytes4 interfaceId) override external pure returns (bool) {
        return interfaceId == type(ERC165).interfaceId
            || interfaceId == type(IDepositContract).interfaceId;
    }

    function to_little_endian_64(uint64 value) internal pure returns (bytes memory ret) {
        ret = new bytes(8);
        for (uint i = 0; i < 8; i++) {
            ret[i] = bytes1(uint8(value >> (8 * i)));
        }
    }
}
