"""Metric-name + env-var drift gate (tier-1).

Every gauge/stat/latency label emitted anywhere in the package must be
declared in the single registry module (``obs/registry.py``) AND appear in
the README metric table; every ``CONSENSUS_SPECS_TPU_*`` environment
variable referenced in the sources must appear in the README env-var
reference. A rename (or a new metric/env knob) that skips the registry or
the docs fails here instead of silently orphaning a dashboard, scrape
rule, or operator playbook.
"""
import os
import re

from consensus_specs_tpu.obs import registry

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_ROOT, "consensus_specs_tpu")

# profiling call sites with a literal first-arg label (multi-line allowed:
# black wraps long calls); labels passed via constants are caught by the
# *_LABEL assignment pattern below
_CALL_RE = re.compile(
    r"profiling\s*\.\s*(?:set_gauge|record_latency|record)\(\s*[\"']([^\"']+)[\"']"
)
# node-labelled emission sites: the base name flows through
# registry.node_label(), which resolves to the bare name or its
# chain[<node>]./serve[<node>]. form — scan the literal first argument
_NODE_LABEL_RE = re.compile(r"node_label\(\s*[\"']([^\"']+)[\"']")
_LABEL_CONST_RE = re.compile(r"^[A-Z_]*LABEL\s*=\s*\"([^\"]+)\"", re.M)
# whole-family declarations (chain/metrics.py GAUGE_LABELS): a tuple of
# label strings exported in a loop — scan every quoted member
_LABEL_TUPLE_RE = re.compile(r"^[A-Z_]*LABELS\s*=\s*\(([^)]*)\)", re.M | re.S)
_ENV_RE = re.compile(r"CONSENSUS_SPECS_TPU_[A-Z0-9_]+")


def _py_sources():
    for dirpath, dirnames, filenames in os.walk(_PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)
    yield os.path.join(_ROOT, "bench.py")


def _emitted_labels():
    labels = {}
    for path in _py_sources():
        with open(path) as fh:
            text = fh.read()
        for m in _CALL_RE.finditer(text):
            labels.setdefault(m.group(1), path)
        for m in _NODE_LABEL_RE.finditer(text):
            labels.setdefault(m.group(1), path)
        for m in _LABEL_CONST_RE.finditer(text):
            labels.setdefault(m.group(1), path)
        for m in _LABEL_TUPLE_RE.finditer(text):
            for member in re.findall(r"\"([^\"]+)\"", m.group(1)):
                labels.setdefault(member, path)
    return labels


def test_every_emitted_label_is_registered():
    missing = {
        label: path
        for label, path in _emitted_labels().items()
        if not registry.known(label)
    }
    assert not missing, (
        "metric labels emitted but missing from obs/registry.py "
        f"(add them to GAUGES/STATS/LATENCIES or DYNAMIC_PREFIXES): {missing}"
    )


def test_emitted_labels_were_actually_found():
    # the scan itself must keep working: the serve plane's known labels
    # have to show up, else a refactor broke the regexes, not the metrics
    found = _emitted_labels()
    for expected in ("serve.queue_depth", "serve.submit_to_result",
                     "bls.rlc_combines", "bls.vm_cache_hits",
                     "chain.apply_batch", "chain.head_changes",
                     "chain.reorgs", "chain.dropped_attestations",
                     "vm.analysis_programs", "vm.analysis_errors",
                     "vm.analysis_hazards", "vm.analysis_max_live",
                     "hist.families", "device.count", "flight.events",
                     "slo.ok", "bls.vm_cache_pruned_bytes",
                     "scale.final_exps_per_slot", "scale.pubkey_hit_rate"):
        assert expected in found, f"label scan lost {expected}"


def test_vm_gauge_families_are_complete():
    # every vm.* gauge either exporter emits (vm.analysis_* from
    # ops/vm_analysis.export_to_obs, vm.fused_* from
    # ops/vm_compile._export_gauges) must be registered, and every
    # registered vm.* gauge must have an emission site — a renamed
    # metric can never silently orphan the README table or a scrape rule
    emitted = {label for label in _emitted_labels()
               if label.startswith("vm.")}
    registered = {n for n in registry.GAUGES if n.startswith("vm.")}
    assert emitted == registered, (
        f"vm gauge drift: emitted-not-registered="
        f"{emitted - registered}, registered-not-emitted="
        f"{registered - emitted}"
    )


def test_merkle_gauge_family_is_complete():
    # the Merkleization plane (ISSUE 18): every merkle.* gauge
    # merkle/levels.export_gauges emits must be registered and every
    # registered merkle.* gauge must have an emission site, and the
    # family must track the counters dict one-to-one (a new counter
    # that skips export_gauges never reaches a scrape)
    from consensus_specs_tpu.merkle import levels as merkle_levels

    emitted = {label for label in _emitted_labels()
               if label.startswith("merkle.")}
    registered = {n for n in registry.GAUGES if n.startswith("merkle.")}
    assert emitted == registered, (
        f"merkle gauge drift: emitted-not-registered="
        f"{emitted - registered}, registered-not-emitted="
        f"{registered - emitted}"
    )
    assert {f"merkle.{k}" for k in merkle_levels.counters} == registered, (
        "merkle counters dict and registered merkle.* gauges diverged"
    )


def test_scale_gauge_family_is_complete():
    # the mainnet workload plane (ISSUE 20): every scale.* gauge the
    # registry / pubkey plane / hierarchy fold / fleet routing emit must
    # be registered and every registered scale.* gauge must have an
    # emission site — the million-validator replay's numbers (pubkey hit
    # rate, final exps per slot, affinity moves) can never silently
    # orphan the README table or a scrape rule
    emitted = {label for label in _emitted_labels()
               if label.startswith("scale.")}
    registered = {n for n in registry.GAUGES if n.startswith("scale.")}
    assert registered, "the scale.* gauge family vanished from the registry"
    assert emitted == registered, (
        f"scale gauge drift: emitted-not-registered="
        f"{emitted - registered}, registered-not-emitted="
        f"{registered - emitted}"
    )


def test_chain_gauge_family_is_complete():
    # the chain plane exports its whole gauge family from one tuple; every
    # member must be a registered gauge and every registered chain gauge
    # must be in the tuple (else export_gauges silently skips it)
    from consensus_specs_tpu.chain import metrics as chain_metrics

    declared = set(chain_metrics.GAUGE_LABELS)
    registered = {n for n in registry.GAUGES if n.startswith("chain.")}
    assert declared == registered, (
        f"chain gauge drift: declared-not-registered={declared - registered}, "
        f"registered-not-declared={registered - declared}"
    )


def test_fleet_gauge_families_are_complete():
    # the PR 7 families (mergeable histograms, device ledger, flight
    # recorder, SLO tracker): every emitted static label is registered
    # AND every registered label has an emission site — a rename in
    # either direction fails here instead of orphaning a scrape rule
    emitted = _emitted_labels()
    for prefix in ("hist.", "device.", "flight.", "slo.", "fleet."):
        family_emitted = {l for l in emitted if l.startswith(prefix)}
        family_registered = {n for n in registry.GAUGES
                             if n.startswith(prefix)}
        assert family_emitted == family_registered, (
            f"{prefix}* gauge drift: emitted-not-registered="
            f"{family_emitted - family_registered}, "
            f"registered-not-emitted={family_registered - family_emitted}"
        )
    # the dynamic per-device family has a real emission site
    dev_src = open(os.path.join(_PKG, "obs", "devices.py")).read()
    assert 'f"device[{lane}]"' in dev_src
    assert "device[" in registry.DYNAMIC_PREFIXES


def test_node_labelled_families_registered():
    # the simnet multi-instance forms: chain[<node>].<name> and
    # serve[<node>].<name> are registered dynamic families, resolve
    # through known(), and spell exactly what node_label() emits —
    # N HeadService/VerificationService instances in one process must
    # publish side by side, never collide
    assert "chain[" in registry.DYNAMIC_PREFIXES
    assert "serve[" in registry.DYNAMIC_PREFIXES
    for label in ("chain[n0].head_slot", "chain[n3].apply_batch",
                  "serve[n0].queue_depth", "serve[n1].submit_to_result"):
        assert registry.known(label), f"{label} not resolvable"
    # node_label is the one spelling, and both planes route through it
    assert registry.node_label("chain.head_slot", "n2") == \
        "chain[n2].head_slot"
    assert registry.node_label("serve.queue_depth", None) == \
        "serve.queue_depth"
    for rel in (("chain", "metrics.py"), ("serve", "metrics.py")):
        src = open(os.path.join(_PKG, *rel)).read()
        assert "node_label(" in src, f"{rel} lost its node_label route"


def test_node_labelled_bases_cover_the_bare_families():
    # every label a node-labelled instance can emit must be a registered
    # BARE name too (the node form only re-scopes it): the scan sees the
    # node_label("<base>") literals, and each base must be registered
    emitted = _emitted_labels()
    node_routed = set()
    for rel in (("chain", "metrics.py"), ("serve", "metrics.py")):
        src = open(os.path.join(_PKG, *rel)).read()
        node_routed.update(_NODE_LABEL_RE.findall(src))
        node_routed.update(_LABEL_CONST_RE.findall(src))
    assert node_routed, "node_label scan found no emission sites"
    for base in node_routed:
        assert registry.known(base), f"node-labelled base {base} unregistered"
        assert base in emitted


def test_telemetry_gauge_families_are_complete():
    # the continuous-telemetry plane (ISSUE 19): the health.* family must
    # track chain/health.GAUGE_LABELS one-to-one (export_gauges zips the
    # tuple — a gauge outside it silently never exports), the TSDB's own
    # timeseries.* health and the snapshot's process.* resource family
    # must each match emitted-vs-registered exactly
    from consensus_specs_tpu.chain import health as chain_health
    from consensus_specs_tpu.obs import snapshot as obs_snapshot

    emitted = _emitted_labels()
    for prefix in ("health.", "timeseries.", "process."):
        family_emitted = {l for l in emitted if l.startswith(prefix)}
        family_registered = {n for n in registry.GAUGES
                             if n.startswith(prefix)}
        assert family_emitted == family_registered, (
            f"{prefix}* gauge drift: emitted-not-registered="
            f"{family_emitted - family_registered}, "
            f"registered-not-emitted={family_registered - family_emitted}"
        )
    assert set(chain_health.GAUGE_LABELS) == \
        {n for n in registry.GAUGES if n.startswith("health.")}, \
        "chain/health.GAUGE_LABELS and registered health.* diverged"
    assert set(obs_snapshot.PROCESS_GAUGE_LABELS) == \
        {n for n in registry.GAUGES if n.startswith("process.")}, \
        "snapshot.PROCESS_GAUGE_LABELS and registered process.* diverged"


def test_telemetry_node_labelled_families_registered():
    # the per-instance forms (health[<node>].<name> from N simnet
    # ledgers, process[<worker>].<name> from the fleet merge) are
    # registered dynamic families and resolve through known()
    assert "health[" in registry.DYNAMIC_PREFIXES
    assert "process[" in registry.DYNAMIC_PREFIXES
    for label in ("health[n0].participation_rate",
                  "health[n3].finality_lag_slots",
                  "process[w0].rss_bytes", "process[w1].cpu_s"):
        assert registry.known(label), f"{label} not resolvable"
    assert registry.node_label("health.head_churn", "n1") == \
        "health[n1].head_churn"
    src = open(os.path.join(_PKG, "chain", "health.py")).read()
    assert "node_label(" in src, "health.py lost its node_label route"


def test_span_stage_registry_matches_tracing_exports():
    # obs/registry.SPAN_STAGES is the canonical stage list; tracing
    # re-exports it — the coverage gate in tests/test_obs.py holds every
    # registered stage to an actual trace export
    from consensus_specs_tpu.obs import tracing

    assert tracing.STAGES == registry.SPAN_STAGES["serve"]
    assert tracing.CHAIN_STAGES == registry.SPAN_STAGES["chain"]


def test_registry_names_are_documented():
    with open(os.path.join(_ROOT, "README.md")) as fh:
        readme = fh.read()
    undocumented = [n for n in registry.all_names() if f"`{n}`" not in readme]
    assert not undocumented, (
        "registered metric names missing from the README metric table: "
        f"{undocumented}"
    )
    for prefix in registry.DYNAMIC_PREFIXES:
        assert f"`{prefix}" in readme, (
            f"dynamic metric family {prefix!r} missing from the README "
            "metric table"
        )


def test_dynamic_prefixes_exist_in_source():
    # a registered dynamic family must correspond to a real emission site
    vm_src = open(os.path.join(_PKG, "ops", "vm.py")).read()
    assert 'f"vm[steps=' in vm_src


def test_env_vars_are_documented():
    with open(os.path.join(_ROOT, "README.md")) as fh:
        readme = fh.read()
    referenced = set()
    for path in _py_sources():
        with open(path) as fh:
            referenced.update(_ENV_RE.findall(fh.read()))
    undocumented = sorted(v for v in referenced if v not in readme)
    assert not undocumented, (
        "CONSENSUS_SPECS_TPU_* env vars referenced in sources but missing "
        f"from the README env-var reference: {undocumented}"
    )
