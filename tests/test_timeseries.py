"""Time-series store (ISSUE 19): the merge algebra (max-sub wins, ties
sum, hist deltas add), multi-resolution retention, the wire codec, and
the acceptance property — a fleet feed split across N stores and merged
is BIT-EXACT against the same feed into one store, through a real JSON
round trip. All inputs use dyadic-rational values (multiples of 2^-6),
so float addition is exact and `==` is the honest comparison.
"""
import json

import pytest

from consensus_specs_tpu.obs import hist
from consensus_specs_tpu.obs.exposition import start_exposition
from consensus_specs_tpu.obs.timeseries import (
    TS_WIRE_VERSION,
    TimeSeriesError,
    TimeSeriesStore,
    downsample,
    merge_level,
    merge_point,
    merge_wires,
    new_point,
    render_wire,
)
from consensus_specs_tpu.ops import profiling


@pytest.fixture(autouse=True)
def _clean_profiling():
    profiling.reset()
    yield
    profiling.reset()


def _q(x):
    """Dyadic rational: exact under float addition."""
    return x / 64.0


def _json_roundtrip(wire):
    return json.loads(json.dumps(wire, sort_keys=True))


def _point(g=None, h=None):
    p = new_point()
    for label, (value, sub) in (g or {}).items():
        p["g"][label] = [value, sub]
    for label, d in (h or {}).items():
        p["h"][label] = {"counts": dict(d.get("counts", {})),
                         "count": d.get("count", 0),
                         "sum": d.get("sum", 0.0)}
    return p


# -- point algebra ------------------------------------------------------------


def test_merge_point_max_sub_wins_and_ties_sum():
    a = _point(g={"x": (_q(3), 5), "y": (_q(1), 2)})
    b = _point(g={"x": (_q(9), 5), "y": (_q(7), 1), "z": (_q(2), 0)})
    out = merge_point(a, b)
    assert out["g"]["x"] == [_q(12), 5]   # same sub: contributions sum
    assert out["g"]["y"] == [_q(1), 2]    # newer sub wins outright
    assert out["g"]["z"] == [_q(2), 0]    # disjoint labels union
    # commutative on the nose
    assert merge_point(b, a) == out


def test_merge_point_hist_deltas_add():
    a = _point(h={"lat": {"counts": {3: 2}, "count": 2, "sum": _q(4)}})
    b = _point(h={"lat": {"counts": {3: 1, 5: 4}, "count": 5,
                          "sum": _q(6)}})
    out = merge_point(a, b)
    assert out["h"]["lat"] == {"counts": {3: 3, 5: 4}, "count": 7,
                               "sum": _q(10)}


def test_merge_point_is_associative():
    pts = [
        _point(g={"x": (_q(1), 0)}, h={"l": {"counts": {1: 1},
                                             "count": 1, "sum": _q(1)}}),
        _point(g={"x": (_q(2), 0), "y": (_q(8), 3)}),
        _point(g={"x": (_q(4), 1)}, h={"l": {"counts": {2: 5},
                                             "count": 5, "sum": _q(2)}}),
    ]
    left = merge_point(merge_point(pts[0], pts[1]), pts[2])
    right = merge_point(pts[0], merge_point(pts[1], pts[2]))
    assert left == right


def _synthetic_level(seed, n_points=23, labels=("a", "b", "c")):
    """Deterministic {idx: point} map — varied subs, values, hist mass."""
    level = {}
    for i in range(n_points):
        idx = (seed * 7 + i * 3) % 40
        g = {}
        for j, label in enumerate(labels):
            if (i + j + seed) % 2:
                g[label] = (_q((seed + 1) * (i + 1) * (j + 2)),
                            idx * 4 + (i + seed) % 4)
        h = {}
        if (i + seed) % 3 == 0:
            h["lat"] = {"counts": {(i % 6): i + 1}, "count": i + 1,
                        "sum": _q(i)}
        cur = level.get(idx)
        p = _point(g=g, h=h)
        level[idx] = merge_point(cur, p) if cur is not None else p
    return level


def test_downsample_commutes_with_merge():
    """The load-bearing algebra property: folding two feeds coarser and
    then merging equals merging and then folding — for every factor the
    retention rings use. This is WHY the fleet's coarse levels are exact
    and not an approximation of the workers' fine levels."""
    a = _synthetic_level(seed=1)
    b = _synthetic_level(seed=4)
    for factor in (2, 10, 60):
        merged_then_down = downsample(merge_level(a, b), factor)
        down_then_merged = merge_level(downsample(a, factor),
                                       downsample(b, factor))
        assert merged_then_down == down_then_merged, f"factor {factor}"


# -- store ingestion + retention ----------------------------------------------


def _feed(store, t, gauges):
    store.sample(now=float(t), gauges=gauges, hists={})


def test_store_coarse_levels_equal_downsampled_fine_level():
    store = TimeSeriesStore(interval_s=1.0, capacity=512)
    for t in range(0, 130):
        _feed(store, t, {"g.x": _q(t), "g.y": _q(2 * t + 1)})
    wire = store.to_wire()
    fine = {int(i): p for i, p in wire["levels"]["1"].items()}
    for factor in (10, 60):
        want = downsample({i: _decode(p) for i, p in fine.items()}, factor)
        got = {int(i): _decode(p)
               for i, p in wire["levels"][str(factor)].items()}
        assert got == want, f"level {factor} diverged from its definition"


def _decode(wire_point):
    p = new_point()
    for label, pair in wire_point["g"].items():
        p["g"][label] = [float(pair[0]), int(pair[1])]
    for label, d in wire_point["h"].items():
        p["h"][label] = {"counts": {int(i): int(n)
                                    for i, n in d["counts"].items()},
                         "count": int(d["count"]),
                         "sum": float(d["sum"])}
    return p


def test_store_eviction_bounds_every_level():
    store = TimeSeriesStore(interval_s=1.0, capacity=16)
    for t in range(0, 400):
        _feed(store, t, {"g.x": _q(t)})
    wire = store.to_wire()
    for res, level in wire["levels"].items():
        assert len(level) <= 16, f"level {res} grew past capacity"
    # the fine level evicted (400 samples > 16 points) and said so
    assert store.evicted > 0
    assert store.samples == 400
    # retained fine points are the NEWEST (eviction pops the oldest idx)
    fine_idxs = sorted(int(i) for i in wire["levels"]["1"])
    assert fine_idxs == list(range(384, 400))


def test_store_hist_samples_record_deltas_not_cumulatives():
    store = TimeSeriesStore(interval_s=1.0, capacity=64)
    h = hist.Histogram()
    h.observe(0.001)
    h.observe(0.002)
    store.sample(now=0.0, gauges={}, hists={"lat": h})
    h.observe(0.004)
    store.sample(now=1.0, gauges={}, hists={"lat": h})
    wire = store.to_wire()
    fine = wire["levels"]["1"]
    assert fine["0"]["h"]["lat"]["count"] == 2   # first sample: full state
    assert fine["1"]["h"]["lat"]["count"] == 1   # second: the delta only
    # the 10x point holds the SUM of the window's deltas == cumulative
    assert wire["levels"]["10"]["0"]["h"]["lat"]["count"] == 3


# -- the acceptance property: split feed == single feed -----------------------


def _label_split_feeds():
    """One fleet-shaped feed: per-worker label namespaces (the live
    fleet's shape — worker gauges arrive prefixed), identical sample
    clock. Returns (single_store, [worker stores])."""
    single = TimeSeriesStore(interval_s=1.0, capacity=256)
    w0 = TimeSeriesStore(interval_s=1.0, capacity=256)
    w1 = TimeSeriesStore(interval_s=1.0, capacity=256)
    for t in range(0, 75):
        g0 = {"serve[w0].queue_depth": _q(t % 13),
              "serve[w0].submits": _q(3 * t)}
        g1 = {"serve[w1].queue_depth": _q((t + 5) % 11),
              "serve[w1].submits": _q(2 * t + 1)}
        single.sample(now=float(t), gauges={**g0, **g1}, hists={})
        w0.sample(now=float(t), gauges=g0, hists={})
        w1.sample(now=float(t), gauges=g1, hists={})
    return single, [w0, w1]


def test_merged_fleet_wire_is_bitexact_vs_single_store_label_split():
    single, workers = _label_split_feeds()
    merged = merge_wires([_json_roundtrip(w.to_wire()) for w in workers])
    assert _json_roundtrip(merged) == _json_roundtrip(single.to_wire())


def test_merged_fleet_wire_is_bitexact_vs_single_store_time_split():
    """Same label, feed split in TIME across two stores (a worker handoff
    mid-soak): the max-sub rule makes the merged coarse points identical
    to the uninterrupted store's."""
    single = TimeSeriesStore(interval_s=1.0, capacity=256)
    early = TimeSeriesStore(interval_s=1.0, capacity=256)
    late = TimeSeriesStore(interval_s=1.0, capacity=256)
    for t in range(0, 64):
        g = {"health.participation_rate": _q(40 + t % 9)}
        single.sample(now=float(t), gauges=g, hists={})
        (early if t < 31 else late).sample(now=float(t), gauges=g,
                                           hists={})
    merged = merge_wires([_json_roundtrip(early.to_wire()),
                          _json_roundtrip(late.to_wire())])
    assert _json_roundtrip(merged) == _json_roundtrip(single.to_wire())


def test_merged_render_is_bitexact_too():
    """/timeseries serves the RENDERED document — the property must
    survive rendering, not just the wire."""
    single, workers = _label_split_feeds()
    merged = merge_wires([w.to_wire() for w in workers])
    assert json.dumps(render_wire(merged), sort_keys=True) == \
        json.dumps(single.render(), sort_keys=True)


def test_merge_is_idempotent_on_duplicate_feeds():
    """Re-ingesting the same worker wire (a double poll) must not double
    gauge values: same (sub, value) contributions sum — so this is the
    one algebra caveat — but POINTWISE self-merge keeps eviction and
    structure sane; the router dedupes by polling latest-per-worker.
    What we pin here: merging a wire with an EMPTY wire is identity."""
    single, _ = _label_split_feeds()
    wire = single.to_wire()
    empty = TimeSeriesStore(interval_s=1.0, capacity=4).to_wire()
    assert _json_roundtrip(merge_wires([wire, empty])) == \
        _json_roundtrip(wire)


# -- wire hygiene -------------------------------------------------------------


def test_merge_rejects_wire_version_mismatch():
    good = TimeSeriesStore(interval_s=1.0).to_wire()
    bad = dict(good, v=TS_WIRE_VERSION + 1)
    with pytest.raises(TimeSeriesError):
        merge_wires([good, bad])
    with pytest.raises(TimeSeriesError):
        render_wire({"levels": {}})  # missing version entirely


def test_merge_rejects_interval_mismatch():
    a = TimeSeriesStore(interval_s=1.0)
    b = TimeSeriesStore(interval_s=6.0)
    _feed(a, 0, {"x": 1.0})
    _feed(b, 0, {"x": 1.0})
    with pytest.raises(TimeSeriesError):
        merge_wires([a.to_wire(), b.to_wire()])


def test_merge_rejects_malformed_points():
    good = TimeSeriesStore(interval_s=1.0)
    _feed(good, 0, {"x": 1.0})
    wire = _json_roundtrip(good.to_wire())
    wire["levels"]["1"]["0"]["g"]["x"] = ["not-a-number", None]
    with pytest.raises(TimeSeriesError):
        merge_wires([wire])


# -- rendering + artifacts ----------------------------------------------------


def test_render_wire_shape_and_percentiles():
    store = TimeSeriesStore(interval_s=2.0, capacity=64)
    h = hist.Histogram()
    for _ in range(100):
        h.observe(0.010)
    store.sample(now=0.0, gauges={"g.x": _q(1)}, hists={"lat": h})
    doc = store.render()
    assert doc["v"] == TS_WIRE_VERSION and doc["interval_s"] == 2.0
    by_res = {lv["resolution_s"]: lv for lv in doc["levels"]}
    assert set(by_res) == {2.0, 20.0, 120.0}
    point = by_res[2.0]["points"][0]
    assert point["t"] == 0.0
    assert point["gauges"]["g.x"] == _q(1)
    lat = point["hists"]["lat"]
    assert lat["count"] == 100
    # log-bucketed percentiles: within one bucket width of the truth
    assert 8.0 <= lat["p50_ms"] <= 12.0
    assert 8.0 <= lat["p99_ms"] <= 12.0


def test_dump_jsonl_is_one_header_plus_one_line_per_point(tmp_path):
    store = TimeSeriesStore(interval_s=1.0, capacity=64)
    for t in range(0, 12):
        _feed(store, t, {"g.x": _q(t)})
    path = store.dump_jsonl(str(tmp_path / "ts.jsonl"))
    lines = [json.loads(l) for l in open(path) if l.strip()]
    header, rows = lines[0], lines[1:]
    assert header["timeseries"] == f"v{TS_WIRE_VERSION}"
    assert header["points"] == len(rows)
    assert header["levels"] == [1.0, 10.0, 60.0]
    # 12 fine points + 2 at 10x + 1 at 60x
    assert len(rows) == 12 + 2 + 1
    for row in rows:
        assert set(row) >= {"idx", "t", "gauges", "hists", "resolution_s"}


def test_timeseries_endpoint_serves_merged_document():
    single, workers = _label_split_feeds()
    merged = merge_wires([w.to_wire() for w in workers])
    with start_exposition(
            port=0, timeseries_fn=lambda: render_wire(merged)) as server:
        import urllib.request

        with urllib.request.urlopen(server.url("/timeseries")) as resp:
            doc = json.loads(resp.read())
    assert doc == json.loads(json.dumps(single.render()))
