"""debug codecs: encode/decode round-trip + seeded random objects
(consensus_specs_tpu/debug/; reference eth2spec/debug/ 252 LoC)."""
from random import Random

import pytest

from consensus_specs_tpu.builder import build_spec_module
from consensus_specs_tpu.debug.decode import decode
from consensus_specs_tpu.debug.encode import encode
from consensus_specs_tpu.debug.random_value import (
    RandomizationMode, get_random_ssz_object,
)
from consensus_specs_tpu.utils.ssz.ssz_typing import Container


def _containers(spec, limit=None):
    out = []
    for name, obj in sorted(vars(spec).items()):
        if (isinstance(obj, type) and issubclass(obj, Container)
                and obj is not Container and obj.fields()):
            out.append((name, obj))
    return out[:limit] if limit else out


@pytest.mark.parametrize("mode", [
    RandomizationMode.mode_random,
    RandomizationMode.mode_zero,
    RandomizationMode.mode_max,
    RandomizationMode.mode_one_count,
])
def test_random_object_roundtrips_phase0(mode):
    spec = build_spec_module("phase0", "minimal")
    rng = Random(4040 + mode.value)
    for name, typ in _containers(spec):
        value = get_random_ssz_object(rng, typ, 100, 5, mode)
        # ssz serialization round-trip
        again = typ.decode_bytes(value.encode_bytes())
        assert again.hash_tree_root() == value.hash_tree_root(), name
        # debug-codec round-trip, with root re-checking enabled
        plain = encode(value, include_hash_tree_roots=True)
        back = decode(plain, typ)
        assert back.hash_tree_root() == value.hash_tree_root(), name


def test_random_object_roundtrips_merge():
    spec = build_spec_module("merge", "minimal")
    rng = Random(11)
    for name, typ in _containers(spec):
        value = get_random_ssz_object(rng, typ, 64, 3, RandomizationMode.mode_random)
        assert typ.decode_bytes(value.encode_bytes()).hash_tree_root() == value.hash_tree_root(), name
        assert decode(encode(value), typ).hash_tree_root() == value.hash_tree_root(), name


def test_decode_rejects_wrong_root_annotation():
    spec = build_spec_module("phase0", "minimal")
    cp = spec.Checkpoint(epoch=3, root=b"\x01" * 32)
    plain = encode(cp, include_hash_tree_roots=True)
    plain["hash_tree_root"] = "0x" + "00" * 32
    with pytest.raises(AssertionError):
        decode(plain, spec.Checkpoint)


def test_chaos_mode_produces_valid_objects():
    spec = build_spec_module("altair", "minimal")
    rng = Random(5)
    typ = spec.BeaconBlockBody
    for _ in range(3):
        value = get_random_ssz_object(rng, typ, 100, 4,
                                      RandomizationMode.mode_random, chaos=True)
        assert typ.decode_bytes(value.encode_bytes()) == value
