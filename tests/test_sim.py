"""simnet: the adversarial multi-node convergence gate (tier-1).

Every named scenario class runs 4 real nodes (HeadService +
VerificationService each) through the deterministic discrete-event
fabric under the STRICT differential gate: identical block sets,
identical latest-message tables, one head everywhere, and that head
bit-identical to ``spec.get_head`` on each node's store AND on a
from-scratch union store. Determinism is pinned by the event-stream
digest (same seed -> identical run), and the fault-plan dataclass
(serve/load.py) gets its own seed-determinism gate here.
"""
import random

import pytest

from consensus_specs_tpu.serve.load import (
    FAULT_KINDS,
    GossipFaultPlan,
    plan_gossip_faults,
)
from consensus_specs_tpu.sim import (
    SCENARIOS,
    build_world,
    get_scenario,
    run_scenario,
    scenario_names,
)


@pytest.fixture(scope="module")
def world():
    return build_world()


# per-scenario evidence the attack actually happened (beyond convergence)
_SCENARIO_EVIDENCE = {
    "partition_heal": lambda r: r.partition_drops > 0 and r.last_heal_s > 0
    and r.sync_sends > 0,
    "latency_skew": lambda r: r.deliveries > 0,
    "lossy_links": lambda r: r.loss_drops > 0 and r.sync_sends > 0,
    "equivocation": lambda r: r.equivocations > 0,
    "withheld_orphans": lambda r: r.withheld > 0 and sum(
        p["resolved"] for p in r.per_node.values()) > 0,
    "long_range_reorg": lambda r: True,  # head-not-on-fork is in the gate
    "censored_aggregates": lambda r: r.censored > 0,
}


def test_scenario_library_shape():
    # the acceptance floor: >= 6 named classes, >= 4 nodes each, and the
    # evidence table stays in lockstep with the library
    assert len(SCENARIOS) >= 6
    assert set(_SCENARIO_EVIDENCE) == set(scenario_names())
    for sc in SCENARIOS.values():
        assert sc.nodes >= 4
        assert sc.review_finding  # docs/simnet_threat_model.md mapping


@pytest.mark.parametrize("name", scenario_names())
def test_scenario_converges(world, name):
    """The tentpole gate: strict differential convergence per scenario —
    any divergence raises SimDivergence inside run_scenario."""
    spec, anchor_state, anchor_block = world
    report = run_scenario(
        get_scenario(name), spec=spec, anchor_state=anchor_state,
        anchor_block=anchor_block, seed=7, strict=True)
    assert report.converged and report.error is None
    assert report.nodes >= 4
    # the network was genuinely disturbed before it converged
    assert report.diverged_samples > 0
    assert _SCENARIO_EVIDENCE[name](report), (
        f"{name}: attack evidence missing from {report.to_dict()}")
    # every node did real work and ended in agreement
    for node_name, snap in report.per_node.items():
        assert snap["applied"] > 0, f"{node_name} applied nothing"
        assert snap["deferred_pending"] == 0
        assert snap["backend_calls"] > 0  # verdicts flowed via the service
    assert report.heads_per_sec_min > 0


def test_same_seed_same_run(world):
    """Full determinism: the event-stream digest, the agreed head, and
    every traffic counter replay identically under a fixed seed."""
    spec, anchor_state, anchor_block = world
    kw = dict(spec=spec, anchor_state=anchor_state,
              anchor_block=anchor_block, seed=23)
    a = run_scenario(get_scenario("partition_heal"), **kw)
    b = run_scenario(get_scenario("partition_heal"), **kw)
    assert a.digest == b.digest
    assert a.head == b.head and a.head_slot == b.head_slot
    assert a.deliveries == b.deliveries
    assert a.heal_to_convergence_s == b.heal_to_convergence_s
    assert a.per_node == {
        n: {**s, "heads_per_sec": a.per_node[n]["heads_per_sec"]}
        for n, s in b.per_node.items()
    }  # wall-clock query rate aside, node outcomes are identical
    c = run_scenario(get_scenario("partition_heal"), **dict(kw, seed=24))
    assert c.digest != a.digest


def test_with_nodes_rescales_the_attack_too():
    """Rescaling a scenario must never disarm it: partition groups
    re-split and latency-skew targets remap onto surviving indices."""
    skewed = get_scenario("latency_skew").with_nodes(3)
    assert skewed.nodes == 3
    assert dict(skewed.latency_skew) == {2: 20.0}  # laggard survives
    split = get_scenario("partition_heal").with_nodes(6)
    assert split.partitions[0].groups == ((0, 1, 2), (3, 4, 5))


def test_more_nodes_still_converge(world):
    """The scenario rescales: 6 nodes re-split the partition groups and
    the gate still holds."""
    spec, anchor_state, anchor_block = world
    report = run_scenario(
        get_scenario("partition_heal"), spec=spec,
        anchor_state=anchor_state, anchor_block=anchor_block, seed=7,
        nodes=6)
    assert report.converged and report.nodes == 6
    assert report.partition_drops > 0


def test_node_labelled_metrics_published(world):
    """After a run, the per-node chain[*]/serve[*] families are in the
    profiling summary — N instances coexisted without gauge collisions."""
    from consensus_specs_tpu.ops import profiling

    spec, anchor_state, anchor_block = world
    run_scenario(get_scenario("equivocation"), spec=spec,
                 anchor_state=anchor_state, anchor_block=anchor_block,
                 seed=7)
    snap = profiling.summary()
    for node in ("n0", "n3"):
        assert f"chain[{node}].head_slot" in snap
        assert f"chain[{node}].blocks" in snap
        assert f"serve[{node}].queue_depth" in snap
    # the per-node head slots agree — same values, separate gauges
    assert (snap["chain[n0].head_slot"]["gauge"]
            == snap["chain[n3].head_slot"]["gauge"])


def test_flight_journals_per_node(world, tmp_path):
    """One JSONL journal per node AND per light client, stamped, on the
    simulated clock."""
    import json

    spec, anchor_state, anchor_block = world
    report = run_scenario(
        get_scenario("withheld_orphans"), spec=spec,
        anchor_state=anchor_state, anchor_block=anchor_block, seed=7,
        flight_dir=str(tmp_path))
    files = sorted(p.name for p in tmp_path.iterdir())
    assert files == [
        f"sim_flight_withheld_orphans_c{i}.jsonl"
        for i in range(report.light_clients)
    ] + [
        f"sim_flight_withheld_orphans_n{i}.jsonl"
        for i in range(report.nodes)
    ]
    first_node = "sim_flight_withheld_orphans_n0.jsonl"
    lines = [json.loads(ln) for ln in
             (tmp_path / first_node).read_text().splitlines()]
    header, events = lines[0], lines[1:]
    assert header["node"] == "n0" and header["events"] > 0
    kinds = {e["kind"] for e in events}
    assert "on_block" in kinds and "defer" in kinds
    assert all(e["node"] == "n0" for e in events)
    # timestamps are simulation seconds, bounded by the run's end
    assert all(0.0 <= e["t"] <= report.sim_end_s for e in events)


# -- fault-plan dataclass (serve/load.py satellite) ---------------------------


def test_fault_plan_seed_determinism():
    """Same seed + rates -> structurally identical plan (the dataclass
    equality the sim's script builder relies on)."""
    args = (200, 0.1, 0.1, 0.1, 0.1)
    a = plan_gossip_faults(random.Random(5), *args)
    b = plan_gossip_faults(random.Random(5), *args)
    assert isinstance(a, GossipFaultPlan)
    assert a == b and a.kinds == b.kinds
    c = plan_gossip_faults(random.Random(6), *args)
    assert a != c


def test_fault_plan_covers_new_kinds():
    plan = plan_gossip_faults(random.Random(3), 400, 0.1, 0.1, 0.1, 0.1)
    assert set(plan.kinds) == set(FAULT_KINDS)
    assert plan[0] == "ok"  # the stream never starts with a fault
    counts = plan.counts()
    assert counts["equivocation"] > 0 and counts["censored_agg"] > 0
    assert sum(counts.values()) == len(plan) == 400
    # sequence protocol (pre-dataclass callers): count/iter/index
    assert plan.count("ok") == counts["ok"]


def test_fault_plan_band_stability():
    """Adding a new rate band never perturbs the draws of earlier kinds
    at a fixed seed — old two-rate callers see the same plan prefix
    behavior they always did."""
    old = plan_gossip_faults(random.Random(9), 300, 0.15, 0.15)
    new = plan_gossip_faults(random.Random(9), 300, 0.15, 0.15, 0.0, 0.0)
    assert old.kinds == new.kinds
    assert set(old.kinds) <= {"ok", "invalid_sig", "orphan"}
