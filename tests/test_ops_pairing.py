"""JAX pairing engine vs the oracle: curve ops, Miller loop, verification."""
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

# whole-pairing programs: long XLA compiles on the CPU backend
pytestmark = pytest.mark.slow

from consensus_specs_tpu.ops import curve, fq, pairing, towers as tw  # noqa: E402
from consensus_specs_tpu.utils import bls12_381 as oracle  # noqa: E402
from consensus_specs_tpu.utils.bls12_381 import (  # noqa: E402
    G1_GEN, G2_GEN, R, ec_mul, ec_neg, ec_to_affine,
)

rng = random.Random(23)


def g1_points(ks):
    """Host->device: batched G1 affine Fq coords for k*G1."""
    xs, ys = [], []
    for k in ks:
        x, y = ec_to_affine(ec_mul(G1_GEN, k))
        xs.append(fq.to_mont_int(x.n))
        ys.append(fq.to_mont_int(y.n))
    return np.stack(xs), np.stack(ys)


def g2_points(ks):
    xs, ys = [], []
    for k in ks:
        x, y = ec_to_affine(ec_mul(G2_GEN, k))
        xs.append(np.stack([fq.to_mont_int(x.c0), fq.to_mont_int(x.c1)]))
        ys.append(np.stack([fq.to_mont_int(y.c0), fq.to_mont_int(y.c1)]))
    return np.stack(xs), np.stack(ys)


def test_g2_jacobian_double_add_matches_oracle():
    dbl = jax.jit(lambda p: curve.double(curve.FQ2_OPS, p))
    qx, qy = g2_points([5])
    one = tw.fq2_const(1, 0, (1,))
    T = curve.point(qx, qy, one)
    T2 = dbl(T)
    # affine-ize on host via oracle
    x = tw.fq2_to_oracle(np.asarray(fq.canonical(T2["x"]))[0])
    y = tw.fq2_to_oracle(np.asarray(fq.canonical(T2["y"]))[0])
    z = tw.fq2_to_oracle(np.asarray(fq.canonical(T2["z"]))[0])
    zinv = z.inverse()
    aff = (x * zinv * zinv, y * zinv * zinv * zinv)
    expect = ec_to_affine(ec_mul(G2_GEN, 10))
    assert aff == expect

    madd = jax.jit(lambda p, ax, ay: curve.add_mixed(curve.FQ2_OPS, p, ax, ay))
    qx3, qy3 = g2_points([3])
    T3 = madd(T2, qx3, qy3)
    x = tw.fq2_to_oracle(np.asarray(fq.canonical(T3["x"]))[0])
    y = tw.fq2_to_oracle(np.asarray(fq.canonical(T3["y"]))[0])
    z = tw.fq2_to_oracle(np.asarray(fq.canonical(T3["z"]))[0])
    zinv = z.inverse()
    aff = (x * zinv * zinv, y * zinv * zinv * zinv)
    assert aff == ec_to_affine(ec_mul(G2_GEN, 13))


def test_miller_loop_matches_oracle():
    """The device Miller loop scales its line functions by Fq2 subfield
    factors (inversion-free evaluation — see ops/pairing.py docstring), so
    raw outputs equal the oracle's only UP TO a subfield factor: compare
    after final exponentiation, which kills exactly those factors."""
    ks_g1 = [1, 7]
    ks_g2 = [1, 11]
    px, py = g1_points(ks_g1)
    qx, qy = g2_points(ks_g2)
    f = np.asarray(jax.jit(lambda *a: fq.canonical(pairing.miller_loop(*a)))(qx, qy, px, py))
    for i in range(2):
        got = tw.fq12_to_oracle(f[i])
        p_aff = ec_to_affine(ec_mul(G1_GEN, ks_g1[i]))
        q_aff = ec_to_affine(ec_mul(G2_GEN, ks_g2[i]))
        expect = oracle.miller_loop(q_aff, p_aff)
        # the documented invariant exactly: device and oracle Miller outputs
        # differ by an Fq2 subfield factor only — i.e. the ratio is fixed by
        # the p^2 Frobenius. Stricter than comparing whole pairings (any
        # non-subfield corruption fails here even if final exp would kill it)
        ratio = got * expect.inverse()
        assert ratio.frobenius().frobenius() == ratio, f"miller mismatch at {i}"


def test_pairing_product_check():
    """e(aP, Q) * e(-P, aQ) == 1 — the bilinearity identity, on device."""
    check = jax.jit(lambda p1, p2: pairing.pairing_product_is_one([p1, p2]))
    a = 5
    px1, py1 = g1_points([a, 1])
    qx1, qy1 = g2_points([1, a])
    # negate second G1 point
    neg = ec_to_affine(ec_neg(ec_mul(G1_GEN, 1)))
    px1[1] = fq.to_mont_int(neg[0].n)
    py1[1] = fq.to_mont_int(neg[1].n)
    ok = np.asarray(
        check((px1[:1], py1[:1], qx1[:1], qy1[:1]), (px1[1:], py1[1:], qx1[1:], qy1[1:]))
    )
    assert bool(ok[0])

    # and a wrong pair fails
    px2, py2 = g1_points([a, 2])
    qx2, qy2 = g2_points([1, a])
    px2[1] = fq.to_mont_int(neg[0].n)
    py2[1] = fq.to_mont_int(neg[1].n)
    # second pair is e(-P, aQ) but first is e(aP, Q)... make first wrong: use 2P
    px_bad, py_bad = g1_points([a + 1])
    ok2 = np.asarray(
        check((px_bad, py_bad, qx2[:1], qy2[:1]), (px2[1:], py2[1:], qx2[1:], qy2[1:]))
    )
    assert not bool(ok2[0])


def test_g1_scalar_mul_subgroup_check():
    smul = jax.jit(
        lambda x, y: curve.scalar_mul_fixed(curve.FQ_OPS, x, y, curve.subgroup_check_bits())
    )
    px, py = g1_points([3, 9])
    out = smul(px, py)
    z_can = np.asarray(fq.canonical(out["z"]))
    assert not z_can.any()  # r*P == infinity for subgroup points
