"""Tier-1 coverage for the mainnet-scale pubkey plane (ISSUE 20):
bytes-exact LRU accounting and eviction, mirroring into (and eviction
out of) the backend `_PK_CACHE`, and batched-decompression equivalence
against the per-key decode path. Crypto is kept to a handful of tiny
keys so the module stays inside the tier-1 budget; the registry /
routing / hierarchy halves of the plane live in test_scale.py."""
import numpy as np
import pytest

from consensus_specs_tpu.scale import pubkeys


def _real_pubkeys(n, base=1):
    from consensus_specs_tpu.utils import bls

    return [bls.SkToPk((base + i) << 4) for i in range(n)]


def test_pubkey_plane_byte_accounting_and_eviction():
    pks = _real_pubkeys(6)
    probe = pubkeys.PubkeyPlane(budget_bytes=1 << 30, mirror_backend=False)
    probe.warm(pks[:1])
    per_entry = probe.bytes
    assert per_entry > 48  # decompressed limbs dominate

    plane = pubkeys.PubkeyPlane(budget_bytes=3 * per_entry,
                                mirror_backend=False)
    hits, misses = plane.warm(pks[:3])
    assert (hits, misses) == (0, 3)
    assert plane.bytes == 3 * per_entry <= plane.budget_bytes
    assert len(plane) == 3 and plane.evictions == 0

    hits, misses = plane.warm(pks[:3])
    assert (hits, misses) == (3, 0)

    # two more keys force two LRU evictions; accounting stays exact
    plane.warm(pks[3:5])
    assert plane.evictions == 2
    assert plane.bytes == 3 * per_entry
    assert pks[0] not in plane and pks[1] not in plane
    assert pks[4] in plane
    assert plane.hit_rate() == pytest.approx(3 / 8)


def test_pubkey_plane_mirrors_and_unmirrors_backend_cache():
    from consensus_specs_tpu.ops import bls_backend

    pks = _real_pubkeys(3, base=100)
    for pk in pks:
        bls_backend._PK_CACHE.pop(pk, None)
    probe = pubkeys.PubkeyPlane(budget_bytes=1 << 30, mirror_backend=False)
    probe.warm(pks[:1])
    plane = pubkeys.PubkeyPlane(budget_bytes=2 * probe.bytes)
    plane.warm(pks)
    assert plane.evictions == 1
    # resident keys are warm in the backend cache; evicted keys are not
    assert pks[0] not in bls_backend._PK_CACHE
    assert pks[1] in bls_backend._PK_CACHE and pks[2] in bls_backend._PK_CACHE
    for pk in pks:
        bls_backend._PK_CACHE.pop(pk, None)


def test_pubkey_plane_batched_equals_per_key_decode():
    from consensus_specs_tpu.ops import bls_backend

    pks = _real_pubkeys(4, base=50)
    bad = b"\xa0" + b"\xff" * 47  # x out of range: rejected, never cached
    inf = b"\xc0" + b"\x00" * 47  # infinity: invalid as a pubkey
    plane = pubkeys.PubkeyPlane(budget_bytes=1 << 30, mirror_backend=False)
    plane.warm(pks + [bad, inf])
    assert plane.rejected == 2 and len(plane) == 4
    for pk in pks:
        got_x, got_y = plane.get(pk)
        want_x, want_y = bls_backend._pubkey_limbs_compute(pk)
        np.testing.assert_array_equal(np.asarray(got_x), np.asarray(want_x))
        np.testing.assert_array_equal(np.asarray(got_y), np.asarray(want_y))
