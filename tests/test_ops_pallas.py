"""Cross-checks for the Pallas Montgomery-multiply kernel (ops/pallas_fq.py)
against ops/fq.py's jnp lowering and the exact-integer oracle.

Runs the kernel in interpret mode on CPU (Pallas TPU compilation requires
real hardware; the Mosaic-lowered A/B measurement is staged in
tools/tpu_probe.py and gated on a granted tunnel window — TPU_NOTES.md).
"""
import numpy as np

from consensus_specs_tpu.utils.jax_env import force_cpu

force_cpu()

from consensus_specs_tpu.ops import fq, pallas_fq  # noqa: E402
from consensus_specs_tpu.utils.bls12_381 import P  # noqa: E402


def _rand_loose(rng, shape, max_bits=401):
    """Random loose Montgomery residues: values < 2^max_bits with limbs
    < 2^28 (the carry invariant every VM register satisfies)."""
    vals = np.zeros(shape + (fq.NUM_LIMBS,), dtype=np.uint64)
    flat = vals.reshape(-1, fq.NUM_LIMBS)
    for i in range(flat.shape[0]):
        x = rng.randrange(1 << max_bits)
        flat[i] = fq._int_to_limbs_np(x)
    return vals


def _as_ints(limbs):
    flat = np.asarray(limbs).reshape(-1, fq.NUM_LIMBS)
    return [fq.limbs_to_int(row) for row in flat]


def test_pallas_mont_mul_matches_oracle_and_fq():
    import random

    rng = random.Random(20260730)
    a = _rand_loose(rng, (5, 3))
    b = _rand_loose(rng, (5, 3))

    got = np.asarray(pallas_fq.mont_mul(a, b))
    want_fq = np.asarray(fq.mont_mul(a, b))

    rinv = pow(fq.R_MONT, -1, P)
    for ga, wa, ia, ib in zip(
        _as_ints(got), _as_ints(want_fq), _as_ints(a), _as_ints(b)
    ):
        # same residue class as the oracle...
        assert ga % P == (ia * ib * rinv) % P
        # ...and within the loose-output magnitude contract
        assert ga < (ia * ib) // fq.R_MONT + P + 1
        assert wa % P == ga % P


def test_pallas_mont_mul_edge_values():
    zero = np.zeros((4, fq.NUM_LIMBS), dtype=np.uint64)
    one = np.broadcast_to(fq.ONE_MONT, (4, fq.NUM_LIMBS)).copy()
    pm1 = np.broadcast_to(
        fq._int_to_limbs_np(P - 1), (4, fq.NUM_LIMBS)
    ).copy()
    maxv = np.full((4, fq.NUM_LIMBS), fq.MASK, dtype=np.uint64)  # 2^420 - 1

    for a, b in [(zero, one), (one, one), (pm1, pm1), (maxv, one), (one, maxv)]:
        got = np.asarray(pallas_fq.mont_mul(a, b))
        want = np.asarray(fq.mont_mul(a, b))
        ga, wa = _as_ints(got), _as_ints(want)
        for g, w in zip(ga, wa):
            assert g % P == w % P
        assert got.max(initial=0) < (1 << 28)


def test_pallas_mont_mul_odd_batch_padding():
    """Batch sizes that are not tile multiples pad with zero lanes."""
    import random

    rng = random.Random(7)
    a = _rand_loose(rng, (3,), max_bits=382)
    b = _rand_loose(rng, (3,), max_bits=382)
    got = np.asarray(pallas_fq.mont_mul(a, b))
    want = np.asarray(fq.mont_mul(a, b))
    for g, w in zip(_as_ints(got), _as_ints(want)):
        assert g % P == w % P


def test_pallas_dispatch_flag(monkeypatch):
    """fq.mont_mul must actually route through the kernel when the flag is
    on (a vacuous mod-p comparison would stay green even if the dispatch
    silently broke — count the kernel calls)."""
    import random

    calls = {"n": 0}
    real = pallas_fq.mont_mul

    def counting(a, b):
        calls["n"] += 1
        return real(a, b)

    monkeypatch.setattr(pallas_fq, "mont_mul", counting)

    rng = random.Random(11)
    a = _rand_loose(rng, (2,), max_bits=382)
    b = _rand_loose(rng, (2,), max_bits=382)

    monkeypatch.setenv("CONSENSUS_SPECS_TPU_PALLAS", "1")
    assert pallas_fq.enabled()
    via_fq = np.asarray(fq.mont_mul(a, b))
    assert calls["n"] == 1, "flag on: fq.mont_mul did not dispatch to the kernel"

    monkeypatch.setenv("CONSENSUS_SPECS_TPU_PALLAS", "0")
    assert not pallas_fq.enabled()
    direct = np.asarray(fq.mont_mul(a, b))
    assert calls["n"] == 1, "flag off: fq.mont_mul still dispatched to the kernel"

    for g, w in zip(_as_ints(via_fq), _as_ints(direct)):
        assert g % P == w % P
