"""End-to-end gossip→head latency plane (ISSUE 12): the SlotClock math,
deadline-aware flush scheduling in the serve plane, the per-stage +
end-to-end histogram families, Chrome flow links, the adversarial simnet
run with speculation/rollback through the strict convergence gate, and
the fleet's merged scrape carrying the end-to-end histogram.

Everything here runs crypto-free (verdict-style backends, simnet's
VerdictBackend, verdict-mode fleet workers) so tier-1 stays fast; the
real-crypto serve path is covered by tests/test_serve.py and the full
matrix by `make latency-bench`.
"""
import time

import pytest

from consensus_specs_tpu.obs import flight, latency, slo, tracing
from consensus_specs_tpu.obs.tracing import Tracer
from consensus_specs_tpu.ops import profiling
from consensus_specs_tpu.serve.service import SlotClock, VerificationService
from consensus_specs_tpu.utils import bls

PK = b"\x02" * 48


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_TRACE", "0")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "0")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_DEVICES", "0")
    monkeypatch.delenv("CONSENSUS_SPECS_TPU_SLOT_MS", raising=False)
    monkeypatch.delenv("CONSENSUS_SPECS_TPU_SPECULATE", raising=False)
    profiling.reset()
    latency.reset()
    tracing.reset_global()
    flight.reset_global()
    slo.reset_global()
    was = bls.bls_active
    bls.bls_active = True
    yield
    bls.bls_active = was
    profiling.reset()
    latency.reset()
    tracing.reset_global()
    flight.reset_global()
    slo.reset_global()


class OkBackend:
    """Crypto-free backend: verdict rides in the signature (endswith
    b"ok"), same contract the obs tests use."""

    def __init__(self):
        self.calls = 0

    def _go(self, signatures):
        self.calls += 1
        return [bytes(s).endswith(b"ok") for s in signatures]

    def batch_fast_aggregate_verify(self, pubkey_sets, messages, signatures,
                                    mesh=None):
        return self._go(signatures)

    def batch_aggregate_verify(self, pubkey_lists, message_lists, signatures,
                               mesh=None):
        return self._go(signatures)


class _Oracle:
    def verify_one(self, pending):
        return bytes(pending.signature).endswith(b"ok")


def _svc(**kw):
    kw.setdefault("backend", OkBackend())
    kw.setdefault("oracle", _Oracle())
    kw.setdefault("bucket_fn", lambda k: 8)
    return VerificationService(**kw)


# -- SlotClock ----------------------------------------------------------------


def test_slot_clock_math():
    t = {"now": 0.0}
    clk = SlotClock(0.1, clock=lambda: t["now"], origin=0.0)
    assert clk.slot_index(0.25) == 2
    assert clk.slot_end(0.25) == pytest.approx(0.3)
    assert clk.remaining(0.25) == pytest.approx(0.05)
    # exactly on a boundary: the NEXT slot's end
    assert clk.slot_end(0.2) == pytest.approx(0.3)
    t["now"] = 0.41
    assert clk.slot_index() == 4
    assert clk.remaining() == pytest.approx(0.09)


def test_slot_clock_from_env(monkeypatch):
    monkeypatch.delenv("CONSENSUS_SPECS_TPU_SLOT_MS", raising=False)
    assert SlotClock.from_env() is None
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_SLOT_MS", "0")
    assert SlotClock.from_env() is None
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_SLOT_MS", "not-a-number")
    assert SlotClock.from_env() is None  # malformed degrades, never raises
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_SLOT_MS", "250")
    clk = SlotClock.from_env()
    assert clk is not None and clk.slot_s == pytest.approx(0.25)


def test_service_arms_slot_clock_from_env(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_SLOT_MS", "125")
    with _svc(max_batch=1, max_wait_ms=0) as svc:
        assert svc.slot_clock is not None
        assert svc.slot_clock.slot_s == pytest.approx(0.125)
    with _svc(max_batch=1, max_wait_ms=0,
              slot_clock=SlotClock(0.5)) as svc:
        assert svc.slot_clock.slot_s == 0.5  # explicit wins over env


# -- deadline-aware flushing --------------------------------------------------


def test_deadline_flush_fires_before_max_wait(monkeypatch):
    """With a 50 ms slot clock and a 10 s max_wait, the slot-budget rule
    — not size, not max_wait — must fire the flush: the submit resolves
    within the slot, the deadline counters tick, and the flight journal
    carries the deadline_flush event."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "1")
    flight.reset_global()
    t0 = time.perf_counter()
    with _svc(max_batch=64, max_wait_ms=10_000,
              slot_clock=SlotClock(0.05)) as svc:
        futs = [svc.submit("fast_aggregate", [PK], b"m%d" % i, b"s%d-ok" % i)
                for i in range(3)]
        assert all(f.result(timeout=5) is True for f in futs)
        waited = time.perf_counter() - t0
        assert waited < 5.0  # a 10 s max_wait flush would still be parked
        assert svc.metrics.deadline_flushes >= 1
        assert svc.metrics.last_deadline_budget_ms <= 50.0
    _, gauges = profiling.stats_and_gauges()
    assert gauges.get("serve.deadline_flushes", 0) >= 1
    events = flight.global_recorder().events()
    dl = [e for e in events if e["kind"] == "deadline_flush"]
    assert dl and dl[0]["plane"] == "serve"
    assert dl[0]["data"]["items"] >= 1


def test_classic_flush_untouched_without_slot_clock():
    """No slot clock (env unset, no param): the flush rule is exactly
    size-OR-deadline and the deadline counters never move."""
    with _svc(max_batch=4, max_wait_ms=20) as svc:
        assert svc.slot_clock is None
        fut = svc.submit("fast_aggregate", [PK], b"m", b"s-ok")
        assert fut.result(timeout=10) is True
        assert svc.metrics.deadline_flushes == 0


def test_explicit_deadline_wins_over_slot_grid():
    """A caller-supplied deadline_s takes precedence: an already-blown
    deadline flushes immediately even mid-slot."""
    with _svc(max_batch=64, max_wait_ms=10_000,
              slot_clock=SlotClock(3600.0)) as svc:  # huge slot
        fut = svc.submit("fast_aggregate", [PK], b"m", b"s-ok",
                         deadline_s=time.perf_counter() - 1.0)
        assert fut.result(timeout=5) is True
        assert svc.metrics.deadline_flushes >= 1


def test_downstream_p99_shrinks_the_budget():
    """The budget deadline subtracts the live downstream p99: feed a fat
    device-stage distribution and the read must reflect it (the number
    the scheduler subtracts)."""
    for _ in range(64):
        latency.note_stage("device", 0.040)
        latency.note_stage("prep", 0.010)
        latency.note_stage("finalize", 0.001)
    latency.reset()  # cold cache, histograms stay (they live in profiling)
    total = latency.downstream_p99_s()
    assert total >= 0.045  # prep + device + finalize p99s sum
    # the cache answers repeat reads without a fresh histogram walk
    assert latency.downstream_p99_s() == total


# -- per-stage + end-to-end recording -----------------------------------------


def test_stage_histograms_fill_on_a_flush():
    with _svc(max_batch=4, max_wait_ms=5) as svc:
        futs = [svc.submit("fast_aggregate", [PK], b"m%d" % i, b"s-ok")
                for i in range(4)]
        assert all(f.result(timeout=10) for f in futs)
    hists = profiling.latency_histograms()
    for stage in ("queue_wait", "prep", "device", "finalize"):
        h = hists.get(latency.stage_label(stage))
        assert h is not None and h.count >= 1, stage
    assert hists[latency.stage_label("queue_wait")].count >= 4


def test_ingress_span_and_flow_ride_the_request_trace():
    tracer = Tracer()
    b = latency.birth()
    with _svc(max_batch=1, max_wait_ms=0, tracer=tracer) as svc:
        fut = svc.submit("fast_aggregate", [PK], b"m", b"s-ok",
                         birth_s=b.t, flow_id=b.trace_id)
        assert fut.result(timeout=10) is True
    [done] = tracer.completed()
    assert "ingress" in done.span_names()
    assert done.flow == b.trace_id
    # the ingress hop landed in the stage histogram too
    h = profiling.latency_histograms().get(latency.stage_label("ingress"))
    assert h is not None and h.count == 1


def test_birth_ids_are_unique_and_monotone():
    latency.reset()
    ids = [latency.birth().trace_id for _ in range(5)]
    assert ids == sorted(set(ids))


def test_latency_snapshot_selects_the_plane_families():
    latency.note_stage("device", 0.01)
    latency.note_gossip_to_head(0.05)
    profiling.record_latency("serve.submit_to_result", 0.02)  # excluded
    snap = latency.snapshot()
    assert set(snap) == {latency.stage_label("device"),
                         latency.GOSSIP_TO_HEAD_LABEL}
    assert snap[latency.GOSSIP_TO_HEAD_LABEL]["n"] == 1


# -- the adversarial end-to-end run (simnet, crypto-free) ---------------------


def test_sim_latency_plane_end_to_end_with_speculation():
    """One latency_skew scenario (laggard node, deferral churn, invalid
    signatures) with deadline flushing AND speculative head application,
    through the STRICT differential convergence gate — speculation with
    rollback must be invisible to consensus, and the latency plane must
    have filled: gossip_to_head observations, ingress stage mass, the
    declared objective met, deadline flushes and rollbacks exercised."""
    from consensus_specs_tpu.sim.runner import build_world, run_scenario
    from consensus_specs_tpu.sim.scenarios import get_scenario

    spec, anchor_state, anchor_block = build_world()
    report = run_scenario(
        get_scenario("latency_skew"), spec=spec, anchor_state=anchor_state,
        anchor_block=anchor_block, seed=7, strict=True, query_rounds=16,
        service_kwargs={"max_wait_ms": 25.0, "max_batch": 8,
                        "slot_clock": SlotClock(0.010)},
        head_kwargs={"speculative": True})
    assert report.converged
    assert report.events.get("invalid_sig", 0) >= 1  # liars were present

    hists = profiling.latency_histograms()
    g2h = hists.get(latency.GOSSIP_TO_HEAD_LABEL)
    assert g2h is not None and g2h.count > 0
    assert hists[latency.stage_label("ingress")].count > 0
    assert hists[latency.stage_label("head")].count > 0

    evaluated = slo.global_tracker().evaluate(export=False)
    obj = evaluated["gossip_to_head_p99"]
    assert obj["n"] == g2h.count and obj["ok"] is True

    per_node = report.per_node
    assert sum(v["deadline_flushes"] for v in per_node.values()) > 0
    assert sum(v["speculative_applied"] for v in per_node.values()) > 0
    # the invalid-signature traffic forced real rollbacks — and the
    # strict gate above already proved they were exact
    assert sum(v["rollbacks"] for v in per_node.values()) > 0


def test_sim_speculative_and_plain_runs_agree():
    """Same scenario, same seed, with and without speculation: identical
    agreed head and identical per-node applied counts — speculation is
    pure latency, never state."""
    from consensus_specs_tpu.sim.runner import build_world, run_scenario
    from consensus_specs_tpu.sim.scenarios import get_scenario

    spec, anchor_state, anchor_block = build_world()

    def run(speculative):
        profiling.reset()
        latency.reset()
        return run_scenario(
            get_scenario("withheld_orphans"), spec=spec,
            anchor_state=anchor_state, anchor_block=anchor_block, seed=11,
            strict=True, query_rounds=16,
            head_kwargs={"speculative": speculative})

    plain = run(False)
    spec_run = run(True)
    assert plain.head == spec_run.head
    assert plain.head_slot == spec_run.head_slot
    for name in plain.per_node:
        assert (plain.per_node[name]["applied"]
                == spec_run.per_node[name]["applied"])
        assert plain.per_node[name]["dropped"] \
            == spec_run.per_node[name]["dropped"]


# -- fleet: the merged scrape carries the end-to-end histogram ----------------


def test_fleet_merged_scrape_carries_gossip_to_head():
    """Router-side HeadServices consume fleet-routed verdicts while the
    end-to-end histogram accumulates in the ROUTER process — the merged
    fleet /metrics must carry it (n > 0) alongside the worker families
    (the ISSUE 12 acceptance surface)."""
    from consensus_specs_tpu.serve.fleet import FleetRouter
    from consensus_specs_tpu.sim.fleet_replay import run_fleet_replay

    router = FleetRouter(workers=2, backend="verdict",
                         env={"SERVE_MAX_WAIT_MS": "2"})
    try:
        out = run_fleet_replay("partition_heal", router=router, seed=7,
                               strict=True)
        assert out["report"].converged
        text = router.scrape_text()
        fam = ("consensus_specs_tpu_latency_gossip_to_head_latency_hist_"
               "seconds_count")
        [line] = [l for l in text.splitlines() if l.startswith(fam + " ")]
        assert int(line.rsplit(" ", 1)[1]) > 0
        # worker-side serve families still ride the same scrape (the
        # merge stayed a merge, the local overlay did not clobber it)
        assert "consensus_specs_tpu_serve_node" in text
        # the SLO surface must see the router-local end-to-end histogram
        # too — /healthz (and the control loop's burn rates) evaluate the
        # same overlay, not just the worker snapshots, or the declared
        # gossip_to_head_p99 objective could never fire at the fleet level
        health = router.healthz()
        obj = health["slo"]["gossip_to_head_p99"]
        assert obj["n"] > 0
    finally:
        router.close()
