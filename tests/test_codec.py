"""Oracle-equivalence gate for the batched input codec (ops/codec.py).

Every codec output must be BIT-IDENTICAL to the pure-Python oracle
(utils/bls12_381.py) / the per-item compute functions in ops/bls_backend
— on valid points, invalid encodings, non-subgroup points (including
cofactor-torsion points, the adversarial corner of the fast membership
tests), and infinity, across batch sizes 1..256.

Fast tests cover the raw-int host path (the CPU-fallback serving path in
tier-1). The VM/jax device path runs the same suite under --run-slow
(CONSENSUS_SPECS_TPU_CODEC_DEVICE=1 forces it on CPU, where the programs
are slow but correct).
"""
import random

import numpy as np
import pytest

from consensus_specs_tpu.ops import bls_backend as B
from consensus_specs_tpu.ops import codec, fq
from consensus_specs_tpu.utils import bls12_381 as O

DST = B.DST
SIZES = [1, 2, 3, 4, 5, 8, 16, 33, 64, 256]
POOL = 256


def _norm(v):
    """Codec results and per-item results on one footing: ValueErrors
    (raised or returned) compare by message, limb payloads by bytes."""
    if isinstance(v, ValueError):
        return ("err", str(v))
    if v is None:
        return ("inf",)
    if isinstance(v, tuple):
        return ("ok", tuple(np.asarray(x).tobytes() for x in v))
    return ("ok", np.asarray(v).tobytes())


def _ref(fn, blob):
    try:
        return _norm(fn(blob))
    except ValueError as e:
        return ("err", str(e))


def _rand_g1_affine(rng):
    while True:
        x = rng.randrange(O.P)
        y = O.fq_sqrt((x * x % O.P * x + 4) % O.P)
        if y is not None:
            return (O.Fq(x), O.Fq(y))


def _rand_g2_affine(rng):
    while True:
        x = O.Fq2(rng.randrange(O.P), rng.randrange(O.P))
        y = (x * x * x + O.B_G2).sqrt()
        if y is not None:
            return (x, y)


def _pool_g1():
    """Valid members, invalid encodings, infinity, random non-members,
    and [r]T cofactor-torsion points — POOL blobs, deterministic."""
    rng = random.Random(11)
    blobs = []
    for i in range(POOL):
        r = i % 8
        if r < 3:  # subgroup member
            k = rng.randrange(1, O.R)
            blobs.append(O.g1_to_bytes(O.ec_mul(O.G1_GEN, k)))
        elif r == 3:  # random curve point: non-member w.h.p.
            blobs.append(O.g1_to_bytes(O.ec_from_affine(_rand_g1_affine(rng))))
        elif r == 4:  # cofactor torsion: [r]T kills the G1 part only
            s = O.ec_mul(O.ec_from_affine(_rand_g1_affine(rng)), O.R)
            blobs.append(O.g1_to_bytes(s))
        elif r == 5:  # infinity (valid and corrupted)
            good = bytes([O.FLAG_COMPRESSED | O.FLAG_INFINITY]) + b"\x00" * 47
            blobs.append(good if i % 2 else good[:1] + b"\x01" + good[2:])
        elif r == 6:  # x not on curve / x out of range
            if i % 2:
                blobs.append(bytes([0x80]) + b"\x00" * 46 + b"\x05")
            else:
                blobs.append(
                    bytes([0x9F]) + b"\xff" * 47
                )  # x >= p with sign bit games
        else:  # structural: wrong length, missing compress bit
            blobs.append([b"\x00" * 48, b"\x12" * 48, b"\xc0" + b"\x00" * 40,
                          O.g1_to_bytes(O.G1_GEN)[:47]][i % 4])
    return blobs


def _pool_g2():
    rng = random.Random(13)
    blobs = []
    for i in range(POOL):
        r = i % 8
        if r < 3:
            k = rng.randrange(1, O.R)
            blobs.append(O.g2_to_bytes(O.ec_mul(O.G2_GEN, k)))
        elif r == 3:  # random curve point: outside G2 w.h.p.
            blobs.append(O.g2_to_bytes(_rand_g2_affine(rng)))
        elif r == 4:  # cofactor torsion on the twist
            s = O.ec_mul(O.ec_from_affine(_rand_g2_affine(rng)), O.R)
            blobs.append(O.g2_to_bytes(s))
        elif r == 5:
            good = bytes([O.FLAG_COMPRESSED | O.FLAG_INFINITY]) + b"\x00" * 95
            blobs.append(good if i % 2 else good[:5] + b"\x01" + good[6:])
        elif r == 6:
            if i % 2:
                blobs.append(bytes([0x80]) + b"\x00" * 94 + b"\x07")
            else:
                blobs.append(bytes([0x9F]) + b"\xff" * 95)
        else:
            blobs.append([b"\x00" * 96, b"\x34" * 96, b"\xc0" + b"\x01" * 95,
                          O.g2_to_bytes(O.G2_GEN)[:95]][i % 4])
    return blobs


def _pool_msgs():
    rng = random.Random(17)
    msgs = [b"", b"\x00", b"q" * 130]  # length edges incl. > one SHA block
    while len(msgs) < POOL:
        msgs.append(rng.randbytes(rng.choice([8, 32, 64])))
    return msgs


_G1 = _pool_g1()
_G2 = _pool_g2()
_MSGS = _pool_msgs()
# oracle references computed once per distinct blob, reused by all sizes
_G1_REF = {b: _ref(B._pubkey_limbs_compute, b) for b in set(_G1)}
_G2_REF = {b: _ref(B._signature_limbs_compute, b) for b in set(_G2)}
_MSG_REF = {m: _norm(B._message_limbs_compute(m)) for m in set(_MSGS)}


@pytest.mark.parametrize("n", SIZES)
def test_pubkey_batch_matches_oracle(n):
    blobs = _G1[:n]
    got = codec.pubkey_limbs_batch(blobs)
    assert [_norm(v) for v in got] == [_G1_REF[b] for b in blobs]


@pytest.mark.parametrize("n", SIZES)
def test_signature_batch_matches_oracle(n):
    blobs = _G2[:n]
    got = codec.signature_limbs_batch(blobs)
    assert [_norm(v) for v in got] == [_G2_REF[b] for b in blobs]


@pytest.mark.parametrize("n", SIZES)
def test_message_batch_matches_oracle(n):
    msgs = _MSGS[:n]
    got = codec.message_limbs_batch(msgs, DST)
    assert [_norm(v) for v in got] == [_MSG_REF[m] for m in msgs]


def test_decompress_infinity_is_none():
    inf1 = bytes([O.FLAG_COMPRESSED | O.FLAG_INFINITY]) + b"\x00" * 47
    inf2 = bytes([O.FLAG_COMPRESSED | O.FLAG_INFINITY]) + b"\x00" * 95
    assert codec.decompress_g1_batch([inf1]) == [None]
    assert codec.decompress_g2_batch([inf2]) == [None]
    # the backend-facing wrappers turn infinity into the oracle's error
    assert _norm(codec.pubkey_limbs_batch([inf1])[0]) == _ref(
        B._pubkey_limbs_compute, inf1
    )
    assert _norm(codec.signature_limbs_batch([inf2])[0]) == _ref(
        B._signature_limbs_compute, inf2
    )


def test_expand_message_xmd_batch_matches_oracle():
    msgs = [b"", b"abc", b"q" * 200, b"\x00" * 31]
    for lib in (32, 64, 100, 256):
        got = codec.expand_message_xmd_batch(msgs, DST, lib)
        want = [O.expand_message_xmd(m, DST, lib) for m in msgs]
        assert got == want


def test_int_batch_inverse_matches_fermat():
    rng = random.Random(19)
    vals = [0, 1, O.P - 1] + [rng.randrange(O.P) for _ in range(61)]
    got = codec.int_batch_inverse(vals)
    for v, iv in zip(vals, got):
        assert iv == (pow(v, O.P - 2, O.P) if v else 0)


def test_glv_beta_eigenvalue_against_generator():
    """The G1 host membership test hinges on phi(P) == [-z^2]P with
    _BETA_G1 the matching cube root; pin that pairing to the oracle."""
    z = codec._X_ABS
    g = O.ec_to_affine(O.G1_GEN)
    phi = (codec._BETA_G1 * g[0].n % O.P, g[1].n)
    q = O.ec_to_affine(O.ec_neg(O.ec_mul(O.G1_GEN, z * z)))
    assert phi == (q[0].n, q[1].n)
    assert pow(codec._BETA_G1, 3, O.P) == 1 and codec._BETA_G1 != 1


def test_g1_subgroup_host_matches_definitional():
    """The GLV two-ladder test vs the oracle's [r]P == O, specifically on
    torsion points where a wrong eigenvalue/criterion would diverge."""
    rng = random.Random(23)
    pts = []
    for _ in range(6):
        aff = _rand_g1_affine(rng)
        pts.append(aff)
        s = O.ec_mul(O.ec_from_affine(aff), O.R)
        if s is not None:
            pts.append(O.ec_to_affine(s))
    for k in (1, 2, 12345):
        pts.append(O.ec_to_affine(O.ec_mul(O.G1_GEN, k)))
    got = codec._g1_subgroup_host([(x.n, y.n) for x, y in pts])
    want = [O.is_in_g1_subgroup(O.ec_from_affine(a)) for a in pts]
    assert got == want


def test_g2_subgroup_host_matches_oracle():
    rng = random.Random(29)
    pts = [_rand_g2_affine(rng) for _ in range(6)]
    for _ in range(3):
        s = O.ec_mul(O.ec_from_affine(_rand_g2_affine(rng)), O.R)
        if s is not None:
            pts.append(O.ec_to_affine(s))
    for k in (1, 7, 99999):
        pts.append(O.ec_to_affine(O.ec_mul(O.G2_GEN, k)))
    got = codec._g2_subgroup_host(
        [((x.c0, x.c1), (y.c0, y.c1)) for x, y in pts]
    )
    want = [O.is_in_g2_subgroup(O.ec_from_affine(a)) for a in pts]
    assert got == want


# -- jax field kernels (shared sqrt chains / batch-inversion ladder) --------


def test_fq_batch_inverse_kernel():
    rng = random.Random(31)
    vals = [0, 1, O.P - 1] + [rng.randrange(O.P) for _ in range(13)]
    arr = np.stack([fq.to_mont_int(v) for v in vals])
    out = codec.fq_batch_inverse(arr)
    for v, limbs in zip(vals, out):
        want = pow(v, O.P - 2, O.P) if v else 0
        assert fq.from_mont_limbs(limbs) == want


def test_fq2_sqrt_batch_matches_oracle_choice():
    """Bit-identical root CHOICE, not just +/- equivalence; ok False
    exactly where the oracle returns None; b == 0 branches included."""
    rng = random.Random(37)
    vals = []
    for _ in range(10):
        v = O.Fq2(rng.randrange(O.P), rng.randrange(O.P))
        vals.append(v)
        vals.append(v.square())  # guaranteed residue
    for a in (0, 1, 5, O.P - 1):
        vals.append(O.Fq2(a, 0))  # b == 0 lanes
    arr = np.stack(
        [np.stack([fq.to_mont_int(v.c0), fq.to_mont_int(v.c1)]) for v in vals]
    )
    roots, ok = codec.fq2_sqrt_batch(arr)
    for v, r, k in zip(vals, roots, ok):
        want = v.sqrt()
        assert bool(k) == (want is not None)
        if want is not None:
            assert fq.from_mont_limbs(r[0]) == want.c0
            assert fq.from_mont_limbs(r[1]) == want.c1


# -- device path (VM programs + jax decode kernels), --run-slow only --------


@pytest.fixture
def force_device(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_CODEC_DEVICE", "1")


@pytest.mark.slow
@pytest.mark.parametrize("n", [1, 3, 8])
def test_device_pubkey_batch(force_device, n):
    blobs = _G1[:n]
    got = codec.pubkey_limbs_batch(blobs)
    assert [_norm(v) for v in got] == [_G1_REF[b] for b in blobs]


@pytest.mark.slow
@pytest.mark.parametrize("n", [1, 3, 8])
def test_device_signature_batch(force_device, n):
    blobs = _G2[:n]
    got = codec.signature_limbs_batch(blobs)
    assert [_norm(v) for v in got] == [_G2_REF[b] for b in blobs]


@pytest.mark.slow
@pytest.mark.parametrize("n", [1, 3])
def test_device_message_batch(force_device, n):
    msgs = _MSGS[:n]
    got = codec.message_limbs_batch(msgs, DST)
    assert [_norm(v) for v in got] == [_MSG_REF[m] for m in msgs]
