"""Field-ALU VM correctness: assembler, scheduler, and vmlib formulas vs the
pure-Python oracle. All programs here share one small shape bucket
(W=64, steps padded to 256, regs padded to 64) so the suite pays for at most
one XLA compile (persistent-cached on disk afterwards)."""
import random

import pytest

jax = pytest.importorskip("jax")

from consensus_specs_tpu.ops import fq, vm, vmlib  # noqa: E402
from consensus_specs_tpu.utils import bls12_381 as O  # noqa: E402

rng = random.Random(99)

BUCKET = dict(w_mul=64, w_lin=64, pad_steps_to=256, pad_regs_to=64)


def run(prog, ins_ints, batch_shape=()):
    pr = prog.assemble(**BUCKET)
    ins = {k: fq.to_mont_int(v) for k, v in ins_ints.items()}
    out = vm.execute(pr, ins, batch_shape=batch_shape)
    return {k: fq.from_mont_limbs(v) for k, v in out.items()}


def test_alu_chain():
    prog = vm.Prog()
    a, b, c, d = (prog.inp(n) for n in "abcd")
    r = (a * b + c - d) * (a + a) - b
    prog.out(r, "r")
    av, bv, cv, dv = (rng.randrange(O.P) for _ in range(4))
    got = run(prog, dict(a=av, b=bv, c=cv, d=dv))["r"]
    assert got == (((av * bv + cv - dv) * 2 * av) - bv) % O.P


def test_auto_compress_long_chains():
    # force magnitudes past the lazy-reduction bounds: deep add/sub chains
    prog = vm.Prog()
    a = prog.inp("a")
    b = prog.inp("b")
    acc = a
    for _ in range(40):
        acc = acc + acc  # doubles the bound each time; must auto-compress
    acc = acc - b
    acc = acc * acc
    prog.out(acc, "r")
    av, bv = rng.randrange(O.P), rng.randrange(O.P)
    exp = pow((av * (1 << 40) - bv) % O.P, 2, O.P)
    assert run(prog, dict(a=av, b=bv))["r"] == exp


def test_f2_mul_square_vs_oracle():
    prog = vm.Prog()
    x = vmlib.f2_inputs(prog, "x")
    y = vmlib.f2_inputs(prog, "y")
    m = x * y
    s = x.square()
    xi = x.mul_xi()
    prog.out(m.c0, "m0")
    prog.out(m.c1, "m1")
    prog.out(s.c0, "s0")
    prog.out(s.c1, "s1")
    prog.out(xi.c0, "xi0")
    prog.out(xi.c1, "xi1")
    xv = O.Fq2(rng.randrange(O.P), rng.randrange(O.P))
    yv = O.Fq2(rng.randrange(O.P), rng.randrange(O.P))
    got = run(
        prog,
        {"x.0": xv.c0, "x.1": xv.c1, "y.0": yv.c0, "y.1": yv.c1},
    )
    mv = xv * yv
    sv = xv * xv
    xiv = xv * O.Fq2(1, 1)
    assert (got["m0"], got["m1"]) == (mv.c0, mv.c1)
    assert (got["s0"], got["s1"]) == (sv.c0, sv.c1)
    assert (got["xi0"], got["xi1"]) == (xiv.c0, xiv.c1)


def _g1_prog_add():
    prog = vm.Prog()
    p1 = tuple(prog.inp(f"p.{c}") for c in "xyz")
    p2 = tuple(prog.inp(f"q.{c}") for c in "xyz")
    x3, y3, z3 = vmlib.g1_complete_add(prog, p1, p2)
    prog.out(x3, "x")
    prog.out(y3, "y")
    prog.out(z3, "z")
    return prog


def _to_affine_ints(x, y, z):
    if z == 0:
        return None
    zi = pow(z, O.P - 2, O.P)
    return (x * zi) % O.P, (y * zi) % O.P


def _oracle_affine(pt):
    if pt is None:
        return None
    aff = O.ec_to_affine(pt)
    return aff[0].n, aff[1].n


@pytest.mark.parametrize(
    "k1,k2",
    [(5, 7), (3, 3), (11, 0), (0, 13), (9, -9), (0, 0)],
    ids=["generic", "double", "q-inf", "p-inf", "negatives", "both-inf"],
)
def test_g1_complete_add_vs_oracle(k1, k2):
    """RCB complete addition handles generic/double/infinity/inverse cases."""
    prog = _g1_prog_add()

    def proj(k):
        if k == 0:
            return {"x": 0, "y": 1, "z": 0}
        aff = O.ec_to_affine(O.ec_mul(O.G1_GEN, k % O.R))
        return {"x": aff[0].n, "y": aff[1].n, "z": 1}

    a, b = proj(k1), proj(k2)
    ins = {f"p.{c}": a[c] for c in "xyz"}
    ins.update({f"q.{c}": b[c] for c in "xyz"})
    got = run(prog, ins)
    got_aff = _to_affine_ints(got["x"], got["y"], got["z"])
    exp_pt = O.ec_mul(O.G1_GEN, (k1 + k2) % O.R) if (k1 + k2) % O.R else None
    assert got_aff == _oracle_affine(exp_pt)


def test_g1_tree_sum_vs_oracle():
    ks = [rng.randrange(1, O.R) for _ in range(5)]
    prog = vm.Prog()
    pts = []
    for j in range(5):
        pts.append(tuple(prog.inp(f"p{j}.{c}") for c in "xyz"))
    x3, y3, z3 = vmlib.g1_tree_sum(prog, pts)
    prog.out(x3, "x")
    prog.out(y3, "y")
    prog.out(z3, "z")
    ins = {}
    for j, k in enumerate(ks):
        aff = O.ec_to_affine(O.ec_mul(O.G1_GEN, k))
        ins[f"p{j}.x"] = aff[0].n
        ins[f"p{j}.y"] = aff[1].n
        ins[f"p{j}.z"] = 1
    got = run(prog, ins)
    got_aff = _to_affine_ints(got["x"], got["y"], got["z"])
    assert got_aff == _oracle_affine(O.ec_mul(O.G1_GEN, sum(ks) % O.R))


def _rand_fq12():
    def r2():
        return O.Fq2(rng.randrange(O.P), rng.randrange(O.P))

    def r6():
        return O.Fq6(r2(), r2(), r2())

    return O.Fq12(r6(), r6())


def _f12_prog(fn, n_out=12):
    prog = vm.Prog()
    a = [prog.inp(f"a.{i}") for i in range(12)]
    r = fn(prog, a)
    for i in range(12):
        prog.out(r[i], f"r.{i}")
    return prog


def _f12_run(prog, x: O.Fq12):
    from consensus_specs_tpu.ops.bls_backend import (
        _flat_ints_to_oracle,
        _oracle_to_flat_ints,
    )

    flat = _oracle_to_flat_ints(x)
    got = run(prog, {f"a.{i}": flat[i] for i in range(12)})
    return _flat_ints_to_oracle([got[f"r.{i}"] for i in range(12)])


def test_f12_square_and_frobenius_vs_oracle():
    x = _rand_fq12()
    assert _f12_run(_f12_prog(vmlib.f12_square), x) == x * x
    assert _f12_run(
        _f12_prog(lambda p, a: vmlib.f12_frobenius(p, a, 1)), x
    ) == x.frobenius()
    assert _f12_run(
        _f12_prog(lambda p, a: vmlib.f12_frobenius(p, a, 2)), x
    ) == x.frobenius().frobenius()
    assert _f12_run(_f12_prog(vmlib.f12_conj), x) == x.conjugate()


def test_f12_cyclotomic_square_vs_oracle():
    # land a random element in the cyclotomic subgroup via the easy part
    f = _rand_fq12()
    g = f.conjugate() * f.inverse()
    g = g.frobenius().frobenius() * g
    got = _f12_run(_f12_prog(vmlib.f12_cyclotomic_square), g)
    assert got == g * g


def _rand_unitary():
    f = _rand_fq12()
    g = f.conjugate() * f.inverse()
    return g.frobenius().frobenius() * g


def test_f12_cyclotomic_square_comps_vs_oracle():
    """The depth-lean component-form squaring (ISSUE 10): same map as the
    flat Granger-Scott squaring, ~5 ALU levels instead of ~11."""
    def fn(p, a):
        return vmlib.f12_from_comps(
            vmlib.f12_cyclotomic_square_comps(p, vmlib.f12_to_comps(a)))

    g = _rand_unitary()
    assert _f12_run(_f12_prog(fn), g) == g * g


def test_cyc_pow_spine_and_window_vs_oracle():
    """The two new static-exponent ladders on a unitary base: the
    deferred-product spine (frobenius variant) and the sliding-window
    ladder (windowed variant), each vs exact-int pow."""
    e = 0xD3A1  # several set bits incl. adjacent ones
    g = _rand_unitary()

    def spine(p, a):
        return vmlib._cyc_pow_spine(p, vmlib.f12_to_comps(a), e)

    def window(p, a):
        return vmlib._cyc_pow_window(p, a, e)

    exp = g
    for b in bin(e)[3:]:
        exp = exp * exp
        if b == "1":
            exp = exp * g
    assert _f12_run(_f12_prog(spine), g) == exp
    assert _f12_run(_f12_prog(window), g) == exp


def _oracle_hard_part(g):
    # the one shared exact-int HHT chain (bls_backend owns the formula)
    from consensus_specs_tpu.ops.bls_backend import hard_part_res_oracle

    return hard_part_res_oracle(g)


@pytest.mark.parametrize("builder", [
    vmlib.build_hard_part_windowed,
    vmlib.build_hard_part_frobenius,
], ids=["windowed", "frobenius"])
def test_hard_part_variants_vs_oracle(builder):
    """The ISSUE 10 width-for-depth hard parts are BIT-identical to the
    exact-int HHT on random unitary inputs (production assembly shape, so
    the executable is the one bls_backend routes to)."""
    from consensus_specs_tpu.ops import bls_backend as bb

    prog = builder(1)
    pr = prog.assemble(w_mul=bb.W_MUL, w_lin=bb.W_LIN,
                       pad_steps_to=bb.PAD_STEPS, pad_regs_to=bb._pow2(64))
    from consensus_specs_tpu.ops.bls_backend import (
        _flat_ints_to_oracle,
        _oracle_to_flat_ints,
    )

    g = _rand_unitary()
    flat = _oracle_to_flat_ints(g)
    out = vm.execute(pr, {f"g.{i}": fq.to_mont_int(flat[i]) for i in range(12)})
    got = _flat_ints_to_oracle(
        [fq.from_mont_limbs(out[f"res.{i}"]) for i in range(12)]
    )
    assert got == _oracle_hard_part(g)


# ---------------------------------------------------------------------------
# the assembler's own bound machinery
# ---------------------------------------------------------------------------


def test_inp_loose_bound_accepts_another_programs_output():
    """The RLC feed path: program 1's out() is compressed but LOOSE
    (< 2^382, not < p); program 2 declares that magnitude via inp(bound=)
    and must still compute correctly when the raw limbs are fed straight
    back in with no host canonicalization."""
    p1 = vm.Prog()
    a, b = p1.inp("a"), p1.inp("b")
    p1.out(a * b, "r")
    av, bv = rng.randrange(O.P), rng.randrange(O.P)
    pr1 = p1.assemble(**BUCKET)
    raw = vm.execute(
        pr1, {"a": fq.to_mont_int(av), "b": fq.to_mont_int(bv)}
    )["r"]  # loose Montgomery limbs, NOT reduced mod p

    p2 = vm.Prog()
    x = p2.inp("x", bound=vmlib.RLC_F_BOUND)
    y = p2.inp("y")
    assert p2.ops[x.idx].bound == vmlib.RLC_F_BOUND  # declaration recorded
    p2.out(x * y + x, "r")
    yv = rng.randrange(O.P)
    got = vm.execute(
        p2.assemble(**BUCKET), {"x": raw, "y": fq.to_mont_int(yv)}
    )["r"]
    expect = (av * bv * yv + av * bv) % O.P
    assert fq.from_mont_limbs(got) == expect


def test_b_cap_assertion_fires_on_overdeclared_input():
    """_B_CAP guards declared input bounds too: a declaration at the
    15-limb capacity can never be carry-safe."""
    prog = vm.Prog()
    with pytest.raises(AssertionError, match="missing compress"):
        prog.inp("a", bound=1 << 420)


def test_sub_auto_compresses_loose_operands():
    """Loose-declared operands past the borrowless-subtract preconditions
    (subtrahend <= MP, minuend headroom) must be auto-compressed, keeping
    the result exact."""
    prog = vm.Prog()
    a = prog.inp("a", bound=1 << 412)
    b = prog.inp("b", bound=1 << 412)  # far above the MP subtrahend cap
    prog.out(a - b, "r")
    assert all(op.bound < (1 << 420) for op in prog.ops)
    av, bv = rng.randrange(O.P), rng.randrange(O.P)
    got = run(prog, dict(a=av, b=bv))["r"]
    assert got == (av - bv) % O.P


def test_cse_key_symmetry_for_commutative_ops():
    prog = vm.Prog()
    a, b = prog.inp("a"), prog.inp("b")
    # commutative: both operand orders must hit one op
    assert (a * b).idx == (b * a).idx
    assert (a + b).idx == (b + a).idx
    # and repeats add no ops at all
    n = len(prog.ops)
    assert (a * b).idx == (b * a).idx
    assert len(prog.ops) == n
    # subtraction is NOT commutative: orders must stay distinct
    assert (a - b).idx != (b - a).idx
