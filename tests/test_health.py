"""Consensus health ledger (ISSUE 19): per-slot rows from a stubbed
HeadService (participation weighting, finality lag, churn/reorg deltas,
unexplained-reorg accounting under declared disruption windows), the
summary/aggregate algebra, the HEALTH gate, and the gauge export the
TSDB samples. Crypto-free: the ledger only reads counters and dicts.
"""
import pytest

from consensus_specs_tpu.chain.health import (
    DEFAULT_PARTICIPATION_FLOOR,
    GAUGE_LABELS,
    HealthLedger,
    aggregate_summaries,
    evaluate_gate,
)
from consensus_specs_tpu.ops import profiling


@pytest.fixture(autouse=True)
def _clean_profiling():
    profiling.reset()
    yield
    profiling.reset()


class _Spec:
    SLOTS_PER_EPOCH = 8

    def get_current_slot(self, store):
        return store.current_slot

    def compute_start_slot_at_epoch(self, epoch):
        return epoch * self.SLOTS_PER_EPOCH


class _Checkpoint:
    def __init__(self, epoch):
        self.epoch = epoch


class _Store:
    def __init__(self):
        self.current_slot = 0
        self.finalized_checkpoint = _Checkpoint(0)


class _ForkChoice:
    def __init__(self):
        self._balances = {}
        self.votes = {}


class _Metrics:
    def __init__(self):
        self._c = {"head_changes": 0, "reorgs": 0, "rollbacks": 0,
                   "last_reorg_depth": 0}

    def counters(self):
        return dict(self._c)


class _FakeHead:
    """The minimal HeadService surface the ledger reads."""

    def __init__(self):
        self.spec = _Spec()
        self.store = _Store()
        self.fc = _ForkChoice()
        self.metrics = _Metrics()
        self.deferred_count = 0


def _vote(head, validator, balance, voted=True):
    head.fc._balances[validator] = balance
    if voted:
        head.fc.votes[validator] = object()


def test_participation_is_balance_weighted():
    head = _FakeHead()
    _vote(head, 0, 32, voted=True)
    _vote(head, 1, 32, voted=True)
    _vote(head, 2, 96, voted=False)  # one heavy abstainer
    rec = HealthLedger(head).observe_slot(slot=5)
    assert rec["participation_rate"] == pytest.approx(64 / 160)
    assert rec["slot"] == 5


def test_empty_validator_set_reads_zero_not_crash():
    rec = HealthLedger(_FakeHead()).observe_slot(slot=0)
    assert rec["participation_rate"] == 0.0


def test_finality_lag_is_slots_past_finalized_epoch_start():
    head = _FakeHead()
    head.store.finalized_checkpoint = _Checkpoint(2)  # start slot 16
    led = HealthLedger(head)
    assert led.observe_slot(slot=18)["finality_lag_slots"] == 2
    assert led.observe_slot(slot=40)["finality_lag_slots"] == 24
    # finalized ahead of the queried slot clamps at 0, never negative
    assert led.observe_slot(slot=10)["finality_lag_slots"] == 0
    assert led.summary()["finality_lag_max"] == 24


def test_counter_deltas_not_cumulatives_per_slot():
    head = _FakeHead()
    led = HealthLedger(head)
    head.metrics._c.update(head_changes=3, rollbacks=1)
    rec = led.observe_slot(slot=1)
    assert rec["head_churn"] == 3 and rec["rollback_rate"] == 1
    # no movement next slot: deltas read 0, totals hold
    rec = led.observe_slot(slot=2)
    assert rec["head_churn"] == 0 and rec["rollback_rate"] == 0
    assert led.head_churn_total == 3 and led.rollbacks_total == 1


def test_unexplained_reorgs_only_accumulate_outside_declared_windows():
    head = _FakeHead()
    led = HealthLedger(head)
    # a reorg inside a declared disruption window: explained
    head.metrics._c.update(reorgs=1, last_reorg_depth=2)
    rec = led.observe_slot(slot=1, expect_reorgs=True)
    assert rec["unexplained_reorgs"] == 0 and rec["reorg_depth"] == 2
    # the same movement outside any window: counted, and it sticks
    head.metrics._c.update(reorgs=3, last_reorg_depth=5)
    rec = led.observe_slot(slot=2, expect_reorgs=False)
    assert rec["unexplained_reorgs"] == 2
    assert led.summary()["unexplained_reorgs"] == 2
    assert led.summary()["reorgs_total"] == 3
    assert led.summary()["reorg_depth_max"] == 5


def test_reorg_depth_reads_zero_when_head_only_extended():
    head = _FakeHead()
    head.metrics._c.update(last_reorg_depth=7)  # stale depth, no reorg
    assert HealthLedger(head).observe_slot(slot=1)["reorg_depth"] == 0


def test_gauges_export_under_node_label():
    head = _FakeHead()
    _vote(head, 0, 32)
    HealthLedger(head, node="n2").observe_slot(slot=3)
    gauges = profiling.stats_and_gauges()[1]
    for label in GAUGE_LABELS:
        name = label.split("health.", 1)[1]
        assert f"health[n2].{name}" in gauges, f"missing {name}"
    assert gauges["health[n2].participation_rate"] == 1.0
    # bare (node=None) form uses the registered base names
    HealthLedger(head).observe_slot(slot=3)
    gauges = profiling.stats_and_gauges()[1]
    assert "health.participation_rate" in gauges


def test_record_window_is_bounded_but_extremes_are_cumulative():
    head = _FakeHead()
    led = HealthLedger(head, window=4)
    _vote(head, 0, 32)
    head.store.finalized_checkpoint = _Checkpoint(0)
    for slot in range(10):
        led.observe_slot(slot=slot)
    assert len(led.records()) == 4
    assert led.summary()["slots_observed"] == 10
    # the max lag happened before the ring dropped it; summary keeps it
    assert led.summary()["finality_lag_max"] == 9


def test_aggregate_summaries_takes_the_worst_case_per_bound():
    a = {"slots_observed": 10, "participation_min": 0.9,
         "participation_mean": 0.95, "participation_last": 0.92,
         "finality_lag_max": 4, "finality_lag_last": 2,
         "reorg_depth_max": 1, "reorgs_total": 2, "unexplained_reorgs": 0,
         "head_churn_total": 5, "rollbacks_total": 1,
         "deferral_depth_max": 3}
    b = dict(a, participation_min=0.7, finality_lag_max=30,
             unexplained_reorgs=1, reorgs_total=1)
    agg = aggregate_summaries([a, b])
    assert agg["participation_min"] == 0.7     # min across nodes
    assert agg["finality_lag_max"] == 30       # max across nodes
    assert agg["unexplained_reorgs"] == 1      # sums
    assert agg["reorgs_total"] == 3
    assert aggregate_summaries([])["slots_observed"] == 0


def test_gate_verdicts_and_reasons():
    head = _FakeHead()
    _vote(head, 0, 32)
    led = HealthLedger(head)
    for slot in range(4):
        led.observe_slot(slot=slot)
    ok = evaluate_gate(led.summary())
    assert ok["ok"] and ok["reasons"] == []
    assert ok["participation_floor"] == DEFAULT_PARTICIPATION_FLOOR
    # each bound trips independently, with a legible reason string
    sick = dict(led.summary(), participation_min=0.1,
                finality_lag_max=999, unexplained_reorgs=2)
    verdict = evaluate_gate(sick)
    assert not verdict["ok"] and len(verdict["reasons"]) == 3
    assert any("participation_min" in r for r in verdict["reasons"])
    assert any("finality_lag_max" in r for r in verdict["reasons"])
    assert any("unexplained_reorgs" in r for r in verdict["reasons"])
    # a lag that grew and recovered still fails the bound it crossed
    recovered = dict(led.summary(), finality_lag_max=100,
                     finality_lag_last=2)
    assert not evaluate_gate(recovered, finality_lag_max_slots=64)["ok"]
    # empty horizon is never a pass
    assert not evaluate_gate(aggregate_summaries([]))["ok"]


def test_soak_scenario_shapes_the_horizon():
    """The soak's scenario keeps the zero-unexplained-reorg gate a real
    claim: the canonical chain must be fork-free by construction, every
    partition window must respect the epoch boundary invariant, and the
    horizon must cover >= 1000 slots at the acceptance epoch count."""
    from consensus_specs_tpu.bench.soak import WARMUP_EPOCHS, soak_scenario

    sc = soak_scenario(128)
    spe = 8
    assert sc.fork_rate == 0.0
    assert sc.epochs == 128 and sc.name == "telemetry_soak"
    assert sc.epochs * spe - 1 >= 1000 + WARMUP_EPOCHS * spe
    assert sc.partitions, "soak without disruption proves nothing"
    for w in sc.partitions:
        epoch = int(w.form_slot) // spe
        assert w.form_slot == epoch * spe + 2
        assert w.heal_slot == (epoch + 1) * spe + 1
        assert len(w.groups) == 2
