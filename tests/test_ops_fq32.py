"""uint32-only field arithmetic (ops/fq32.py) vs the exact-integer oracle —
the SURVEY §7.3 #1 fallback representation for v5e's 32-bit vector units."""
from random import Random

import numpy as np

from consensus_specs_tpu.ops import fq32
from consensus_specs_tpu.utils.bls12_381 import P

RNG = Random(321321)


def _rand():
    return RNG.randrange(P)


def test_limb_roundtrip():
    for _ in range(10):
        x = _rand()
        assert fq32.limbs_to_int(fq32._int_to_limbs_np(x)) == x
    assert fq32.from_mont_limbs(fq32.to_mont_int(12345)) == 12345


def test_mont_mul_matches_oracle():
    for _ in range(20):
        a, b = _rand(), _rand()
        out = np.asarray(fq32.mont_mul(fq32.to_mont_int(a), fq32.to_mont_int(b)))
        assert fq32.from_mont_limbs(out) == a * b % P
        # uint32 everywhere
        assert out.dtype == np.uint32


def test_add_sub_match_oracle():
    for _ in range(20):
        a, b = _rand(), _rand()
        s = np.asarray(fq32.add(fq32.to_mont_int(a), fq32.to_mont_int(b)))
        assert fq32.from_mont_limbs(s) == (a + b) % P
        d = np.asarray(fq32.sub(fq32.to_mont_int(a), fq32.to_mont_int(b)))
        assert fq32.from_mont_limbs(d) == (a - b) % P


def test_chained_ops_stay_bounded():
    # a long chain of muls/adds/subs must stay within limb capacity and
    # remain correct — the lazy-reduction audit in practice
    a_int, acc_int = _rand(), 1
    acc = fq32.to_mont_int(1)
    a = fq32.to_mont_int(a_int)
    for i in range(30):
        if i % 3 == 0:
            acc = fq32.mont_mul(acc, a)
            acc_int = acc_int * a_int % P
        elif i % 3 == 1:
            acc = fq32.add(acc, a)
            acc_int = (acc_int + a_int) % P
        else:
            acc = fq32.sub(acc, a)
            acc_int = (acc_int - a_int) % P
        assert np.asarray(acc).max() < (1 << 32)
    assert fq32.from_mont_limbs(np.asarray(acc)) == acc_int


def test_canonical_and_batched():
    xs = [_rand() for _ in range(8)]
    batch = np.stack([fq32.to_mont_int(x) for x in xs])
    sq = np.asarray(fq32.mont_mul(batch, batch))
    for i, x in enumerate(xs):
        assert fq32.from_mont_limbs(sq[i]) == x * x % P
    canon = np.asarray(fq32.canonical(batch))
    for i, x in enumerate(xs):
        # canonical() reduces the MONTGOMERY representative to [0, p)
        assert fq32.limbs_to_int(canon[i]) == (x * fq32.R_MONT) % P


def test_compiles_without_x64():
    """The whole point: the kernel must trace as pure 32-bit."""
    import jax

    fn = jax.jit(lambda a, b: fq32.mont_mul(a, b))
    a = fq32.to_mont_int(_rand())
    b = fq32.to_mont_int(_rand())
    lowered = fn.lower(a, b)
    text = lowered.as_text()
    assert "u64" not in text  # no 64-bit unsigned arithmetic anywhere
    out = np.asarray(fn(a, b))
    assert out.dtype == np.uint32
