"""HeadService integration: real spec histories, serve-plane signature
routing, deferred/dropped gossip, chain metrics + exposition, tracing
spans, and the head-bench glue.

The differential claim here runs on REAL histories (blocks built through
the actual state transition, attestations from real committees) with the
service's inline differential assert enabled — every block and every
attestation batch compares the maintained head against a from-scratch
``spec.get_head``. The synthetic randomized gate lives in
tests/test_chain.py.
"""
import json
import random
import urllib.request

import pytest

from consensus_specs_tpu.builder import build_spec_module
from consensus_specs_tpu.chain import HeadService
from consensus_specs_tpu.obs.tracing import CHAIN_STAGES, Tracer
from consensus_specs_tpu.serve.load import (
    BAD_SIGNATURE,
    VerdictBackend,
    plan_gossip_faults,
)
from consensus_specs_tpu.serve.service import VerificationService
from consensus_specs_tpu.test import context
from consensus_specs_tpu.test.helpers.attestations import (
    get_valid_attestation,
    next_epoch_with_attestations,
)
from consensus_specs_tpu.test.helpers.block import (
    build_empty_block_for_next_slot,
)
from consensus_specs_tpu.test.helpers.state import (
    state_transition_and_sign_block,
)
from consensus_specs_tpu.utils import bls


@pytest.fixture(scope="module")
def spec():
    return build_spec_module("phase0", "minimal")


@pytest.fixture(scope="module")
def genesis_state(spec):
    return context.get_genesis_state(
        spec, context.default_balances, context.default_activation_threshold
    )


@pytest.fixture(autouse=True)
def _bls_off():
    # histories are built with the stubbed switchboard (the reference's
    # `make test` posture); service-routing tests flip it on themselves
    was = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = was


def _service(spec, genesis_state, **kw):
    state = genesis_state.copy()
    anchor_block = spec.BeaconBlock(state_root=state.hash_tree_root())
    head = HeadService(spec, state, anchor_block, **kw)
    return head, state


def _tick_to(spec, head, slot):
    store = head.store
    for s in range(int(spec.get_current_slot(store)) + 1, int(slot) + 1):
        head.on_tick(store.genesis_time + s * int(spec.config.SECONDS_PER_SLOT))


def _fork_pair(spec, base_state, tag_a=b"\x01", tag_b=b"\x02"):
    """Two competing siblings on the next slot."""
    state_a, state_b = base_state.copy(), base_state.copy()
    block_a = build_empty_block_for_next_slot(spec, state_a)
    block_a.body.graffiti = spec.Bytes32(tag_a * 32)
    signed_a = state_transition_and_sign_block(spec, state_a, block_a)
    block_b = build_empty_block_for_next_slot(spec, state_b)
    block_b.body.graffiti = spec.Bytes32(tag_b * 32)
    signed_b = state_transition_and_sign_block(spec, state_b, block_b)
    return (state_a, signed_a), (state_b, signed_b)


# -- real-history differential ------------------------------------------------


def test_real_history_differential(spec, genesis_state):
    """Three epochs of blocks-with-attestations (justified checkpoint
    moves), then a two-sibling fork flipped by a gossip vote — the inline
    differential assert runs after EVERY block and batch."""
    head, _ = _service(spec, genesis_state, differential=True)
    state = genesis_state.copy()
    for _ in range(3):
        _, signed_blocks, state = next_epoch_with_attestations(
            spec, state, True, False)
        for sb in signed_blocks:
            _tick_to(spec, head, sb.message.slot)
            head.on_block(sb)
    assert int(head.store.justified_checkpoint.epoch) > 0
    assert bytes(spec.get_head(head.store)) == bytes(head.get_head())

    (state_a, signed_a), (state_b, signed_b) = _fork_pair(spec, state)
    _tick_to(spec, head, signed_a.message.slot)
    head.on_block(signed_a)
    head.on_block(signed_b)
    root_a = spec.hash_tree_root(signed_a.message)
    root_b = spec.hash_tree_root(signed_b.message)
    tie = head.get_head()
    assert tie in (root_a, root_b)
    loser_state, loser_signed, loser_root = (
        (state_a, signed_a, root_a) if tie == root_b
        else (state_b, signed_b, root_b))
    att = get_valid_attestation(
        spec, loser_state, slot=loser_signed.message.slot, signed=False,
        beacon_block_root=loser_root)
    _tick_to(spec, head, loser_signed.message.slot + 1)
    summary = head.on_attestations([att])
    assert summary["applied"] > 0
    assert head.get_head() == loser_root
    snap = head.metrics.snapshot()
    assert snap["reorgs"] >= 1 and snap["head_changes"] >= 2


@pytest.mark.slow
def test_real_history_finalization_prunes_slow(spec, genesis_state):
    """Five epochs with current+previous-epoch attestations: the store
    FINALIZES on the validated path, the proto-array prunes, and the
    differential assert holds throughout."""
    head, _ = _service(spec, genesis_state, differential=True)
    state = genesis_state.copy()
    for epoch in range(5):
        prev = epoch > 1
        _, signed_blocks, state = next_epoch_with_attestations(
            spec, state, True, prev)
        for sb in signed_blocks:
            _tick_to(spec, head, sb.message.slot)
            head.on_block(sb)
    assert int(head.store.finalized_checkpoint.epoch) > 0
    assert head.metrics.snapshot()["pruned_nodes"] > 0
    assert bytes(spec.get_head(head.store)) == bytes(head.get_head())


# -- serve-plane routing ------------------------------------------------------


def _routed_service(spec, genesis_state):
    """A HeadService over a VerificationService whose verdicts are
    carried by the signature bytes (serve/load.py VerdictBackend)."""
    backend = VerdictBackend()
    svc = VerificationService(backend=backend, max_batch=16, max_wait_ms=2.0)
    head, state = _service(spec, genesis_state, service=svc,
                           differential=True)
    return head, state, svc, backend


def test_service_routes_verdicts(spec, genesis_state):
    """Valid signatures apply; BAD_SIGNATURE comes back False from the
    service and the attestation is dropped WITHOUT touching either fork
    choice — while the spec store and proto array stay head-identical."""
    head, state, svc, backend = _routed_service(spec, genesis_state)
    try:
        (state_a, signed_a), (state_b, signed_b) = _fork_pair(spec, state)
        _tick_to(spec, head, signed_a.message.slot)
        head.on_block(signed_a)
        head.on_block(signed_b)
        root_a = spec.hash_tree_root(signed_a.message)
        root_b = spec.hash_tree_root(signed_b.message)
        tie = head.get_head()
        loser_state, loser_signed, loser_root = (
            (state_a, signed_a, root_a) if tie == root_b
            else (state_b, signed_b, root_b))
        _tick_to(spec, head, loser_signed.message.slot + 1)

        bls.bls_active = True  # verdicts must flow through the service
        bad = get_valid_attestation(
            spec, loser_state, slot=loser_signed.message.slot, signed=False,
            beacon_block_root=loser_root)
        bad.signature = spec.BLSSignature(BAD_SIGNATURE)
        summary = head.on_attestations([bad])
        assert summary == {"applied": 0, "stale": 0, "deferred": 0,
                           "dropped": 1, "resolved": 0}
        assert head.get_head() == tie  # nothing moved
        assert not head.store.latest_messages

        good = get_valid_attestation(
            spec, loser_state, slot=loser_signed.message.slot, signed=False,
            beacon_block_root=loser_root)
        summary = head.on_attestations([good])
        assert summary["applied"] > 0 and summary["dropped"] == 0
        assert head.get_head() == loser_root
        assert backend.calls > 0  # the verdicts really came from the backend
    finally:
        svc.close(timeout=30)


def test_unknown_block_defers_then_resolves(spec, genesis_state):
    """Gossip for a block the store has not seen parks in the deferral
    buffer and applies when the block arrives — the spec's 'delay
    consideration' rule, end to end through the service."""
    head, state, svc, _ = _routed_service(spec, genesis_state)
    try:
        fork_state = state.copy()
        block = build_empty_block_for_next_slot(spec, fork_state)
        signed = state_transition_and_sign_block(spec, fork_state, block)
        root = spec.hash_tree_root(block)
        att = get_valid_attestation(spec, fork_state, slot=block.slot,
                                    signed=False, beacon_block_root=root)
        _tick_to(spec, head, block.slot + 1)

        bls.bls_active = True
        summary = head.on_attestations([att])
        assert summary["deferred"] == 1 and head.deferred_count == 1
        assert head.metrics.snapshot()["deferred_pending"] == 1

        bls.bls_active = False  # the block path verifies inline
        head.on_block(signed)  # arrival retries the deferred gossip
        snap = head.metrics.snapshot()
        assert snap["resolved"] == 1 and snap["deferred_pending"] == 0
        assert head.get_head() == root
    finally:
        svc.close(timeout=30)


def test_deferred_attestation_survives_unrelated_blocks(spec, genesis_state):
    """The order-independence regression (simnet reordering): an
    attestation heard before its block must survive MORE unrelated block
    arrivals than its whole retry budget, then still apply the moment its
    own block lands via a different peer."""
    head, state = _service(spec, genesis_state, defer_retries=2)
    # the attested fork block, withheld from the service for now
    fork_state = state.copy()
    block = build_empty_block_for_next_slot(spec, fork_state)
    block.body.graffiti = spec.Bytes32(b"\x07" * 32)
    signed = state_transition_and_sign_block(spec, fork_state, block)
    root = spec.hash_tree_root(block)
    att = get_valid_attestation(spec, fork_state, slot=block.slot,
                                signed=False, beacon_block_root=root)
    _tick_to(spec, head, block.slot + 1)
    summary = head.on_attestations([att])
    assert summary["deferred"] == 1 and head.deferred_count == 1

    # five unrelated main-chain blocks arrive — far past defer_retries=2.
    # None of them resolves the entry, so none may consume its budget
    # (and the interleaved clock ticks re-examine it uncharged)
    st = state.copy()
    for _ in range(5):
        sb = state_transition_and_sign_block(
            spec, st, build_empty_block_for_next_slot(spec, st))
        _tick_to(spec, head, sb.message.slot)
        head.on_block(sb)
    assert head.deferred_count == 1, "unrelated arrivals evicted the entry"

    # the attested block finally arrives via "a different peer"
    head.on_block(signed)
    snap = head.metrics.snapshot()
    assert snap["resolved"] == 1 and snap["deferred_pending"] == 0
    assert head.store.latest_messages  # the vote applied
    assert bytes(spec.get_head(head.store)) == bytes(head.get_head())


def test_deferred_block_vs_attestation_order_is_irrelevant(spec,
                                                           genesis_state):
    """Same gossip, two delivery orders (block-then-attestation vs
    attestation-then-block): identical head and latest messages."""
    fork_state = genesis_state.copy()
    block = build_empty_block_for_next_slot(spec, fork_state)
    signed = state_transition_and_sign_block(spec, fork_state, block)
    root = spec.hash_tree_root(block)

    def run(block_first: bool):
        head, _ = _service(spec, genesis_state)
        att = get_valid_attestation(spec, fork_state.copy(),
                                    slot=block.slot, signed=False,
                                    beacon_block_root=root)
        _tick_to(spec, head, block.slot + 1)
        if block_first:
            head.on_block(signed)
            head.on_attestations([att])
        else:
            head.on_attestations([att])
            head.on_block(signed)
        table = {
            int(i): (int(m.epoch), bytes(m.root))
            for i, m in head.store.latest_messages.items()
        }
        return bytes(head.get_head()), table

    head_a, votes_a = run(block_first=True)
    head_b, votes_b = run(block_first=False)
    assert head_a == head_b == bytes(root)
    assert votes_a == votes_b and votes_a


def test_stale_deferred_entries_evict_via_epoch_window(spec, genesis_state):
    """An entry whose block never arrives is evicted by the spec's
    stale-epoch rule as the clock advances — not leaked, not charged to
    unrelated arrivals."""
    head, state = _service(spec, genesis_state)
    never_known = spec.Root(b"\x77" * 32)
    att = get_valid_attestation(spec, state.copy(), slot=state.slot,
                                signed=False)
    att.data.beacon_block_root = never_known
    _tick_to(spec, head, state.slot + 2)
    summary = head.on_attestations([att])
    assert summary["deferred"] == 1
    # clock to epoch 3: target epoch 0 leaves the {current, previous}
    # window and the tick's (uncharged) re-route drops the entry
    _tick_to(spec, head, int(spec.SLOTS_PER_EPOCH) * 3)
    assert head.deferred_count == 0
    assert head.metrics.snapshot()["dropped"] == 1


def test_time_gated_deferrals_charge_retries(spec, genesis_state):
    """Entries gated on the CLOCK (far-future target epoch) spend one
    retry per slot tick — the budget still bounds time-gated spinning."""
    head, state = _service(spec, genesis_state, defer_retries=2)
    att = get_valid_attestation(spec, state.copy(), slot=state.slot,
                                signed=False)
    att.data.target.epoch = spec.Epoch(64)  # far future: never applies
    summary = head.on_attestations([att])
    assert summary["deferred"] == 1
    _tick_to(spec, head, state.slot + 1)  # retry 1 -> re-defer (charged)
    assert head.deferred_count == 1
    _tick_to(spec, head, state.slot + 2)  # retry 2 -> budget exhausted
    assert head.deferred_count == 0
    assert head.metrics.snapshot()["dropped"] == 1


def test_stale_epoch_attestation_drops(spec, genesis_state):
    head, state = _service(spec, genesis_state)
    att = get_valid_attestation(spec, state.copy(), slot=state.slot,
                                signed=False)
    # clock far ahead: target epoch 0 is neither current nor previous
    _tick_to(spec, head, int(spec.SLOTS_PER_EPOCH) * 3)
    summary = head.on_attestations([att])
    assert summary == {"applied": 0, "stale": 0, "deferred": 0,
                       "dropped": 1, "resolved": 0}


# -- observability ------------------------------------------------------------


def test_chain_gauges_and_exposition(spec, genesis_state):
    """The chain.* family lands in profiling.summary() and renders on a
    live /metrics endpoint; /snapshot serves the ChainMetrics snapshot."""
    from consensus_specs_tpu.obs.exposition import start_exposition
    from consensus_specs_tpu.ops import profiling

    profiling.reset()
    head, state = _service(spec, genesis_state, differential=True)
    st = state.copy()
    signed = state_transition_and_sign_block(
        spec, st, build_empty_block_for_next_slot(spec, st))
    _tick_to(spec, head, signed.message.slot)
    head.on_block(signed)

    snap = profiling.summary()
    from consensus_specs_tpu.chain.metrics import GAUGE_LABELS

    for label in GAUGE_LABELS:
        assert label in snap, f"{label} missing from profiling summary"
    assert snap["chain.blocks"]["gauge"] == 2.0  # anchor + one block

    with start_exposition(snapshot_fn=head.metrics.snapshot) as server:
        with urllib.request.urlopen(server.url("/metrics"), timeout=10) as r:
            body = r.read().decode()
        chain_lines = [ln for ln in body.splitlines()
                       if ln.startswith("consensus_specs_tpu_chain_")]
        assert len(chain_lines) >= len(GAUGE_LABELS)
        with urllib.request.urlopen(server.url("/snapshot"), timeout=10) as r:
            snapshot = json.loads(r.read().decode())
        assert snapshot["blocks"] == 1 and "apply_latency" in snapshot


def test_batch_spans_traced(spec, genesis_state):
    tracer = Tracer(capacity=64)
    head, state = _service(spec, genesis_state, tracer=tracer)
    att = get_valid_attestation(spec, state.copy(), slot=state.slot,
                                signed=False)
    _tick_to(spec, head, state.slot + 1)
    head.on_attestations([att])
    done = [t for t in tracer.completed() if t.kind == "chain_apply"]
    assert done, "no chain_apply trace finished"
    names = done[-1].span_names()
    assert set(CHAIN_STAGES) <= names


# -- bench glue ---------------------------------------------------------------


def test_head_replay_bench_smoke(spec, monkeypatch):
    """A miniature `bench.py --mode head` run end to end: heads asserted
    equal at the sample points, fault plan exercised, JSON-able result."""
    monkeypatch.setenv("HEAD_TREE_SIZES", "24")
    monkeypatch.setenv("HEAD_EPOCHS", "2")
    monkeypatch.setenv("HEAD_EVENTS_PER_EPOCH", "12")
    monkeypatch.setenv("HEAD_BATCH", "6")
    monkeypatch.setenv("HEAD_QUERY_ROUNDS", "8")
    monkeypatch.delenv("SERVE_METRICS_PORT", raising=False)
    from consensus_specs_tpu.bench.head_replay import run_head_bench

    result = run_head_bench()
    assert result["mode"] == "head"
    assert result["trees"][0]["heads_match"] is True
    assert result["trees"][0]["spec_queries"] > 0
    assert result["value"] > 0
    assert f"head[{result['blocks']}]" in result["per_mode_best"]
    json.dumps(result)  # the line bench.py prints must be serializable


def test_gossip_fault_plan_shape():
    rng = random.Random(3)
    plan = plan_gossip_faults(rng, 200, invalid_rate=0.2, orphan_rate=0.2)
    assert plan[0] == "ok"  # the stream never starts with a fault
    kinds = set(plan)
    assert kinds == {"ok", "invalid_sig", "orphan"}
    assert plan.count("invalid_sig") + plan.count("orphan") < 120


def test_verdict_backend_contract():
    backend = VerdictBackend()
    out = backend.batch_fast_aggregate_verify(
        [[b"k"], [b"k"]], [b"m", b"m"], [b"\x01" * 96, BAD_SIGNATURE])
    assert out == [True, False]
    assert backend.calls == 1 and backend.items == 2
