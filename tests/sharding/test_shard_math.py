from consensus_specs_tpu.test.sharding.unittests.test_shard_math import *  # noqa: F401,F403
