from consensus_specs_tpu.test.sharding.epoch_processing.test_shard_work_cycle import *  # noqa: F401,F403
