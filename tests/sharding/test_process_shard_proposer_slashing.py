from consensus_specs_tpu.test.sharding.block_processing.test_process_shard_proposer_slashing import *  # noqa: F401,F403
