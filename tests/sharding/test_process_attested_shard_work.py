from consensus_specs_tpu.test.sharding.block_processing.test_process_attested_shard_work import *  # noqa: F401,F403
