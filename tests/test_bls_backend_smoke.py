"""Default-suite smoke of the flagship pairing pipeline (VERDICT r3 weak
#5: the full VM/pairing suites are slow-marked, so a plain `make test`
previously never touched the repo's core component).

One tiny batch — the smallest shape bucket (K<=2, N=2), one valid and one
corrupted verification — through the REAL device pipeline
(ops/bls_backend.batch_fast_aggregate_verify: decode, VM Miller product,
host easy part, VM hard part). First compile is ~20-40 s cold but persists
in the XLA compilation cache; warm runs take seconds. The exhaustive
K=1..2048 cross-checks remain in the slow-marked suites
(tests/test_bls_backend_tpu.py)."""
from consensus_specs_tpu.utils.jax_env import force_cpu

force_cpu()

from consensus_specs_tpu.ops import bls_backend  # noqa: E402
from consensus_specs_tpu.utils import bls  # noqa: E402


def test_pairing_pipeline_smoke():
    sks = [5, 6]
    pks = [bls.SkToPk(sk) for sk in sks]
    msg = b"smoke" * 6 + b"xy"
    sig = bls.Aggregate([bls.Sign(sk, msg) for sk in sks])

    got = bls_backend.batch_fast_aggregate_verify(
        [pks, pks], [msg, b"\xee" * 32], [sig, sig]
    )
    assert bool(got[0]), "valid aggregate rejected by the device pipeline"
    assert not bool(got[1]), "wrong-message aggregate accepted"
    # the oracle agrees on both verdicts
    assert bls.FastAggregateVerify(pks, msg, sig)
    assert not bls.FastAggregateVerify(pks, b"\xee" * 32, sig)
