"""The Merkleization plane (ISSUE 18): mode knob, level-batched hashing,
the incremental layer cache, the cross-element cold-build plane, and the
differential-oracle assert.

Crypto-free by design: no pairings, no spec build, no XLA compiles —
everything here is SSZ views + sha256, so the whole module stays inside
the tier-1 time budget even on a cold runner.
"""
import hashlib

import pytest

from consensus_specs_tpu.merkle import cache as mcache
from consensus_specs_tpu.merkle import levels as mlevels
from consensus_specs_tpu.merkle.cache import LevelTree
from consensus_specs_tpu.utils.ssz.ssz_typing import (
    Bitlist, Bitvector, Bytes32, Bytes48, Container, List as SSZList,
    Vector, boolean, uint8, uint64, uint256, merkleize_chunks,
)

sha = lambda b: hashlib.sha256(b).digest()  # noqa: E731


def _chunks(n, tag=0):
    return [sha(bytes([tag, i % 256, i // 256])) for i in range(n)]


# -- mode knob ---------------------------------------------------------------


def test_mode_knob_env_and_forced(monkeypatch):
    monkeypatch.delenv(mlevels.MODE_ENV, raising=False)
    mlevels.configure(None)
    assert mlevels.requested_mode() == "auto"
    monkeypatch.setenv(mlevels.MODE_ENV, "python")
    assert mlevels.requested_mode() == "python"
    monkeypatch.setenv(mlevels.MODE_ENV, "bogus")
    assert mlevels.requested_mode() == "auto"  # unknown value -> default
    with mlevels.forced_mode("native"):
        assert mlevels.requested_mode() == "native"
        with mlevels.forced_mode("python"):  # innermost wins
            assert mlevels.requested_mode() == "python"
            assert not mlevels.plane_enabled()
            assert not mlevels.use_native()
        assert mlevels.requested_mode() == "native"
    monkeypatch.delenv(mlevels.MODE_ENV, raising=False)


def test_mode_knob_configure_and_invalid():
    mlevels.configure("python")
    try:
        assert mlevels.requested_mode() == "python"
        assert mlevels.mode() == "python"
    finally:
        mlevels.configure(None)
    with pytest.raises(ValueError):
        mlevels.configure("turbo")
    with pytest.raises(ValueError):
        with mlevels.forced_mode("turbo"):
            pass


def test_resolved_mode_auto_matches_availability():
    with mlevels.forced_mode("auto"):
        expected = "native" if mlevels._native() is not None else "python"
        assert mlevels.mode() == expected


# -- level hashing: native == hashlib oracle ---------------------------------


def test_hash_level_matches_hashlib_both_modes():
    for n in (1, 2, 7, 8, 15, 16, 33):
        level = _chunks(n)
        ref_level = level + ([mlevels.ZERO_HASHES[3]] if n % 2 else [])
        ref = [sha(ref_level[2 * i] + ref_level[2 * i + 1])
               for i in range(len(ref_level) // 2)]
        for m in ("python", "native"):
            with mlevels.forced_mode(m):
                assert mlevels.hash_level(level, 3) == ref, (m, n)


def test_hash_pair_blob_matches_hashlib_both_modes():
    for n_pairs in (1, 8, 21):
        blob = b"".join(_chunks(2 * n_pairs))
        ref = b"".join(sha(blob[i << 6:(i + 1) << 6])
                       for i in range(n_pairs))
        for m in ("python", "native"):
            with mlevels.forced_mode(m):
                assert mlevels.hash_pair_blob(blob) == ref, (m, n_pairs)


def test_native_levels_counter_moves_when_native_runs():
    if mlevels._native() is None:
        pytest.skip("native sha256 library not built")
    before = mlevels.counters["native_levels"]
    with mlevels.forced_mode("native"):
        mlevels.hash_level(_chunks(32), 0)
    assert mlevels.counters["native_levels"] == before + 1
    # python mode must never touch the native counter
    before = mlevels.counters["native_levels"]
    with mlevels.forced_mode("python"):
        mlevels.hash_level(_chunks(32), 0)
    assert mlevels.counters["native_levels"] == before


# -- the incremental layer cache ---------------------------------------------


def test_leveltree_root_matches_merkleize_chunks():
    for n in (0, 1, 2, 3, 8, 33):
        for limit in (64, 2**20):
            depth = (limit - 1).bit_length() if limit > 1 else 0
            tree = LevelTree(depth, _chunks(n))
            assert tree.root() == merkleize_chunks(_chunks(n), limit=limit), \
                (n, limit)


def test_leveltree_batched_update_matches_rebuild():
    depth = 12
    chunks = _chunks(40)
    tree = LevelTree(depth, chunks)
    updates = {i: sha(b"new%d" % i) for i in (0, 1, 13, 38, 39)}
    appends = [sha(b"app%d" % i) for i in range(5)]
    tree.update(updates, appends)
    for i, c in updates.items():
        chunks[i] = c
    chunks.extend(appends)
    assert tree.root() == LevelTree(depth, chunks).root()
    assert tree.root() == merkleize_chunks(chunks, limit=2**depth)


def test_leveltree_growth_past_power_of_two_boundary():
    depth = 10
    tree = LevelTree(depth, _chunks(3))
    chunks = _chunks(3)
    # grow 3 -> 4 -> 5 -> 9: crosses two power-of-two boundaries, the
    # top-layer rebuild path must keep pace with the oracle
    for i in range(6):
        c = sha(b"grow%d" % i)
        tree.append(c)
        chunks.append(c)
        assert tree.root() == merkleize_chunks(chunks, limit=2**depth), i


def test_leveltree_empty_and_single_ops():
    tree = LevelTree(8, [])
    assert tree.root() == mlevels.ZERO_HASHES[8]
    tree.append(sha(b"a"))
    assert tree.root() == merkleize_chunks([sha(b"a")], limit=2**8)
    tree.set_chunk(0, sha(b"b"))
    assert tree.root() == merkleize_chunks([sha(b"b")], limit=2**8)


def test_leveltree_dirty_nodes_counter_moves():
    tree = LevelTree(16, _chunks(64))
    before = mlevels.counters["dirty_nodes"]
    tree.set_chunk(17, sha(b"x"))
    moved = mlevels.counters["dirty_nodes"] - before
    # one dirty path: one parent per present level, far fewer than a
    # full 64-chunk rebuild
    assert 1 <= moved <= 7


def test_leveltree_is_the_ssz_chunk_tree():
    from consensus_specs_tpu.utils.ssz import ssz_typing

    assert ssz_typing._ChunkTree is mcache.LevelTree


# -- the cross-element cold-build plane --------------------------------------


class _Check(Container):
    epoch: uint64
    root: Bytes32


class _Val(Container):
    pubkey: Bytes48
    balance: uint64
    slashed: boolean
    flags: Bitvector[9]
    words: Vector[uint64, 3]
    checkpoint: _Check


def _val(i):
    return _Val(
        pubkey=Bytes48(bytes([i % 256]) * 48),
        balance=uint64(32 * 10**9 + i),
        slashed=boolean(i % 2),
        flags=Bitvector[9](*[bool((i >> b) & 1) for b in range(9)]),
        words=Vector[uint64, 3](uint64(i), uint64(i + 1), uint64(i + 2)),
        checkpoint=_Check(epoch=uint64(i), root=Bytes32(sha(b"%d" % i))),
    )


def _plane():
    from consensus_specs_tpu.merkle import plane

    return plane


def test_plane_roots_match_per_element_walk():
    if not mlevels.plane_enabled():
        pytest.skip("native sha256 library not built")
    plane = _plane()
    elems = [_val(i) for i in range(20)]
    got = plane.batched_element_roots(elems)
    assert got is not None
    assert got == [bytes(e.hash_tree_root()) for e in elems]


def test_plane_unsupported_and_small_series_fall_back():
    plane = _plane()
    if not mlevels.plane_enabled():
        pytest.skip("native sha256 library not built")
    # below the batching threshold: not worth the column build
    assert plane.batched_element_roots(
        [_val(i) for i in range(plane.MIN_PLANE_ELEMS - 1)]) is None
    # dynamically-shaped elements (length mix-in inside): must decline
    # and count the fallback
    inner = SSZList[uint64, 64]
    before = mlevels.counters["fallbacks"]
    assert plane.batched_element_roots(
        [inner(uint64(1)) for _ in range(20)]) is None
    assert mlevels.counters["fallbacks"] == before + 1
    # python mode: the oracle path may never consult the plane
    with mlevels.forced_mode("python"):
        assert plane.batched_element_roots(
            [_val(i) for i in range(20)]) is None


def test_packed_basic_raw_widths():
    plane = _plane()
    vals = [uint64(i * 7) for i in range(10)]
    assert plane.packed_basic_raw(uint64, vals) == b"".join(
        v.encode_bytes() for v in vals)
    assert plane.packed_basic_raw(uint8, [uint8(3), uint8(250)]) == \
        bytes([3, 250])
    # non-machine-word width: decline, caller keeps its join
    assert plane.packed_basic_raw(uint256, [uint256(5)]) is None


def test_series_roots_identical_native_vs_python():
    views = [
        SSZList[_Val, 2**30](*[_val(i) for i in range(33)]),
        SSZList[uint64, 2**18](*[uint64(i * 3) for i in range(100)]),
        Bitlist[2**10](*[bool(i % 3 == 0) for i in range(77)]),
        Vector[Bytes32, 7](*[Bytes32(sha(b"%d" % i)) for i in range(7)]),
    ]
    for view in views:
        typ = type(view)
        enc = view.encode_bytes()
        with mlevels.forced_mode("native"):
            nat = bytes(typ.decode_bytes(enc).hash_tree_root())
        with mlevels.forced_mode("python"):
            ora = bytes(typ.decode_bytes(enc).hash_tree_root())
        assert nat == ora, typ


def test_incremental_reroot_matches_cold_rebuild():
    regs = SSZList[_Val, 2**30](*[_val(i) for i in range(40)])
    with mlevels.forced_mode("native"):
        regs.hash_tree_root()
        regs[7] = _val(1000)
        regs[13].balance = uint64(1)  # deep aliased mutation
        regs.append(_val(2000))
        warm = bytes(regs.hash_tree_root())
    with mlevels.forced_mode("python"):
        cold = bytes(type(regs).decode_bytes(regs.encode_bytes())
                     .hash_tree_root())
    assert warm == cold


def test_cache_hits_counter_moves_on_warm_reroot():
    regs = SSZList[uint64, 2**18](*[uint64(i) for i in range(64)])
    regs.hash_tree_root()
    before = mlevels.counters["cache_hits"]
    regs[5] = uint64(999)
    regs.hash_tree_root()
    assert mlevels.counters["cache_hits"] > before


# -- the differential oracle -------------------------------------------------


def test_diff_check_passes_and_raises():
    plane = _plane()
    view = SSZList[uint64, 2**18](*[uint64(i) for i in range(50)])
    root = bytes(view.hash_tree_root())
    plane.diff_check(view, root)  # bit-identical: no raise
    with pytest.raises(AssertionError, match="MERKLE DIVERGED"):
        plane.diff_check(view, b"\xff" * 32)


def test_diff_env_gates_facade_assert(monkeypatch):
    from consensus_specs_tpu.utils.ssz import ssz_impl

    monkeypatch.setenv(mlevels.DIFF_ENV, "1")
    assert mlevels.diff_enabled()
    view = SSZList[uint64, 2**18](*[uint64(i) for i in range(50)])
    # the facade re-derives through the python oracle and asserts —
    # passing silently IS the test
    ssz_impl.hash_tree_root(view)
    monkeypatch.delenv(mlevels.DIFF_ENV)
    assert not mlevels.diff_enabled()


# -- obs surface -------------------------------------------------------------


def test_export_gauges_publishes_merkle_family():
    from consensus_specs_tpu.ops import profiling

    mlevels.counters["native_levels"] += 0  # family exists regardless
    mlevels.export_gauges()
    summ = profiling.summary()
    for key in ("merkle.native_levels", "merkle.cache_hits",
                "merkle.dirty_nodes", "merkle.fallbacks"):
        assert key in summ and "gauge" in summ[key], key


def test_note_root_seconds_fills_latency_stage():
    from consensus_specs_tpu.obs import latency

    mlevels.note_root_seconds(0.0017)
    snap = latency.snapshot()
    label = latency.stage_label("merkle_root")
    assert label in snap and snap[label]["n"] >= 1
    assert "merkle_root" in latency.STAGES
