"""Committed VECTORS_REPORT.md staleness gate + report determinism.

Mirrors tests/test_render_spec.py's committed-document contract
(ADVICE round 5): the in-repo sweep evidence must equal what
tools/check_vectors.py would write for the actual vector tree, so the
committed report can never silently diverge from the tree `make sweep`
produced. The gate needs an emitted tree, so it skips where none exists
(the report is meaningless without its subject); the format/determinism
tests run everywhere on a synthetic tree.
"""
import importlib.util
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CHECKER = os.path.join(_REPO, "tools", "check_vectors.py")


def _load_checker():
    spec = importlib.util.spec_from_file_location("check_vectors_under_test",
                                                  _CHECKER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _vector_tree():
    """First existing candidate tree: env VECTORS_DIR, the Makefile
    default, or the in-repo sweep target."""
    candidates = []
    if os.environ.get("VECTORS_DIR"):
        candidates.append(os.environ["VECTORS_DIR"])
    candidates.append(
        os.path.join(_REPO, "..", "consensus-spec-tests", "tests")
    )
    candidates.append(os.path.join(_REPO, ".vectors"))
    for c in candidates:
        if os.path.isdir(c):
            return c
    return None


def test_committed_report_matches_tree():
    """The staleness gate: re-render the report from the tree and require
    it byte-identical to the committed VECTORS_REPORT.md (run `make
    sweep` after regenerating vectors)."""
    root = _vector_tree()
    if root is None:
        pytest.skip("no emitted vector tree on this machine (make sweep)")
    cv = _load_checker()
    counts, incomplete, empty_cases, snappy_parts = cv.scan_tree(root)
    # identical verdict derivation to the CLI, decode spot-check included
    # (sample_decode_failures is deterministic for a given tree)
    ok = (not incomplete and not empty_cases and sum(counts.values()) > 0
          and not cv.sample_decode_failures(snappy_parts))
    fresh = cv.render_report(counts, incomplete, empty_cases,
                             snappy_parts, ok)
    committed_path = os.path.join(_REPO, "VECTORS_REPORT.md")
    assert os.path.exists(committed_path), "missing VECTORS_REPORT.md"
    with open(committed_path) as f:
        assert f.read() == fresh, (
            "VECTORS_REPORT.md is stale — run `make sweep` after changing "
            "the generators or the vector tree"
        )


def _fake_tree(tmp_path, n_cases=3):
    for i in range(n_cases):
        case = (tmp_path / "minimal" / "phase0" / "sanity" / "sanity"
                / "pyspec_tests" / f"case_{i}")
        case.mkdir(parents=True)
        (case / "meta.yaml").write_text("description: x\n")
    return tmp_path


def test_report_is_deterministic_and_timestamp_free(tmp_path):
    """Two renders of the same tree must be byte-identical — the report
    may not embed timestamps, machine paths, or any other run-local state
    (that is what makes the staleness gate above possible at all)."""
    cv = _load_checker()
    root = str(_fake_tree(tmp_path))
    a = cv.render_report(*cv.scan_tree(root), ok=True)
    b = cv.render_report(*cv.scan_tree(root), ok=True)
    assert a == b
    assert "| minimal | phase0 | sanity | 3 |" in a
    assert "- total cases: **3**" in a
    assert "- verdict: **PASS**" in a
    assert str(tmp_path) not in a  # no machine-local paths
    import re

    assert not re.search(r"\b20\d\d-\d\d-\d\d\b", a)  # no date stamp


def test_scan_tree_flags_incomplete_and_empty(tmp_path):
    cv = _load_checker()
    root = _fake_tree(tmp_path)
    empty = (root / "minimal" / "phase0" / "sanity" / "sanity"
             / "pyspec_tests" / "empty_case")
    empty.mkdir(parents=True)
    bad = (root / "minimal" / "phase0" / "sanity" / "sanity"
           / "pyspec_tests" / "case_0" / "INCOMPLETE")
    bad.write_text("")
    counts, incomplete, empty_cases, _ = cv.scan_tree(str(root))
    assert counts[("minimal", "phase0", "sanity")] == 4
    assert len(incomplete) == 1 and len(empty_cases) == 1
    report = cv.render_report(counts, incomplete, empty_cases, [], False)
    assert "- verdict: **FAIL**" in report
