from consensus_specs_tpu.test.merge.genesis.test_initialization import *  # noqa: F401,F403
