from consensus_specs_tpu.test.merge.block_processing.test_process_execution_payload import *  # noqa: F401,F403
