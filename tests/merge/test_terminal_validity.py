from consensus_specs_tpu.test.merge.unittests.test_terminal_validity import *  # noqa: F401,F403
