from consensus_specs_tpu.test.merge.unittests.test_transition_predicates import *  # noqa: F401,F403
