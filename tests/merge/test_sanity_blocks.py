from consensus_specs_tpu.test.merge.sanity.test_blocks import *  # noqa: F401,F403
