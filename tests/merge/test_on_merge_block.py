from consensus_specs_tpu.test.merge.fork_choice.test_on_merge_block import *  # noqa: F401,F403
