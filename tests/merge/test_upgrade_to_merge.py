from consensus_specs_tpu.test.merge.fork.test_upgrade_to_merge import *  # noqa: F401,F403
