"""Generator system (L6) tests: snappy codec, runner lifecycle, vector
round-trip (consensus_specs_tpu/gen/; reference gen_runner.py:41-235)."""
import random

import pytest

from consensus_specs_tpu.utils.snappy import compress, decompress


def test_snappy_roundtrip():
    rng = random.Random(7)
    for n in (0, 1, 59, 60, 61, 255, 4096, 70000):
        data = bytes(rng.randrange(256) for _ in range(n))
        assert decompress(compress(data)) == data


def test_snappy_decodes_copies():
    # hand-built stream with a 1-byte-offset copy: "abcabcabcabc"
    # literal "abc" (tag 0b000010_00 -> len 3), then copy len 9 offset 3
    stream = bytes([12]) + bytes([(3 - 1) << 2]) + b"abc" + bytes([((9 - 4) << 2) | 1, 3])
    assert decompress(stream) == b"abcabcabcabc"


def test_gen_runner_lifecycle(tmp_path):
    from consensus_specs_tpu.gen.gen_runner import detect_incomplete, run_generator
    from consensus_specs_tpu.gen.gen_typing import TestCase, TestProvider

    calls = []

    def make_case(name, fn):
        return TestCase(
            fork_name="phase0", preset_name="minimal", runner_name="demo",
            handler_name="h", suite_name="s", case_name=name, case_fn=fn,
        )

    def good():
        calls.append("good")
        return [("value", "data", {"x": 1}), ("blob", "ssz", b"\x01\x02"),
                ("note", "meta", "hi")]

    def bad():
        raise RuntimeError("boom")

    provider = TestProvider(
        prepare=lambda: None,
        make_cases=lambda: [make_case("ok", good), make_case("crash", bad)],
    )
    rc = run_generator("demo", [provider], args=["-o", str(tmp_path)])
    assert rc == 1  # failure reported
    ok_dir = tmp_path / "minimal/phase0/demo/h/s/ok"
    assert (ok_dir / "value.yaml").exists()
    assert decompress((ok_dir / "blob.ssz_snappy").read_bytes()) == b"\x01\x02"
    assert "note" in (ok_dir / "meta.yaml").read_text()
    assert not (ok_dir / "INCOMPLETE").exists()
    # the crashed case keeps its sentinel for regeneration
    crash_dir = tmp_path / "minimal/phase0/demo/h/s/crash"
    assert (crash_dir / "INCOMPLETE").exists()
    assert detect_incomplete(tmp_path) == [str(crash_dir)]
    assert (tmp_path / "testgen_error_log.txt").read_text().count("boom") == 1

    # incremental: second run skips the complete case, retries the crashed one
    calls.clear()
    run_generator("demo", [provider], args=["-o", str(tmp_path)])
    assert calls == []  # good case not re-run


@pytest.mark.slow
def test_operations_vector_roundtrip(tmp_path):
    """Generate one handler's vectors and REPLAY one like a client would."""
    from consensus_specs_tpu.gen.gen_from_tests import run_state_test_generators

    mods = {"phase0": {
        "attestation":
            "consensus_specs_tpu.test.phase0.block_processing.test_process_attestation",
    }}
    rc = run_state_test_generators(
        "operations", mods, args=["-o", str(tmp_path), "-l", "minimal"]
    )
    assert rc == 0
    case = tmp_path / "minimal/phase0/operations/attestation/pyspec_tests/success"
    from consensus_specs_tpu.builder import build_spec_module

    spec = build_spec_module("phase0", "minimal")
    state = spec.BeaconState.decode_bytes(
        decompress((case / "pre.ssz_snappy").read_bytes())
    )
    att = spec.Attestation.decode_bytes(
        decompress((case / "attestation.ssz_snappy").read_bytes())
    )
    post = spec.BeaconState.decode_bytes(
        decompress((case / "post.ssz_snappy").read_bytes())
    )
    spec.process_attestation(state, att)
    assert state.hash_tree_root() == post.hash_tree_root()
    # invalid case: no post part on disk
    invalid = tmp_path / "minimal/phase0/operations/attestation/pyspec_tests/future_target_epoch"
    assert invalid.exists() and not (invalid / "post.ssz_snappy").exists()


def test_ssz_generic_cases_all_executable():
    """Every ssz_generic case runs: valid cases emit parts, invalid cases
    prove the decoder rejects their bytes (generation doubles as a decoder
    strictness test)."""
    from consensus_specs_tpu.gen.generators.ssz_generic import make_cases

    n_valid = n_invalid = 0
    for case in make_cases():
        parts = case.case_fn()
        assert parts
        if case.suite_name == "valid":
            n_valid += 1
            assert any(name == "value" for name, _, _ in parts)
        else:
            n_invalid += 1
            assert len(parts) == 1  # just the malformed bytes
    assert n_valid >= 15 and n_invalid >= 15
