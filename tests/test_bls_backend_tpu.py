"""Cross-check the JAX/TPU BLS backend against the pure-Python oracle —
the reference's py_ecc-vs-milagro cross-check pattern
(reference: tests/generators/bls/main.py:80, 108-114) applied to the new
backend."""
import pytest

from consensus_specs_tpu.utils import bls

# whole-pairing device programs: long XLA compiles on the CPU backend
pytestmark = pytest.mark.slow


PRIVKEYS = [i + 1 for i in range(8)]
PUBKEYS = [bls.SkToPk(sk) for sk in PRIVKEYS]
MESSAGES = [bytes([i]) * 32 for i in range(4)]


@pytest.fixture(autouse=True)
def _restore_backend():
    yield
    bls.use_py_ecc()


def test_verify_matches_oracle():
    from consensus_specs_tpu.ops import bls_backend

    msg = MESSAGES[0]
    sig = bls.Sign(PRIVKEYS[0], msg)
    assert bls_backend.verify(PUBKEYS[0], msg, sig) is True
    # wrong message
    assert bls_backend.verify(PUBKEYS[0], MESSAGES[1], sig) is False
    # wrong key
    assert bls_backend.verify(PUBKEYS[1], msg, sig) is False
    # garbage signature encoding
    assert bls_backend.verify(PUBKEYS[0], msg, b"\xff" * 96) is False
    # infinity signature
    assert bls_backend.verify(PUBKEYS[0], msg, bls.G2_POINT_AT_INFINITY) is False


def test_fast_aggregate_verify_matches_oracle():
    from consensus_specs_tpu.ops import bls_backend

    msg = MESSAGES[2]
    sigs = [bls.Sign(sk, msg) for sk in PRIVKEYS[:5]]
    agg = bls.Aggregate(sigs)
    pks = PUBKEYS[:5]
    assert bls.FastAggregateVerify(pks, msg, agg) is True
    assert bls_backend.fast_aggregate_verify(pks, msg, agg) is True
    # missing participant
    assert bls_backend.fast_aggregate_verify(pks[:4], msg, agg) is False
    # empty
    assert bls_backend.fast_aggregate_verify([], msg, agg) is False
    # infinity pubkey in the set
    inf_pk = b"\xc0" + b"\x00" * 47
    assert bls_backend.fast_aggregate_verify(pks + [inf_pk], msg, agg) is False


def test_batch_fast_aggregate_verify_mixed_validity():
    from consensus_specs_tpu.ops import bls_backend

    msg_a, msg_b = MESSAGES[0], MESSAGES[1]
    sig_a = bls.Aggregate([bls.Sign(sk, msg_a) for sk in PRIVKEYS[:3]])
    sig_b = bls.Aggregate([bls.Sign(sk, msg_b) for sk in PRIVKEYS[3:6]])
    batch_pks = [PUBKEYS[:3], PUBKEYS[3:6], PUBKEYS[:2], PUBKEYS[:3]]
    batch_msgs = [msg_a, msg_b, msg_a, msg_b]
    batch_sigs = [sig_a, sig_b, sig_a, sig_a]  # [valid, valid, wrong-set, wrong-msg]
    got = bls_backend.batch_fast_aggregate_verify(batch_pks, batch_msgs, batch_sigs)
    assert list(got) == [True, True, False, False]
    # every lane must agree with the oracle
    for pks, m, s, g in zip(batch_pks, batch_msgs, batch_sigs, got):
        assert bls.FastAggregateVerify(pks, m, s) == bool(g)


def test_aggregate_verify_matches_oracle():
    from consensus_specs_tpu.ops import bls_backend

    pairs = list(zip(PRIVKEYS[:3], MESSAGES[:3]))
    sigs = [bls.Sign(sk, m) for sk, m in pairs]
    agg = bls.Aggregate(sigs)
    pks = PUBKEYS[:3]
    msgs = MESSAGES[:3]
    assert bls.AggregateVerify(pks, msgs, agg) is True
    assert bls_backend.aggregate_verify(pks, msgs, agg) is True
    # swapped messages
    assert bls_backend.aggregate_verify(pks, [msgs[1], msgs[0], msgs[2]], agg) is False
    # mismatched lengths
    assert bls_backend.aggregate_verify(pks, msgs[:2], agg) is False


def test_switchboard_tpu_backend_routing():
    msg = MESSAGES[3]
    sig = bls.Sign(PRIVKEYS[7], msg)
    bls.use_tpu()
    assert bls.backend_name() == "tpu"
    assert bls.Verify(PUBKEYS[7], msg, sig) is True
    assert bls.Verify(PUBKEYS[6], msg, sig) is False
    agg = bls.Aggregate([bls.Sign(sk, msg) for sk in PRIVKEYS[:2]])
    assert bls.FastAggregateVerify(PUBKEYS[:2], msg, agg) is True
