"""Cross-check the JAX/TPU BLS backend against the pure-Python oracle —
the reference's py_ecc-vs-milagro cross-check pattern
(reference: tests/generators/bls/main.py:80, 108-114) applied to the new
backend."""
import pytest

from consensus_specs_tpu.utils import bls

# whole-pairing device programs: long XLA compiles on the CPU backend
pytestmark = pytest.mark.slow


PRIVKEYS = [i + 1 for i in range(8)]
PUBKEYS = [bls.SkToPk(sk) for sk in PRIVKEYS]
MESSAGES = [bytes([i]) * 32 for i in range(4)]


@pytest.fixture(autouse=True)
def _restore_backend():
    yield
    bls.use_py_ecc()


def test_verify_matches_oracle():
    from consensus_specs_tpu.ops import bls_backend

    msg = MESSAGES[0]
    sig = bls.Sign(PRIVKEYS[0], msg)
    assert bls_backend.verify(PUBKEYS[0], msg, sig) is True
    # wrong message
    assert bls_backend.verify(PUBKEYS[0], MESSAGES[1], sig) is False
    # wrong key
    assert bls_backend.verify(PUBKEYS[1], msg, sig) is False
    # garbage signature encoding
    assert bls_backend.verify(PUBKEYS[0], msg, b"\xff" * 96) is False
    # infinity signature
    assert bls_backend.verify(PUBKEYS[0], msg, bls.G2_POINT_AT_INFINITY) is False


def test_fast_aggregate_verify_matches_oracle():
    from consensus_specs_tpu.ops import bls_backend

    msg = MESSAGES[2]
    sigs = [bls.Sign(sk, msg) for sk in PRIVKEYS[:5]]
    agg = bls.Aggregate(sigs)
    pks = PUBKEYS[:5]
    assert bls.FastAggregateVerify(pks, msg, agg) is True
    assert bls_backend.fast_aggregate_verify(pks, msg, agg) is True
    # missing participant
    assert bls_backend.fast_aggregate_verify(pks[:4], msg, agg) is False
    # empty
    assert bls_backend.fast_aggregate_verify([], msg, agg) is False
    # infinity pubkey in the set
    inf_pk = b"\xc0" + b"\x00" * 47
    assert bls_backend.fast_aggregate_verify(pks + [inf_pk], msg, agg) is False


def test_batch_fast_aggregate_verify_mixed_validity():
    from consensus_specs_tpu.ops import bls_backend

    msg_a, msg_b = MESSAGES[0], MESSAGES[1]
    sig_a = bls.Aggregate([bls.Sign(sk, msg_a) for sk in PRIVKEYS[:3]])
    sig_b = bls.Aggregate([bls.Sign(sk, msg_b) for sk in PRIVKEYS[3:6]])
    batch_pks = [PUBKEYS[:3], PUBKEYS[3:6], PUBKEYS[:2], PUBKEYS[:3]]
    batch_msgs = [msg_a, msg_b, msg_a, msg_b]
    batch_sigs = [sig_a, sig_b, sig_a, sig_a]  # [valid, valid, wrong-set, wrong-msg]
    got = bls_backend.batch_fast_aggregate_verify(batch_pks, batch_msgs, batch_sigs)
    assert list(got) == [True, True, False, False]
    # every lane must agree with the oracle
    for pks, m, s, g in zip(batch_pks, batch_msgs, batch_sigs, got):
        assert bls.FastAggregateVerify(pks, m, s) == bool(g)


def test_aggregate_verify_matches_oracle():
    from consensus_specs_tpu.ops import bls_backend

    pairs = list(zip(PRIVKEYS[:3], MESSAGES[:3]))
    sigs = [bls.Sign(sk, m) for sk, m in pairs]
    agg = bls.Aggregate(sigs)
    pks = PUBKEYS[:3]
    msgs = MESSAGES[:3]
    assert bls.AggregateVerify(pks, msgs, agg) is True
    assert bls_backend.aggregate_verify(pks, msgs, agg) is True
    # swapped messages
    assert bls_backend.aggregate_verify(pks, [msgs[1], msgs[0], msgs[2]], agg) is False
    # mismatched lengths
    assert bls_backend.aggregate_verify(pks, msgs[:2], agg) is False


def test_switchboard_tpu_backend_routing():
    msg = MESSAGES[3]
    sig = bls.Sign(PRIVKEYS[7], msg)
    bls.use_tpu()
    assert bls.backend_name() == "tpu"
    assert bls.Verify(PUBKEYS[7], msg, sig) is True
    assert bls.Verify(PUBKEYS[6], msg, sig) is False
    agg = bls.Aggregate([bls.Sign(sk, msg) for sk in PRIVKEYS[:2]])
    assert bls.FastAggregateVerify(PUBKEYS[:2], msg, agg) is True


def test_bucket_boundary_64_65():
    """K=64 fills the 64-bucket exactly; K=65 rolls into the 128 bucket —
    both must agree with the oracle (ops/bls_backend.py _K_BUCKETS)."""
    from consensus_specs_tpu.ops import bls_backend
    from consensus_specs_tpu.utils.bls12_381 import R

    assert bls_backend._k_bucket(64) == 64
    assert bls_backend._k_bucket(65) == 128

    for k in (64, 65):
        sks = list(range(1, k + 1))
        pks = [bls.SkToPk(sk) for sk in sks]
        msg = bytes([k]) * 32
        sig = bls.Sign(sum(sks) % R, msg)  # aggregate via summed secret key
        assert bool(
            bls_backend.batch_fast_aggregate_verify([pks], [msg], [sig])[0]
        ) is True
        # drop one signer: must fail in the same bucket shape
        assert bool(
            bls_backend.batch_fast_aggregate_verify([pks[:-1]], [msg], [sig])[0]
        ) is False


def test_random_invalid_encodings_match_oracle():
    """Random/malformed pubkey+signature byte strings: backend and oracle
    must agree on every rejection (the reference's py_ecc-vs-milagro pattern,
    reference generators/bls/main.py:80, 108-114)."""
    import random

    from consensus_specs_tpu.ops import bls_backend

    rng = random.Random(99)
    msg = b"\x77" * 32
    good_sig = bls.Sign(PRIVKEYS[0], msg)

    bad_pubkeys = [
        bytes(rng.randrange(256) for _ in range(48)),  # random bytes
        b"\x00" * 48,                                   # no compression flag
        b"\xc0" + b"\x00" * 47,                         # infinity
        bytes([0x80]) + b"\xff" * 47,                   # x >= p territory
        PUBKEYS[0][:-1] + bytes([PUBKEYS[0][-1] ^ 1]),  # bit flip (off-curve)
    ]
    for pk in bad_pubkeys:
        got = bls_backend.verify(pk, msg, good_sig)
        want = bls.Verify(pk, msg, good_sig)
        assert got == want == False  # noqa: E712

    bad_sigs = [
        bytes(rng.randrange(256) for _ in range(96)),
        b"\x00" * 96,
        b"\xc0" + b"\x00" * 95,  # infinity signature
        good_sig[:-1] + bytes([good_sig[-1] ^ 1]),
    ]
    for sig in bad_sigs:
        got = bls_backend.verify(PUBKEYS[0], msg, sig)
        want = bls.Verify(PUBKEYS[0], msg, sig)
        assert got == want == False  # noqa: E712


@pytest.mark.skipif(
    "CONSENSUS_SPECS_TPU_WIDE_K" not in __import__("os").environ,
    reason="wide-committee compiles take minutes on CPU; set "
    "CONSENSUS_SPECS_TPU_WIDE_K=1 (TPU runs should)",
)
@pytest.mark.parametrize("k", [512, 2048])
def test_wide_committee_matches_oracle(k):
    """Sync-committee width (512) and mainnet max committee (2048)
    (BASELINE.md workload constants)."""
    from consensus_specs_tpu.ops import bls_backend
    from consensus_specs_tpu.utils.bls12_381 import R

    sks = list(range(1, k + 1))
    pks = [bls.SkToPk(sk) for sk in sks]
    msg = bytes([k % 251]) * 32
    sig = bls.Sign(sum(sks) % R, msg)
    got = bls_backend.batch_fast_aggregate_verify([pks], [msg], [sig])
    assert bool(got[0]) is True
    got_bad = bls_backend.batch_fast_aggregate_verify([pks[1:]], [msg], [sig])
    assert bool(got_bad[0]) is False
