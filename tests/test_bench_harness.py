"""Unit tests for bench.py's parent/child harness logic.

The accelerator child emits committee-stage lines, epoch-stage lines, a
pallas_ab probe line, and error lines, all interleaved; `_best_line` is
the parent's only view of a killed window, so its selection rules are
what decide whether a granted window becomes a recorded number
(TPU_NOTES.md; round-4 verdict item 1). These tests pin those rules
without needing any device.
"""
import importlib.util
import json
import os
import sys

import pytest

_BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _lines(*objs):
    return ("\n".join(json.dumps(o) for o in objs)).encode()


def test_best_line_picks_max_value(bench):
    best, err = bench._best_line(_lines(
        {"value": 100.0, "mode": "committee", "stage": "rep 1/3"},
        {"value": 300.0, "mode": "committee"},
        {"value": 250.0, "mode": "epoch", "stage": "warmup (compile-inclusive)"},
    ))
    assert err is None
    assert best["value"] == 300.0
    # both modes landed: each mode's best is attached for the record
    assert best["per_mode_best"] == {"committee": 300.0, "epoch": 250.0}


def test_best_line_single_mode_has_no_per_mode_key(bench):
    best, _ = bench._best_line(_lines({"value": 42.0, "mode": "committee"}))
    assert best["value"] == 42.0
    assert "per_mode_best" not in best


def test_best_line_warmup_shape_never_shadows_comparable(bench):
    """ADVICE round 5: the stage-0 4x8 liveness shape posts absurd
    per-sig rates; it must not become the headline OR occupy the
    committee slot of per_mode_best when a comparable shape landed."""
    best, err = bench._best_line(_lines(
        {"value": 9000.0, "mode": "committee", "n": 4, "k": 8},
        {"value": 310.0, "mode": "committee", "n": 32, "k": 128},
        {"value": 250.0, "mode": "epoch"},
    ))
    assert err is None
    assert best["value"] == 310.0 and (best["n"], best["k"]) == (32, 128)
    assert best["per_mode_best"] == {
        "committee[4x8]": 9000.0,
        "committee[32x128]": 310.0,
        "epoch": 250.0,
    }


def test_best_line_warmup_shape_used_when_alone(bench):
    """A window that only landed the liveness pre-pass still records it
    (better a tiny-shape number than none)."""
    best, _ = bench._best_line(_lines(
        {"value": 9000.0, "mode": "committee", "n": 4, "k": 8},
    ))
    assert best["value"] == 9000.0


def test_best_line_attaches_probes_and_surfaces_error(bench):
    best, err = bench._best_line(_lines(
        {"value": 500.0, "mode": "committee"},
        {"value": 0.0, "error": "epoch stage RuntimeError: device lost"},
        {"probe": "pallas_ab", "pallas_over_u64": 2.5, "pallas_chain_match": True},
        {"probe": "vm_step_ab", "fused_over_u64": 3.0},
    ))
    # a later stage's failure must not discard the landed committee number
    assert best["value"] == 500.0
    # BOTH probe lines survive, keyed by name, without the "probe" key
    assert best["probes"]["pallas_ab"]["pallas_over_u64"] == 2.5
    assert best["probes"]["vm_step_ab"]["fused_over_u64"] == 3.0
    assert "probe" not in best["probes"]["pallas_ab"]
    assert "device lost" in err


def test_best_line_none_on_errors_only(bench):
    best, err = bench._best_line(_lines({"value": 0.0, "error": "backend init hang"}))
    assert best is None
    assert err == "backend init hang"


def test_best_line_ignores_garbage(bench):
    raw = b"WARNING: noise\n" + _lines({"value": 7.0, "mode": "committee"}) + b"\nnot json"
    best, err = bench._best_line(raw)
    assert best["value"] == 7.0 and err is None


@pytest.mark.slow  # ~185 s: the worst tier-1 offender (ISSUE 11 audit)
def test_child_runs_committee_then_epoch_then_probe(bench, monkeypatch, capsys):
    """The child must run the window-proven committee shape FIRST, then
    epoch, then the pallas A/B — one process, every stage surviving the
    previous one's failure (round-4 verdict: a grant must never be
    gambled on epoch mode alone)."""
    calls = []

    def fake_run_workload(emit_partial=None, override=None, child_quick=False):
        calls.append(override)
        if override[3] == "epoch":
            raise RuntimeError("window died mid-epoch")
        return {"value": 123.0, "vs_baseline": 0.1, "mode": override[3]}

    class FakeJax:
        @staticmethod
        def default_backend():
            return "tpu"

    monkeypatch.setattr(bench, "run_workload", fake_run_workload)
    monkeypatch.setitem(sys.modules, "jax", FakeJax())
    monkeypatch.setenv(bench._CHILD_FLAG, "1")
    for v in ("BENCH_N", "BENCH_K", "BENCH_REPS", "BENCH_MODE"):
        monkeypatch.delenv(v, raising=False)

    bench.main()
    out = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]

    assert calls[0] == (4, 8, 1, "committee")  # instant first TPU number
    assert calls[1] == (32, 128, 3, "committee")
    assert calls[2][3] == "epoch"
    assert out[0]["value"] == 123.0 and out[0]["mode"] == "committee"
    assert any("epoch stage RuntimeError" in o.get("error", "") for o in out)
    # both probe stages still ran after the epoch failure (probe_error is
    # fine here: the fake jax can't run a real kernel)
    assert [o["probe"] for o in out if "probe" in o] == [
        "pallas_ab", "vm_step_ab",
    ]


def test_child_env_override_collapses_to_single_stage(bench, monkeypatch, capsys):
    calls = []

    def fake_run_workload(emit_partial=None, override=None, child_quick=False):
        calls.append((override, child_quick))
        return {"value": 9.0, "vs_baseline": 0.01, "mode": "epoch"}

    monkeypatch.setattr(bench, "run_workload", fake_run_workload)
    monkeypatch.setenv(bench._CHILD_FLAG, "1")
    monkeypatch.setenv("BENCH_MODE", "epoch")

    bench.main()
    out = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert calls == [(None, True)]
    assert out[-1]["value"] == 9.0


def test_init_watchdog_fires_on_hang(bench, monkeypatch, capsys):
    """A backend init that outlives BENCH_INIT_DEADLINE must flush a
    parseable error line and exit the child — the harvest loop's sampling
    rate depends on dead attempts dying fast."""
    import threading

    monkeypatch.setenv("BENCH_INIT_DEADLINE", "0.05")
    exited = threading.Event()
    codes = []

    def fake_exit(code):
        codes.append(code)
        exited.set()

    class HangingJax:
        @staticmethod
        def default_backend():
            exited.wait(5)  # blocks until the watchdog "exits"
            return "tpu"

    monkeypatch.setitem(sys.modules, "jax", HangingJax())
    got = bench._init_backend_with_watchdog(exit_fn=fake_exit)
    assert codes == [3]
    assert got is False  # the fake backend eventually answered 'tpu'
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "backend init exceeded" in line["error"]


def test_init_watchdog_noop_on_fast_init(bench, monkeypatch, capsys):
    monkeypatch.setenv("BENCH_INIT_DEADLINE", "5")

    class FastJax:
        @staticmethod
        def default_backend():
            return "cpu"

    monkeypatch.setitem(sys.modules, "jax", FastJax())
    codes = []
    assert bench._init_backend_with_watchdog(exit_fn=codes.append) is True
    import time

    time.sleep(0.1)
    assert codes == [] and capsys.readouterr().out == ""
