"""Random-linear-combination batch verification
(ops/bls_backend.batch_verify_rlc): bit-identical verdicts vs the
per-item path over valid/invalid/mixed/malformed/infinity inputs, the
bisection fallback's localization, the batch-of-1 degeneration,
deterministic injected rngs, and the jax combine (ops/pairing.rlc_combine)
against the exact-int oracle.

Tier-1 runs the small-N end-to-end cases (they share PROG A shapes the
default run compiles anyway) plus logic-level bisection at 16/64 through
an exact host-oracle combine; the wide end-to-end batches (16/64/256,
both combine backends) ride --run-slow like the rest of the device-deep
suites.
"""
import random

import numpy as np
import pytest

from consensus_specs_tpu.ops import bls_backend as bb
from consensus_specs_tpu.ops import fq
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils import bls12_381 as O
from consensus_specs_tpu.utils.bls12_381 import P, R


def _committee(tag: int, k: int = 2, good: bool = True):
    """One fast_aggregate item (pubkeys, message, signature); corrupt the
    message after signing when not ``good``."""
    sks = [1000 * tag + j + 1 for j in range(k)]
    pks = [bls.SkToPk(sk) for sk in sks]
    msg = (b"rlc%03d" % tag) + b"\x00" * 26
    sig = bls.Sign(sum(sks) % R, msg)
    if not good:
        msg = b"\xff" + msg[1:]
    return ("fast_aggregate", pks, msg, sig)


def _aggregate_item(tag: int, k: int = 2, good: bool = True):
    sks = [5000 * tag + j + 1 for j in range(k)]
    pks = [bls.SkToPk(sk) for sk in sks]
    msgs = [(b"ag%03d_%d" % (tag, j)) + b"\x00" * 24 for j in range(k)]
    sig = bls.Aggregate([bls.Sign(sk, m) for sk, m in zip(sks, msgs)])
    if not good:
        sig = bls.Sign(999, b"z" * 32)
    return ("aggregate", pks, msgs, sig)


def _per_item_verdicts(items) -> np.ndarray:
    out = np.zeros(len(items), dtype=bool)
    fast = [(i, it) for i, it in enumerate(items) if it[0] == "fast_aggregate"]
    agg = [(i, it) for i, it in enumerate(items) if it[0] == "aggregate"]
    if fast:
        res = bb.batch_fast_aggregate_verify(
            [it[1] for _, it in fast], [it[2] for _, it in fast],
            [it[3] for _, it in fast],
        )
        for (i, _), r in zip(fast, res):
            out[i] = bool(r)
    if agg:
        res = bb.batch_aggregate_verify(
            [it[1] for _, it in agg], [it[2] for _, it in agg],
            [it[3] for _, it in agg],
        )
        for (i, _), r in zip(agg, res):
            out[i] = bool(r)
    return out


# -- tier-1: small-N end-to-end gate ----------------------------------------


def test_rlc_mixed_small_batch_matches_per_item(monkeypatch):
    """Valid / corrupted / malformed-signature / infinity-signature in one
    batch: verdicts bit-identical to the per-item path, failures localized
    by bisection."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_CHUNK", "2")
    good_sig = bls.Sign(9, b"p" * 32)
    items = [
        _committee(1, k=2, good=True),
        _committee(2, k=1, good=False),                 # wrong message
        ("fast_aggregate", [bls.SkToPk(7)], b"m" * 32,
         b"\xa0" + b"\x01" * 95),                       # undecodable sig
        ("fast_aggregate", [bls.SkToPk(8)], b"n" * 32,
         b"\xc0" + b"\x00" * 95),                       # infinity sig
        ("fast_aggregate", [b"\xc0" + b"\x00" * 47],
         b"p" * 32, good_sig),                          # infinity pubkey
    ]
    before = dict(bb.RLC_STATS)
    got = bb.batch_verify_rlc(items, rng=random.Random(0xA5))
    want = _per_item_verdicts(items)
    assert np.array_equal(got, want)
    assert list(got) == [True, False, False, False, False]
    # malformed/infinity items never reached the combine: 2 candidates
    assert bb.RLC_STATS["items"] - before["items"] == 2
    # full combine failed (one bad candidate) -> one bisection -> exact
    # singleton finalizations
    assert bb.RLC_STATS["bisections"] > before["bisections"]


def test_rlc_all_valid_single_combine(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_CHUNK", "2")
    items = [_committee(11, k=2), _committee(12, k=2)]
    before = dict(bb.RLC_STATS)
    got = bb.batch_verify_rlc(items, rng=random.Random(1))
    assert list(got) == [True, True]
    assert bb.RLC_STATS["combines"] - before["combines"] == 1
    assert bb.RLC_STATS["bisections"] == before["bisections"]
    # the whole batch paid ONE final exponentiation
    assert bb.RLC_STATS["final_exps"] - before["final_exps"] == 1


def test_rlc_all_invalid_batch(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_CHUNK", "2")
    items = [_committee(21, good=False), _committee(22, good=False)]
    before = dict(bb.RLC_STATS)
    got = bb.batch_verify_rlc(items, rng=random.Random(2))
    assert list(got) == [False, False]
    assert bb.RLC_STATS["bisections"] - before["bisections"] == 1


def test_rlc_batch_of_one_degenerates_to_plain_path():
    before = dict(bb.RLC_STATS)
    assert list(bb.batch_verify_rlc([_committee(31)])) == [True]
    assert list(bb.batch_verify_rlc([_committee(32, good=False)])) == [False]
    # no combine ran: the plain per-item finalization answered both
    assert bb.RLC_STATS["combines"] == before["combines"]


def test_rlc_mixed_kinds_one_combine(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_CHUNK", "2")
    items = [_committee(41, k=2), _aggregate_item(42, k=2)]
    before = dict(bb.RLC_STATS)
    got = bb.batch_verify_rlc(items, rng=random.Random(3))
    assert list(got) == [True, True]
    # both kinds' Miller outputs merged into ONE combined check
    assert bb.RLC_STATS["combines"] - before["combines"] == 1
    assert bb.RLC_STATS["final_exps"] - before["final_exps"] == 1


def test_rlc_empty_and_bad_kind():
    assert list(bb.batch_verify_rlc([])) == []
    with pytest.raises(ValueError):
        bb.batch_verify_rlc([("proposer", [b"x"], b"m", b"s")])


# -- deterministic injected rng ---------------------------------------------


def test_rlc_scalars_deterministic_and_nonzero():
    a = bb._rlc_scalars(8, random.Random(7))
    b = bb._rlc_scalars(8, random.Random(7))
    assert np.array_equal(a, b)  # injected rng reproduces exactly
    c = bb._rlc_scalars(8, random.Random(8))
    assert not np.array_equal(a, c)
    assert a.shape == (8, 128)
    assert (a.sum(axis=1) > 0).all()  # nonzero scalars only
    # os.urandom default: right shape, nonzero
    d = bb._rlc_scalars(3)
    assert d.shape == (3, 128) and (d.sum(axis=1) > 0).all()


def test_rlc_verdicts_reproducible_with_injected_rng(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_CHUNK", "2")
    items = [_committee(51), _committee(52, good=False)]
    before = dict(bb.RLC_STATS)
    got1 = bb.batch_verify_rlc(items, rng=random.Random(9))
    mid = dict(bb.RLC_STATS)
    got2 = bb.batch_verify_rlc(items, rng=random.Random(9))
    after = dict(bb.RLC_STATS)
    assert np.array_equal(got1, got2) and list(got1) == [True, False]
    # identical scalars -> identical combine/bisection trajectory
    assert ({k: mid[k] - before[k] for k in mid}
            == {k: after[k] - mid[k] for k in after})


def test_reset_rlc_stats_and_clamped_serve_deltas():
    """reset_rlc_stats() zeroes the ledger + gauges, and a ServeMetrics
    baseline captured BEFORE a reset must clamp its deltas at zero (a
    rewound counter reads as no activity, never negative combines)."""
    from consensus_specs_tpu.ops import profiling
    from consensus_specs_tpu.serve.metrics import ServeMetrics

    bb.RLC_STATS["combines"] += 3
    bb.RLC_STATS["final_exps"] += 5
    sm = ServeMetrics()  # baseline sees the inflated counters
    bb.reset_rlc_stats()
    assert all(v == 0 for v in bb.RLC_STATS.values())
    assert profiling.summary()["bls.rlc_combines"]["gauge"] == 0.0
    assert profiling.summary()["bls.rlc_bisections"]["gauge"] == 0.0
    snap = sm.snapshot()
    assert snap["rlc"]["combines"] == 0  # clamped, not negative
    assert snap["rlc"]["final_exps"] == 0
    assert snap["final_exps_per_item"] == 0.0


# -- bisection localization at width (exact-oracle combine) -----------------


class _FakeLay:
    fold = 1

    def split(self, i):
        return i, ""


def _oracle_pow(f, e: int):
    acc = None
    for ch in bin(e)[2:]:
        if acc is not None:
            acc = acc * acc
        if ch == "1":
            acc = f if acc is None else acc * f
    return acc


def _oracle_combine(fs, bits, mesh=None):
    """Exact host reference of the combine stage (same contract as
    _rlc_combine_vm) — lets the bisection orchestration run at width
    with real final-exp math but no VM programs."""
    total = None
    for i in range(fs.shape[0]):
        f = bb._flat_ints_to_oracle(
            [fq.from_mont_limbs(fs[i, j]) for j in range(12)]
        )
        e = int("".join(str(int(x)) for x in bits[i]), 2)
        x = _oracle_pow(f, e)
        total = x if total is None else total * x
    return bb._oracle_to_flat_ints(total)


def _fake_miller(fs_rows):
    """Monkeypatch target for _miller_fast_aggregate: hands batch_verify_rlc
    pre-chosen f rows (valid item -> f = 1, whose final exp is 1; invalid
    -> a random Fq12, which fails the final exp with certainty ~1/r)."""
    def fake(pubkey_sets, messages, signatures, mesh=None):
        n = len(pubkey_sets)
        out = {"aggz": np.stack([fq.to_mont_int(1)] * n)}
        for j in range(12):
            out[f"f.{j}"] = np.stack([fs_rows[i][j] for i in range(n)])
        return out, _FakeLay(), np.ones(n, dtype=bool)

    return fake


def _f_row(valid: bool, rng: random.Random) -> np.ndarray:
    if valid:
        return np.stack([fq.to_mont_int(1 if j == 0 else 0)
                         for j in range(12)])
    return np.stack([fq.to_mont_int(rng.randrange(P)) for j in range(12)])


@pytest.mark.parametrize("n,bad", [(2, 1), (16, 3), (64, 40)])
def test_rlc_bisection_localizes_bad_items(monkeypatch, n, bad):
    """A single corrupted item in batches of 2/16/64 is isolated by
    bisection (everything else True), with O(log N) extra combines."""
    rng = random.Random(n * 1000 + bad)
    fs_rows = [_f_row(i != bad, rng) for i in range(n)]
    monkeypatch.setattr(bb, "_miller_fast_aggregate", _fake_miller(fs_rows))
    monkeypatch.setattr(bb, "_rlc_combine_vm", _oracle_combine)
    items = [("fast_aggregate", [b"\x01" * 48], b"m%03d" % i, b"s")
             for i in range(n)]
    before = dict(bb.RLC_STATS)
    got = bb.batch_verify_rlc(items, rng=rng)
    want = np.ones(n, dtype=bool)
    want[bad] = False
    assert np.array_equal(got, want)
    d = {k: bb.RLC_STATS[k] - before[k] for k in bb.RLC_STATS}
    assert d["items"] == n
    # one failing path down the tree: <= 2 combines per level + the root
    import math

    levels = max(1, math.ceil(math.log2(n)))
    assert d["bisections"] <= levels
    assert d["combines"] <= 1 + 2 * levels


def test_rlc_bisection_all_invalid_wide(monkeypatch):
    n = 16
    rng = random.Random(77)
    fs_rows = [_f_row(False, rng) for _ in range(n)]
    monkeypatch.setattr(bb, "_miller_fast_aggregate", _fake_miller(fs_rows))
    monkeypatch.setattr(bb, "_rlc_combine_vm", _oracle_combine)
    items = [("fast_aggregate", [b"\x01" * 48], b"w%03d" % i, b"s")
             for i in range(n)]
    got = bb.batch_verify_rlc(items, rng=rng)
    assert not got.any()


# -- jax combine backend + oracle cross-check -------------------------------


def test_pairing_rlc_combine_matches_oracle():
    """ops/pairing.rlc_combine == exact-int oracle prod f_i^{r_i}."""
    from consensus_specs_tpu.ops import pairing

    rng = random.Random(13)
    fs_o = []
    for _ in range(2):
        fs_o.append(O.Fq12(
            O.Fq6(*[O.Fq2(rng.randrange(P), rng.randrange(P))
                    for _ in range(3)]),
            O.Fq6(*[O.Fq2(rng.randrange(P), rng.randrange(P))
                    for _ in range(3)]),
        ))
    fs = np.stack([
        np.stack([fq.to_mont_int(c) for c in bb._oracle_to_flat_ints(f)])
        for f in fs_o
    ])
    bits = bb._rlc_scalars(2, rng)
    got = np.asarray(pairing.rlc_combine(fs, bits.astype(bool)))
    got_o = bb._flat_ints_to_oracle(
        [fq.from_mont_limbs(got[j]) for j in range(12)]
    )
    want = None
    for f, brow in zip(fs_o, bits):
        e = int("".join(str(int(x)) for x in brow), 2)
        x = _oracle_pow(f, e)
        want = x if want is None else want * x
    assert bb._oracle_to_flat_ints(got_o) == bb._oracle_to_flat_ints(want)


def test_rlc_jax_backend_end_to_end(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_BACKEND", "jax")
    items = [_committee(61, k=2), _committee(62, k=2)]
    got = bb.batch_verify_rlc(items, rng=random.Random(4))
    assert list(got) == [True, True]


# -- final-exp routing ------------------------------------------------------


def test_rlc_final_host_and_device_agree(monkeypatch):
    """The combined check's hard part is bit-identical whether it runs as
    an exact-int oracle HHT on host or a hard_part VM row on device."""
    rng = random.Random(21)
    good = [1] + [0] * 11  # f = 1 passes
    bad = [rng.randrange(P) for _ in range(12)]
    for mode in ("host", "device"):
        monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_FINAL", mode)
        assert bb._final_exp_is_one(list(good)) is True
        assert bb._final_exp_is_one(list(bad)) is False
    # degenerate f = 0: False without any hard part
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_FINAL", "host")
    assert bb._final_exp_is_one([0] * 12) is False


def test_hard_part_oracle_matches_vm_on_real_item():
    """Host-oracle HHT vs the device hard part on a REAL unitary g (the
    easy-part output of a genuine Miller value), both verdict polarities."""
    (_, pks, msg, sig) = _committee(71, k=1)
    out, lay, precheck = bb._miller_fast_aggregate([pks], [msg], [sig], None)
    assert out is not None and precheck[0]
    r, ns = lay.split(0)
    coeffs = [fq.from_mont_limbs(out[f"{ns}f.{j}"][r]) for j in range(12)]
    g = bb._easy_part_flat(coeffs)
    gm = np.stack([fq.to_mont_int(c) for c in g])
    assert bb._hard_part_is_one_oracle(g) is True
    assert bool(bb._run_hard_part(gm[None])[0]) is True
    # perturb g out of the kernel: both must say False
    g_bad = list(g)
    g_bad[0] = (g_bad[0] + 1) % P
    gm_bad = np.stack([fq.to_mont_int(c) for c in g_bad])
    assert bb._hard_part_is_one_oracle(g_bad) is False
    assert bool(bb._run_hard_part(gm_bad[None])[0]) is False


# -- collector integration --------------------------------------------------


def test_collector_flush_rlc(monkeypatch):
    from consensus_specs_tpu.batch_verify import SignatureCollector

    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_CHUNK", "2")
    kind, pks, msg, sig = _committee(81, k=2)
    col = SignatureCollector()
    assert col._fast_aggregate_verify(pks, msg, sig) is True
    assert col._fast_aggregate_verify(pks, msg, sig) is True  # duplicate
    assert col._fast_aggregate_verify(pks, b"\xff" + msg[1:], sig) is True
    got = col.flush(rlc=True)
    assert np.array_equal(got, col.flush_oracle())
    assert list(got) == [True, True, False]


# -- wide end-to-end batches (slow: fresh big-program compiles) -------------


@pytest.mark.slow
def test_rlc_wide_batches_match_per_item_vm():
    for n, bad in ((16, 5), (64, None)):
        items = [_committee(100 + i, k=1, good=(i != bad)) for i in range(n)]
        got = bb.batch_verify_rlc(items, rng=random.Random(n))
        want = _per_item_verdicts(items)
        assert np.array_equal(got, want)
        if bad is None:
            assert got.all()
        else:
            assert got.sum() == n - 1 and not got[bad]


@pytest.mark.slow
def test_rlc_256_valid_vm():
    items = [_committee(400 + i, k=1) for i in range(256)]
    before = dict(bb.RLC_STATS)
    got = bb.batch_verify_rlc(items, rng=random.Random(256))
    assert got.all()
    assert bb.RLC_STATS["final_exps"] - before["final_exps"] == 1


@pytest.mark.slow
def test_rlc_wide_jax_backend(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_BACKEND", "jax")
    n, bad = 16, 11
    items = [_committee(300 + i, k=1, good=(i != bad)) for i in range(n)]
    got = bb.batch_verify_rlc(items, rng=random.Random(5))
    want = np.ones(n, dtype=bool)
    want[bad] = False
    assert np.array_equal(got, want)
