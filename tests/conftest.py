"""Test session config.

Sets up a virtual 8-device CPU platform BEFORE jax is imported anywhere, so
multi-chip sharding tests (mesh/pjit/shard_map) run without TPU hardware.
Also wires the reference-style CLI flags (--preset/--fork/--disable-bls)
(reference: tests/core/pyspec/eth2spec/test/conftest.py:30-93).
"""

# Override — don't setdefault. The outer environment may carry
# JAX_PLATFORMS=axon (a single-TPU tunnel); under that, the first device op
# blocks retrying the TPU and the whole session hangs. The CPU-mesh suite
# must win — even when a sitecustomize hook already imported jax at
# interpreter start (jax_env handles both cases).
from consensus_specs_tpu.utils.jax_env import force_cpu  # noqa: E402

force_cpu(n_devices=8)

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--preset", action="store", type=str, default="minimal",
        help="preset to run tests against: minimal or mainnet",
    )
    parser.addoption(
        "--fork", action="append", type=str, default=None,
        help="fork(s) to run tests against (repeatable)",
    )
    parser.addoption(
        "--disable-bls", action="store_true", default=True,
        help="disable BLS for tests that do not require it (the default, "
        "mirroring the reference's `make test`, reference Makefile:100; "
        "@always_bls tests still run real BLS)",
    )
    parser.addoption(
        "--enable-bls", action="store_true", default=False,
        help="run every test with real BLS (reference `make citest` mode)",
    )
    parser.addoption(
        "--run-slow", action="store_true", default=False,
        help="run tests marked slow (long XLA compiles / big batches)",
    )
    parser.addoption(
        "--bls-type", action="store", type=str, default="py_ecc",
        help="BLS backend: py_ecc (pure-python oracle) or tpu (JAX backend)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long XLA compiles / large batches; needs --run-slow"
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--run-slow"):
        return
    skip_slow = pytest.mark.skip(reason="slow test: pass --run-slow to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)


@pytest.fixture(autouse=True)
def _configure_harness(request):
    from consensus_specs_tpu.test import context
    from consensus_specs_tpu.utils import bls

    context.DEFAULT_TEST_PRESET = request.config.getoption("--preset")
    forks = request.config.getoption("--fork")
    context.DEFAULT_PYTEST_FORKS = set(forks) if forks else None
    # default: BLS off except @always_bls (reference `make test`,
    # Makefile:100); --enable-bls mirrors `make citest` (Makefile:111)
    context.DEFAULT_BLS_ACTIVE = bool(request.config.getoption("--enable-bls"))
    bls_type = request.config.getoption("--bls-type")
    if bls_type == "tpu":
        bls.use_tpu()
    else:
        bls.use_py_ecc()
    yield
