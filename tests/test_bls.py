"""BLS12-381 oracle tests: curve structure, pairing bilinearity, serialization,
hash-to-curve self-consistency, and the IETF signature API.

Modeled on the reference BLS generator's cross-check strategy
(reference: tests/generators/bls/main.py).
"""
import pytest

from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.bls12_381 import (
    Fq2,
    Fq12,
    G1_GEN,
    G2_GEN,
    R,
    B_G2,
    ec_add,
    ec_eq,
    ec_mul,
    ec_neg,
    ec_to_affine,
    g1_from_bytes,
    g1_to_bytes,
    g2_from_bytes,
    g2_to_bytes,
    hash_to_g2,
    is_on_curve_g1,
    is_on_curve_g2,
    is_in_g2_subgroup,
    iso_map_g2,
    map_to_curve_sswu_g2,
    pairing,
    expand_message_xmd,
)

pytestmark = pytest.mark.bls  # crypto-heavy suite


def test_generators_on_curve_and_order():
    assert is_on_curve_g1(ec_to_affine(G1_GEN))
    assert is_on_curve_g2(ec_to_affine(G2_GEN))
    assert ec_mul(G1_GEN, R) is None
    assert ec_mul(G2_GEN, R) is None


def test_ec_group_laws_g1():
    p2 = ec_mul(G1_GEN, 2)
    assert ec_eq(ec_add(G1_GEN, G1_GEN), p2)
    p5 = ec_mul(G1_GEN, 5)
    assert ec_eq(ec_add(p2, ec_mul(G1_GEN, 3)), p5)
    assert ec_add(p5, ec_neg(p5)) is None
    assert ec_eq(ec_add(p5, None), p5)


def test_g1_serialization_roundtrip():
    for k in (1, 2, 3, 12345, R - 1):
        pt = ec_to_affine(ec_mul(G1_GEN, k))
        data = g1_to_bytes(pt)
        assert len(data) == 48
        back = g1_from_bytes(data)
        assert back == pt
    # infinity
    inf_bytes = bytes([0xC0]) + b"\x00" * 47
    assert g1_from_bytes(inf_bytes) is None
    assert g1_to_bytes(None) == inf_bytes


def test_g1_generator_known_compressed_encoding():
    # well-known compressed encoding of the G1 generator
    expected = bytes.fromhex(
        "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
        "6c55e83ff97a1aeffb3af00adb22c6bb"
    )
    assert g1_to_bytes(ec_to_affine(G1_GEN)) == expected


def test_g2_serialization_roundtrip():
    for k in (1, 2, 7, 98765):
        pt = ec_to_affine(ec_mul(G2_GEN, k))
        data = g2_to_bytes(pt)
        assert len(data) == 96
        assert g2_from_bytes(data) == pt
    inf = bytes([0xC0]) + b"\x00" * 95
    assert g2_from_bytes(inf) is None
    assert g2_to_bytes(None) == inf


def test_invalid_encodings_rejected():
    with pytest.raises(ValueError):
        g1_from_bytes(b"\x00" * 48)  # compression bit unset
    with pytest.raises(ValueError):
        g1_from_bytes(bytes([0x80]) + b"\xff" * 47)  # x >= p
    with pytest.raises(ValueError):
        g1_from_bytes(bytes([0xE0]) + b"\x00" * 47)  # infinity with sign bit
    with pytest.raises(ValueError):
        g2_from_bytes(b"\x11" * 96)


def test_fq2_sqrt():
    a = Fq2(5, 7)
    sq = a * a
    root = sq.sqrt()
    assert root is not None and root * root == sq


def test_sswu_maps_to_isogenous_then_real_curve():
    # SSWU output is on E'(A', B'); iso_map moves it onto E: y^2 = x^3 + 4(1+u).
    # This check would fail if any of the 15 isogeny constants were wrong.
    from consensus_specs_tpu.utils.bls12_381 import SSWU_A, SSWU_B

    for seed in range(5):
        u = Fq2(seed * 1234567 + 1, seed * 7654321 + 2)
        x, y = map_to_curve_sswu_g2(u)
        assert y * y == x * x * x + SSWU_A * x + SSWU_B
        xi, yi = iso_map_g2(x, y)
        assert yi * yi == xi * xi * xi + B_G2


def test_hash_to_g2_in_subgroup_and_deterministic():
    h1 = hash_to_g2(b"test message", bls.DST)
    h2 = hash_to_g2(b"test message", bls.DST)
    assert ec_eq(h1, h2)
    assert is_on_curve_g2(ec_to_affine(h1))
    assert is_in_g2_subgroup(h1)
    h3 = hash_to_g2(b"different", bls.DST)
    assert not ec_eq(h1, h3)


def test_expand_message_xmd_length_and_determinism():
    out = expand_message_xmd(b"msg", b"DST", 256)
    assert len(out) == 256
    assert out == expand_message_xmd(b"msg", b"DST", 256)
    assert out[:32] != b"\x00" * 32


def test_pairing_bilinearity():
    e = pairing(ec_to_affine(G2_GEN), ec_to_affine(G1_GEN))
    assert e != Fq12.one()
    # e(aP, Q) == e(P, Q)^a
    a, b = 5, 7
    e_a = pairing(ec_to_affine(G2_GEN), ec_to_affine(ec_mul(G1_GEN, a)))
    assert e_a == e.pow(a)
    # e(aP, bQ) == e(P, Q)^(ab)
    e_ab = pairing(ec_to_affine(ec_mul(G2_GEN, b)), ec_to_affine(ec_mul(G1_GEN, a)))
    assert e_ab == e.pow(a * b)
    # e(P, Q)^r == 1
    assert e.pow(R) == Fq12.one()


def test_sign_verify():
    sk = 42
    pk = bls.SkToPk(sk)
    msg = b"\x12" * 32
    sig = bls.Sign(sk, msg)
    assert bls.Verify(pk, msg, sig)
    assert not bls.Verify(pk, b"\x13" * 32, sig)
    assert not bls.Verify(bls.SkToPk(43), msg, sig)
    # tampered signature: invalid encodings return False (never raise)
    assert not bls.Verify(pk, msg, b"\x00" * 96)
    assert not bls.Verify(b"\x00" * 48, msg, sig)


def test_zero_privkey_rejected():
    with pytest.raises(ValueError):
        bls.Sign(0, b"msg")
    with pytest.raises(ValueError):
        bls.SkToPk(0)


def test_aggregate_and_fast_aggregate_verify():
    msg = b"\x34" * 32
    sks = [1, 2, 3, 4]
    pks = [bls.SkToPk(sk) for sk in sks]
    sigs = [bls.Sign(sk, msg) for sk in sks]
    agg = bls.Aggregate(sigs)
    assert bls.FastAggregateVerify(pks, msg, agg)
    assert not bls.FastAggregateVerify(pks[:3], msg, agg)
    assert not bls.FastAggregateVerify(pks, b"\x35" * 32, agg)
    assert not bls.FastAggregateVerify([], msg, agg)


def test_aggregate_verify_distinct_messages():
    sks = [11, 22, 33]
    msgs = [bytes([i]) * 32 for i in range(3)]
    pks = [bls.SkToPk(sk) for sk in sks]
    sigs = [bls.Sign(sk, m) for sk, m in zip(sks, msgs)]
    agg = bls.Aggregate(sigs)
    assert bls.AggregateVerify(pks, msgs, agg)
    assert not bls.AggregateVerify(pks, msgs[::-1], agg)
    assert not bls.AggregateVerify(pks, msgs[:2], agg)


def test_aggregate_empty_raises():
    with pytest.raises(ValueError):
        bls.Aggregate([])


def test_aggregate_pks_matches_sum():
    sks = [5, 6]
    pks = [bls.SkToPk(sk) for sk in sks]
    agg_pk = bls.AggregatePKs(pks)
    assert agg_pk == bls.SkToPk(11)


def test_key_validate():
    assert bls.KeyValidate(bls.SkToPk(99))
    assert not bls.KeyValidate(bytes([0xC0]) + b"\x00" * 47)  # infinity
    assert not bls.KeyValidate(b"\x00" * 48)


def test_signature_to_G2_roundtrip():
    sig = bls.Sign(7, b"m")
    coords = bls.signature_to_G2(sig)
    ((x0, x1), (y0, y1)) = coords
    aff = (Fq2(x0, x1), Fq2(y0, y1))
    assert is_on_curve_g2(aff)


def test_bls_switch_stubs():
    bls.bls_active = False
    try:
        assert bls.Verify(b"junk", b"m", b"junk") is True
        assert bls.Sign(123, b"m") == bls.STUB_SIGNATURE
        assert bls.SkToPk(123) == bls.STUB_PUBKEY
        assert bls.Aggregate([]) == bls.STUB_SIGNATURE
    finally:
        bls.bls_active = True


def test_psi_cofactor_clearing_matches_scalar_multiply():
    # the Budroni-Pintore psi decomposition must equal the definitional
    # [H_EFF_G2] scalar multiply on arbitrary E'(Fq2) points (pre-cofactor,
    # outside the subgroup)
    from consensus_specs_tpu.utils import bls12_381 as O

    dst = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
    for i in range(4):
        u0, u1 = O.hash_to_field_fq2(bytes([40 + i]) * 32, 2, dst)
        q = O.ec_add(
            O.ec_from_affine(O.iso_map_g2(*O.map_to_curve_sswu_g2(u0))),
            O.ec_from_affine(O.iso_map_g2(*O.map_to_curve_sswu_g2(u1))),
        )
        fast = O.ec_to_affine(O.clear_cofactor_g2(q))
        slow = O.ec_to_affine(O._clear_cofactor_g2_scalar(q))
        assert fast == slow


def test_psi_membership_matches_scalar_check():
    # Scott's psi criterion must agree with [r]P == infinity on members
    # (hash outputs, generator multiples) AND non-members (pre-cofactor
    # curve points)
    from consensus_specs_tpu.utils import bls12_381 as O

    dst = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
    members = [O.hash_to_g2(bytes([i]) * 32, dst) for i in range(2)]
    members += [O.ec_mul(O.G2_GEN, k) for k in (1, 987654321)]
    for p in members:
        assert O.is_in_g2_subgroup(p)
        assert O._is_in_g2_subgroup_scalar(p)
    for i in range(3):
        u0, _ = O.hash_to_field_fq2(bytes([70 + i]) * 32, 2, dst)
        q = O.ec_from_affine(O.iso_map_g2(*O.map_to_curve_sswu_g2(u0)))
        assert O.is_in_g2_subgroup(q) == O._is_in_g2_subgroup_scalar(q)
