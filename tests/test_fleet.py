"""Serve fleet (ISSUE 11): consistent-hash routing, the snapshot merge
algebra on the aggregator, the shed policy, and a REAL 2-worker fleet of
`serve/worker.py` processes (verdict backend — no crypto or compiles,
spawned once per module) driven through verdict identity, cache affinity,
exactness, a forced fault -> SLO-burn -> shed/drain escalation, and the
simnet partition_heal scenario replayed against the live fleet.
"""
import json
import time

import pytest

from consensus_specs_tpu.obs import flight, registry
from consensus_specs_tpu.obs import snapshot as osnap
from consensus_specs_tpu.obs.fleet import FleetAggregator
from consensus_specs_tpu.obs.slo import ShedPolicy, SloTracker, worst_burn
from consensus_specs_tpu.serve.cache import check_key
from consensus_specs_tpu.serve.fleet import FleetRouter, HashRing
from consensus_specs_tpu.serve.load import BAD_SIGNATURE
from consensus_specs_tpu.ops import profiling


@pytest.fixture(autouse=True)
def _clean_profiling():
    profiling.reset()
    yield
    profiling.reset()


def _pk(i):
    return bytes([i]) * 48


def _snap(worker, hists=None, gauges=None, stats=None, events=None,
          pid=1, spans=None):
    snap = {"v": osnap.WIRE_VERSION, "worker": worker, "pid": pid,
            "hists": hists or {}, "gauges": gauges or {},
            "stats": stats or {}}
    if events is not None:
        snap["flight"] = {"counters": {"events": len(events)},
                          "events": events}
    if spans is not None:
        snap["spans"] = {"traces": spans}
    return snap


def _wire(values):
    from consensus_specs_tpu.obs.hist import Histogram

    h = Histogram()
    for v in values:
        h.observe(v)
    return osnap.hist_to_wire(h)


# -- consistent-hash ring -----------------------------------------------------


def test_ring_routes_deterministically_and_affinely():
    ring = HashRing()
    for label in ("w0", "w1", "w2"):
        ring.add(label)
    keys = [check_key("fast_aggregate", [_pk(i)], bytes([i]) * 32,
                      bytes([i]) * 96) for i in range(64)]
    first = [ring.route(k) for k in keys]
    assert [ring.route(k) for k in keys] == first  # same key, same worker
    assert len(set(first)) == 3  # all workers own some arc


def test_ring_removal_only_remaps_the_drained_workers_keys():
    ring = HashRing()
    for label in ("w0", "w1", "w2"):
        ring.add(label)
    keys = [check_key("fast_aggregate", [_pk(i)], bytes([i]) * 32,
                      bytes([i]) * 96) for i in range(128)]
    before = {k: ring.route(k) for k in keys}
    ring.remove("w1")
    for k, owner in before.items():
        if owner != "w1":
            # the consistent-hashing property: surviving workers keep
            # every key they had (their result caches stay warm)
            assert ring.route(k) == owner
        else:
            assert ring.route(k) in ("w0", "w2")


# -- aggregator merge algebra -------------------------------------------------


def test_aggregator_merges_hists_exactly_and_namespaces_gauges():
    aggr = FleetAggregator()
    a, b = [0.01, 0.02, 0.5], [0.015, 4.0]
    aggr.ingest("w0", _snap(
        "w0", hists={"serve.submit_to_result": _wire(a)},
        gauges={"serve.queue_depth": 2.0, "bls.rlc_combines": 3.0,
                "slo.ok": 1.0},
        stats={"serve.batch_flush": {"calls": 2, "total_s": 1.0,
                                     "max_s": 0.7}}))
    aggr.ingest("w1", _snap(
        "w1", hists={"serve.submit_to_result": _wire(b)},
        gauges={"serve.queue_depth": 5.0, "bls.rlc_combines": 4.0},
        stats={"serve.batch_flush": {"calls": 1, "total_s": 0.2,
                                     "max_s": 0.2}}))
    merged = aggr.merged_hists()["serve.submit_to_result"]
    from consensus_specs_tpu.obs.hist import Histogram

    whole = Histogram()
    for v in a + b:
        whole.observe(v)
    assert merged.state()["counts"] == whole.state()["counts"]
    assert merged.count == 5
    gauges = aggr.merged_gauges()
    # instance gauges re-scope per worker; counters sum; slo.* drops
    assert gauges["serve[w0].queue_depth"] == 2.0
    assert gauges["serve[w1].queue_depth"] == 5.0
    assert gauges["bls.rlc_combines"] == 7.0
    assert not any(g.startswith("slo.") for g in gauges)
    stats = aggr.merged_stats()["serve.batch_flush"]
    assert stats == {"calls": 3, "total_s": 1.2, "max_s": 0.7}
    # the merged view renders through the standard Prometheus renderer
    text = aggr.render_metrics(local_gauges={"fleet.workers": 2.0})
    assert ("consensus_specs_tpu_serve_submit_to_result_latency_hist_"
            "seconds_count 5") in text
    assert "consensus_specs_tpu_fleet_workers 2.0" in text
    assert 'serve_node{label="serve[w0].queue_depth"} 2.0' in text


def test_merged_view_local_gauges_never_clobber_worker_counters():
    """The overlay rule: router-authoritative planes (fleet.*, slo.*)
    replace, unknown keys add, but a local counter colliding with the
    worker merge keeps the WORKER sum — e.g. the router dumping its own
    journal sets a local flight.events that must not shadow the fleet's."""
    aggr = FleetAggregator()
    aggr.ingest("w0", _snap("w0", gauges={"flight.events": 5.0}))
    aggr.ingest("w1", _snap("w1", gauges={"flight.events": 7.0}))
    _, gauges, _ = aggr.merged_view(local_gauges={
        "flight.events": 1.0, "fleet.workers": 2.0, "slo.ok": 1.0})
    assert gauges["flight.events"] == 12.0  # worker sum, not the local 1.0
    assert gauges["fleet.workers"] == 2.0
    assert gauges["slo.ok"] == 1.0


def test_snapshot_flight_since_ships_only_new_events(monkeypatch):
    """The control tick's delta protocol: flight_since filters the ring
    worker-side, and the aggregator's last_seq is what the router feeds
    back — re-ingesting a delta continues the journal without gaps."""
    monkeypatch.setenv(flight.FLIGHT_ENV, "1")
    flight.reset_global()
    try:
        rec = flight.global_recorder()
        for i in range(3):
            rec.note("serve", "flush", items=i)
        full = osnap.take_process_snapshot(worker="w0")
        assert [e["seq"] for e in full["flight"]["events"]] == [1, 2, 3]
        delta = osnap.take_process_snapshot(worker="w0", flight_since=2)
        assert [e["seq"] for e in delta["flight"]["events"]] == [3]
        # counters stay cumulative on the delta snapshot
        assert delta["flight"]["counters"]["events"] == 3
        aggr = FleetAggregator()
        aggr.ingest("w0", full)
        assert aggr.last_seq("w0") == 3
        rec.note("serve", "flush", items=3)
        aggr.ingest("w0", osnap.take_process_snapshot(
            worker="w0", flight_since=aggr.last_seq("w0")))
        assert [e["seq"] for e in aggr.journal_events()] == [1, 2, 3, 4]
    finally:
        flight.reset_global()


def test_aggregator_journal_is_incremental_and_worker_stamped():
    aggr = FleetAggregator()
    ev = [{"seq": 1, "t": 0.1, "plane": "serve", "kind": "flush",
           "data": {}},
          {"seq": 2, "t": 0.2, "plane": "serve", "kind": "cache_hit",
           "data": {}}]
    aggr.ingest("w0", _snap("w0", events=ev))
    # re-ingesting the same ring must not duplicate events
    aggr.ingest("w0", _snap("w0", events=ev + [
        {"seq": 3, "t": 0.3, "plane": "serve", "kind": "flush",
         "data": {}}]))
    events = aggr.journal_events()
    assert [e["seq"] for e in events] == [1, 2, 3]
    assert all(e["worker"] == "w0" for e in events)
    jsonl = aggr.journal_jsonl(reason="test")
    header = json.loads(jsonl.splitlines()[0])
    assert header["events"] == 3 and header["workers"] == ["w0"]


def _ev(seq, t=None):
    return {"seq": seq, "t": t if t is not None else seq / 10.0,
            "plane": "serve", "kind": "flush", "data": {}}


def test_aggregator_restart_resets_watermarks_and_keeps_both_journals():
    """The ISSUE 19 restart regression: a respawned worker restarts its
    flight seq / trace rid counters from 1. Watermarks keyed by label
    alone would hide the fresh incarnation's entire journal and span
    stream below the dead process's high water; pid-keyed watermarks
    reset, and the merged journal keeps BOTH incarnations' events."""
    aggr = FleetAggregator()
    aggr.ingest("w0", _snap("w0", pid=100, events=[_ev(1), _ev(2), _ev(3)],
                            spans=[{"rid": 1, "spans": []},
                                   {"rid": 2, "spans": []}]))
    assert aggr.last_seq("w0", pid=100) == 3
    assert aggr.last_rid("w0", pid=100) == 2
    # the router asks on behalf of a pid the aggregator has never seen
    # (the respawn just happened): the delta cursors MUST answer 0 —
    # answering 3 would make the new worker ship nothing, forever
    assert aggr.last_seq("w0", pid=200) == 0
    assert aggr.last_rid("w0", pid=200) == 0
    # the new incarnation's restarted sequence numbers merge from the top
    aggr.ingest("w0", _snap("w0", pid=200, events=[_ev(1, t=9.1),
                                                   _ev(2, t=9.2)],
                            spans=[{"rid": 1, "spans": []}]))
    events = aggr.journal_events()
    assert [e["seq"] for e in events] == [1, 2, 3, 1, 2]
    assert [e["pid"] for e in events] == [100, 100, 100, 200, 200]
    assert aggr.last_seq("w0", pid=200) == 2
    assert aggr.last_rid("w0", pid=200) == 1
    # span sections carry the LIVE incarnation's pid
    assert aggr.worker_span_sections()["w0"]["pid"] == 200


def test_aggregator_same_pid_reingest_still_dedupes():
    # the restart reset must not break the normal incremental contract:
    # the same incarnation re-shipping its ring dedupes by seq
    aggr = FleetAggregator()
    aggr.ingest("w0", _snap("w0", pid=100, events=[_ev(1), _ev(2)]))
    aggr.ingest("w0", _snap("w0", pid=100, events=[_ev(1), _ev(2),
                                                   _ev(3)]))
    assert [e["seq"] for e in aggr.journal_events()] == [1, 2, 3]


def test_aggregator_rejects_wrong_wire_version():
    aggr = FleetAggregator()
    with pytest.raises(osnap.WireError):
        aggr.ingest("w0", {"v": 999})


# -- shed policy --------------------------------------------------------------


def _eval(burns, ok=True, n=10):
    return {"serve_p99": {"label": "serve.submit_to_result", "ok": ok,
                          "n": n, "burn_rate": burns}}


def test_policy_quiet_fleet_decides_nothing():
    policy = ShedPolicy(shed_burn=4.0, drain_burn=32.0)
    assert policy.decide(_eval({"60s": 0.5}), {"w0": _eval({"60s": 0.9})}) \
        == []


def test_policy_sheds_the_worst_burning_worker():
    policy = ShedPolicy(shed_burn=4.0, drain_burn=32.0)
    decisions = policy.decide(
        _eval({"60s": 6.0}),
        {"w0": _eval({"60s": 1.0}), "w1": _eval({"60s": 9.0})})
    assert len(decisions) == 1
    d = decisions[0]
    assert (d.worker, d.action) == ("w1", "shed")
    assert d.burn == 9.0 and d.objective == "serve_p99"


def test_policy_escalates_to_drain():
    policy = ShedPolicy(shed_burn=4.0, drain_burn=32.0)
    # past the drain threshold outright
    d = policy.decide(_eval({"60s": 40.0}),
                      {"w0": _eval({"60s": 40.0})})[0]
    assert d.action == "drain"
    # or shed-to-the-bottom and still burning
    d = policy.decide(_eval({"60s": 6.0}), {"w0": _eval({"60s": 6.0})},
                      rungs={"w0": 2})[0]
    assert d.action == "drain"


def test_worst_burn_picks_the_peak_window():
    obj, window, rate = worst_burn(_eval({"60s": 2.0, "300s": 7.5}))
    assert (obj, window, rate) == ("serve_p99", "300s", 7.5)


# -- a real 2-worker fleet (verdict backend, spawned once per module) ---------


@pytest.fixture(scope="module")
def fleet():
    router = FleetRouter(workers=2, backend="verdict",
                         env={"SERVE_MAX_WAIT_MS": "2"})
    yield router
    router.close()


def test_fleet_verdict_identity_and_affinity(fleet):
    pks = [_pk(1), _pk(2)]
    futs, want = [], []
    for i in range(24):
        msg = bytes([i]) * 32
        sig = BAD_SIGNATURE if i % 6 == 5 else bytes([i]) * 96
        futs.append(fleet.submit("fast_aggregate", pks, msg, sig))
        want.append(i % 6 != 5)
    assert [f.result(timeout=30) for f in futs] == want
    # affinity: resubmitting identical content goes to the same worker
    # and is answered by ITS cache — the fleet verifies each distinct
    # check exactly once
    snaps = fleet.poll_snapshots()
    hits_before = {w: s["extra"]["serve"]["cache_hits"]
                   for w, s in snaps.items()}
    futs = [fleet.submit("fast_aggregate", pks, bytes([i]) * 32,
                         bytes([i]) * 96) for i in range(4)]
    assert all(f.result(timeout=30) for f in futs)
    snaps = fleet.poll_snapshots()
    gained = sum(s["extra"]["serve"]["cache_hits"] - hits_before[w]
                 for w, s in snaps.items())
    assert gained == 4


def test_fleet_merged_scrape_is_exact_merge_of_worker_snapshots(fleet):
    snaps = fleet.poll_snapshots()
    label = "serve.submit_to_result"
    wires = [s["hists"][label] for s in snaps.values()]
    expect_count = sum(w["count"] for w in wires)
    expect_buckets = {}
    for w in wires:
        for idx, n in w["counts"].items():
            expect_buckets[int(idx)] = expect_buckets.get(int(idx), 0) + n
    merged = fleet.aggregator.merged_hists()[label]
    assert merged.count == expect_count
    assert merged.state()["counts"] == expect_buckets
    fam = ("consensus_specs_tpu_serve_submit_to_result_latency_hist_"
           "seconds_count")
    text = fleet.scrape_text()
    [count_line] = [l for l in text.splitlines()
                    if l.startswith(fam + " ")]
    assert int(count_line.rsplit(" ", 1)[1]) == expect_count
    # per-worker namespaced instance gauges ride the same scrape
    assert 'label="serve[w0].queue_depth"' in text


def test_fleet_healthz_and_exposition_endpoint(fleet):
    import urllib.request

    server = fleet.start_exposition(port=0)
    try:
        with urllib.request.urlopen(server.url("/healthz"),
                                    timeout=10) as resp:
            hz = json.loads(resp.read())
        assert hz["ok"] is True and hz["workers"] == ["w0", "w1"]
        with urllib.request.urlopen(server.url("/metrics"),
                                    timeout=10) as resp:
            body = resp.read().decode()
        assert "consensus_specs_tpu_fleet_workers 2.0" in body
    finally:
        server.close()


def test_worker_protocol_answers_unknown_ops_with_errors(fleet):
    from consensus_specs_tpu.serve.fleet import WorkerProtocolError

    with pytest.raises(WorkerProtocolError, match="unknown op"):
        fleet.handle("w0").rpc({"op": "no_such_op"}, timeout=10)


def test_sim_partition_heal_replayed_against_the_live_fleet(fleet):
    """The simnet satellite: a real scenario, real worker PROCESSES doing
    every node's verification, and the strict differential convergence
    gate still green — the fleet is transparent to consensus."""
    from consensus_specs_tpu.sim.fleet_replay import run_fleet_replay

    out = run_fleet_replay("partition_heal", strict=True, router=fleet)
    assert out["report"].converged
    assert out["fleet"]["routed"] > 0
    submits = [w["submits"] for w in out["fleet"]["per_worker"].values()]
    assert sum(submits) > 0 and len(submits) == 2


# -- forced fault -> burn -> shed escalation (its own fleet) ------------------


def test_fault_burns_merged_slo_and_sheds_then_drains(monkeypatch):
    """The control loop end to end on a live fleet: a slow-fault on one
    worker lights up the MERGED histograms, the policy sheds THAT worker
    down the ladder (journaled on both sides), holddown-free ticks
    escalate to rung 2 and finally drain — and the drained worker's keys
    re-home while the fleet keeps answering."""
    # arm the ROUTER-side recorder too: the decisions must journal
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "1")
    flight.reset_global()
    objectives = [{"name": "serve_p99", "label": "serve.submit_to_result",
                   "quantile": 99.0, "threshold_s": 0.05}]
    router = FleetRouter(
        workers=2, backend="verdict",
        env={"SERVE_MAX_WAIT_MS": "2", "CONSENSUS_SPECS_TPU_FLIGHT": "1"},
        objectives=objectives,
        policy=ShedPolicy(shed_burn=2.0, drain_burn=10000.0),
        holddown_s=0.0)
    try:
        pks = [_pk(3)]
        futs = [router.submit("fast_aggregate", pks, bytes([i]) * 32,
                              bytes([i]) * 96) for i in range(6)]
        [f.result(timeout=30) for f in futs]
        router.control_tick()  # baseline checkpoint: clean traffic

        # craft distinct traffic that all routes to ONE worker
        target, items, i = None, [], 50
        while len(items) < 6 and i < 250:
            msg, sig = bytes([i]) * 32, bytes([i]) * 96
            label = router.route_label(
                check_key("fast_aggregate", pks, msg, sig))
            if target is None:
                target = label
            if label == target:
                items.append((msg, sig))
            i += 1
        router.handle(target).inject_fault(calls=64, mode="slow", ms=150)
        futs = [router.submit("fast_aggregate", pks, m, s)
                for m, s in items]
        assert all(f.result(timeout=60) for f in futs)

        time.sleep(1.1)  # checkpoint spacing
        tick = router.control_tick()
        assert tick["decisions"], f"no decision: {tick['slo']}"
        d = tick["decisions"][0]
        assert d["worker"] == target and d["action"] == "shed"
        assert d["rung_to"] == 1 and d["burn"] >= 2.0
        snap = router.poll_snapshots()[target]
        assert snap["extra"]["ladder_rung"] == 1

        # escalate: rung 2, then (still burning at the bottom) drain
        d2 = router.control_tick()["decisions"][0]
        assert (d2["action"], d2["rung_to"]) == ("shed", 2)
        d3 = router.control_tick()["decisions"][0]
        assert d3["action"] == "drain"
        assert router.live_workers == [w for w in ("w0", "w1")
                                       if w != target]

        # reconstruction: decision events + the worker's own transitions
        events = [json.loads(l) for l in
                  router.journal_jsonl().splitlines()[1:]]
        fleet_kinds = [e["kind"] for e in events if e["plane"] == "fleet"]
        assert fleet_kinds.count("shed") == 2 and "drain" in fleet_kinds
        transitions = [e["data"] for e in events
                       if e["kind"] == "shed_rung"
                       and e.get("worker") == target]
        assert [(t["rung_from"], t["rung_to"]) for t in transitions] == \
            [(0, 1), (1, 2)]

        # the survivor still answers (the drained arc re-homed)
        fut = router.submit("fast_aggregate", pks, b"\xee" * 32,
                            b"\xdd" * 96)
        assert fut.result(timeout=30) is True
        assert router.sheds == 2 and router.drains == 1
    finally:
        router.close()
        flight.reset_global()


def test_drain_answers_submits_already_on_the_pipe():
    """A submit that routed to a worker just before its drain (the ring
    read races ring.remove) is still answered: the worker keeps reading
    until stdin EOF instead of breaking out at the drain op."""
    router = FleetRouter(workers=1, backend="verdict",
                         env={"SERVE_MAX_WAIT_MS": "2"})
    try:
        h = router.handle("w0")
        h.rpc({"op": "drain"}, timeout=10)
        # the drain is acked but stdin is still open — this submit sits
        # behind it on the pipe, exactly the shed-to-drain race window
        fut = h.submit("fast_aggregate", [_pk(1)], b"\x02" * 32,
                       b"\x03" * 96)
        assert fut.result(timeout=30) is True
    finally:
        router.close()


def test_crashed_worker_is_reaped_from_the_ring(monkeypatch):
    """A kill -9 (not a drain) must not black-hole the dead worker's key
    arc: the next control tick evicts it from the ring, journals
    worker_lost, and the survivor answers the re-homed keys."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "1")
    flight.reset_global()
    router = FleetRouter(workers=2, backend="verdict",
                         env={"SERVE_MAX_WAIT_MS": "2"})
    try:
        victim = router.route_label(b"\xaa" * 32)
        router.handle(victim)._proc.kill()
        router.handle(victim)._proc.wait(timeout=10)
        router.control_tick()
        assert victim not in router.live_workers
        survivor = [w for w in ("w0", "w1") if w != victim][0]
        # the dead arc re-homed: every key now routes to the survivor
        for i in range(16):
            assert router.route_label(bytes([i]) * 32) == survivor
        fut = router.submit("fast_aggregate", [_pk(9)], b"\xaa" * 32,
                            b"\xbb" * 96)
        assert fut.result(timeout=30) is True
        lost = [e for e in router.journal_jsonl().splitlines()[1:]
                if json.loads(e)["kind"] == "worker_lost"]
        assert len(lost) == 1
        assert json.loads(lost[0])["data"]["worker"] == victim
    finally:
        router.close()
        flight.reset_global()


# -- flight dump collision fix (satellite) ------------------------------------


def test_flight_dump_paths_are_worker_suffixed(tmp_path, monkeypatch):
    base = str(tmp_path / "flight_dump.jsonl")
    monkeypatch.delenv(flight.WORKER_ENV, raising=False)
    assert flight.resolve_dump_path(base) == base  # untouched outside
    monkeypatch.setenv(flight.WORKER_ENV, "w3")
    resolved = flight.resolve_dump_path(base)
    import os

    assert resolved.endswith(f".w3-pid{os.getpid()}.jsonl")
    rec = flight.FlightRecorder()
    rec.note("serve", "flush", items=1)
    written = rec.dump(base, reason="test")
    assert written == resolved and os.path.exists(written)
    # two "processes" (labels) sharing one configured path never collide
    monkeypatch.setenv(flight.WORKER_ENV, "w4")
    assert flight.resolve_dump_path(base) != resolved


def test_fleet_gauges_are_registered_and_documented_shapes():
    for name in ("fleet.workers", "fleet.snapshots", "fleet.requests",
                 "fleet.sheds", "fleet.drains", "serve.ladder_rung"):
        assert registry.known(name), f"{name} unregistered"
    # the worker-namespaced serve family resolves for fleet labels too
    assert registry.known("serve[w0].submit_to_result")
    assert registry.node_label("serve.ladder_rung", "w1") == \
        "serve[w1].ladder_rung"


def test_slo_tracker_accepts_explicit_hists():
    from consensus_specs_tpu.obs.hist import Histogram

    h = Histogram()
    for v in (0.01, 0.02, 5.0):
        h.observe(v)
    clock = [0.0]
    tracker = SloTracker(
        objectives=[{"name": "serve_p99",
                     "label": "serve.submit_to_result",
                     "quantile": 99.0, "threshold_s": 1.0}],
        clock=lambda: clock[0])
    tracker.evaluate(hists={"serve.submit_to_result": Histogram()},
                     export=False)
    clock[0] = 120.0
    out = tracker.evaluate(hists={"serve.submit_to_result": h},
                           export=False)["serve_p99"]
    assert out["n"] == 3 and out["ok"] is False
    # burn: 1 over of 3 in the window, budget 1% -> ~33x
    assert out["burn_rate"]["60s"] == pytest.approx((1 / 3) / 0.01)
    # export=False kept the slo.* gauges untouched
    assert "slo.ok" not in profiling.stats_and_gauges()[1]
