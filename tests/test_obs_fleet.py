"""Fleet observability (ISSUE 7): the per-device occupancy ledger
(obs/devices.py), the cross-plane flight recorder (obs/flight.py) with its
fault-triggered JSONL dump, and SLO burn-rate tracking (obs/slo.py) with
the upgraded /healthz. Everything runs against crypto-free backends so
tier-1 stays fast; the real-crypto glue is `make serve-trace` /
`make serve-bench`.
"""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from consensus_specs_tpu.obs import devices, flight, slo, tracing
from consensus_specs_tpu.obs.exposition import start_exposition
from consensus_specs_tpu.ops import profiling
from consensus_specs_tpu.serve import VerificationService
from consensus_specs_tpu.serve.load import (BAD_SIGNATURE,
                                            FailingBackendProxy,
                                            VerdictBackend)
from consensus_specs_tpu.utils import bls

PK = b"\x01" * 48


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_TRACE", "0")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "0")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_DEVICES", "0")
    monkeypatch.delenv("CONSENSUS_SPECS_TPU_SLO", raising=False)
    profiling.reset()
    tracing.reset_global()
    devices.reset_global()
    flight.reset_global()
    slo.reset_global()
    was = bls.bls_active
    bls.bls_active = True
    yield
    bls.bls_active = was
    tracing.reset_global()
    devices.reset_global()
    flight.reset_global()
    slo.reset_global()


class RlcVerdictBackend(VerdictBackend):
    """VerdictBackend + the RLC entry point, so the serve default route
    (and therefore the FULL degradation ladder: RLC -> per-group ->
    oracle) is exercisable with crypto-free verdicts."""

    def batch_verify_rlc(self, items, mesh=None, rng=None):
        self.calls += 1
        return [bytes(sig) != BAD_SIGNATURE
                for _kind, _pks, _msgs, sig in items]


class _Oracle:
    def verify_one(self, pending):
        return bytes(pending.signature) != BAD_SIGNATURE


def _svc(backend, **kw):
    kw.setdefault("bucket_fn", lambda k: 8)
    kw.setdefault("oracle", _Oracle())
    return VerificationService(backend=backend, **kw)


# -- device occupancy ledger --------------------------------------------------


def test_ledger_accumulates_busy_time_per_lane():
    t = {"now": 100.0}
    led = devices.DeviceLedger(clock=lambda: t["now"])
    led.note_busy(0, 100.0, 100.5, label="vm")
    led.note_busy(0, 100.5, 100.75, label="vm")
    led.note_busy(devices.HOST_LANE, 100.0, 100.25, label="prep")
    t["now"] = 101.0  # 1s elapsed
    util = led.utilization()
    assert util["0"] == pytest.approx(0.75)
    assert util["host"] == pytest.approx(0.25)
    snap = led.snapshot()
    assert snap["lanes"]["0"]["events"] == 2
    assert snap["lanes"]["0"]["busy_s"] == pytest.approx(0.75)
    assert snap["lanes"]["host"]["utilization"] == pytest.approx(0.25)
    tl = led.timeline()
    assert ("0", "vm", 100.0, 100.5) in tl
    assert ("host", "prep", 100.0, 100.25) in tl


def test_ledger_note_execution_maps_meshless_runs_to_device_zero():
    led = devices.DeviceLedger(clock=lambda: 0.0)
    led.note_execution(None, 1.0, 0.5, label="vm[steps=64]")
    assert led.snapshot()["lanes"] == {
        "0": {"busy_s": 0.5, "utilization": 1.0, "events": 1}}


def test_ledger_gauges_use_registered_families():
    from consensus_specs_tpu.obs import registry

    led = devices.DeviceLedger()
    led.note_busy(0, 0.0, 0.1)
    led.note_busy(devices.HOST_LANE, 0.0, 0.1)
    led.export_gauges()
    summ = profiling.summary()
    assert summ["device.count"] == {"gauge": 2.0}
    assert "device[0]" in summ and "device[host]" in summ
    for label in ("device.count", "device.busy_s", "device[0]",
                  "device[host]"):
        assert registry.known(label), label


def test_serve_prep_stage_feeds_the_host_lane(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_DEVICES", "1")
    devices.reset_global()
    with _svc(RlcVerdictBackend(), max_batch=4, max_wait_ms=5) as svc:
        futs = [svc.submit("fast_aggregate", [PK], b"m%d" % i, b"ok")
                for i in range(8)]
        assert all(f.result(timeout=10) for f in futs)
    snap = devices.global_ledger().snapshot()
    assert "host" in snap["lanes"] and snap["lanes"]["host"]["events"] >= 1


def test_disabled_ledger_is_a_none_check(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_DEVICES", "0")
    assert devices.maybe_ledger() is None
    with _svc(RlcVerdictBackend(), max_batch=1, max_wait_ms=0) as svc:
        assert svc._devices is None
        assert svc.submit("fast_aggregate", [PK], b"m", b"ok").result(
            timeout=10) is True


def test_occupancy_lane_rides_the_chrome_trace(monkeypatch, tmp_path):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_DEVICES", "1")
    devices.reset_global()
    tracer = tracing.global_tracer()
    led = devices.global_ledger()
    led.note_busy(0, tracer._t0 + 0.001, tracer._t0 + 0.002, label="vm")
    led.note_busy(devices.HOST_LANE, tracer._t0, tracer._t0 + 0.001,
                  label="prep")
    path = tracing.dump_trace(str(tmp_path / "trace.json"))
    with open(path) as fh:
        doc = json.load(fh)
    lane = [e for e in doc["traceEvents"] if e.get("pid") == 3]
    assert any(e["ph"] == "M" and e["args"].get("name") == "device-occupancy"
               for e in lane)
    xs = [e for e in lane if e["ph"] == "X"]
    assert {e["args"]["lane"] for e in xs} == {"0", "host"}
    assert all(e["ts"] >= 0 and e["dur"] > 0 for e in xs)


# -- flight recorder ----------------------------------------------------------


def test_flight_ring_is_bounded_and_counts_drops():
    rec = flight.FlightRecorder(capacity=4, clock=lambda: 1.0)
    for i in range(10):
        rec.note("serve", "flush", items=i)
    events = rec.events()
    assert len(events) == 4
    assert [e["data"]["items"] for e in events] == [6, 7, 8, 9]
    c = rec.counters()
    assert c["events"] == 10 and c["dropped"] == 6 and c["retained"] == 4


def test_flight_dump_jsonl_roundtrip(tmp_path):
    rec = flight.FlightRecorder(capacity=16, clock=lambda: 2.5)
    rec.note("chain", "on_block", slot=7, root="ab" * 8)
    rec.note("vm", "assembly_stall", key="hard_part[k=0,fold=32]",
             seconds=6.2)
    path = rec.dump(str(tmp_path / "flight.jsonl"), reason="test")
    lines = [json.loads(l) for l in open(path).read().splitlines()]
    assert lines[0] == {"flight": "v1", "reason": "test", "events": 2,
                        "retained": 2, "dropped": 0}
    assert lines[1]["plane"] == "chain" and lines[1]["kind"] == "on_block"
    assert lines[1]["data"]["slot"] == 7 and lines[1]["seq"] == 1
    assert lines[2]["data"]["key"] == "hard_part[k=0,fold=32]"
    rec.export_gauges()
    summ = profiling.summary()
    assert summ["flight.events"] == {"gauge": 2.0}
    assert summ["flight.dumps"] == {"gauge": 1.0}


def test_flight_off_path_is_a_none_check_and_overhead_is_bounded(
        monkeypatch):
    """The PR 4 zero-cost bar: with the recorder off the service stores
    None (no locks, env reads, or allocations join the hot path); with it
    on, the per-event cost stays at deque-append scale. Both sides are
    measured so the overhead claim is a number, not an assertion."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "0")
    with _svc(RlcVerdictBackend(), max_batch=1, max_wait_ms=0) as svc:
        assert svc._flight is None
    assert flight.maybe_recorder() is None

    n = 20_000
    # OFF path: the exact branch every hot-path site runs when disabled —
    # one attribute load + identity check, no locks/env reads/allocations
    off_guard = None
    t0 = time.perf_counter()
    acc = 0
    for _ in range(n):
        if off_guard is not None:  # pragma: no cover - never taken
            acc += 1
    per_off = (time.perf_counter() - t0) / n
    # ON path
    rec = flight.FlightRecorder(capacity=4096)
    t0 = time.perf_counter()
    for i in range(n):
        rec.note("serve", "flush", items=i)
    per_event = (time.perf_counter() - t0) / n
    # deque-append scale: microseconds, not milliseconds (generous bounds
    # so a loaded CI host never flaps); both sides measured so the
    # overhead claim is a number, not an assertion
    print(f"flight overhead: off {per_off * 1e9:.0f}ns/event, "
          f"on {per_event * 1e6:.2f}us/event")
    assert per_off < 1e-5, f"off-path guard cost {per_off * 1e9:.0f}ns"
    assert per_event < 1e-3, f"flight note cost {per_event * 1e6:.1f}us"
    assert rec.counters()["events"] == n


def test_flight_ring_env_tolerates_malformed_values(monkeypatch):
    """A typo'd CONSENSUS_SPECS_TPU_FLIGHT_RING must degrade to the
    default capacity, never crash the service construction that armed
    the recorder."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "1")
    for bad in ("4k", "", "-5"):
        monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT_RING", bad)
        flight.reset_global()
        rec = flight.maybe_recorder()
        assert rec is not None
        assert rec._ring.maxlen == flight.DEFAULT_RING
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT_RING", "16")
    flight.reset_global()
    assert flight.maybe_recorder()._ring.maxlen == 16


def test_flightdump_endpoint_serves_jsonl_and_404s_when_off(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "1")
    flight.reset_global()
    flight.note("serve", "flush", items=3)
    with start_exposition(port=0) as server:
        with urllib.request.urlopen(server.url("/flightdump"),
                                    timeout=30) as resp:
            body = resp.read().decode()
        lines = [json.loads(l) for l in body.splitlines()]
        assert lines[0]["flight"] == "v1"
        assert lines[1]["kind"] == "flush"
        monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "0")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(server.url("/flightdump"), timeout=30)


def test_injected_serve_fault_dumps_a_ladder_reconstruction(
        monkeypatch, tmp_path):
    """The ISSUE 7 acceptance path: BAD_SIGNATURE traffic (serve/load.py)
    flows while an injected backend failure poisons the first flush
    repeatedly; the flight dump written ON the fault must reconstruct the
    degradation-ladder transition — flush, RLC retry, RLC->per-group,
    group retry, ->oracle — in journal order."""
    dump_path = str(tmp_path / "fault.jsonl")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "1")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT_DUMP", dump_path)
    flight.reset_global()
    # calls 1+2 poison the RLC attempt+retry, 3+4 the per-group
    # attempt+retry -> the ladder bottoms out on the oracle and dumps
    backend = FailingBackendProxy(RlcVerdictBackend(),
                                  fail_calls=(1, 2, 3, 4))
    with _svc(backend, max_batch=4, max_wait_ms=10_000,
              backend_retries=1) as svc:
        futs = [
            svc.submit("fast_aggregate", [PK], b"m0", b"ok"),
            svc.submit("fast_aggregate", [PK], b"m1", BAD_SIGNATURE),
            svc.submit("fast_aggregate", [PK], b"m2", b"ok"),
            svc.submit("fast_aggregate", [PK], b"m3", b"ok"),
        ]
        results = [f.result(timeout=30) for f in futs]
    # stream integrity survived the full degradation
    assert results == [True, False, True, True]
    assert backend.fired == 4
    assert os.path.exists(dump_path), "fault did not dump the journal"
    lines = [json.loads(l) for l in open(dump_path).read().splitlines()]
    assert lines[0]["reason"] == "serve_backend_degraded_to_oracle"
    kinds = [(e["plane"], e["kind"]) for e in lines[1:]]
    ladder = [("serve", "flush"),
              ("serve", "backend_retry"),          # rlc retry
              ("serve", "degraded_rlc_to_groups"),
              ("serve", "backend_retry"),          # per-group retry
              ("serve", "degraded_to_oracle"),
              ("flight", "fault")]
    it = iter(kinds)
    assert all(step in it for step in ladder), (
        f"ladder not reconstructable from {kinds}"
    )
    stages = [e["data"].get("stage") for e in lines[1:]
              if e["kind"] == "backend_retry"]
    assert stages == ["rlc", "group"]
    # seq strictly increases: the journal is ordered evidence
    seqs = [e["seq"] for e in lines[1:]]
    assert seqs == sorted(seqs)


# -- SLO tracking -------------------------------------------------------------


def test_slo_objectives_env_overrides(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_SLO",
                       "serve_p99_ms=120,chain_p99_ms=77")
    objs = {o["name"]: o for o in slo.declared_objectives()}
    assert objs["serve_p99"]["threshold_s"] == pytest.approx(0.120)
    assert objs["chain_p99"]["threshold_s"] == pytest.approx(0.077)


def test_slo_vacuously_ok_with_no_traffic():
    tracker = slo.SloTracker(clock=lambda: 0.0)
    out = tracker.evaluate()
    assert all(e["ok"] and e["n"] == 0 for e in out.values())
    summ = profiling.summary()
    assert summ["slo.ok"] == {"gauge": 1.0}
    assert summ["slo.violations"] == {"gauge": 0.0}


def test_slo_violation_and_margin(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_SLO", "serve_p99_ms=50")
    for _ in range(100):
        profiling.record_latency("serve.submit_to_result", 0.010)
    for _ in range(10):  # 9% of traffic way over the 50ms objective
        profiling.record_latency("serve.submit_to_result", 0.500)
    tracker = slo.SloTracker(clock=lambda: 0.0)
    out = tracker.evaluate()
    serve = out["serve_p99"]
    assert serve["n"] == 110 and not serve["ok"]
    assert serve["attained_ms"] > 50.0
    assert serve["margin"] < 1.0
    assert serve["bad_fraction"] == pytest.approx(10 / 110, abs=1e-6)
    summ = profiling.summary()
    assert summ["slo.ok"] == {"gauge": 0.0}
    assert summ["slo.violations"] == {"gauge": 1.0}


def test_slo_multi_window_burn_rates_see_a_fresh_burst(monkeypatch):
    """A burst of errors inside the fast window burns hot against the
    60s window while the 300s window (which also saw the clean history)
    burns slower — the multi-window page/ticket split."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_SLO", "serve_p99_ms=50")
    t = {"now": 0.0}
    tracker = slo.SloTracker(clock=lambda: t["now"])
    tracker.evaluate()  # empty baseline checkpoint at t=0
    t["now"] = 10.0
    for _ in range(980):
        profiling.record_latency("serve.submit_to_result", 0.010)
    t["now"] = 280.0
    tracker.evaluate()  # clean checkpoint inside the slow window only
    t["now"] = 290.0    # burst now: 50% of fresh traffic is over-objective
    for _ in range(10):
        profiling.record_latency("serve.submit_to_result", 0.500)
    for _ in range(10):
        profiling.record_latency("serve.submit_to_result", 0.010)
    out = tracker.evaluate()
    burn = out["serve_p99"]["burn_rate"]
    # fast window: 10 bad / 20 new = 0.5 bad fraction over a 0.01 budget
    assert burn["60s"] == pytest.approx(50.0)
    # slow window baseline is t=0: 10 bad / 1000 new = 1.0x burn
    assert burn["300s"] == pytest.approx(1.0)
    assert profiling.summary()["slo.worst_burn_rate"] == {"gauge": 50.0}


def test_healthz_reports_slo_state(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_SLO", "serve_p99_ms=50")
    slo.reset_global()
    for _ in range(50):
        profiling.record_latency("serve.submit_to_result", 0.200)
    with start_exposition(port=0) as server:
        with urllib.request.urlopen(server.url("/healthz"),
                                    timeout=30) as resp:
            body = json.loads(resp.read().decode())
    assert body["ok"] is False  # violated objective flips liveness detail
    assert body["slo"]["serve_p99"]["ok"] is False
    assert body["slo"]["chain_p99"]["ok"] is True  # vacuous


def test_slo_bench_flow_reports_nonzero_burn(monkeypatch):
    """The bench path (reset -> baseline evaluate -> run -> section):
    violations during the run must show up as burn, not the structural
    0.0 a single end-of-run evaluate would produce with no baseline."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_SLO", "serve_p99_ms=50")
    slo.reset_global()
    slo.global_tracker().evaluate()  # the baseline the benches record
    for _ in range(80):
        profiling.record_latency("serve.submit_to_result", 0.010)
    for _ in range(20):
        profiling.record_latency("serve.submit_to_result", 0.500)
    section = slo.global_tracker().bench_section()
    serve = section["serve_p99"]
    assert serve["ok"] is False
    # 20 bad / 100 in-run over a 0.01 budget
    assert serve["burn_rate"]["60s"] == pytest.approx(20.0)


def test_slo_bench_section_shape():
    for _ in range(64):
        profiling.record_latency("serve.submit_to_result", 0.020)
    section = slo.global_tracker().bench_section()
    serve = section["serve_p99"]
    assert serve["ok"] is True and serve["n"] == 64
    assert serve["margin"] > 1.0
    assert set(serve["burn_rate"]) == {"60s", "300s"}
    assert "margin" not in section["chain_p99"]  # no traffic, no margin


# -- concurrent scrape over the whole fleet plane -----------------------------


def test_fleet_writers_vs_scrape_hammer(monkeypatch):
    """Histogram writers + flight notes + device intervals racing /metrics
    and /healthz scrapes: no exceptions, consistent totals."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "1")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_DEVICES", "1")
    flight.reset_global()
    devices.reset_global()
    errors = []
    stop = threading.Event()
    n_threads, iters = 3, 300

    def writer(tid):
        try:
            for i in range(iters):
                profiling.record_latency("serve.submit_to_result",
                                         0.001 * (i % 7 + 1))
                flight.note("serve", "flush", items=i)
                devices.global_ledger().note_busy(tid, i * 1e-4,
                                                  i * 1e-4 + 5e-5)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader(server):
        try:
            while not stop.is_set():
                urllib.request.urlopen(server.url("/metrics"),
                                       timeout=30).read()
                urllib.request.urlopen(server.url("/healthz"),
                                       timeout=30).read()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    with start_exposition(port=0) as server:
        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(n_threads)]
        r = threading.Thread(target=reader, args=(server,))
        r.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        stop.set()
        r.join(30)
    assert errors == []
    assert flight.global_recorder().counters()["events"] == n_threads * iters
    lat = profiling.latency_summary()["serve.submit_to_result"]
    assert lat["n"] == n_threads * iters
    assert len(devices.global_ledger().snapshot()["lanes"]) == n_threads
