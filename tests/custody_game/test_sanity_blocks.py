from consensus_specs_tpu.test.custody_game.sanity.test_blocks import *  # noqa: F401,F403
