from consensus_specs_tpu.test.custody_game.block_processing.test_process_attestation import *  # noqa: F401,F403
