from consensus_specs_tpu.test.custody_game.epoch_processing.test_custody_epoch_passes import *  # noqa: F401,F403
