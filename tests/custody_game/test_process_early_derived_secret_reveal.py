from consensus_specs_tpu.test.custody_game.block_processing.test_process_early_derived_secret_reveal import *  # noqa: F401,F403
