from consensus_specs_tpu.test.phase0.epoch_processing.test_process_registry_updates import *  # noqa: F401,F403
