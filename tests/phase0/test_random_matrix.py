from consensus_specs_tpu.test.phase0.random.test_random_matrix import *  # noqa: F401,F403
