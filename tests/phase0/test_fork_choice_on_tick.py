from consensus_specs_tpu.test.phase0.unittests.fork_choice.test_on_tick import *  # noqa: F401,F403
