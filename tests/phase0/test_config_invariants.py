from consensus_specs_tpu.test.phase0.unittests.test_config_invariants import *  # noqa: F401,F403
