from consensus_specs_tpu.test.phase0.random.test_random import *  # noqa: F401,F403
