from consensus_specs_tpu.test.phase0.finality.test_finality import *  # noqa: F401,F403
