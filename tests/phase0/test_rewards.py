from consensus_specs_tpu.test.phase0.rewards.test_rewards import *  # noqa: F401,F403
