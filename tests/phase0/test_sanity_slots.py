from consensus_specs_tpu.test.phase0.sanity.test_slots import *  # noqa: F401,F403
