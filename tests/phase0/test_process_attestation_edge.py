from consensus_specs_tpu.test.phase0.block_processing.test_process_attestation_edge import *  # noqa: F401,F403
