from consensus_specs_tpu.test.phase0.epoch_processing.test_process_justification_and_finalization import *  # noqa: F401,F403
