from consensus_specs_tpu.test.phase0.fork_choice.test_get_head import *  # noqa: F401,F403
