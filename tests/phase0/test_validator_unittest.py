from consensus_specs_tpu.test.phase0.unittests.test_validator_unittest import *  # noqa: F401,F403
