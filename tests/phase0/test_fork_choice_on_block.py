from consensus_specs_tpu.test.phase0.fork_choice.test_on_block import *  # noqa: F401,F403
