from consensus_specs_tpu.test.phase0.unittests.test_weak_subjectivity import *  # noqa: F401,F403
