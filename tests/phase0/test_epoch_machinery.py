from consensus_specs_tpu.test.phase0.unittests.test_epoch_machinery import *  # noqa: F401,F403
