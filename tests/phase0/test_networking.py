from consensus_specs_tpu.test.phase0.unittests.test_networking import *  # noqa: F401,F403
