from consensus_specs_tpu.test.phase0.genesis.test_genesis import *  # noqa: F401,F403
