from consensus_specs_tpu.test.phase0.block_processing.test_process_randao import *  # noqa: F401,F403
