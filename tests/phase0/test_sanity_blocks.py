from consensus_specs_tpu.test.phase0.sanity.test_blocks import *  # noqa: F401,F403
