from consensus_specs_tpu.test.phase0.block_processing.test_process_voluntary_exit import *  # noqa: F401,F403
