"""Tier-1 coverage for the mainnet-scale workload plane (ISSUE 20):
registry determinism + spec-shuffle equivalence, lazy iteration memory
bounds, committee-affinity routing, and the hierarchical verify
path's accounting. Crypto is kept to a handful of tiny keys so the
whole module stays inside the tier-1 budget; the pubkey-plane LRU
has its own module, test_scale_pubkeys.py."""
import hashlib
import tracemalloc

import pytest

from consensus_specs_tpu.scale import hierarchy, pubkeys, registry, routing
from consensus_specs_tpu.scale.registry import Registry, shuffle_batch


# ---------------------------------------------------------------------------
# registry: determinism + spec equivalence
# ---------------------------------------------------------------------------


def test_registry_digest_is_seed_deterministic():
    a = Registry(24, seed=7).digest()
    b = Registry(24, seed=7).digest()
    c = Registry(24, seed=8).digest()
    assert a == b
    assert a != c
    # sampled digests are deterministic too (the 1M bench's form)
    assert (Registry(24, seed=7).digest(sample=5)
            == Registry(24, seed=7).digest(sample=5))


def test_registry_secret_keys_distinct_and_small():
    reg = Registry(1 << 20, seed=3)
    sks = {reg.secret_key(i) for i in (0, 1, 5, (1 << 20) - 1)}
    assert len(sks) == 4
    assert all(0 < sk < (1 << 40) for sk in sks)
    with pytest.raises(IndexError):
        reg.secret_key(1 << 20)


def test_shuffle_batch_matches_spec_minimal_and_mainnet():
    from consensus_specs_tpu.builder import build_spec_module

    seed = hashlib.sha256(b"scale-shuffle-equivalence").digest()
    for preset, n in (("minimal", 97), ("mainnet", 65)):
        spec = build_spec_module("phase0", preset)
        rounds = int(spec.SHUFFLE_ROUND_COUNT)
        mine = shuffle_batch(n, seed, rounds)
        ref = [int(spec.compute_shuffled_index(
            spec.uint64(i), spec.uint64(n), seed)) for i in range(n)]
        assert mine.tolist() == ref


def test_registry_committees_match_spec_compute_committee():
    from consensus_specs_tpu.builder import build_spec_module

    spec = build_spec_module("phase0", "mainnet")
    # pin the registry's baked-in mainnet constants against specsrc
    assert registry.SLOTS_PER_EPOCH == int(spec.SLOTS_PER_EPOCH)
    assert registry.MAX_COMMITTEES_PER_SLOT == int(
        spec.MAX_COMMITTEES_PER_SLOT)
    assert registry.TARGET_COMMITTEE_SIZE == int(spec.TARGET_COMMITTEE_SIZE)
    assert registry.SHUFFLE_ROUND_COUNT == int(spec.SHUFFLE_ROUND_COUNT)

    n, slot = 131, 5
    reg = Registry(n, seed=11)
    per_slot = reg.committees_per_slot()
    assert per_slot == 1  # below the target size floor
    seed = reg.attester_seed(slot // registry.SLOTS_PER_EPOCH)
    count = per_slot * registry.SLOTS_PER_EPOCH
    flat = (slot % registry.SLOTS_PER_EPOCH) * per_slot
    indices = [spec.uint64(i) for i in range(n)]
    ref = [int(v) for v in spec.compute_committee(
        indices, seed, spec.uint64(flat), spec.uint64(count))]
    assert reg.committee(slot, 0).tolist() == ref


def test_committee_fanout_covers_registry_once_per_epoch():
    reg = Registry(4096, seed=2, shuffle_rounds=4)
    seen = []
    for slot in range(registry.SLOTS_PER_EPOCH):
        for com in reg.committees_at_slot(slot):
            seen.extend(int(v) for v in com)
    assert sorted(seen) == list(range(4096))
    assert registry.attesters_per_slot(4096) == 128
    assert registry.committee_count_per_slot(1 << 20) == 64


def test_registry_lazy_iteration_is_memory_bounded():
    # a million-validator registry + one epoch permutation must stay
    # columnar: the uint64 column is 8 MB; the budget leaves headroom
    # for numpy temporaries but is far below any per-validator
    # materialization (1M Python ints alone would be ~28 MB+)
    tracemalloc.start()
    try:
        reg = Registry(1 << 20, seed=5, shuffle_rounds=2)
        com = reg.committee(0, 0)
        assert len(com) == (1 << 20) // (32 * 64)
        # streaming the index column in batches must not accumulate
        count = 0
        for idx, _pks in Registry(256, seed=5).iter_pubkeys(batch=64):
            count += len(idx)
        assert count == 256
        _, peak = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    assert peak < 64 * (1 << 20), f"peak {peak} bytes: not columnar"


# ---------------------------------------------------------------------------
# routing: committee affinity on the consistent-hash ring
# ---------------------------------------------------------------------------


class _FakeRouter:
    def __init__(self, labels):
        from consensus_specs_tpu.serve.fleet import HashRing
        import threading

        self._ring = HashRing()
        for lb in labels:
            self._ring.add(lb)
        self._lock = threading.Lock()
        self.requests = 0
        self.submitted = []

    def route_label(self, key):
        return self._ring.route(key)

    def handle(self, label):
        router = self

        class _H:
            def submit(self, kind, pks, msgs, sig, birth_s=None,
                       flow_id=None):
                from concurrent.futures import Future

                router.submitted.append(label)
                fut = Future()
                fut.set_result(True)
                return fut

        return _H()


def test_committee_affinity_is_stable_and_counts_moves():
    fake = _FakeRouter(["w0", "w1", "w2"])
    fleet = routing.CommitteeFleet(router=fake)
    first = fleet.assignment(range(32))
    # stable: resubmitting every committee lands the same worker
    for ci in range(32):
        fleet.submit_committee(ci, "fast_aggregate", [b"\x22" * 48],
                               b"m" * 32, b"\x11" * 96)
    assert fleet.assignment(range(32)) == first
    assert fleet.affinity_moves == 0
    assert fleet.committees_routed == 32
    assert len(set(first.values())) > 1  # committees actually spread

    # ring churn moves only the drained worker's committees
    fake._ring.remove("w1")
    moved = sum(1 for ci, lb in first.items()
                if fleet.label_for(ci) != lb)
    assert moved == sum(1 for lb in first.values() if lb == "w1")
    for ci in range(32):
        fleet.submit_committee(ci, "fast_aggregate", [b"\x22" * 48],
                               b"m" * 32, b"\x11" * 96)
    assert fleet.affinity_moves == moved


# ---------------------------------------------------------------------------
# hierarchy: slot fold accounting + bisection localization
# ---------------------------------------------------------------------------


def test_verify_slot_accounting_and_bad_committee_localization():
    reg = Registry(64, seed=13, slots_per_epoch=8, target_size=2,
                   shuffle_rounds=4)
    assert reg.committees_per_slot() == 4
    items = hierarchy.committee_items(reg, slot=3)
    bad_ci = 2
    items[bad_ci] = hierarchy.corrupt_item(items[bad_ci])

    plane = pubkeys.PubkeyPlane(budget_bytes=1 << 30, mirror_backend=True)
    report = hierarchy.verify_slot(items, slot=3, plane=plane)
    assert report.committees == 4
    assert report.attestations == sum(len(it[1]) for it in items)
    assert report.bad_committees == [bad_ci]
    assert report.bisections >= 1  # the slot root failed and split
    assert report.pubkey_misses > 0 and report.pubkey_hits == 0

    flat = hierarchy.verify_slot_flat(items)
    oracle = hierarchy.verify_slot_oracle(items)
    assert report.verdicts.tolist() == flat.tolist() == oracle.tolist()

    # all-valid slot: ONE combine, ONE final exp, no bisection; the
    # pubkey plane serves the whole slot from residency
    good = hierarchy.committee_items(reg, slot=3)
    report2 = hierarchy.verify_slot(good, slot=3, plane=plane)
    assert report2.all_valid and not report2.bad_committees
    assert report2.combines == 1 and report2.bisections == 0
    assert report2.final_exps_per_slot == 1.0
    assert report2.pubkey_hits > 0 and report2.pubkey_misses == 0
