"""tools/bench_compare.py — the round-over-round perf regression gate.

Pins the selection/comparability rules on synthetic BENCH_r*.json trees:
platform-keyed comparison (CPU fallbacks never score against TPU
windows), per-shape keys, per_mode_best joining, the skip conditions, and
the exit codes `make bench-compare` turns into a visible failure.
"""
import importlib.util
import json
import os

import pytest

_TOOL = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "bench_compare.py")


@pytest.fixture(scope="module")
def bc():
    spec = importlib.util.spec_from_file_location("bench_compare_under_test",
                                                  _TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_round(tmp_path, n, parsed):
    doc = {"n": n, "rc": 0, "parsed": parsed}
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(doc))


def _parsed(value, platform="cpu", mode="committee", n=32, k=128, **extra):
    out = {"metric": "sigs/sec", "value": value, "vs_baseline": 0.1,
           "platform": platform, "mode": mode, "n": n, "k": k}
    out.update(extra)
    return out


def test_ok_within_threshold(tmp_path, bc, capsys):
    _write_round(tmp_path, 1, _parsed(300.0))
    _write_round(tmp_path, 2, _parsed(280.0))  # -6.7%
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "OK" in capsys.readouterr().out


def test_regression_past_threshold_fails(tmp_path, bc, capsys):
    _write_round(tmp_path, 1, _parsed(300.0))
    _write_round(tmp_path, 2, _parsed(150.0))  # -50%
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "FAIL" in out and "cpu:committee[32x128]" in out


def test_threshold_flag_tightens(tmp_path, bc):
    _write_round(tmp_path, 1, _parsed(300.0))
    _write_round(tmp_path, 2, _parsed(280.0))  # -6.7%
    assert bc.main(["--dir", str(tmp_path), "--max-regression", "5"]) == 1


def test_improvement_never_fails(tmp_path, bc):
    _write_round(tmp_path, 1, _parsed(300.0))
    _write_round(tmp_path, 2, _parsed(900.0))
    assert bc.main(["--dir", str(tmp_path), "--max-regression", "1"]) == 0


def test_platform_mismatch_skips(tmp_path, bc, capsys):
    """A CPU fallback round after a TPU window is ~10x slower for reasons
    that say nothing about the code — must SKIP, not FAIL."""
    _write_round(tmp_path, 1, _parsed(3170.0, platform="tpu"))
    _write_round(tmp_path, 2, _parsed(325.0, platform="cpu (fallback)"))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "SKIP" in capsys.readouterr().out


def test_cpu_fallback_compares_against_plain_cpu(tmp_path, bc):
    _write_round(tmp_path, 1, _parsed(300.0, platform="cpu"))
    _write_round(tmp_path, 2, _parsed(100.0, platform="cpu (fallback)"))
    assert bc.main(["--dir", str(tmp_path)]) == 1


def test_shape_keys_never_cross(tmp_path, bc):
    """The 4x8 liveness shape must not be scored against 32x128."""
    _write_round(tmp_path, 1, _parsed(9000.0, n=4, k=8))
    _write_round(tmp_path, 2, _parsed(300.0, n=32, k=128))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_per_mode_best_joins_comparison(tmp_path, bc, capsys):
    _write_round(tmp_path, 1, _parsed(
        300.0, per_mode_best={"committee[32x128]": 300.0, "epoch": 250.0}))
    _write_round(tmp_path, 2, _parsed(
        310.0, per_mode_best={"committee[32x128]": 310.0, "epoch": 50.0}))
    assert bc.main(["--dir", str(tmp_path)]) == 1  # epoch collapsed -80%
    assert "cpu:epoch" in capsys.readouterr().out


def test_head_mode_keys_by_tree_size(tmp_path, bc, capsys):
    """`--mode head` lines key as head[<blocks>] (matching the keys the
    head bench emits in per_mode_best), so a 64-block tree's heads/sec
    never scores against a 1024-block tree's — and the per-tree
    per_mode_best entries diff round over round."""
    head_line = _parsed(
        1_500_000.0, mode="head", n=None, k=None, blocks=1024,
        per_mode_best={"head[64]": 1_800_000.0, "head[1024]": 1_500_000.0})
    assert bc._shape_key(head_line) == "head[1024]"
    _write_round(tmp_path, 1, head_line)
    worse = _parsed(
        800_000.0, mode="head", n=None, k=None, blocks=1024,
        per_mode_best={"head[64]": 1_700_000.0, "head[1024]": 800_000.0})
    _write_round(tmp_path, 2, worse)
    assert bc.main(["--dir", str(tmp_path)]) == 1  # 47% drop at 1024
    out = capsys.readouterr().out
    assert "cpu:head[1024]" in out and "cpu:head[64]" in out
    # a different tree size is a different key, never compared
    _write_round(tmp_path, 3, _parsed(5.0, mode="head", n=None, k=None,
                                      blocks=4096))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_newest_without_usable_value_fails(tmp_path, bc, capsys):
    _write_round(tmp_path, 1, _parsed(300.0))
    _write_round(tmp_path, 2, {"value": 0.0, "error": "backend init hang"})
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "no usable parsed value" in capsys.readouterr().out


def test_unusable_previous_rounds_are_walked_past(tmp_path, bc, capsys):
    """An error round in the middle must not mask the last good baseline."""
    _write_round(tmp_path, 1, _parsed(300.0))
    _write_round(tmp_path, 2, {"value": 0.0, "error": "window died"})
    _write_round(tmp_path, 3, _parsed(100.0))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "BENCH_r01.json" in capsys.readouterr().out


def test_single_round_skips(tmp_path, bc):
    _write_round(tmp_path, 1, _parsed(300.0))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_empty_dir_skips(tmp_path, bc):
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_round_ordering_is_numeric_not_lexical(tmp_path, bc):
    """r2 vs r10 must order 2 < 10 (lexical would say '10' < '2')."""
    _write_round(tmp_path, 2, _parsed(300.0))
    _write_round(tmp_path, 10, _parsed(100.0))
    files = bc.round_files(str(tmp_path))
    assert [os.path.basename(f) for f in files] == [
        "BENCH_r02.json", "BENCH_r10.json"]
    assert bc.main(["--dir", str(tmp_path)]) == 1  # r10 regressed vs r02


def _slo_parsed(value, margin, ok, n=100, **extra):
    return _parsed(value, mode="serve", n=None, k=None,
                   slo={"serve_p99": {"ok": ok, "n": n, "margin": margin,
                                      "objective_ms": 5000.0,
                                      "attained_ms": 5000.0 / margin,
                                      "burn_rate": {"60s": 0.0}}},
                   **extra)


def test_slo_newly_violated_objective_fails(tmp_path, bc, capsys):
    """The SLO gate (ISSUE 7): a previously-met objective the newest
    round violates fails outright, even though throughput stayed flat."""
    _write_round(tmp_path, 1, _slo_parsed(300.0, margin=2.5, ok=True))
    _write_round(tmp_path, 2, _slo_parsed(300.0, margin=0.8, ok=False))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:slo:serve_p99" in out and "SLO VIOLATED" in out


def test_slo_margin_jitter_within_met_never_fails(tmp_path, bc, capsys):
    """Tail latencies flap far more than throughput: a big margin drop
    that still MEETS the objective is reported, not failed."""
    _write_round(tmp_path, 1, _slo_parsed(300.0, margin=9.0, ok=True))
    _write_round(tmp_path, 2, _slo_parsed(300.0, margin=1.4, ok=True))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "cpu:slo:serve_p99" in capsys.readouterr().out


def test_slo_still_violated_is_not_a_new_failure(tmp_path, bc):
    """ok False -> False: already red last round; the throughput gate
    still decides (a permanently-red objective must not wedge every
    future round — the VIOLATION round already failed once)."""
    _write_round(tmp_path, 1, _slo_parsed(300.0, margin=0.7, ok=False))
    _write_round(tmp_path, 2, _slo_parsed(300.0, margin=0.6, ok=False))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_slo_objectives_without_traffic_are_skipped(tmp_path, bc):
    quiet = _parsed(300.0, mode="serve", n=None, k=None,
                    slo={"chain_p99": {"ok": True, "n": 0,
                                       "objective_ms": 2000.0,
                                       "attained_ms": 0.0,
                                       "burn_rate": {}}})
    assert bc.extract_slo({"parsed": quiet}) == {}


def test_slo_gate_reached_without_common_throughput_keys(tmp_path, bc,
                                                         capsys):
    """Shared SLO keys are comparables in their own right: two rounds
    with disjoint throughput shapes (say the head bench changed tree
    sizes) but the same declared objective must still gate a
    met -> violated transition instead of skipping."""
    _write_round(tmp_path, 1, _parsed(
        1000.0, mode="head", n=None, k=None, blocks=1024,
        slo={"chain_p99": {"ok": True, "n": 50, "margin": 3.0,
                           "objective_ms": 2000.0, "attained_ms": 666.0,
                           "burn_rate": {}}}))
    _write_round(tmp_path, 2, _parsed(
        900.0, mode="head", n=None, k=None, blocks=128,  # disjoint shape
        slo={"chain_p99": {"ok": False, "n": 50, "margin": 0.5,
                           "objective_ms": 2000.0, "attained_ms": 4000.0,
                           "burn_rate": {}}}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "SLO VIOLATED" in capsys.readouterr().out


def test_slo_only_previous_round_is_a_usable_baseline(tmp_path, bc,
                                                      capsys):
    """A prior round whose headline value is unusable (<=0) but whose slo
    section recorded objective state still baselines the SLO gate — the
    walk must not skip past it to 'no earlier round'."""
    broken_headline = _slo_parsed(300.0, margin=2.0, ok=True)
    broken_headline["value"] = 0.0  # headline unusable, slo intact
    _write_round(tmp_path, 1, broken_headline)
    _write_round(tmp_path, 2, _slo_parsed(300.0, margin=0.5, ok=False))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "SLO VIOLATED" in capsys.readouterr().out


def _sim_parsed(value, scenarios, **extra):
    """A `--mode sim` line: ``scenarios`` maps name -> (converged,
    heal_to_convergence_s)."""
    return _parsed(value, mode="sim", n=None, k=None,
                   sim={name: {"converged": conv,
                               "heal_to_convergence_s": heal,
                               "nodes": 4, "deliveries": 500}
                        for name, (conv, heal) in scenarios.items()},
                   **extra)


def test_sim_newly_diverging_scenario_fails(tmp_path, bc, capsys):
    """The simnet gate: a scenario that converged last round and
    diverges in the newest fails outright — differential convergence is
    a correctness claim, not a perf number."""
    _write_round(tmp_path, 1, _sim_parsed(
        1500.0, {"partition_heal": (True, 0.07),
                 "equivocation": (True, 6.1)}))
    _write_round(tmp_path, 2, _sim_parsed(
        1500.0, {"partition_heal": (False, 0.07),
                 "equivocation": (True, 6.2)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:sim:partition_heal" in out and "SIM DIVERGED" in out


def test_sim_heal_latency_jitter_never_fails(tmp_path, bc, capsys):
    """Heal-to-convergence latency movement within 'converged' is
    report-only, like SLO margin jitter."""
    _write_round(tmp_path, 1, _sim_parsed(
        1500.0, {"partition_heal": (True, 0.05)}))
    _write_round(tmp_path, 2, _sim_parsed(
        1500.0, {"partition_heal": (True, 4.90)}))  # 98x slower heal
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "cpu:sim:partition_heal" in capsys.readouterr().out


def test_sim_still_diverged_is_not_a_new_failure(tmp_path, bc):
    """converged False -> False: the divergence round already failed
    once; a permanently-red scenario must not wedge every future round."""
    _write_round(tmp_path, 1, _sim_parsed(
        1500.0, {"lossy_links": (False, 0.0)}))
    _write_round(tmp_path, 2, _sim_parsed(
        1500.0, {"lossy_links": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_sim_scenarios_join_without_common_throughput_keys(tmp_path, bc,
                                                           capsys):
    """Shared sim keys are comparables in their own right (the SLO
    rule): disjoint throughput shapes must still gate a converged ->
    diverged transition instead of skipping."""
    _write_round(tmp_path, 1, _parsed(
        1000.0, mode="head", n=None, k=None, blocks=1024,
        sim={"withheld_orphans": {"converged": True,
                                  "heal_to_convergence_s": 6.0}}))
    _write_round(tmp_path, 2, _parsed(
        900.0, mode="head", n=None, k=None, blocks=128,
        sim={"withheld_orphans": {"converged": False,
                                  "heal_to_convergence_s": 0.0}}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "SIM DIVERGED" in capsys.readouterr().out


def test_sim_per_scenario_throughput_keys_diff(tmp_path, bc, capsys):
    """The per_mode_best sim[<scenario>] deliveries/sec keys join the
    throughput comparison like any other shape."""
    _write_round(tmp_path, 1, _sim_parsed(
        1500.0, {"partition_heal": (True, 0.07)},
        per_mode_best={"sim[partition_heal]": 1400.0}))
    _write_round(tmp_path, 2, _sim_parsed(
        1500.0, {"partition_heal": (True, 0.07)},
        per_mode_best={"sim[partition_heal]": 300.0}))  # -79%
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "cpu:sim[partition_heal]" in capsys.readouterr().out


def test_sim_extract_shapes(bc):
    doc = {"parsed": _sim_parsed(1500.0, {"a": (True, 1.5)})}
    assert bc.extract_sim(doc) == {
        "cpu:sim:a": {"converged": True, "heal_s": 1.5}}
    assert bc.extract_sim({"parsed": {"error": "boom"}}) == {}
    assert bc.extract_sim({"parsed": _parsed(300.0)}) == {}


def _mesh_parsed(value, counts, **extra):
    """A `--mode serve-mesh` line: ``counts`` maps device count (str) ->
    (ok, sigs_per_sec) or (ok, sigs_per_sec, efficiency)."""
    mesh = {}
    for name, row in counts.items():
        ok, sigs = row[0], row[1]
        entry = {"ok": ok}
        if ok:
            entry["sigs_per_sec"] = sigs
            if len(row) > 2:
                entry["efficiency"] = row[2]
        else:
            entry["error"] = "child exceeded 900s"
        mesh[name] = entry
    return _parsed(value, mode="serve-mesh", n=None, k=None, mesh=mesh,
                   **extra)


def test_mesh_newly_erroring_device_count_fails(tmp_path, bc, capsys):
    """The mesh gate (ISSUE 9): a device count that verified last round
    and errors in the newest fails outright — losing a working mesh size
    is an availability regression, not perf jitter."""
    _write_round(tmp_path, 1, _mesh_parsed(
        2000.0, {"1": (True, 2000.0), "4": (True, 1900.0, 0.24)}))
    _write_round(tmp_path, 2, _mesh_parsed(
        2000.0, {"1": (True, 2000.0), "4": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:mesh:4" in out and "MESH ERRORED" in out


def test_mesh_throughput_and_efficiency_are_report_only(tmp_path, bc,
                                                        capsys):
    """Per-count sigs/sec and scaling efficiency never fail on their own
    (CPU virtual devices timeshare two host cores — the numbers carry no
    scaling signal until real accelerator rounds)."""
    _write_round(tmp_path, 1, _mesh_parsed(
        2000.0, {"1": (True, 2000.0), "4": (True, 1900.0, 0.24)}))
    _write_round(tmp_path, 2, _mesh_parsed(
        2000.0, {"1": (True, 2000.0), "4": (True, 400.0, 0.05)}))  # -79%
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "cpu:mesh:4" in capsys.readouterr().out


def test_mesh_still_erroring_is_not_a_new_failure(tmp_path, bc):
    """ok False -> False: the round that lost the device count already
    failed once; a permanently-broken count must not wedge every round."""
    _write_round(tmp_path, 1, _mesh_parsed(
        2000.0, {"1": (True, 2000.0), "8": (False, 0.0)}))
    _write_round(tmp_path, 2, _mesh_parsed(
        2000.0, {"1": (True, 2000.0), "8": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_mesh_keys_join_without_common_throughput_keys(tmp_path, bc,
                                                       capsys):
    """Shared mesh keys are comparables in their own right (the SLO/sim
    rule): disjoint throughput shapes must still gate an ok -> error
    transition instead of skipping."""
    _write_round(tmp_path, 1, _parsed(
        1000.0, mode="head", n=None, k=None, blocks=1024,
        mesh={"2": {"ok": True, "sigs_per_sec": 1500.0}}))
    _write_round(tmp_path, 2, _parsed(
        900.0, mode="head", n=None, k=None, blocks=128,
        mesh={"2": {"ok": False, "error": "shard_map compile"}}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "MESH ERRORED" in capsys.readouterr().out


def test_mesh_extract_shapes(bc):
    doc = {"parsed": _mesh_parsed(
        2000.0, {"1": (True, 2000.0), "2": (True, 1500.0, 0.375)})}
    assert bc.extract_mesh(doc) == {
        "cpu:mesh:1": {"ok": True, "sigs_per_sec": 2000.0,
                       "efficiency": None},
        "cpu:mesh:2": {"ok": True, "sigs_per_sec": 1500.0,
                       "efficiency": 0.375},
    }
    # single `--mesh N` serve lines (flat mesh_devices field, no `mesh`
    # per-count section) and error rounds extract nothing
    assert bc.extract_mesh({"parsed": _parsed(
        300.0, mode="serve", n=None, k=None, mesh_devices=4)}) == {}
    assert bc.extract_mesh({"parsed": {"error": "boom"}}) == {}


def test_markdown_table_written_to_github_step_summary(tmp_path, bc,
                                                      monkeypatch):
    summary_file = tmp_path / "summary.md"
    monkeypatch.setenv("GITHUB_STEP_SUMMARY", str(summary_file))
    _write_round(tmp_path, 1, _slo_parsed(300.0, margin=2.0, ok=True))
    _write_round(tmp_path, 2, _slo_parsed(280.0, margin=1.8, ok=True))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    body = summary_file.read_text()
    assert "| key | previous | newest | delta | status |" in body
    assert "`cpu:serve`" in body and "`cpu:slo:serve_p99`" in body
    assert "-6.7%" in body


def test_markdown_table_falls_back_to_stdout(tmp_path, bc, monkeypatch,
                                             capsys):
    monkeypatch.delenv("GITHUB_STEP_SUMMARY", raising=False)
    _write_round(tmp_path, 1, _parsed(300.0))
    _write_round(tmp_path, 2, _parsed(280.0))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "| key | previous | newest | delta | status |" in out
    assert "| `cpu:committee[32x128]` |" in out


def test_real_repo_rounds_pass(bc, monkeypatch):
    """The committed BENCH_r*.json history must satisfy its own gate at
    the DEFAULT threshold (this is the `make bench-compare` invocation CI
    runs; the ambient env knob must not change the test's meaning)."""
    monkeypatch.delenv("BENCH_COMPARE_MAX_REGRESSION", raising=False)
    assert bc.main([]) == 0


# -- the finalexp hard-part race gate (ISSUE 10) ----------------------------


def _fx_parsed(value, cells, **extra):
    """A --mode finalexp round: cells maps "variant,rows" ->
    (ok, ms_per_row)."""
    section = {
        name: {"ok": ok, "ms_per_row": ms}
        for name, (ok, ms) in cells.items()
    }
    return _parsed(value, mode="finalexp", n=None, k=None,
                   finalexp=section, **extra)


def test_finalexp_newly_erroring_variant_fails(tmp_path, bc, capsys):
    """A hard-part variant cell that verified last round and errors in the
    newest fails outright — losing a finalization variant is a
    correctness/availability regression (mirror of MESH ERRORED)."""
    _write_round(tmp_path, 1, _fx_parsed(
        8.0, {"host,1": (True, 16.5), "frobenius,2": (True, 269.0)}))
    _write_round(tmp_path, 2, _fx_parsed(
        8.0, {"host,1": (True, 16.5), "frobenius,2": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:finalexp:frobenius,2" in out and "FINALEXP ERRORED" in out


def test_finalexp_ms_per_row_is_report_only(tmp_path, bc, capsys):
    """ms/row movement — including a device route going slower than host —
    never fails on its own (the route decision is auto-made per platform;
    CPU numbers carry no accelerator signal)."""
    _write_round(tmp_path, 1, _fx_parsed(
        8.0, {"host,2": (True, 16.5), "frobenius,2": (True, 12.0)}))
    _write_round(tmp_path, 2, _fx_parsed(
        8.0, {"host,2": (True, 16.5), "frobenius,2": (True, 300.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "cpu:finalexp:frobenius,2" in capsys.readouterr().out


def test_finalexp_still_erroring_is_not_a_new_failure(tmp_path, bc):
    _write_round(tmp_path, 1, _fx_parsed(
        8.0, {"host,1": (True, 16.5), "windowed,4": (False, 0.0)}))
    _write_round(tmp_path, 2, _fx_parsed(
        8.0, {"host,1": (True, 16.5), "windowed,4": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_finalexp_keys_join_without_common_throughput_keys(tmp_path, bc,
                                                           capsys):
    """Shared finalexp cells are comparables in their own right (the
    SLO/sim/mesh rule): disjoint throughput shapes must still gate an
    ok -> error transition instead of skipping."""
    _write_round(tmp_path, 1, _parsed(
        1000.0, mode="head", n=None, k=None, blocks=1024,
        finalexp={"bit_serial,1": {"ok": True, "ms_per_row": 1223.0}}))
    _write_round(tmp_path, 2, _parsed(
        900.0, mode="head", n=None, k=None, blocks=128,
        finalexp={"bit_serial,1": {"ok": False, "ms_per_row": 0.0}}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "FINALEXP ERRORED" in capsys.readouterr().out


def test_finalexp_new_variant_cells_are_not_gated_until_seen(tmp_path, bc):
    """A variant appearing for the first time (no previous-round cell) is
    report-only — new variants join the gate once they have a baseline."""
    _write_round(tmp_path, 1, _fx_parsed(8.0, {"host,1": (True, 16.5)}))
    _write_round(tmp_path, 2, _fx_parsed(
        8.0, {"host,1": (True, 16.5), "frobenius,8": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


# -- fleet gate (ISSUE 11: `bench.py --mode serve-fleet` worker counts) -------


def _fleet_parsed(value, counts, **extra):
    """A `--mode serve-fleet` line: ``counts`` maps worker count (str) ->
    (ok, sigs_per_sec)."""
    fleet = {}
    for name, (ok, sigs) in counts.items():
        entry = {"ok": ok}
        if ok:
            entry["sigs_per_sec"] = sigs
        else:
            entry["error"] = "warm failed: worker w0 unreachable"
        fleet[name] = entry
    return _parsed(value, mode="serve-fleet", n=None, k=None, fleet=fleet,
                   **extra)


def test_fleet_newly_erroring_worker_count_fails(tmp_path, bc, capsys):
    """A worker count that verified (verdicts + exact merged scrape) last
    round and errors now fails outright — losing a working fleet size is
    an availability regression, the mesh-gate mirror."""
    _write_round(tmp_path, 1, _fleet_parsed(
        45.0, {"1": (True, 35.0), "2": (True, 45.0)}))
    _write_round(tmp_path, 2, _fleet_parsed(
        44.0, {"1": (True, 34.0), "2": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:fleet:2" in out and "FLEET ERRORED" in out


def test_fleet_sigs_per_sec_is_report_only(tmp_path, bc, capsys):
    """Per-worker-count sigs/sec (and therefore the 2-worker speedup)
    never fails on its own — shared-host process scaling jitters."""
    _write_round(tmp_path, 1, _fleet_parsed(
        45.0, {"1": (True, 35.0), "2": (True, 45.0)}))
    _write_round(tmp_path, 2, _fleet_parsed(
        45.0, {"1": (True, 30.0), "2": (True, 9.0)}))  # -80% per count
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "cpu:fleet:2" in capsys.readouterr().out


def test_fleet_still_erroring_is_not_a_new_failure(tmp_path, bc):
    _write_round(tmp_path, 1, _fleet_parsed(
        35.0, {"1": (True, 35.0), "4": (False, 0.0)}))
    _write_round(tmp_path, 2, _fleet_parsed(
        35.0, {"1": (True, 35.0), "4": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_fleet_keys_join_without_common_throughput_keys(tmp_path, bc,
                                                        capsys):
    """Shared fleet keys are comparables in their own right (the SLO/sim/
    mesh rule): disjoint throughput shapes must still gate ok -> error."""
    _write_round(tmp_path, 1, _parsed(
        1000.0, mode="head", n=None, k=None, blocks=1024,
        fleet={"2": {"ok": True, "sigs_per_sec": 45.0}}))
    _write_round(tmp_path, 2, _parsed(
        900.0, mode="head", n=None, k=None, blocks=128,
        fleet={"2": {"ok": False, "error": "worker died"}}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "FLEET ERRORED" in capsys.readouterr().out


def test_fleet_new_counts_are_not_gated_until_seen(tmp_path, bc):
    """A worker count appearing for the first time has no baseline —
    report-only this round, gated from the next."""
    _write_round(tmp_path, 1, _fleet_parsed(35.0, {"1": (True, 35.0)}))
    _write_round(tmp_path, 2, _fleet_parsed(
        35.0, {"1": (True, 35.0), "8": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_fleet_extract_shapes(bc):
    doc = {"parsed": _fleet_parsed(
        45.0, {"1": (True, 35.0), "2": (True, 45.0)})}
    assert bc.extract_fleet(doc) == {
        "cpu:fleet:1": {"ok": True, "sigs_per_sec": 35.0},
        "cpu:fleet:2": {"ok": True, "sigs_per_sec": 45.0},
    }
    # error rounds and sections without rows extract nothing
    assert bc.extract_fleet({"parsed": {"error": "boom"}}) == {}
    assert bc.extract_fleet({"parsed": _parsed(300.0)}) == {}


# -- latency state gate (ISSUE 12) --------------------------------------------


def _latency_parsed(value, scenarios, **extra):
    """A `bench.py --mode latency` line: {scenario: (ok, p99_ms)}."""
    section = {}
    for name, (ok, p99) in scenarios.items():
        entry = {"ok": ok, "p99_ms": p99, "n": 128, "converged": ok,
                 "improved": True}
        if not ok:
            entry["error"] = "objective violated"
        section[name] = entry
    return _parsed(value, mode="latency", n=None, k=None, latency=section,
                   **extra)


def test_latency_newly_violating_scenario_fails(tmp_path, bc, capsys):
    """A scenario whose deadline-mode gossip_to_head_p99 met the declared
    objective last round and violates it now fails outright — "LATENCY
    SLO VIOLATED", the SLO-state mirror for the end-to-end plane."""
    _write_round(tmp_path, 1, _latency_parsed(
        25.0, {"latency_skew": (True, 40.0), "lossy_links": (True, 39.0)}))
    _write_round(tmp_path, 2, _latency_parsed(
        0.8, {"latency_skew": (False, 1250.0),
              "lossy_links": (True, 41.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:latency:latency_skew" in out
    assert "LATENCY SLO VIOLATED" in out


def test_latency_p99_movement_is_report_only(tmp_path, bc, capsys):
    """The per-scenario p99 milliseconds jitter on shared CPU hosts —
    only the objective-state crossing fails the latency gate, never the
    number moving within ok (the headline `value` keeps the ordinary
    throughput gate, like every other mode)."""
    _write_round(tmp_path, 1, _latency_parsed(
        25.0, {"latency_skew": (True, 40.0)}))
    _write_round(tmp_path, 2, _latency_parsed(
        24.0, {"latency_skew": (True, 80.0)}))  # p99 2x worse, still met
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "cpu:latency:latency_skew" in capsys.readouterr().out


def test_latency_still_violated_is_not_a_new_failure(tmp_path, bc):
    _write_round(tmp_path, 1, _latency_parsed(
        25.0, {"lossy_links": (False, 1500.0)}))
    _write_round(tmp_path, 2, _latency_parsed(
        25.0, {"lossy_links": (False, 1600.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_latency_keys_join_without_common_throughput_keys(tmp_path, bc,
                                                          capsys):
    """Shared latency keys are comparables in their own right (the
    SLO/sim/mesh/fleet rule): disjoint throughput shapes still gate."""
    _write_round(tmp_path, 1, _parsed(
        1000.0, mode="head", n=None, k=None, blocks=1024,
        latency={"latency_skew": {"ok": True, "p99_ms": 40.0}}))
    _write_round(tmp_path, 2, _parsed(
        900.0, mode="head", n=None, k=None, blocks=128,
        latency={"latency_skew": {"ok": False, "p99_ms": 1250.0}}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "LATENCY SLO VIOLATED" in capsys.readouterr().out


def test_latency_new_scenarios_are_not_gated_until_seen(tmp_path, bc):
    _write_round(tmp_path, 1, _latency_parsed(
        25.0, {"latency_skew": (True, 40.0)}))
    _write_round(tmp_path, 2, _latency_parsed(
        25.0, {"latency_skew": (True, 40.0),
               "lossy_links": (False, 1500.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_latency_extract_shapes(bc):
    doc = {"parsed": _latency_parsed(
        25.0, {"latency_skew": (True, 40.5), "lossy_links": (True, 39.7)})}
    assert bc.extract_latency(doc) == {
        "cpu:latency:latency_skew": {"ok": True, "p99_ms": 40.5},
        "cpu:latency:lossy_links": {"ok": True, "p99_ms": 39.7},
    }
    assert bc.extract_latency({"parsed": {"error": "boom"}}) == {}
    assert bc.extract_latency({"parsed": _parsed(300.0)}) == {}


# -- the vmexec execution-backend race gate (ISSUE 13) ----------------------


def _vx_parsed(value, cells, **extra):
    """A --mode vmexec round: cells maps "kind,rows" ->
    (ok, fused_ms_row, interp_ms_row)."""
    section = {
        name: {"ok": ok, "fused_ms_row": fused, "interp_ms_row": interp,
               "fused_compile_s": 1.0,
               "speedup": round(interp / fused, 2) if fused else None}
        for name, (ok, fused, interp) in cells.items()
    }
    return _parsed(value, mode="vmexec", n=None, k=None,
                   vmexec=section, **extra)


def test_vmexec_newly_erroring_cell_fails(tmp_path, bc, capsys):
    """A (kind, rows) cell whose fused lowering ran AND matched the
    interpreter bitwise last round and errors (or mismatches) now fails
    outright — losing the fused backend on a program kind is a
    correctness/availability regression (mirror of FINALEXP ERRORED)."""
    _write_round(tmp_path, 1, _vx_parsed(
        5.5, {"g2_subgroup,1": (True, 46.3, 255.0),
              "hard_part_frobenius,8": (True, 35.0, 113.0)}))
    _write_round(tmp_path, 2, _vx_parsed(
        5.5, {"g2_subgroup,1": (True, 46.3, 255.0),
              "hard_part_frobenius,8": (False, 0.0, 113.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:vmexec:hard_part_frobenius,8" in out
    assert "VMEXEC ERRORED" in out


def test_vmexec_ms_row_is_report_only(tmp_path, bc, capsys):
    """Fused/interp ms-row movement — even the fused path losing to the
    interpreter — never fails on its own: the auto route re-measures per
    machine, and CPU numbers jitter; the page-worthy event is a cell
    STOPPING (error or bitwise mismatch), not slowing."""
    _write_round(tmp_path, 1, _vx_parsed(
        5.5, {"g2_subgroup,1": (True, 46.3, 255.0)}))
    _write_round(tmp_path, 2, _vx_parsed(
        5.5, {"g2_subgroup,1": (True, 400.0, 255.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "cpu:vmexec:g2_subgroup,1" in capsys.readouterr().out


def test_vmexec_still_erroring_is_not_a_new_failure(tmp_path, bc):
    _write_round(tmp_path, 1, _vx_parsed(
        5.5, {"h2g_finish,8": (False, 0.0, 90.0)}))
    _write_round(tmp_path, 2, _vx_parsed(
        5.5, {"h2g_finish,8": (False, 0.0, 90.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_vmexec_keys_join_without_common_throughput_keys(tmp_path, bc,
                                                         capsys):
    """Shared vmexec cells are comparables in their own right (the
    SLO/sim/mesh/finalexp rule): disjoint throughput shapes must still
    gate an ok -> error transition instead of skipping."""
    _write_round(tmp_path, 1, _parsed(
        1000.0, mode="head", n=None, k=None, blocks=1024,
        vmexec={"g2_subgroup,1": {"ok": True, "fused_ms_row": 46.3,
                                  "interp_ms_row": 255.0}}))
    _write_round(tmp_path, 2, _parsed(
        900.0, mode="head", n=None, k=None, blocks=128,
        vmexec={"g2_subgroup,1": {"ok": False, "fused_ms_row": 0.0,
                                  "interp_ms_row": 255.0}}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "VMEXEC ERRORED" in capsys.readouterr().out


def test_vmexec_new_cells_are_not_gated_until_seen(tmp_path, bc):
    """A cell appearing for the first time (no previous-round entry) is
    report-only — new kinds join the gate once they have a baseline."""
    _write_round(tmp_path, 1, _vx_parsed(
        5.5, {"g2_subgroup,1": (True, 46.3, 255.0)}))
    _write_round(tmp_path, 2, _vx_parsed(
        5.5, {"g2_subgroup,1": (True, 46.3, 255.0),
              "rlc_combine,8": (False, 0.0, 500.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_vmexec_cold_cells_ride_the_state_gate(tmp_path, bc, capsys):
    """ISSUE 15: the fresh-process cold-start cells (`cold,<kind>` ok =
    fused-ready + bit-identical + within the seconds-scale budget;
    `cold_nodedup,<kind>` the per-chunk baseline arm) are ordinary
    vmexec cells to the gate — a round whose cold arm stops fitting
    (ok True -> False) fails, while ready_s movement alone is
    report-only (the cells carry no ms_row keys, which coerce to 0)."""
    def cold(ok, ready):
        return {"ok": ok, "ready_s": ready, "within_budget": ok,
                "distinct_structs": 7, "chunks": 69}

    _write_round(tmp_path, 1, _parsed(
        5.5, mode="vmexec", n=None, k=None,
        vmexec={"cold,g2_subgroup": cold(True, 79.0),
                "cold_nodedup,g2_subgroup": cold(True, 430.0)}))
    _write_round(tmp_path, 2, _parsed(
        5.5, mode="vmexec", n=None, k=None,
        vmexec={"cold,g2_subgroup": cold(True, 95.0),  # slower: fine
                "cold_nodedup,g2_subgroup": cold(True, 500.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    _write_round(tmp_path, 3, _parsed(
        5.5, mode="vmexec", n=None, k=None,
        vmexec={"cold,g2_subgroup": cold(False, 600.0),  # over budget
                "cold_nodedup,g2_subgroup": cold(True, 500.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:vmexec:cold,g2_subgroup" in out
    assert "VMEXEC ERRORED" in out


def test_vmexec_extract_shapes(bc):
    doc = {"parsed": _vx_parsed(
        5.5, {"g2_subgroup,1": (True, 46.3, 255.0)})}
    got = bc.extract_vmexec(doc)
    assert got == {"cpu:vmexec:g2_subgroup,1": {
        "ok": True, "fused_ms_row": 46.3, "interp_ms_row": 255.0}}
    assert bc.extract_vmexec({"parsed": {"error": "boom"}}) == {}
    assert bc.extract_vmexec({"parsed": _parsed(1.0)}) == {}


# -- the light-client proofs state gate (ISSUE 16) ---------------------------


def _proofs_parsed(value, shapes, **extra):
    """A `--mode proofs` round: shapes maps "clients=<N>" ->
    (verified, proofs_per_sec, hit_rate, p99_ms)."""
    section = {
        name: {"verified": ver, "proofs_per_sec": pps, "hit_rate": hit,
               "p99_ms": p99, "clients": 20000, "slots": 8, "workers": 4,
               "backend": "oracle"}
        for name, (ver, pps, hit, p99) in shapes.items()
    }
    return _parsed(value, mode="proofs", n=None, k=None,
                   proofs=section, **extra)


def test_proofs_newly_unverified_shape_fails(tmp_path, bc, capsys):
    """The proofs gate: a client-count shape whose every served artifact
    verified (validate_light_client_update + is_valid_merkle_branch
    against a re-Merkleized root) last round and stops verifying in the
    newest fails outright — "PROOFS DIVERGED", the sim-gate mirror for
    the read path."""
    _write_round(tmp_path, 1, _proofs_parsed(
        16000.0, {"clients=20000": (True, 16000.0, 0.9996, 0.03)}))
    _write_round(tmp_path, 2, _proofs_parsed(
        16000.0, {"clients=20000": (False, 16500.0, 0.9996, 0.03)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:proofs:clients=20000" in out and "PROOFS DIVERGED" in out


def test_proofs_throughput_and_hit_rate_are_report_only(tmp_path, bc,
                                                        capsys):
    """proofs/sec, cache hit rate, and p99 movement within verified never
    fail the proofs gate on their own (serve throughput on shared CPU
    hosts jitters; the page-worthy event is the verdict flipping). The
    headline `value` still rides the ordinary throughput gate."""
    _write_round(tmp_path, 1, _proofs_parsed(
        16000.0, {"clients=20000": (True, 16000.0, 0.9996, 0.03)}))
    _write_round(tmp_path, 2, _proofs_parsed(
        15000.0, {"clients=20000": (True, 15000.0, 0.52, 9.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "cpu:proofs:clients=20000" in capsys.readouterr().out


def test_proofs_still_unverified_is_not_a_new_failure(tmp_path, bc):
    """verified False -> False: the flip round already failed once; a
    permanently-red shape must not wedge every future round."""
    _write_round(tmp_path, 1, _proofs_parsed(
        16000.0, {"clients=1000": (False, 16000.0, 0.99, 0.03)}))
    _write_round(tmp_path, 2, _proofs_parsed(
        16000.0, {"clients=1000": (False, 16000.0, 0.99, 0.03)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_proofs_keys_join_without_common_throughput_keys(tmp_path, bc,
                                                         capsys):
    """Shared proofs keys are comparables in their own right (the
    SLO/sim/mesh/fleet rule): disjoint throughput shapes must still gate
    a verified -> unverified transition instead of skipping."""
    _write_round(tmp_path, 1, _parsed(
        1000.0, mode="head", n=None, k=None, blocks=1024,
        proofs={"clients=20000": {"verified": True,
                                  "proofs_per_sec": 16000.0,
                                  "hit_rate": 0.9996, "p99_ms": 0.03}}))
    _write_round(tmp_path, 2, _parsed(
        900.0, mode="head", n=None, k=None, blocks=128,
        proofs={"clients=20000": {"verified": False,
                                  "proofs_per_sec": 16000.0,
                                  "hit_rate": 0.9996, "p99_ms": 0.03}}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "PROOFS DIVERGED" in capsys.readouterr().out


def test_proofs_only_previous_round_is_a_usable_baseline(tmp_path, bc,
                                                         capsys):
    """A prior round whose headline value is unusable but whose proofs
    section recorded verification state still baselines the proofs gate —
    the walk must not skip past it to 'no earlier round'."""
    broken = _proofs_parsed(
        16000.0, {"clients=20000": (True, 16000.0, 0.9996, 0.03)})
    broken["value"] = 0.0  # headline unusable, proofs section intact
    _write_round(tmp_path, 1, broken)
    _write_round(tmp_path, 2, _proofs_parsed(
        16000.0, {"clients=20000": (False, 16000.0, 0.9996, 0.03)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "PROOFS DIVERGED" in capsys.readouterr().out


def test_proofs_new_shapes_are_not_gated_until_seen(tmp_path, bc):
    """A client-count shape appearing for the first time has no baseline
    — report-only this round, gated from the next."""
    _write_round(tmp_path, 1, _proofs_parsed(
        16000.0, {"clients=20000": (True, 16000.0, 0.9996, 0.03)}))
    _write_round(tmp_path, 2, _proofs_parsed(
        16000.0, {"clients=20000": (True, 16000.0, 0.9996, 0.03),
                  "clients=1000000": (False, 0.0, 0.0, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_proofs_extract_shapes(bc):
    doc = {"parsed": _proofs_parsed(
        16000.0, {"clients=20000": (True, 16320.01, 0.9996, 0.028)})}
    assert bc.extract_proofs(doc) == {
        "cpu:proofs:clients=20000": {
            "ok": True, "proofs_per_sec": 16320.01, "hit_rate": 0.9996,
            "p99_ms": 0.028}}
    assert bc.extract_proofs({"parsed": {"error": "boom"}}) == {}
    assert bc.extract_proofs({"parsed": _parsed(300.0)}) == {}


# -- the Merkleization state gate (ISSUE 18) ---------------------------------


def _merkle_parsed(value, cells, **extra):
    """A `--mode merkle` round: cells maps cell name ->
    (ok, speedup)."""
    section = {
        name: {"ok": ok, "speedup": spd, "native_s": 0.1, "python_s": 0.6}
        for name, (ok, spd) in cells.items()
    }
    return _parsed(value, mode="merkle", n=None, k=None,
                   merkle=section, **extra)


def test_merkle_newly_diverged_cell_fails(tmp_path, bc, capsys):
    """The merkle gate: a race cell whose native batched root was
    bit-identical to the pure-python oracle last round and diverges in
    the newest fails outright — "MERKLE DIVERGED", the proofs-gate
    mirror for the hashing plane."""
    _write_round(tmp_path, 1, _merkle_parsed(
        300.0, {"state_cold": (True, 6.0)}))
    _write_round(tmp_path, 2, _merkle_parsed(
        300.0, {"state_cold": (False, 7.5)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:merkle:state_cold" in out and "MERKLE DIVERGED" in out


def test_merkle_speedup_movement_is_report_only(tmp_path, bc, capsys):
    """Speedup shrinking (even below 1x) never fails the merkle gate on
    its own — CPU hashing throughput jitters; the page-worthy event is
    bit-identity breaking."""
    _write_round(tmp_path, 1, _merkle_parsed(
        300.0, {"state_cold": (True, 6.0),
                "state_incremental": (True, 46.0)}))
    _write_round(tmp_path, 2, _merkle_parsed(
        290.0, {"state_cold": (True, 0.8),
                "state_incremental": (True, 2.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "cpu:merkle:state_cold" in capsys.readouterr().out


def test_merkle_still_diverged_is_not_a_new_failure(tmp_path, bc):
    """ok False -> False: the flip round already failed once; a
    permanently-red cell must not wedge every future round."""
    _write_round(tmp_path, 1, _merkle_parsed(
        300.0, {"proof_world": (False, 3.0)}))
    _write_round(tmp_path, 2, _merkle_parsed(
        300.0, {"proof_world": (False, 3.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_merkle_keys_join_without_common_throughput_keys(tmp_path, bc,
                                                         capsys):
    """Shared merkle keys are comparables in their own right (the
    SLO/sim/proofs rule): disjoint throughput shapes must still gate an
    identical -> diverged transition instead of skipping."""
    _write_round(tmp_path, 1, _parsed(
        1000.0, mode="head", n=None, k=None, blocks=1024,
        merkle={"state_cold": {"ok": True, "speedup": 6.0}}))
    _write_round(tmp_path, 2, _parsed(
        900.0, mode="head", n=None, k=None, blocks=128,
        merkle={"state_cold": {"ok": False, "speedup": 6.0}}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "MERKLE DIVERGED" in capsys.readouterr().out


def test_merkle_only_previous_round_is_a_usable_baseline(tmp_path, bc,
                                                         capsys):
    """A prior round whose headline value is unusable but whose merkle
    section recorded bit-identity state still baselines the merkle gate —
    the walk must not skip past it to 'no earlier round'."""
    broken = _merkle_parsed(300.0, {"state_cold": (True, 6.0)})
    broken["value"] = 0.0  # headline unusable, merkle section intact
    _write_round(tmp_path, 1, broken)
    _write_round(tmp_path, 2, _merkle_parsed(
        300.0, {"state_cold": (False, 6.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "MERKLE DIVERGED" in capsys.readouterr().out


def test_merkle_new_cells_are_not_gated_until_seen(tmp_path, bc):
    """A race cell appearing for the first time has no baseline —
    report-only this round, gated from the next."""
    _write_round(tmp_path, 1, _merkle_parsed(
        300.0, {"state_cold": (True, 6.0)}))
    _write_round(tmp_path, 2, _merkle_parsed(
        300.0, {"state_cold": (True, 6.0),
                "state_incremental": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_merkle_extract_shapes(bc):
    doc = {"parsed": _merkle_parsed(
        300.0, {"state_cold": (True, 6.01)})}
    assert bc.extract_merkle(doc) == {
        "cpu:merkle:state_cold": {"ok": True, "speedup": 6.01}}
    assert bc.extract_merkle({"parsed": {"error": "boom"}}) == {}
    assert bc.extract_merkle({"parsed": _parsed(300.0)}) == {}


# -- mainnet-scale workload state gate (ISSUE 20) ----------------------------


def _mainnet_parsed(value, sections, **extra):
    """A `--mode mainnet` round: sections maps section name ->
    (ok, atts_per_sec)."""
    section = {
        name: {"ok": ok, "atts_per_sec": aps, "validators": 1 << 20}
        for name, (ok, aps) in sections.items()
    }
    return _parsed(value, mode="mainnet", n=None, k=None,
                   mainnet=section, **extra)


def test_mainnet_newly_diverged_section_fails(tmp_path, bc, capsys):
    """The mainnet gate: a replay section whose correctness claim held
    last round (hierarchical verdicts identical to the flat path) and
    breaks in the newest fails outright — "MAINNET DIVERGED", the
    merkle-gate mirror for the million-validator workload plane."""
    _write_round(tmp_path, 1, _mainnet_parsed(
        300.0, {"slot_replay": (True, 450.0)}))
    _write_round(tmp_path, 2, _mainnet_parsed(
        300.0, {"slot_replay": (False, 460.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:mainnet:slot_replay" in out and "MAINNET DIVERGED" in out


def test_mainnet_atts_per_sec_movement_is_report_only(tmp_path, bc,
                                                      capsys):
    """Attestations/sec halving never fails the mainnet gate on its own
    — CPU replay throughput jitters; the page-worthy event is verdict
    identity (or the strict sim gate) breaking."""
    _write_round(tmp_path, 1, _mainnet_parsed(
        300.0, {"slot_replay": (True, 450.0),
                "censored_sim": (True, 0.0)}))
    _write_round(tmp_path, 2, _mainnet_parsed(
        290.0, {"slot_replay": (True, 210.0),
                "censored_sim": (True, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "cpu:mainnet:slot_replay" in capsys.readouterr().out


def test_mainnet_still_diverged_is_not_a_new_failure(tmp_path, bc):
    """ok False -> False: the flip round already failed once; a
    permanently-red section must not wedge every future round."""
    _write_round(tmp_path, 1, _mainnet_parsed(
        300.0, {"bad_committee": (False, 0.0)}))
    _write_round(tmp_path, 2, _mainnet_parsed(
        300.0, {"bad_committee": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_mainnet_keys_join_without_common_throughput_keys(tmp_path, bc,
                                                          capsys):
    """Shared mainnet keys are comparables in their own right (the
    SLO/sim/merkle rule): disjoint throughput shapes must still gate an
    ok -> broken transition instead of skipping."""
    _write_round(tmp_path, 1, _parsed(
        1000.0, mode="head", n=None, k=None, blocks=1024,
        mainnet={"censored_sim": {"ok": True, "atts_per_sec": 0.0}}))
    _write_round(tmp_path, 2, _parsed(
        900.0, mode="head", n=None, k=None, blocks=128,
        mainnet={"censored_sim": {"ok": False, "atts_per_sec": 0.0}}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "MAINNET DIVERGED" in capsys.readouterr().out


def test_mainnet_only_previous_round_is_a_usable_baseline(tmp_path, bc,
                                                          capsys):
    """A prior round whose headline value is unusable but whose mainnet
    section recorded verdict state still baselines the mainnet gate —
    the walk must not skip past it to 'no earlier round'."""
    broken = _mainnet_parsed(300.0, {"affinity": (True, 0.0)})
    broken["value"] = 0.0  # headline unusable, mainnet section intact
    _write_round(tmp_path, 1, broken)
    _write_round(tmp_path, 2, _mainnet_parsed(
        300.0, {"affinity": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    assert "MAINNET DIVERGED" in capsys.readouterr().out


def test_mainnet_new_sections_are_not_gated_until_seen(tmp_path, bc):
    """A section appearing for the first time has no baseline —
    report-only this round, gated from the next."""
    _write_round(tmp_path, 1, _mainnet_parsed(
        300.0, {"slot_replay": (True, 450.0)}))
    _write_round(tmp_path, 2, _mainnet_parsed(
        300.0, {"slot_replay": (True, 450.0),
                "bad_committee": (False, 0.0)}))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_mainnet_extract_shapes(bc):
    doc = {"parsed": _mainnet_parsed(
        300.0, {"slot_replay": (True, 444.1)})}
    assert bc.extract_mainnet(doc) == {
        "cpu:mainnet:slot_replay": {"ok": True, "atts_per_sec": 444.1}}
    assert bc.extract_mainnet({"parsed": {"error": "boom"}}) == {}
    assert bc.extract_mainnet({"parsed": _parsed(300.0)}) == {}


# -- consensus-health state gate (ISSUE 19) ----------------------------------


def _health_parsed(value, ok, pmin, reorgs=0, per_node=None, **extra):
    """A `--mode soak` line: the ledger's gate verdict + aggregate
    summary (and optional per-node summaries) under ``health``."""
    summary = {"participation_min": pmin, "unexplained_reorgs": reorgs}
    return _parsed(value, mode="soak", n=None, k=None,
                   health={"gate": {"ok": ok, "reasons": [],
                                    "summary": summary},
                           "aggregate": summary,
                           "per_node": per_node or {}},
                   **extra)


def test_health_newly_diverged_gate_fails(tmp_path, bc, capsys):
    """A soak whose health gate held last round and reports DIVERGED now
    fails outright — slow-burn consensus regressions are correctness,
    not perf jitter."""
    _write_round(tmp_path, 1, _health_parsed(160.0, True, 0.84))
    _write_round(tmp_path, 2, _health_parsed(160.0, False, 0.41,
                                             reorgs=2))
    assert bc.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "cpu:health:aggregate" in out and "HEALTH DIVERGED" in out


def test_health_participation_jitter_within_green_gate_passes(
        tmp_path, bc, capsys):
    _write_round(tmp_path, 1, _health_parsed(160.0, True, 0.92))
    _write_round(tmp_path, 2, _health_parsed(160.0, True, 0.78))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "0.9200 -> 0.7800" in capsys.readouterr().out


def test_health_still_diverged_is_not_a_new_failure(tmp_path, bc):
    _write_round(tmp_path, 1, _health_parsed(160.0, False, 0.41))
    _write_round(tmp_path, 2, _health_parsed(160.0, False, 0.40))
    assert bc.main(["--dir", str(tmp_path)]) == 0


def test_health_per_node_rows_inherit_aggregate_verdict(bc):
    doc = {"parsed": _health_parsed(
        160.0, True, 0.84,
        per_node={"n0": {"participation_min": 0.84,
                         "unexplained_reorgs": 0},
                  "n1": {"participation_min": 0.9,
                         "unexplained_reorgs": 0}})}
    rows = bc.extract_health(doc)
    assert set(rows) == {"cpu:health:aggregate", "cpu:health:n0",
                         "cpu:health:n1"}
    assert rows["cpu:health:n0"] == {"ok": True, "participation_min": 0.84,
                                     "unexplained_reorgs": 0}
    assert bc.extract_health({"parsed": {"error": "boom"}}) == {}
    assert bc.extract_health({"parsed": _parsed(300.0)}) == {}


def test_headline_trajectory_spans_every_round(tmp_path, bc, capsys):
    """The all-rounds trajectory: the markdown summary traces the
    headline across r01→r03, not just the newest pair."""
    _write_round(tmp_path, 1, _parsed(300.0))
    _write_round(tmp_path, 2, _parsed(330.0))
    _write_round(tmp_path, 3, _parsed(360.0))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    files = bc.round_files(str(tmp_path))
    lines = bc.headline_trajectory(files)
    assert len(lines) == 1
    assert "r01 300" in lines[0] and "r03 360" in lines[0]
    assert "+20.0% over 3 rounds" in lines[0]
    out = capsys.readouterr().out
    assert "Headline trajectory (all rounds)" in out


def test_headline_trajectory_skips_single_round_keys(tmp_path, bc):
    _write_round(tmp_path, 1, _parsed(300.0))
    _write_round(tmp_path, 2, _parsed(310.0, mode="soak", n=None, k=None))
    files = bc.round_files(str(tmp_path))
    # committee[32x128] and soak each appear once: nothing to trace
    assert bc.headline_trajectory(files) == []
