from consensus_specs_tpu.test.altair.unittests.test_config_invariants import *  # noqa: F401,F403
