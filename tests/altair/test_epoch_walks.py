from consensus_specs_tpu.test.altair.unittests.test_epoch_walks import *  # noqa: F401,F403
