from consensus_specs_tpu.test.altair.rewards.test_inactivity_scores import *  # noqa: F401,F403
