from consensus_specs_tpu.test.altair.transition.test_transition import *  # noqa: F401,F403
