from consensus_specs_tpu.test.altair.random.test_random_matrix import *  # noqa: F401,F403
