from consensus_specs_tpu.test.altair.epoch_processing.test_process_participation_flag_updates import *  # noqa: F401,F403
