from consensus_specs_tpu.test.altair.unittests.test_validator import *  # noqa: F401,F403
