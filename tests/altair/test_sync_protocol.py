from consensus_specs_tpu.test.altair.unittests.test_sync_protocol import *  # noqa: F401,F403
