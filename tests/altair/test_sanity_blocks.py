from consensus_specs_tpu.test.altair.sanity.test_blocks import *  # noqa: F401,F403
