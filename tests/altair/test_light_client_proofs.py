from consensus_specs_tpu.test.altair.unittests.test_light_client_proofs import *  # noqa: F401,F403
