from consensus_specs_tpu.test.altair.fork.test_upgrade_to_altair import *  # noqa: F401,F403
