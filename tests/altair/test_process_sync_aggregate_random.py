from consensus_specs_tpu.test.altair.block_processing.test_process_sync_aggregate_random import *  # noqa: F401,F403
