"""Custody-game crypto primitives (utils/custody.py; reference
specs/custody_game/beacon-chain.md:258-335)."""
from random import Random

from consensus_specs_tpu.utils import bls, custody


def test_legendre_bit_matches_euler_criterion():
    rng = Random(55)
    q = custody.CUSTODY_PRIME
    for _ in range(20):
        a = rng.randrange(1, q)
        euler = pow(a, (q - 1) // 2, q)
        want = 1 if euler == 1 else 0
        assert custody.legendre_bit(a, q) == want
    assert custody.legendre_bit(0, q) == 0
    assert custody.legendre_bit(q + 4, q) == custody.legendre_bit(4, q)
    # small prime sanity: QRs mod 7 are {1,2,4}
    assert [custody.legendre_bit(a, 7) for a in range(1, 7)] == [1, 1, 0, 1, 0, 0]


def test_custody_atoms_padding():
    atoms = custody.get_custody_atoms(b"\x01" * 33)
    assert len(atoms) == 2
    assert atoms[0] == b"\x01" * 32
    assert atoms[1] == b"\x01" + b"\x00" * 31
    assert custody.get_custody_atoms(b"") == []


def test_custody_secrets_shape():
    sig = bls.Sign(7, b"\x03" * 32)
    secrets = custody.get_custody_secrets(sig)
    assert len(secrets) == 3  # 96 bytes of x-coordinate in 32-byte chunks
    assert all(0 <= s < 2**256 for s in secrets)
    # deterministic per signature
    assert secrets == custody.get_custody_secrets(sig)


def test_compute_custody_bit_deterministic_and_key_sensitive():
    data = bytes(Random(8).getrandbits(8) for _ in range(512))
    key_a = bls.Sign(11, b"\x01" * 32)
    key_b = bls.Sign(12, b"\x01" * 32)
    bit_a = custody.compute_custody_bit(key_a, data)
    assert bit_a in (0, 1)
    assert custody.compute_custody_bit(key_a, data) == bit_a
    # with 10 legendre bits, bit=1 has probability ~2^-10: a different key
    # virtually always gives 0; both keys giving 1 would be astonishing
    assert not (bit_a == 1 and custody.compute_custody_bit(key_b, data) == 1)


def test_universal_hash_function_linearity_breaks():
    # UHF must distinguish atom order (it's a polynomial evaluation)
    secrets = [3, 5, 7]
    a = [b"\x01" + b"\x00" * 31, b"\x02" + b"\x00" * 31]
    b = [a[1], a[0]]
    assert custody.universal_hash_function(a, secrets) != \
        custody.universal_hash_function(b, secrets)


def test_custody_periods_are_staggered_and_consistent():
    E = custody.EPOCHS_PER_CUSTODY_PERIOD
    for validator_index in (0, 1, 7, E - 1, E + 5):
        for epoch in (0, 1, E - 1, E, 3 * E + 17):
            period = custody.get_custody_period_for_validator(validator_index, epoch)
            # the keying randao epoch lands after the period ends (padding)
            randao_epoch = custody.get_randao_epoch_for_custody_period(
                period, validator_index
            )
            period_end = (period + 1) * E - validator_index % E
            assert randao_epoch == period_end + custody.CUSTODY_PERIOD_TO_RANDAO_PADDING
            # the epoch really falls inside the period's staggered window
            start = period * E - validator_index % E
            assert start <= epoch < start + E
    # two validators with different offsets get different boundaries
    assert (
        custody.get_custody_period_for_validator(0, E - 1)
        != custody.get_custody_period_for_validator(1, E - 1)
    )
