"""Device-batched KZG point-proof verification vs the host oracle
(ops/kzg_backend.py; BASELINE config #5's device path)."""
import pytest

from consensus_specs_tpu.utils.jax_env import force_cpu

force_cpu()

from consensus_specs_tpu.utils import bls12_381 as O  # noqa: E402
from consensus_specs_tpu.utils import kzg  # noqa: E402
from consensus_specs_tpu.ops import kzg_backend  # noqa: E402


TAU = 0x5EED  # the module setup's secret — shared so the z==tau test binds


@pytest.fixture(scope="module")
def setup():
    return kzg.lazy_setup(tau=TAU, n=16)


def _cases(setup, count=3):
    """(commitment, proof, z, y, expected) tuples: valid proofs, a wrong-y
    proof, and a wrong-point proof."""
    out = []
    for i in range(count):
        coeffs = [(7 * i + j * j + 1) % kzg.MODULUS for j in range(5 + i)]
        commitment = kzg.commit_to_poly(setup, coeffs)
        z = (31 * i + 2) % kzg.MODULUS
        proof, y = kzg.prove_at_point(setup, coeffs, z)
        out.append((commitment, proof, z, y, True))
    # wrong claimed value
    c, p, z, y, _ = out[0]
    out.append((c, p, z, (y + 1) % kzg.MODULUS, False))
    # proof for a different point
    c2, p2, z2, y2, _ = out[1]
    out.append((c2, p2, (z2 + 5) % kzg.MODULUS, y2, False))
    return out


@pytest.mark.slow
def test_batch_matches_oracle(setup):
    cases = _cases(setup)
    got = kzg_backend.batch_verify_point_proofs(
        setup,
        [c for c, p, z, y, e in cases],
        [p for c, p, z, y, e in cases],
        [z for c, p, z, y, e in cases],
        [y for c, p, z, y, e in cases],
    )
    want = [e for c, p, z, y, e in cases]
    oracle = [
        kzg.verify_point_proof(setup, c, p, z, y) for c, p, z, y, _ in cases
    ]
    assert oracle == want  # the oracle agrees with the constructed truth
    assert list(got) == want, (list(got), want)


@pytest.mark.slow
def test_identity_commitment_edge(setup):
    # p(X) = y0 constant: proof is the zero polynomial commitment
    # (infinity); the device path must absorb the infinity lane and agree
    coeffs = [11]
    commitment = kzg.commit_to_poly(setup, coeffs)
    proof, y = kzg.prove_at_point(setup, coeffs, z=4)
    got = kzg_backend.batch_verify_point_proofs(
        setup, [commitment], [proof], [4], [y]
    )
    assert bool(got[0]) == kzg.verify_point_proof(setup, commitment, proof, 4, y)
    assert bool(got[0])


def test_tau_query_oracle_fallback(setup):
    # z == tau: [tau - z]G2 is the point at infinity, which has no affine
    # form — the device path must answer that item via the oracle fallback
    # (and the all-fallback batch shape must not touch the device at all)
    coeffs = [3, 1, 4, 1, 5]
    commitment = kzg.commit_to_poly(setup, coeffs)
    # the scenario's whole point: [tau - z]G2 degenerates to infinity
    h0 = O.ec_add(setup.g2[1], O.ec_neg(O.ec_mul(O.G2_GEN, TAU)))
    assert O.ec_to_affine(h0) is None
    proof, y = kzg.prove_at_point(setup, coeffs, z=TAU)
    got = kzg_backend.batch_verify_point_proofs(
        setup, [commitment], [proof], [TAU], [y]
    )
    want = kzg.verify_point_proof(setup, commitment, proof, TAU, y)
    assert bool(got[0]) == want
