"""JAX Fq limb arithmetic vs the pure-Python oracle, bit-exact."""
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from consensus_specs_tpu.ops import fq  # noqa: E402  (enables x64)
from consensus_specs_tpu.utils.bls12_381 import P  # noqa: E402

rng = random.Random(7)


def rand_fq():
    return rng.randrange(P)


def test_limb_roundtrip():
    for _ in range(10):
        x = rand_fq()
        limbs = fq.to_mont_int(x)
        assert fq.from_mont_limbs(limbs) == x


def test_mont_mul_matches_oracle():
    xs = [0, 1, 2, P - 1, P - 2] + [rand_fq() for _ in range(20)]
    ys = [1, 0, P - 1, 3, P // 2] + [rand_fq() for _ in range(20)]
    a = np.stack([fq.to_mont_int(x) for x in xs])
    b = np.stack([fq.to_mont_int(y) for y in ys])
    out = np.asarray(fq.mont_mul(a, b))
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert fq.from_mont_limbs(out[i]) == (x * y) % P, f"mismatch at {i}"


def test_add_sub_neg():
    xs = [rand_fq() for _ in range(16)]
    ys = [rand_fq() for _ in range(16)]
    a = np.stack([fq.to_mont_int(x) for x in xs])
    b = np.stack([fq.to_mont_int(y) for y in ys])
    s = np.asarray(fq.add(a, b))
    d = np.asarray(fq.sub(a, b))
    n = np.asarray(fq.neg(a))
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert fq.from_mont_limbs(s[i]) == (x + y) % P
        assert fq.from_mont_limbs(d[i]) == (x - y) % P
        assert fq.from_mont_limbs(n[i]) == (-x) % P


def test_edge_zero_and_one():
    one = fq.const(1)
    zero = fq.const(0)
    x = fq.to_mont_int(rand_fq())
    assert fq.from_mont_limbs(np.asarray(fq.mont_mul(x, one))) == fq.from_mont_limbs(x)
    assert fq.from_mont_limbs(np.asarray(fq.mont_mul(x, zero))) == 0
    assert bool(np.asarray(fq.is_zero(np.asarray(zero))))


def test_mont_mul_jit_and_batch():
    f = jax.jit(fq.mont_mul)
    xs = [rand_fq() for _ in range(64)]
    ys = [rand_fq() for _ in range(64)]
    a = np.stack([fq.to_mont_int(x) for x in xs])
    b = np.stack([fq.to_mont_int(y) for y in ys])
    out = np.asarray(f(a, b))
    for i in range(64):
        assert fq.from_mont_limbs(out[i]) == (xs[i] * ys[i]) % P
