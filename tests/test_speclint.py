"""Unit tests for the speclint call-signature pass (tools/speclint.py).

The pass is the repo's slice of the reference's strict-mypy gate
(reference Makefile:133-136, linter.ini): a fork override that changes a
helper's parameters must fail `make lint` at every stale call site.
These tests seed exactly that class of bug into a synthetic namespace
and check the pass reports it — and stays silent on the legal shapes it
must not flag (splats, shadowing, defaults, keywords).
"""
import importlib.util
import os
import textwrap

import pytest

_SPECLINT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools", "speclint.py"
)


@pytest.fixture(scope="module")
def speclint():
    spec = importlib.util.spec_from_file_location("speclint_under_test", _SPECLINT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _build_ns(src, tmp_path, name="seeded_spec"):
    """Exec ``src`` the way the builder does — compiled against a real
    file so inspect.getsource works — and return the namespace dict."""
    path = tmp_path / f"{name}.py"
    path.write_text(textwrap.dedent(src))
    ns = {}
    code = compile(path.read_text(), str(path), "exec")
    exec(code, ns)
    # mimic module globals: functions defined by the exec see ns as their
    # __globals__, which is what check_call_signatures keys on
    return ns


def test_wrong_arity_is_caught(speclint, tmp_path):
    ns = _build_ns(
        """
        def helper(state, index):
            return index

        def process_thing(state):
            return helper(state)  # stale call site: missing 'index'
        """,
        tmp_path,
    )
    findings = speclint.check_call_signatures(ns, "<seeded>")
    assert len(findings) == 1
    assert "process_thing" in findings[0] and "helper()" in findings[0]


def test_unknown_keyword_is_caught(speclint, tmp_path):
    ns = _build_ns(
        """
        def helper(state, index=0):
            return index

        def process_thing(state):
            return helper(state, idx=3)  # typo'd keyword
        """,
        tmp_path,
    )
    findings = speclint.check_call_signatures(ns, "<seeded>")
    assert len(findings) == 1 and "does not bind" in findings[0]


def test_too_many_positionals_is_caught(speclint, tmp_path):
    ns = _build_ns(
        """
        def helper(state):
            return state

        def process_thing(state):
            return helper(state, 1, 2)
        """,
        tmp_path,
    )
    assert len(speclint.check_call_signatures(ns, "<seeded>")) == 1


def test_legal_shapes_stay_silent(speclint, tmp_path):
    ns = _build_ns(
        """
        def helper(state, index=0, *, flag=False):
            return index

        def uses_default(state):
            return helper(state)

        def uses_keyword(state):
            return helper(state, index=2, flag=True)

        def uses_splat(state, args):
            return helper(*args)  # unknowable statically: skipped

        def shadows(state):
            helper = len  # local shadow: the ns function is NOT the callee
            return helper(state)
        """,
        tmp_path,
    )
    assert speclint.check_call_signatures(ns, "<seeded>") == []


def test_non_function_callees_are_skipped(speclint, tmp_path):
    ns = _build_ns(
        """
        class Thing:
            def __init__(self, a, b):
                pass

        def make(state):
            return Thing(1, 2, 3)  # classes use a different convention: skipped
        """,
        tmp_path,
    )
    assert speclint.check_call_signatures(ns, "<seeded>") == []


# ---------------------------------------------------------------------------
# duplicate-definition sweep (pyflakes F811 class)
# ---------------------------------------------------------------------------


def _dup_findings(speclint, src):
    import ast

    src = textwrap.dedent(src)
    tree = ast.parse(src)
    noqa = {i + 1 for i, line in enumerate(src.splitlines())
            if "noqa" in line}
    return speclint.check_duplicate_defs(tree, "mod.py", noqa)


def test_duplicate_test_function_is_caught(speclint):
    findings = _dup_findings(
        speclint,
        """
        def test_x():
            assert True

        def test_x():  # the classic: the first test silently never runs
            assert False
        """,
    )
    assert len(findings) == 1
    assert "test_x" in findings[0] and "line 2" in findings[0]


def test_duplicate_class_and_method_are_caught(speclint):
    findings = _dup_findings(
        speclint,
        """
        class C:
            def m(self):
                return 1

            def m(self):
                return 2

        class C:
            pass
        """,
    )
    assert len(findings) == 2
    assert any("'m'" in f for f in findings)
    assert any("'C'" in f for f in findings)


def test_branch_split_definitions_are_legal(speclint):
    findings = _dup_findings(
        speclint,
        """
        try:
            from fast import impl
        except ImportError:
            def impl():
                return None

        if True:
            def helper():
                return 1
        else:
            def helper():
                return 2
        """,
    )
    assert findings == []


def test_duplicate_inside_else_branch_is_caught(speclint):
    findings = _dup_findings(
        speclint,
        """
        try:
            import fast
        except ImportError:
            pass
        else:
            def test_x():
                assert True

            def test_x():
                assert False
        """,
    )
    assert len(findings) == 1 and "test_x" in findings[0]


def test_property_setter_idiom_is_exempt(speclint):
    findings = _dup_findings(
        speclint,
        """
        class C:
            @property
            def x(self):
                return self._x

            @x.setter
            def x(self, v):
                self._x = v
        """,
    )
    assert findings == []


def test_mark_decorated_duplicates_are_still_caught(speclint):
    # the exemption is ONLY the @x.setter accumulator idiom; a foreign
    # dotted decorator must not shield a shadowing redefinition
    findings = _dup_findings(
        speclint,
        """
        import pytest

        @pytest.mark.slow
        def test_x():
            assert True

        @pytest.mark.slow
        def test_x():
            assert False
        """,
    )
    assert len(findings) == 1 and "test_x" in findings[0]


def test_noqa_suppresses_duplicate_definition(speclint):
    findings = _dup_findings(
        speclint,
        """
        def f():
            return 1

        def f():  # noqa: deliberate override
            return 2
        """,
    )
    assert findings == []


def test_repo_tooling_is_covered_by_the_walk(speclint):
    # the satellite contract: the source walk lints tools/ and bench.py,
    # not just the package — a duplicate def there must be reachable
    files = list(speclint._py_files())
    names = {os.path.basename(f) for f in files}
    assert "bench.py" in names and "speclint.py" in names
    assert any(os.sep + "tools" + os.sep in f for f in files)
