"""Unit tests for the speclint call-signature pass (tools/speclint.py).

The pass is the repo's slice of the reference's strict-mypy gate
(reference Makefile:133-136, linter.ini): a fork override that changes a
helper's parameters must fail `make lint` at every stale call site.
These tests seed exactly that class of bug into a synthetic namespace
and check the pass reports it — and stays silent on the legal shapes it
must not flag (splats, shadowing, defaults, keywords).
"""
import importlib.util
import os
import textwrap

import pytest

_SPECLINT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools", "speclint.py"
)


@pytest.fixture(scope="module")
def speclint():
    spec = importlib.util.spec_from_file_location("speclint_under_test", _SPECLINT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _build_ns(src, tmp_path, name="seeded_spec"):
    """Exec ``src`` the way the builder does — compiled against a real
    file so inspect.getsource works — and return the namespace dict."""
    path = tmp_path / f"{name}.py"
    path.write_text(textwrap.dedent(src))
    ns = {}
    code = compile(path.read_text(), str(path), "exec")
    exec(code, ns)
    # mimic module globals: functions defined by the exec see ns as their
    # __globals__, which is what check_call_signatures keys on
    return ns


def test_wrong_arity_is_caught(speclint, tmp_path):
    ns = _build_ns(
        """
        def helper(state, index):
            return index

        def process_thing(state):
            return helper(state)  # stale call site: missing 'index'
        """,
        tmp_path,
    )
    findings = speclint.check_call_signatures(ns, "<seeded>")
    assert len(findings) == 1
    assert "process_thing" in findings[0] and "helper()" in findings[0]


def test_unknown_keyword_is_caught(speclint, tmp_path):
    ns = _build_ns(
        """
        def helper(state, index=0):
            return index

        def process_thing(state):
            return helper(state, idx=3)  # typo'd keyword
        """,
        tmp_path,
    )
    findings = speclint.check_call_signatures(ns, "<seeded>")
    assert len(findings) == 1 and "does not bind" in findings[0]


def test_too_many_positionals_is_caught(speclint, tmp_path):
    ns = _build_ns(
        """
        def helper(state):
            return state

        def process_thing(state):
            return helper(state, 1, 2)
        """,
        tmp_path,
    )
    assert len(speclint.check_call_signatures(ns, "<seeded>")) == 1


def test_legal_shapes_stay_silent(speclint, tmp_path):
    ns = _build_ns(
        """
        def helper(state, index=0, *, flag=False):
            return index

        def uses_default(state):
            return helper(state)

        def uses_keyword(state):
            return helper(state, index=2, flag=True)

        def uses_splat(state, args):
            return helper(*args)  # unknowable statically: skipped

        def shadows(state):
            helper = len  # local shadow: the ns function is NOT the callee
            return helper(state)
        """,
        tmp_path,
    )
    assert speclint.check_call_signatures(ns, "<seeded>") == []


def test_non_function_callees_are_skipped(speclint, tmp_path):
    ns = _build_ns(
        """
        class Thing:
            def __init__(self, a, b):
                pass

        def make(state):
            return Thing(1, 2, 3)  # classes use a different convention: skipped
        """,
        tmp_path,
    )
    assert speclint.check_call_signatures(ns, "<seeded>") == []
