"""The generated spec-document set (make docs) must stay complete and in
sync with the executable sources: every specsrc module renders, key
normative functions appear as anchored headings, and the committed tree
matches a fresh render (so editing specsrc without `make docs` fails CI).
"""
import importlib.util
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOL = os.path.join(_REPO, "tools", "render_spec.py")


@pytest.fixture(scope="module")
def render_spec():
    spec = importlib.util.spec_from_file_location("render_spec_under_test", _TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _specsrc_modules():
    root = os.path.join(_REPO, "consensus_specs_tpu", "specsrc")
    for fork in sorted(os.listdir(root)):
        d = os.path.join(root, fork)
        if not os.path.isdir(d) or fork.startswith("__"):
            continue
        for fn in sorted(os.listdir(d)):
            if fn.endswith(".py") and not fn.startswith("__"):
                yield fork, fn[:-3], os.path.join(d, fn)


def test_every_module_renders_nonempty(render_spec):
    count = 0
    for fork, name, path in _specsrc_modules():
        with open(path) as f:
            doc = render_spec.render_module(fork, name, f.read())
        assert doc.startswith(f"# {fork} — ")
        assert "```python" in doc, f"{fork}/{name}: no code blocks"
        count += 1
    assert count >= 19  # 5 forks' worth of documents


def test_normative_functions_are_anchored(render_spec):
    path = os.path.join(
        _REPO, "consensus_specs_tpu", "specsrc", "phase0", "beacon_chain.py"
    )
    with open(path) as f:
        doc = render_spec.render_module("phase0", "beacon_chain", f.read())
    for fn in ("state_transition", "process_attestation", "process_deposit",
               "get_beacon_proposer_index", "slash_validator"):
        assert f"### `{fn}`" in doc, fn
    assert "### `BeaconState` (container)" in doc
    # the section banners became headings
    assert doc.count("\n## ") >= 4


def test_committed_tree_matches_fresh_render(render_spec):
    """docs/specs/ is generated output: a specsrc edit without `make docs`
    must fail here, keeping the committed documents trustworthy."""
    for fork, name, path in _specsrc_modules():
        committed = os.path.join(_REPO, "docs", "specs", fork, f"{name}.md")
        assert os.path.exists(committed), f"missing {committed} — run `make docs`"
        with open(path) as f:
            fresh = render_spec.render_module(fork, name, f.read())
        with open(committed) as f:
            assert f.read() == fresh, (
                f"{committed} is stale — run `make docs` after editing specsrc"
            )
