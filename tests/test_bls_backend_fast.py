"""Default-run slice of the TPU-backend cross-checks (VERDICT r2 #6): one
real verify + one bucket edge, small programs only — so the flagship
correctness path is exercised on every plain `pytest tests/` run, not just
under --run-slow. The deep/wide cases live in test_bls_backend_tpu.py."""
from consensus_specs_tpu.utils import bls


def test_single_verify_and_k2_bucket():
    from consensus_specs_tpu.ops import bls_backend

    sk1, sk2 = 41, 42
    pk1, pk2 = bls.SkToPk(sk1), bls.SkToPk(sk2)
    msg = b"\x05" * 32
    sig1 = bls.Sign(sk1, msg)
    assert bls_backend.verify(pk1, msg, sig1) is True
    assert bls_backend.verify(pk2, msg, sig1) is False

    agg = bls.Aggregate([sig1, bls.Sign(sk2, msg)])
    got = bls_backend.batch_fast_aggregate_verify(
        [[pk1, pk2], [pk1]], [msg, msg], [agg, agg]
    )
    assert list(got) == [True, False]
