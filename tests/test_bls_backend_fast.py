"""Default-run slice of the TPU-backend cross-checks (VERDICT r2 #6): one
real verify + one bucket edge, small programs only — so the flagship
correctness path is exercised on every plain `pytest tests/` run, not just
under --run-slow. The deep/wide cases live in test_bls_backend_tpu.py."""
from consensus_specs_tpu.utils import bls


def test_single_verify_and_k2_bucket():
    from consensus_specs_tpu.ops import bls_backend

    sk1, sk2 = 41, 42
    pk1, pk2 = bls.SkToPk(sk1), bls.SkToPk(sk2)
    msg = b"\x05" * 32
    sig1 = bls.Sign(sk1, msg)
    assert bls_backend.verify(pk1, msg, sig1) is True
    assert bls_backend.verify(pk2, msg, sig1) is False

    agg = bls.Aggregate([sig1, bls.Sign(sk2, msg)])
    got = bls_backend.batch_fast_aggregate_verify(
        [[pk1, pk2], [pk1]], [msg, msg], [agg, agg]
    )
    assert list(got) == [True, False]


# -- _cached eviction semantics (ISSUE 2 satellite) --------------------------


def _with_cap(cache, cap):
    from consensus_specs_tpu.ops import bls_backend

    bls_backend._CACHE_CAPS[id(cache)] = cap
    return cache


def test_cached_hit_refreshes_recency_order():
    from consensus_specs_tpu.ops.bls_backend import _CACHE_CAPS, _cached

    cache = _with_cap({}, 8)
    try:
        for i in range(4):
            _cached(cache, bytes([i]), lambda k: ("v", k))
        _cached(cache, b"\x01", lambda k: ("new", k))  # hit: no recompute
        assert cache[b"\x01"] == ("v", b"\x01")
        # dict order IS recency order: the hit key moved last
        assert list(cache.keys()) == [b"\x00", b"\x02", b"\x03", b"\x01"]
    finally:
        del _CACHE_CAPS[id(cache)]


def test_cached_half_eviction_drops_only_cold_half():
    from consensus_specs_tpu.ops.bls_backend import _CACHE_CAPS, _cached

    cache = _with_cap({}, 8)
    try:
        for i in range(8):
            _cached(cache, bytes([i]), lambda k: k)
        for i in (0, 1, 2, 3):  # refresh the first four: now hottest
            _cached(cache, bytes([i]), lambda k: None)
        _cached(cache, b"\x63", lambda k: k)  # overflow -> evict cold half
        assert sorted(cache.keys()) == [
            b"\x00", b"\x01", b"\x02", b"\x03", b"\x63"
        ]
    finally:
        del _CACHE_CAPS[id(cache)]


def test_cached_valueerror_never_cached_and_reraised():
    from consensus_specs_tpu.ops.bls_backend import _CACHE_CAPS, _cached

    calls = []
    cache = _with_cap({}, 8)

    def compute(k):
        calls.append(k)
        return ValueError("bad input")

    try:
        for _ in range(2):
            try:
                _cached(cache, b"k", compute)
                assert False, "expected ValueError"
            except ValueError as e:
                assert str(e) == "bad input"
        assert cache == {}  # never cached ...
        assert len(calls) == 2  # ... so every miss recomputes
    finally:
        del _CACHE_CAPS[id(cache)]


def test_prewarm_codec_path_skips_invalid_values():
    """The batched-codec prewarm fills caches exactly like _cached would:
    validation failures (ValueError VALUES) never enter, valid items do."""
    from consensus_specs_tpu.ops import bls_backend

    sks = list(range(201, 221))
    pks = [bls.SkToPk(sk) for sk in sks]
    bad_pk = b"\xa0" + b"\x01" * 47  # not on curve
    inf_pk = b"\xc0" + b"\x00" * 47  # infinity: KeyValidate rejects
    for pk in pks + [bad_pk, inf_pk]:
        bls_backend._PK_CACHE.pop(pk, None)
    before = dict(bls_backend.PREP_STATS)
    bls_backend.prewarm_host_caches([], [], pks + [bad_pk, inf_pk])
    assert all(pk in bls_backend._PK_CACHE for pk in pks)
    assert bad_pk not in bls_backend._PK_CACHE
    assert inf_pk not in bls_backend._PK_CACHE
    assert (
        bls_backend.PREP_STATS["codec_items"]
        == before["codec_items"] + len(pks) + 2
    )


def test_reset_prep_state_clears_pool_latch_and_counters():
    from consensus_specs_tpu.ops import bls_backend, profiling

    bls_backend._set_pool_broken(True)
    assert bls_backend._POOL_BROKEN is True
    assert bls_backend.PREP_STATS["pool_broken_latches"] >= 1
    assert profiling.summary()["bls.prep_pool_broken"]["gauge"] == 1.0
    bls_backend.reset_prep_state()
    assert bls_backend._POOL_BROKEN is False
    assert all(v == 0 for v in bls_backend.PREP_STATS.values())
    assert profiling.summary()["bls.prep_pool_broken"]["gauge"] == 0.0


# -- .vm_cache pruning (ISSUE 6 satellite) -----------------------------------


def test_prune_vm_cache_evicts_by_idle_age_and_size(tmp_path):
    import os
    import time as _time

    from consensus_specs_tpu.ops.bls_backend import prune_vm_cache

    d = str(tmp_path)
    now = _time.time()
    # two stale entries (40 days idle), two fresh, one foreign file
    for name, age_days, size in (
        ("v1_aaaa_old1.pkl", 40, 1000),
        ("v1_aaaa_old2.pkl", 41, 1000),
        ("v1_bbbb_new1.pkl", 1, 1000),
        ("v1_bbbb_new2.pkl", 0, 1000),
    ):
        p = os.path.join(d, name)
        with open(p, "wb") as fh:
            fh.write(b"\x00" * size)
        os.utime(p, (now - age_days * 86400, now - age_days * 86400))
    with open(os.path.join(d, "README.txt"), "w") as fh:
        fh.write("not a cache entry")

    out = prune_vm_cache(max_age_days=30, max_bytes=0, cache_dir=d)
    assert out["evicted"] == 2 and out["kept"] == 2
    left = sorted(os.listdir(d))
    assert left == ["README.txt", "v1_bbbb_new1.pkl", "v1_bbbb_new2.pkl"]
    # the prune publishes what it reclaimed through the registry (ISSUE 7
    # satellite: previously the returned dict was the only record)
    from consensus_specs_tpu.ops import profiling

    summ = profiling.summary()
    assert summ["bls.vm_cache_pruned_entries"] == {"gauge": 2.0}
    assert summ["bls.vm_cache_pruned_bytes"] == {"gauge": 2000.0}

    # size cap: keep only the newest entry's bytes
    out = prune_vm_cache(max_age_days=0, max_bytes=1000, cache_dir=d)
    assert out["evicted"] == 1 and out["kept"] == 1
    assert out["kept_bytes"] == 1000
    assert sorted(os.listdir(d)) == ["README.txt", "v1_bbbb_new2.pkl"]

    # disabled rules (<= 0) evict nothing
    out = prune_vm_cache(max_age_days=0, max_bytes=0, cache_dir=d)
    assert out["evicted"] == 0 and out["kept"] == 1
