"""Default-run slice of the TPU-backend cross-checks (VERDICT r2 #6): one
real verify + one bucket edge, small programs only — so the flagship
correctness path is exercised on every plain `pytest tests/` run, not just
under --run-slow. The deep/wide cases live in test_bls_backend_tpu.py."""
from consensus_specs_tpu.utils import bls


def test_single_verify_and_k2_bucket():
    from consensus_specs_tpu.ops import bls_backend

    sk1, sk2 = 41, 42
    pk1, pk2 = bls.SkToPk(sk1), bls.SkToPk(sk2)
    msg = b"\x05" * 32
    sig1 = bls.Sign(sk1, msg)
    assert bls_backend.verify(pk1, msg, sig1) is True
    assert bls_backend.verify(pk2, msg, sig1) is False

    agg = bls.Aggregate([sig1, bls.Sign(sk2, msg)])
    got = bls_backend.batch_fast_aggregate_verify(
        [[pk1, pk2], [pk1]], [msg, msg], [agg, agg]
    )
    assert list(got) == [True, False]


# -- _cached eviction semantics (ISSUE 2 satellite) --------------------------


def _with_cap(cache, cap):
    from consensus_specs_tpu.ops import bls_backend

    bls_backend._CACHE_CAPS[id(cache)] = cap
    return cache


def test_cached_hit_refreshes_recency_order():
    from consensus_specs_tpu.ops.bls_backend import _CACHE_CAPS, _cached

    cache = _with_cap({}, 8)
    try:
        for i in range(4):
            _cached(cache, bytes([i]), lambda k: ("v", k))
        _cached(cache, b"\x01", lambda k: ("new", k))  # hit: no recompute
        assert cache[b"\x01"] == ("v", b"\x01")
        # dict order IS recency order: the hit key moved last
        assert list(cache.keys()) == [b"\x00", b"\x02", b"\x03", b"\x01"]
    finally:
        del _CACHE_CAPS[id(cache)]


def test_cached_half_eviction_drops_only_cold_half():
    from consensus_specs_tpu.ops.bls_backend import _CACHE_CAPS, _cached

    cache = _with_cap({}, 8)
    try:
        for i in range(8):
            _cached(cache, bytes([i]), lambda k: k)
        for i in (0, 1, 2, 3):  # refresh the first four: now hottest
            _cached(cache, bytes([i]), lambda k: None)
        _cached(cache, b"\x63", lambda k: k)  # overflow -> evict cold half
        assert sorted(cache.keys()) == [
            b"\x00", b"\x01", b"\x02", b"\x03", b"\x63"
        ]
    finally:
        del _CACHE_CAPS[id(cache)]


def test_cached_valueerror_never_cached_and_reraised():
    from consensus_specs_tpu.ops.bls_backend import _CACHE_CAPS, _cached

    calls = []
    cache = _with_cap({}, 8)

    def compute(k):
        calls.append(k)
        return ValueError("bad input")

    try:
        for _ in range(2):
            try:
                _cached(cache, b"k", compute)
                assert False, "expected ValueError"
            except ValueError as e:
                assert str(e) == "bad input"
        assert cache == {}  # never cached ...
        assert len(calls) == 2  # ... so every miss recomputes
    finally:
        del _CACHE_CAPS[id(cache)]


def test_prewarm_codec_path_skips_invalid_values():
    """The batched-codec prewarm fills caches exactly like _cached would:
    validation failures (ValueError VALUES) never enter, valid items do."""
    from consensus_specs_tpu.ops import bls_backend

    sks = list(range(201, 221))
    pks = [bls.SkToPk(sk) for sk in sks]
    bad_pk = b"\xa0" + b"\x01" * 47  # not on curve
    inf_pk = b"\xc0" + b"\x00" * 47  # infinity: KeyValidate rejects
    for pk in pks + [bad_pk, inf_pk]:
        bls_backend._PK_CACHE.pop(pk, None)
    before = dict(bls_backend.PREP_STATS)
    bls_backend.prewarm_host_caches([], [], pks + [bad_pk, inf_pk])
    assert all(pk in bls_backend._PK_CACHE for pk in pks)
    assert bad_pk not in bls_backend._PK_CACHE
    assert inf_pk not in bls_backend._PK_CACHE
    assert (
        bls_backend.PREP_STATS["codec_items"]
        == before["codec_items"] + len(pks) + 2
    )


def test_reset_prep_state_clears_pool_latch_and_counters():
    from consensus_specs_tpu.ops import bls_backend, profiling

    bls_backend._set_pool_broken(True)
    assert bls_backend._POOL_BROKEN is True
    assert bls_backend.PREP_STATS["pool_broken_latches"] >= 1
    assert profiling.summary()["bls.prep_pool_broken"]["gauge"] == 1.0
    bls_backend.reset_prep_state()
    assert bls_backend._POOL_BROKEN is False
    assert all(v == 0 for v in bls_backend.PREP_STATS.values())
    assert profiling.summary()["bls.prep_pool_broken"]["gauge"] == 0.0


# -- .vm_cache pruning (ISSUE 6 satellite) -----------------------------------


def test_prune_vm_cache_evicts_by_idle_age_and_size(tmp_path):
    import os
    import time as _time

    from consensus_specs_tpu.ops.bls_backend import prune_vm_cache

    d = str(tmp_path)
    now = _time.time()
    # two stale entries (40 days idle), two fresh, one foreign file
    for name, age_days, size in (
        ("v1_aaaa_old1.pkl", 40, 1000),
        ("v1_aaaa_old2.pkl", 41, 1000),
        ("v1_bbbb_new1.pkl", 1, 1000),
        ("v1_bbbb_new2.pkl", 0, 1000),
    ):
        p = os.path.join(d, name)
        with open(p, "wb") as fh:
            fh.write(b"\x00" * size)
        os.utime(p, (now - age_days * 86400, now - age_days * 86400))
    with open(os.path.join(d, "README.txt"), "w") as fh:
        fh.write("not a cache entry")

    out = prune_vm_cache(max_age_days=30, max_bytes=0, cache_dir=d)
    assert out["evicted"] == 2 and out["kept"] == 2
    left = sorted(os.listdir(d))
    assert left == ["README.txt", "v1_bbbb_new1.pkl", "v1_bbbb_new2.pkl"]
    # the prune publishes what it reclaimed through the registry (ISSUE 7
    # satellite: previously the returned dict was the only record)
    from consensus_specs_tpu.ops import profiling

    summ = profiling.summary()
    assert summ["bls.vm_cache_pruned_entries"] == {"gauge": 2.0}
    assert summ["bls.vm_cache_pruned_bytes"] == {"gauge": 2000.0}

    # size cap: keep only the newest entry's bytes
    out = prune_vm_cache(max_age_days=0, max_bytes=1000, cache_dir=d)
    assert out["evicted"] == 1 and out["kept"] == 1
    assert out["kept_bytes"] == 1000
    assert sorted(os.listdir(d)) == ["README.txt", "v1_bbbb_new2.pkl"]

    # disabled rules (<= 0) evict nothing
    out = prune_vm_cache(max_age_days=0, max_bytes=0, cache_dir=d)
    assert out["evicted"] == 0 and out["kept"] == 1


# -- final-exp row batching (ISSUE 10 tentpole layer 2) ----------------------


def test_final_exp_batcher_coalesces_concurrent_rows(monkeypatch):
    """Concurrent device-routed hard-part rows (one per flush) coalesce
    into ONE multi-row VM execution, and the window's row count lands on
    the bls.final_exp_rows_inflight gauge."""
    import threading
    import time as _time

    import numpy as np

    from consensus_specs_tpu.ops import bls_backend, fq, profiling

    calls = []

    def fake_run(rows, mesh=None, kind=None):
        calls.append((rows.shape[0], kind))
        _time.sleep(0.01)
        return np.ones(rows.shape[0], dtype=bool)

    monkeypatch.setattr(bls_backend, "_run_hard_part", fake_run)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FINAL_EXP_WINDOW_MS", "80")
    batcher = bls_backend._FinalExpBatcher()
    results = []
    barrier = threading.Barrier(4)

    def worker():
        barrier.wait()
        g = np.zeros((12, fq.NUM_LIMBS), dtype=np.uint64)
        results.append(batcher.run(g))

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == [True] * 4
    assert sum(c for c, _ in calls) == 4
    assert len(calls) == 1, calls  # one coalesced window
    # auto-routing at 4 rows picks the frobenius width-for-depth variant
    assert calls[0][1] == "hard_part_frobenius"
    gauge = profiling.summary()["bls.final_exp_rows_inflight"]["gauge"]
    assert gauge == 4.0


def test_final_exp_batcher_never_mixes_meshes(monkeypatch):
    """Windows are keyed by mesh: a sharded caller's row must never be
    diverted onto an unsharded leader's placement (or vice versa)."""
    import threading

    import numpy as np

    from consensus_specs_tpu.ops import bls_backend, fq

    calls = []

    def fake_run(rows, mesh=None, kind=None):
        calls.append((rows.shape[0], mesh))
        return np.ones(rows.shape[0], dtype=bool)

    monkeypatch.setattr(bls_backend, "_run_hard_part", fake_run)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FINAL_EXP_WINDOW_MS", "80")
    batcher = bls_backend._FinalExpBatcher()
    barrier = threading.Barrier(4)
    results = []

    def worker(mesh):
        barrier.wait()
        g = np.zeros((12, fq.NUM_LIMBS), dtype=np.uint64)
        results.append(batcher.run(g, mesh=mesh))

    # two callers per "mesh" (a hashable stand-in suffices for keying)
    threads = [threading.Thread(target=worker, args=(m,))
               for m in (None, "mesh-a", None, "mesh-a")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == [True] * 4
    assert sorted(c for c, _ in calls) == [2, 2]  # one window per mesh key
    assert sorted(str(m) for _, m in calls) == ["None", "mesh-a"]


def test_final_exp_batcher_propagates_failures(monkeypatch):
    """A failed window must fail EVERY joined caller (never hang a
    follower), and later windows recover independently."""
    import threading

    import numpy as np

    from consensus_specs_tpu.ops import bls_backend, fq

    def boom(rows, mesh=None, kind=None):
        raise RuntimeError("device fell over")

    monkeypatch.setattr(bls_backend, "_run_hard_part", boom)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FINAL_EXP_WINDOW_MS", "50")
    batcher = bls_backend._FinalExpBatcher()
    errs = []
    barrier = threading.Barrier(2)

    def worker():
        barrier.wait()
        g = np.zeros((12, fq.NUM_LIMBS), dtype=np.uint64)
        try:
            batcher.run(g)
            errs.append(None)
        except RuntimeError as e:
            errs.append(str(e))

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errs == ["device fell over"] * 2
    # recovery: a later lone row succeeds once the backend does
    monkeypatch.setattr(
        bls_backend, "_run_hard_part",
        lambda rows, mesh=None, kind=None: np.ones(rows.shape[0], dtype=bool))
    g = np.zeros((12, fq.NUM_LIMBS), dtype=np.uint64)
    assert batcher.run(g) is True


def test_hard_part_kind_routing(monkeypatch):
    """auto routes small row counts to the frobenius variant and
    lane-saturated batches to the legacy bit-serial chain; the env pin
    always wins."""
    from consensus_specs_tpu.ops import bls_backend

    monkeypatch.delenv("CONSENSUS_SPECS_TPU_HARD_PART", raising=False)
    assert bls_backend._hard_part_kind(1) == "hard_part_frobenius"
    assert bls_backend._hard_part_kind(16) == "hard_part_frobenius"
    assert bls_backend._hard_part_kind(17) == "hard_part"
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_HARD_PART", "windowed")
    assert bls_backend._hard_part_kind(1) == "hard_part_windowed"
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_HARD_PART", "bit_serial")
    assert bls_backend._hard_part_kind(1) == "hard_part"


# -- per-program .vm_cache keys (ISSUE 10 satellite) -------------------------


def test_program_fingerprints_are_per_kind():
    """Every registry kind gets its own cache fingerprint, derived from
    (vm+fq core, shared vmlib source, the kind's claimed builder source)
    — so keys are distinct and deterministic."""
    from consensus_specs_tpu.ops import bls_backend, vmlib

    fps = {k: bls_backend._program_fingerprint(k) for k in vmlib.BUILDERS}
    assert len(set(fps.values())) == len(fps)  # all distinct
    # stable across calls (lru + deterministic hashing)
    assert fps["hard_part"] == bls_backend._program_fingerprint("hard_part")


def test_builder_source_split_claims_only_its_kind():
    """The shared/local source split behind the per-program keys: each
    kind's emit/builder bodies are cut out of the shared hash and claimed
    by that kind alone, while shared algebra stays in the shared part."""
    from consensus_specs_tpu.ops import vmlib

    shared, local_hp = vmlib.builder_source_parts("hard_part")
    _, local_frob = vmlib.builder_source_parts("hard_part_frobenius")
    assert "def _emit_hard_part(" not in shared
    assert "def _emit_hard_part_frobenius(" not in shared
    assert "def _emit_hard_part(" in local_hp
    assert "def _emit_hard_part_frobenius(" in local_frob
    assert "def _emit_hard_part_frobenius(" not in local_hp
    # shared helpers every builder leans on remain in the shared hash
    assert "def f12_mul(" in shared
    assert "def f12_cyclotomic_square_comps(" in shared


def test_editing_one_builder_rekeys_only_that_kind(monkeypatch):
    """The satellite's whole point: a one-builder edit must re-key only
    that kind's cached programs (simulated by perturbing one kind's
    claimed source through builder_source_parts)."""
    from consensus_specs_tpu.ops import bls_backend, vmlib

    before = {
        k: bls_backend._program_fingerprint(k)
        for k in ("hard_part", "hard_part_frobenius", "rlc_combine")
    }
    real = vmlib.builder_source_parts

    def perturbed(kind):
        shared, local = real(kind)
        if kind == "hard_part_frobenius":
            local = local + "# edited\n"
        return shared, local

    monkeypatch.setattr(vmlib, "builder_source_parts", perturbed)
    bls_backend._program_fingerprint.cache_clear()
    bls_backend._core_fingerprint_parts.cache_clear()
    try:
        after = {
            k: bls_backend._program_fingerprint(k)
            for k in ("hard_part", "hard_part_frobenius", "rlc_combine")
        }
    finally:
        monkeypatch.undo()
        bls_backend._program_fingerprint.cache_clear()
        bls_backend._core_fingerprint_parts.cache_clear()
    assert after["hard_part_frobenius"] != before["hard_part_frobenius"]
    assert after["hard_part"] == before["hard_part"]
    assert after["rlc_combine"] == before["rlc_combine"]


def test_prune_evicts_stale_fingerprint_entries(tmp_path):
    """Entries whose cache version or per-program fingerprint no longer
    matches the current sources can never hit again — prune_vm_cache
    evicts them regardless of age; unknown kinds and current-fingerprint
    entries stay."""
    import os

    from consensus_specs_tpu.ops import bls_backend
    from consensus_specs_tpu.ops.bls_backend import (
        _VM_CACHE_VERSION,
        prune_vm_cache,
    )

    d = str(tmp_path)
    cur_fp = bls_backend._program_fingerprint("hard_part")
    v = _VM_CACHE_VERSION
    names = {
        # current version + current fingerprint: kept
        f"v{v}_{cur_fp}_hard_part_k0_f1_w96x192_p256.pkl": False,
        # current version, stale fingerprint for a known kind: evicted
        f"v{v}_{'0' * 10}_hard_part_k0_f32_w96x192_p256.pkl": True,
        # old cache version: evicted
        f"v{v - 1}_{'a' * 10}_hard_part_k0_f1_w96x192_p256.pkl": True,
        # unknown kind (older/newer checkout): kept for age/size rules
        f"v{v}_{'b' * 10}_future_kind_k0_f1_w96x192_p256.pkl": False,
        # non-cache-shaped name: untouched
        "v1_aaaa_old1.pkl": False,
    }
    for name in names:
        with open(os.path.join(d, name), "wb") as fh:
            fh.write(b"\x00" * 10)
    out = prune_vm_cache(max_age_days=0, max_bytes=0, cache_dir=d)
    assert out["evicted"] == 2
    left = set(os.listdir(d))
    for name, evicted in names.items():
        assert (name not in left) == evicted, name
    # evict_stale=False restores the pure age/size behavior
    out = prune_vm_cache(max_age_days=0, max_bytes=0, cache_dir=d,
                         evict_stale=False)
    assert out["evicted"] == 0
