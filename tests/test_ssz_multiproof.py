"""Merkle multiproof tests: construction + verification round-trips over
live views (reference algebra: ssz/merkle-proofs.md:249-357), packed
basic-leaf proofs, and a light-client-style multiproof over the altair
BeaconState authenticating both sync-protocol gindices in one proof."""
import random

from consensus_specs_tpu.utils.ssz.gindex import get_generalized_index
from consensus_specs_tpu.utils.ssz.proofs import (
    build_multiproof,
    build_proof,
    calculate_merkle_root,
    get_branch_indices,
    get_helper_indices,
    get_tree_node,
    verify_merkle_multiproof,
    verify_merkle_proof,
)
from consensus_specs_tpu.utils.ssz.ssz_typing import (
    Bitlist,
    ByteVector,
    Container,
    List,
    Union,
    Vector,
    uint64,
)

Bytes32 = ByteVector[32]


class Pair(Container):
    x: uint64
    y: Bytes32


class Demo(Container):
    slot: uint64
    pair: Pair
    nums: List[uint64, 4096]
    pairs: List[Pair, 1 << 20]
    roots: Vector[Bytes32, 64]
    bits: Bitlist[2048]
    tag: Union[None, uint64, Pair]


def make_demo(rng):
    return Demo(
        slot=uint64(rng.randrange(1 << 40)),
        pair=Pair(x=uint64(7), y=Bytes32(rng.randbytes(32))),
        nums=List[uint64, 4096]([uint64(rng.randrange(1 << 50)) for _ in range(100)]),
        pairs=List[Pair, 1 << 20](
            [Pair(x=uint64(i), y=Bytes32(rng.randbytes(32))) for i in range(33)]
        ),
        roots=Vector[Bytes32, 64]([Bytes32(rng.randbytes(32)) for _ in range(64)]),
        bits=Bitlist[2048]([bool(rng.randrange(2)) for _ in range(700)]),
        tag=Union[None, uint64, Pair](1, uint64(99)),
    )


def test_single_proof_paths_incl_packed_basics():
    rng = random.Random(5)
    d = make_demo(rng)
    root = d.hash_tree_root()
    cases = [
        (("slot",), d.slot.hash_tree_root()),
        (("pair",), d.pair.hash_tree_root()),
        (("pair", "y"), d.pair.y.hash_tree_root()),
        (("pairs", 17), d.pairs[17].hash_tree_root()),
        (("pairs", 17, "x"), d.pairs[17].x.hash_tree_root()),
        (("roots", 63), d.roots[63].hash_tree_root()),
        # packed basic leaves (previously raised NotImplementedError):
        # the proven leaf is the CHUNK holding the element
        (("nums", 10), None),
        (("bits", 300), None),
        (("nums", "__len__"), len(d.nums).to_bytes(32, "little")),
    ]
    for path, leaf in cases:
        g = get_generalized_index(Demo, *path)
        if leaf is None:
            leaf = get_tree_node(d, g)
        proof = build_proof(d, *path)
        assert verify_merkle_proof(leaf, proof, g, root), path
        # tamper detection
        bad = bytes(32) if bytes(leaf) != bytes(32) else b"\x01" * 32
        assert not verify_merkle_proof(bad, proof, g, root), path


def test_packed_chunk_leaf_contains_element_bytes():
    rng = random.Random(6)
    d = make_demo(rng)
    g = get_generalized_index(Demo, "nums", 10)
    chunk = get_tree_node(d, g)
    # uint64 packing: 4 per chunk, element 10 at offset (10 % 4) * 8
    off = (10 % 4) * 8
    assert chunk[off : off + 8] == int(d.nums[10]).to_bytes(8, "little")


def test_multiproof_round_trip_random_index_sets():
    rng = random.Random(7)
    d = make_demo(rng)
    root = d.hash_tree_root()
    paths = [
        ("slot",),
        ("pair", "x"),
        ("pair", "y"),
        ("pairs", 3),
        ("pairs", 30, "y"),
        ("roots", 0),
        ("roots", 31),
        ("nums", 5),
        ("bits", 100),
        ("nums", "__len__"),
    ]
    for _ in range(12):
        k = rng.randrange(1, 6)
        chosen = rng.sample(paths, k)
        gindices = [get_generalized_index(Demo, *p) for p in chosen]
        if len(set(gindices)) != len(gindices):
            continue  # duplicate target nodes are degenerate
        leaves, proof = build_multiproof(d, gindices)
        assert verify_merkle_multiproof(leaves, proof, gindices, root)
        if proof:
            tampered = list(proof)
            tampered[0] = b"\xff" * 32
            assert not verify_merkle_multiproof(leaves, tampered, gindices, root)
        if leaves:
            tampered = list(leaves)
            tampered[-1] = b"\xfe" * 32
            assert not verify_merkle_multiproof(tampered, proof, gindices, root)


def test_multiproof_shares_helpers_vs_single_proofs():
    """The point of a multiproof: fewer helper nodes than the sum of the
    individual branches."""
    rng = random.Random(8)
    d = make_demo(rng)
    gindices = [
        get_generalized_index(Demo, "roots", 0),
        get_generalized_index(Demo, "roots", 1),
        get_generalized_index(Demo, "roots", 2),
    ]
    helpers = get_helper_indices(gindices)
    singles = sum(len(get_branch_indices(g)) for g in gindices)
    assert len(helpers) < singles


def test_single_is_special_case_of_multi():
    rng = random.Random(9)
    d = make_demo(rng)
    root = d.hash_tree_root()
    g = get_generalized_index(Demo, "pairs", 7)
    branch = build_proof(d, "pairs", 7)
    leaves, proof = build_multiproof(d, [g])
    assert [bytes(b) for b in proof] == [bytes(b) for b in branch]
    assert leaves == [get_tree_node(d, g)]
    assert calculate_merkle_root(leaves[0], proof, g) == bytes(root)


def test_union_nodes():
    rng = random.Random(10)
    d = make_demo(rng)
    root = d.hash_tree_root()
    g_tag = get_generalized_index(Demo, "tag")
    proof = [get_tree_node(d, i) for i in get_branch_indices(g_tag)]
    assert verify_merkle_proof(d.tag.hash_tree_root(), proof, g_tag, root)


def test_cold_cache_proofs_bit_identical_to_warm(monkeypatch):
    """ISSUE 16 satellite: the proof builders read interior nodes out of
    the incremental `_ChunkTree` layer caches when a series has hashed
    before, and fall back to explicit re-merkleization (`_chunk_layer` +
    `_subtree_node`) when it hasn't. The two routes must be bit-identical
    — a freshly deserialized view (cold caches) must serve the exact
    bytes a long-lived warm view serves. Forcing `_cached_tree` to None
    disables the cache route outright, so every node goes through the
    fallback."""
    from consensus_specs_tpu.utils.ssz import proofs as proofs_mod

    rng = random.Random(11)
    d = make_demo(rng)
    root = bytes(d.hash_tree_root())  # warms every series cache
    paths = [
        ("slot",), ("pairs", 17), ("pairs", 30, "y"), ("roots", 63),
        ("nums", 10), ("bits", 300), ("nums", "__len__"),
    ]
    gindices = [get_generalized_index(Demo, *p) for p in paths]

    warm_branches = [build_proof(d, *p) for p in paths]
    warm_leaves, warm_multi = build_multiproof(d, gindices)

    monkeypatch.setattr(proofs_mod, "_cached_tree", lambda view: None)
    cold_branches = [build_proof(d, *p) for p in paths]
    cold_leaves, cold_multi = build_multiproof(d, gindices)
    monkeypatch.undo()

    for path, warm, cold in zip(paths, warm_branches, cold_branches):
        assert [bytes(x) for x in warm] == [bytes(x) for x in cold], path
    assert [bytes(x) for x in warm_leaves] == [bytes(x) for x in cold_leaves]
    assert [bytes(x) for x in warm_multi] == [bytes(x) for x in cold_multi]
    # both routes verify against the one root
    assert verify_merkle_multiproof(cold_leaves, cold_multi, gindices, root)


def test_fresh_deserialization_proofs_match_warm_view():
    """The decode_bytes round trip — a view whose layer caches were never
    warmed by incremental updates, the state every proof-serving replica
    restarts into — must produce bit-identical branches to the long-lived
    view it was serialized from, over the light-client gindices (105:
    finalized_checkpoint.root, 55: next_sync_committee)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from consensus_specs_tpu.builder import build_spec_module

    spec = build_spec_module("altair", "minimal")
    warm = spec.BeaconState()
    warm.slot = spec.Slot(77)
    warm.finalized_checkpoint.epoch = spec.Epoch(4)
    warm.finalized_checkpoint.root = spec.Root(b"\x17" * 32)
    root = bytes(warm.hash_tree_root())

    cold = spec.BeaconState.decode_bytes(warm.encode_bytes())
    assert bytes(cold.hash_tree_root()) == root

    g_fin = get_generalized_index(spec.BeaconState,
                                  "finalized_checkpoint", "root")
    g_sync = get_generalized_index(spec.BeaconState, "next_sync_committee")
    warm_fin = build_proof(warm, "finalized_checkpoint", "root")
    cold_fin = build_proof(cold, "finalized_checkpoint", "root")
    assert [bytes(x) for x in warm_fin] == [bytes(x) for x in cold_fin]
    warm_leaves, warm_proof = build_multiproof(warm, [g_fin, g_sync])
    cold_leaves, cold_proof = build_multiproof(cold, [g_fin, g_sync])
    assert [bytes(x) for x in warm_leaves] == [bytes(x) for x in cold_leaves]
    assert [bytes(x) for x in warm_proof] == [bytes(x) for x in cold_proof]
    assert verify_merkle_multiproof(cold_leaves, cold_proof,
                                    [g_fin, g_sync], root)


def test_light_client_multiproof_over_altair_state():
    """One multiproof authenticating finalized_checkpoint.root AND
    next_sync_committee — the two altair sync-protocol commitments
    (reference specs/altair/sync-protocol.md:67-85 carries them as two
    separate branches; a multiproof serves both from one witness set)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from consensus_specs_tpu.builder import build_spec_module

    spec = build_spec_module("altair", "minimal")
    state = spec.BeaconState()
    state.slot = spec.Slot(1234)
    state.finalized_checkpoint.epoch = spec.Epoch(9)
    state.finalized_checkpoint.root = spec.Root(b"\x42" * 32)

    g_fin = get_generalized_index(spec.BeaconState, "finalized_checkpoint", "root")
    g_sync = get_generalized_index(spec.BeaconState, "next_sync_committee")
    # the sync-protocol constants (reference specs/altair/sync-protocol.md +
    # setup.py:476-481): FINALIZED_ROOT_INDEX=105 addresses the checkpoint's
    # `root` field, NEXT_SYNC_COMMITTEE_INDEX=55 the committee container
    assert int(g_fin) == 105
    assert int(g_sync) == 55
    leaves, proof = build_multiproof(state, [g_fin, g_sync])
    assert verify_merkle_multiproof(
        leaves, proof, [g_fin, g_sync], state.hash_tree_root()
    )
    assert bytes(leaves[0]) == bytes(state.finalized_checkpoint.root)
    assert bytes(leaves[1]) == bytes(state.next_sync_committee.hash_tree_root())
