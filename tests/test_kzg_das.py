"""KZG commitments + DAS erasure coding (utils/kzg.py; reference
specs/das/das-core.md:63-190, specs/sharding/beacon-chain.md:717-721)."""
from random import Random

import pytest

from consensus_specs_tpu.utils import kzg
from consensus_specs_tpu.utils.kzg import MODULUS

RNG = Random(1717)
N = 16  # polynomial/evaluation domain size for the tests
SETUP = kzg.Setup(tau=RNG.randrange(2, MODULUS), n=2 * N)


def _random_data(n):
    return [RNG.randrange(MODULUS) for _ in range(n)]


def test_fft_matches_naive_evaluation():
    coeffs = _random_data(8)
    omega = kzg.root_of_unity(8)
    evals = kzg.fft(coeffs)
    for i in range(8):
        x = pow(omega, i, MODULUS)
        want = sum(c * pow(x, k, MODULUS) for k, c in enumerate(coeffs)) % MODULUS
        assert evals[i] == want


def test_fft_ifft_roundtrip():
    coeffs = _random_data(N)
    assert kzg.inverse_fft(kzg.fft(coeffs)) == coeffs


def test_das_extension_halves_are_consistent():
    # the defining property: IFFT of the reverse-bit-ordered extended data
    # has an all-zero second half (das-core.md:89-97, 113-121)
    data = _random_data(N)
    extended = kzg.extend_data(data)
    assert extended[:N] == data
    poly = kzg.inverse_fft(kzg.reverse_bit_order_list(extended))
    assert all(c == 0 for c in poly[N:])
    assert kzg.unextend_data(extended) == data


@pytest.mark.parametrize("missing", [[0], [1, 3], [0, 2, 5, 7]])
def test_recover_data(missing):
    # split the extended data into 8 subgroups, drop up to half, recover
    data = _random_data(N)
    extended = kzg.extend_data(data)
    rbo = kzg.reverse_bit_order_list(extended)
    points_per = len(rbo) // 8
    subgroups = [rbo[i * points_per:(i + 1) * points_per] for i in range(8)]
    damaged = [None if i in missing else s for i, s in enumerate(subgroups)]
    recovered = kzg.recover_data(damaged)
    assert recovered == rbo


def test_recover_data_rejects_inconsistent_samples():
    data = _random_data(N)
    rbo = kzg.reverse_bit_order_list(kzg.extend_data(data))
    points_per = len(rbo) // 8
    subgroups = [list(rbo[i * points_per:(i + 1) * points_per]) for i in range(8)]
    subgroups[7][0] = (subgroups[7][0] + 1) % MODULUS  # corrupt one point
    with pytest.raises(AssertionError):
        kzg.recover_data(subgroups)


def test_kzg_single_point_proof():
    coeffs = _random_data(N)
    commitment = kzg.commit_to_poly(SETUP, coeffs)
    z = RNG.randrange(MODULUS)
    proof, y = kzg.prove_at_point(SETUP, coeffs, z)
    assert kzg.verify_point_proof(SETUP, commitment, proof, z, y)
    assert not kzg.verify_point_proof(SETUP, commitment, proof, z, (y + 1) % MODULUS)
    assert not kzg.verify_point_proof(SETUP, commitment, proof, (z + 1) % MODULUS, y)


def test_kzg_coset_multi_proof():
    # one DAS sample: a coset of size 4 out of the N-point domain
    coeffs = _random_data(N)
    commitment = kzg.commit_to_poly(SETUP, coeffs)
    coset_size = 4
    x = pow(kzg.root_of_unity(N), 3, MODULUS)  # an arbitrary domain point
    proof, ys = kzg.prove_coset(SETUP, coeffs, x, coset_size)
    assert kzg.check_multi_kzg_proof(SETUP, commitment, proof, x, ys)
    bad_ys = list(ys)
    bad_ys[0] = (bad_ys[0] + 1) % MODULUS
    assert not kzg.check_multi_kzg_proof(SETUP, commitment, proof, x, bad_ys)


def test_commit_to_data_matches_commit_to_poly():
    data = _random_data(N)
    poly = kzg.inverse_fft(kzg.reverse_bit_order_list(data))
    from consensus_specs_tpu.utils.bls12_381 import ec_eq

    assert ec_eq(
        kzg.commit_to_data(SETUP, data), kzg.commit_to_poly(SETUP, poly)
    )


def test_sharding_degree_proof():
    # (reference specs/sharding/beacon-chain.md:717-721)
    points_count = N
    coeffs = _random_data(points_count)
    commitment = kzg.commit_to_poly(SETUP, coeffs)
    dproof = kzg.degree_proof(SETUP, coeffs, points_count)
    assert kzg.verify_degree_proof(SETUP, commitment, dproof, points_count)
    # a polynomial of HIGHER degree cannot satisfy the bound's proof shape:
    # reusing the same degree_proof with a different commitment must fail
    other = kzg.commit_to_poly(SETUP, _random_data(2 * N))
    assert not kzg.verify_degree_proof(SETUP, other, dproof, points_count)


def test_das_sampling_end_to_end():
    """extend -> sample (multiproofs) -> verify each -> drop half ->
    reconstruct (utils/das.py; das-core.md:113-190)."""
    from consensus_specs_tpu.utils import das

    data = _random_data(N)
    extended = kzg.extend_data(data)
    points_per_sample = 4
    sample_count = len(extended) // points_per_sample
    commitment = kzg.commit_to_data(SETUP, extended)
    samples = das.sample_data(SETUP, extended, points_per_sample)
    assert len(samples) == sample_count
    for s in samples:
        assert das.verify_sample(SETUP, s, sample_count, commitment)
    # a corrupted sample fails verification
    bad = das.DASSample(
        index=samples[0].index, proof=samples[0].proof,
        data=[(samples[0].data[0] + 1) % kzg.MODULUS] + list(samples[0].data[1:]),
    )
    assert not das.verify_sample(SETUP, bad, sample_count, commitment)
    # out-of-range index: rejected, not aliased
    oob = das.DASSample(
        index=samples[0].index + sample_count, proof=samples[0].proof,
        data=list(samples[0].data),
    )
    assert not das.verify_sample(SETUP, oob, sample_count, commitment)
    # reconstruct from half the samples — alternating AND contiguous drops
    for keep in (
        lambda i: i % 2 == 0,
        lambda i: i < sample_count // 2,
        lambda i: i >= sample_count // 2,
    ):
        kept = [s if keep(i) else None for i, s in enumerate(samples)]
        recovered = das.reconstruct_extended_data(
            kept, sample_count, points_per_sample
        )
        assert recovered == list(extended)


def test_sharding_fee_market_and_blob_check():
    """Sample-price updates move toward target and stay bounded; the
    shard-blob acceptance combines commitment + degree proof
    (utils/sharding.py; sharding/beacon-chain.md:433-457, 700-721)."""
    from consensus_specs_tpu.utils import sharding

    price = 1000
    # oversubscribed blobs push the price up, capped
    up = sharding.compute_updated_sample_price(
        price, sharding.MAX_SAMPLES_PER_BLOB, active_shards=64
    )
    assert up > price
    assert sharding.compute_updated_sample_price(
        sharding.MAX_SAMPLE_PRICE, sharding.MAX_SAMPLES_PER_BLOB, 64
    ) == sharding.MAX_SAMPLE_PRICE
    # undersubscribed pulls it down, floored
    down = sharding.compute_updated_sample_price(price, 0, active_shards=64)
    assert down < price
    assert sharding.compute_updated_sample_price(
        sharding.MIN_SAMPLE_PRICE, 0, 64
    ) <= sharding.MIN_SAMPLE_PRICE
    # exactly on target with minimal delta: stable within the min delta of 1
    assert abs(sharding.compute_updated_sample_price(
        price, sharding.TARGET_SAMPLES_PER_BLOB, 64
    ) - price) <= 1

    # committee lookahead: one period behind the period boundary
    P_ = 64
    assert sharding.compute_committee_source_epoch(P_ * 3 + 5, P_) == P_ * 2
    assert sharding.compute_committee_source_epoch(P_ - 1, P_) == 0

    # blob acceptance
    data = _random_data(N)
    poly = kzg.inverse_fft(kzg.reverse_bit_order_list(data))
    commitment = kzg.commit_to_data(SETUP, data)
    dproof = kzg.degree_proof(SETUP, poly, N)
    assert sharding.verify_shard_blob_commitment(SETUP, commitment, dproof, data)
    other = kzg.commit_to_poly(SETUP, _random_data(N))
    assert not sharding.verify_shard_blob_commitment(SETUP, other, dproof, data)
