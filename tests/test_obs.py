"""Observability plane (consensus_specs_tpu/obs/): span tracing through
the serve pipeline, Chrome trace export (golden-schema gated), the
Prometheus /metrics + /snapshot + /healthz endpoint under live load,
concurrent writers-vs-readers safety, the per-program VM registry, and
the profiling satellites (dynamic ENABLED, full reset).

Everything here runs against crypto-free backends so tier-1 stays fast;
the real-crypto serve path is covered by tests/test_serve.py and the
trace/endpoint glue by `make serve-trace`.
"""
import json
import os
import random
import re
import sys
import threading
import time
import urllib.request

import pytest

from consensus_specs_tpu.obs import devices, flight, slo
from consensus_specs_tpu.obs import programs as obs_programs
from consensus_specs_tpu.obs import registry, tracing
from consensus_specs_tpu.obs.exposition import start_exposition
from consensus_specs_tpu.obs.tracing import (
    CHAIN_STAGES,
    STAGES,
    WORKER_PID_BASE,
    Tracer,
    stitched_chrome,
    trace_to_wire,
    wire_spans,
)
from consensus_specs_tpu.ops import profiling
from consensus_specs_tpu.serve import VerificationService
from consensus_specs_tpu.serve.metrics import ServeMetrics
from consensus_specs_tpu.utils import bls

PK = b"\x01" * 48
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden",
                      "obs_trace_golden.json")


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    # the obs plane and profiling are process-global; every test starts
    # from zero and leaves tracing disabled
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_TRACE", "0")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "0")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_DEVICES", "0")
    profiling.reset()
    tracing.reset_global()
    obs_programs.reset()
    devices.reset_global()
    flight.reset_global()
    slo.reset_global()
    was = bls.bls_active
    bls.bls_active = True
    yield
    bls.bls_active = was
    tracing.reset_global()
    devices.reset_global()
    flight.reset_global()
    slo.reset_global()


class RlcBackend:
    """Crypto-free batched backend WITH the RLC entry point (so the serve
    default route — and therefore the `combine` span — is exercised):
    an item verifies True iff its signature ends with b"ok"."""

    def __init__(self):
        self.rlc_calls = 0
        self.calls = 0

    def batch_verify_rlc(self, items, mesh=None, rng=None):
        self.rlc_calls += 1
        return [sig.endswith(b"ok") for _kind, _pks, _msgs, sig in items]

    def _go(self, signatures):
        self.calls += 1
        return [s.endswith(b"ok") for s in signatures]

    def batch_fast_aggregate_verify(self, pubkey_sets, messages, signatures,
                                    mesh=None):
        return self._go(signatures)

    def batch_aggregate_verify(self, pubkey_lists, message_lists, signatures,
                               mesh=None):
        return self._go(signatures)


class _Oracle:
    def verify_one(self, pending):
        return bytes(pending.signature).endswith(b"ok")


def _svc(backend, **kw):
    kw.setdefault("bucket_fn", lambda k: 8)
    kw.setdefault("oracle", _Oracle())
    return VerificationService(backend=backend, **kw)


# -- tracer core ------------------------------------------------------------


def test_tracer_ring_is_bounded():
    tr = Tracer(capacity=8)
    for i in range(50):
        t = tr.begin("fast_aggregate", 2, t_submit=float(i))
        tr.span(t, "queue_wait", float(i), float(i) + 0.5)
        tr.finish(t, True, t_done=float(i) + 1.0)
    done = tr.completed()
    assert len(done) == 8  # bounded, keeps the newest
    assert done[-1].total_s == 1.0 and done[-1].ok is True
    assert done[0].rid == 43  # 50 begun, first 42 evicted
    assert tr.finished_total() == 50  # the monotone count is NOT capped
    other = tr.to_chrome()["otherData"]
    assert (other["requests"], other["finished_total"]) == (8, 50)


def test_tracer_pins_slow_exemplars_over_running_p99():
    tr = Tracer(capacity=256, exemplar_capacity=4)
    # 100 fast requests establish the running p99, then one 100x outlier
    for i in range(100):
        t = tr.begin("fast_aggregate", 1, t_submit=0.0)
        tr.finish(t, True, t_done=0.010)
    slow = tr.begin("fast_aggregate", 1, t_submit=0.0)
    tr.finish(slow, True, t_done=1.0)
    assert slow.pinned
    assert slow in tr.exemplars()
    assert len(tr.exemplars()) <= 4
    assert tr.running_p99_s() > 0


def test_events_before_tracer_epoch_never_export_negative_ts():
    """The global tracer is created lazily: the first traced VM execution
    (or a trace begun with an earlier explicit t_submit) can predate the
    tracer's epoch. The epoch rewinds so Perfetto never clamps/drops
    those events for sitting before the trace origin."""
    tr = Tracer(clock=lambda: 100.0)  # epoch = 100.0
    tr.note_execution(steps=1, regs=1, batch=(), sharded=False,
                      t0=40.0, seconds=30.0)  # finished before epoch
    early = tr.begin("fast_aggregate", 1, t_submit=50.0)
    tr.span(early, "queue_wait", 50.0, 60.0)
    tr.finish(early, True, t_done=60.0)
    for ev in tr.to_chrome()["traceEvents"]:
        if ev["ph"] == "X":
            assert ev["ts"] >= 0.0, ev
    # the execution sits exactly at the (rewound) origin
    vm_ev = [e for e in tr.to_chrome()["traceEvents"]
             if e["pid"] == 2 and e["ph"] == "X"][0]
    assert vm_ev["ts"] == 0.0


def test_span_many_skips_none_traces():
    tr = Tracer()
    a = tr.begin("aggregate", 3, t_submit=0.0)
    tr.span_many([a, None], "prep", 0.0, 1.0)
    assert a.span_names() == {"prep"}


# -- chrome export ----------------------------------------------------------


def _golden_tracer():
    """Deterministic tracer + registry content (fixed clock, fixed
    timestamps) — the input of the golden-file test."""
    t = {"now": 0.0}

    def clock():
        t["now"] += 0.001
        return t["now"]

    tr = Tracer(capacity=16, exemplar_capacity=4, clock=clock)  # _t0=0.001
    # the serve request carries a gossip ingress record (ISSUE 12): an
    # ingress span from its birth timestamp and flow id 7 — the Chrome
    # flow link the chain batch below terminates
    req = tr.begin("fast_aggregate", 2, t_submit=0.002, flow=7)
    tr.span(req, "ingress", 0.0015, 0.002)
    tr.span(req, "queue_wait", 0.002, 0.004)
    tr.span(req, "prep", 0.004, 0.005)
    tr.span(req, "combine", 0.006, 0.008)
    tr.span(req, "device", 0.005, 0.009)
    tr.span(req, "finalize", 0.009, 0.010)
    tr.finish(req, True, t_done=0.010)
    tr.note_execution(steps=256, regs=640, batch=(4,), sharded=False,
                      t0=0.005, seconds=0.003)
    # one chain-plane batch record (PR 5's validate/sig_wait/apply/sweep
    # stages — part of the golden schema since PR 7 so the trace-coverage
    # gate below can hold every registered stage to an export; the head
    # stage + absorbed flow ids are the ISSUE 12 gossip→head stitching)
    chain = tr.begin("chain_apply", 3, t_submit=0.011)
    tr.span(chain, "validate", 0.011, 0.012)
    tr.span(chain, "sig_wait", 0.012, 0.014)
    tr.span(chain, "apply", 0.014, 0.015)
    tr.span(chain, "sweep", 0.015, 0.016)
    tr.span(chain, "head", 0.016, 0.017)
    # flow 7 is the router-local serve request above; 8 and 9 were
    # forwarded over the worker protocol and STARTED on worker pids
    # (_golden_worker_sections) — the chain batch finishes all three
    # (ISSUE 19: flow ids survive the process boundary)
    chain.flows = (7, 8, 9)
    tr.finish(chain, True, t_done=0.017)
    obs_programs.note_assembly("hard_part[k=0,fold=32]", n_steps=4864,
                               n_regs=1024, seconds=1.5,
                               disk_cache_hit=False)
    obs_programs.note_assembly("miller_product[k=8,fold=8]", n_steps=2816,
                               n_regs=960, seconds=0.0123,
                               disk_cache_hit=True)
    return tr


def _golden_worker_sections():
    """Deterministic per-worker span sections (the shape
    ``FleetAggregator.worker_span_sections`` returns): two workers, one
    request each, every serve stage present, each carrying the flow id
    the router forwarded (8 and 9 — terminated by the chain batch in
    ``_golden_tracer``). w0's submit predates the router tracer's epoch,
    so the stitch's origin-rewind is part of the golden too."""

    def wire(rid, flow, t0):
        return {
            "rid": rid, "kind": "fast_aggregate", "n_keys": 2,
            "t_submit": t0, "ok": True, "pinned": False,
            "total_s": 0.0035, "flow": flow, "flows": [],
            "spans": [["queue_wait", t0, t0 + 0.001],
                      ["prep", t0 + 0.001, t0 + 0.0015],
                      ["combine", t0 + 0.002, t0 + 0.0025],
                      ["device", t0 + 0.0015, t0 + 0.003],
                      ["finalize", t0 + 0.003, t0 + 0.0035]],
        }

    return {"w0": {"pid": 4242, "traces": [wire(1, 8, 0.0005)]},
            "w1": {"pid": 4243, "traces": [wire(1, 9, 0.003)]}}


def _golden_stitched():
    return stitched_chrome(_golden_tracer(), _golden_worker_sections())


def test_chrome_export_schema():
    doc = _golden_tracer().to_chrome()
    assert set(doc) == {"traceEvents", "displayTimeUnit", "programRegistry",
                        "otherData"}
    names = set()
    flow_events = []
    for ev in doc["traceEvents"]:
        # "s"/"f" are Chrome FLOW events (the ISSUE 12 gossip→head links)
        assert ev["ph"] in ("X", "M", "s", "f")
        assert isinstance(ev["pid"], int)
        if ev["ph"] == "X":
            assert ev["ts"] >= 0 and ev["dur"] >= 0
            assert isinstance(ev["tid"], int)
            names.add(ev["name"])
        elif ev["ph"] in ("s", "f"):
            flow_events.append(ev)
    # all five pipeline stages + the ingress hop + the chain batch stages
    # + the VM execution row made it out
    assert set(STAGES) <= names
    assert set(CHAIN_STAGES) <= names
    assert "ingress" in names and "head" in names
    assert any(n.startswith("vm[steps=256") for n in names)
    # the flow arrows: ONE local start (the serve request's finalize) and
    # a finish per absorbed flow id on the chain batch's head stage —
    # ids 8/9 get their starts from worker pids in the STITCHED export
    starts = [e for e in flow_events if e["ph"] == "s"]
    finishes = [e for e in flow_events if e["ph"] == "f"]
    assert len(starts) == 1 and starts[0]["id"] == 7
    assert sorted(e["id"] for e in finishes) == [7, 8, 9]
    assert all(starts[0]["ts"] <= e["ts"] and e["bp"] == "e"
               for e in finishes)
    reg = doc["programRegistry"]
    assert reg["vm_cache"] == {"disk_hits": 1, "disk_misses": 1}
    assert reg["programs"]["hard_part[k=0,fold=32]"]["vm_cache"] == "miss"
    assert reg["programs"]["hard_part[k=0,fold=32]"]["assembly_s"] == 1.5


def test_every_registered_span_stage_is_exported():
    """The trace-coverage gate (ISSUE 7 satellite): every span stage any
    plane registers in ``obs/registry.SPAN_STAGES`` must appear in the
    golden tracer's Chrome export — a plane that registers stages but
    never exports them (or registers a stage the tracing plane dropped)
    fails HERE, so future planes cannot silently ship untraced."""
    doc = _golden_tracer().to_chrome()
    exported = {ev["name"] for ev in doc["traceEvents"] if ev["ph"] == "X"}
    for plane, stages in registry.SPAN_STAGES.items():
        missing = set(stages) - exported
        assert not missing, (
            f"plane {plane!r} registers span stages that no exported "
            f"trace carries: {sorted(missing)} — extend _golden_tracer() "
            "with the new plane's spans (and regen the golden) so the "
            "coverage gate holds it to an export"
        )
    # the re-exported tuples stay in lockstep with the registry
    assert STAGES == registry.SPAN_STAGES["serve"]
    assert CHAIN_STAGES == registry.SPAN_STAGES["chain"]


def test_stitched_chrome_joins_worker_pids_by_flow_id():
    """The ISSUE 19 stitching contract: worker spans render on their own
    pids (WORKER_PID_BASE + index in sorted-label order), every serve
    stage appears on EVERY worker pid, and each forwarded flow id's
    worker-side start has a router-side finish — the fleet trace reads
    as one pipeline across >= 2 processes."""
    doc = _golden_stitched()
    pids = doc["otherData"]["workerPids"]
    assert pids == {"w0": {"pid": WORKER_PID_BASE, "os_pid": 4242},
                    "w1": {"pid": WORKER_PID_BASE + 1, "os_pid": 4243}}
    by_pid = {}
    starts, finishes = {}, set()
    for ev in doc["traceEvents"]:
        if ev["ph"] == "X":
            by_pid.setdefault(ev["pid"], set()).add(ev["name"])
        elif ev["ph"] == "s":
            starts[ev["id"]] = ev
        elif ev["ph"] == "f":
            finishes.add(ev["id"])
    worker_pids = [p for p in by_pid if p >= WORKER_PID_BASE]
    assert len(worker_pids) >= 2
    for pid in worker_pids:
        assert set(STAGES) <= by_pid[pid], f"pid {pid} missing stages"
    # every worker-side flow start joins a router-side finish by id,
    # start before finish (Perfetto draws the cross-pid arrow)
    worker_starts = {fid: ev for fid, ev in starts.items()
                     if ev["pid"] >= WORKER_PID_BASE}
    assert sorted(worker_starts) == [8, 9]
    assert set(worker_starts) <= finishes
    finish_ts = {ev["id"]: ev["ts"] for ev in doc["traceEvents"]
                 if ev["ph"] == "f"}
    for fid, ev in worker_starts.items():
        assert ev["ts"] <= finish_ts[fid]
    # w0's submit (0.0005s) predates the tracer epoch (0.001s): the
    # rewind keeps every stitched timestamp non-negative
    assert all(ev["ts"] >= 0 for ev in doc["traceEvents"]
               if ev["ph"] in ("X", "s", "f"))


def test_trace_wire_roundtrip_and_rid_deltas():
    """`trace_to_wire` is JSON-safe and `wire_spans` ships rid DELTAS —
    the snapshot carrier contract the aggregator's watermarks rely on."""
    t = {"now": 0.0}

    def clock():
        t["now"] += 0.001
        return t["now"]

    tr = Tracer(capacity=8, clock=clock)
    for i in range(3):
        req = tr.begin("fast_aggregate", 1, t_submit=0.001 * i,
                       flow=20 + i)
        tr.span(req, "finalize", 0.001 * i, 0.001 * i + 0.0005)
        tr.finish(req, True, t_done=0.001 * i + 0.0005)
    wires = wire_spans(tr)
    assert [w["rid"] for w in wires] == [1, 2, 3]
    # JSON round trip preserves everything the stitch consumes
    back = json.loads(json.dumps(wires[0]))
    assert back == trace_to_wire(tr.completed()[0])
    assert back["flow"] == 20 and back["spans"][0][0] == "finalize"
    # the incremental form: only rids past the watermark ship
    assert [w["rid"] for w in wire_spans(tr, since_rid=2)] == [3]


def test_chrome_export_matches_golden(tmp_path):
    """The export schema is a public contract (Perfetto/chrome://tracing
    consume it): byte-identical JSON for a fixed synthetic input — the
    STITCHED document since ISSUE 19, so the golden pins worker pids and
    cross-process flow joins too. On intentional schema changes
    regenerate with `python tests/test_obs.py --regen-golden`."""
    path = str(tmp_path / "trace.json")
    with open(path, "w") as fh:
        fh.write(json.dumps(_golden_stitched(), indent=1, sort_keys=True))
    with open(path) as fh:
        got = json.load(fh)
    with open(GOLDEN) as fh:
        want = json.load(fh)
    assert got == want


# -- service integration ----------------------------------------------------


def test_service_traces_all_five_stages():
    be = RlcBackend()
    tracer = Tracer()
    with _svc(be, tracer=tracer, max_batch=4, max_wait_ms=10_000) as svc:
        futs = [
            svc.submit("fast_aggregate", [PK], b"m%d" % i, b"s%d-ok" % i)
            for i in range(4)
        ]
        assert all(f.result(timeout=10) is True for f in futs)
    assert be.rlc_calls >= 1
    done = tracer.completed()
    assert len(done) == 4
    for tr in done:
        assert set(STAGES) <= tr.span_names()
        assert tr.ok is True and tr.total_s > 0
        # spans nest sanely: queue_wait starts at submit, finalize ends last
        spans = {name: (a, b) for name, a, b in tr.spans}
        assert spans["queue_wait"][0] == tr.t_submit
        assert spans["finalize"][1] >= spans["device"][1]
    names = {e["name"] for e in tracer.to_chrome()["traceEvents"]
             if e["ph"] == "X"}
    assert set(STAGES) <= names


def test_service_without_tracer_is_zero_cost():
    # env off + no explicit tracer -> the service stores None and no
    # global tracer traffic happens
    with _svc(RlcBackend(), max_batch=1, max_wait_ms=0) as svc:
        assert svc._tracer is None
        assert svc.submit("fast_aggregate", [PK], b"m", b"s-ok").result(
            timeout=10) is True
    assert tracing.global_tracer().completed() == []


def test_service_picks_up_env_enabled_global_tracer(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_TRACE", "1")
    tracing.reset_global()
    with _svc(RlcBackend(), max_batch=1, max_wait_ms=0) as svc:
        assert svc._tracer is tracing.global_tracer()
        assert svc.submit("fast_aggregate", [PK], b"m", b"s-ok").result(
            timeout=10) is True
    assert len(tracing.global_tracer().completed()) == 1


def test_oracle_fallback_requests_still_finish_traces():
    class Broken(RlcBackend):
        def batch_verify_rlc(self, items, mesh=None, rng=None):
            raise RuntimeError("combine exploded")

        def _go(self, signatures):
            raise RuntimeError("device exploded")

    tracer = Tracer()
    with _svc(Broken(), tracer=tracer, max_batch=2, max_wait_ms=10_000,
              backend_retries=0) as svc:
        f1 = svc.submit("fast_aggregate", [PK], b"m1", b"a-ok")
        f2 = svc.submit("fast_aggregate", [PK], b"m2", b"b-bad")
        assert f1.result(timeout=10) is True
        assert f2.result(timeout=10) is False
    done = tracer.completed()
    assert len(done) == 2  # every degraded request still finished a trace
    assert {tr.ok for tr in done} == {True, False}
    for tr in done:
        assert "finalize" in tr.span_names()


# -- exposition endpoint ----------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [-+]?[0-9.eE+-]+$"
)


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.status, resp.read().decode()


def test_exposition_scrapeable_under_load():
    """/metrics parses as Prometheus text WHILE submit threads hammer the
    service; /snapshot is the live ServeMetrics JSON; /healthz answers."""
    be = RlcBackend()
    svc = _svc(be, max_batch=8, max_wait_ms=1)
    server = start_exposition(metrics=svc.metrics, port=0)
    stop = threading.Event()
    errors = []

    def hammer(tid):
        i = 0
        try:
            while not stop.is_set():
                svc.submit("fast_aggregate", [PK], b"t%d-%d" % (tid, i),
                           b"s-ok")
                i += 1
        except Exception as e:  # pragma: no cover - surfaced by assert
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(3)]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 5
        seen_queue_gauge = False
        for _ in range(3):
            status, body = _get(server.url("/metrics"))
            assert status == 200
            for line in body.splitlines():
                if not line or line.startswith("#"):
                    continue
                assert _PROM_LINE.match(line), f"unparseable: {line!r}"
            if "consensus_specs_tpu_serve_queue_depth" in body:
                seen_queue_gauge = True
            assert time.time() < deadline
        assert seen_queue_gauge
        status, body = _get(server.url("/snapshot"))
        snap = json.loads(body)
        assert status == 200 and snap["submits"] > 0
        status, body = _get(server.url("/healthz"))
        health = json.loads(body)
        # the PR 7 /healthz upgrade: liveness + SLO state in one body
        assert status == 200 and health["ok"] is True
        assert set(health["slo"]) == {"serve_p99", "chain_p99",
                                      "gossip_to_head_p99"}
        serve_slo = health["slo"]["serve_p99"]
        assert serve_slo["n"] > 0 and serve_slo["ok"] is True
        with pytest.raises(urllib.error.HTTPError):
            _get(server.url("/nope"))
    finally:
        stop.set()
        for t in threads:
            t.join(10)
        svc.close(timeout=30)
        server.close()
    assert errors == []


def test_exposition_default_snapshot_is_profiling_summary():
    profiling.set_gauge("serve.queue_depth", 7)
    with start_exposition(port=0) as server:
        _, body = _get(server.url("/snapshot"))
        snap = json.loads(body)
    assert snap["profile"]["serve.queue_depth"] == {"gauge": 7.0}


# -- concurrency hammer -----------------------------------------------------


def test_concurrent_writers_vs_snapshot_and_trace_readers():
    """Threaded hammer: ServeMetrics note_* + tracer begin/span/finish
    racing snapshot()/completed()/render_prometheus() readers. The
    assertion is consistency at the end and no exceptions in flight."""
    m = ServeMetrics()
    tracer = Tracer(capacity=128)
    n_threads, iters = 4, 400
    errors = []
    done = threading.Event()

    def writer(tid):
        try:
            for i in range(iters):
                m.note_submit()
                m.note_enqueued(i % 7)
                m.note_batch(2, 4, 8, 0.0001)
                m.note_result(0.0001 * (i % 5 + 1))
                tr = tracer.begin("fast_aggregate", 2, t_submit=0.0)
                tracer.span(tr, "queue_wait", 0.0, 0.0001)
                tracer.finish(tr, True, t_done=0.001)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            while not done.is_set():
                m.snapshot()
                tracer.completed()
                tracer.to_chrome()
                registry.render_prometheus()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    done.set()
    r.join(30)
    assert errors == []
    assert m.submits == n_threads * iters
    assert m.batches == n_threads * iters
    assert len(tracer.completed()) == 128  # ring stayed bounded
    snap = m.snapshot()
    assert snap["latency"]["count"] == n_threads * iters


# -- program registry -------------------------------------------------------


def test_program_registry_and_vm_cache_gauges():
    obs_programs.note_assembly("g2_subgroup[k=0,fold=8]", n_steps=512,
                               n_regs=256, seconds=2.5, disk_cache_hit=False)
    obs_programs.note_assembly("g2_subgroup[k=0,fold=8]", n_steps=512,
                               n_regs=256, seconds=0.01, disk_cache_hit=True)
    snap = obs_programs.registry_snapshot()
    assert snap["vm_cache"] == {"disk_hits": 1, "disk_misses": 1}
    assert snap["programs"]["g2_subgroup[k=0,fold=8]"]["vm_cache"] == "hit"
    summ = profiling.summary()
    assert summ["bls.vm_cache_hits"] == {"gauge": 1.0}
    assert summ["bls.vm_cache_misses"] == {"gauge": 1.0}
    # profiling.reset() wipes gauges, and note_assembly fires only once
    # per program per process (lru_cache) — export_gauges() re-publishes
    # so a multi-mode bench's later stages still carry the counters
    profiling.reset()
    assert "bls.vm_cache_hits" not in profiling.summary()
    obs_programs.export_gauges()
    assert profiling.summary()["bls.vm_cache_hits"] == {"gauge": 1.0}


def test_backend_program_resolution_feeds_registry(monkeypatch, tmp_path):
    """ops/bls_backend._program notes (steps, regs, assembly time, disk
    hit/miss) for every program it resolves — checked against a tiny
    synthetic program in an isolated cache dir so no real assembly (or
    repo-level cache state) is involved."""
    from consensus_specs_tpu.ops import bls_backend, vm, vmlib

    calls = {}

    def fake_build(k, fold):
        prog = vm.Prog()
        a = prog.inp("a")
        prog.out(a * a, "out")
        calls["built"] = (k, fold)
        return prog

    monkeypatch.setattr(vmlib, "build_miller_product", fake_build)
    monkeypatch.setattr(bls_backend, "_vm_cache_dir", lambda: str(tmp_path))
    bls_backend._program.cache_clear()
    try:
        assembled, _fold = bls_backend._program("miller_product", 1, 1)
        # second resolution from a cleared lru_cache: the pickle written
        # above answers -> disk HIT recorded
        bls_backend._program.cache_clear()
        bls_backend._program("miller_product", 1, 1)
    finally:
        bls_backend._program.cache_clear()
    assert calls["built"] == (1, 1)
    snap = obs_programs.registry_snapshot()
    entry = snap["programs"].get("miller_product[k=1,fold=1]")
    assert entry is not None
    assert entry["steps"] == assembled.n_steps
    assert entry["regs"] == assembled.n_regs
    assert entry["assembly_s"] >= 0
    assert snap["vm_cache"] == {"disk_hits": 1, "disk_misses": 1}
    assert entry["vm_cache"] == "hit"  # the latest resolution wins the entry


# -- profiling satellites ---------------------------------------------------


def test_profiling_enabled_is_dynamic(monkeypatch):
    monkeypatch.delenv("CONSENSUS_SPECS_TPU_PROFILE", raising=False)
    assert profiling.enabled() is False
    assert profiling.ENABLED is False  # the legacy alias reads live too
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_PROFILE", "1")
    assert profiling.enabled() is True
    assert profiling.ENABLED is True


def test_profiling_reset_clears_all_three_families():
    profiling.record("x.stat", 1.0)
    profiling.record_latency("x.lat", 0.5)
    profiling.set_gauge("x.gauge", 2.0)
    summ = profiling.summary()
    # the three recorded families + the hist.families tracking gauge
    assert {"x.stat", "x.lat", "x.gauge"} <= set(summ)
    assert summ["hist.families"] == {"gauge": 1.0}
    profiling.reset()
    assert profiling.summary() == {}
    assert profiling.latency_summary() == {}


def test_profiling_post_reset_runs_match_fresh_process():
    """Post-reset latency accounting must be identical to a fresh
    process: replay the same stream twice across a reset and require the
    exact same summary (the reruns-are-comparable contract — trivially
    deterministic now that fixed-bucket histograms replaced the sampled
    reservoir, and pinned here so a future implementation keeps it)."""

    def fill():
        profiling.reset()
        rng = random.Random(1)
        for _ in range(4096 + 512):
            profiling.record_latency("l", rng.random())
        return profiling.latency_summary()["l"]

    assert fill() == fill()


def test_profiling_snapshot_carries_observation_counts():
    """Every percentile family exposes ``n`` next to the p50/p95/p99
    points (ISSUE 7 satellite: consumers judge statistical weight)."""
    for _ in range(37):
        profiling.record_latency("serve.submit_to_result", 0.01)
    fam = profiling.snapshot()["serve.submit_to_result"]
    assert fam["n"] == 37 and fam["count"] == 37
    assert {"p50_ms", "p95_ms", "p99_ms"} <= set(fam)


# -- bench --trace glue -----------------------------------------------------


def test_bench_serve_trace_flag_writes_chrome_json(tmp_path, monkeypatch,
                                                   capsys):
    """`bench.py --mode serve --trace out.json` enables tracing before the
    load runs, dumps the global tracer, and attaches the path to the JSON
    line — glued here with a stub load so no crypto/compiles are paid."""
    import importlib.util

    bench_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench.py")
    spec = importlib.util.spec_from_file_location("bench_trace_glue",
                                                  bench_path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    import consensus_specs_tpu.serve.load as load_mod
    import consensus_specs_tpu.utils.jax_env as jax_env

    def fake_serve_bench():
        # the real run constructs the service AFTER main() set the env;
        # mirror that and push one batch through the traced pipeline
        tracing.reset_global()
        with _svc(RlcBackend(), max_batch=2, max_wait_ms=10_000) as svc:
            a = svc.submit("fast_aggregate", [PK], b"m1", b"s-ok")
            b = svc.submit("fast_aggregate", [PK], b"m2", b"s-ok")
            assert a.result(timeout=10) and b.result(timeout=10)
        return {"value": 1.0, "vs_baseline": 0.0, "mode": "serve"}

    monkeypatch.setattr(load_mod, "run_serve_bench", fake_serve_bench)
    monkeypatch.setattr(jax_env, "force_cpu", lambda *a, **k: None)
    out = tmp_path / "trace.json"
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--mode", "serve", "--trace", str(out)])
    bench.main()
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["trace"] == str(out)
    assert line["trace_requests"] == 2
    with open(out) as fh:
        doc = json.load(fh)
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert set(STAGES) <= names  # all five stages for >= 1 request
    assert "programRegistry" in doc


if __name__ == "__main__" and "--regen-golden" in sys.argv:
    os.environ["CONSENSUS_SPECS_TPU_TRACE"] = "0"
    obs_programs.reset()
    with open(GOLDEN, "w") as fh:
        fh.write(json.dumps(_golden_stitched(), indent=1, sort_keys=True))
    print(f"regenerated {GOLDEN}")
