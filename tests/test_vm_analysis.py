"""vmlint (ops/vm_analysis.py + tools/vmlint.py): the VM static-analysis
gate. Tier-1 keeps to small program shapes (fold <= 2, minimal K) and pure
host analysis — no device execution, no XLA compiles; the full production
registry (chunk-16 rlc_combine, folded hard part) runs under --run-slow.

What must hold:
- the independent bound re-derivation confirms every registered program
  (zero soundness findings) and the tier-1 subset matches the committed
  VMLINT_BASELINE.json;
- a reintroduced PR 3 select-then-multiply ladder (input-ready ops consumed
  thousands of steps later) is statically hazard-flagged, while the shipped
  chained form is not;
- seeded assembler bugs — a tampered tracker bound, a capacity overflow, a
  violated borrowless-subtract precondition, an unsound input declaration —
  each produce an error finding, and the gate turns any error or baseline
  pressure/depth regression into a failure (what `make check` enforces).
"""
import pytest

from consensus_specs_tpu.ops import fq, vm, vm_analysis, vmlib

# the production assembly shape (mirrors ops/bls_backend W_MUL/W_LIN/pads)
SHAPE = dict(w_mul=96, w_lin=192, pad_steps_to=256, pad_regs_to=64)


def _tiny_prog():
    """A few ALU ops with every kind represented."""
    prog = vm.Prog()
    a, b, c = (prog.inp(n) for n in "abc")
    r = (a * b + c) - a
    prog.out(r * r, "r")
    return prog


# ---------------------------------------------------------------------------
# bound soundness
# ---------------------------------------------------------------------------


def test_tiny_program_rederives_clean():
    r = vm_analysis.analyze_prog(_tiny_prog(), name="tiny")
    assert r["errors"] == 0
    assert r["bounds"]["checked"] > 0
    assert r["bounds"]["max_bound_bits"] <= 420


def test_tampered_tracker_bound_is_detected():
    prog = _tiny_prog()
    # simulate assembler drift: one op's tracked bound disagrees with the
    # transfer function the ALU actually implements
    alu = next(i for i, op in enumerate(prog.ops) if op.kind == 0)
    prog.ops[alu].bound += 1
    r = vm_analysis.analyze_prog(prog, name="tampered")
    assert any(f["rule"] == "bound-mismatch" for f in r["findings"])
    assert r["errors"] >= 1


def test_seeded_capacity_overflow_is_detected_and_gated():
    prog = _tiny_prog()
    a = prog.inp("loose", bound=1 << 419)
    # bypass Prog.add's auto-compress the way an assembler bug would:
    # an ADD whose derived bound reaches the 15-limb capacity
    prog.ops.append(vm._Op(1, a.idx, a.idx, (1 << 419) * 2))
    r = vm_analysis.analyze_prog(prog, name="seeded")
    assert any(f["rule"] == "bound-overflow" for f in r["findings"])
    # the gate (what `make check` runs) must fail on it regardless of
    # baseline scalars
    failures = vm_analysis.gate(
        [r], {"seeded": vm_analysis.baseline_entry(r)})
    assert any("bound-overflow" in f for f in failures)


def test_sub_precondition_violation_is_detected():
    prog = vm.Prog()
    a = prog.inp("a")
    b = prog.inp("b", bound=1 << 410)  # > MP: illegal subtrahend
    prog.ops.append(vm._Op(2, a.idx, b.idx, fq.P + fq.MP))
    r = vm_analysis.analyze_prog(prog, name="subbug")
    assert any(
        f["rule"] == "sub-subtrahend-overflow" for f in r["findings"])


def test_unsound_input_declaration_is_detected():
    prog = vm.Prog()
    a = prog.inp("a", bound=1 << 100)  # tighter than p: no canonical
    prog.out(a * a, "r")               # residue fits the declaration
    r = vm_analysis.analyze_prog(prog, name="tightinput")
    assert any(f["rule"] == "input-bound-unsound" for f in r["findings"])


def test_redundant_compress_and_dead_values_flagged():
    prog = vm.Prog()
    a, b = prog.inp("a"), prog.inp("b")
    dead = a * b  # never reaches an out()
    assert dead.bound
    c = prog.compress(a)  # canonical input: compress reduces nothing
    prog.out(c + b, "r")
    r = vm_analysis.analyze_prog(prog, name="waste")
    assert r["bounds"]["dead_ops"] >= 1
    assert r["bounds"]["redundant_compress"] >= 1
    # waste is warn-class: it must NOT fail the gate
    assert r["errors"] == 0


# ---------------------------------------------------------------------------
# the PR 3 scheduler-hazard regression (select-then-multiply)
# ---------------------------------------------------------------------------


def _select_then_multiply(n_bits=96):
    """The register-blowup form PR 3 eliminated: every bit's multiply is
    PREcomputed against the loop-invariant f, so all n_bits x 12 products
    are input-ready, get scheduled at step ~0, and sit live until their
    distant ladder level consumes them."""
    prog = vm.Prog()
    f = [prog.inp(f"f.{j}") for j in range(12)]
    bits = [prog.inp(f"r.{t}") for t in range(n_bits)]
    pre = [[bits[t] * f[j] for j in range(12)] for t in range(n_bits)]
    acc = f
    for t in range(n_bits):
        acc = vmlib.f12_square(prog, acc)
        acc = [acc[j] + pre[t][j] for j in range(12)]
    for j in range(12):
        prog.out(acc[j], f"c.{j}")
    return prog


def _chained(n_bits=96):
    """The shipped form: every multiply chains on the accumulator, so live
    ranges stay one ladder level long."""
    prog = vm.Prog()
    f = [prog.inp(f"f.{j}") for j in range(12)]
    bits = [prog.inp(f"r.{t}") for t in range(n_bits)]
    acc = f
    for t in range(n_bits):
        acc = vmlib.f12_square(prog, acc)
        m = vmlib.f12_mul(prog, acc, f)
        acc = [acc[j] + (bits[t] * m[j]) for j in range(12)]
    for j in range(12):
        prog.out(acc[j], f"c.{j}")
    return prog


def test_select_then_multiply_hazard_is_flagged():
    bad = vm_analysis.analyze_prog(
        _select_then_multiply(), name="select", **SHAPE)
    good = vm_analysis.analyze_prog(_chained(), name="chained", **SHAPE)
    assert bad["pressure"]["hazard"] is True
    assert any(f["rule"] == "live-range-outliers" for f in bad["findings"])
    assert good["pressure"]["hazard"] is False
    assert good["errors"] == 0
    # the hazard IS a register blowup: several times the chained pressure
    assert bad["pressure"]["max_live"] > 3 * good["pressure"]["max_live"]
    # and the gate fails on it even with matching baseline scalars
    failures = vm_analysis.gate(
        [bad], {"select": vm_analysis.baseline_entry(bad)})
    assert any("live-range-outliers" in f for f in failures)


# ---------------------------------------------------------------------------
# schedule reports / cost model / assembled-program stats
# ---------------------------------------------------------------------------


def test_cost_report_classifies_and_predicts():
    prog = vmlib.build_hard_part(1)
    r = vm_analysis.analyze_prog(prog, name="hard", **SHAPE)
    c = r["cost"]
    # the hard part is the canonical depth-bound program: the critical
    # path IS the schedule, with mul utilization in the single digits
    assert c["classification"] == "depth-bound"
    assert c["critical_path"] == c["sched_steps"]
    assert c["mul_utilization"] < 0.10
    assert c["predicted_row_s"] > 0.5  # ~seconds per row on CPU
    assert len(c["mul_width_profile"]) == 8


# the legacy bit-serial hard part's padded step count (ISSUE 10's "4864-
# step chain"): the acceptance bar for the width-for-depth variants
_LEGACY_HARD_PART_STEPS = 4864


def test_hard_part_variants_recover_depth():
    """ISSUE 10 acceptance, satellite 3: the new hard-part variants cut
    the vmlint critical path below 0.5x the legacy 4864-step chain (the
    frobenius flagship >= 2.5x), and the pipelined multi-row fold-8 shape
    is no longer depth-bound — width hides the residual depth."""
    frob = vm_analysis.analyze_prog(
        vmlib.build_hard_part_frobenius(1), name="frob", **SHAPE)
    assert frob["errors"] == 0
    crit = frob["cost"]["critical_path"]
    assert crit < 0.5 * _LEGACY_HARD_PART_STEPS
    assert crit * 2.5 <= _LEGACY_HARD_PART_STEPS  # the >=2.5x flagship bar

    win = vm_analysis.analyze_prog(
        vmlib.build_hard_part_windowed(1), name="win", **SHAPE)
    assert win["errors"] == 0
    assert win["cost"]["critical_path"] < 0.5 * _LEGACY_HARD_PART_STEPS

    # the pipelined multi-row shape (fold 8, the _fold_for cap for the
    # new variants): classified balanced or width-bound, NOT depth-bound
    frob8 = vm_analysis.analyze_prog(
        vmlib.build_hard_part_frobenius(8), name="frob8", **SHAPE)
    assert frob8["errors"] == 0
    assert frob8["cost"]["classification"] in ("balanced", "width-bound")
    # and the depth recovery survives folding: same critical path
    assert frob8["cost"]["critical_path"] == crit

    # ISSUE 13: the fused straight-line lowering's predicted runtime
    # (real per-level widths + per-level/per-chunk glue, no register-file
    # traffic) must beat the 280 µs/step interpreter model on the
    # pipelined frobenius fold-8 shape — the static-model statement of
    # the measured fused win `make vmexec-bench` re-checks dynamically
    assert frob8["cost"]["predicted_fused_row_s"] > 0
    assert (frob8["cost"]["predicted_fused_row_s"]
            < frob8["cost"]["predicted_row_s"])
    assert frob8["cost"]["fused_chunks"] > 0


def test_program_stats_cross_checks_the_ir_analysis():
    prog = _chained(24)
    r = vm_analysis.analyze_prog(prog, name="x", **SHAPE)
    assembled = prog.assemble(**SHAPE)
    ps = vm_analysis.program_stats(assembled)
    # the instruction-tensor recount must agree with the IR analysis
    assert ps["sched_steps"] == r["pressure"]["sched_steps"]
    assert ps["mul_ops"] == r["cost"]["mul_ops"]
    assert ps["lin_ops"] == r["cost"]["add_ops"] + r["cost"]["sub_ops"]
    assert ps["max_reg_occupancy"] <= ps["alloc_regs"]


# ---------------------------------------------------------------------------
# structural canonicalization (ISSUE 15)
# ---------------------------------------------------------------------------


def test_detect_period_and_window_selection():
    a, b, c = (7, 0, 0), (2, 1, 0), (1, 0, 1)
    assert vm_analysis.detect_period([a, b] * 40) == 2
    assert vm_analysis.detect_period([a, b, c] * 30 + [a]) == 3
    # sparse interruptions (the set-bit rows of a real ladder) survive
    # the match-fraction threshold
    sigs = ([a, b] * 20 + [c] + [a, b] * 20)
    assert vm_analysis.detect_period(sigs) == 2
    # aperiodic: no period
    import random as _r

    rng = _r.Random(5)
    rand = [(rng.randrange(50), rng.randrange(9), rng.randrange(9))
            for _ in range(200)]
    assert vm_analysis.detect_period(rand) is None
    # window selection: largest period multiple <= target, 2x-clamped
    assert vm_analysis.select_window(None, 24) == 24
    assert vm_analysis.select_window(14, 24) == 14
    assert vm_analysis.select_window(6, 24) == 24
    assert vm_analysis.select_window(28, 24) == 28  # period > target: itself
    assert vm_analysis.select_window(96, 24) == 24  # > 2x target: clamped


def _ladder_prog(iters=12):
    prog = vm.Prog()
    acc = prog.inp("acc")
    other = prog.inp("other")
    for i in range(iters):
        k = prog.const(1000003 * (i + 1))
        acc = acc * acc + other * k
        other = other * other - acc
    prog.out(acc, "acc")
    prog.out(other, "other")
    return prog


def test_structural_plan_dedups_ladder_chunks():
    """A repeated loop body canonicalizes to FEWER distinct structures
    than chunks — constants become per-instance operand slots, carry
    wiring becomes per-instance gather tables — and every instance's
    tables are self-consistent (index ranges, struct refs)."""
    prog = _ladder_prog()
    assembled = prog.assemble(w_mul=64, w_lin=64, pad_steps_to=256,
                              pad_regs_to=64)
    plan = vm_analysis.lowering_plan(assembled, chunk_steps=3)
    sp = vm_analysis.structural_plan(plan)
    inst = sp["instances"]
    assert len(sp["structs"]) < len(inst)
    for c in inst:
        body = sp["structs"][c["struct"]]
        assert len(c["in_idx"]) == body["n_in"]
        assert len(c["consts"]) == body["n_const"]
        assert len(c["boundary_idx"]) == c["m_out"]
        assert all(0 <= i < c["m_in"] for i in c["in_idx"])
        n_out = len(body["out"])
        assert all(0 <= i < n_out + c["m_in"]
                   for i in c["boundary_idx"])
    # dedup=False salts every key: the per-chunk baseline
    sp0 = vm_analysis.structural_plan(plan, dedup=False)
    assert len(sp0["structs"]) == len(sp0["instances"])
    # the canonical bodies are instance-value-free: runs exist for the
    # super-op folding to exploit
    runs = vm_analysis.superop_runs(inst, min_run=2)
    assert runs and max(r for _, r in runs) >= 4


def test_superop_runs_require_shape_invariant_carry():
    inst = [
        {"struct": "A", "m_in": 4, "m_out": 4},
        {"struct": "A", "m_in": 4, "m_out": 4},
        {"struct": "A", "m_in": 4, "m_out": 4},
        {"struct": "B", "m_in": 4, "m_out": 4},
        {"struct": "A", "m_in": 4, "m_out": 6},  # width change: no run
        {"struct": "A", "m_in": 6, "m_out": 6},
        {"struct": "A", "m_in": 6, "m_out": 6},
    ]
    assert vm_analysis.superop_runs(inst, min_run=3) == [(0, 3)]
    assert vm_analysis.superop_runs(inst, min_run=2) == [(0, 3), (5, 2)]
    assert vm_analysis.superop_runs([], min_run=2) == []


def test_structural_stats_report_shape():
    st = vm_analysis.structural_stats(
        _ladder_prog().assemble(w_mul=64, w_lin=64, pad_steps_to=256,
                                pad_regs_to=64), chunk_target=4)
    assert st["chunks"] >= st["distinct_structs"] >= 1
    assert st["dedup_ratio"] >= 1.0
    assert st["predicted_cold_s"] <= st["predicted_cold_nodedup_s"]
    # the report + baseline entry carry the structural shape
    r = vm_analysis.analyze_prog(_ladder_prog(), name="ladder")
    assert r["structure"]["distinct_structs"] >= 1
    entry = vm_analysis.baseline_entry(r)
    assert entry["distinct_structs"] == r["structure"]["distinct_structs"]
    assert entry["dedup_ratio"] == r["structure"]["dedup_ratio"]


# ---------------------------------------------------------------------------
# baseline gate
# ---------------------------------------------------------------------------


def test_gate_detects_pressure_and_depth_regressions():
    r = vm_analysis.analyze_prog(_tiny_prog(), name="p")
    entry = vm_analysis.baseline_entry(r)
    assert vm_analysis.gate([r], {"p": entry}) == []
    # a regression: baseline pinned tighter than the current program
    tight = dict(entry, max_live=max(1, entry["max_live"] // 2))
    assert any("max_live regressed" in f
               for f in vm_analysis.gate([r], {"p": tight}))
    tight = dict(entry, critical_path=max(1, entry["critical_path"] - 2))
    assert any("critical_path regressed" in f
               for f in vm_analysis.gate([r], {"p": tight}))
    # unknown program: must demand a baseline entry
    assert any("not in VMLINT_BASELINE" in f
               for f in vm_analysis.gate([r], {}))


def test_tier1_registry_is_sound_and_matches_committed_baseline():
    """The acceptance gate, tier-1 slice: vmlint independently re-derives
    and confirms bounds for the small-shape registry programs, and their
    pressure/depth scalars match the committed VMLINT_BASELINE.json."""
    reports = vm_analysis.run_registry(tier1_only=True, export=False)
    assert len(reports) >= 9
    for r in reports:
        assert r["errors"] == 0, (r["name"], r["findings"])
        assert r["bounds"]["checked"] > 0
        assert r["pressure"]["hazard"] is False
    failures = vm_analysis.gate(reports, vm_analysis.load_baseline())
    assert failures == []


@pytest.mark.slow
def test_full_registry_is_sound_and_matches_committed_baseline():
    """Full production shapes (chunk-16 rlc_combine, fold-8 hard part,
    production codec folds): ~20 s of host assembly + analysis."""
    reports = vm_analysis.run_registry(tier1_only=False, export=False)
    assert len(reports) >= 18
    for r in reports:
        assert r["errors"] == 0, (r["name"], r["findings"])
    assert vm_analysis.gate(reports, vm_analysis.load_baseline()) == []


# ---------------------------------------------------------------------------
# observability export
# ---------------------------------------------------------------------------


def test_analysis_exports_to_obs_registry_and_gauges():
    from consensus_specs_tpu.obs import programs as obs_programs
    from consensus_specs_tpu.ops import profiling

    r = vm_analysis.analyze_prog(_tiny_prog(), name="tiny[k=0,fold=1]")
    vm_analysis.export_to_obs([r])
    snap = obs_programs.registry_snapshot()["programs"]
    analysis = snap["tiny[k=0,fold=1]"]["analysis"]
    assert analysis["max_live"] == r["pressure"]["max_live"]
    assert analysis["classification"] == r["cost"]["classification"]
    gauges = profiling.summary()
    assert gauges["vm.analysis_programs"]["gauge"] == 1
    assert gauges["vm.analysis_errors"]["gauge"] == 0
    # analyze-then-execute ordering: a later note_assembly for the same
    # key must MERGE, keeping the analysis sub-dict alongside the
    # measured assembly stats
    obs_programs.note_assembly(
        "tiny[k=0,fold=1]", n_steps=8, n_regs=16, seconds=0.01,
        disk_cache_hit=False)
    merged = obs_programs.registry_snapshot()["programs"]["tiny[k=0,fold=1]"]
    assert merged["steps"] == 8
    assert merged["analysis"]["max_live"] == r["pressure"]["max_live"]
