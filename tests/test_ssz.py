"""SSZ engine tests: serialization, merkleization, deserialization roundtrips.

Modeled on the reference's ssz_generic / ssz_static test strategy
(reference: tests/generators/ssz_generic, SURVEY.md section 4.8).
"""
import pytest

from consensus_specs_tpu.utils.hash_function import hash
from consensus_specs_tpu.utils.ssz.ssz_impl import hash_tree_root, serialize
from consensus_specs_tpu.utils.ssz.ssz_typing import (
    Bitlist, Bitvector, ByteList, Bytes32, Bytes48, Container, List, Union,
    Vector, boolean, uint8, uint16, uint32, uint64, uint256,
)


def test_uint_serialization():
    assert serialize(uint64(0)) == b"\x00" * 8
    assert serialize(uint64(0x0123456789ABCDEF)) == bytes.fromhex("efcdab8967452301")
    assert serialize(uint8(255)) == b"\xff"
    assert serialize(uint16(0x1234)) == b"\x34\x12"
    assert uint64.decode_bytes(b"\x01" + b"\x00" * 7) == 1


def test_uint_range_checks():
    with pytest.raises(ValueError):
        uint8(256)
    with pytest.raises(ValueError):
        uint64(-1)
    with pytest.raises(ValueError):
        uint64(2**64)


def test_uint_checked_arithmetic():
    a = uint64(2**62)
    assert a + a - a == a
    assert type(a + 1) is uint64
    with pytest.raises(ValueError):
        _ = uint64(2**63) * 2
    with pytest.raises(ValueError):
        _ = uint64(0) - 1
    assert uint64(7) // 2 == 3
    assert uint64(7) % 2 == 1


def test_uint_hash_tree_root():
    assert hash_tree_root(uint64(17)) == (17).to_bytes(8, "little") + b"\x00" * 24
    assert hash_tree_root(uint256(1)) == (1).to_bytes(32, "little")
    assert hash_tree_root(boolean(True)) == b"\x01" + b"\x00" * 31


def test_bytes32_htr_is_identity():
    v = Bytes32(b"\x42" * 32)
    assert hash_tree_root(v) == b"\x42" * 32
    assert serialize(v) == b"\x42" * 32


def test_bytes48_htr_pads_second_chunk():
    v = Bytes48(b"\x01" * 48)
    chunk0 = b"\x01" * 32
    chunk1 = b"\x01" * 16 + b"\x00" * 16
    assert hash_tree_root(v) == hash(chunk0 + chunk1)


def test_vector_of_uint64():
    v = Vector[uint64, 4](1, 2, 3, 4)
    expected_ser = b"".join(i.to_bytes(8, "little") for i in (1, 2, 3, 4))
    assert serialize(v) == expected_ser
    assert hash_tree_root(v) == expected_ser  # 32 bytes exactly = single chunk
    assert Vector[uint64, 4].decode_bytes(expected_ser) == v


def test_vector_wrong_length_rejected():
    with pytest.raises(ValueError):
        Vector[uint64, 4](1, 2, 3)


def test_list_mix_in_length():
    l = List[uint64, 1024](1, 2)
    chunks_root_input = serialize(l).ljust(32, b"\x00")
    # limit 1024 uint64 = 256 chunks -> depth 8 over zero-padded tree
    from consensus_specs_tpu.utils.ssz.ssz_typing import merkleize_chunks

    root = merkleize_chunks([chunks_root_input], limit=256)
    assert hash_tree_root(l) == hash(root + (2).to_bytes(32, "little"))
    assert List[uint64, 1024].decode_bytes(serialize(l)) == l


def test_list_limit_enforced():
    l = List[uint64, 2](1, 2)
    with pytest.raises(ValueError):
        l.append(3)
    with pytest.raises(ValueError):
        List[uint64, 2](1, 2, 3)


def test_empty_list_htr():
    from consensus_specs_tpu.utils.ssz.ssz_typing import ZERO_HASHES

    l = List[uint64, 1024]()
    assert hash_tree_root(l) == hash(ZERO_HASHES[8] + b"\x00" * 32)


def test_bitvector():
    bv = Bitvector[10](1, 0, 1, 0, 0, 0, 0, 0, 1, 1)
    assert serialize(bv) == bytes([0b00000101, 0b00000011])
    assert Bitvector[10].decode_bytes(serialize(bv)) == bv
    with pytest.raises(ValueError):
        Bitvector[10].decode_bytes(bytes([0xFF, 0xFF]))  # nonzero padding


def test_bitlist():
    bl = Bitlist[16](1, 0, 1)
    # bits 101 + delimiter at position 3 -> 0b1101
    assert serialize(bl) == bytes([0b1101])
    assert Bitlist[16].decode_bytes(serialize(bl)) == bl
    assert len(bl) == 3
    empty = Bitlist[16]()
    assert serialize(empty) == bytes([1])
    assert Bitlist[16].decode_bytes(bytes([1])) == empty
    with pytest.raises(ValueError):
        Bitlist[16].decode_bytes(b"")
    with pytest.raises(ValueError):
        Bitlist[16].decode_bytes(bytes([0b101, 0]))  # missing delimiter
    with pytest.raises(ValueError):
        Bitlist[2].decode_bytes(bytes([0b1101]))  # 3 bits > limit 2


class FixedC(Container):
    a: uint64
    b: Bytes32


class VarC(Container):
    a: uint64
    items: List[uint8, 32]
    b: uint16


def test_container_fixed_serialization():
    c = FixedC(a=uint64(5), b=Bytes32(b"\x09" * 32))
    assert serialize(c) == (5).to_bytes(8, "little") + b"\x09" * 32
    assert FixedC.decode_bytes(serialize(c)) == c
    assert hash_tree_root(c) == hash(
        ((5).to_bytes(8, "little") + b"\x00" * 24) + b"\x09" * 32
    )


def test_container_variable_serialization():
    c = VarC(a=uint64(1), items=List[uint8, 32](7, 8, 9), b=uint16(2))
    ser = serialize(c)
    # fixed part: 8 bytes a + 4 byte offset + 2 bytes b = 14; offset = 14
    assert ser == (1).to_bytes(8, "little") + (14).to_bytes(4, "little") + (2).to_bytes(
        2, "little"
    ) + bytes([7, 8, 9])
    assert VarC.decode_bytes(ser) == c


def test_container_defaults_and_mutation():
    c = VarC()
    assert c.a == 0 and len(c.items) == 0
    c.a = 42
    assert c.a == uint64(42)
    c.items.append(uint8(1))
    assert len(c.items) == 1
    with pytest.raises(AttributeError):
        c.nonexistent = 1


def test_container_snapshot_on_store_alias_on_read():
    inner = FixedC(a=uint64(1))

    class Outer(Container):
        x: FixedC

    o = Outer(x=inner)
    inner.a = uint64(99)
    assert o.x.a == 1  # stored a snapshot
    o.x.a = uint64(5)
    assert o.x.a == 5  # reads alias


def test_container_copy_is_deep():
    c = VarC(a=uint64(1), items=List[uint8, 32](1))
    c2 = c.copy()
    c2.items.append(uint8(2))
    c2.a = uint64(9)
    assert len(c.items) == 1 and c.a == 1


def test_union():
    U = Union[None, uint16, uint32]
    u = U(1, uint16(0xAABB))
    assert serialize(u) == bytes([1, 0xBB, 0xAA])
    assert U.decode_bytes(serialize(u)) == u
    n = U(0)
    assert serialize(n) == bytes([0])
    assert hash_tree_root(u) == hash(
        (uint16(0xAABB).encode_bytes().ljust(32, b"\x00")) + (1).to_bytes(32, "little")
    )


def test_bytelist():
    bl = ByteList[64](b"abc")
    assert serialize(bl) == b"abc"
    assert ByteList[64].decode_bytes(b"abc") == bl
    with pytest.raises(ValueError):
        ByteList[2](b"abc")


def test_nested_variable_lists():
    T = List[List[uint8, 4], 4]
    v = T([List[uint8, 4](1, 2), List[uint8, 4](), List[uint8, 4](3)])
    ser = serialize(v)
    assert T.decode_bytes(ser) == v


def test_vector_of_containers_htr():
    T = Vector[FixedC, 2]
    v = T([FixedC(a=uint64(1)), FixedC(a=uint64(2))])
    assert hash_tree_root(v) == hash(
        v[0].hash_tree_root() + v[1].hash_tree_root()
    )
