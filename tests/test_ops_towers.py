"""JAX tower arithmetic vs the oracle: Fq2 and flat-basis Fq12."""
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from consensus_specs_tpu.ops import fq, towers  # noqa: E402
from consensus_specs_tpu.utils.bls12_381 import (  # noqa: E402
    Fq2, Fq6, Fq12, P,
)

rng = random.Random(11)

# jit once — eager per-op dispatch is far too slow for thousand-op graphs
_fq2_mul = jax.jit(towers.fq2_mul)
_fq2_square = jax.jit(towers.fq2_square)
_fq2_add = jax.jit(towers.fq2_add)
_fq2_sub = jax.jit(towers.fq2_sub)
_fq12_mul = jax.jit(towers.fq12_mul)
_fq12_conj = jax.jit(towers.fq12_conjugate)


def rand_fq2():
    return Fq2(rng.randrange(P), rng.randrange(P))


def rand_fq12():
    def rand_fq6():
        return Fq6(rand_fq2(), rand_fq2(), rand_fq2())

    return Fq12(rand_fq6(), rand_fq6())


def test_fq2_mul_matches_oracle():
    for _ in range(8):
        x, y = rand_fq2(), rand_fq2()
        a = towers.fq2_from_oracle(x)
        b = towers.fq2_from_oracle(y)
        assert towers.fq2_to_oracle(np.asarray(_fq2_mul(a, b))) == x * y
        assert towers.fq2_to_oracle(np.asarray(_fq2_square(a))) == x * x
        assert towers.fq2_to_oracle(np.asarray(_fq2_add(a, b))) == x + y
        assert towers.fq2_to_oracle(np.asarray(_fq2_sub(a, b))) == x - y


def test_fq12_roundtrip():
    for _ in range(4):
        x = rand_fq12()
        a = towers.fq12_from_oracle(x)
        assert towers.fq12_to_oracle(np.asarray(a)) == x


def test_fq12_mul_matches_oracle():
    for _ in range(6):
        x, y = rand_fq12(), rand_fq12()
        a = towers.fq12_from_oracle(x)
        b = towers.fq12_from_oracle(y)
        got = towers.fq12_to_oracle(np.asarray(_fq12_mul(a, b)))
        assert got == x * y


def test_fq12_conjugate_matches_oracle():
    for _ in range(4):
        x = rand_fq12()
        a = towers.fq12_from_oracle(x)
        got = towers.fq12_to_oracle(np.asarray(_fq12_conj(a)))
        assert got == x.conjugate()


def test_fq12_one():
    one = towers.fq12_one()
    assert towers.fq12_to_oracle(np.asarray(one)) == Fq12.one()
    x = rand_fq12()
    a = towers.fq12_from_oracle(x)
    assert towers.fq12_to_oracle(np.asarray(_fq12_mul(a, one))) == x
    assert bool(np.asarray(towers.fq12_is_one(_fq12_mul(a, one))) ) is False or x == Fq12.one()
    assert bool(np.asarray(towers.fq12_is_one(one)))
