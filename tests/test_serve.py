"""Serve plane (consensus_specs_tpu/serve/): flush triggers, cache/dedup
semantics, oracle fallback on backend failure, and the randomized stream
equivalence gate (service results == SignatureCollector.flush_oracle()).

The plumbing tests run against a crypto-free counting backend so tier-1
stays fast; the oracle-delegating backend ties the 200-request stream
equivalence to real pure-Python crypto on the ~dozen UNIQUE items only
(duplicates must never reach the backend — that is the assertion); and one
small real-device-backend test reuses the exact shapes
tests/test_bls_backend_fast.py already compiles on every default run.
"""
import random
import time

import numpy as np
import pytest

from consensus_specs_tpu.batch_verify import SignatureCollector
from consensus_specs_tpu.serve import (
    QueueFull,
    ResultCache,
    ServiceClosed,
    VerificationService,
    check_key,
)
from consensus_specs_tpu.utils import bls

PK = b"\x01" * 48  # plumbing tests never decode keys; any bytes serve


@pytest.fixture(autouse=True)
def _bls_on():
    from consensus_specs_tpu.ops import profiling

    profiling.reset()  # latency reservoirs/gauges are process-global
    was = bls.bls_active
    bls.bls_active = True
    yield
    bls.bls_active = was


class CountingBackend:
    """Crypto-free batched backend: an item verifies True iff its
    signature ends with b"ok". Counts entry-point calls and items (the
    same ledger ops/bls_backend.py CALL_COUNTS keeps for the real one).
    Deliberately has NO batch_verify_rlc: the service must fall back to
    the per-group path for such backends."""

    def __init__(self, delay_s=0.0, fail_always=False, fail_calls=()):
        self.calls = 0
        self.items = 0
        self.rlc_calls = 0
        self.delay_s = delay_s
        self.fail_always = fail_always
        self.fail_calls = set(fail_calls)

    def _go(self, signatures):
        self.calls += 1
        if self.fail_always or self.calls in self.fail_calls:
            raise RuntimeError(f"injected backend failure (call {self.calls})")
        if self.delay_s:
            time.sleep(self.delay_s)
        self.items += len(signatures)
        return np.array([s.endswith(b"ok") for s in signatures], dtype=bool)

    def batch_fast_aggregate_verify(self, pubkey_sets, messages, signatures,
                                    mesh=None):
        return self._go(signatures)

    def batch_aggregate_verify(self, pubkey_lists, message_lists, signatures,
                               mesh=None):
        return self._go(signatures)


class OracleBackend(CountingBackend):
    """Batched entry points that resolve each item through the pure-Python
    oracle — real crypto, item-at-a-time, with the call ledger. Lets the
    stream equivalence test exercise real verification on unique items
    without paying device compiles in tier-1."""

    def _go(self, signatures):
        raise NotImplementedError

    def batch_fast_aggregate_verify(self, pubkey_sets, messages, signatures,
                                    mesh=None):
        self.calls += 1
        self.items += len(signatures)
        return np.array(
            [bls.FastAggregateVerify(pks, m, s)
             for pks, m, s in zip(pubkey_sets, messages, signatures)],
            dtype=bool,
        )

    def batch_aggregate_verify(self, pubkey_lists, message_lists, signatures,
                               mesh=None):
        self.calls += 1
        self.items += len(signatures)
        return np.array(
            [bls.AggregateVerify(pks, ms, s)
             for pks, ms, s in zip(pubkey_lists, message_lists, signatures)],
            dtype=bool,
        )

    def batch_verify_rlc(self, items, mesh=None, rng=None):
        """The micro-batch RLC entry the service routes whole flushes
        through by default — resolved per item via the oracle so the
        stream-equivalence gate exercises the routing with real crypto
        on unique items only."""
        self.calls += 1
        self.rlc_calls += 1
        self.items += len(items)
        return np.array(
            [bls.FastAggregateVerify(pks, msgs, sig)
             if kind == "fast_aggregate"
             else bls.AggregateVerify(pks, msgs, sig)
             for kind, pks, msgs, sig in items],
            dtype=bool,
        )


class CountingOracle:
    """verify_one fallback with the signature-suffix truth rule."""

    def __init__(self):
        self.calls = 0

    def verify_one(self, pending):
        self.calls += 1
        return bytes(pending.signature).endswith(b"ok")


def _svc(backend, **kw):
    kw.setdefault("bucket_fn", lambda k: 8)
    kw.setdefault("oracle", CountingOracle())
    return VerificationService(backend=backend, **kw)


# -- flush triggers ---------------------------------------------------------


def test_size_triggered_flush():
    be = CountingBackend()
    with _svc(be, max_batch=4, max_wait_ms=10_000) as svc:
        futs = [
            svc.submit("fast_aggregate", [PK], b"m%d" % i, b"s%d-ok" % i)
            for i in range(4)
        ]
        # max_wait is 10 s: only the size trigger can resolve these quickly
        assert [f.result(timeout=5) for f in futs] == [True] * 4
    assert be.calls == 1 and be.items == 4
    assert svc.metrics.batches == 1 and svc.metrics.rows_filled == 4


def test_deadline_triggered_flush():
    be = CountingBackend()
    with _svc(be, max_batch=1000, max_wait_ms=30) as svc:
        f1 = svc.submit("fast_aggregate", [PK], b"m1", b"a-ok")
        f2 = svc.submit("fast_aggregate", [PK], b"m2", b"b-bad")
        # far below max_batch: only the deadline trigger can flush
        assert f1.result(timeout=5) is True
        assert f2.result(timeout=5) is False
    assert svc.metrics.batches >= 1 and svc.metrics.rows_filled == 2


def test_shutdown_drain_resolves_everything():
    be = CountingBackend()
    svc = _svc(be, max_batch=1000, max_wait_ms=600_000)
    futs = [
        svc.submit("fast_aggregate", [PK], b"m%d" % i, b"s%d-ok" % i)
        for i in range(5)
    ]
    svc.close(timeout=30)  # neither trigger fired — close must drain
    assert all(f.done() for f in futs)
    assert [f.result() for f in futs] == [True] * 5
    assert be.items == 5


def test_submit_after_close_raises():
    svc = _svc(CountingBackend())
    svc.close(timeout=30)
    with pytest.raises(ServiceClosed):
        svc.submit("fast_aggregate", [PK], b"m", b"s-ok")


# -- cache + dedup ----------------------------------------------------------


def test_inflight_join_and_cache_hit_verify_once():
    be = CountingBackend(delay_s=0.2)
    with _svc(be, max_batch=1, max_wait_ms=0) as svc:
        f1 = svc.submit("fast_aggregate", [PK], b"dup", b"sig-ok")
        # worker is sleeping inside the backend: identical content joins
        # the in-flight future instead of re-entering the queue
        f2 = svc.submit("fast_aggregate", [PK], b"dup", b"sig-ok")
        assert f2 is f1
        assert f1.result(timeout=10) is True
        # completed now: a third identical submit is a result-cache hit
        f3 = svc.submit("fast_aggregate", [PK], b"dup", b"sig-ok")
        assert f3.done() and f3.result() is True
    assert be.items == 1  # the duplicate content hit the backend ONCE
    assert svc.metrics.inflight_joins == 1
    assert svc.metrics.cache_hits == 1
    assert svc.metrics.hit_rate > 0


def test_result_cache_lru_and_key_framing():
    c = ResultCache(capacity=2)
    ka = check_key("fast_aggregate", [b"pk1"], b"m", b"s")
    kb = check_key("fast_aggregate", [b"pk2"], b"m", b"s")
    kc = check_key("fast_aggregate", [b"pk3"], b"m", b"s")
    c.put(ka, True)
    c.put(kb, False)
    assert c.get(ka) is True  # refreshes ka
    c.put(kc, True)  # evicts kb (LRU), not ka
    assert c.get(kb) is None and c.get(ka) is True and c.get(kc) is True
    assert len(c) == 2 and c.hits == 3 and c.misses == 1

    # length framing: a different pubkey split must never alias
    assert (check_key("fast_aggregate", [b"ab", b"c"], b"m", b"s")
            != check_key("fast_aggregate", [b"a", b"bc"], b"m", b"s"))
    # kind and message-shape tags must never alias either
    assert (check_key("fast_aggregate", [b"pk"], b"m", b"s")
            != check_key("aggregate", [b"pk"], [b"m"], b"s"))


# -- eager reference rules --------------------------------------------------


def test_reference_rules_answered_eagerly():
    be = CountingBackend()
    with _svc(be) as svc:
        assert svc.submit("fast_aggregate", [], b"m", b"s").result() is False
        assert svc.submit("aggregate", [PK], [], b"s").result() is False
        assert svc.submit("aggregate", [PK], [b"a", b"b"], b"s").result() is False
        bls.bls_active = False
        try:
            assert svc.submit("fast_aggregate", [PK], b"m", b"s-bad").result() is True
        finally:
            bls.bls_active = True
        with pytest.raises(ValueError):
            svc.submit("proposer", [PK], b"m", b"s")
    assert be.calls == 0  # nothing above may reach the backend


# -- failure handling -------------------------------------------------------


def test_backend_failure_degrades_to_oracle():
    be = CountingBackend(fail_always=True)
    orc = CountingOracle()
    with _svc(be, oracle=orc, max_batch=4, max_wait_ms=10_000,
              backend_retries=1) as svc:
        futs = [
            svc.submit("fast_aggregate", [PK], b"m%d" % i,
                       b"s%d-ok" % i if i % 2 == 0 else b"s%d-bad" % i)
            for i in range(4)
        ]
        got = [f.result(timeout=10) for f in futs]
    assert got == [True, False, True, False]  # correct, not lost/corrupted
    assert be.calls == 2  # first attempt + one bounded retry, then oracle
    assert orc.calls == 4
    assert svc.metrics.fallback_items == 4
    assert svc.metrics.backend_retries == 1


def test_transient_failure_recovers_on_retry():
    be = CountingBackend(fail_calls=(1,))
    with _svc(be, max_batch=2, max_wait_ms=10_000, backend_retries=1) as svc:
        f1 = svc.submit("fast_aggregate", [PK], b"m1", b"a-ok")
        f2 = svc.submit("fast_aggregate", [PK], b"m2", b"b-ok")
        assert f1.result(timeout=10) is True and f2.result(timeout=10) is True
    assert be.calls == 2 and be.items == 2  # retry carried the batch
    assert svc.metrics.fallback_items == 0


def test_backpressure_queue_full():
    be = CountingBackend(delay_s=0.5)
    svc = _svc(be, max_batch=1, max_wait_ms=0, max_queue=1)
    try:
        f1 = svc.submit("fast_aggregate", [PK], b"m1", b"a-ok")
        time.sleep(0.1)  # worker takes m1 and sleeps inside the backend
        f2 = svc.submit("fast_aggregate", [PK], b"m2", b"b-ok")
        with pytest.raises(QueueFull):
            svc.submit("fast_aggregate", [PK], b"m3", b"c-ok", timeout=0.05)
        assert f1.result(timeout=10) is True
        assert f2.result(timeout=10) is True
    finally:
        svc.close(timeout=30)


# -- randomized stream equivalence (acceptance gate) ------------------------


def _build_pool():
    """Distinct verifiable content: both kinds, mixed K buckets, a share
    of corrupt items (wrong message / wrong signature -> False)."""
    from consensus_specs_tpu.utils.bls12_381 import R

    pool = []
    for i, k in enumerate([1, 2, 3, 5, 1, 2, 8, 3]):
        sks = [100 * (i + 1) + j + 1 for j in range(k)]
        pks = [bls.SkToPk(sk) for sk in sks]
        msg = (b"fa%02d" % i) + b"\x00" * 28
        # aggregate of same-message sigs == one sig by the summed key
        sig = bls.Sign(sum(sks) % R, msg)
        if i % 4 == 3:
            msg = b"\xff" + msg[1:]  # corrupt: must verify False
        pool.append(("fast_aggregate", pks, msg, sig))
    for i, k in enumerate([1, 2, 3]):
        sks = [1000 + 10 * i + j + 1 for j in range(k)]
        pks = [bls.SkToPk(sk) for sk in sks]
        msgs = [(b"ag%02d_%d" % (i, j)) + b"\x00" * 24 for j in range(k)]
        sig = bls.Aggregate([bls.Sign(sk, m) for sk, m in zip(sks, msgs)])
        if i == 2:
            sig = bls.Sign(999, b"z" * 32)  # unrelated signature: False
        pool.append(("aggregate", pks, msgs, sig))
    return pool


def test_randomized_stream_equivalence_vs_oracle():
    """>= 200 mixed submit()s (both kinds, mixed K buckets, duplicates
    injected): service results must be bit-identical to the collector's
    flush_oracle() on the same stream, every duplicate verified exactly
    once (backend item ledger == unique count), cache hit rate > 0."""
    from consensus_specs_tpu.ops.bls_backend import _k_bucket

    rng = random.Random(0xC0FFEE)
    pool = _build_pool()
    events = [pool[rng.randrange(len(pool))] for _ in range(200)]
    events[: len(pool)] = pool  # every distinct item appears at least once

    # sequential reference: the same stream recorded through the collector
    # and resolved by flush_oracle() (per-occurrence pure-Python verify)
    col = SignatureCollector()
    for kind, pks, msgs, sig in events:
        if kind == "fast_aggregate":
            assert col._fast_aggregate_verify(pks, msgs, sig) is True
        else:
            assert col._aggregate_verify(pks, msgs, sig) is True
    uniq, members = col._unique_checks()
    assert len(uniq) == len(pool)
    # flush_oracle on the unique slice, fanned out in record order — the
    # oracle verdict per occurrence without 200 redundant pairings
    ucol = SignatureCollector()
    ucol.checks = [col.checks[i] for i in uniq]
    want_unique = ucol.flush_oracle()
    want = np.zeros(len(events), dtype=bool)
    for u, m in enumerate(members):
        want[m] = want_unique[u]

    be = OracleBackend()
    svc = VerificationService(backend=be, bucket_fn=_k_bucket,
                              max_batch=32, max_wait_ms=5)
    try:
        futs = [svc.submit(kind, pks, msgs, sig)
                for kind, pks, msgs, sig in events]
        got = np.array([f.result(timeout=120) for f in futs], dtype=bool)
    finally:
        svc.close(timeout=60)

    assert np.array_equal(got, want)
    assert want.any() and not want.all()  # stream carried Trues AND Falses
    # every duplicate verified exactly once: the backend saw each distinct
    # item one time, and dedup absorbed everything else
    assert be.items == len(pool)
    # micro-batches rode the default RLC route (whole-flush combine), not
    # the per-(kind, K-bucket) path
    assert be.rlc_calls > 0
    m = svc.metrics
    assert m.cache_hits + m.inflight_joins == len(events) - len(pool)
    assert m.hit_rate > 0
    snap = m.snapshot()
    # joins share the first submitter's Future and therefore its latency
    # sample; everyone else (enqueued + cache hits) records one
    assert snap["latency"]["count"] == len(events) - m.inflight_joins
    assert 0 < snap["occupancy_rows"] <= 1


def test_service_with_real_device_backend(monkeypatch):
    """The service in front of the REAL batched backend, at the exact
    shapes tests/test_bls_backend_fast.py and tests/test_rlc.py compile
    on every default run — both submits flush as ONE micro-batch through
    batch_verify_rlc (the serve default), whose failed combined check
    bisects down to exact per-item verdicts."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_CHUNK", "2")
    sk1, sk2 = 41, 42
    pk1, pk2 = bls.SkToPk(sk1), bls.SkToPk(sk2)
    msg = b"\x05" * 32
    agg = bls.Aggregate([bls.Sign(sk1, msg), bls.Sign(sk2, msg)])

    from consensus_specs_tpu.ops import bls_backend

    bls_backend.reset_call_counts()
    svc = VerificationService(max_batch=2, max_wait_ms=10_000)
    try:
        f_good = svc.submit("fast_aggregate", [pk1, pk2], msg, agg)
        # same K bucket (2) so both ride ONE flush; the doubled pk1
        # aggregates to the wrong key -> False
        f_bad = svc.submit("fast_aggregate", [pk1, pk1], msg, agg)
        assert f_good.result(timeout=300) is True
        assert f_bad.result(timeout=300) is False
        # duplicate of a completed item: cache, not crypto
        assert svc.submit("fast_aggregate", [pk1, pk2], msg, agg).result() is True
    finally:
        svc.close(timeout=60)
    assert bls_backend.CALL_COUNTS["batch_verify_rlc"] == 1
    assert bls_backend.CALL_COUNTS["batch_fast_aggregate_verify"] == 0
    assert bls_backend.CALL_COUNTS["items"] == 2
    assert svc.metrics.fallback_items == 0
    snap = svc.metrics.snapshot()
    assert snap["rlc"]["combines"] >= 1


def test_rlc_env_off_reverts_to_per_group_path(monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC", "0")
    be = OracleBackend()
    kind, pks, msg, sig = _build_pool()[0]
    with _svc(be, max_batch=1, max_wait_ms=0) as svc:
        assert svc.submit(kind, pks, msg, sig).result(timeout=30) is True
    assert be.rlc_calls == 0 and be.calls == 1  # grouped path answered


def test_rlc_failure_degrades_to_per_group_then_oracle():
    """An RLC-specific fault (batch_verify_rlc raising) must degrade to
    the per-group batched path — NOT straight to the sequential oracle —
    and still resolve every request correctly."""

    class RlcBrokenBackend(CountingBackend):
        def batch_verify_rlc(self, items, mesh=None, rng=None):
            self.rlc_calls += 1
            raise RuntimeError("combine program exploded")

    be = RlcBrokenBackend()
    with _svc(be, max_batch=2, max_wait_ms=10_000, backend_retries=1) as svc:
        f1 = svc.submit("fast_aggregate", [PK], b"m1", b"a-ok")
        f2 = svc.submit("fast_aggregate", [PK], b"m2", b"b-bad")
        assert f1.result(timeout=10) is True
        assert f2.result(timeout=10) is False
    assert be.rlc_calls == 2  # attempt + bounded retry
    assert be.items == 2  # the per-group path carried the batch
    assert svc.metrics.fallback_items == 0  # oracle never needed
    assert svc.metrics.backend_retries == 1


# -- collector integration --------------------------------------------------


def test_collector_flush_routes_through_service():
    """SignatureCollector.flush(service=...) returns the same verdicts in
    record order as flush_oracle(), with duplicates fanned out."""
    from consensus_specs_tpu.utils.bls12_381 import R

    sks = [11, 12]
    pks = [bls.SkToPk(sk) for sk in sks]
    msg = b"flush-via-service" + b"\x00" * 15
    sig = bls.Sign(sum(sks) % R, msg)

    col = SignatureCollector()
    assert col._fast_aggregate_verify(pks, msg, sig) is True
    assert col._fast_aggregate_verify(pks, msg, sig) is True  # duplicate
    assert col._fast_aggregate_verify(pks, b"\xff" + msg[1:], sig) is True

    be = OracleBackend()
    svc = VerificationService(backend=be, max_batch=8, max_wait_ms=5)
    try:
        got = col.flush(service=svc)
    finally:
        svc.close(timeout=60)
    assert np.array_equal(got, col.flush_oracle())
    assert list(got) == [True, True, False]
    assert be.items == 2  # duplicate collapsed before submission


def test_pipeline_prep_device_split_in_snapshot():
    """The two-stage pipeline reports where flush time goes: every flush
    gets a prep-stage timing (even for backends with no host caches) and
    a device-stage timing, and the snapshot carries the backend prep-plane
    counters (serial-fallback items, pool-broken latch)."""
    be = CountingBackend()
    svc = VerificationService(backend=be, max_batch=4, max_wait_ms=5)
    try:
        futs = [
            svc.submit("fast_aggregate", [PK], b"m%d" % i, b"s%d-ok" % i)
            for i in range(8)
        ]
        assert all(f.result(timeout=10) is True for f in futs)
    finally:
        svc.close(timeout=30)
    snap = svc.metrics.snapshot()
    assert snap["prep_batches"] >= 1
    # both split counters are per FLUSH (a flush can hold several
    # (kind, K-bucket) groups, counted separately by `batches`)
    assert snap["prep_batches"] == snap["device_flushes"] > 0
    assert snap["batches"] >= snap["device_flushes"]
    for key in ("prep_ms_per_flush", "prep_ms_total",
                "device_ms_per_flush", "device_ms_total"):
        assert snap[key] >= 0.0
    assert "serial_fallback_items" in snap["prep"]
    assert "pool_broken" in snap["prep"]
    # RLC amortization counters ride the snapshot too (deltas since this
    # service was constructed; zero here — CountingBackend has no RLC)
    assert snap["rlc"].get("combines", 0) == 0
    assert snap["final_exps_per_item"] == 0.0
