"""Fused straight-line lowering (ops/vm_compile.py, ISSUE 13): identity
against the interpreter and the exact-int IR oracle, chunk-boundary
liveness, routing (interp|fused|auto + the measured-winner persistence),
the interpreter fallback with its flight event, and the fused
``.vm_cache`` key/prune rules.

Everything here runs at SYNTHETIC-program scale (tens of levels, tiny
chunk overrides) so the whole module stays in the tier-1 budget — the
fused XLA compile bill for REGISTRY programs (~0.4 s per scheduled level
on CPU) lives in `make vmexec-smoke` and the @slow tier instead."""
import os
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from consensus_specs_tpu.ops import (  # noqa: E402
    bls_backend as bb, fq, vm, vm_analysis, vm_compile, vmlib,
)
from consensus_specs_tpu.utils import bls12_381 as O  # noqa: E402

rng = random.Random(31)

BUCKET = dict(w_mul=64, w_lin=64, pad_steps_to=256, pad_regs_to=64)


@pytest.fixture(autouse=True)
def _fresh_fused_state():
    vm_compile.reset_fused_state()
    yield
    vm_compile.reset_fused_state()


def _mixed_prog(depth=6):
    """A synthetic program exercising every op kind, constants, input
    reuse, and enough depth to span several tiny chunks."""
    prog = vm.Prog()
    a = prog.inp("a")
    b = prog.inp("b")
    c = prog.inp("c")
    k = prog.const(0x1234567890ABCDEF ^ O.P // 3)
    acc = a * b + k
    other = (b - c) * (a + k)
    for _ in range(depth):
        acc = acc * acc + other
        other = other * b - a
    prog.out(acc, "acc")
    prog.out(other, "other")
    return prog


def _rand_inputs(prog, rows=0):
    names = set()
    ints = [
        {n: rng.randrange(O.P) for n in prog.input_names}
        for _ in range(max(1, rows))
    ]
    if rows:
        arrs = {
            n: np.stack([fq.to_mont_int(row[n]) for row in ints])
            for n in ints[0]
        }
    else:
        arrs = {n: fq.to_mont_int(v) for n, v in ints[0].items()}
    return ints, arrs


def _run_both(assembled, arrs, batch_shape, monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "interp")
    out_i = vm.execute(assembled, arrs, batch_shape=batch_shape)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "fused")
    out_f = vm.execute(assembled, arrs, batch_shape=batch_shape)
    return out_i, out_f


def test_fused_identity_and_oracle_scalar(monkeypatch):
    prog = _mixed_prog()
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "5")
    out_i, out_f = _run_both(assembled, arrs, (), monkeypatch)
    want = vm_analysis.eval_ir(prog, ints[0])
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name
        got = fq.limbs_to_int(np.asarray(out_f[name]))
        # full loose-representative identity, not just mod-p agreement
        assert got == want[name], name
    assert vm_compile._COUNTERS["executions"] == 1
    assert vm_compile._COUNTERS["fallbacks"] == 0


def test_fused_identity_batch_axis(monkeypatch):
    prog = _mixed_prog(depth=4)
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog, rows=3)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "4")
    out_i, out_f = _run_both(assembled, arrs, (3,), monkeypatch)
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name
    for r in range(3):
        want = vm_analysis.eval_ir(prog, ints[r])
        for name, w in want.items():
            assert fq.limbs_to_int(np.asarray(out_f[name])[r]) == w


@pytest.mark.parametrize("chunk", ["1", "3", "1000000"])
def test_chunk_boundary_liveness(monkeypatch, chunk):
    """Identity must hold at EVERY chunking — chunk=1 puts a carry
    boundary after every level (maximum live-set stress), the huge value
    collapses to a single chunk (no boundaries at all)."""
    prog = _mixed_prog(depth=3)
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", chunk)
    out_i, out_f = _run_both(assembled, arrs, (), monkeypatch)
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name


def test_fused_f12_formula_vs_oracle(monkeypatch):
    """A real vmlib formula block (Fq12 mul) through the fused backend,
    held to the pure-Python field oracle — the same contract
    tests/test_vm.py pins on the interpreter."""
    prog = vm.Prog()
    x = [prog.inp(f"x{i}") for i in range(12)]
    y = [prog.inp(f"y{i}") for i in range(12)]
    m = vmlib.f12_mul(prog, x, y)
    for i, c in enumerate(m):
        prog.out(c, f"m{i}")
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "8")
    out_i, out_f = _run_both(assembled, arrs, (), monkeypatch)
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name
    want = vm_analysis.eval_ir(prog, ints[0])
    for name, w in want.items():
        assert fq.limbs_to_int(np.asarray(out_f[name])) == w


def test_fused_fallback_flight_event(monkeypatch):
    """A fused trace/compile/run failure must fall back to the
    interpreter (correct outputs, no exception) and journal a
    vm/fused_fallback flight event."""
    from consensus_specs_tpu.obs import flight

    prog = _mixed_prog(depth=2)
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "fused")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "1")
    flight.reset_global()

    def boom(*a, **kw):
        raise RuntimeError("injected lowering failure")

    monkeypatch.setattr(vm_compile, "run_fused", boom)
    out = vm.execute(assembled, arrs)
    want = vm_analysis.eval_ir(prog, ints[0])
    for name, w in want.items():
        assert fq.limbs_to_int(np.asarray(out[name])) == w
    assert vm_compile._COUNTERS["fallbacks"] == 1
    events = [e for e in flight.global_recorder().events()
              if e.get("plane") == "vm" and e.get("kind") == "fused_fallback"]
    assert events, "fused_fallback flight event missing"
    assert "injected lowering failure" in events[-1]["data"]["error"]
    flight.reset_global()


def test_auto_routing_uses_measured_winner(monkeypatch):
    """auto == interp until a fused measurement exists; once the ledger
    holds both warm numbers the measured winner takes the call."""
    prog = _mixed_prog(depth=2)
    assembled = prog.assemble(**BUCKET)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "auto")
    assert not vm_compile.use_fused(assembled)  # no measurements: interp
    assembled._exec_stats = {"fused_ms_row": 1.0, "interp_ms_row": 5.0}
    assert vm_compile.use_fused(assembled)
    assembled._exec_stats = {"fused_ms_row": 5.0, "interp_ms_row": 1.0}
    assert not vm_compile.use_fused(assembled)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "interp")
    assembled._exec_stats = {"fused_ms_row": 1.0, "interp_ms_row": 5.0}
    assert not vm_compile.use_fused(assembled)  # pinned interp always wins
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "fused")
    assembled._exec_stats = {}
    assert vm_compile.use_fused(assembled)  # pinned fused compiles on demand


def test_auto_routing_persists_across_processes(monkeypatch, tmp_path):
    """The measured-winner pair rides the .vm_cache lowering plan: a
    fresh Program instance (== fresh process) with the same fused cache
    key adopts the persisted verdict — but auto only SERVES fused once
    the shape is compiled (warm_fused/pinned-fused), never paying the
    cold compile bill mid-call."""
    monkeypatch.setattr(bb, "_vm_cache_dir", lambda: str(tmp_path))
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "6")
    prog = _mixed_prog(depth=2)
    assembled = prog.assemble(**BUCKET)
    assembled.meta["fused_key"] = ("synthetic", 0, 1, "cafe0123")
    ints, arrs = _rand_inputs(prog)

    # measure both paths in "process one" (interp first, then fused twice
    # so the second, warm call lands in the ledger and persists)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "interp")
    vm.execute(assembled, arrs)
    vm.execute(assembled, arrs)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "fused")
    vm.execute(assembled, arrs)
    vm.execute(assembled, arrs)
    st = assembled._exec_stats
    assert st.get("fused_ms_row") is not None
    assert st.get("interp_ms_row") is not None

    plan_path = vm_compile._plan_cache_path(assembled)
    assert plan_path is not None and os.path.exists(plan_path)
    import pickle

    with open(plan_path, "rb") as fh:
        meas = pickle.load(fh).get("measured") or {}
    assert "fused_ms_row" in meas and "interp_ms_row" in meas

    # force the persisted pair to a known winner, then simulate a fresh
    # process: a new Program object with the same cache identity
    with open(plan_path, "rb") as fh:
        plan = pickle.load(fh)
    plan["measured"] = {"fused_ms_row": 1.0, "interp_ms_row": 9.0}
    with open(plan_path, "wb") as fh:
        pickle.dump(plan, fh)
    vm_compile.reset_fused_state()
    fresh = prog.assemble(**BUCKET)
    fresh.meta["fused_key"] = ("synthetic", 0, 1, "cafe0123")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "auto")
    assert vm_compile.use_fused(fresh)  # winner adopted off the disk plan
    # ...but a not-yet-compiled shape must stay on the interpreter: auto
    # never pays the cold trace+compile bill inside a call
    assert not vm_compile.use_fused(fresh, shape_sig=((), False))
    before = vm_compile._COUNTERS["executions"]
    out_cold = vm.execute(fresh, arrs)
    assert vm_compile._COUNTERS["executions"] == before  # interp served it
    vm_compile.warm_fused(fresh, ())
    assert vm_compile.use_fused(fresh, shape_sig=((), False))
    out_a = vm.execute(fresh, arrs)
    assert vm_compile._COUNTERS["executions"] == before + 1  # fused now
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "interp")
    out_i = vm.execute(fresh, arrs)
    for name in out_i:
        assert np.array_equal(np.asarray(out_a[name]),
                              np.asarray(out_i[name])), name
        assert np.array_equal(np.asarray(out_cold[name]),
                              np.asarray(out_i[name])), name

    # a persisted interp win keeps auto on the interpreter
    plan["measured"] = {"fused_ms_row": 9.0, "interp_ms_row": 1.0}
    with open(plan_path, "wb") as fh:
        pickle.dump(plan, fh)
    vm_compile.reset_fused_state()
    fresh2 = prog.assemble(**BUCKET)
    fresh2.meta["fused_key"] = ("synthetic", 0, 1, "cafe0123")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "auto")
    assert not vm_compile.use_fused(fresh2)


def test_warm_fused_reports_compile_seconds(monkeypatch):
    prog = _mixed_prog(depth=2)
    assembled = prog.assemble(**BUCKET)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "6")
    dt = vm_compile.warm_fused(assembled, ())
    assert dt > 0.0
    assert vm_compile.warm_fused(assembled, ()) == 0.0  # in-process warm


# -- fused .vm_cache key + prune rules (ISSUE 13 satellite) ----------------


def _fused_name(lowering=None, version=None, kind="g2_subgroup", fp=None):
    lowering = vm_compile.LOWERING_VERSION if lowering is None else lowering
    version = bb._VM_CACHE_VERSION if version is None else version
    fp = bb._program_fingerprint(kind) if fp is None else fp
    return (f"fused_l{lowering}_v{version}_{fp}_{kind}"
            f"_k0_f1_w96x192_p1024_c24.pkl")


def test_fused_cache_stale_rules():
    assert not bb._vm_cache_entry_stale(_fused_name())
    # a lowering bump evicts fused plans WITHOUT touching interp tensors
    assert bb._vm_cache_entry_stale(
        _fused_name(lowering=vm_compile.LOWERING_VERSION + 1))
    assert bb._vm_cache_entry_stale(
        _fused_name(version=bb._VM_CACHE_VERSION + 1))
    # a moved per-program fingerprint (edited builder) evicts too
    assert bb._vm_cache_entry_stale(_fused_name(fp="00000000"))
    # unknown kinds are kept (age/size still bound them)
    assert not bb._vm_cache_entry_stale(
        _fused_name(kind="not_a_builder", fp="00000000"))
    # malformed fused names are kept, never crash
    assert not bb._vm_cache_entry_stale("fused_weird.pkl")


def test_prune_evicts_stale_fused_entries(tmp_path):
    stale = tmp_path / _fused_name(lowering=vm_compile.LOWERING_VERSION + 1)
    fresh = tmp_path / _fused_name()
    interp = tmp_path / (
        f"v{bb._VM_CACHE_VERSION}_{bb._program_fingerprint('g2_subgroup')}"
        "_g2_subgroup_k0_f1_w96x192_p1024.pkl")
    for p in (stale, fresh, interp):
        p.write_bytes(b"x" * 64)
    res = bb.prune_vm_cache(max_age_days=0, max_bytes=0,
                            cache_dir=str(tmp_path))
    assert not stale.exists()  # old lowering version: gone immediately
    assert fresh.exists()      # current fused artifact: kept
    assert interp.exists()     # interp tensors: untouched by the bump
    assert res["evicted"] == 1 and res["kept"] == 2


def test_fused_key_rides_program_cache(tmp_path, monkeypatch):
    """bls_backend._program stamps the fused cache identity onto the
    assembled (and disk-cached) program's meta so the lowering can disk-
    key its plan; the stamp survives the pickle round-trip."""
    monkeypatch.setattr(bb, "_vm_cache_dir", lambda: str(tmp_path))
    bb._program.cache_clear()
    try:
        prog, fold = bb._program("g2_subgroup", 0, 1)
        key = prog.meta.get("fused_key")
        assert key is not None
        kind, k, f, fp = key
        assert (kind, k, f) == ("g2_subgroup", 0, 1)
        assert fp == bb._program_fingerprint("g2_subgroup")
        bb._program.cache_clear()
        again, _ = bb._program("g2_subgroup", 0, 1)  # disk hit this time
        assert again.meta.get("fused_key") == key
    finally:
        bb._program.cache_clear()


# -- `make native` discoverability warning (ISSUE 13 satellite) ------------


def test_assemble_warns_once_when_native_kernel_missing(monkeypatch, capsys):
    monkeypatch.setattr(vm, "_NATIVE_SCHED", None)
    monkeypatch.setattr(vm, "_NATIVE_WARNED", False)
    prog = _mixed_prog(depth=1)
    prog.assemble(**BUCKET)
    err = capsys.readouterr().err
    assert "make native" in err and "libvmsched" in err
    prog2 = _mixed_prog(depth=1)
    prog2.assemble(**BUCKET)
    assert "make native" not in capsys.readouterr().err  # once per process


def test_no_warning_when_native_kernel_present(monkeypatch, capsys):
    # _warn_native_missing only prints when the kernel is absent; with a
    # (real or stand-in) kernel loaded it stays silent
    monkeypatch.setattr(vm, "_NATIVE_SCHED", object())
    monkeypatch.setattr(vm, "_NATIVE_WARNED", False)
    vm._warn_native_missing()
    assert "make native" not in capsys.readouterr().err
    assert vm._NATIVE_WARNED is False


# -- full-registry identity (out of tier-1) --------------------------------


@pytest.mark.slow
def test_vmexec_smoke_full_registry(monkeypatch):
    """The `make vmexec-smoke` module over the ENTIRE BUILDERS registry
    (production shapes): fused == interp == exact-int oracle. Pays one
    fused XLA compile per program — minutes-to-hours on a cold persistent
    cache, so @slow (the CI job runs the module's default cheap subset)."""
    from consensus_specs_tpu.ops import vmexec_smoke

    monkeypatch.setenv("VMEXEC_SMOKE_FULL", "1")
    assert vmexec_smoke.main() == 0
