"""Fused straight-line lowering (ops/vm_compile.py, ISSUE 13): identity
against the interpreter and the exact-int IR oracle, chunk-boundary
liveness, routing (interp|fused|auto + the measured-winner persistence),
the interpreter fallback with its flight event, and the fused
``.vm_cache`` key/prune rules.

Everything here runs at SYNTHETIC-program scale (tens of levels, tiny
chunk overrides) so the whole module stays in the tier-1 budget — the
fused XLA compile bill for REGISTRY programs (~0.4 s per scheduled level
on CPU) lives in `make vmexec-smoke` and the @slow tier instead."""
import os
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from consensus_specs_tpu.ops import (  # noqa: E402
    bls_backend as bb, fq, vm, vm_analysis, vm_compile, vmlib,
)
from consensus_specs_tpu.utils import bls12_381 as O  # noqa: E402

rng = random.Random(31)

BUCKET = dict(w_mul=64, w_lin=64, pad_steps_to=256, pad_regs_to=64)


@pytest.fixture(autouse=True)
def _fresh_fused_state():
    vm_compile.reset_fused_state()
    yield
    vm_compile.reset_fused_state()


def _mixed_prog(depth=6):
    """A synthetic program exercising every op kind, constants, input
    reuse, and enough depth to span several tiny chunks."""
    prog = vm.Prog()
    a = prog.inp("a")
    b = prog.inp("b")
    c = prog.inp("c")
    k = prog.const(0x1234567890ABCDEF ^ O.P // 3)
    acc = a * b + k
    other = (b - c) * (a + k)
    for _ in range(depth):
        acc = acc * acc + other
        other = other * b - a
    prog.out(acc, "acc")
    prog.out(other, "other")
    return prog


def _rand_inputs(prog, rows=0):
    names = set()
    ints = [
        {n: rng.randrange(O.P) for n in prog.input_names}
        for _ in range(max(1, rows))
    ]
    if rows:
        arrs = {
            n: np.stack([fq.to_mont_int(row[n]) for row in ints])
            for n in ints[0]
        }
    else:
        arrs = {n: fq.to_mont_int(v) for n, v in ints[0].items()}
    return ints, arrs


def _run_both(assembled, arrs, batch_shape, monkeypatch):
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "interp")
    out_i = vm.execute(assembled, arrs, batch_shape=batch_shape)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "fused")
    out_f = vm.execute(assembled, arrs, batch_shape=batch_shape)
    return out_i, out_f


def test_fused_identity_and_oracle_scalar(monkeypatch):
    prog = _mixed_prog()
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "5")
    out_i, out_f = _run_both(assembled, arrs, (), monkeypatch)
    want = vm_analysis.eval_ir(prog, ints[0])
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name
        got = fq.limbs_to_int(np.asarray(out_f[name]))
        # full loose-representative identity, not just mod-p agreement
        assert got == want[name], name
    assert vm_compile._COUNTERS["executions"] == 1
    assert vm_compile._COUNTERS["fallbacks"] == 0


def test_fused_identity_batch_axis(monkeypatch):
    prog = _mixed_prog(depth=4)
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog, rows=3)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "4")
    out_i, out_f = _run_both(assembled, arrs, (3,), monkeypatch)
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name
    for r in range(3):
        want = vm_analysis.eval_ir(prog, ints[r])
        for name, w in want.items():
            assert fq.limbs_to_int(np.asarray(out_f[name])[r]) == w


@pytest.mark.parametrize("chunk", ["1", "3", "1000000"])
def test_chunk_boundary_liveness(monkeypatch, chunk):
    """Identity must hold at EVERY chunking — chunk=1 puts a carry
    boundary after every level (maximum live-set stress), the huge value
    collapses to a single chunk (no boundaries at all)."""
    prog = _mixed_prog(depth=3)
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", chunk)
    out_i, out_f = _run_both(assembled, arrs, (), monkeypatch)
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name


def test_fused_f12_formula_vs_oracle(monkeypatch):
    """A real vmlib formula block (Fq12 mul) through the fused backend,
    held to the pure-Python field oracle — the same contract
    tests/test_vm.py pins on the interpreter."""
    prog = vm.Prog()
    x = [prog.inp(f"x{i}") for i in range(12)]
    y = [prog.inp(f"y{i}") for i in range(12)]
    m = vmlib.f12_mul(prog, x, y)
    for i, c in enumerate(m):
        prog.out(c, f"m{i}")
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "8")
    out_i, out_f = _run_both(assembled, arrs, (), monkeypatch)
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name
    want = vm_analysis.eval_ir(prog, ints[0])
    for name, w in want.items():
        assert fq.limbs_to_int(np.asarray(out_f[name])) == w


def test_fused_fallback_flight_event(monkeypatch):
    """A fused trace/compile/run failure must fall back to the
    interpreter (correct outputs, no exception) and journal a
    vm/fused_fallback flight event."""
    from consensus_specs_tpu.obs import flight

    prog = _mixed_prog(depth=2)
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "fused")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_FLIGHT", "1")
    flight.reset_global()

    def boom(*a, **kw):
        raise RuntimeError("injected lowering failure")

    monkeypatch.setattr(vm_compile, "run_fused", boom)
    out = vm.execute(assembled, arrs)
    want = vm_analysis.eval_ir(prog, ints[0])
    for name, w in want.items():
        assert fq.limbs_to_int(np.asarray(out[name])) == w
    assert vm_compile._COUNTERS["fallbacks"] == 1
    events = [e for e in flight.global_recorder().events()
              if e.get("plane") == "vm" and e.get("kind") == "fused_fallback"]
    assert events, "fused_fallback flight event missing"
    assert "injected lowering failure" in events[-1]["data"]["error"]
    flight.reset_global()


def test_auto_routing_uses_measured_winner(monkeypatch):
    """auto == interp until a fused measurement exists; once the ledger
    holds both warm numbers the measured winner takes the call."""
    prog = _mixed_prog(depth=2)
    assembled = prog.assemble(**BUCKET)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "auto")
    assert not vm_compile.use_fused(assembled)  # no measurements: interp
    assembled._exec_stats = {"fused_ms_row": 1.0, "interp_ms_row": 5.0}
    assert vm_compile.use_fused(assembled)
    assembled._exec_stats = {"fused_ms_row": 5.0, "interp_ms_row": 1.0}
    assert not vm_compile.use_fused(assembled)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "interp")
    assembled._exec_stats = {"fused_ms_row": 1.0, "interp_ms_row": 5.0}
    assert not vm_compile.use_fused(assembled)  # pinned interp always wins
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "fused")
    assembled._exec_stats = {}
    assert vm_compile.use_fused(assembled)  # pinned fused compiles on demand


def test_auto_routing_persists_across_processes(monkeypatch, tmp_path):
    """The measured-winner pair rides the .vm_cache lowering plan: a
    fresh Program instance (== fresh process) with the same fused cache
    key adopts the persisted verdict — but auto only SERVES fused once
    the shape is compiled (warm_fused/pinned-fused), never paying the
    cold compile bill mid-call."""
    monkeypatch.setattr(bb, "_vm_cache_dir", lambda: str(tmp_path))
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "6")
    prog = _mixed_prog(depth=2)
    assembled = prog.assemble(**BUCKET)
    assembled.meta["fused_key"] = ("synthetic", 0, 1, "cafe0123")
    ints, arrs = _rand_inputs(prog)

    # measure both paths in "process one" (interp first, then fused twice
    # so the second, warm call lands in the ledger and persists)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "interp")
    vm.execute(assembled, arrs)
    vm.execute(assembled, arrs)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "fused")
    vm.execute(assembled, arrs)
    vm.execute(assembled, arrs)
    st = assembled._exec_stats
    assert st.get("fused_ms_row") is not None
    assert st.get("interp_ms_row") is not None

    plan_path = vm_compile._plan_cache_path(assembled)
    assert plan_path is not None and os.path.exists(plan_path)
    import pickle

    with open(plan_path, "rb") as fh:
        meas = pickle.load(fh).get("measured") or {}
    assert "fused_ms_row" in meas and "interp_ms_row" in meas

    # force the persisted pair to a known winner, then simulate a fresh
    # process: a new Program object with the same cache identity
    with open(plan_path, "rb") as fh:
        plan = pickle.load(fh)
    plan["measured"] = {"fused_ms_row": 1.0, "interp_ms_row": 9.0}
    with open(plan_path, "wb") as fh:
        pickle.dump(plan, fh)
    vm_compile.reset_fused_state()
    fresh = prog.assemble(**BUCKET)
    fresh.meta["fused_key"] = ("synthetic", 0, 1, "cafe0123")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "auto")
    assert vm_compile.use_fused(fresh)  # winner adopted off the disk plan
    # ...but a not-yet-compiled shape must stay on the interpreter: auto
    # never pays the cold trace+compile bill inside a call
    assert not vm_compile.use_fused(fresh, shape_sig=((), False))
    before = vm_compile._COUNTERS["executions"]
    out_cold = vm.execute(fresh, arrs)
    assert vm_compile._COUNTERS["executions"] == before  # interp served it
    vm_compile.warm_fused(fresh, ())
    assert vm_compile.use_fused(fresh, shape_sig=((), False))
    out_a = vm.execute(fresh, arrs)
    assert vm_compile._COUNTERS["executions"] == before + 1  # fused now
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "interp")
    out_i = vm.execute(fresh, arrs)
    for name in out_i:
        assert np.array_equal(np.asarray(out_a[name]),
                              np.asarray(out_i[name])), name
        assert np.array_equal(np.asarray(out_cold[name]),
                              np.asarray(out_i[name])), name

    # a persisted interp win keeps auto on the interpreter
    plan["measured"] = {"fused_ms_row": 9.0, "interp_ms_row": 1.0}
    with open(plan_path, "wb") as fh:
        pickle.dump(plan, fh)
    vm_compile.reset_fused_state()
    fresh2 = prog.assemble(**BUCKET)
    fresh2.meta["fused_key"] = ("synthetic", 0, 1, "cafe0123")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "auto")
    assert not vm_compile.use_fused(fresh2)


def test_warm_fused_reports_compile_seconds(monkeypatch):
    prog = _mixed_prog(depth=2)
    assembled = prog.assemble(**BUCKET)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "6")
    dt = vm_compile.warm_fused(assembled, ())
    assert dt > 0.0
    assert vm_compile.warm_fused(assembled, ()) == 0.0  # in-process warm


# -- fused .vm_cache key + prune rules (ISSUE 13 + 15 satellites) ----------


def _plan_name(lowering=None, version=None, kind="g2_subgroup", fp=None,
               chunk=24):
    lowering = vm_compile.LOWERING_VERSION if lowering is None else lowering
    version = bb._VM_CACHE_VERSION if version is None else version
    fp = bb._program_fingerprint(kind) if fp is None else fp
    return (f"fusedplan_l{lowering}_v{version}_{fp}_{kind}"
            f"_k0_f1_w96x192_p1024_c{chunk}.pkl")


def _struct_name(key="ab" * 12, lowering=None):
    lowering = vm_compile.LOWERING_VERSION if lowering is None else lowering
    return f"fusedstruct_l{lowering}_{key}.pkl"


def test_fused_cache_stale_rules():
    assert not bb._vm_cache_entry_stale(_plan_name())
    # a lowering bump evicts fused plans WITHOUT touching interp tensors
    assert bb._vm_cache_entry_stale(
        _plan_name(lowering=vm_compile.LOWERING_VERSION + 1))
    assert bb._vm_cache_entry_stale(
        _plan_name(version=bb._VM_CACHE_VERSION + 1))
    # a moved per-program fingerprint (edited builder) evicts too
    assert bb._vm_cache_entry_stale(_plan_name(fp="00000000"))
    # unknown kinds are kept (age/size still bound them)
    assert not bb._vm_cache_entry_stale(
        _plan_name(kind="not_a_builder", fp="00000000"))
    # shared structure bodies re-key on the lowering version alone
    assert not bb._vm_cache_entry_stale(_struct_name())
    assert bb._vm_cache_entry_stale(
        _struct_name(lowering=vm_compile.LOWERING_VERSION + 1))
    # the RETIRED PR 13 per-program keying is stale on sight — ANY
    # version, including one matching the current numbers
    assert bb._vm_cache_entry_stale(
        f"fused_l{vm_compile.LOWERING_VERSION}_v{bb._VM_CACHE_VERSION}_"
        f"{bb._program_fingerprint('g2_subgroup')}_g2_subgroup"
        "_k0_f1_w96x192_p1024_c24.pkl")
    assert bb._vm_cache_entry_stale("fused_l1_v2_cafe_g2_subgroup"
                                    "_k0_f1_w96x192_p1024_c24.pkl")
    assert bb._vm_cache_entry_stale("fused_weird.pkl")
    # malformed new-prefix names are kept, never crash
    assert not bb._vm_cache_entry_stale("fusedplan_weird.pkl")
    assert not bb._vm_cache_entry_stale("fusedstruct_weird.pkl")


def _write_plan_entry(tmp_path, refs, name=None):
    import pickle

    p = tmp_path / (name or _plan_name())
    with open(p, "wb") as fh:
        pickle.dump({"format": 2, "struct_refs": list(refs)}, fh)
    return p


def test_prune_evicts_stale_fused_entries(tmp_path):
    stale = tmp_path / _plan_name(lowering=vm_compile.LOWERING_VERSION + 1)
    old_keying = tmp_path / (
        "fused_l1_v2_cafe_g2_subgroup_k0_f1_w96x192_p1024_c24.pkl")
    interp = tmp_path / (
        f"v{bb._VM_CACHE_VERSION}_{bb._program_fingerprint('g2_subgroup')}"
        "_g2_subgroup_k0_f1_w96x192_p1024.pkl")
    for p in (stale, old_keying, interp):
        p.write_bytes(b"x" * 64)
    fresh = _write_plan_entry(tmp_path, [])
    res = bb.prune_vm_cache(max_age_days=0, max_bytes=0,
                            cache_dir=str(tmp_path))
    assert not stale.exists()      # old lowering version: gone immediately
    assert not old_keying.exists()  # retired PR 13 keying: gone on sight
    assert fresh.exists()          # current fused plan: kept
    assert interp.exists()         # interp tensors: untouched by the bump
    assert res["evicted"] == 2 and res["kept"] == 2


def test_prune_keeps_referenced_structs_evicts_orphans(tmp_path):
    key_live, key_orphan = "aa" * 12, "bb" * 12
    live = tmp_path / _struct_name(key_live)
    orphan = tmp_path / _struct_name(key_orphan)
    for p in (live, orphan):
        p.write_bytes(b"x" * 64)
    plan = _write_plan_entry(tmp_path, [key_live])
    # make everything "old": referenced structs must still survive the
    # age rule because their referencing plan survives
    import os as _os
    import time as _time

    old = _time.time() - 90 * 86400
    _os.utime(live, (old, old))
    _os.utime(orphan, (old, old))
    res = bb.prune_vm_cache(max_age_days=365, max_bytes=0,
                            cache_dir=str(tmp_path))
    assert plan.exists()
    assert live.exists()        # referenced: survives despite its age
    assert not orphan.exists()  # no referencing plan: evicted
    assert res["evicted"] == 1


def test_prune_drops_structs_when_referencing_plan_goes(tmp_path):
    """When the last referencing plan is age-evicted, its structures
    orphan and go in the same prune; a corrupt plan contributes no refs
    (and the loader side falls back to re-derivation, tested below)."""
    key = "cc" * 12
    struct = tmp_path / _struct_name(key)
    struct.write_bytes(b"x" * 64)
    plan = _write_plan_entry(tmp_path, [key])
    import os as _os
    import time as _time

    old = _time.time() - 90 * 86400
    _os.utime(plan, (old, old))
    res = bb.prune_vm_cache(max_age_days=30, max_bytes=0,
                            cache_dir=str(tmp_path))
    assert not plan.exists()
    assert not struct.exists()
    assert res["evicted"] == 2


def test_fused_key_rides_program_cache(tmp_path, monkeypatch):
    """bls_backend._program stamps the fused cache identity onto the
    assembled (and disk-cached) program's meta so the lowering can disk-
    key its plan; the stamp survives the pickle round-trip."""
    monkeypatch.setattr(bb, "_vm_cache_dir", lambda: str(tmp_path))
    bb._program.cache_clear()
    try:
        prog, fold = bb._program("g2_subgroup", 0, 1)
        key = prog.meta.get("fused_key")
        assert key is not None
        kind, k, f, fp = key
        assert (kind, k, f) == ("g2_subgroup", 0, 1)
        assert fp == bb._program_fingerprint("g2_subgroup")
        bb._program.cache_clear()
        again, _ = bb._program("g2_subgroup", 0, 1)  # disk hit this time
        assert again.meta.get("fused_key") == key
    finally:
        bb._program.cache_clear()


# -- structural dedup + super-op coarsening (ISSUE 15) ---------------------


def _periodic_prog(iters=10):
    """A ladder-shaped program: one fixed loop body stamped ``iters``
    times — the structure class the chunk canonicalizer collapses. The
    two chains consume each other so the scheduler keeps them in
    lockstep (a constant steady-state live width, like the production
    square-and-multiply ladders); the per-iteration constants prove
    constants dedup as runtime operands."""
    prog = vm.Prog()
    acc = prog.inp("acc")
    other = prog.inp("other")
    for i in range(iters):
        k = prog.const(1000003 * (i + 1))  # per-iteration constant
        acc = acc * acc + other * k
        other = other * other - acc
    prog.out(acc, "acc")
    prog.out(other, "other")
    return prog


def test_structural_dedup_collapses_chunks(monkeypatch):
    """The ladder's repeated chunks must hash to FEWER distinct
    structures than chunks, runs must fold into scan super-ops, and the
    outputs must stay bit-identical to the interpreter + oracle."""
    prog = _periodic_prog(iters=12)
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "4")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_SUPEROP", "2")
    out_i, out_f = _run_both(assembled, arrs, (), monkeypatch)
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name
    want = vm_analysis.eval_ir(prog, ints[0])
    for name, w in want.items():
        assert fq.limbs_to_int(np.asarray(out_f[name])) == w
    fp = vm_compile._FUSED[id(assembled)]
    st = fp.struct_stats
    assert st["distinct_structs"] < st["chunks"], st
    assert st["superop_segments"] >= 1, st
    # compile units actually dedup'd: fewer misses than chunks
    assert vm_compile._COUNTERS["struct_misses"] < st["chunks"] + 1


def test_superop_off_still_identical(monkeypatch):
    prog = _periodic_prog(iters=8)
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog, rows=2)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "4")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_SUPEROP", "off")
    out_i, out_f = _run_both(assembled, arrs, (2,), monkeypatch)
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name
    fp = vm_compile._FUSED[id(assembled)]
    assert fp.struct_stats["superop_segments"] == 0


def test_dedup_off_pins_per_chunk_baseline(monkeypatch):
    """CONSENSUS_SPECS_TPU_VM_DEDUP=0 is the PR 13 one-compile-per-chunk
    baseline the cold bench races: every chunk its own structure, no
    super-ops, identity unchanged."""
    prog = _periodic_prog(iters=8)
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "4")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_DEDUP", "0")
    out_i, out_f = _run_both(assembled, arrs, (), monkeypatch)
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name
    fp = vm_compile._FUSED[id(assembled)]
    st = fp.struct_stats
    assert st["distinct_structs"] == st["chunks"]
    assert st["superop_segments"] == 0


def test_struct_cache_shared_across_programs(monkeypatch):
    """Two PROGRAMS with the same canonical chunk structure share the
    in-process compiled structures: the second program's warm is all
    structural hits, zero new compiles — and the batch shape SERVED
    through those hits (a different (program, shape) pair than the one
    that compiled them) stays bit-identical to the interpreter and the
    exact-int oracle (the ISSUE 15 acceptance case)."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "4")
    a = _periodic_prog(iters=6).assemble(**BUCKET)
    prog_b = _periodic_prog(iters=6)
    b = prog_b.assemble(**BUCKET)  # fresh program, same canonical form
    vm_compile.warm_fused(a, ())
    misses_after_a = vm_compile._COUNTERS["struct_misses"]
    assert misses_after_a > 0
    vm_compile.warm_fused(b, ())
    assert vm_compile._COUNTERS["struct_misses"] == misses_after_a
    assert vm_compile._COUNTERS["struct_hits"] > 0
    ints, arrs = _rand_inputs(prog_b)
    out_i, out_f = _run_both(b, arrs, (), monkeypatch)
    want = vm_analysis.eval_ir(prog_b, ints[0])
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name
        assert fq.limbs_to_int(np.asarray(out_f[name])) == want[name]


def test_corrupted_struct_entry_falls_back_to_rederive(
        monkeypatch, tmp_path):
    """A corrupted shared structure entry must make _load_plan return
    None (the caller re-derives and re-stores) — never raise into the
    execute path."""
    monkeypatch.setattr(bb, "_vm_cache_dir", lambda: str(tmp_path))
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "4")
    prog = _periodic_prog(iters=6)
    assembled = prog.assemble(**BUCKET)
    assembled.meta["fused_key"] = ("synthetic", 0, 1, "cafe0123")
    fp = vm_compile.fused_program(assembled)  # derives + stores
    refs = sorted(fp.plan["structs"])
    assert refs
    for ref in refs:
        spath = vm_compile._struct_cache_path(ref)
        assert os.path.exists(spath), ref
    # corrupt one structure entry on disk
    with open(vm_compile._struct_cache_path(refs[0]), "wb") as fh:
        fh.write(b"not a pickle")
    assert vm_compile._load_plan(assembled) is None
    # a fresh "process" still lowers fine (re-derive + re-store)
    vm_compile.reset_fused_state()
    fresh = prog.assemble(**BUCKET)
    fresh.meta["fused_key"] = ("synthetic", 0, 1, "cafe0123")
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "fused")
    out = vm.execute(fresh, arrs)
    want = vm_analysis.eval_ir(prog, ints[0])
    for name, w in want.items():
        assert fq.limbs_to_int(np.asarray(out[name])) == w
    assert vm_compile._load_plan(fresh) is not None  # re-stored intact


def test_env_knob_hardening_warns_once(monkeypatch, capsys):
    """Invalid or non-positive structural-dedup knobs warn ONCE on
    stderr and fall back to the documented default — never raise."""
    vm_compile._ENV_WARNED.clear()
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "banana")
    assert vm_compile.chunk_steps() == vm_analysis.FUSED_CHUNK_STEPS
    assert vm_compile.chunk_steps() == vm_analysis.FUSED_CHUNK_STEPS
    err = capsys.readouterr().err
    assert err.count("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK") == 1
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "-8")
    vm_compile._ENV_WARNED.clear()
    assert vm_compile.chunk_steps() == vm_analysis.FUSED_CHUNK_STEPS
    assert "ignoring invalid" in capsys.readouterr().err
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_DEDUP", "maybe")
    vm_compile._ENV_WARNED.clear()
    assert vm_compile.dedup_enabled() is True
    assert "CONSENSUS_SPECS_TPU_VM_DEDUP" in capsys.readouterr().err
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_SUPEROP", "1")
    vm_compile._ENV_WARNED.clear()
    assert vm_compile.superop_min_run({"sched_steps": 8, "n_mul": 1,
                                       "n_lin": 1}) == 3  # auto fallback
    assert "CONSENSUS_SPECS_TPU_VM_SUPEROP" in capsys.readouterr().err
    # valid values parse silently
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "7")
    assert vm_compile.chunk_steps() == 7
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_SUPEROP", "4")
    assert vm_compile.superop_min_run({"sched_steps": 8}) == 4
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_SUPEROP", "off")
    assert vm_compile.superop_min_run({"sched_steps": 8}) == 0
    assert capsys.readouterr().err == ""


def test_background_warm_flips_auto_to_fused(monkeypatch):
    """CONSENSUS_SPECS_TPU_VM_WARM_BG=1: an auto-routed call whose
    measured winner is fused but whose shape is cold serves the
    INTERPRETER and enqueues a background warm; once the warm lands,
    auto flips to fused for that shape."""
    prog = _mixed_prog(depth=2)
    assembled = prog.assemble(**BUCKET)
    ints, arrs = _rand_inputs(prog)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "6")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "auto")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_WARM_BG", "1")
    assembled._exec_stats = {"fused_ms_row": 1.0, "interp_ms_row": 5.0}
    # shape not compiled: the call must stay on the interpreter...
    assert not vm_compile.use_fused(assembled, shape_sig=((), False))
    before = vm_compile._COUNTERS["executions"]
    out_cold = vm.execute(assembled, arrs)
    assert vm_compile._COUNTERS["executions"] == before
    # ...but the background warm flips the route once it lands
    assert vm_compile.bg_warm_drain(timeout=120.0)
    assembled._exec_stats = {"fused_ms_row": 1.0, "interp_ms_row": 5.0}
    assert vm_compile.use_fused(assembled, shape_sig=((), False))
    out_warm = vm.execute(assembled, arrs)
    assert vm_compile._COUNTERS["executions"] == before + 1
    for name in out_cold:
        assert np.array_equal(np.asarray(out_cold[name]),
                              np.asarray(out_warm[name])), name


# -- `make native` discoverability warning (ISSUE 13 satellite) ------------


def test_assemble_warns_once_when_native_kernel_missing(monkeypatch, capsys):
    monkeypatch.setattr(vm, "_NATIVE_SCHED", None)
    monkeypatch.setattr(vm, "_NATIVE_WARNED", False)
    prog = _mixed_prog(depth=1)
    prog.assemble(**BUCKET)
    err = capsys.readouterr().err
    assert "make native" in err and "libvmsched" in err
    prog2 = _mixed_prog(depth=1)
    prog2.assemble(**BUCKET)
    assert "make native" not in capsys.readouterr().err  # once per process


def test_no_warning_when_native_kernel_present(monkeypatch, capsys):
    # _warn_native_missing only prints when the kernel is absent; with a
    # (real or stand-in) kernel loaded it stays silent
    monkeypatch.setattr(vm, "_NATIVE_SCHED", object())
    monkeypatch.setattr(vm, "_NATIVE_WARNED", False)
    vm._warn_native_missing()
    assert "make native" not in capsys.readouterr().err
    assert vm._NATIVE_WARNED is False


# -- full-registry identity (out of tier-1) --------------------------------


@pytest.mark.slow
def test_vmexec_smoke_full_registry(monkeypatch):
    """The `make vmexec-smoke` module over the ENTIRE BUILDERS registry
    (production shapes): fused == interp == exact-int oracle. Pays one
    fused XLA compile per program — minutes-to-hours on a cold persistent
    cache, so @slow (the CI job runs the module's default cheap subset)."""
    from consensus_specs_tpu.ops import vmexec_smoke

    monkeypatch.setenv("VMEXEC_SMOKE_FULL", "1")
    assert vmexec_smoke.main() == 0
