"""Cross-checks for the fused VM-step Pallas kernel (ops/pallas_step.py):
one kernel doing both ALU units on a 14-bit uint32 register file must be
bit-identical to the default u64 scan path (ops/vm.py _vm_step).

Runs in interpret mode on CPU (Mosaic compilation needs real hardware;
the on-hardware A/B rides the bench child's probe stage — TPU_NOTES.md).
"""
import numpy as np

from consensus_specs_tpu.utils.jax_env import force_cpu

force_cpu()

import jax.numpy as jnp  # noqa: E402
import pytest  # noqa: E402

from consensus_specs_tpu.ops import fq, pallas_step, vm  # noqa: E402


def _rand_loose(rng, shape, max_bits=401):
    vals = np.zeros(shape + (fq.NUM_LIMBS,), dtype=np.uint64)
    flat = vals.reshape(-1, fq.NUM_LIMBS)
    for i in range(flat.shape[0]):
        flat[i] = fq._int_to_limbs_np(rng.randrange(1 << max_bits))
    return vals


def test_split_join_roundtrip():
    import random

    rng = random.Random(7)
    x = _rand_loose(rng, (4, 3))
    back = np.asarray(pallas_step.join14(pallas_step.split14(x)))
    assert np.array_equal(back, x)


def test_fused_step_matches_u64_step():
    """One synthetic VM step — random operands on both units, mixed
    add/sub lanes — through the fused kernel vs the u64 scan body."""
    import random

    rng = random.Random(13)
    batch, w_mul, w_lin, n_regs = 3, 8, 16, 64

    regs = _rand_loose(rng, (batch, n_regs))
    # sub lanes need b <= MP (the borrowless shift bound): use sub-2^382
    # values on the b side, the compress-output bound every real program
    # maintains (vm.Prog.sub compresses b first)
    msa = np.array([rng.randrange(n_regs) for _ in range(w_mul)], np.int32)
    msb = np.array([rng.randrange(n_regs) for _ in range(w_mul)], np.int32)
    lsa = np.array([rng.randrange(n_regs) for _ in range(w_lin)], np.int32)
    lsb = np.array([rng.randrange(n_regs) for _ in range(w_lin)], np.int32)
    lsub = np.array([rng.random() < 0.5 for _ in range(w_lin)])
    for r in set(lsb[lsub].tolist()):
        regs[:, r] = _rand_loose(rng, (batch,), max_bits=381)
    dests = rng.sample(range(n_regs), w_mul + w_lin)
    msd = np.array(dests[:w_mul], np.int32)
    lsd = np.array(dests[w_mul:], np.int32)
    instr = (msa, msb, msd, lsa, lsb, lsub, lsd)

    want, _ = vm._vm_step(jnp.asarray(regs), tuple(jnp.asarray(x) for x in instr))

    regs14 = pallas_step.split14(jnp.asarray(regs))
    got14, _ = vm._vm_step14(regs14, tuple(jnp.asarray(x) for x in instr))
    got = pallas_step.join14(got14)

    assert np.array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_full_program_matches_u64_path(monkeypatch):
    """A real assembled pairing program end-to-end: vm.execute in fused
    mode must return bit-identical outputs to the default path."""
    from __graft_entry__ import _example_program_and_inputs

    prog, regs, _ = _example_program_and_inputs(batch=2)
    # recover the named inputs from the loaded register file
    ins = {
        name: np.asarray(regs[..., int(r), :])
        for name, r in zip(prog.input_names, prog.input_regs)
    }

    want = vm.execute(prog, ins, batch_shape=(2,))
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_PALLAS", "step")
    got = vm.execute(prog, ins, batch_shape=(2,))

    assert want.keys() == got.keys()
    for name in want:
        assert np.array_equal(got[name], want[name]), name


@pytest.mark.slow  # ~20 s/mode of 8-device mesh compiles (and the jax<0.5
# shard_map fallback only recently made these runnable at all — they were
# collection-time AttributeErrors before; the --run-slow lane keeps them)
@pytest.mark.parametrize("mode", ["1", "step"])
def test_pallas_modes_under_mesh(monkeypatch, mode):
    """Pallas dispatch under an 8-device mesh: a pallas_call is opaque to
    GSPMD, so these modes route through shard_map — every device traces
    its own per-shard kernel on its batch slice. Outputs must be
    bit-identical to the unsharded u64 path."""
    import jax
    from jax.sharding import Mesh

    from __graft_entry__ import _example_program_and_inputs

    prog, regs, _ = _example_program_and_inputs(batch=8)
    ins = {
        name: np.asarray(regs[..., int(r), :])
        for name, r in zip(prog.input_names, prog.input_regs)
    }
    want = vm.execute(prog, ins, batch_shape=(8,))

    mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("batch",))
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_PALLAS", mode)
    got = vm.execute(prog, ins, batch_shape=(8,), mesh=mesh)

    assert want.keys() == got.keys()
    for name in want:
        assert np.array_equal(got[name], want[name]), name
