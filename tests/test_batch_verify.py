"""Collect-then-batch-verify plane vs the reference's sequential model
(consensus_specs_tpu/batch_verify.py; hot loop reference
specs/phase0/beacon-chain.md:1742-1756)."""
import numpy as np
import pytest

from consensus_specs_tpu.batch_verify import SignatureCollector, replay_blocks_batched
from consensus_specs_tpu.utils import bls


def _mk_check(col, k, msg, corrupt=False):
    sks = list(range(1, k + 1))
    pks = [bls.SkToPk(sk) for sk in sks]
    sig = bls.Aggregate([bls.Sign(sk, msg) for sk in sks])
    if corrupt:
        msg = b"X" + msg[1:]
    col._fast_aggregate_verify(pks, msg, sig)


def test_collector_records_and_answers_true():
    with SignatureCollector() as col:
        assert bls.FastAggregateVerify([b"\x01" * 48], b"\x02" * 32, b"\x03" * 96)
        assert not bls.FastAggregateVerify([], b"\x02" * 32, b"\x03" * 96)  # empty: eager False
        assert not bls.AggregateVerify([b"\x01" * 48], [], b"\x03" * 96)  # mismatch: eager False
    # interception removed on exit
    assert bls.FastAggregateVerify.__name__ != "_fast_aggregate_verify"
    assert len(col.checks) == 1


def test_flush_matches_oracle_small():
    col = SignatureCollector()
    _mk_check(col, 2, b"m1" + b"\x00" * 30)
    _mk_check(col, 3, b"m2" + b"\x00" * 30)
    _mk_check(col, 2, b"m3" + b"\x00" * 30, corrupt=True)  # must fail
    got = col.flush()
    want = col.flush_oracle()
    assert np.array_equal(got, want)
    assert list(want) == [True, True, False]


def test_flush_dedups_identical_checks():
    """The same attestation included in multiple blocks is ONE backend
    verification, fanned out to every occurrence — equivalent to the
    per-occurrence oracle."""
    from consensus_specs_tpu.ops import bls_backend

    col = SignatureCollector()
    _mk_check(col, 2, b"d1" + b"\x00" * 30)
    _mk_check(col, 2, b"d1" + b"\x00" * 30)  # identical record
    _mk_check(col, 2, b"d2" + b"\x00" * 30, corrupt=True)
    _mk_check(col, 2, b"d2" + b"\x00" * 30, corrupt=True)  # identical again
    bls_backend.reset_call_counts()
    got = col.flush()
    assert bls_backend.CALL_COUNTS["items"] == 2  # 4 records, 2 uniques
    want = col.flush_oracle()
    assert np.array_equal(got, want)
    assert list(want) == [True, True, False, False]


@pytest.mark.slow
def test_epoch_replay_batched_matches_sequential():
    """Replay two slots of real blocks-with-attestations twice: once with
    per-call oracle verification (the reference model), once collected +
    batch-verified; post-states and check results must agree."""
    from consensus_specs_tpu.test.context import build_spec_module
    from consensus_specs_tpu.test.helpers.genesis import create_genesis_state
    from consensus_specs_tpu.test.helpers.state import next_epoch
    from consensus_specs_tpu.test.helpers.attestations import (
        next_slots_with_attestations,
    )

    spec = build_spec_module("phase0", "minimal")
    bls.bls_active = True
    try:
        state = create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * 64, spec.MAX_EFFECTIVE_BALANCE
        )
        next_epoch(spec, state)
        base = state.copy()
        # build two slots of blocks carrying real signed attestations
        _, signed_blocks, post_sequential = next_slots_with_attestations(
            spec, state, 2, True, False
        )

        # batched replay from the same base
        replay_state = base.copy()
        ok = replay_blocks_batched(spec, replay_state, signed_blocks)
        assert ok.all()
        # block sigs + one attestation per block from slot 2 onward
        assert len(ok) >= len(signed_blocks)
        assert spec.hash_tree_root(replay_state) == spec.hash_tree_root(post_sequential)
    finally:
        bls.bls_active = True


@pytest.mark.slow
def test_epoch_replay_detects_corruption():
    from consensus_specs_tpu.test.context import build_spec_module
    from consensus_specs_tpu.test.helpers.genesis import create_genesis_state
    from consensus_specs_tpu.test.helpers.state import next_epoch
    from consensus_specs_tpu.test.helpers.attestations import (
        next_slots_with_attestations,
    )

    spec = build_spec_module("phase0", "minimal")
    bls.bls_active = True
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 64, spec.MAX_EFFECTIVE_BALANCE
    )
    next_epoch(spec, state)
    base = state.copy()
    _, signed_blocks, _ = next_slots_with_attestations(spec, state, 2, True, False)

    # corrupt one attestation signature in the last block; the signature is
    # not part of the state, but the block root changes, so recompute the
    # block's state root (with stub BLS — the corruption must only surface
    # at flush time) and re-sign the block itself
    from consensus_specs_tpu.test.helpers.block import sign_block

    bad = signed_blocks[-1].message.copy()
    assert len(bad.body.attestations) > 0
    bad.body.attestations[0].signature = spec.BLSSignature(b"\xaa" + b"\x00" * 95)

    scratch = base.copy()
    bls.bls_active = False
    for sb in signed_blocks[:-1]:
        spec.state_transition(scratch, sb)
    bad.state_root = spec.compute_new_state_root(scratch, bad)
    bls.bls_active = True
    resigned = sign_block(spec, scratch, bad)

    replay_state = base.copy()
    ok = replay_blocks_batched(
        spec, replay_state, list(signed_blocks[:-1]) + [resigned]
    )
    assert not ok.all()
    # re-resolve the same checks sequentially: identical verdicts
    with SignatureCollector(spec) as col2:
        state2 = base.copy()
        for sb in list(signed_blocks[:-1]) + [resigned]:
            spec.state_transition(state2, sb)
    assert np.array_equal(ok, col2.flush_oracle())


@pytest.mark.slow
def test_fork_choice_attestations_batched():
    """on_attestation feeding with collected checks matches the sequential
    model: same latest_messages, all checks verify."""
    from consensus_specs_tpu.batch_verify import feed_attestations_batched
    from consensus_specs_tpu.test.context import build_spec_module
    from consensus_specs_tpu.test.helpers.attestations import get_valid_attestation
    from consensus_specs_tpu.test.helpers.block import build_empty_block_for_next_slot
    from consensus_specs_tpu.test.helpers.fork_choice import (
        get_genesis_forkchoice_store, slot_time,
    )
    from consensus_specs_tpu.test.helpers.genesis import create_genesis_state
    from consensus_specs_tpu.test.helpers.state import state_transition_and_sign_block

    spec = build_spec_module("phase0", "minimal")
    bls.bls_active = True
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 64, spec.MAX_EFFECTIVE_BALANCE
    )
    store = get_genesis_forkchoice_store(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    spec.on_tick(store, slot_time(spec, store, block.slot + 1))
    spec.on_block(store, signed_block)

    attestations = [
        get_valid_attestation(spec, state, slot=block.slot, index=i, signed=True)
        for i in range(int(spec.get_committee_count_per_slot(
            state, spec.get_current_epoch(state)
        )))
    ]
    ok = feed_attestations_batched(spec, store, attestations)
    assert len(ok) == len(attestations) and ok.all()
    # every attester's LMD vote landed, exactly as sequential feeding would
    voters = set()
    for a in attestations:
        voters |= set(spec.get_attesting_indices(state, a.data, a.aggregation_bits))
    assert set(store.latest_messages) == voters


@pytest.mark.slow
def test_fork_choice_attestations_streamed_matches_batched():
    """feed_attestations_streamed (the serve-plane twin): identical store
    effects and verdicts, duplicate gossip copies verified once."""
    from consensus_specs_tpu.batch_verify import feed_attestations_streamed
    from consensus_specs_tpu.ops import bls_backend
    from consensus_specs_tpu.serve import VerificationService
    from consensus_specs_tpu.test.context import build_spec_module
    from consensus_specs_tpu.test.helpers.attestations import get_valid_attestation
    from consensus_specs_tpu.test.helpers.block import build_empty_block_for_next_slot
    from consensus_specs_tpu.test.helpers.fork_choice import (
        get_genesis_forkchoice_store, slot_time,
    )
    from consensus_specs_tpu.test.helpers.genesis import create_genesis_state
    from consensus_specs_tpu.test.helpers.state import state_transition_and_sign_block

    spec = build_spec_module("phase0", "minimal")
    bls.bls_active = True
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 64, spec.MAX_EFFECTIVE_BALANCE
    )
    store = get_genesis_forkchoice_store(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    signed_block = state_transition_and_sign_block(spec, state, block)
    spec.on_tick(store, slot_time(spec, store, block.slot + 1))
    spec.on_block(store, signed_block)

    attestations = [
        get_valid_attestation(spec, state, slot=block.slot, index=i, signed=True)
        for i in range(int(spec.get_committee_count_per_slot(
            state, spec.get_current_epoch(state)
        )))
    ]
    # gossip duplication: every attestation arrives twice (two peers)
    stream = attestations + attestations
    bls_backend.reset_call_counts()
    svc = VerificationService()
    try:
        ok = feed_attestations_streamed(spec, store, iter(stream), service=svc)
    finally:
        svc.close(timeout=60)
    assert len(ok) == len(stream) and ok.all()
    # each distinct aggregate hit the backend once despite two copies
    assert bls_backend.CALL_COUNTS["items"] == len(attestations)
    voters = set()
    for a in attestations:
        voters |= set(spec.get_attesting_indices(state, a.data, a.aggregation_bits))
    assert set(store.latest_messages) == voters


def test_randao_and_exit_checks_ride_the_deferred_plane():
    """VERDICT r3 weak #6: randao and voluntary-exit bls.Verify calls are
    assert-style and must be COLLECTED (not eagerly verified), while
    process_deposit's conditional Verify stays eager."""
    from consensus_specs_tpu.test.context import build_spec_module
    from consensus_specs_tpu.test.helpers.block import build_empty_block_for_next_slot
    from consensus_specs_tpu.test.helpers.genesis import create_genesis_state
    from consensus_specs_tpu.test.helpers.state import (
        next_slot, state_transition_and_sign_block,
    )
    from consensus_specs_tpu.test.helpers.voluntary_exits import prepare_signed_exits

    spec = build_spec_module("phase0", "minimal")
    bls.bls_active = True
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 64, spec.MAX_EFFECTIVE_BALANCE
    )
    # age the registry so an exit is admissible
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    next_slot(spec, state)

    exits = prepare_signed_exits(spec, state, [60])
    block = build_empty_block_for_next_slot(spec, state)
    block.body.voluntary_exits = exits
    signed = state_transition_and_sign_block(spec, state.copy(), block)

    with SignatureCollector(spec) as col:
        spec.state_transition(state, signed)
    # deferred checks: proposer sig + randao reveal + the exit signature
    assert len(col.checks) == 3
    ok = col.flush()
    assert ok.all()
    # the exit landed optimistically during collection
    assert state.validators[60].exit_epoch != spec.FAR_FUTURE_EPOCH


def test_corrupt_randao_caught_at_flush_not_collection():
    from consensus_specs_tpu.test.context import build_spec_module
    from consensus_specs_tpu.test.helpers.block import build_empty_block_for_next_slot
    from consensus_specs_tpu.test.helpers.genesis import create_genesis_state
    from consensus_specs_tpu.test.helpers.state import state_transition_and_sign_block

    spec = build_spec_module("phase0", "minimal")
    bls.bls_active = True
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 64, spec.MAX_EFFECTIVE_BALANCE
    )
    block = build_empty_block_for_next_slot(spec, state)
    # a VALID-encoding G2 point that is NOT the proposer's reveal: seal and
    # sign the block through a throwaway collector (the eager path would
    # refuse to even build it)
    block.body.randao_reveal = bls.Sign(12345, b"\x13" * 32)
    with SignatureCollector(spec):
        signed = state_transition_and_sign_block(spec, state.copy(), block)

    with SignatureCollector(spec) as col:
        spec.state_transition(state, signed)  # collection never raises
    ok = col.flush()
    assert not ok.all()  # the bogus reveal fails at flush time
    # outside the context the eager oracle is restored
    assert bls.Verify.__name__ != "_verify"


def test_deposit_verify_stays_eager_inside_collector():
    """An invalid deposit proof-of-possession must be decided DURING
    collection (validator skipped, deposit absorbed) — deferring it would
    change the post-state."""
    from consensus_specs_tpu.test.context import build_spec_module
    from consensus_specs_tpu.test.helpers.deposits import prepare_state_and_deposit
    from consensus_specs_tpu.test.helpers.genesis import create_genesis_state

    spec = build_spec_module("phase0", "minimal")
    bls.bls_active = True
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 64, spec.MAX_EFFECTIVE_BALANCE
    )
    n_before = len(state.validators)
    deposit = prepare_state_and_deposit(
        spec, state, n_before, spec.MAX_EFFECTIVE_BALANCE, signed=False
    )  # unsigned PoP: invalid
    index_before = int(state.eth1_deposit_index)
    with SignatureCollector(spec) as col:
        spec.process_deposit(state, deposit)
    # decided eagerly: no deferred check, no validator created, but the
    # deposit itself was absorbed (index advanced past it)
    assert len(col.checks) == 0
    assert len(state.validators) == n_before
    assert int(state.eth1_deposit_index) == index_before + 1
