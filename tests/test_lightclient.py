"""Light-client proof plane (ISSUE 16): artifact construction +
verification, the content-addressed ``ProofService`` front, the simnet
``light_client`` node kind, and the proofs bench section shape.

Tier-1 budget: everything here is crypto-free (VerdictBackend verdicts,
SHA-256-only Merkle checks) except the two tests that pin the REAL
sync-committee signature path — one pairing each through the pure-Python
oracle, no XLA compiles anywhere.
"""
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from types import SimpleNamespace

import pytest

from consensus_specs_tpu.lightclient.proof_tree import (
    FINALIZED_ROOT_GINDEX,
    NEXT_SYNC_COMMITTEE_GINDEX,
    ProofArtifact,
    ProofWorld,
    build_head_proof,
    proof_key,
    verify_artifact,
    verify_head_proof,
)
from consensus_specs_tpu.lightclient.serve_proofs import (
    ProofCache,
    ProofMetrics,
    ProofService,
)


@pytest.fixture(scope="module")
def spec():
    from consensus_specs_tpu.builder import build_spec_module

    return build_spec_module("altair", "minimal")


@pytest.fixture(scope="module")
def world(spec):
    return ProofWorld(spec)


# -- content addressing ------------------------------------------------------


def test_proof_key_content_addressing():
    r1, r2 = b"\x01" * 32, b"\x02" * 32
    assert proof_key(5, r1) == proof_key(5, r1)
    assert proof_key(5, r1) != proof_key(6, r1)
    assert proof_key(5, r1) != proof_key(5, r2)
    # length framing: (slot, root) pairs never collide by concatenation
    assert proof_key(1, b"\x00" * 4) != proof_key(1, b"\x00" * 8)
    art = ProofArtifact(slot=9, state_root=r1, finalized_root=r2,
                        finality_branch=[])
    assert art.key == proof_key(9, r1)


# -- the bounded cache -------------------------------------------------------


def test_proof_cache_lru_bounds_and_counters():
    cache = ProofCache(capacity=2)
    arts = {i: ProofArtifact(slot=i, state_root=bytes([i]) * 32,
                             finalized_root=b"", finality_branch=[])
            for i in range(3)}
    keys = {i: arts[i].key for i in range(3)}
    assert cache.get(keys[0]) is None  # miss
    cache.put(keys[0], arts[0])
    cache.put(keys[1], arts[1])
    assert cache.get(keys[0]) is arts[0]  # hit; 0 now most-recent
    cache.put(keys[2], arts[2])           # evicts 1, not 0
    assert cache.get(keys[1]) is None
    assert cache.get(keys[0]) is arts[0]
    assert len(cache) == 2
    assert cache.hits == 2 and cache.misses == 2
    assert cache.hit_rate == 0.5


# -- metrics -----------------------------------------------------------------


def test_proof_metrics_hit_rate_counts_joins_and_exports_gauges():
    from consensus_specs_tpu.ops import profiling

    profiling.reset()
    m = ProofMetrics(node=None)
    m.note_build()
    m.note_served()                 # the build
    m.note_served(hit=True)
    m.note_served(joined=True)      # a join is NOT a rebuild: counts hit
    m.note_verdict(True)
    m.note_verdict(False)
    assert m.served == 3 and m.builds == 1
    assert m.hit_rate == pytest.approx(2 / 3)
    m.export_gauges()
    summary = profiling.summary()
    assert summary["lightclient.proofs_served"]["gauge"] == 3
    assert summary["lightclient.proof_builds"]["gauge"] == 1
    assert summary["lightclient.inflight_joins"]["gauge"] == 1
    assert summary["lightclient.updates_verified"]["gauge"] == 1
    assert summary["lightclient.verify_failures"]["gauge"] == 1
    assert summary["lightclient.cache_hit_rate"]["gauge"] == \
        pytest.approx(2 / 3)


# -- the serving front -------------------------------------------------------


def _artifact(slot=7, root=b"\x07" * 32):
    return ProofArtifact(slot=slot, state_root=root, finalized_root=b"",
                         finality_branch=[])


def test_proof_service_builds_once_then_hits():
    svc = ProofService(capacity=8)
    builds = []

    def build():
        builds.append(1)
        return _artifact()

    a1 = svc.serve(7, b"\x07" * 32, build)
    a2 = svc.serve(7, b"\x07" * 32, build)
    assert a1 is a2 and len(builds) == 1
    snap = svc.snapshot()
    assert snap["served"] == 2 and snap["builds"] == 1
    assert snap["cache_hits"] == 1 and snap["hit_rate"] == 0.5
    assert snap["cache_entries"] == 1 and snap["pending"] == 0


def test_proof_service_inflight_dedup_joins_one_build():
    svc = ProofService(capacity=8)
    builds = []
    release = threading.Event()

    def slow_build():
        builds.append(1)
        release.wait(timeout=30)
        return _artifact()

    with ThreadPoolExecutor(max_workers=4) as pool:
        futs = [pool.submit(svc.serve, 7, b"\x07" * 32, slow_build)
                for _ in range(4)]
        # wait until the one owner is inside the build and the three
        # joiners are parked on its future
        deadline = time.time() + 30
        while time.time() < deadline:
            if builds and svc.snapshot()["pending"] == 1:
                break
            time.sleep(0.01)
        release.set()
        got = [f.result(timeout=30) for f in futs]
    assert len(builds) == 1
    assert all(g is got[0] for g in got)
    snap = svc.snapshot()
    assert snap["served"] == 4 and snap["builds"] == 1
    assert snap["inflight_joins"] == 3 and snap["pending"] == 0


def test_proof_service_failed_build_propagates_and_clears():
    svc = ProofService(capacity=8)

    def bad_build():
        raise RuntimeError("no such state")

    with pytest.raises(RuntimeError):
        svc.serve(7, b"\x07" * 32, bad_build)
    assert svc.snapshot()["pending"] == 0
    # the key is not poisoned: a later good build serves
    art = svc.serve(7, b"\x07" * 32, _artifact)
    assert art.slot == 7


def _verdict_artifact(signature):
    """An artifact shaped for ProofService._verify — the update only
    needs the signature attribute, so the VerdictBackend path stays
    crypto-free."""
    art = _artifact()
    art.update = SimpleNamespace(sync_committee_signature=signature)
    art.signing_root = b"\x0a" * 32
    art.participant_pubkeys = [b"\xc0" + b"\x00" * 47]
    return art


def test_proof_service_verdict_routes_through_verification_service():
    from consensus_specs_tpu.serve.load import BAD_SIGNATURE, VerdictBackend
    from consensus_specs_tpu.serve.service import VerificationService

    backend = VerdictBackend()
    verifier = VerificationService(backend, max_batch=8, max_wait_ms=1.0)
    try:
        svc = ProofService(verifier=verifier)
        good = svc.serve(1, b"\x01" * 32,
                         lambda: _verdict_artifact(b"\x05" * 96))
        assert good.verified is True
        bad = svc.serve(2, b"\x02" * 32,
                        lambda: _verdict_artifact(BAD_SIGNATURE))
        assert bad.verified is False
        snap = svc.snapshot()
        assert snap["updates_verified"] == 1
        assert snap["verify_failures"] == 1
        assert backend.calls >= 1  # the verdicts actually flowed through
    finally:
        verifier.close(timeout=30)


def test_proof_service_without_verifier_leaves_verdict_unset():
    svc = ProofService()
    art = svc.serve(3, b"\x03" * 32, lambda: _verdict_artifact(b"\x05" * 96))
    assert art.verified is None


# -- the artifact itself (real sync-committee crypto) ------------------------


def test_world_artifact_verifies_end_to_end(spec, world):
    """The one full-stack check: validate_light_client_update (branches,
    period math, REAL FastAggregateVerify over the sum-sk signature) plus
    the external-root branch checks — against an independently
    re-Merkleized root from a fresh deserialization."""
    slot = world.finalized_slot + 3
    artifact = world.build_artifact(slot)
    assert artifact.finality_gindex == FINALIZED_ROOT_GINDEX
    assert artifact.sync_gindex == NEXT_SYNC_COMMITTEE_GINDEX
    assert len(artifact.participant_pubkeys) == \
        int(spec.SYNC_COMMITTEE_SIZE)
    state = world.head_state(slot)
    fresh = spec.BeaconState.decode_bytes(state.encode_bytes())
    verify_artifact(spec, artifact, world.snapshot,
                    world.genesis_validators_root,
                    state_root=bytes(fresh.hash_tree_root()))


def test_tampered_artifact_fails_verification(spec, world):
    slot = world.finalized_slot + 4
    # a flipped finality-branch byte: the spec validate rejects it
    artifact = world.build_artifact(slot)
    artifact.finality_branch[0] = bytes(
        [artifact.finality_branch[0][0] ^ 1]) + artifact.finality_branch[0][1:]
    artifact.update.finality_branch = [
        spec.Bytes32(b) for b in artifact.finality_branch]
    with pytest.raises(AssertionError):
        verify_artifact(spec, artifact, world.snapshot,
                        world.genesis_validators_root)
    # a corrupted signature: branches fine, FastAggregateVerify False
    artifact = world.build_artifact(slot)
    sig = bytes(artifact.update.sync_committee_signature)
    artifact.update.sync_committee_signature = spec.BLSSignature(
        sig[:-1] + bytes([sig[-1] ^ 1]))
    with pytest.raises(AssertionError):
        verify_artifact(spec, artifact, world.snapshot,
                        world.genesis_validators_root)


def test_unsigned_artifact_branches_still_verify(spec, world):
    """signed=False: the branch/multiproof layer is independent of the
    signature layer (and crypto-free)."""
    from consensus_specs_tpu.lightclient.proof_tree import (
        floorlog2, subtree_index,
    )
    from consensus_specs_tpu.utils.ssz.proofs import verify_merkle_multiproof

    slot = world.finalized_slot + 5
    artifact = world.build_artifact(slot, signed=False)
    assert artifact.participant_pubkeys == []
    g = artifact.finality_gindex
    assert spec.is_valid_merkle_branch(
        spec.Root(artifact.finalized_root),
        [spec.Bytes32(b) for b in artifact.finality_branch],
        floorlog2(g), subtree_index(g),
        spec.Root(artifact.state_root))
    assert verify_merkle_multiproof(
        artifact.multi_leaves, artifact.multi_proof,
        artifact.multi_gindices, artifact.state_root)


# -- the phase0/simnet head-proof shape --------------------------------------


def test_head_proof_round_trip_and_tamper(spec, world):
    state = world.head_state(world.finalized_slot + 6)
    root = bytes(state.hash_tree_root())
    artifact = build_head_proof(spec, state)
    assert artifact.update is None  # phase0 shape: branch only
    verify_head_proof(spec, artifact, root)
    with pytest.raises(AssertionError):
        verify_head_proof(spec, artifact, b"\x99" * 32)
    artifact.finalized_root = b"\x99" * 32
    with pytest.raises(AssertionError):
        verify_head_proof(spec, artifact, root)


# -- the simnet light_client node kind ---------------------------------------


class _StubServer:
    """serve_head_proof()-shaped server for LightClientNode unit tests."""

    def __init__(self, name, response):
        self.name = name
        self.response = response

    def serve_head_proof(self):
        return dict(self.response)


def _head_response(spec, world, slot, node="n0"):
    state = world.head_state(slot)
    block = spec.BeaconBlock(slot=spec.Slot(slot))
    return {
        "state": state,
        "node": node,
        "head_root": bytes(spec.hash_tree_root(block)),
        "head_slot": slot,
        "block": block,
        "artifact": build_head_proof(spec, state),
    }


def test_light_client_node_accepts_rejects_and_staleness(spec, world):
    from consensus_specs_tpu.sim.node import LightClientNode

    fresh = _head_response(spec, world, world.finalized_slot + 8)
    client = LightClientNode(0, spec, fresh["state"])

    assert client.fetch(_StubServer("n0", fresh))
    assert client.verified == 1 and client.head_slot == \
        world.finalized_slot + 8

    # a server whose proof commits to a DIFFERENT state root: rejected
    other_state = world.head_state(world.finalized_slot + 9)
    lying = dict(_head_response(spec, world, world.finalized_slot + 9))
    lying["artifact"] = build_head_proof(spec, other_state)
    assert not client.fetch(_StubServer("n1", lying))
    assert client.failures == 1

    # a served head root that does not re-hash to the served block
    forged = dict(fresh)
    forged["head_root"] = b"\x55" * 32
    assert not client.fetch(_StubServer("n2", forged))
    assert client.failures == 2

    # a lagging node's stale (older-slot) proof: rejected, NOT a failure
    stale = dict(fresh)
    stale["head_slot"] = client.head_slot - 1
    stale["block"] = spec.BeaconBlock(slot=spec.Slot(client.head_slot - 1))
    stale["head_root"] = bytes(spec.hash_tree_root(stale["block"]))
    stale["artifact"] = fresh["artifact"]
    assert not client.fetch(_StubServer("n3", stale))
    assert client.rejected_stale == 1 and client.failures == 2
    assert client.head_slot == world.finalized_slot + 8  # unchanged

    snap = client.snapshot()
    assert snap["fetches"] == 4 and snap["verified"] == 1
    # the rejects landed in the client's own flight journal
    kinds = [e["kind"] for e in client.recorder.events()]
    assert kinds.count("proof_accept") == 1
    assert kinds.count("proof_reject") == 2
    assert kinds.count("proof_stale") == 1


def test_scenario_report_carries_light_client_evidence():
    """One strict scenario run with the default 2 light clients: the
    report's proof plane fields are populated and every client converged
    to the agreed head (the gate would have raised otherwise)."""
    from consensus_specs_tpu.sim import build_world, get_scenario, \
        run_scenario

    spec, anchor_state, anchor_block = build_world()
    report = run_scenario(
        get_scenario("partition_heal"), spec=spec,
        anchor_state=anchor_state, anchor_block=anchor_block, seed=7,
        strict=True)
    assert report.converged
    assert report.light_clients == 2
    assert set(report.per_client) == {"c0", "c1"}
    assert report.proofs_served >= report.light_clients
    assert report.proofs_verified > 0 and report.proof_failures == 0
    assert 0.0 <= report.proof_cache_hit_rate <= 1.0
    heads = {c["head"] for c in report.per_client.values()}
    assert len(heads) == 1  # both clients at the one agreed head
    for snap in report.per_client.values():
        assert snap["verified"] > 0 and snap["failures"] == 0
    # the dict form ships per_client for the matrix report
    assert report.to_dict()["per_client"] == report.per_client


# -- the bench section shape -------------------------------------------------


def test_proofs_bench_emits_gated_section(monkeypatch, world):
    """A tiny verdict-backend replay: the JSON line must carry the
    `proofs` section bench_compare state-gates, with verified True, the
    (N - R)/N steady-state hit rate, and a p99 from the proof_serve
    stage. The warm phase still runs the full spec verification (one
    real pairing per slot)."""
    from consensus_specs_tpu.bench.proofs import run_proofs_bench

    monkeypatch.setenv("CONSENSUS_SPECS_TPU_PROOF_CLIENTS", "64")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_PROOF_SLOTS", "2")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_PROOF_WORKERS", "2")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_PROOF_BACKEND", "verdict")
    result = run_proofs_bench()
    assert result["mode"] == "proofs" and result["platform"] == "cpu"
    assert result["verified"] is True
    assert result["checked_requests"] == 64
    row = result["proofs"]["clients=64"]
    assert row["verified"] is True
    # serves = 64 client fetches + one warm request per slot; only the
    # 2 slot-first builds miss
    assert row["hit_rate"] == pytest.approx((66 - 2) / 66)
    assert row["proofs_per_sec"] > 0 and row["p99_ms"] >= 0
    assert result["per_mode_best"] == {
        "proofs[clients=64]": row["proofs_per_sec"]}
    assert result["service"]["builds"] == 2
