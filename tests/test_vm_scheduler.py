"""The bucketed/incremental assembler (ISSUE 10 tentpole layer 3) vs the
legacy list scheduler: schedule equivalence and throughput.

The new `Prog.assemble` (union-find next-free-step buckets + vectorized
liveness/emission, optionally the native csrc/vm_sched.c kernel) must
produce BIT-IDENTICAL programs to `Prog.assemble_legacy` — not merely
equivalent outputs: identical instruction tensors, register maps, and
schedule metadata for every registry builder. Tensor identity implies
output identity on every input, and the execution tests below close the
loop by actually running old-vs-new schedules on random inputs.

The @slow throughput smoke pins the acceptance bars: >= 4x legacy ops/sec
on the chunk-16 rlc_combine and cold assembly <= 2 s.
"""
import random
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from consensus_specs_tpu.ops import fq, vm, vmlib  # noqa: E402
from consensus_specs_tpu.utils import bls12_381 as O  # noqa: E402

rng = random.Random(1234)

# the production assembly shape (ops/bls_backend W_MUL/W_LIN/pads)
SHAPE = dict(w_mul=96, w_lin=192, pad_steps_to=256, pad_regs_to=64)

# every registry kind at its smallest meaningful shape — the builder set
# the schedule-equivalence gate walks
SMALL_SHAPES = [
    ("miller_product", 1, 1),
    ("aggregate_verify", 2, 1),
    ("rlc_combine", 2, 1),
    ("hard_part", 0, 1),
    ("hard_part_windowed", 0, 1),
    ("hard_part_frobenius", 0, 1),
    ("g1_subgroup", 0, 1),
    ("g2_subgroup", 0, 1),
    ("h2g_finish", 0, 1),
]


def test_small_shapes_cover_every_builder():
    """Drift guard: the equivalence gate must walk EVERY registry kind —
    a builder added to vmlib.BUILDERS without a SMALL_SHAPES row would
    silently skip the scheduler bit-identity and execution sweeps."""
    assert set(vmlib.BUILDERS) == {s[0] for s in SMALL_SHAPES}


def _assert_programs_identical(p1, p2):
    assert p1.n_regs == p2.n_regs
    assert p1.n_steps == p2.n_steps
    for a, b in zip(p1.instr, p2.instr):
        assert np.array_equal(a, b)
    assert np.array_equal(p1.input_regs, p2.input_regs)
    assert np.array_equal(p1.output_regs, p2.output_regs)
    assert p1.input_names == p2.input_names
    assert p1.output_names == p2.output_names
    assert p1.const_regs == p2.const_regs
    assert p1.meta == p2.meta


@pytest.mark.parametrize("kind,k,fold", SMALL_SHAPES,
                         ids=[s[0] for s in SMALL_SHAPES])
def test_bucketed_schedule_identical_to_legacy(kind, k, fold):
    """Tensor identity for every registry builder: the strongest form of
    the schedule-equivalence gate (identical programs execute identically
    on EVERY input, not just the sampled ones)."""
    prog = vmlib.BUILDERS[kind](k, fold)
    p_new = prog.assemble(**SHAPE)
    p_leg = prog.assemble_legacy(**SHAPE)
    _assert_programs_identical(p_new, p_leg)


def test_python_fallback_matches_native(monkeypatch):
    """The pure-Python bucketed path (no csrc/libvmsched.so) produces the
    same program as whatever `assemble` resolves to by default."""
    prog = vmlib.build_g2_subgroup_check(1)
    p_default = prog.assemble(**SHAPE)
    monkeypatch.setattr(vm, "_NATIVE_SCHED", None)
    p_py = prog.assemble(**SHAPE)
    _assert_programs_identical(p_default, p_py)


def test_annotate_writes_schedule_back_onto_ir():
    """vm_analysis reads step/last_use_step/reg off the IR ops; the
    default assemble must annotate, and annotate=False must not be
    required for correctness of the returned Program."""
    prog = vmlib.build_g1_subgroup_check(1)
    p1 = prog.assemble(annotate=False, **SHAPE)
    assert all(op.step == -1 for op in prog.ops[:4])  # untouched defaults
    p2 = prog.assemble(**SHAPE)
    _assert_programs_identical(p1, p2)
    scheduled = [op for op in prog.ops if op.kind in (0, 1, 2)]
    assert scheduled and all(op.step >= 0 for op in scheduled)
    assert all(op.reg >= 0 for op in prog.ops)


def _random_inputs(program):
    return {
        name: fq.to_mont_int(rng.randrange(O.P))
        for name in program.input_names
    }


def _execute_pair(prog, ins, shape):
    """Outputs of the legacy-scheduled vs bucketed-scheduled program on
    identical inputs (shared small execution bucket so the suite pays one
    XLA compile per program shape)."""
    p_new = prog.assemble(**shape)
    p_leg = prog.assemble_legacy(**shape)
    out_new = vm.execute(p_new, ins)
    out_leg = vm.execute(p_leg, ins)
    assert set(out_new) == set(out_leg)
    return out_new, out_leg


def test_executed_outputs_bit_exact_on_random_inputs():
    """The ISSUE's literal gate on a fast shape: execute old-vs-new
    schedules on random inputs and compare outputs bit-exactly. (Tensor
    identity above already implies this for every builder; running it
    end-to-end also covers the execute() plumbing. The full-registry
    execution sweep is the @slow test below.)"""
    prog = vm.Prog()
    names = "abcdef"
    vals = [prog.inp(n) for n in names]
    acc = vals[0]
    for v in vals[1:]:
        acc = (acc * v + v) - vals[0]
        acc = acc * acc
    prog.out(acc, "r")
    small = dict(w_mul=64, w_lin=64, pad_steps_to=256, pad_regs_to=64)
    for _ in range(3):
        ins = {n: fq.to_mont_int(rng.randrange(O.P)) for n in names}
        out_new, out_leg = _execute_pair(prog, ins, small)
        assert np.array_equal(out_new["r"], out_leg["r"])

    # and one real registry builder through the same gate
    g2 = vmlib.build_g2_subgroup_check(1)
    aff = O.ec_to_affine(O.ec_mul(O.G2_GEN, 7))
    ins = {
        "pt.x.0": fq.to_mont_int(aff[0].c0),
        "pt.x.1": fq.to_mont_int(aff[0].c1),
        "pt.y.0": fq.to_mont_int(aff[1].c0),
        "pt.y.1": fq.to_mont_int(aff[1].c1),
    }
    out_new, out_leg = _execute_pair(g2, ins, SHAPE)
    for name in out_new:
        assert np.array_equal(out_new[name], out_leg[name])


@pytest.mark.slow
def test_every_registry_program_executes_bit_exact():
    """Full schedule-equivalence execution sweep: every BUILDERS program,
    old-vs-new schedules, random inputs, bit-exact output limbs."""
    for kind, k, fold in SMALL_SHAPES:
        prog = vmlib.BUILDERS[kind](k, fold)
        pr = prog.assemble(**SHAPE)
        ins = _random_inputs(pr)
        out_new, out_leg = _execute_pair(prog, ins, SHAPE)
        for name in out_new:
            assert np.array_equal(out_new[name], out_leg[name]), (kind, name)


@pytest.mark.slow
def test_assembly_throughput_smoke():
    """Acceptance bars (ISSUE 10): >= 4x legacy ops/sec on the chunk-16
    rlc_combine, cold assembly <= 2 s, and the headline >= 1M ops/sec.
    The 4x bar needs the native kernel (`make native`); the pure-Python
    fallback is held to >= 2.5x and the same absolute bounds."""
    prog = vmlib.build_rlc_combine(16, 1)
    n = len(prog.ops)
    t_new = min(
        _timed(lambda: prog.assemble(annotate=False, **SHAPE))
        for _ in range(2)
    )
    t_leg = _timed(lambda: prog.assemble_legacy(**SHAPE))
    speedup = t_leg / t_new
    assert t_new <= 2.0, f"cold assembly {t_new:.2f}s > 2s"
    assert n / t_new >= 1_000_000, f"{n / t_new:.0f} ops/s < 1M"
    bar = 4.0 if vm._NATIVE_SCHED is not None else 2.5
    assert speedup >= bar, (
        f"assembler speedup {speedup:.2f}x < {bar}x "
        f"(native={vm._NATIVE_SCHED is not None})")


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
