"""Cross-chip G1 aggregation-tree reduction (SURVEY §2.7/P2) vs the host
oracle, on the 8-device virtual CPU mesh."""
import numpy as np
import pytest

from consensus_specs_tpu.utils.jax_env import force_cpu

force_cpu(8)

import jax  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from consensus_specs_tpu.ops import fq, mesh_reduce  # noqa: E402
from consensus_specs_tpu.utils import bls  # noqa: E402
from consensus_specs_tpu.utils import bls12_381 as O  # noqa: E402


@pytest.fixture(scope="module")
def mesh():
    devices = np.array(jax.devices("cpu")[:8])
    return Mesh(devices, ("dev",))


def _proj_from_int_point(pt):
    out = np.zeros((3, fq.NUM_LIMBS), dtype=np.uint64)
    if pt is None:
        out[1] = fq.to_mont_int(1)
        return out
    x, y = pt
    out[0] = fq.to_mont_int(x.n)
    out[1] = fq.to_mont_int(y.n)
    out[2] = fq.to_mont_int(1)
    return out


def _affine_from_proj(agg):
    x, y, z = (fq.from_mont_limbs(agg[i]) for i in range(3))
    if z == 0:
        return None
    zi = pow(z, -1, O.P)
    return (x * zi % O.P, y * zi % O.P)


def test_complete_add_matches_oracle_cases():
    g = O.ec_to_affine(O.G1_GEN)
    two_g = O.ec_to_affine(O.ec_double(O.G1_GEN))
    cases = [
        (g, g),          # doubling through the complete formula
        (g, two_g),      # generic add
        (None, g),       # infinity + P
        (g, None),       # P + infinity
        (None, None),    # infinity + infinity
        (g, (g[0], O.Fq((-g[1].n) % O.P))),  # P + (-P) -> infinity
    ]
    for a, b in cases:
        pa = _proj_from_int_point(a)[None]
        pb = _proj_from_int_point(b)[None]
        got = _affine_from_proj(np.asarray(mesh_reduce.g1_complete_add(pa, pb))[0])
        ea = O.ec_from_affine(a) if a else None
        eb = O.ec_from_affine(b) if b else None
        want_pt = O.ec_add(ea, eb)
        want_aff = O.ec_to_affine(want_pt)
        want = None if want_aff is None else (want_aff[0].n, want_aff[1].n)
        assert got == want, (a, b)


@pytest.mark.slow  # ~44 s of sharded compiles (ISSUE 11 tier-1 audit)
def test_mesh_aggregate_matches_oracle(mesh):
    # two shapes: sub-device-count (padding exercises infinity lanes) and a
    # multi-chunk fold; each k compiles its own scan length, so keep this
    # list short — the 2048-key mainnet shape runs in dryrun_multichip
    ks = [7, 32]
    for k in ks:
        pts_int = [O.ec_mul(O.G1_GEN, 3 * i + 1) for i in range(k)]
        pts = np.stack(
            [_proj_from_int_point(O.ec_to_affine(p)) for p in pts_int]
        )
        agg = mesh_reduce.mesh_aggregate_g1(pts, mesh)
        got = _affine_from_proj(agg)
        want_pt = None
        for p in pts_int:
            want_pt = O.ec_add(want_pt, p)
        want_aff = O.ec_to_affine(want_pt)
        assert got == (want_aff[0].n, want_aff[1].n), k


@pytest.mark.slow  # ~23 s: 64-key device aggregation (ISSUE 11 audit)
def test_aggregate_pubkeys_device_path_vs_oracle(mesh):
    privkeys = list(range(1, 65))
    pubkeys = [bls.SkToPk(sk) for sk in privkeys]
    got = mesh_reduce.aggregate_pubkeys(pubkeys, mesh)
    want = bls.AggregatePKs(pubkeys)
    assert bytes(got) == bytes(want)
