"""Native batched SHA-256 (csrc/sha256_batch.c via utils/native_sha256)."""
import hashlib
import time
from random import Random

from consensus_specs_tpu.utils import native_sha256


def test_native_matches_hashlib():
    if not native_sha256.available():
        import pytest

        pytest.skip("no compiler available to build the native kernel")
    rng = Random(66)
    for n in (1, 2, 7, 64, 1000):
        data = bytes(rng.getrandbits(8) for _ in range(64 * n))
        got = native_sha256.hash_pairs(data)
        want = b"".join(
            hashlib.sha256(data[64 * i: 64 * (i + 1)]).digest() for i in range(n)
        )
        assert got == want


def test_merkleize_uses_native_consistently():
    # hash_tree_root must be identical whichever path runs
    from consensus_specs_tpu.merkle import levels
    from consensus_specs_tpu.utils.ssz import ssz_typing as tz

    chunks = [bytes([i]) * 32 for i in range(33)]
    with levels.forced_mode("native"):
        root = tz.merkleize_chunks(chunks, limit=64)
    # force the pure path and compare
    with levels.forced_mode("python"):
        assert tz.merkleize_chunks(chunks, limit=64) == root


def test_layer_batching_throughput_sanity():
    if not native_sha256.available():
        import pytest

        pytest.skip("no compiler available to build the native kernel")
    data = b"\xab" * (64 * 4096)
    t0 = time.perf_counter()
    native_sha256.hash_pairs(data)
    native_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(4096):
        hashlib.sha256(data[64 * i: 64 * (i + 1)]).digest()
    hashlib_dt = time.perf_counter() - t0
    # the native layer call must at least be in the same league; typically
    # it wins on per-call overhead (this is a sanity check, not a benchmark)
    assert native_dt < hashlib_dt * 3


def test_hash_many_matches_hashlib():
    """Variable-length batched hashing (the expand_message_xmd backend):
    length edges around the SHA block/padding boundaries, empty message,
    empty batch — and hashlib-fallback equality when native is absent."""
    rng = Random(67)
    msgs = [b"", b"a", b"x" * 55, b"y" * 56, b"z" * 64, b"w" * 119,
            b"v" * 120, b"u" * 200]
    msgs += [bytes(rng.getrandbits(8) for _ in range(rng.randrange(300)))
             for _ in range(32)]
    got = native_sha256.hash_many(msgs)
    assert got == [hashlib.sha256(m).digest() for m in msgs]
    assert native_sha256.hash_many([]) == []
