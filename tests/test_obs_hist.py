"""Mergeable log-bucketed histograms (obs/hist.py): fixed-bound bucket
determinism, exact merge algebra (commutative + associative, split-feed ==
single-feed), percentile agreement with the exact nearest-rank statistic
the replaced reservoir computed (within one bucket width — the ISSUE 7
acceptance bar), the Prometheus ``_bucket``/``_sum``/``_count``
exposition, and the writers-vs-readers concurrency hammer over live
merge + scrape.
"""
import random
import re
import threading

import pytest

from consensus_specs_tpu.obs import hist, registry
from consensus_specs_tpu.ops import profiling


@pytest.fixture(autouse=True)
def _clean_profiling():
    profiling.reset()
    yield
    profiling.reset()


def _feed(values):
    h = hist.Histogram()
    for v in values:
        h.observe(v)
    return h


def _stream(seed, n, dist="exp"):
    rng = random.Random(seed)
    if dist == "exp":
        return [rng.expovariate(10.0) for _ in range(n)]
    return [rng.uniform(1e-4, 2.0) for _ in range(n)]


# -- bucket map --------------------------------------------------------------


def test_bucket_bounds_are_a_fixed_function_of_index():
    # mergeability rests on this: the same value lands in the same bucket
    # in every process, and bounds derive from the index alone
    for v in (1e-6, 0.001, 0.5, 1.0, 7.25, 100.0):
        i = hist.bucket_index(v)
        assert hist.bucket_lower(i) < v <= hist.bucket_upper(i) or (
            # lower edge exactness: 2^(i/8) itself belongs to bucket i-?
            v == hist.bucket_lower(i))
        assert hist.bucket_upper(i) / max(hist.bucket_lower(i), 1e-300) \
            <= hist.WIDTH_FACTOR + 1e-12 or i == hist.MIN_INDEX


def test_extreme_values_clamp_to_edge_buckets():
    assert hist.bucket_index(1e-300) == hist.MIN_INDEX
    assert hist.bucket_index(1e300) == hist.MAX_INDEX
    assert hist.bucket_index(0.0) == hist.MIN_INDEX - 1  # zero bucket
    assert hist.bucket_index(-1.0) == hist.MIN_INDEX - 1
    h = _feed([0.0, 1e-300, 1e300])
    assert h.count == 3 and len(h.state()["counts"]) == 3


# -- percentile agreement (the reservoir-replacement acceptance bar) ---------


@pytest.mark.parametrize("dist", ["exp", "uniform"])
@pytest.mark.parametrize("q", [50.0, 95.0, 99.0])
def test_percentiles_agree_with_exact_nearest_rank_within_one_bucket(q, dist):
    """On identical input streams the histogram percentile must sit
    within one bucket width (factor 2^(1/8) ≈ 1.0905) of the exact
    nearest-rank percentile — the statistic the Algorithm-R reservoir
    reported at full retention."""
    values = _stream(11, 4000, dist)
    h = _feed(values)
    exact = profiling._percentile(sorted(values), q)
    got = h.percentile(q)
    assert exact > 0
    ratio = got / exact
    assert 1.0 / hist.WIDTH_FACTOR - 1e-9 <= ratio <= hist.WIDTH_FACTOR + 1e-9, (
        f"p{q} {dist}: exact={exact} hist={got} ratio={ratio}"
    )


def test_percentiles_clamp_to_observed_extremes():
    h = _feed([0.25])
    assert h.percentile(50) == 0.25  # single observation is exact
    h2 = _feed([0.1] * 99 + [0.9])
    assert h2.percentile(100) <= 0.9 + 1e-12
    assert h2.percentile(1) >= 0.1 - 1e-12


def test_count_over_reads_error_mass_from_buckets():
    h = _feed([0.01] * 90 + [1.0] * 10)
    assert h.count_over(0.5) == 10
    assert h.count_over(2.0) == 0
    # threshold inside the 0.01 bucket: that bucket's mass stays below
    assert h.count_over(0.01) == 10


# -- merge algebra ------------------------------------------------------------


def test_merge_commutes_and_split_feed_equals_single_feed():
    values = _stream(7, 3000)
    whole = _feed(values)
    a = _feed(values[0::2])
    b = _feed(values[1::2])
    ab, ba = a.merge(b), b.merge(a)
    for merged in (ab, ba):
        st, wt = merged.state(), whole.state()
        assert st["counts"] == wt["counts"]
        assert st["count"] == wt["count"]
        assert st["min"] == wt["min"] and st["max"] == wt["max"]
        assert st["sum"] == pytest.approx(wt["sum"], rel=1e-9)
    assert ab.state()["counts"] == ba.state()["counts"]


def test_merge_is_associative():
    values = _stream(13, 3000)
    a, b, c = (_feed(values[i::3]) for i in range(3))
    left = a.merge(b).merge(c)
    right = a.merge(b.merge(c))
    assert left.state()["counts"] == right.state()["counts"]
    assert left.count == right.count == len(values)
    # and every percentile read off the merged fleet view matches the
    # single-process view exactly (identical bucket contents)
    whole = _feed(values)
    for q in (50, 95, 99):
        assert left.percentile(q) == whole.percentile(q)


def test_merge_leaves_inputs_untouched():
    a, b = _feed([0.1, 0.2]), _feed([0.3])
    merged = a.merge(b)
    assert (a.count, b.count, merged.count) == (2, 1, 3)
    a.observe(0.4)
    assert merged.count == 3  # detached


# -- snapshot wire format (the fleet's cross-process boundary) ----------------


def test_wire_round_trip_is_state_identical():
    """serialize -> deserialize preserves the histogram's exact value
    state (bucket dict, count, float sum bit-for-bit, extremes) — through
    a REAL json encode/decode, since the worker protocol ships ndjson."""
    import json

    from consensus_specs_tpu.obs import snapshot as osnap

    for seed, dist in ((3, "exp"), (4, "uniform")):
        h = _feed(_stream(seed, 2500, dist))
        wire = json.loads(json.dumps(osnap.hist_to_wire(h)))
        back = osnap.hist_from_wire(wire)
        assert back.state() == h.state()


def test_wire_merge_is_bit_identical_to_in_process_merge():
    """The ISSUE 11 acceptance property: serialize -> deserialize ->
    merge must equal the in-process merge of the same histograms — the
    split-feed == single-feed gate EXTENDED across the wire format. Every
    field is compared exactly (== on floats: the merge folds sums in the
    same order either way, and json round-trips float repr losslessly)."""
    import json

    from consensus_specs_tpu.obs import snapshot as osnap

    values = _stream(17, 4000)
    parts = [_feed(values[i::3]) for i in range(3)]
    in_process = parts[0].merge(parts[1]).merge(parts[2])
    wires = [json.loads(json.dumps(osnap.hist_to_wire(p))) for p in parts]
    over_wire = osnap.merge_hist_wires(wires)
    assert over_wire.state() == in_process.state()
    # and both equal the single-feed histogram's buckets/counts
    whole = _feed(values)
    assert over_wire.state()["counts"] == whole.state()["counts"]
    assert over_wire.count == whole.count
    for q in (50, 95, 99):
        assert over_wire.percentile(q) == whole.percentile(q)


def test_wire_rejects_malformed_and_wrong_version():
    from consensus_specs_tpu.obs import snapshot as osnap

    with pytest.raises(osnap.WireError):
        osnap.hist_from_wire({"counts": "nope"})
    with pytest.raises(osnap.WireError):
        osnap.check_version({"v": 99})
    with pytest.raises(osnap.WireError):
        osnap.check_version([])


def test_process_snapshot_carries_hists_gauges_and_stats():
    from consensus_specs_tpu.obs import snapshot as osnap

    profiling.record_latency("serve.submit_to_result", 0.25)
    profiling.set_gauge("serve.queue_depth", 3)
    profiling.record("serve.batch_flush", 0.5)
    snap = osnap.check_version(osnap.take_process_snapshot(worker="wX"))
    assert snap["worker"] == "wX" and snap["pid"]
    assert osnap.hist_from_wire(
        snap["hists"]["serve.submit_to_result"]).count == 1
    assert snap["gauges"]["serve.queue_depth"] == 3
    assert snap["stats"]["serve.batch_flush"]["calls"] == 1


# -- Prometheus exposition ----------------------------------------------------

_BUCKET_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)_bucket\{le="(?P<le>[^"]+)"\} '
    r"(?P<cum>\d+)$"
)


def test_prometheus_histogram_lines_render_and_parse():
    """/metrics carries full histogram families: monotone cumulative
    ``_bucket`` series with ascending ``le`` bounds ending at ``+Inf``,
    plus consistent ``_sum``/``_count`` — parsed here line by line."""
    values = _stream(5, 500)
    for v in values:
        profiling.record_latency("serve.submit_to_result", v)
    text = registry.render_prometheus()
    fam = "consensus_specs_tpu_serve_submit_to_result_latency_hist_seconds"
    buckets = []
    the_sum = the_count = None
    for line in text.splitlines():
        if line.startswith("#") or not line.startswith(fam):
            continue
        m = _BUCKET_RE.match(line)
        if m:
            buckets.append((m.group("le"), int(m.group("cum"))))
        elif line.startswith(fam + "_sum "):
            the_sum = float(line.rsplit(" ", 1)[1])
        elif line.startswith(fam + "_count "):
            the_count = int(line.rsplit(" ", 1)[1])
    assert buckets and buckets[-1][0] == "+Inf"
    les = [float(le) for le, _ in buckets[:-1]]
    assert les == sorted(les)  # ascending bounds
    cums = [c for _, c in buckets]
    assert cums == sorted(cums)  # monotone cumulative counts
    assert cums[-1] == the_count == len(values)
    assert the_sum == pytest.approx(sum(values), rel=1e-6)
    # the PR 4 summary surface coexists (same family base, _latency_seconds)
    assert f'{fam.replace("_hist", "")}{{quantile="0.99"}}' in text


# -- concurrency hammer -------------------------------------------------------


def test_concurrent_writers_vs_merge_and_scrape_readers():
    """Writer threads observing into shared histograms (direct + through
    profiling.record_latency) race readers doing merge(), percentile(),
    and full Prometheus scrapes. Assertions: no exceptions in flight,
    exact final counts, and every mid-flight merge was self-consistent."""
    shared = [hist.Histogram() for _ in range(3)]
    n_threads, iters = 4, 500
    errors = []
    done = threading.Event()

    def writer(tid):
        try:
            rng = random.Random(tid)
            for i in range(iters):
                v = rng.expovariate(100.0)
                shared[i % len(shared)].observe(v)
                profiling.record_latency("serve.submit_to_result", v)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            while not done.is_set():
                merged = shared[0].merge(shared[1]).merge(shared[2])
                # self-consistency under concurrent writes: bucket mass
                # equals the merged count at the moment of each snapshot
                assert sum(merged.state()["counts"].values()) == merged.count
                merged.percentile(99)
                registry.render_prometheus()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    done.set()
    r.join(30)
    assert errors == []
    total = sum(h.count for h in shared)
    assert total == n_threads * iters
    fleet = shared[0].merge(shared[1]).merge(shared[2])
    assert fleet.count == total
    assert profiling.latency_summary()["serve.submit_to_result"]["n"] == total
