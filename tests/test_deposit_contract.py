"""Deposit-contract model vs the consensus spec
(consensus_specs_tpu/deposit_contract/model.py twin of
deposit_contract/deposit_contract.sol; reference
specs/phase0/deposit-contract.md + beacon-chain.md:1835-1887)."""
from random import Random

from consensus_specs_tpu.builder import build_spec_module
from consensus_specs_tpu.deposit_contract import DepositContractModel
from consensus_specs_tpu.utils import bls


def _spec():
    return build_spec_module("phase0", "minimal")


def _deposit_datas(spec, n, rng):
    out = []
    for i in range(n):
        sk = i + 1
        out.append(spec.DepositData(
            pubkey=bls.SkToPk(sk),
            withdrawal_credentials=bytes([i]) * 32,
            amount=spec.MAX_EFFECTIVE_BALANCE,
            signature=bytes(rng.getrandbits(8) for _ in range(96)),
        ))
    return out


def test_incremental_root_matches_ssz_list_root():
    """The contract's accumulated root equals hash_tree_root of the spec's
    List[DepositData, 2**32] of leaf roots at every prefix length."""
    spec = _spec()
    rng = Random(31)
    datas = _deposit_datas(spec, 9, rng)
    model = DepositContractModel()
    leaf_list_type = spec.List[spec.DepositData, 2**spec.DEPOSIT_CONTRACT_TREE_DEPTH]
    for i, dd in enumerate(datas):
        model.deposit(spec.hash_tree_root(dd))
        ssz_root = spec.hash_tree_root(leaf_list_type(*datas[: i + 1]))
        assert model.get_deposit_root() == ssz_root
        assert model.get_deposit_count() == (i + 1).to_bytes(8, "little")


def test_proofs_verify_with_is_valid_merkle_branch():
    spec = _spec()
    rng = Random(32)
    datas = _deposit_datas(spec, 7, rng)
    model = DepositContractModel()
    for dd in datas:
        model.deposit(spec.hash_tree_root(dd))
    root = model.get_deposit_root()
    for index, dd in enumerate(datas):
        proof = model.proof_at(index)
        assert len(proof) == spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1
        assert spec.is_valid_merkle_branch(
            leaf=spec.hash_tree_root(dd),
            branch=proof,
            depth=spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1,
            index=index,
            root=root,
        )
    # a proof against a longer tree state must also verify for old leaves
    # only when recomputed for that state
    proof_old = model.proof_at(0, deposit_count=3)
    partial = DepositContractModel()
    for dd in datas[:3]:
        partial.deposit(spec.hash_tree_root(dd))
    assert spec.is_valid_merkle_branch(
        leaf=spec.hash_tree_root(datas[0]),
        branch=proof_old,
        depth=spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1,
        index=0,
        root=partial.get_deposit_root(),
    )


def test_end_to_end_process_deposit():
    """Contract accumulator -> proof -> spec.process_deposit applies it."""
    from consensus_specs_tpu.test.helpers.genesis import create_genesis_state
    from consensus_specs_tpu.test.helpers.keys import privkeys, pubkeys

    spec = _spec()
    bls.bls_active = True
    try:
        state = create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * 8, spec.MAX_EFFECTIVE_BALANCE
        )
        new_index = len(state.validators)
        sk, pk = privkeys[new_index], pubkeys[new_index]
        withdrawal_credentials = (
            spec.BLS_WITHDRAWAL_PREFIX + spec.hash(pk)[1:]
        )
        deposit_message = spec.DepositMessage(
            pubkey=pk,
            withdrawal_credentials=withdrawal_credentials,
            amount=spec.MAX_EFFECTIVE_BALANCE,
        )
        domain = spec.compute_domain(spec.DOMAIN_DEPOSIT)
        signature = bls.Sign(sk, spec.compute_signing_root(deposit_message, domain))
        deposit_data = spec.DepositData(
            pubkey=pk,
            withdrawal_credentials=withdrawal_credentials,
            amount=spec.MAX_EFFECTIVE_BALANCE,
            signature=signature,
        )

        model = DepositContractModel()
        model.deposit(spec.hash_tree_root(deposit_data))

        # the beacon state trusts the contract root via eth1 data
        state.eth1_data = spec.Eth1Data(
            deposit_root=model.get_deposit_root(),
            deposit_count=model.deposit_count,
            block_hash=b"\x22" * 32,
        )
        state.eth1_deposit_index = 0

        deposit = spec.Deposit(proof=model.proof_at(0), data=deposit_data)
        pre_count = len(state.validators)
        spec.process_deposit(state, deposit)
        assert len(state.validators) == pre_count + 1
        assert state.validators[new_index].pubkey == pk
        assert state.balances[new_index] == spec.MAX_EFFECTIVE_BALANCE
    finally:
        bls.bls_active = True
