"""Deposit-contract model vs the consensus spec
(consensus_specs_tpu/deposit_contract/model.py twin of
deposit_contract/deposit_contract.sol; reference
specs/phase0/deposit-contract.md + beacon-chain.md:1835-1887).

The randomized differential suite at the bottom stands in for the
reference's dapptools fuzz + web3 harness
(solidity_deposit_contract/tests/deposit_contract.t.sol,
web3_tester/tests/test_deposit.py): no solc/EVM exists in this image
(see COMPONENTS.md), so the executable twin is driven with random
deposit sequences and checked — every prefix root, every branch proof,
and a battery of corruptions that must FAIL — against the repo's own
SSZ engine, which the main test tree independently validates against
the consensus spec."""
import pytest

from random import Random

from consensus_specs_tpu.builder import build_spec_module
from consensus_specs_tpu.deposit_contract import DepositContractModel
from consensus_specs_tpu.utils import bls


def _spec():
    return build_spec_module("phase0", "minimal")


def _deposit_datas(spec, n, rng):
    out = []
    for i in range(n):
        sk = i + 1
        out.append(spec.DepositData(
            pubkey=bls.SkToPk(sk),
            withdrawal_credentials=bytes([i]) * 32,
            amount=spec.MAX_EFFECTIVE_BALANCE,
            signature=bytes(rng.getrandbits(8) for _ in range(96)),
        ))
    return out


def test_incremental_root_matches_ssz_list_root():
    """The contract's accumulated root equals hash_tree_root of the spec's
    List[DepositData, 2**32] of leaf roots at every prefix length."""
    spec = _spec()
    rng = Random(31)
    datas = _deposit_datas(spec, 9, rng)
    model = DepositContractModel()
    leaf_list_type = spec.List[spec.DepositData, 2**spec.DEPOSIT_CONTRACT_TREE_DEPTH]
    for i, dd in enumerate(datas):
        model.deposit(spec.hash_tree_root(dd))
        ssz_root = spec.hash_tree_root(leaf_list_type(*datas[: i + 1]))
        assert model.get_deposit_root() == ssz_root
        assert model.get_deposit_count() == (i + 1).to_bytes(8, "little")


def test_proofs_verify_with_is_valid_merkle_branch():
    spec = _spec()
    rng = Random(32)
    datas = _deposit_datas(spec, 7, rng)
    model = DepositContractModel()
    for dd in datas:
        model.deposit(spec.hash_tree_root(dd))
    root = model.get_deposit_root()
    for index, dd in enumerate(datas):
        proof = model.proof_at(index)
        assert len(proof) == spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1
        assert spec.is_valid_merkle_branch(
            leaf=spec.hash_tree_root(dd),
            branch=proof,
            depth=spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1,
            index=index,
            root=root,
        )
    # a proof against a longer tree state must also verify for old leaves
    # only when recomputed for that state
    proof_old = model.proof_at(0, deposit_count=3)
    partial = DepositContractModel()
    for dd in datas[:3]:
        partial.deposit(spec.hash_tree_root(dd))
    assert spec.is_valid_merkle_branch(
        leaf=spec.hash_tree_root(datas[0]),
        branch=proof_old,
        depth=spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1,
        index=0,
        root=partial.get_deposit_root(),
    )


def test_end_to_end_process_deposit():
    """Contract accumulator -> proof -> spec.process_deposit applies it."""
    from consensus_specs_tpu.test.helpers.genesis import create_genesis_state
    from consensus_specs_tpu.test.helpers.keys import privkeys, pubkeys

    spec = _spec()
    bls.bls_active = True
    try:
        state = create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * 8, spec.MAX_EFFECTIVE_BALANCE
        )
        new_index = len(state.validators)
        sk, pk = privkeys[new_index], pubkeys[new_index]
        withdrawal_credentials = (
            spec.BLS_WITHDRAWAL_PREFIX + spec.hash(pk)[1:]
        )
        deposit_message = spec.DepositMessage(
            pubkey=pk,
            withdrawal_credentials=withdrawal_credentials,
            amount=spec.MAX_EFFECTIVE_BALANCE,
        )
        domain = spec.compute_domain(spec.DOMAIN_DEPOSIT)
        signature = bls.Sign(sk, spec.compute_signing_root(deposit_message, domain))
        deposit_data = spec.DepositData(
            pubkey=pk,
            withdrawal_credentials=withdrawal_credentials,
            amount=spec.MAX_EFFECTIVE_BALANCE,
            signature=signature,
        )

        model = DepositContractModel()
        model.deposit(spec.hash_tree_root(deposit_data))

        # the beacon state trusts the contract root via eth1 data
        state.eth1_data = spec.Eth1Data(
            deposit_root=model.get_deposit_root(),
            deposit_count=model.deposit_count,
            block_hash=b"\x22" * 32,
        )
        state.eth1_deposit_index = 0

        deposit = spec.Deposit(proof=model.proof_at(0), data=deposit_data)
        pre_count = len(state.validators)
        spec.process_deposit(state, deposit)
        assert len(state.validators) == pre_count + 1
        assert state.validators[new_index].pubkey == pk
        assert state.balances[new_index] == spec.MAX_EFFECTIVE_BALANCE
    finally:
        bls.bls_active = True


# -- randomized differential fuzz (EVM-harness stand-in) ---------------------
#
# The deposit tree over List[DepositData, 2**32] merkleizes HTR(element)
# leaves; List[Bytes32, 2**32] merkleizes its elements as leaf chunks
# directly — the two trees are shape-identical, so random Bytes32 leaves
# drive the same accumulator/proof algebra without paying a BLS signing
# per leaf. test_incremental_root_matches_ssz_list_root above pins the
# DepositData form of the equivalence.


def _random_walk(spec, rng, n):
    """Drive the model with n random leaves, checking root + count against
    the SSZ engine at EVERY prefix, and a random sample of proofs."""
    leaf_list_type = spec.List[spec.Bytes32, 2**spec.DEPOSIT_CONTRACT_TREE_DEPTH]
    model = DepositContractModel()
    leaves = []
    for i in range(n):
        leaf = bytes(rng.getrandbits(8) for _ in range(32))
        leaves.append(leaf)
        model.deposit(leaf)
        assert model.get_deposit_root() == spec.hash_tree_root(
            leaf_list_type(*leaves)
        ), f"prefix {i + 1}: accumulator root diverged from SSZ"
        assert model.get_deposit_count() == (i + 1).to_bytes(8, "little")
    root = model.get_deposit_root()
    depth = spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1
    for index in rng.sample(range(n), min(n, 5)):
        proof = model.proof_at(index)
        assert spec.is_valid_merkle_branch(
            leaf=leaves[index], branch=proof, depth=depth, index=index, root=root
        )
    return model, leaves, root


@pytest.mark.parametrize("seed", range(20))
def test_differential_random_sequences(seed):
    """20 randomized sequences (1..40 deposits): per-prefix root/count
    equivalence + sampled proof verification + corruptions that must fail."""
    spec = _spec()
    rng = Random(0xDE9051 + seed)
    n = rng.randint(1, 40)
    model, leaves, root = _random_walk(spec, rng, n)
    depth = spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1

    index = rng.randrange(n)
    proof = model.proof_at(index)

    def verifies(leaf=leaves[index], branch=proof, idx=index, rt=root):
        return spec.is_valid_merkle_branch(
            leaf=leaf, branch=branch, depth=depth, index=idx, root=rt
        )

    assert verifies()
    # corrupt one random byte of one random proof element
    elem = rng.randrange(len(proof))
    byte = rng.randrange(32)
    bad = list(proof)
    bad[elem] = (
        bad[elem][:byte]
        + bytes([bad[elem][byte] ^ (1 + rng.randrange(255))])
        + bad[elem][byte + 1 :]
    )
    assert not verifies(branch=bad), "tampered proof element verified"
    # wrong leaf under a correct proof
    assert not verifies(leaf=bytes(32 - len(b"x")) + b"x")
    # wrong index (any other position in the tree)
    if n > 1:
        other = (index + 1 + rng.randrange(n - 1)) % n
        assert not verifies(idx=other), "proof verified at the wrong index"
    # proof recomputed for a shorter tree must not verify against the
    # full tree's root (the length mix-in differs even when the branch
    # hashes agree)
    if n > 1:
        short = model.proof_at(0, deposit_count=n - 1)
        assert not spec.is_valid_merkle_branch(
            leaf=leaves[0], branch=short, depth=depth, index=0, root=root
        )


def test_differential_boundary_counts():
    """Power-of-two boundaries are where the carry/branch logic can go
    wrong: check every count around them, with full proof sweeps."""
    spec = _spec()
    rng = Random(0xB0DA51)
    leaf_list_type = spec.List[spec.Bytes32, 2**spec.DEPOSIT_CONTRACT_TREE_DEPTH]
    depth = spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1
    counts = [1, 2, 3, 4, 5, 7, 8, 9, 15, 16, 17, 31, 32, 33]
    leaves = [bytes(rng.getrandbits(8) for _ in range(32)) for _ in range(max(counts))]
    model = DepositContractModel()
    done = 0
    for target in counts:
        while done < target:
            model.deposit(leaves[done])
            done += 1
        root = model.get_deposit_root()
        assert root == spec.hash_tree_root(leaf_list_type(*leaves[:target]))
        for index in range(target):
            assert spec.is_valid_merkle_branch(
                leaf=leaves[index],
                branch=model.proof_at(index),
                depth=depth,
                index=index,
                root=root,
            )


def test_differential_historical_proofs_all_prefixes():
    """proof_at(index, deposit_count=c) must verify for every (index, c)
    pair against the root of the c-leaf tree — the eth1 provider serves
    proofs for deposits long since superseded."""
    spec = _spec()
    rng = Random(0x41157)
    n = 12
    model, leaves, _ = _random_walk(spec, rng, n)
    depth = spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1
    snapshots = []
    partial = DepositContractModel()
    for leaf in leaves:
        partial.deposit(leaf)
        snapshots.append(partial.get_deposit_root())
    for c in range(1, n + 1):
        for index in range(c):
            assert spec.is_valid_merkle_branch(
                leaf=leaves[index],
                branch=model.proof_at(index, deposit_count=c),
                depth=depth,
                index=index,
                root=snapshots[c - 1],
            )
