"""Mesh-sharded verify plane (ISSUE 9).

Covers the whole rung: the CONSENSUS_SPECS_TPU_MESH provider
(utils/jax_env.get_mesh), the _FoldLayout mesh fold-capping / row-padding
rules (previously untested — the ceil(n/devices) clamp and the
pad-rows-to-device-count floor), the cross-replica Fq12 butterfly
reduction (ops/mesh_rlc.py) against the exact-int oracle, end-to-end
verdict identity of ``batch_verify_rlc(items, mesh=...)`` vs the
single-device path over valid/invalid/malformed/infinity inputs
(bisection through a failed SHARDED combine included), and the serve
plane's mesh degradation rung (mesh failure -> single-device RLC with a
``degraded_mesh_to_single`` flight event + the serve.mesh_fallbacks
gauge).

Tier-1 keeps to the 4-device mixed batch (the multi-chunk butterfly
case) plus jax-free layout/serve tests; the wider device counts
(2 and 8, wide batches) ride --run-slow with the other device-deep
suites.
"""
import random
import types

import numpy as np
import pytest

from consensus_specs_tpu.utils.jax_env import force_cpu, get_mesh

force_cpu(8)

import jax  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from consensus_specs_tpu.ops import bls_backend as bb  # noqa: E402
from consensus_specs_tpu.ops import fq, mesh_rlc  # noqa: E402
from consensus_specs_tpu.utils import bls  # noqa: E402
from consensus_specs_tpu.utils import bls12_381 as O  # noqa: E402
from consensus_specs_tpu.utils.bls12_381 import P, R  # noqa: E402


def _mesh(n: int) -> Mesh:
    return Mesh(np.array(jax.devices("cpu")[:n]), ("batch",))


def _committee(tag: int, k: int = 2, good: bool = True):
    sks = [1000 * tag + j + 1 for j in range(k)]
    pks = [bls.SkToPk(sk) for sk in sks]
    msg = (b"msh%03d" % tag) + b"\x00" * 26
    sig = bls.Sign(sum(sks) % R, msg)
    if not good:
        msg = b"\xff" + msg[1:]
    return ("fast_aggregate", pks, msg, sig)


# -- mesh provider (utils/jax_env.get_mesh) ---------------------------------


def test_get_mesh_resolution(monkeypatch):
    env = "CONSENSUS_SPECS_TPU_MESH"
    monkeypatch.delenv(env, raising=False)
    assert get_mesh() is None  # unset == off
    for off in ("off", "0", "1", "", "none"):
        monkeypatch.setenv(env, off)
        assert get_mesh() is None, off
    monkeypatch.setenv(env, "4")
    m = get_mesh()
    assert m is not None and m.shape["batch"] == 4
    assert m.axis_names == ("batch",)
    monkeypatch.setenv(env, "auto")
    assert get_mesh().shape["batch"] == 8  # conftest's 8 virtual devices
    # non-power-of-two clamps to the floor (butterfly + row padding need
    # a power-of-two axis); over-asking clamps to what exists
    monkeypatch.setenv(env, "6")
    assert get_mesh().shape["batch"] == 4
    monkeypatch.setenv(env, "16")
    assert get_mesh().shape["batch"] == 8
    # malformed specs degrade to the single-device path, never raise
    monkeypatch.setenv(env, "garbage")
    assert get_mesh() is None
    monkeypatch.setenv(env, "-3")
    assert get_mesh() is None


def test_maybe_mesh_off_is_cheap_and_none(monkeypatch):
    from consensus_specs_tpu.utils import jax_env

    monkeypatch.delenv("CONSENSUS_SPECS_TPU_MESH", raising=False)
    assert jax_env.maybe_mesh() is None
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_MESH", "2")
    assert jax_env.maybe_mesh().shape["batch"] == 2


# -- _FoldLayout mesh fold-capping / row-padding (satellite 1) --------------


def _fake_mesh(n_dev: int):
    return types.SimpleNamespace(shape={"batch": n_dev})


@pytest.fixture()
def stub_program(monkeypatch):
    """_FoldLayout resolves a real assembled program; the layout rules
    under test are pure integer math, so stub the (expensive) resolution."""
    def fake_program(kind, k=0, fold=None):
        if fold is None:
            fold = bb._fold_for(kind, k)
        return f"prog[{kind},k={k},f={fold}]", fold

    monkeypatch.setattr(bb, "_program", fake_program)


@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
@pytest.mark.parametrize("n_items", [1, 3, 8, 17])
@pytest.mark.parametrize("kind,k", [("hard_part", 0), ("rlc_combine", 2),
                                    ("miller_product", 16)])
def test_fold_layout_mesh_invariants(stub_program, n_dev, n_items, kind, k):
    mesh = _fake_mesh(n_dev) if n_dev > 1 else None
    lay = bb._FoldLayout(kind, k, n_items, mesh)
    # every item fits, and filler never exceeds one row's worth past the
    # device-count floor
    assert lay.nb == lay.rows * lay.fold
    assert lay.nb >= n_items
    # rows pad to the device count (each device gets >= 1 row) and stay a
    # power of two (shard divisibility)
    if mesh is not None:
        assert lay.rows % n_dev == 0
        assert lay.rows >= n_dev
    assert lay.rows & (lay.rows - 1) == 0
    # the mesh fold clamp: folding past ceil(n/devices) would only run a
    # bigger program on filler — fold never exceeds it
    if mesh is not None:
        assert lay.fold <= bb._pow2(max(1, -(-n_items // n_dev)))
    assert lay.fold <= bb._fold_for(kind, k, n_items)
    # item -> (row, prefix) stays within the padded layout
    for i in range(n_items):
        r, ns = lay.split(i)
        assert 0 <= r < lay.rows
        assert ns == ("" if lay.fold == 1 else f"i{i % lay.fold}.")


def test_fold_layout_pinned_cases(stub_program):
    # 17 hard-part items on 8 devices: fold clamps 16 -> 4, rows pad to 8
    lay = bb._FoldLayout("hard_part", 0, 17, _fake_mesh(8))
    assert (lay.fold, lay.rows, lay.nb) == (4, 8, 32)
    # 1 item on 8 devices: a single fold-1 row padded out to the mesh
    lay = bb._FoldLayout("hard_part", 0, 1, _fake_mesh(8))
    assert (lay.fold, lay.rows) == (1, 8)
    # unsharded 17 items keep the full fold-16 table
    lay = bb._FoldLayout("hard_part", 0, 17, None)
    assert (lay.fold, lay.rows) == (16, 2)


def test_rlc_chunk_shards_the_width():
    # unsharded: the lane-saturating chunk
    assert bb._rlc_chunk(16, None) == 16
    assert bb._rlc_chunk(3, None) == 4
    # mesh: chunk shrinks until every device holds >= 1 chunk row
    assert bb._rlc_chunk(16, _fake_mesh(4)) == 4
    assert bb._rlc_chunk(16, _fake_mesh(8)) == 2
    assert bb._rlc_chunk(3, _fake_mesh(8)) == 1
    assert bb._rlc_chunk(64, _fake_mesh(4)) == 16  # capped at chunk max
    assert bb._rlc_chunk(2, _fake_mesh(2)) == 1


# -- cross-replica Fq12 butterfly (ops/mesh_rlc.py) -------------------------


def _rand_f(rng: random.Random) -> O.Fq12:
    return O.Fq12(
        O.Fq6(*[O.Fq2(rng.randrange(P), rng.randrange(P))
                for _ in range(3)]),
        O.Fq6(*[O.Fq2(rng.randrange(P), rng.randrange(P))
                for _ in range(3)]),
    )


@pytest.mark.slow  # ~36 s of butterfly compiles (ISSUE 11 tier-1 audit)
def test_mesh_fq12_product_matches_oracle():
    """Local fold + ppermute butterfly == exact-int oracle product, at
    sub-device-count (identity padding) and multi-row widths."""
    rng = random.Random(17)
    mesh = _mesh(4)
    for n in (1, 3, 8):
        fs_o = [_rand_f(rng) for _ in range(n)]
        fs = np.stack([
            np.stack([fq.to_mont_int(c)
                      for c in bb._oracle_to_flat_ints(f)])
            for f in fs_o
        ])
        got = mesh_rlc.mesh_fq12_product(fs, mesh)
        got_ints = [fq.from_mont_limbs(got[j]) for j in range(12)]
        want = fs_o[0]
        for f in fs_o[1:]:
            want = want * f
        assert got_ints == bb._oracle_to_flat_ints(want), n


def test_mesh_fq12_identity_padding():
    one = mesh_rlc.fq12_identity()
    assert fq.from_mont_limbs(one[0]) == 1
    assert all(fq.from_mont_limbs(one[j]) == 0 for j in range(1, 12))
    # an all-identity batch reduces to the identity
    got = mesh_rlc.mesh_fq12_product(mesh_rlc.fq12_identity((3,)), _mesh(4))
    assert [fq.from_mont_limbs(got[j]) for j in range(12)] == \
        [1] + [0] * 11


# -- end-to-end verdict identity under the mesh -----------------------------


def _mixed_items():
    """Every input class: valid, corrupted message, undecodable signature,
    infinity signature, infinity pubkey (the test_rlc mixed batch)."""
    return [
        _committee(1, k=2, good=True),
        _committee(2, k=1, good=False),
        ("fast_aggregate", [bls.SkToPk(7)], b"m" * 32,
         b"\xa0" + b"\x01" * 95),
        ("fast_aggregate", [bls.SkToPk(8)], b"n" * 32,
         b"\xc0" + b"\x00" * 95),
        ("fast_aggregate", [b"\xc0" + b"\x00" * 47],
         b"p" * 32, bls.Sign(9, b"p" * 32)),
    ]


def test_mesh_verdict_identity_mixed_batch(monkeypatch):
    """batch_verify_rlc over a 4-device mesh: bit-identical to the
    single-device path and the pinned host-oracle pattern, with the
    corrupted item bisecting through the failed SHARDED combine (chunk 1
    per device -> the cross-replica butterfly actually reduces)."""
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_RLC_CHUNK", "2")
    items = _mixed_items()
    single = bb.batch_verify_rlc(items, rng=random.Random(0xA5))
    before = dict(bb.RLC_STATS)
    got = bb.batch_verify_rlc(items, mesh=_mesh(4), rng=random.Random(0xA5))
    d = {k: bb.RLC_STATS[k] - before[k] for k in bb.RLC_STATS}
    assert np.array_equal(got, single)
    assert list(got) == [True, False, False, False, False]
    # same combine/bisection trajectory as the single-device run with the
    # same injected rng: malformed/infinity items never reach the combine
    assert d["items"] == 2
    assert d["bisections"] >= 1  # the failed sharded combine split
    assert d["final_exps"] == 3  # root combine + 2 singleton finalizations


@pytest.mark.slow
@pytest.mark.parametrize("n_dev", [2, 8])
def test_mesh_verdict_identity_wide(n_dev):
    """Wide batches at the remaining device counts {2, 8}: verdicts
    bit-identical to the single-device path with two corrupted items
    localized by bisection through sharded combines."""
    n, bad = 17, {5, 11}
    items = [_committee(100 + i, k=1, good=(i not in bad))
             for i in range(n)]
    single = bb.batch_verify_rlc(items, rng=random.Random(n_dev))
    got = bb.batch_verify_rlc(items, mesh=_mesh(n_dev),
                              rng=random.Random(n_dev))
    assert np.array_equal(got, single)
    want = np.array([i not in bad for i in range(n)])
    assert np.array_equal(got, want)


def test_mesh_filler_rows_never_flip_verdicts():
    """The per-item (non-RLC) path with rows padded to the device count:
    3 items on 4 devices run one filler row, whose generator-point lanes
    must never leak into real verdicts."""
    items = [_committee(200, k=1), _committee(201, k=1, good=False),
             _committee(202, k=1)]
    sharded = bb.batch_fast_aggregate_verify(
        [it[1] for it in items], [it[2] for it in items],
        [it[3] for it in items], mesh=_mesh(4),
    )
    unsharded = bb.batch_fast_aggregate_verify(
        [it[1] for it in items], [it[2] for it in items],
        [it[3] for it in items],
    )
    assert np.array_equal(sharded, unsharded)
    assert list(sharded) == [True, False, True]


# -- serve-plane mesh rung (degradation ladder rung 0) ----------------------


class _MeshBackend:
    """Crypto-free backend recording whether calls arrived sharded; raises
    on the mesh path when ``explode`` — the serve rung's fault injection."""

    def __init__(self, explode: bool):
        self.explode = explode
        self.mesh_calls = 0
        self.plain_calls = 0

    def batch_verify_rlc(self, items, mesh=None):
        if mesh is not None:
            self.mesh_calls += 1
            if self.explode:
                raise RuntimeError("injected mesh failure")
        else:
            self.plain_calls += 1
        return [bytes(sig) != b"\xba" * 96 for (_k, _p, _m, sig) in items]


def _serve_items(n=3):
    out = []
    for i in range(n):
        sig = b"\xba" * 96 if i == n - 1 else bytes([i + 1]) * 96
        out.append(("fast_aggregate", [b"\x01" * 48],
                    b"%02d" % i + b"m" * 30, sig))
    return out


def test_serve_mesh_fallback_rung():
    """A mesh failure costs one fallback (serve.mesh_fallbacks + the
    degraded_mesh_to_single flight event), never the flush: the
    single-device RLC answers every request correctly."""
    import os

    from consensus_specs_tpu.obs import flight
    from consensus_specs_tpu.serve.service import VerificationService

    os.environ["CONSENSUS_SPECS_TPU_FLIGHT"] = "1"
    flight.reset_global()
    try:
        be = _MeshBackend(explode=True)
        svc = VerificationService(backend=be, mesh=_fake_mesh(2),
                                  max_wait_ms=200.0)
        try:
            futures = [svc.submit(*it) for it in _serve_items()]
            got = [f.result(timeout=30) for f in futures]
        finally:
            svc.close(timeout=30)
        assert got == [True, True, False]
        assert be.mesh_calls >= 1 and be.plain_calls >= 1
        snap = svc.metrics.snapshot()
        assert snap["mesh_devices"] == 2
        assert snap["mesh_fallbacks"] == be.mesh_calls
        kinds = [e["kind"] for e in flight.global_recorder().events()]
        assert "degraded_mesh_to_single" in kinds
    finally:
        del os.environ["CONSENSUS_SPECS_TPU_FLIGHT"]
        flight.reset_global()


def test_serve_mesh_success_no_fallback():
    from consensus_specs_tpu.serve.service import VerificationService

    be = _MeshBackend(explode=False)
    svc = VerificationService(backend=be, mesh=_fake_mesh(2),
                              max_wait_ms=200.0)
    try:
        futures = [svc.submit(*it) for it in _serve_items()]
        got = [f.result(timeout=30) for f in futures]
    finally:
        svc.close(timeout=30)
    assert got == [True, True, False]
    assert be.mesh_calls >= 1 and be.plain_calls == 0
    assert svc.metrics.mesh_fallbacks == 0
    assert svc.mesh_devices == 2


def test_serve_narrow_flush_stays_single_device():
    """A flush narrower than the mesh runs the single-device path — the
    rows would pad to the device count and run mostly filler, and the
    single-device executables are already warm. Not a fallback."""
    from consensus_specs_tpu.serve.service import VerificationService

    be = _MeshBackend(explode=True)  # would raise IF the mesh were used
    svc = VerificationService(backend=be, mesh=_fake_mesh(4),
                              max_wait_ms=200.0)
    try:
        futures = [svc.submit(*it) for it in _serve_items(2)]
        got = [f.result(timeout=30) for f in futures]
    finally:
        svc.close(timeout=30)
    assert got == [True, False]
    assert be.mesh_calls == 0 and be.plain_calls >= 1
    assert svc.metrics.mesh_fallbacks == 0
    assert svc.mesh_devices == 4


def test_mesh_sweep_line_parser():
    """The sweep driver takes the LAST parseable JSON line of a serve
    child (children emit progress noise before the final line)."""
    from consensus_specs_tpu.serve.load import _parse_last_json_line

    out = b'warming up...\n{"value": 1}\nnoise\n{"value": 2, "mode": "serve"}\n'
    assert _parse_last_json_line(out) == {"value": 2, "mode": "serve"}
    assert _parse_last_json_line(b"no json here\n") is None
    assert _parse_last_json_line(b"") is None


def test_serve_single_device_mesh_collapses_to_unsharded():
    """A 1-device mesh is the unsharded path — the service must not pay
    sharded dispatch for it."""
    from consensus_specs_tpu.serve.service import VerificationService

    be = _MeshBackend(explode=False)
    svc = VerificationService(backend=be, mesh=_fake_mesh(1),
                              max_wait_ms=5.0)
    try:
        fut = svc.submit(*_serve_items(2)[0])
        assert fut.result(timeout=30) is True
    finally:
        svc.close(timeout=30)
    assert svc.mesh_devices == 0
    assert be.mesh_calls == 0 and be.plain_calls >= 1


# -- fused lowering under the mesh batch axis (ISSUE 13) --------------------


def test_fused_execution_identity_under_mesh(monkeypatch):
    """The fused straight-line backend must ride `vm.execute(mesh=)`
    bit-identically to the interpreter: the chunk graphs are purely
    batch-elementwise, so GSPMD shards the carry over the mesh axes with
    zero collectives — the contract that lets PR 9's sharded Miller
    loops and PR 10's batcher take either backend unchanged."""
    import random

    from consensus_specs_tpu.ops import vm, vm_compile

    rng = random.Random(17)
    prog = vm.Prog()
    a, b, c = (prog.inp(n) for n in "abc")
    k = prog.const(12345)
    acc = (a * b + k) - c
    for _ in range(4):
        acc = acc * acc + (b - a)
    prog.out(acc, "r")
    assembled = prog.assemble(w_mul=64, w_lin=64, pad_steps_to=256,
                              pad_regs_to=64)
    ints = [{n: rng.randrange(O.P) for n in "abc"} for _ in range(4)]
    ins = {
        n: np.stack([fq.to_mont_int(row[n]) for row in ints])
        for n in "abc"
    }
    mesh = _mesh(2)
    vm_compile.reset_fused_state()
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_FUSED_CHUNK", "3")
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "interp")
    out_i = vm.execute(assembled, ins, batch_shape=(4,), mesh=mesh)
    monkeypatch.setenv("CONSENSUS_SPECS_TPU_VM_EXEC", "fused")
    out_f = vm.execute(assembled, ins, batch_shape=(4,), mesh=mesh)
    out_u = vm.execute(assembled, ins, batch_shape=(4,))  # unsharded fused
    assert vm_compile._COUNTERS["fallbacks"] == 0
    for name in out_i:
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_f[name])), name
        assert np.array_equal(np.asarray(out_i[name]),
                              np.asarray(out_u[name])), name
    vm_compile.reset_fused_state()
