"""Incremental-merkleization correctness: after ANY sequence of mutations,
a cached `hash_tree_root` must equal the root of a freshly-constructed
equal value (remerkleable's role — reference utils/ssz/ssz_impl.py:12-13;
SURVEY §7.3 hard part #6).

The adversarial cases are deep mutations through read aliases
(`state.validators[i].slashed = x`, `att.aggregation_bits[j] = True`)
which bypass the owning series' mutators and must be caught by the
mutation-stamp scan."""
import random

from consensus_specs_tpu.utils.ssz.ssz_typing import (
    Bitlist,
    ByteVector,
    Container,
    List,
    Vector,
    _ChunkTree,
    boolean,
    merkleize_chunks,
    uint8,
    uint64,
    uint256,
)

Bytes32 = ByteVector[32]


class Inner(Container):
    a: uint64
    b: Bytes32


class Outer(Container):
    slot: uint64
    inner: Inner
    bits: Bitlist[1024]
    nums: List[uint64, 1 << 40]
    inners: List[Inner, 1 << 30]
    roots: Vector[Bytes32, 16]


def fresh_root(v):
    """Root computed by a brand-new object with no caches."""
    t = type(v)
    if isinstance(v, Container):
        return t(**{n: getattr(v, n) for n in t.fields()}).hash_tree_root()
    if isinstance(v, (List, Vector)):
        return t(list(v)).hash_tree_root()
    if isinstance(v, Bitlist):
        return t(list(v)).hash_tree_root()
    raise TypeError(t)


def test_chunk_tree_matches_merkleize():
    rng = random.Random(1)
    for limit in (1, 2, 3, 8, 33, 1 << 10):
        depth = (max(1, limit) - 1).bit_length() if limit > 1 else 0
        from consensus_specs_tpu.utils.ssz.ssz_typing import _type_depth

        depth = _type_depth(limit)
        for count in {c for c in (0, 1, 2, limit // 2, limit) if c <= limit}:
            chunks = [rng.randbytes(32) for _ in range(count)]
            tree = _ChunkTree(depth, list(chunks))
            assert tree.root() == merkleize_chunks(chunks, limit=limit)
            # point updates keep matching
            for _ in range(min(count, 5)):
                i = rng.randrange(count)
                chunks[i] = rng.randbytes(32)
                tree.set_chunk(i, chunks[i])
                assert tree.root() == merkleize_chunks(chunks, limit=limit)
            # appends (with growth past power-of-two boundaries)
            for _ in range(3):
                if len(chunks) < limit:
                    c = rng.randbytes(32)
                    chunks.append(c)
                    tree.append(c)
                    assert tree.root() == merkleize_chunks(chunks, limit=limit)


def test_basic_list_incremental_mutations():
    rng = random.Random(2)
    nums = List[uint64, 1 << 40]([uint64(i) for i in range(1000)])
    assert nums.hash_tree_root() == fresh_root(nums)
    for _ in range(30):
        op = rng.randrange(3)
        if op == 0:
            nums[rng.randrange(len(nums))] = uint64(rng.randrange(1 << 60))
        elif op == 1:
            nums.append(uint64(rng.randrange(1 << 60)))
        else:
            nums.pop()
        assert nums.hash_tree_root() == fresh_root(nums)


def test_small_basic_types_incremental():
    b = List[boolean, 333]([boolean(i % 2) for i in range(100)])
    assert b.hash_tree_root() == fresh_root(b)
    b[7] = boolean(1)
    b.append(boolean(0))
    assert b.hash_tree_root() == fresh_root(b)
    u = List[uint256, 64]([uint256(i) for i in range(10)])
    assert u.hash_tree_root() == fresh_root(u)
    u[3] = uint256(1 << 200)
    assert u.hash_tree_root() == fresh_root(u)
    w = List[uint8, 100]([uint8(i) for i in range(50)])
    assert w.hash_tree_root() == fresh_root(w)
    w[49] = uint8(255)
    w.append(uint8(9))
    assert w.hash_tree_root() == fresh_root(w)


def test_composite_list_alias_mutation_detected():
    """The critical case: mutate elements through read aliases only."""
    inners = List[Inner, 1 << 30](
        [Inner(a=uint64(i), b=Bytes32(bytes([i % 256]) * 32)) for i in range(300)]
    )
    r0 = inners.hash_tree_root()
    assert r0 == fresh_root(inners)
    # deep alias mutation — the list's own mutators never run
    inners[123].a = uint64(777)
    r1 = inners.hash_tree_root()
    assert r1 != r0
    assert r1 == fresh_root(inners)
    # replacement via setitem
    inners[5] = Inner(a=uint64(5555), b=Bytes32(b"\xaa" * 32))
    assert inners.hash_tree_root() == fresh_root(inners)
    # append + mutate the appended element through its alias
    inners.append(Inner(a=uint64(1), b=Bytes32()))
    inners[-1].a = uint64(2)
    assert inners.hash_tree_root() == fresh_root(inners)


def test_nested_alias_mutation_two_levels_deep():
    """attestations[i].aggregation_bits[j] — mutation two levels below the
    caching series, invisible to both the list and the element container's
    setattr; only the deep-stamp scan can catch it."""

    class Att(Container):
        bits: Bitlist[2048]
        data: Inner

    atts = List[Att, 128](
        [Att(bits=Bitlist[2048]([False] * 64), data=Inner(a=uint64(i))) for i in range(10)]
    )
    r0 = atts.hash_tree_root()
    atts[4].bits[13] = True  # two levels deep
    r1 = atts.hash_tree_root()
    assert r1 != r0
    assert r1 == fresh_root(atts)
    atts[4].data.a = uint64(99)  # container-in-container
    assert atts.hash_tree_root() == fresh_root(atts)


def test_bitlist_incremental():
    rng = random.Random(3)
    bits = Bitlist[1 << 20]([bool(rng.randrange(2)) for _ in range(3000)])
    assert bits.hash_tree_root() == fresh_root(bits)
    for _ in range(20):
        if rng.randrange(2):
            bits[rng.randrange(len(bits))] = bool(rng.randrange(2))
        else:
            bits.append(bool(rng.randrange(2)))
        assert bits.hash_tree_root() == fresh_root(bits)


def test_container_of_everything_stays_consistent():
    rng = random.Random(4)
    o = Outer(
        slot=uint64(1),
        inner=Inner(a=uint64(2), b=Bytes32(b"\x01" * 32)),
        bits=Bitlist[1024]([False] * 300),
        nums=List[uint64, 1 << 40]([uint64(i) for i in range(500)]),
        inners=List[Inner, 1 << 30]([Inner(a=uint64(i)) for i in range(50)]),
        roots=Vector[Bytes32, 16]([Bytes32(bytes([i]) * 32) for i in range(16)]),
    )
    assert o.hash_tree_root() == fresh_root(o)
    for _ in range(25):
        op = rng.randrange(6)
        if op == 0:
            o.slot = uint64(int(o.slot) + 1)
        elif op == 1:
            o.inner.a = uint64(rng.randrange(1 << 30))
        elif op == 2:
            o.bits[rng.randrange(300)] = True
        elif op == 3:
            o.nums[rng.randrange(len(o.nums))] = uint64(rng.randrange(1 << 30))
        elif op == 4:
            o.inners[rng.randrange(len(o.inners))].b = Bytes32(rng.randbytes(32))
        else:
            o.roots[rng.randrange(16)] = Bytes32(rng.randbytes(32))
        assert o.hash_tree_root() == fresh_root(o)


def test_deepcopy_preserves_independence_and_correctness():
    import copy

    inners = List[Inner, 1 << 30]([Inner(a=uint64(i)) for i in range(100)])
    r0 = inners.hash_tree_root()  # warm the cache
    dup = copy.deepcopy(inners)
    assert dup.hash_tree_root() == r0
    # mutate the copy: original unaffected, copy correct
    dup[7].a = uint64(1 << 50)
    assert inners.hash_tree_root() == r0
    assert dup.hash_tree_root() == fresh_root(dup)
    # mutate the original: copy unaffected
    inners[3].a = uint64(42)
    assert inners.hash_tree_root() == fresh_root(inners)
    assert dup.hash_tree_root() == fresh_root(dup)


def test_pop_cannot_resurrect_stale_roots():
    """Regression (round-4 review): a pop with idx >= len(cached roots)
    invalidates the cache and discards pending dirty marks; a later pop
    must NOT rebuild a tree from the stale element roots (immutable
    elements like Bytes32 have no stamp scan to recover them)."""
    L = List[ByteVector[32], 1024]
    lst = L([ByteVector[32](bytes([i]) * 32) for i in range(10)])
    lst.hash_tree_root()
    lst[2] = ByteVector[32](b"\xaa" * 32)  # dirty mark {2}, not yet hashed
    lst.append(ByteVector[32](b"\xbb" * 32))
    lst.pop(10)  # idx >= len(eroots): invalidate path
    lst.pop(5)  # must not splice stale eroots back to life
    assert lst.hash_tree_root() == fresh_root(lst)


def test_proof_descent_does_not_rehash_the_series():
    """build_proof into one element of a warm large composite list must be
    O(log n) hashes, not a full element-root sweep per branch node."""
    from unittest import mock

    import consensus_specs_tpu.utils.ssz.proofs as proofs_mod
    import consensus_specs_tpu.utils.ssz.ssz_typing as st

    class Holder(Container):
        items: List[Inner, 1 << 20]

    h = Holder(items=List[Inner, 1 << 20]([Inner(a=uint64(i)) for i in range(5000)]))
    h.hash_tree_root()  # warm
    calls = {"n": 0}
    real = st.sha256

    def counting(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    with mock.patch.object(st, "sha256", counting):
        proofs_mod.build_proof(h, "items", 1234, "a")
    assert calls["n"] <= 80, f"proof construction hashed {calls['n']} nodes"


def test_incremental_is_sublinear():
    """One mutation in a large list must re-hash O(log n), not O(n): the
    second hash after a point update must do far less work than the first.
    Measured by hash-call counting (robust vs wall-clock noise)."""
    from unittest import mock

    import consensus_specs_tpu.utils.ssz.ssz_typing as st

    nums = List[uint64, 1 << 40]([uint64(i) for i in range(4096)])
    nums.hash_tree_root()
    calls = {"n": 0}
    real = st.sha256

    def counting(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    with mock.patch.object(st, "sha256", counting):
        nums[2000] = uint64(0)
        nums.hash_tree_root()
    # 1024 chunks -> full rebuild would be ~1023 hashes; the incremental
    # path is one route through the present layers (~10) plus the
    # zero-subtree fold up to the type depth (List[uint64, 2^40] -> depth
    # 38) and the length mix-in: O(log limit), independent of n
    assert calls["n"] <= 45, f"point update re-hashed {calls['n']} nodes"
