"""Differential gate for the incremental proto-array fork choice.

The spec ``get_head`` (O(blocks × validators) recompute) is the oracle;
the proto-array (``chain/proto_array.py``) is the production path. A
:class:`Mirror` drives BOTH from one randomized event stream — block
inserts, latest-message batches, justified-checkpoint moves with
balance-set changes, finalization with pruning — and asserts
bit-identical heads after EVERY mutation batch. Tier-1 runs small trees;
``--run-slow`` runs 64+-block trees with >1k vote updates
(``@pytest.mark.slow`` keeps the tier-1 budget flat).
"""
import random

import pytest

from consensus_specs_tpu.builder import build_spec_module
from consensus_specs_tpu.chain.proto_array import ProtoArray, ProtoForkChoice
from consensus_specs_tpu.test import context


@pytest.fixture(scope="module")
def spec():
    return build_spec_module("phase0", "minimal")


@pytest.fixture(scope="module")
def genesis_state(spec):
    return context.get_genesis_state(
        spec, context.default_balances, context.default_activation_threshold
    )


# -- the differential mirror --------------------------------------------------


class Mirror:
    """One event stream, two fork choices: every mutation lands in the
    spec ``Store`` (oracle) and the :class:`ProtoForkChoice` (production),
    and ``check()`` asserts their heads agree."""

    def __init__(self, spec, genesis_state, rng):
        self.spec = spec
        self.rng = rng
        self.anchor_state = genesis_state.copy()
        self.anchor_block = spec.BeaconBlock(
            state_root=self.anchor_state.hash_tree_root())
        self.store = spec.get_forkchoice_store(self.anchor_state,
                                               self.anchor_block)
        self.anchor_root = spec.hash_tree_root(self.anchor_block)
        self.fc = ProtoForkChoice()
        anchor_stored = self.store.block_states[self.anchor_root]
        self.fc.on_block(
            bytes(self.anchor_root), None, 0,
            self._cp(anchor_stored.current_justified_checkpoint),
            self._cp(anchor_stored.finalized_checkpoint),
        )
        self.roots = [self.anchor_root]
        self.refresh()

    @staticmethod
    def _cp(checkpoint):
        return (int(checkpoint.epoch), bytes(checkpoint.root))

    def refresh(self):
        """Re-sync the proto side's balance/viability inputs from the
        store's checkpoints (what HeadService does after every move)."""
        spec, store = self.spec, self.store
        state = store.checkpoint_states[store.justified_checkpoint]
        active = spec.get_active_validator_indices(
            state, spec.get_current_epoch(state))
        balances = {
            int(i): int(state.validators[i].effective_balance) for i in active
        }
        return self.fc.update_checkpoints(
            self._cp(store.justified_checkpoint),
            self._cp(store.finalized_checkpoint), balances)

    def add_block(self, parent_root, slot, justified_cp=None,
                  finalized_cp=None):
        """Insert a crafted block into both sides; the crafted post-state
        carries the leaf checkpoints the spec's viability filter reads."""
        spec = self.spec
        block = spec.BeaconBlock(
            slot=slot,
            parent_root=parent_root,
            state_root=self.rng.getrandbits(256).to_bytes(32, "little"),
        )
        root = spec.hash_tree_root(block)
        state = self.anchor_state.copy()
        if justified_cp is not None:
            state.current_justified_checkpoint = justified_cp
        if finalized_cp is not None:
            state.finalized_checkpoint = finalized_cp
        self.store.blocks[root] = block
        self.store.block_states[root] = state
        self.fc.on_block(bytes(root), bytes(parent_root), int(slot),
                         self._cp(state.current_justified_checkpoint),
                         self._cp(state.finalized_checkpoint))
        self.roots.append(root)
        return root

    def vote(self, validator, root, epoch):
        """The latest-message rule, applied to both tables."""
        spec, store = self.spec, self.store
        existing = store.latest_messages.get(spec.ValidatorIndex(validator))
        if existing is None or epoch > existing.epoch:
            store.latest_messages[spec.ValidatorIndex(validator)] = \
                spec.LatestMessage(epoch=spec.Epoch(epoch),
                                   root=spec.Root(root))
        self.fc.on_latest_message(int(validator), bytes(root), int(epoch))

    def move_justified(self, epoch, root, balance_shuffle=False):
        """A justified-checkpoint move, with an optionally perturbed
        balance set in the new checkpoint state (exercises the proto
        side's per-vote balance re-basing)."""
        spec = self.spec
        cp = spec.Checkpoint(epoch=epoch, root=root)
        state = self.anchor_state.copy()
        if balance_shuffle:
            for i in range(0, len(state.validators), 3):
                state.validators[i].effective_balance = \
                    spec.EFFECTIVE_BALANCE_INCREMENT * (1 + i % 7)
            # a couple of validators drop out of the active set entirely
            state.validators[1].exit_epoch = spec.Epoch(0)
            state.validators[5].exit_epoch = spec.Epoch(0)
        self.store.checkpoint_states[cp] = state
        self.store.justified_checkpoint = cp
        return self.refresh()

    def move_finalized(self, epoch, root):
        self.store.finalized_checkpoint = self.spec.Checkpoint(
            epoch=epoch, root=root)
        return self.refresh()

    def check(self):
        self.fc.apply()
        proto = self.fc.head()
        oracle = bytes(self.spec.get_head(self.store))
        assert proto == oracle, (
            f"head diverged: proto={proto.hex()[:16]} "
            f"oracle={oracle.hex()[:16]} over {len(self.roots)} blocks"
        )
        return proto


def _grow_tree(m: Mirror, rng, blocks, max_slot, spine, agree=0.6):
    """Random fork tree: every new block parents on any earlier-slot
    block, so sibling races and skip-slots appear naturally. A fraction
    of the crafted leaf states carry checkpoints AGREEING with the later
    justified/finalized moves to ``spine`` — so post-move filtering stays
    a weight race over a nontrivial viable subtree, never a collapse."""
    cp1 = m.spec.Checkpoint(epoch=1, root=spine)
    by_slot = {0: [m.anchor_root], 1: [spine]}
    for _ in range(blocks):
        slot = rng.randint(1, max_slot)
        earlier = [s for s in by_slot if s < slot]
        parent = rng.choice(by_slot[rng.choice(earlier)])
        root = m.add_block(
            parent, slot,
            justified_cp=cp1 if rng.random() < agree else None,
            finalized_cp=cp1 if rng.random() < agree else None,
        )
        by_slot.setdefault(slot, []).append(root)


def _run_differential(spec, genesis_state, seed, blocks, vote_events,
                      check_every=1):
    """The randomized gate: grow, vote in batches, move checkpoints,
    finalize + prune — oracle-equal heads after every batch."""
    rng = random.Random(seed)
    m = Mirror(spec, genesis_state, rng)
    n_validators = len(genesis_state.validators)
    # the spine block is the future justified/finalized checkpoint root
    spine = m.add_block(m.anchor_root, 1)
    _grow_tree(m, rng, blocks, max_slot=24, spine=spine)
    m.check()

    batch, applied = [], 0
    checks = 0
    for e in range(vote_events):
        batch.append((rng.randrange(n_validators), rng.choice(m.roots),
                      rng.randint(0, 4)))
        if len(batch) >= 8:
            for v, r, ep in batch:
                m.vote(v, r, ep)
            applied += len(batch)
            batch = []
            checks += 1
            if checks % check_every == 0:
                m.check()
        if e == vote_events // 3:
            # justified moves to the spine at epoch 1, with a changed
            # balance set: weights must re-base exactly, and the agreeing
            # leaf fraction keeps the filtered tree nontrivial
            m.move_justified(1, spine, balance_shuffle=True)
            m.check()
        if e == (2 * vote_events) // 3:
            # finalize the spine: the proto array prunes everything not
            # descending from it; the spec store keeps all blocks — the
            # heads must still agree
            before = m.fc.block_count
            m.move_finalized(1, spine)
            pruned = before - m.fc.block_count
            assert pruned > 0
            m.check()
    for v, r, ep in batch:
        m.vote(v, r, ep)
    m.check()
    assert applied > 0


# -- tier-1: small randomized trees ------------------------------------------


def test_differential_small_trees(spec, genesis_state):
    for seed in (1, 2, 3, 4):
        _run_differential(spec, genesis_state, seed, blocks=20,
                          vote_events=48)


def test_differential_bushy_tie_breaks(spec, genesis_state):
    # zero-weight sibling forests everywhere: the lexicographic tie-break
    # is the only signal, and it must match the spec's max(weight, root)
    rng = random.Random(99)
    m = Mirror(spec, genesis_state, rng)
    for slot in (1, 2, 3):
        for _ in range(4):
            m.add_block(m.anchor_root, slot)
        m.check()
    # one vote flips the whole forest to the voted branch
    m.vote(0, m.roots[5], 1)
    m.check()


def test_latest_message_rule(spec, genesis_state):
    # a same-epoch vote must NOT displace; a newer-epoch vote must
    rng = random.Random(5)
    m = Mirror(spec, genesis_state, rng)
    a = m.add_block(m.anchor_root, 1)
    b = m.add_block(m.anchor_root, 1)
    m.vote(0, a, 1)
    assert m.check() == bytes(a)
    m.vote(0, b, 1)  # same epoch: must NOT displace
    assert m.check() == bytes(a)
    m.vote(0, b, 2)  # newer epoch: must move
    assert m.check() == bytes(b)


def test_viability_filters_nonmatching_leaves(spec, genesis_state):
    """A branch whose leaf state disagrees with the store's justified
    checkpoint must lose to a viable branch regardless of weight — and
    when NO leaf is viable, the head collapses to the justified root."""
    rng = random.Random(6)
    m = Mirror(spec, genesis_state, rng)
    good_cp = spec.Checkpoint(epoch=1, root=m.anchor_root)
    stale_cp = spec.Checkpoint(epoch=1,
                               root=spec.Root(b"\x42" * 32))
    viable = m.add_block(m.anchor_root, 1, justified_cp=good_cp)
    heavy = m.add_block(m.anchor_root, 1, justified_cp=stale_cp)
    for v in range(8):
        m.vote(v, heavy, 1)
    m.move_justified(1, m.anchor_root)
    head = m.check()
    assert head == viable  # the heavy branch is filtered out
    # drop the last viable leaf's agreement too: justified root wins
    m.move_justified(2, m.anchor_root)
    head = m.check()
    assert head == bytes(m.anchor_root)


def test_pruning_keeps_heads_and_shrinks(spec, genesis_state):
    rng = random.Random(7)
    m = Mirror(spec, genesis_state, rng)
    keep_root = m.add_block(m.anchor_root, 1)
    cp1 = spec.Checkpoint(epoch=1, root=keep_root)
    trunk = keep_root
    side_roots = []
    for slot in range(2, 8):
        trunk = m.add_block(trunk, slot, justified_cp=cp1, finalized_cp=cp1)
        side_roots.append(m.add_block(m.anchor_root, slot))  # pruned later
    m.check()
    before = m.fc.block_count
    m.move_finalized(1, keep_root)
    m.move_justified(1, keep_root)
    assert m.fc.block_count < before
    head = m.check()
    assert head == bytes(trunk)  # the agreeing trunk leaf wins post-prune
    # votes referencing pruned side branches must be inert, not fatal
    m.vote(0, side_roots[0], 3)
    m.check()


# -- speculative apply / rollback differential (ISSUE 12) ---------------------


def _twin_of(fc: ProtoForkChoice) -> ProtoForkChoice:
    """A second fork choice replaying the same tree + checkpoints —
    insertion order is preserved, so the arrays stay index-aligned."""
    twin = ProtoForkChoice()
    for node in fc.array._nodes:
        parent_root = (fc.array._nodes[node.parent].root
                       if node.parent is not None else None)
        twin.on_block(node.root, parent_root, node.slot,
                      node.justified_checkpoint, node.finalized_checkpoint)
    twin.update_checkpoints(fc._justified, fc._finalized,
                            dict(fc._balances))
    return twin


def _weights(fc: ProtoForkChoice):
    return {n.root: n.weight for n in fc.array._nodes}


def test_speculative_rollback_differential(spec, genesis_state):
    """Randomized speculative-apply/rollback sequences (the ISSUE 12
    satellite gate): a speculating twin applies EVERY batch's votes
    before "verdicts", rolls the whole batch back whenever a random
    subset "fails", and re-applies the passing votes — after every batch
    its weights, head, and vote table must be bit-identical to the
    never-speculated Mirror (which itself stays differential against
    ``spec.get_head``). Repeated validators inside one batch exercise
    the LIFO displacement-chain unwind."""
    rng = random.Random(31)
    m = Mirror(spec, genesis_state, rng)
    spine = m.add_block(m.anchor_root, 1)
    _grow_tree(m, rng, 24, max_slot=24, spine=spine)
    m.check()
    twin = _twin_of(m.fc)
    n_validators = len(genesis_state.validators)

    for batch_i in range(12):
        # small validator pool => frequent intra-batch repeats
        votes = [(rng.randrange(min(8, n_validators)), rng.choice(m.roots),
                  rng.randint(0, 4)) for _ in range(8)]
        failing = {i for i in range(len(votes)) if rng.random() < 0.35}

        # speculating side: apply ALL votes, sweep (the speculative head
        # exists and is never consulted by the oracle), then roll back
        # everything on any failure and re-apply only the passing ones
        tokens = []
        for v, r, ep in votes:
            _applied, tok = twin.speculate_latest_message(int(v), bytes(r),
                                                          ep)
            if tok is not None:
                tokens.append(tok)
        twin.apply()
        if failing:
            twin.rollback_latest_messages(tokens)
            for i, (v, r, ep) in enumerate(votes):
                if i not in failing:
                    twin.on_latest_message(int(v), bytes(r), ep)

        # oracle side: only the passing votes ever existed
        for i, (v, r, ep) in enumerate(votes):
            if i not in failing:
                m.vote(v, r, ep)

        if batch_i == 5:
            # a checkpoint move with a perturbed balance set BETWEEN
            # batches (the service contract: never inside one)
            m.move_justified(1, spine, balance_shuffle=True)
            twin.update_checkpoints(m.fc._justified, m.fc._finalized,
                                    dict(m.fc._balances))
        if batch_i == 8:
            m.move_finalized(1, spine)
            twin.update_checkpoints(m.fc._justified, m.fc._finalized,
                                    dict(m.fc._balances))

        twin.apply()
        head = m.check()  # Mirror vs spec.get_head stays the outer gate
        assert twin.head() == head
        assert _weights(twin) == _weights(m.fc)
        assert twin.votes == m.fc.votes


def test_rollback_unwinds_intra_batch_displacement_chain():
    """One validator speculated twice in one batch (epoch 2 then 3):
    rolling back must restore the ORIGINAL vote, not the intermediate."""
    fc = ProtoForkChoice()
    a, b, c = b"a" * 32, b"b" * 32, b"c" * 32
    fc.on_block(a, None, 0, (0, b""), (0, b""))
    fc.on_block(b, a, 1, (0, b""), (0, b""))
    fc.on_block(c, a, 1, (0, b""), (0, b""))
    fc.update_checkpoints((0, a), (0, b""), {0: 100})
    fc.on_latest_message(0, b, 1)
    fc.apply()
    assert fc.head() == b
    before = _weights(fc)
    tokens = []
    for root, epoch in ((c, 2), (b, 3)):
        _applied, tok = fc.speculate_latest_message(0, root, epoch)
        tokens.append(tok)
    assert fc.votes[0] == (b, 3)
    assert fc.rollback_latest_messages(tokens) == 2
    fc.apply()
    assert fc.votes[0] == (b, 1)  # the pre-batch vote, not (c, 2)
    assert _weights(fc) == before
    assert fc.head() == b


# -- proto-array unit behaviors ----------------------------------------------


def test_insert_contract():
    arr = ProtoArray()
    arr.insert(b"a" * 32, None, 0, (0, b""), (0, b""))
    arr.insert(b"b" * 32, b"a" * 32, 1, (0, b""), (0, b""))
    arr.insert(b"b" * 32, b"a" * 32, 1, (0, b""), (0, b""))  # dup: no-op
    assert len(arr) == 2
    with pytest.raises(KeyError):
        arr.insert(b"c" * 32, b"zz" * 16, 2, (0, b""), (0, b""))
    arr.add_delta(b"missing" * 4 + b"e" * 4, 100)  # swallowed
    arr.apply((0, b""), (0, b""))
    assert arr.head(b"a" * 32) == b"b" * 32


def test_reorg_depth_walk():
    arr = ProtoArray()
    arr.insert(b"a" * 32, None, 0, (0, b""), (0, b""))
    arr.insert(b"b" * 32, b"a" * 32, 1, (0, b""), (0, b""))
    arr.insert(b"c" * 32, b"b" * 32, 2, (0, b""), (0, b""))
    arr.insert(b"d" * 32, b"a" * 32, 3, (0, b""), (0, b""))
    # c -> d forks at a: rolls back c's 2 slots
    assert arr.reorg_depth(b"c" * 32, b"d" * 32) == 2
    # extension is not a reorg
    assert arr.reorg_depth(b"b" * 32, b"c" * 32) == 0
    assert arr.reorg_depth(b"x" * 32, b"c" * 32) == 0  # unknown: 0


def test_prune_rebuild_indices():
    arr = ProtoArray()
    arr.insert(b"a" * 32, None, 0, (0, b""), (0, b""))
    arr.insert(b"b" * 32, b"a" * 32, 1, (0, b""), (0, b""))
    arr.insert(b"s" * 32, b"a" * 32, 1, (0, b""), (0, b""))
    arr.insert(b"c" * 32, b"b" * 32, 2, (0, b""), (0, b""))
    dropped = arr.prune(b"b" * 32)
    assert dropped == 2 and len(arr) == 2
    assert b"s" * 32 not in arr and b"a" * 32 not in arr
    arr.apply((0, b""), (0, b""))
    assert arr.head(b"b" * 32) == b"c" * 32


# -- slow: wide randomized stress --------------------------------------------


@pytest.mark.slow
def test_differential_wide_trees_slow(spec, genesis_state):
    """64+-block trees, >1k latest-message updates, checkpoint moves and
    pruning — the full-width differential gate."""
    for seed in (11, 12, 13):
        _run_differential(spec, genesis_state, seed, blocks=96,
                          vote_events=400, check_every=1)


@pytest.mark.slow
def test_differential_deep_churn_slow(spec, genesis_state):
    # a 160-block tree under sustained vote churn across 5 epochs
    _run_differential(spec, genesis_state, 21, blocks=160, vote_events=640)
