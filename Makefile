# Build/test orchestration (L8; fills the role of the reference Makefile:90-200).
# No pip installs happen here — everything runs against the baked-in env.

VECTORS_DIR ?= ../consensus-spec-tests/tests
PYTEST = JAX_PLATFORMS=cpu python -m pytest

GENERATORS = operations sanity epoch_processing rewards finality forks transition random \
             fork_choice ssz_static ssz_generic shuffling bls genesis merkle

# sweep split: state-machine-heavy runners emit minimal-preset only (the
# reference's CI posture); cheap runners emit every preset they define —
# shuffling/bls/ssz_generic/merkle cover mainnet/general too. genesis is
# heavy: its mainnet initialization cases build 16k+-validator states
# through per-deposit processing (hours of single-core time, measured)
HEAVY_GENERATORS = operations sanity epoch_processing rewards finality forks transition \
                   random fork_choice ssz_static genesis
CHEAP_GENERATORS = shuffling bls ssz_generic merkle

.PHONY: test citest test_tpu_backend lint vmlint vm-cache-prune generate_tests \
        detect_generator_incomplete check_vectors bench serve-bench codec-bench multichip \
        clean_vectors generate_random_tests bench-compare check serve-trace head-bench docs \
        sim-bench sim-smoke serve-bench-mesh mesh-smoke clean rlc-bench \
        finalexp-bench finalexp-smoke native sweep serve-fleet-bench fleet-smoke \
        latency-bench latency-smoke vmexec-bench vmexec-smoke vmexec-cold-smoke \
        proof-bench proof-smoke merkle-bench merkle-smoke soak-bench soak-smoke \
        mainnet-bench mainnet-smoke

# fast default: BLS stubbed except @always_bls, 4-way process-parallel
# (reference `make test` = pytest -n 4, reference Makefile:100)
test:
	$(PYTEST) tests/ -q -n 4

# CI-grade: everything incl. slow VM/pairing compiles, real BLS via the
# pure-python oracle (reference `make citest` runs milagro)
citest:
	$(PYTEST) tests/ -q -n 4 --run-slow --enable-bls

# the flagship correctness gate: spec tests routed through the TPU backend
test_tpu_backend:
	$(PYTEST) tests/phase0 -q --run-slow --bls-type=tpu

# static gate: compileall (syntax) + speclint (undefined names, unused
# imports, and the built-spec namespace/annotation checks — the role the
# reference fills with flake8 + strict mypy over its generated spec,
# reference Makefile:133-136; neither tool ships in this image)
lint:
	python -m compileall -q consensus_specs_tpu tests bench.py __graft_entry__.py
	JAX_PLATFORMS=cpu python tools/speclint.py

# VM static-analysis gate (tools/vmlint.py over ops/vm_analysis.py): every
# registered field-ALU program gets its magnitude bounds independently
# re-derived and cross-checked against the assembler (carry-safety of the
# 15-limb lanes), its register pressure and live-range-outlier hazards
# checked, and its critical-path/width/cost profile diffed against the
# committed VMLINT_BASELINE.json — a pressure or depth regression fails.
# Re-pin after a conscious program change: python tools/vmlint.py --update-baseline
vmlint:
	JAX_PLATFORMS=cpu python tools/vmlint.py

# bound .vm_cache/ growth: every vmlib/vm/fq edit re-keys all cached
# programs, so stale multi-MB pickles accumulate — evict entries idle
# longer than VM_CACHE_MAX_AGE_DAYS (default 30) and oldest-first past
# VM_CACHE_MAX_BYTES (default 2 GiB)
vm-cache-prune:
	python -c "from consensus_specs_tpu.ops.bls_backend import prune_vm_cache; \
	import json; print(json.dumps(prune_vm_cache()))"

# emit every cross-client vector suite (reference `make generate_tests`)
generate_tests:
	@for g in $(GENERATORS); do \
		JAX_PLATFORMS=cpu python -m consensus_specs_tpu.gen.generators.$$g \
			-o $(VECTORS_DIR) || exit 1; \
	done

# full reproducible sweep + committed evidence: regenerate the tree
# (minimal preset for the heavy state runners, all presets for the cheap
# ones) and write the validated case-count report the repo commits
# (VECTORS_REPORT.md) — `make sweep` is what CI runs and what re-checks
# the round-4 finding that sweep evidence must persist in-repo
sweep:
	@for g in $(HEAVY_GENERATORS); do \
		JAX_PLATFORMS=cpu python -m consensus_specs_tpu.gen.generators.$$g \
			-o $(VECTORS_DIR) -l minimal || exit 1; \
	done
	@for g in $(CHEAP_GENERATORS); do \
		JAX_PLATFORMS=cpu python -m consensus_specs_tpu.gen.generators.$$g \
			-o $(VECTORS_DIR) || exit 1; \
	done
	JAX_PLATFORMS=cpu python tools/check_vectors.py $(VECTORS_DIR) --report VECTORS_REPORT.md

# regenerate the code-generated random scenario-matrix test modules
# (reference `make -C tests/generators/random`)
generate_random_tests:
	python tools/gen_random_tests.py

detect_generator_incomplete:
	python -c "from consensus_specs_tpu.gen.gen_runner import detect_incomplete; \
	import sys; bad = detect_incomplete('$(VECTORS_DIR)'); \
	print('\n'.join(bad) or 'no incomplete cases'); sys.exit(1 if bad else 0)"

# layout + completeness + snappy spot-check of an emitted vector tree
check_vectors:
	JAX_PLATFORMS=cpu python tools/check_vectors.py $(VECTORS_DIR)

bench:
	python bench.py

# perf regression gate: diff the newest BENCH_r*.json headline against the
# previous round's, keyed by (platform, mode, NxK shape) so CPU fallbacks
# never score against TPU windows; exits nonzero past the allowed drop
# (BENCH_COMPARE_MAX_REGRESSION percent, default 30) — part of `make check`
# so a perf regression is a visible failure, not a silently worse artifact
bench-compare:
	python tools/bench_compare.py

# the static + perf check flow CI runs alongside the test matrix
check: lint vmlint bench-compare sim-smoke

# streaming serve plane (consensus_specs_tpu/serve/): short CPU-sized
# synthetic gossip load — Poisson arrivals, duplicate-heavy traffic, one
# injected backend failure — through the continuous-batching
# VerificationService; emits one JSON line with sustained signatures/sec,
# batch occupancy, cache hit rate, p50/p95/p99 submit->result latency,
# and the prep-vs-device time split of the two-stage pipeline
serve-bench:
	JAX_PLATFORMS=cpu python bench.py --mode serve

# serve bench with the full observability plane on: per-request span
# tracing exported as Chrome trace-event JSON (open serve_trace.json in
# chrome://tracing or Perfetto — device-occupancy and flight-recorder
# lanes included), the flight recorder's JSONL journal dumped next to it,
# and the /metrics + /snapshot + /healthz (SLO-bearing) + /flightdump
# endpoint live on an ephemeral port during the run. CI uploads
# serve_trace.json as a build artifact.
serve-trace:
	JAX_PLATFORMS=cpu SERVE_METRICS_PORT=0 python bench.py --mode serve --trace serve_trace.json --flight serve_flight.jsonl

# mesh scaling sweep for the serve plane: one serve-bench child per
# device count (SERVE_MESH_DEVICES, default 1,2,4,8 virtual CPU devices;
# the count is frozen at XLA backend init, hence child processes), fault
# injection off. The JSON line's `mesh` section carries per-count
# sigs/sec, per-device occupancy lanes, mesh fallbacks, and scaling
# efficiency vs single-device (report-only on CPU — two host cores
# timeshare every virtual device; tools/bench_compare.py gates the
# ok-STATE: a device count that verified last round and errors now fails)
serve-bench-mesh:
	JAX_PLATFORMS=cpu python bench.py --mode serve-mesh

# multi-process fleet scaling sweep (ISSUE 11): one FleetRouter fleet of
# real worker PROCESSES per worker count (SERVE_FLEET_WORKERS, default
# 1,2,4 — counts past the 2 physical cores are report-only), each worker
# warmed at exactly the flush shapes its consistent-hash share of the
# stream produces; the JSON line's `fleet` section carries aggregate
# sigs/sec per count plus the merged-scrape exactness property (merged
# /metrics == exact merge of per-worker snapshots: observation counts
# sum, bucket mass sums). tools/bench_compare.py gates the ok-STATE
# ("FLEET ERRORED"); sigs/sec and the 2-worker speedup are report-only.
serve-fleet-bench:
	JAX_PLATFORMS=cpu python bench.py --mode serve-fleet

# fleet control-plane canary (CI, mirror of mesh-smoke): a 2-worker fleet
# through the strict verdict-identity gate (fleet == single-process
# service == host oracle over valid/corrupted/malformed/infinity), then
# one forced worker fault under load must produce an SLO burn-rate-driven
# shed/drain decision reconstructable end-to-end from the merged flight
# journal (decision + worker provenance + ladder transition) and a
# merged-scrape delta; journal dumps to fleet_flight.jsonl (CI artifact
# on failure). Out of tier-1: the workers pay real-backend compiles.
fleet-smoke:
	JAX_PLATFORMS=cpu python -m consensus_specs_tpu.serve.fleet_smoke

# light-client proof plane (ISSUE 16): replay 10^4-10^6 simulated
# read-only clients (CONSENSUS_SPECS_TPU_PROOF_CLIENTS, default 20000)
# against the content-addressed ProofService — R distinct per-slot
# artifacts (finality branch + next-sync-committee branch + assembled
# LightClientUpdate), every one fully verified by the spec's
# validate_light_client_update AND is_valid_merkle_branch against an
# independently re-Merkleized root before the timed window, every served
# request re-checking its finality branch client-side. The JSON line's
# `proofs` section (verified + proofs/sec + cache hit rate + p99) is
# state-gated round over round by tools/bench_compare.py ("PROOFS
# DIVERGED" when a previously-verified shape stops verifying);
# proofs/sec and hit rate are report-only.
# 10^5 clients and a 16k-validator registry since the native
# Merkleization plane (ISSUE 18); override via env
CONSENSUS_SPECS_TPU_PROOF_CLIENTS ?= 100000
proof-bench: native
	JAX_PLATFORMS=cpu \
	CONSENSUS_SPECS_TPU_PROOF_CLIENTS=$(CONSENSUS_SPECS_TPU_PROOF_CLIENTS) \
	python bench.py --mode proofs

# proof-plane CI canary (fleet-smoke's read-path sibling): one full
# artifact served through a ProofService whose sync-committee signature
# verdict routes through a REAL 2-worker fleet, then verified
# client-side via validate_light_client_update + is_valid_merkle_branch
# against an independently re-Merkleized state root (fresh decode_bytes
# round trip — no warm-cache reuse), with a corrupted-branch negative
# control; journal dumps to proof_flight.jsonl (CI artifact on
# failure). Out of tier-1: the workers pay real-backend compiles.
proof-smoke:
	JAX_PLATFORMS=cpu python -m consensus_specs_tpu.lightclient.proof_smoke

# Merkleization plane race (ISSUE 18): the native batched hash_tree_root
# path (csrc sha256_hash_many per tree level + incremental dirty-set
# re-roots) vs the pure-python oracle on identical states — full-state
# cold root, per-block incremental re-root, and the proof-world artifact
# build+sign, each cell checked bit-identical. The JSON line's `merkle`
# section is state-gated round over round by tools/bench_compare.py
# ("MERKLE DIVERGED" when a cell's roots stop matching); speedups and
# roots/sec are report-only. Builds the native kernel first.
merkle-bench: native
	JAX_PLATFORMS=cpu python bench.py --mode merkle

# Merkleization CI canary: native == pure-python oracle BIT-IDENTITY
# over every SSZ shape class (vectors, lists with length mix-ins,
# bitlists, nested containers, zero-subtree padding) plus a seeded
# random incremental-cache invalidation sweep (random dirty sets +
# appends re-rooted against from-scratch rebuilds); journal dumps to
# merkle_flight.jsonl (CI artifact on failure). Crypto-free and
# compile-free — safe anywhere.
merkle-smoke: native
	JAX_PLATFORMS=cpu python -m consensus_specs_tpu.merkle.smoke

# mesh convergence canary (CI): one serve flush on a 4-virtual-device
# mesh through the STRICT verdict-identity gate (mesh == single-device ==
# host oracle over valid/corrupted/malformed/infinity inputs, bisection
# through the failed sharded combine included, zero silent fallbacks);
# dumps the flight journal to mesh_flight.jsonl on failure — uploaded as
# a CI artifact. Kept out of tier-1: the sharded compiles cost ~1 min.
mesh-smoke:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
		python -m consensus_specs_tpu.serve.mesh_smoke

# prep-only microbenchmark: the batched input codec (ops/codec.py —
# decompression, subgroup checks, hash-to-G2) vs the per-item pure-Python
# prep path, items/sec on a CPU-sized batch (CODEC_ITEMS, default 64);
# the JSON line's vs_baseline field is the batched-over-per-item speedup
codec-bench:
	JAX_PLATFORMS=cpu python bench.py --mode codec

# chain-plane bench: synthetic fork-and-gossip replay through the
# HeadService + incremental proto-array vs the spec-store get_head
# recompute, at growing block-tree sizes (HEAD_TREE_SIZES env); fault
# injection covers invalid-signature and withheld-block (deferred-then-
# resolved) gossip, and the ephemeral /metrics endpoint is scraped
# mid-replay so the JSON line proves the chain.* gauges answer under load
head-bench:
	JAX_PLATFORMS=cpu SERVE_METRICS_PORT=0 python bench.py --mode head

# adversarial multi-node network simulation (consensus_specs_tpu/sim/):
# every named scenario class — partition/heal, latency skew, lossy links,
# equivocating proposals, withheld-block orphans, long-range reorgs,
# censored aggregates — runs N independent HeadService nodes over the
# deterministic discrete-event gossip fabric; the JSON line reports the
# convergence matrix (every honest head bit-identical to spec.get_head on
# the union view), heal-to-convergence latency, and per-node heads/sec.
# Per-node flight journals land in sim_flight/ (CONSENSUS_SPECS_TPU_SIM_*
# env resizes the run)
sim-bench:
	JAX_PLATFORMS=cpu CONSENSUS_SPECS_TPU_SIM_FLIGHT_DIR=sim_flight python bench.py --mode sim

# CI convergence canary (part of `make check`): one small 4-node
# partition-and-heal scenario through the STRICT differential gate,
# dumping per-node flight journals to sim_flight/ — uploaded as CI
# artifacts on failure; exits nonzero with the divergence diagnosis
sim-smoke:
	JAX_PLATFORMS=cpu python -m consensus_specs_tpu.sim.smoke

# long-horizon telemetry soak (ISSUE 19): a 128-epoch (1000+ slot)
# simnet scenario with periodic partitions, replayed against real
# verdict-mode fleet workers — a per-node chain/health.py ledger
# observes every slot past warm-up, a sim-clock TSDB records the full
# gauge history, and the run ends with the stitched cross-process
# Chrome trace (worker-pid spans joined to router flows by flow id).
# Artifacts land in soak_artifacts/ (timeseries JSONL, stitched trace,
# merged fleet timeseries, HTML/SVG timeline); the `health` section is
# state-gated round over round by tools/bench_compare.py ("HEALTH
# DIVERGED"). CONSENSUS_SPECS_TPU_SOAK_* env resizes.
soak-bench:
	JAX_PLATFORMS=cpu CONSENSUS_SPECS_TPU_SOAK_DIR=soak_artifacts python bench.py --mode soak
	python tools/render_timeline.py soak_artifacts/soak_timeseries.jsonl -o soak_artifacts/soak_timeline.html

# soak CI canary: the same pipeline at 26 epochs (~200 slots, well
# under a minute), with the claims turned into an exit status — health
# gate green, scenario converged, >= 2 worker pids flow-joined in the
# stitched trace, one TSDB sample per slot; the timeline render rides
# along as the uploadable artifact
soak-smoke:
	JAX_PLATFORMS=cpu CONSENSUS_SPECS_TPU_SOAK_DIR=soak_artifacts python -m consensus_specs_tpu.sim.soak_smoke
	python tools/render_timeline.py soak_artifacts/soak_timeseries.jsonl -o soak_artifacts/soak_timeline.html

# mainnet-scale workload replay (ISSUE 20): full mainnet-shape slots over
# the synthetic MILLION-validator registry (scale/) — mainnet-preset
# 64-committee shuffling computed columnar, real index-derived pubkeys,
# per-committee aggregate signatures, the hierarchical aggregate-of-
# aggregates fold (whole slot -> ONE RLC combine -> ONE final exp,
# final_exps_per_slot == 1.0), the byte-budgeted decompressed-pubkey
# plane, a planted bad committee localized by bisection, the strict
# censored_aggregates sim at true 64-committee fan-out, and 2-worker
# committee-affinity fleet routing. The JSON line's `mainnet` section is
# state-gated round over round by tools/bench_compare.py ("MAINNET
# DIVERGED"); attestations/sec, pubkey hit rate, and peak RSS are
# report-only numbers. CONSENSUS_SPECS_TPU_SCALE_* env resizes.
mainnet-bench:
	JAX_PLATFORMS=cpu python bench.py --mode mainnet

# mainnet-workload CI canary (fleet-smoke's scale sibling): an
# 8192-validator registry (two full-size committees/slot) through the
# valid / censored / planted-bad-committee rounds with hierarchical ==
# flat == host-oracle verdict identity, one-final-exp accounting, the
# pubkey plane under budget, and committee affinity stable across a
# real 2-worker verdict fleet; journal dumps to scale_flight.jsonl (CI
# artifact on failure). Crypto-light: summed-sk aggregates over small
# secret keys keep it CI-fast.
mainnet-smoke:
	JAX_PLATFORMS=cpu python -m consensus_specs_tpu.scale.smoke

# end-to-end gossip→head latency matrix (ISSUE 12): latency_skew and
# lossy_links simnet scenarios, each run under the classic
# size-or-deadline flush, the slot-budget deadline scheduler
# (CONSENSUS_SPECS_TPU_SLOT_MS semantics, shared SlotClock), and
# deadline+speculative head application — the JSON line carries
# gossip_to_head p50/p99 per scenario × policy, the deadline-flush win
# (baseline p99 / deadline p99), rollback counts from the invalid-sig
# traffic, and an `slo` section evaluating the declared
# gossip_to_head_p99 objective over the exact merge of the deadline-mode
# histograms. tools/bench_compare.py gates the per-scenario ok-state
# ("LATENCY SLO VIOLATED"); the p99 milliseconds are report-only.
# LATENCY_* env resizes (scenarios, wait, slot, nodes, events).
latency-bench:
	JAX_PLATFORMS=cpu CONSENSUS_SPECS_TPU_SIM_FLIGHT_DIR=sim_flight python bench.py --mode latency

# latency-plane CI canary (mirror of sim/mesh/finalexp/fleet smokes): one
# short latency_skew scenario with deadline flushing + speculative head
# application through the STRICT convergence gate, then the
# gossip_to_head_p99 presence assert (the end-to-end histogram must be
# non-empty and the objective met); per-node flight journals land in
# sim_flight/ — uploaded as CI artifacts on failure
latency-smoke:
	JAX_PLATFORMS=cpu python -m consensus_specs_tpu.sim.latency_smoke

# final-exp microbenchmark: per-item easy+hard finalization vs the RLC
# combine (one final exponentiation per batch) on identical Miller
# outputs, items/sec across N in {4,16,64,256}; the JSON line's
# vs_baseline field is the RLC-over-per-item speedup at N=16 (> 1 means
# the combine wins at the acceptance bar; RLC_BENCH_* env resizes)
rlc-bench:
	JAX_PLATFORMS=cpu python bench.py --mode rlc

# hard-part variant race (ISSUE 10): host-oracle HHT vs the VM variants
# (bit_serial legacy chain, windowed, frobenius) at pipelined rows
# {1,2,4,8} on identical valid unitary inputs, ms/row per cell, plus the
# vmlint critical-path ratios (the >=2.5x depth bar) and the bucketed-vs-
# legacy assembler throughput race on the chunk-16 rlc_combine (the >=4x
# / <=2s bars). `finalexp[variant,rows]` cells are state-gated round over
# round by tools/bench_compare.py — an errored variant fails the round,
# a device route merely slower than host is report-only
finalexp-bench:
	JAX_PLATFORMS=cpu python bench.py --mode finalexp

# VM execution-backend race (ISSUE 13): the scan interpreter vs the fused
# straight-line lowering (ops/vm_compile.py) on identical assembled
# programs — warm ms/row both ways, fused trace/compile seconds, and
# per-cell bit-identity, keyed `vmexec[kind,rows]`. First run on a
# machine pays one XLA compile per (kind, rows) cell (persistent-cached
# after — with ISSUE 15's structural dedup a cell compiles one XLA
# executable per DISTINCT chunk structure, not per chunk);
# VMEXEC_KINDS/VMEXEC_ROWS resize. Cells are state-gated round over
# round by tools/bench_compare.py ("VMEXEC ERRORED" — ms/row is
# report-only). Running it also persists each program's measured winner
# into .vm_cache — the verdict CONSENSUS_SPECS_TPU_VM_EXEC=auto adopts
# (auto serves fused only for shapes a warm/pinned/background-warm call
# has compiled). The cold cells (`cold,<kind>` / `cold_nodedup,<kind>`)
# spawn fresh child processes against fresh XLA caches and race
# structural dedup against the PR 13 per-chunk baseline — the
# `cold_speedup` headline is the ISSUE 15 fresh-process
# time-to-fused-ready win (VMEXEC_COLD=dedup skips the minutes-scale
# baseline arm, VMEXEC_COLD=0 skips both).
vmexec-bench:
	JAX_PLATFORMS=cpu python bench.py --mode vmexec

# fresh-process fused-ready canary (CI, ISSUE 15): one child process
# against a brand-new persistent-XLA-cache dir must reach a fused-ready
# g2_subgroup fold-1 (955-level ladder) with bit-identity — proving a
# fresh CI runner / fleet worker gets the fast path in seconds-scale
# time, not the pre-dedup minutes. The VMEXEC_COLD_BUDGET_S budget
# (default 180 s) is reported here and STATE-gated by bench_compare's
# cold cells, not hard-asserted (slow public runners must not flake CI).
vmexec-cold-smoke:
	JAX_PLATFORMS=cpu python -m consensus_specs_tpu.bench.vmexec_cold --smoke

# execution-backend identity canary (CI, mirror of finalexp-smoke): the
# fused straight-line lowering held to BIT-identity against the scan
# interpreter AND the exact-int IR oracle (vm_analysis.eval_ir) over
# registry programs at small assembly shapes (VMEXEC_SMOKE_FULL=1 runs
# the full production-shape registry), batch axis included; dumps the
# flight journal to vmexec_flight.jsonl on failure — uploaded as a CI
# artifact. Kept out of tier-1: it pays real fused XLA compiles.
vmexec-smoke:
	JAX_PLATFORMS=cpu python -m consensus_specs_tpu.ops.vmexec_smoke

# hard-part bit-identity canary (CI, mirror of mesh-smoke): the windowed
# and Frobenius hard-part programs held to full-coefficient identity
# against the exact-int host oracle over valid AND adversarial Fq12
# inputs (identity, random unitary, conjugates, real valid/corrupted
# verification flows, raw non-unitary feeds under the no-false-accept
# contract); dumps the flight journal to finalexp_flight.jsonl on
# failure — uploaded as a CI artifact. Kept out of tier-1 (three
# hard-part XLA compiles)
finalexp-smoke:
	JAX_PLATFORMS=cpu python -m consensus_specs_tpu.ops.finalexp_smoke

multichip:
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('multichip OK')"

clean_vectors:
	rm -rf $(VECTORS_DIR)

# sweep the bench/observability artifacts the serve/sim/mesh targets drop
# at the repo root (all gitignored; this keeps `git status` quiet and the
# tree reproducible after `make serve-trace` / `sim-bench` / `mesh-smoke`)
clean:
	rm -rf serve_trace.json serve_flight.jsonl flight_dump.jsonl \
		mesh_flight.jsonl finalexp_flight.jsonl sim_flight/ \
		fleet_flight.jsonl serve_flight.*.jsonl flight_dump.*.jsonl \
		mesh_flight.*.jsonl finalexp_flight.*.jsonl fleet_flight.*.jsonl \
		vmexec_flight.jsonl vmexec_flight.*.jsonl \
		proof_flight.jsonl proof_flight.*.jsonl \
		merkle_flight.jsonl merkle_flight.*.jsonl \
		scale_flight.jsonl scale_flight.*.jsonl \
		*-pid[0-9]*.jsonl
	find . -name __pycache__ -type d -prune -exec rm -rf {} +

# build the native kernels (csrc/): batched-SHA256 merkleization and the
# VM assembler's scheduling+allocation kernel (ops/vm.py loads it via
# ctypes when present; the pure-Python bucketed scheduler is the fallback)
native:
	gcc -O3 -fPIC -shared -o csrc/libsha256_batch.so csrc/sha256_batch.c
	gcc -O3 -fPIC -shared -o csrc/libvmsched.so csrc/vm_sched.c

# regenerate the human-readable per-fork spec document set from specsrc/
docs:
	python tools/render_spec.py
