"""Pure-Python model of the deposit contract's incremental Merkle
accumulator (deposit_contract/deposit_contract.sol in this repo; fills the
role of the reference's solidity_deposit_contract + web3 harness,
reference specs/phase0/deposit-contract.md).

The model is the executable twin of the Solidity source: same state
(a branch cache + leaf count), same insert/carry algorithm, same
length-mixed root — so its outputs are directly checked against the
consensus spec's ``hash_tree_root``/``is_valid_merkle_branch`` in
tests/test_deposit_contract.py. It also produces the per-leaf Merkle
proofs the spec's ``process_deposit`` consumes (the contract itself never
materializes proofs; an eth1 data provider reconstructs them from the
event log, which is what ``proof_at`` models).
"""
from typing import List

from ..utils.hash_function import hash as sha256

TREE_DEPTH = 32


def _zero_hashes():
    zh = [b"\x00" * 32]
    for _ in range(TREE_DEPTH):
        zh.append(sha256(zh[-1] + zh[-1]))
    return zh


ZERO_HASHES = _zero_hashes()


class DepositContractModel:
    def __init__(self):
        self.branch = [b"\x00" * 32] * TREE_DEPTH
        self.deposit_count = 0
        self._leaves: List[bytes] = []  # event log (for proof reconstruction)

    # -- the contract's own operations --------------------------------------

    def deposit(self, deposit_data_root: bytes) -> None:
        """Insert a DepositData hash_tree_root leaf (deposit())."""
        assert self.deposit_count < 2**TREE_DEPTH - 1, "merkle tree full"
        self.deposit_count += 1
        self._leaves.append(bytes(deposit_data_root))
        node = bytes(deposit_data_root)
        size = self.deposit_count
        for h in range(TREE_DEPTH):
            if size & 1:
                self.branch[h] = node
                return
            node = sha256(self.branch[h] + node)
            size >>= 1
        raise AssertionError("unreachable")

    def get_deposit_root(self) -> bytes:
        node = b"\x00" * 32
        size = self.deposit_count
        for h in range(TREE_DEPTH):
            if size & 1:
                node = sha256(self.branch[h] + node)
            else:
                node = sha256(node + ZERO_HASHES[h])
            size >>= 1
        return sha256(node + self.deposit_count.to_bytes(8, "little") + b"\x00" * 24)

    def get_deposit_count(self) -> bytes:
        return self.deposit_count.to_bytes(8, "little")

    # -- eth1-provider side: proof reconstruction from the event log --------

    def proof_at(self, index: int, deposit_count: int = None) -> List[bytes]:
        """Merkle branch for leaf ``index`` against the tree of the first
        ``deposit_count`` leaves, in is_valid_merkle_branch order (deepest
        first), with the length mix-in appended — depth TREE_DEPTH + 1,
        exactly what process_deposit verifies
        (reference specs/phase0/beacon-chain.md:1852-1860)."""
        if deposit_count is None:
            deposit_count = self.deposit_count
        assert 0 <= index < deposit_count <= len(self._leaves)
        layer = list(self._leaves[:deposit_count])
        proof = []
        idx = index
        for h in range(TREE_DEPTH):
            sibling = idx ^ 1
            proof.append(layer[sibling] if sibling < len(layer) else ZERO_HASHES[h])
            nxt = []
            for i in range(0, len(layer), 2):
                left = layer[i]
                right = layer[i + 1] if i + 1 < len(layer) else ZERO_HASHES[h]
                nxt.append(sha256(left + right))
            layer = nxt
            idx >>= 1
        proof.append(deposit_count.to_bytes(8, "little") + b"\x00" * 24)
        return proof
