from .model import DepositContractModel  # noqa: F401
