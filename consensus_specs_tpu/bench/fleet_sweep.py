"""`bench.py --mode serve-fleet` / `make serve-fleet-bench`: the
multi-process fleet scaling sweep (ISSUE 11).

One measurement per worker count: spawn a real `serve/fleet.FleetRouter`
fleet (bls backend — real pairings in every worker process), warm each
worker's flush shapes OUTSIDE the timed window (the parent knows the
consistent-hash routing, so it warms each worker at exactly the flush
sizes its share of the stream will produce), then push ``rounds`` bursts
of distinct committee aggregates through the router and measure
aggregate verified signatures/sec across the fleet.

The JSON line's ``fleet`` section carries one row per worker count:
``sigs_per_sec``, per-worker submit splits, the merged p99, and
``merge_exact`` — the acceptance property that the merged ``/metrics``
scrape equals the exact merge of the per-worker snapshots (observation
counts sum, per-bucket mass sums; verified here against both the decoded
wire snapshots and the rendered Prometheus text). ``bars`` pre-evaluates
the acceptance checks: two workers >= 1.2x one worker on the 2-core
host, and every gated count merge-exact with correct verdicts.
``tools/bench_compare.py`` gates the ok-STATE round over round ("FLEET
ERRORED", the mesh-gate mirror); sigs/sec and the speedup are
report-only numbers.

Env: SERVE_FLEET_WORKERS ("1,2,4" — counts past 2 are report-only on the
2-core container), SERVE_FLEET_COMMITTEES (16), SERVE_FLEET_K (8),
SERVE_FLEET_ROUNDS (2), SERVE_FLEET_TIMEOUT (s per fleet, 900).
"""
import os
import threading
import time
from typing import Dict, List

from ..serve.cache import check_key
from ..serve.worker import _warm_committees

# north-star share, same constant as the other serve benches
TARGET_PER_CHIP = 150_000 / 8


def _round_traffic(committees: int, k: int, rounds: int):
    """Per-round distinct valid committees (content disjoint across
    rounds so no cross-round cache hit pollutes the scaling number)."""
    return [_warm_committees(k, committees, seed=1000 + r)
            for r in range(rounds)]


def _expected_sizes(traffic, route_label) -> Dict[str, List[int]]:
    """worker label -> warm sizes: for each round, the number of distinct
    items the consistent-hash ring sends that worker (its flush size),
    plus the half/2/1 ladder the serve bench warms (bisection and
    straggler shapes)."""
    sizes: Dict[str, set] = {}
    for round_items in traffic:
        per_worker: Dict[str, int] = {}
        for kind, pks, msg, sig in round_items:
            label = route_label(check_key(kind, pks, msg, sig))
            per_worker[label] = per_worker.get(label, 0) + 1
        for label, n in per_worker.items():
            sizes.setdefault(label, set()).update(
                {n, max(1, n // 2), 2, 1})
    return {label: sorted(s, reverse=True) for label, s in sizes.items()}


def _check_merge_exact(router, scrape_text: str) -> Dict:
    """The acceptance property: merged scrape == exact merge of the
    per-worker snapshots for the submit->result histogram — observation
    counts sum AND per-bucket mass sums."""
    label = "serve.submit_to_result"
    wires = []
    for worker in router.aggregator.workers:
        snap = router.aggregator.worker_snapshot(worker)
        wire = (snap or {}).get("hists", {}).get(label)
        if wire is not None:
            wires.append(wire)
    if not wires:
        return {"ok": False, "error": "no worker histograms"}
    expect_count = sum(int(w["count"]) for w in wires)
    expect_buckets: Dict[int, int] = {}
    for w in wires:
        for idx, n in w["counts"].items():
            expect_buckets[int(idx)] = expect_buckets.get(int(idx), 0) + n
    merged = router.aggregator.merged_hists().get(label)
    merged_state = merged.state() if merged is not None else {}
    counts_ok = (merged_state.get("count") == expect_count
                 and merged_state.get("counts") == expect_buckets)
    # and the RENDERED text agrees (the scrape a Prometheus server sees)
    fam = "consensus_specs_tpu_serve_submit_to_result_latency_hist_seconds"
    scrape_count = None
    for line in scrape_text.splitlines():
        if line.startswith(fam + "_count "):
            scrape_count = int(float(line.rsplit(" ", 1)[1]))
    return {
        "ok": bool(counts_ok and scrape_count == expect_count),
        "n_merged": merged_state.get("count", 0),
        "n_expected": expect_count,
        "n_scrape": scrape_count,
        "buckets": len(expect_buckets),
    }


def _measure_count(n_workers: int, committees: int, k: int, rounds: int,
                   future_timeout: float) -> Dict:
    """One fleet at one worker count: warm, drive, verify, measure."""
    from ..serve.fleet import FleetRouter

    traffic = _round_traffic(committees, k, rounds)
    router = FleetRouter(
        workers=n_workers, backend="bls",
        # one flush per round per worker: the burst (pipe writes, tens
        # of ms) lands inside the wait window, so the warmed shapes are
        # the executed shapes; the window is also per-round DEAD TIME
        # every count pays once, so it stays small relative to a flush
        env={"SERVE_MAX_WAIT_MS": "100", "SERVE_MAX_BATCH": "64"})
    try:
        warm_sizes = _expected_sizes(traffic, router.route_label)
        # warm every worker CONCURRENTLY (each is its own process; the
        # wall cost is the slowest worker, not the sum)
        errs: List[str] = []

        def _warm(label, sizes):
            try:
                router.handle(label).warm(k, sizes, timeout=future_timeout)
            except Exception as e:
                errs.append(f"{label}: {type(e).__name__}: {e}"[:200])

        threads = [threading.Thread(target=_warm, args=(label, sizes))
                   for label, sizes in warm_sizes.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(future_timeout)
        if errs:
            return {"ok": False, "error": f"warm failed: {errs[0]}"}

        served = 0
        wrong = 0
        elapsed = 0.0
        for round_items in traffic:
            t0 = time.perf_counter()
            futures = [router.submit(kind, pks, msg, sig)
                       for kind, pks, msg, sig in round_items]
            results = [bool(f.result(timeout=future_timeout))
                       for f in futures]
            elapsed += time.perf_counter() - t0
            served += sum(len(pks) for _, pks, _, _ in round_items)
            wrong += sum(1 for got in results if got is not True)
        if wrong:
            return {"ok": False,
                    "error": f"{wrong} wrong verdicts on valid traffic"}

        snaps = router.poll_snapshots()
        merge = _check_merge_exact(router, router.scrape_text())
        merged_hist = router.aggregator.merged_hists().get(
            "serve.submit_to_result")
        per_worker = {
            label: snap["extra"]["serve"]["submits"]
            for label, snap in sorted(snaps.items())
        }
        return {
            "ok": bool(merge["ok"]),
            "workers": n_workers,
            "sigs_per_sec": round(served / elapsed, 2) if elapsed else 0.0,
            "elapsed_s": round(elapsed, 3),
            "served": served,
            "per_worker_submits": per_worker,
            "p99_ms": (round(merged_hist.percentile(99) * 1e3, 3)
                       if merged_hist is not None else 0.0),
            "merge_exact": merge,
        }
    except Exception as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
    finally:
        router.close()


def run_fleet_bench() -> dict:
    """Drive the sweep; returns bench.py's result dict."""
    counts = []
    for tok in os.environ.get("SERVE_FLEET_WORKERS", "1,2,4").split(","):
        tok = tok.strip()
        if tok.isdigit() and int(tok) > 0:
            counts.append(int(tok))
    # 32 distinct committees per round: enough crypto per flush that the
    # per-round fixed costs (flush wait window, host finalization) stop
    # diluting the scaling signal — measured 1.28x at 2 workers vs 1.21x
    # with 16 committees on the 2-core container
    committees = int(os.environ.get("SERVE_FLEET_COMMITTEES", "32"))
    k = int(os.environ.get("SERVE_FLEET_K", "8"))
    rounds = int(os.environ.get("SERVE_FLEET_ROUNDS", "2"))
    timeout = float(os.environ.get("SERVE_FLEET_TIMEOUT", "900"))

    fleet: Dict[str, Dict] = {}
    for n in counts:
        fleet[str(n)] = _measure_count(n, committees, k, rounds, timeout)

    one = fleet.get("1", {})
    two = fleet.get("2", {})
    base = one.get("sigs_per_sec", 0.0) if one.get("ok") else 0.0
    speedup = None
    if base > 0 and two.get("ok"):
        speedup = round(two["sigs_per_sec"] / base, 4)
        two["speedup_vs_1"] = speedup
    for n_str, row in fleet.items():
        d = int(n_str)
        if row.get("ok") and base > 0 and d > 1:
            row["efficiency"] = round(row["sigs_per_sec"] / (d * base), 4)

    ok_rows = [r for r in fleet.values() if r.get("ok")]
    best = max((r["sigs_per_sec"] for r in ok_rows), default=0.0)
    bars = {
        # the 2-core-host acceptance bar: two processes must beat one by
        # >= 1.2x aggregate sigs/sec (counts past 2 are report-only —
        # virtual parallelism ends at the physical core count)
        "two_workers_ge_1_2x": bool(speedup is not None and speedup >= 1.2),
        "gated_counts_ok": all(
            fleet.get(str(n), {}).get("ok", False) for n in (1, 2)
            if str(n) in fleet),
        "merge_exact_everywhere": all(
            r.get("merge_exact", {}).get("ok", False) for r in ok_rows),
    }
    return dict(
        metric="aggregate BLS signatures verified/sec (serve fleet)",
        value=best,
        vs_baseline=best / TARGET_PER_CHIP,
        platform="cpu",
        mode="serve-fleet",
        worker_counts=counts,
        committees=committees,
        k=k,
        rounds=rounds,
        fleet=fleet,
        bars=bars,
    )
