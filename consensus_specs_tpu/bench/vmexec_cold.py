"""Fresh-process time-to-fused-ready probe (ISSUE 15).

Run as a CHILD process (``python -m consensus_specs_tpu.bench.vmexec_cold``)
so nothing is warm: it measures the wall seconds from process entry
(heavy imports included) to a fused-ready program — ``bls_backend
._program`` resolution, structural-plan derivation/load, and
``warm_fused`` for one batch shape — then spot-checks one fused
execution bit-identical to the interpreter. Emits one machine-readable
line::

    VMEXEC_COLD_JSON {"ok": true, "ready_s": ..., "distinct_structs": ...}

The vmexec bench (`make vmexec-bench`) runs two arms, each against a
FRESH persistent-XLA-cache dir: structural dedup on (the default) and
``CONSENSUS_SPECS_TPU_VM_DEDUP=0`` (the PR 13 one-compile-per-chunk
baseline) — their ready_s ratio is the ISSUE 15 acceptance number
(>= 5x for the 955-level g2_subgroup ladder).

``--smoke`` is the CI entry (`make vmexec-cold-smoke`): it forces a
fresh temp XLA cache itself, asserts the process REACHES fused-ready
with bit-identity (exit 1 otherwise), and reports the seconds against
the VMEXEC_COLD_BUDGET_S budget (default 180) — over-budget is a
warning here, not a failure: the budget is STATE-gated round over round
through the bench's cold cells by tools/bench_compare.py, mirroring how
VMEXEC cells gate, rather than hard-failing CI on a slow runner.

Env: VMEXEC_COLD_KIND (default g2_subgroup), VMEXEC_COLD_K (default 0),
VMEXEC_COLD_ROWS (default 1), VMEXEC_COLD_SEED, VMEXEC_COLD_BUDGET_S.
"""
import json
import os
import random
import sys
import time


def main(argv=None) -> int:
    t0 = time.monotonic()
    args = sys.argv[1:] if argv is None else argv
    smoke = "--smoke" in args
    smoke_cache = None
    if smoke:
        # a COLD cache pair is the point of the smoke: a pre-warmed
        # runner XLA cache (or a pre-derived .vm_cache plan) would make
        # the number meaningless (deleted on the way out)
        import tempfile

        smoke_cache = tempfile.mkdtemp(prefix="vmexec_cold_xla_")
        os.environ["CONSENSUS_SPECS_TPU_XLA_CACHE"] = smoke_cache
        os.environ["CONSENSUS_SPECS_TPU_VM_CACHE"] = os.path.join(
            smoke_cache, "vm")

    kind = os.environ.get("VMEXEC_COLD_KIND", "g2_subgroup")
    k = int(os.environ.get("VMEXEC_COLD_K", "0") or 0)
    rows = int(os.environ.get("VMEXEC_COLD_ROWS", "1") or 1)
    budget_s = float(os.environ.get("VMEXEC_COLD_BUDGET_S", "180"))

    from ..utils.jax_env import force_cpu

    force_cpu()

    import numpy as np

    from ..ops import bls_backend as bb, fq, vm, vm_compile
    from ..utils import bls12_381 as O

    result = {
        "ok": False,
        "kind": kind,
        "rows": rows,
        "dedup": vm_compile.dedup_enabled(),
        "budget_s": budget_s,
    }
    try:
        program, _fold = bb._program(kind, k, 1)
        t_prog = time.monotonic()
        fp = vm_compile.fused_program(program)
        warm_s = vm_compile.warm_fused(program, (rows,))
        ready_s = time.monotonic() - t0
        result.update(
            ready_s=round(ready_s, 1),
            program_s=round(t_prog - t0, 1),
            warm_s=round(warm_s, 1),
            within_budget=bool(ready_s <= budget_s),
            struct_misses=vm_compile._COUNTERS["struct_misses"],
            **fp.struct_stats,
        )
        rng = random.Random(int(os.environ.get("VMEXEC_COLD_SEED", "5")))
        ins = {
            name: np.stack([fq.to_mont_int(rng.randrange(O.P))
                            for _ in range(rows)])
            for name in program.input_names
        }
        os.environ["CONSENSUS_SPECS_TPU_VM_EXEC"] = "fused"
        out_f = vm.execute(program, ins, batch_shape=(rows,))
        os.environ["CONSENSUS_SPECS_TPU_VM_EXEC"] = "interp"
        out_i = vm.execute(program, ins, batch_shape=(rows,))
        identical = set(out_f) == set(out_i) and all(
            np.array_equal(np.asarray(out_f[name]),
                           np.asarray(out_i[name]))
            for name in out_f)
        result["identical"] = bool(identical)
        result["ok"] = bool(identical)
    except Exception as e:
        result["error"] = f"{type(e).__name__}: {e}"[:300]

    print("VMEXEC_COLD_JSON " + json.dumps(result), flush=True)
    if smoke_cache:
        import shutil

        shutil.rmtree(smoke_cache, ignore_errors=True)
    if smoke:
        if not result["ok"]:
            print(f"vmexec-cold-smoke FAIL: {result}")
            return 1
        verdict = ("within" if result.get("within_budget")
                   else "OVER (report-only — bench_compare state-gates it)")
        print(
            f"vmexec-cold-smoke: OK — {kind} rows={rows} fused-ready in "
            f"{result['ready_s']}s ({result['distinct_structs']} distinct "
            f"structures / {result['chunks']} chunks, window "
            f"{result['window']}), {verdict} the {budget_s:.0f}s budget")
        return 0
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
