"""RLC microbenchmark: per-item final exponentiation vs the
random-linear-combination combine, items/sec across batch sizes.

Both contenders get the SAME (N, 12, L) Miller-output rows — PROG A runs
once on a couple of real committees and its f rows are tiled to N (the
finalization cost is data-independent; the RLC scalars stay fresh per
item) — so the race isolates exactly what batch_verify_rlc changes:

  per-item: N host easy parts (pooled at scale) + N device hard-part rows
            (ops/bls_backend._finalize_per_item — the pre-RLC pipeline);
  RLC:      ONE combine program over the N rows (chunked,
            vmlib.build_rlc_combine) + ONE easy part + ONE hard part
            (host oracle on CPU, device row under an accelerator —
            CONSENSUS_SPECS_TPU_RLC_FINAL).

The per-item hard part amortizes through lane folding, so this is a fair
fight: the combine must beat a fold-32 hard-part program, not a naive
one-row-per-item loop. Acceptance (ISSUE 3): RLC wins items/sec at
N >= 16 on plain CPU.

Env: RLC_BENCH_NS (default "4,16,64,256"), RLC_BENCH_REPS (default 1,
best-of over reps after a warmup), RLC_BENCH_SEED.
"""
import os
import random
import time

import numpy as np


def _build_f_rows(seed: int) -> np.ndarray:
    """(2, 12, L) Miller-output rows from two real K=2 committee checks
    (both valid), via the shared PROG A stage."""
    from ..ops import bls_backend as bb
    from ..utils import bls
    from ..utils.bls12_381 import R

    sks = [seed * 100 + 1, seed * 100 + 2]
    pks = [bls.SkToPk(sk) for sk in sks]
    msgs = [b"rlc-bench-%d" % i + b"\x00" * 20 for i in range(2)]
    sigs = [bls.Sign(sum(sks) % R, m) for m in msgs]
    out, lay, precheck = bb._miller_fast_aggregate(
        [pks, pks], msgs, sigs, None
    )
    assert out is not None and precheck[:2].all()
    rows = []
    for i in range(2):
        r, ns = lay.split(i)
        rows.append(np.stack([out[f"{ns}f.{j}"][r] for j in range(12)]))
    return np.stack(rows)


def run_rlc_bench() -> dict:
    """Returns bench.py's result dict. ``value`` is RLC items/sec at the
    largest N; ``vs_baseline`` is the RLC-over-per-item speedup at N=16
    (> 1 means the combine wins where the acceptance bar sits); the
    ``sizes`` table carries every N."""
    from ..ops import bls_backend as bb

    ns = [
        int(x)
        for x in os.environ.get("RLC_BENCH_NS", "4,16,64,256").split(",")
    ]
    reps = max(1, int(os.environ.get("RLC_BENCH_REPS", "1")))
    seed = int(os.environ.get("RLC_BENCH_SEED", "7"))
    rng = random.Random(seed)

    base = _build_f_rows(seed)

    def rlc_once(fs):
        bits = bb._rlc_scalars(fs.shape[0], rng)
        coeffs = bb._rlc_combine_vm(fs, bits)
        ok = bb._final_exp_is_one(coeffs)
        assert ok, "rlc combined check failed on valid items"

    sizes = {}
    for n in ns:
        fs = base[np.arange(n) % base.shape[0]]
        # warmup pays assembly + XLA compile for both contenders' shapes
        got = bb._finalize_per_item(fs)
        assert got.all(), "per-item finalization failed on valid items"
        rlc_once(fs)

        per_item_s = min(
            _timed(lambda: bb._finalize_per_item(fs)) for _ in range(reps)
        )
        rlc_s = min(_timed(lambda: rlc_once(fs)) for _ in range(reps))
        sizes[n] = {
            "per_item_items_per_s": round(n / per_item_s, 2),
            "rlc_items_per_s": round(n / rlc_s, 2),
            "rlc_speedup": round(per_item_s / rlc_s, 3),
        }

    n_gate = 16 if 16 in sizes else max(sizes)
    n_top = max(sizes)
    return dict(
        metric="RLC vs per-item final exponentiation (items/sec)",
        value=sizes[n_top]["rlc_items_per_s"],
        vs_baseline=sizes[n_gate]["rlc_speedup"],
        mode="rlc",
        n=n_top,
        gate_n=n_gate,
        chunk=bb._rlc_chunk_max(),
        final=bb._rlc_final_mode(),
        reps=reps,
        sizes={str(k): v for k, v in sorted(sizes.items())},
    )


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
