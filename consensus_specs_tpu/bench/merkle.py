"""`bench.py --mode merkle` / `make merkle-bench`: the Merkleization race.

Three cells, each the native batched plane vs the pure-python oracle on
IDENTICAL inputs with bit-identity checked per cell (the ``ok`` flags
feed tools/bench_compare.py's "MERKLE DIVERGED" state gate; the
throughput numbers are report-only):

- ``merkle[state_cold]``       — full altair BeaconState
  (CONSENSUS_SPECS_TPU_MERKLE_VALIDATORS registry) hash_tree_root from a
  fresh ``decode_bytes`` (cold caches) — the column-batched plane's
  headline: one native call per tree level instead of ~9 hashlib calls
  per validator.
- ``merkle[state_incremental]`` — per-block re-root: a block's state
  delta (touched validators + one deposit append) against the warm
  incremental layer cache vs a from-scratch pure-python rebuild —
  blocks/sec, the O(log N · changed) dirty-set bar.
- ``merkle[proof_world]``       — the proof plane's consumer number:
  per-slot ``build_update_artifact`` (+sign) on cold states through the
  native plane vs forced-python, same states.

Modes are forced through ``merkle/levels.forced_mode`` so one process
measures both sides; ``merkle.*`` counter gauges and the
``latency[merkle_root]`` histogram ride along in the result.
"""
import os
import time

VALIDATORS_ENV = "CONSENSUS_SPECS_TPU_MERKLE_VALIDATORS"
BLOCKS_ENV = "CONSENSUS_SPECS_TPU_MERKLE_BLOCKS"
TOUCH_ENV = "CONSENSUS_SPECS_TPU_MERKLE_TOUCH"


def run_merkle_bench() -> dict:
    from ..builder import build_spec_module
    from ..lightclient.proof_tree import ProofWorld, build_update_artifact
    from ..merkle import levels as _levels
    from ..obs import latency
    from ..ops import profiling
    from ..utils.ssz.ssz_impl import hash_tree_root

    profiling.reset()
    latency.reset()
    _levels.reset_counters()

    from ..scale.registry import attesters_per_slot

    n_validators = int(os.environ.get(VALIDATORS_ENV, "16384"))
    n_blocks = max(1, int(os.environ.get(BLOCKS_ENV, "16")))
    # the per-block state delta defaults to the registry's REAL per-slot
    # attestation fan-out (n/SLOTS_PER_EPOCH — every committee of the
    # slot, the same shape the mainnet replay drives), not a made-up
    # constant; TOUCH_ENV still overrides for sweeps
    n_touch = max(1, int(os.environ.get(
        TOUCH_ENV, str(attesters_per_slot(n_validators)))))

    spec = build_spec_module("altair", "minimal")
    world = ProofWorld(spec, validators=n_validators)
    state = world.head_state(world.finalized_slot + 1)
    enc_state = state.encode_bytes()
    enc_fin = world.finalized_state.encode_bytes()

    cells = {}
    all_ok = True

    # -- merkle[state_cold]: full-state cold root ------------------------
    def cold_root(mode: str):
        with _levels.forced_mode(mode):
            fresh = spec.BeaconState.decode_bytes(enc_state)
            t0 = time.perf_counter()
            root = bytes(hash_tree_root(fresh))
            return root, time.perf_counter() - t0

    py_root, _ = cold_root("python")
    na_root, _ = cold_root("native")
    py_s = min(cold_root("python")[1] for _ in range(3))
    na_s = min(cold_root("native")[1] for _ in range(3))
    ok = py_root == na_root
    all_ok &= ok
    cells["state_cold"] = {
        "ok": bool(ok),
        "python_s": round(py_s, 5),
        "native_s": round(na_s, 5),
        "speedup": round(py_s / na_s, 2) if na_s > 0 else 0.0,
        "roots_per_sec": round(1.0 / na_s, 2) if na_s > 0 else 0.0,
        "validators": n_validators,
    }

    # -- merkle[state_incremental]: per-block re-root --------------------
    # one warm native state absorbs every block's delta through the
    # incremental cache; the python side re-roots a from-scratch decode
    # carrying the same cumulative delta (the pre-plane per-block cost)
    def apply_delta(st, b: int) -> None:
        for k in range(n_touch):
            i = (b * n_touch + k) % len(st.validators)
            st.validators[i].effective_balance = spec.Gwei(
                31 * 10**9 + b * n_touch + k)
        st.validators.append(spec.Validator(
            pubkey=spec.BLSPubkey((10**6 + b).to_bytes(48, "little")),
            effective_balance=spec.Gwei(32 * 10**9)))
        st.slot = spec.Slot(int(st.slot) + 1)

    warm = spec.BeaconState.decode_bytes(enc_state)
    with _levels.forced_mode("native"):
        hash_tree_root(warm)  # seed the caches
    nat_s = 0.0
    py_blocks_s = []
    inc_ok = True
    py_ref = spec.BeaconState.decode_bytes(enc_state)
    for b in range(n_blocks):
        apply_delta(warm, b)
        with _levels.forced_mode("native"):
            t0 = time.perf_counter()
            r_inc = bytes(hash_tree_root(warm))
            nat_s += time.perf_counter() - t0
        # oracle: same cumulative delta, cold from-scratch python re-root
        apply_delta(py_ref, b)
        with _levels.forced_mode("python"):
            fresh = spec.BeaconState.decode_bytes(py_ref.encode_bytes())
            t0 = time.perf_counter()
            r_py = bytes(hash_tree_root(fresh))
            py_blocks_s.append(time.perf_counter() - t0)
        inc_ok &= r_inc == r_py
    py_s_total = sum(py_blocks_s)
    all_ok &= inc_ok
    cells["state_incremental"] = {
        "ok": bool(inc_ok),
        "python_s_per_block": round(py_s_total / n_blocks, 5),
        "native_s_per_block": round(nat_s / n_blocks, 6),
        "speedup": round(py_s_total / nat_s, 2) if nat_s > 0 else 0.0,
        "blocks_per_sec": round(n_blocks / nat_s, 2) if nat_s > 0 else 0.0,
        "blocks": n_blocks,
        "touched_per_block": n_touch,
    }

    # -- merkle[proof_world]: artifact build+sign on cold states ---------
    def timed_build(mode: str, slot: int):
        st = world.head_state(slot)
        fin = spec.BeaconState.decode_bytes(enc_fin)
        with _levels.forced_mode(mode):
            t0 = time.perf_counter()
            art = build_update_artifact(
                spec, st, fin,
                genesis_validators_root=world.genesis_validators_root,
                sign=world.sign)
            return art, time.perf_counter() - t0

    base = world.finalized_slot + 100
    a_na, _ = timed_build("native", base)
    a_py, _ = timed_build("python", base)
    na_bs = min(timed_build("native", base + 1 + k)[1] for k in range(3))
    py_bs = min(timed_build("python", base + 1 + k)[1] for k in range(3))
    pw_ok = (bytes(a_na.state_root) == bytes(a_py.state_root)
             and a_na.finality_branch == a_py.finality_branch
             and a_na.multi_proof == a_py.multi_proof)
    all_ok &= pw_ok
    cells["proof_world"] = {
        "ok": bool(pw_ok),
        "python_s_per_slot": round(py_bs, 5),
        "native_s_per_slot": round(na_bs, 5),
        "speedup": round(py_bs / na_bs, 2) if na_bs > 0 else 0.0,
        "validators": n_validators,
    }

    _levels.export_gauges()
    lat = latency.snapshot()
    counters = dict(_levels.counters)

    inc = cells["state_incremental"]
    return dict(
        metric="incremental state re-roots/sec (native plane)",
        value=inc["blocks_per_sec"],
        vs_baseline=cells["state_cold"]["speedup"],
        unit="blocks/sec",
        mode="merkle",
        platform="cpu",
        merkle_mode=_levels.mode(),
        native_available=bool(_levels.plane_enabled()),
        validators=n_validators,
        ok=bool(all_ok),
        cold_speedup=cells["state_cold"]["speedup"],
        incremental_speedup=inc["speedup"],
        proof_world_speedup=cells["proof_world"]["speedup"],
        roots_per_sec=cells["state_cold"]["roots_per_sec"],
        blocks_per_sec=inc["blocks_per_sec"],
        merkle=cells,
        counters=counters,
        per_mode_best={
            f"merkle[{name}]": cell["speedup"] for name, cell in cells.items()
        },
        stage_latency=lat,
        profile=profiling.summary(),
    )
