"""In-window Pallas A/B: hand-tiled Montgomery-multiply kernel
(`ops/pallas_fq.py`) vs the jnp uint64 lowering of `ops/fq.mont_mul`,
on whatever device JAX resolved.

This is the measurement SURVEY §7.3 ranks as research risk #1-#2 and the
round-4 verdict asks for: it decides whether the
CONSENSUS_SPECS_TPU_PALLAS dispatch defaults on. It runs as the LAST
stage of the bench child (bench.py) because tunnel grants evaporate
between process launches (TPU_NOTES.md round-4 entry) — the same process
that lands the throughput number answers the kernel question.

Both sides are jit-wrapped identically and validated on a chained
product (each kernel consuming its own output for `iters` rounds), so a
reported ratio is backed by bit-exact agreement with the host oracle.
"""
import time


def run_pallas_ab(batch: int = 4096, iters: int = 32) -> dict:
    """Returns a dict with per-side mul/s, the pallas/u64 ratio, and
    chained-product match flags. Raises on device failure — the caller
    (bench child stage 3) turns that into a probe_error line."""
    import jax
    import numpy as np

    from ..ops import fq, pallas_fq

    xs = [(i * 0x9E3779B97F4A7C15 + 1) % fq.P for i in range(batch)]
    a = np.stack([fq.to_mont_int(x) for x in xs])
    b = np.stack([fq.to_mont_int((x * 7 + 3) % fq.P) for x in xs])
    da, db = jax.device_put(a), jax.device_put(b)

    chain_want = xs[0]
    b0 = (xs[0] * 7 + 3) % fq.P
    for _ in range(iters):
        chain_want = chain_want * b0 % fq.P

    def side(fn):
        f = jax.jit(fn)
        t0 = time.time()
        f(da, db).block_until_ready()
        compile_s = time.time() - t0
        t0 = time.time()
        out = da
        for _ in range(iters):
            out = f(out, db)
        out.block_until_ready()
        run_s = time.time() - t0
        match = fq.from_mont_limbs(np.asarray(out)[0]) == chain_want
        return batch * iters / run_s, compile_s, match

    # baseline MUST be the u64 lowering itself — fq.mont_mul dispatches to
    # the Pallas kernel under CONSENSUS_SPECS_TPU_PALLAS=1, which would
    # silently turn this into a Pallas-vs-Pallas non-measurement
    u64_rate, u64_compile, u64_match = side(lambda u, v: fq.mont_mul_u64(u, v))
    pl_rate, pl_compile, pl_match = side(pallas_fq.mont_mul)

    return {
        "platform": jax.default_backend(),
        "u64_mul_per_s": round(u64_rate),
        "u64_compile_s": round(u64_compile, 1),
        "u64_chain_match": bool(u64_match),
        "pallas_mul_per_s": round(pl_rate),
        "pallas_compile_s": round(pl_compile, 1),
        "pallas_chain_match": bool(pl_match),
        "pallas_over_u64": round(pl_rate / u64_rate, 3),
    }


def run_step_ab(batch: int = 128, reps: int = 3) -> dict:
    """Whole-VM-program A/B across the three dispatch modes — '0' (u64
    scan), '1' (mont_mul-only Pallas), 'step' (fused mul+lin kernel on the
    14-bit register file, ops/pallas_step.py) — on one real assembled
    pairing program. This is the measurement that decides the production
    CONSENSUS_SPECS_TPU_PALLAS default. A mode's speedup ratio is emitted
    ONLY if its outputs matched mode '0' bit-for-bit; a mismatching mode
    reports its raw timings and match=False, never a headline ratio."""
    import os
    import time

    import numpy as np

    from __graft_entry__ import _example_program_and_inputs
    from ..ops import vm

    prog, regs, _ = _example_program_and_inputs(batch=batch)
    ins = {
        name: np.asarray(regs[..., int(r), :])
        for name, r in zip(prog.input_names, prog.input_regs)
    }

    def run_mode(value):
        old = os.environ.get("CONSENSUS_SPECS_TPU_PALLAS")
        os.environ["CONSENSUS_SPECS_TPU_PALLAS"] = value
        try:
            t0 = time.time()
            out = vm.execute(prog, ins, batch_shape=(batch,))
            compile_s = time.time() - t0
            t0 = time.time()
            for _ in range(reps):
                out = vm.execute(prog, ins, batch_shape=(batch,))
            run_s = (time.time() - t0) / reps
        finally:
            if old is None:
                os.environ.pop("CONSENSUS_SPECS_TPU_PALLAS", None)
            else:
                os.environ["CONSENSUS_SPECS_TPU_PALLAS"] = old
        return out, compile_s, run_s

    import jax

    result = {
        "platform": jax.default_backend(),
        "batch": batch,
        "n_steps": prog.n_steps,
    }
    baseline = None
    rates = {}
    matched = {}
    for mode, tag in (("0", "u64"), ("1", "mont"), ("step", "fused")):
        out, compile_s, run_s = run_mode(mode)
        if baseline is None:
            baseline = out
            match = True
        else:
            match = all(
                np.array_equal(out[k], baseline[k]) for k in baseline
            )
        result[f"{tag}_compile_s"] = round(compile_s, 1)
        result[f"{tag}_run_s"] = round(run_s, 3)
        result[f"{tag}_match"] = bool(match)
        rates[tag] = run_s
        matched[tag] = match
    for tag in ("mont", "fused"):
        if matched[tag]:  # a broken kernel never gets a headline ratio
            result[f"{tag}_over_u64"] = round(rates["u64"] / rates[tag], 3)
    return result
