"""Synthetic fork-and-gossip replay: spec-store ``get_head`` vs the
chain plane's proto-array, at growing block-tree sizes.

The spec's ``get_head`` re-derives the whole fork choice per query
(``filter_block_tree`` rescans every block's children, every descent
step re-sums latest-message balances): O(blocks² + blocks × validators)
as written. The chain plane answers the same question from a maintained
pointer. This bench replays ONE identical gossip history against both
and reports heads/sec each, per tree size — `make head-bench`'s
acceptance bar is proto-array ≥ 10x the spec path at the largest tree on
CPU (``vs_baseline`` = speedup/10 at that tree).

The replay (per tree size, epochs phase by phase on a live clock):
- a randomized fork tree over E epochs (branching parents at every slot,
  one shared crafted state — no state transitions: the thing measured is
  fork-choice maintenance, not block processing);
- attestation gossip batches whose committees/targets are real spec
  committees of the crafted state, with fault injection from
  ``serve/load.py``: ``invalid_sig`` events carry ``BAD_SIGNATURE`` (the
  service answers False — must be dropped), ``orphan`` events reference
  an epoch block withheld until mid-phase (must defer, then resolve when
  the block arrives);
- the proto path runs the REAL pipeline: ``HeadService`` +
  ``VerificationService`` over the crypto-free ``VerdictBackend``
  (batching/dedup/False-routing exercised, pairings skipped — verdicts,
  not crypto, are what fork choice consumes);
- the spec path replays the identical applied-vote sequence and calls
  ``spec.get_head`` at up to HEAD_SPEC_QUERIES sample batches (the cap
  is reported — at 1k blocks a single spec query costs ~a second);
- heads are ASSERTED equal at every spec sample point: a replay that
  diverges fails loudly instead of recording a throughput number.

``heads/sec`` is **query serving throughput**: after each applied batch,
how many ``get_head()`` answers per second the store can serve — the
question every proposal/attestation duty asks. The proto path reads the
maintained pointer (HEAD_QUERY_ROUNDS reads per batch, timed); the spec
path pays its full recompute per query. Ingestion is NOT hidden in that
number — it is reported alongside (``gossip_events_per_sec``, the
``chain.apply_batch`` latency reservoir), and the proto path's ingestion
includes the whole service round-trip the spec replay is spared.

Env knobs: HEAD_TREE_SIZES ("64,256,1024"), HEAD_EPOCHS (4),
HEAD_EVENTS_PER_EPOCH (32), HEAD_BATCH (8), HEAD_SEED (7),
HEAD_QUERY_ROUNDS (64), HEAD_INVALID_RATE (0.06), HEAD_ORPHAN_RATE
(0.06), HEAD_SPEC_QUERIES (4); SERVE_METRICS_PORT serves /metrics +
/snapshot during the largest proto replay and the JSON line records the
mid-load ``chain.*`` scrape.
"""
import os
import random
import time
from typing import Dict, List, Optional, Tuple


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, str(default)))


class _Tree:
    """A synthetic fork tree: spec BeaconBlocks over slots 1..8*E with
    randomized parents, plus the per-epoch committee tables of the one
    shared crafted state."""

    def __init__(self, spec, anchor_state, anchor_block, epochs: int,
                 n_blocks: int, rng: random.Random):
        self.spec = spec
        self.epochs = epochs
        self.anchor_root = spec.hash_tree_root(anchor_block)
        self.blocks: Dict = {self.anchor_root: anchor_block}
        self.parent: Dict = {}
        self.slot_of: Dict = {int(anchor_block.slot): [self.anchor_root]}
        self.by_epoch: List[List] = [[] for _ in range(epochs)]
        slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
        # last slot stops one short of the final epoch boundary: phase e
        # runs with the clock at slot 8*(e+1), and every epoch-e
        # attestation (slot <= 8e+7) must already be "in the past"
        total_slots = slots_per_epoch * epochs - 1
        roots_by_slot: Dict[int, List] = {0: [self.anchor_root]}
        ordered_slots = [0]
        for i in range(n_blocks):
            slot = rng.randint(1, total_slots)
            # parent: any block at a strictly earlier slot (genesis always
            # qualifies) — this is what makes the tree a fork tree
            candidates = [s for s in ordered_slots if s < slot]
            parent_slot = rng.choice(candidates)
            parent_root = rng.choice(roots_by_slot[parent_slot])
            block = spec.BeaconBlock(
                slot=slot,
                proposer_index=0,
                parent_root=parent_root,
                state_root=rng.getrandbits(256).to_bytes(32, "little"),
            )
            root = spec.hash_tree_root(block)
            if root in self.blocks:
                continue
            self.blocks[root] = block
            self.parent[root] = parent_root
            if slot not in roots_by_slot:
                roots_by_slot[slot] = []
                ordered_slots.append(slot)
            roots_by_slot[slot].append(root)
            self.by_epoch[slot // slots_per_epoch].append(root)
        self.leaves = (set(self.blocks) - {self.anchor_root}
                       - set(self.parent.values()))

        # committee tables per epoch, from the one crafted state — the
        # same committees `store_target_checkpoint_state` derives
        self.committees: Dict[Tuple[int, int], List[int]] = {}
        self.committee_count: Dict[int, int] = {}
        state = anchor_state.copy()
        for epoch in range(epochs):
            start = spec.compute_start_slot_at_epoch(spec.Epoch(epoch))
            if state.slot < start:
                spec.process_slots(state, start)
            per_slot = int(spec.get_committee_count_per_slot(
                state, spec.Epoch(epoch)))
            for s in range(int(start), int(start) + slots_per_epoch):
                self.committee_count[s] = per_slot
                for idx in range(per_slot):
                    self.committees[(s, idx)] = [
                        int(v) for v in spec.get_beacon_committee(
                            state, spec.Slot(s), spec.CommitteeIndex(idx))
                    ]

    def ancestor_at(self, root, slot: int):
        r = root
        while int(self.blocks[r].slot) > slot:
            r = self.parent[r]
        return r


class _Gossip:
    """One attestation gossip event (spec Attestation + precomputed
    committee indices + its fault tag)."""

    __slots__ = ("attestation", "indices", "fault", "block_root")

    def __init__(self, attestation, indices, fault, block_root):
        self.attestation = attestation
        self.indices = indices
        self.fault = fault
        self.block_root = block_root


def _build_gossip(spec, tree: _Tree, epoch: int, events: int,
                  rng: random.Random, plan: List[str],
                  withheld: set) -> List[_Gossip]:
    """Epoch-``epoch`` gossip: full-committee aggregates over the epoch's
    blocks. ``orphan`` events pick a withheld block when one exists."""
    from ..serve.load import BAD_SIGNATURE

    slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
    target_slot = epoch * slots_per_epoch
    pool = tree.by_epoch[epoch]
    out: List[_Gossip] = []
    if not pool:
        return out
    withheld_pool = [r for r in pool if r in withheld]
    open_pool = [r for r in pool if r not in withheld]
    for e in range(events):
        fault = plan[e]
        if fault == "orphan" and withheld_pool:
            root = rng.choice(withheld_pool)
        elif open_pool:
            root = rng.choice(open_pool)
        else:
            root = rng.choice(pool)
        block = tree.blocks[root]
        slot = int(block.slot)
        idx = rng.randrange(tree.committee_count[slot])
        committee = tree.committees[(slot, idx)]
        target_root = tree.ancestor_at(root, target_slot)
        data = spec.AttestationData(
            slot=slot,
            index=idx,
            beacon_block_root=root,
            source=spec.Checkpoint(),
            target=spec.Checkpoint(epoch=epoch, root=target_root),
        )
        bits = spec.Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](
            [1] * len(committee))
        signature = (BAD_SIGNATURE if fault == "invalid_sig"
                     else (b"\x5e" + bytes(target_root)[:15]
                           + bytes(root)[:16]) * 3)
        att = spec.Attestation(data=data, aggregation_bits=bits,
                               signature=signature)
        out.append(_Gossip(att, list(committee), fault, root))
    return out


def _slot_time(spec, genesis_time: int, slot: int) -> int:
    return int(genesis_time) + slot * int(spec.config.SECONDS_PER_SLOT)


def _proto_replay(spec, anchor_state, anchor_block, tree: _Tree,
                  gossip_by_epoch, withheld_by_epoch, batch: int,
                  query_rounds: int, expose: bool):
    """The production path: HeadService + VerificationService over the
    VerdictBackend. Returns (heads per batch index, timing, summary,
    scrape record)."""
    from ..chain import HeadService
    from ..serve.load import VerdictBackend
    from ..serve.service import VerificationService
    from ..utils import bls

    backend = VerdictBackend()
    scrape: Dict[str, object] = {}
    was_active = bls.bls_active
    bls.bls_active = True  # verdicts must flow through the service
    exposition = None
    svc = VerificationService(backend=backend, max_batch=max(8, batch),
                              max_wait_ms=2.0)
    try:
        head = HeadService(spec, anchor_state, anchor_block, service=svc,
                           differential=False)
        if expose:
            port_env = (os.environ.get("SERVE_METRICS_PORT") or "").strip()
            if port_env:
                from ..obs.exposition import start_exposition

                exposition = start_exposition(
                    snapshot_fn=head.metrics.snapshot, port=int(port_env))
        shared_state = head.store.block_states[tree.anchor_root]
        heads: List[bytes] = []
        queries = 0
        query_s = 0.0
        events = 0
        scrape_thread = None

        def _scrape_midload():
            # on a HELPER thread (serve/load.py pattern): a slow or wedged
            # endpoint must never inflate the timed ingestion window — the
            # scrape still happens while the replay is live
            import urllib.request

            try:
                with urllib.request.urlopen(exposition.url("/metrics"),
                                            timeout=30) as r:
                    body = r.read().decode()
                scrape["lines"] = len(body.splitlines())
                scrape["chain_lines"] = sum(
                    1 for ln in body.splitlines()
                    if ln.startswith("consensus_specs_tpu_chain_"))
            except Exception:
                pass

        t0 = time.perf_counter()
        for epoch, gossip in enumerate(gossip_by_epoch):
            # clock to the first slot PAST the epoch (its attestations all
            # become "slot in the past"), then the epoch's open blocks
            clock_slot = (epoch + 1) * int(spec.SLOTS_PER_EPOCH)
            head.on_tick(_slot_time(spec, anchor_state.genesis_time,
                                    clock_slot))
            withheld = withheld_by_epoch[epoch]
            for root in tree.by_epoch[epoch]:
                if root not in withheld:
                    head.import_block_unchecked(tree.blocks[root],
                                                state=shared_state)
            head.resweep()
            mid = len(gossip) // 2
            for start in range(0, len(gossip), batch):
                if start <= mid < start + batch:
                    # mid-phase: the withheld blocks arrive; deferred
                    # orphan gossip must resolve on the last arrival
                    for i, root in enumerate(sorted(withheld)):
                        head.import_block_unchecked(
                            tree.blocks[root], state=shared_state,
                            resolve=(i == len(withheld) - 1))
                    if not withheld:
                        head.resweep()
                    withheld = set()
                chunk = gossip[start:start + batch]
                head.on_attestations([g.attestation for g in chunk])
                events += len(chunk)
                # the serving measurement: answer get_head against the
                # live store, query_rounds times per applied batch
                tq = time.perf_counter()
                h = None
                for _ in range(query_rounds):
                    h = head.get_head()
                query_s += time.perf_counter() - tq
                queries += query_rounds
                heads.append(bytes(h))
                if exposition is not None and scrape_thread is None:
                    import threading

                    scrape_thread = threading.Thread(
                        target=_scrape_midload, daemon=True)
                    scrape_thread.start()
        elapsed = time.perf_counter() - t0
        if scrape_thread is not None:
            scrape_thread.join(35)
        timing = {
            "queries": queries,
            "query_s": query_s,
            "events": events,
            "wall_s": elapsed,
        }
        return heads, timing, head.metrics.snapshot(), scrape
    finally:
        svc.close(timeout=30)
        if exposition is not None:
            exposition.close()
        bls.bls_active = was_active


def _spec_replay(spec, anchor_state, anchor_block, tree: _Tree,
                 gossip_by_epoch, withheld_by_epoch, batch: int,
                 proto_heads: List[bytes], max_queries: int):
    """The oracle path over the identical history: direct Store
    mutations + ``spec.get_head`` at sampled batch indices, asserted
    against the proto path's head at the same index."""
    store = spec.get_forkchoice_store(anchor_state, anchor_block)
    shared_state = store.block_states[tree.anchor_root]

    # total batch count drives the sample stride
    n_batches = sum(
        (len(g) + batch - 1) // batch for g in gossip_by_epoch if g)
    stride = max(1, n_batches // max(1, max_queries))
    deferred: List[_Gossip] = []
    batch_index = 0
    queries = 0
    query_s = 0.0

    def apply(g: _Gossip):
        att = g.attestation
        spec.update_latest_messages(store, g.indices, att)

    for epoch, gossip in enumerate(gossip_by_epoch):
        store.time = spec.uint64(_slot_time(
            spec, anchor_state.genesis_time,
            (epoch + 1) * int(spec.SLOTS_PER_EPOCH)))
        withheld = set(withheld_by_epoch[epoch])
        for root in tree.by_epoch[epoch]:
            if root not in withheld:
                store.blocks[root] = tree.blocks[root]
                store.block_states[root] = shared_state
        mid = len(gossip) // 2
        for start in range(0, len(gossip), batch):
            if start <= mid < start + batch:
                for root in sorted(withheld):
                    store.blocks[root] = tree.blocks[root]
                    store.block_states[root] = shared_state
                withheld = set()
                still = []
                for g in deferred:
                    if g.block_root in store.blocks:
                        apply(g)
                    else:
                        still.append(g)
                deferred = still
            for g in gossip[start:start + batch]:
                if g.fault == "invalid_sig":
                    continue  # the service answered False; never applied
                if g.block_root not in store.blocks:
                    deferred.append(g)
                else:
                    apply(g)
            if batch_index % stride == 0 and queries < max_queries:
                tq = time.perf_counter()
                got = bytes(spec.get_head(store))
                query_s += time.perf_counter() - tq
                assert got == proto_heads[batch_index], (
                    f"head divergence at batch {batch_index}: "
                    f"spec={got.hex()[:16]} "
                    f"proto={proto_heads[batch_index].hex()[:16]}"
                )
                queries += 1
            batch_index += 1
    return queries, query_s


def run_head_bench() -> dict:
    """Drive the replay across HEAD_TREE_SIZES; returns bench.py's result
    dict (ready for ``_emit_result``)."""
    from ..builder import build_spec_module
    from ..obs import programs as obs_programs, slo
    from ..ops import profiling
    from ..serve.load import plan_gossip_faults
    from ..test.helpers.genesis import create_genesis_state

    profiling.reset()
    obs_programs.export_gauges()
    slo.reset_global()
    # baseline checkpoint: the final slo section's burn windows measure
    # this run (an empty ring would diff the end state against itself)
    slo.global_tracker().evaluate()

    sizes = [int(s) for s in os.environ.get(
        "HEAD_TREE_SIZES", "64,256,1024").split(",") if s.strip()]
    epochs = _env_int("HEAD_EPOCHS", 4)
    events_per_epoch = _env_int("HEAD_EVENTS_PER_EPOCH", 32)
    batch = _env_int("HEAD_BATCH", 8)
    query_rounds = _env_int("HEAD_QUERY_ROUNDS", 64)
    seed = _env_int("HEAD_SEED", 7)
    invalid_rate = _env_float("HEAD_INVALID_RATE", 0.06)
    orphan_rate = _env_float("HEAD_ORPHAN_RATE", 0.06)
    spec_queries = _env_int("HEAD_SPEC_QUERIES", 4)

    spec = build_spec_module("phase0", "minimal")
    anchor_state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * (int(spec.SLOTS_PER_EPOCH) * 8),
        spec.MAX_EFFECTIVE_BALANCE)
    anchor_block = spec.BeaconBlock(state_root=anchor_state.hash_tree_root())

    trees = []
    per_mode_best: Dict[str, float] = {}
    largest: Optional[dict] = None
    for n_blocks in sizes:
        rng = random.Random(seed + n_blocks)
        tree = _Tree(spec, anchor_state, anchor_block, epochs, n_blocks, rng)
        gossip_by_epoch = []
        withheld_by_epoch = []
        for epoch in range(epochs):
            plan = plan_gossip_faults(rng, events_per_epoch,
                                      invalid_rate, orphan_rate)
            # only LEAF blocks can be withheld: a withheld interior block
            # would orphan its own descendants' imports
            pool = [r for r in tree.by_epoch[epoch] if r in tree.leaves]
            held = set(rng.sample(pool, max(1, len(pool) // 8))) \
                if pool else set()
            withheld_by_epoch.append(held)
            gossip_by_epoch.append(
                _build_gossip(spec, tree, epoch, events_per_epoch, rng,
                              plan, held))
        expose = n_blocks == max(sizes)
        heads, timing, snapshot, scrape = _proto_replay(
            spec, anchor_state, anchor_block, tree, gossip_by_epoch,
            withheld_by_epoch, batch, query_rounds, expose)
        s_queries, s_query_s = _spec_replay(
            spec, anchor_state, anchor_block, tree, gossip_by_epoch,
            withheld_by_epoch, batch, heads, spec_queries)
        proto_rate = (timing["queries"] / timing["query_s"]
                      if timing["query_s"] > 0 else 0.0)
        spec_rate = s_queries / s_query_s if s_query_s > 0 else 0.0
        speedup = proto_rate / spec_rate if spec_rate > 0 else 0.0
        entry = {
            "blocks": len(tree.blocks) - 1,
            "proto_heads_per_sec": round(proto_rate, 2),
            "spec_heads_per_sec": round(spec_rate, 4),
            "speedup": round(speedup, 2),
            "proto_queries": timing["queries"],
            # the spec path is SAMPLED (it pays a full recompute per
            # query): the cap is part of the record, never silent
            "spec_queries": s_queries,
            "heads_match": True,  # _spec_replay asserted every sample
            # ingestion is its own number, not hidden in heads/sec: the
            # proto side paid validation + the service round-trip here
            "gossip_events_per_sec": round(
                timing["events"] / timing["wall_s"], 2)
                if timing["wall_s"] > 0 else 0.0,
            "ingest_wall_s": round(timing["wall_s"], 3),
            "applied": snapshot["applied"],
            "deferred": snapshot["deferred"],
            "resolved": snapshot["resolved"],
            "dropped": snapshot["dropped"],
            "head_changes": snapshot["head_changes"],
            "reorgs": snapshot["reorgs"],
        }
        if scrape:
            entry["metrics_scrape_lines"] = scrape.get("lines", 0)
            entry["metrics_chain_lines"] = scrape.get("chain_lines", 0)
        trees.append(entry)
        per_mode_best[f"head[{entry['blocks']}]"] = round(proto_rate, 2)
        if largest is None or entry["blocks"] >= largest["blocks"]:
            largest = entry

    result = dict(
        metric="fork-choice get_head queries/sec (proto-array chain plane)",
        value=largest["proto_heads_per_sec"],
        # the acceptance bar: proto >= 10x the spec path at the largest
        # benched tree — vs_baseline 1.0 == exactly 10x
        vs_baseline=round(largest["speedup"] / 10.0, 4),
        unit="heads/sec",
        mode="head",
        blocks=largest["blocks"],
        epochs=epochs,
        events_per_epoch=events_per_epoch,
        batch=batch,
        seed=seed,
        invalid_rate=invalid_rate,
        orphan_rate=orphan_rate,
        speedup_at_largest=largest["speedup"],
        trees=trees,
        per_mode_best=per_mode_best,
        # SLO state over the replay's chain.apply_batch histogram (the
        # serve objective rides along vacuously when no serve traffic
        # ran) — the section tools/bench_compare.py gates
        slo=slo.global_tracker().bench_section(),
        profile=profiling.summary(),
    )
    if "metrics_scrape_lines" in largest:
        result["metrics_scrape_lines"] = largest["metrics_scrape_lines"]
        result["metrics_chain_lines"] = largest["metrics_chain_lines"]
    return result
