"""Epoch-replay benchmark: a mainnet-shaped epoch of signature checks
through the batched device pipeline (BASELINE config #4).

Workload shape (reference protocol constants, BASELINE.md):
  SLOTS x COMMITTEES FastAggregateVerify items of K_att signers each
  (the process_attestation hot loop, reference
  specs/phase0/beacon-chain.md:1742-1756, :719-735),
  + SLOTS sync-aggregate verifies of K_sync=512
  (altair process_sync_aggregate, specs/altair/beacon-chain.md:535-565),
  + SLOTS block-proposer verifies of K=1
  (verify_block_signature, specs/phase0/beacon-chain.md:1253-1258).

Mainnet defaults 32 x 64 x 146 cover ~300k attesting validators. Setup cost
is kept linear in the number of CHECKS, not signatures: an aggregate of
same-message signatures from keys {sk_i} equals Sign(sum sk_i mod r), so
each committee costs one G2 multiply to construct — and the whole built
check set is cached on disk keyed by its shape, so only the FIRST attempt
of a round pays it (a granted TPU window must never be spent on host-side
setup; see TPU_NOTES.md).

Env: BENCH_EPOCH_SLOTS, BENCH_EPOCH_COMMITTEES, BENCH_EPOCH_K,
BENCH_EPOCH_K_SYNC, BENCH_EPOCH_POOL (pubkey pool size), BENCH_REPS.
"""
import os
import pickle
import time

import numpy as np

from ..batch_verify import SignatureCollector
from ..utils import bls
from ..utils.bls12_381 import R

TARGET_PER_CHIP = 150_000 / 8

_CACHE_VERSION = 1


def _env_int(name, default):
    return int(os.environ.get(name, str(default)))


def _cache_path(slots, committees, k_att, k_sync, pool_size):
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    d = os.path.join(root, ".bench_cache")
    os.makedirs(d, exist_ok=True)
    name = f"epoch_v{_CACHE_VERSION}_{slots}x{committees}x{k_att}s{k_sync}p{pool_size}.pkl"
    return os.path.join(d, name)


def build_epoch_checks(slots, committees, k_att, k_sync, pool_size):
    """Synthesize the epoch's checks into a SignatureCollector (as if a
    32-block replay had just been collected). The (pubkeys, message,
    signature) triples are disk-cached by shape: they are deterministic in
    the parameters, and rebuilding them costs minutes of host-side G2
    multiplies that would otherwise eat a granted TPU window."""
    pool_size = max(pool_size, k_att, k_sync)
    path = _cache_path(slots, committees, k_att, k_sync, pool_size)
    try:
        with open(path, "rb") as f:
            triples = pickle.load(f)
        col = SignatureCollector()
        for pks, msg, sig in triples:
            col._fast_aggregate_verify(pks, msg, sig)
        return col
    except Exception:
        pass  # absent/corrupt cache: rebuild below
    col = SignatureCollector()

    privkeys = list(range(1, pool_size + 1))
    pubkeys = [bls.SkToPk(sk) for sk in privkeys]

    for slot in range(slots):
        # attestation committees: distinct message per (slot, committee)
        for c in range(committees):
            start = (slot * committees + c) % (pool_size - k_att + 1)
            ks = privkeys[start:start + k_att]
            pks = pubkeys[start:start + k_att]
            msg = b"att" + slot.to_bytes(8, "little") + c.to_bytes(8, "little") + b"\x00" * 13
            agg_sk = sum(ks) % R
            sig = bls.Sign(agg_sk, msg)
            col._fast_aggregate_verify(pks, msg, sig)
        # one sync aggregate per slot
        if k_sync > 0:
            ks = privkeys[:k_sync]
            msg = b"sync" + slot.to_bytes(8, "little") + b"\x00" * 20
            sig = bls.Sign(sum(ks) % R, msg)
            col._fast_aggregate_verify(pubkeys[:k_sync], msg, sig)
        # one proposer signature per slot
        proposer = slot % pool_size
        msg = b"blk" + slot.to_bytes(8, "little") + b"\x00" * 21
        col._fast_aggregate_verify(
            [pubkeys[proposer]], msg, bls.Sign(privkeys[proposer], msg)
        )

    try:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(
                [(c.pubkeys, c.messages, c.signature) for c in col.checks], f
            )
        os.replace(tmp, path)
    except Exception:
        pass  # cache write is an optimization only
    return col


def _seed_host_caches(col, slots, committees, k_att, k_sync, pool_size):
    """Disk-persist the limb encodings (hash-to-G2 points, decoded
    signatures/pubkeys) for this workload and seed the backend caches from
    them — the checks are deterministic in the shape, and a granted TPU
    window must not spend ~15 s re-hashing 2k messages in a cold process."""
    from ..ops import bls_backend as B

    msgs, sigs, pks = set(), set(), set()
    for c in col.checks:
        if isinstance(c.messages, (bytes, bytearray)):
            msgs.add(bytes(c.messages))
        else:  # aggregate kind: per-key message list
            msgs.update(bytes(m) for m in c.messages)
        sigs.add(bytes(c.signature))
        pks.update(bytes(p) for p in c.pubkeys)
    # the limb layout is an implementation detail of the ops package — a
    # stale file from a different limb width/count or backend revision
    # would silently seed wrong encodings into live verification caches,
    # so the fingerprint is part of the NAME (like .vm_cache entries)
    import hashlib

    from ..ops import fq

    with open(fq.__file__, "rb") as fh:
        fq_fp = hashlib.sha256(fh.read()).hexdigest()[:10]
    # fq alone defines the limb encoding — keying on the full builder
    # fingerprint would invalidate this 100+ s rebuild on every VM edit
    tag = f"_limbs_{fq.LIMB_BITS}x{fq.NUM_LIMBS}_{fq_fp}.pkl"
    path = _cache_path(slots, committees, k_att, k_sync, pool_size).replace(
        ".pkl", tag
    )
    try:
        with open(path, "rb") as f:
            m, s, p = pickle.load(f)
        if msgs <= set(m) and sigs <= set(s) and pks <= set(p):
            # spot-verify one entry of EACH cache against a fresh
            # recompute before trusting the file: the fq fingerprint in
            # the name can't see layout changes in bls_backend's sig/pk
            # encoders, and the key-superset check can't see values
            for loaded, live, compute in (
                (m, msgs, B._message_limbs_compute),
                (s, sigs, B._signature_limbs_compute),
                (p, pks, B._pubkey_limbs_compute),
            ):
                probe = next(iter(live))
                fresh = compute(probe)
                if isinstance(fresh, ValueError) or not np.array_equal(
                    np.asarray(loaded[probe]), np.asarray(fresh)
                ):
                    raise ValueError("limb cache spot-check mismatch")
            B._MSG_CACHE.update(m)
            B._SIG_CACHE.update(s)
            B._PK_CACHE.update(p)
            return
    except Exception:
        pass  # absent/corrupt: rebuild below
    B.prewarm_host_caches(list(msgs), list(sigs), list(pks))
    # the pool fills what it can (it no-ops on single-core hosts like the
    # build container); compute the remainder serially so the persisted
    # cache is COMPLETE — this runs offline, never inside a TPU window
    for m in msgs:
        if m not in B._MSG_CACHE:
            B._message_limbs(m)
    for sg in sigs:
        if sg not in B._SIG_CACHE:
            try:
                B._signature_limbs(sg)
            except ValueError:
                pass  # invalid sigs aren't cached (by design)
    for pk in pks:
        if pk not in B._PK_CACHE:
            try:
                B._pubkey_limbs(pk)
            except ValueError:
                pass
    try:
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            pickle.dump(
                (
                    {k: v for k, v in B._MSG_CACHE.items() if k in msgs},
                    {k: v for k, v in B._SIG_CACHE.items() if k in sigs},
                    {k: v for k, v in B._PK_CACHE.items() if k in pks},
                ),
                f,
            )
        os.replace(tmp, path)
    except Exception:
        pass  # cache write is an optimization only


def run_epoch_replay(emit_partial=None) -> dict:
    """Run the epoch workload; returns the final result dict.

    ``emit_partial``, if given, is called with an in-progress result dict
    after setup, after the warmup (compile-inclusive timing), and after
    every rep — so a TPU window that dies mid-run still leaves the best
    number obtained so far on stdout (TPU_NOTES.md failure mode 3)."""
    import jax

    platform = jax.default_backend()
    on_cpu = platform == "cpu"

    # CPU fallback keeps the epoch SHAPE but shrinks the axes so a number
    # still lands within the bench deadline; the TPU run uses mainnet scale
    slots = _env_int("BENCH_EPOCH_SLOTS", 2 if on_cpu else 32)
    committees = _env_int("BENCH_EPOCH_COMMITTEES", 2 if on_cpu else 64)
    k_att = _env_int("BENCH_EPOCH_K", 8 if on_cpu else 146)
    k_sync = _env_int("BENCH_EPOCH_K_SYNC", 16 if on_cpu else 512)
    pool = _env_int("BENCH_EPOCH_POOL", max(k_att, k_sync))
    reps = _env_int("BENCH_REPS", 2 if on_cpu else 1)

    n_sigs = slots * (committees * k_att + k_sync + 1)

    # RLC combine (one final exponentiation for the whole epoch's checks)
    # is the epoch default; CONSENSUS_SPECS_TPU_RLC=0 reverts to per-item
    # finalization for A/B
    from ..ops.bls_backend import rlc_enabled

    rlc = rlc_enabled()

    def result(value, **extra):
        out = dict(
            value=value,
            vs_baseline=value / TARGET_PER_CHIP,
            platform=platform,
            mode="epoch",
            slots=slots,
            committees=committees,
            k=k_att,
            signatures=n_sigs,
            rlc=rlc,
        )
        out.update(extra)
        return out

    t0 = time.perf_counter()
    col = build_epoch_checks(slots, committees, k_att, k_sync, pool)
    _seed_host_caches(col, slots, committees, k_att, k_sync, max(pool, k_att, k_sync))
    setup_s = time.perf_counter() - t0

    # warmup compiles each bucket; its timing (compile-inclusive) is itself
    # a valid lower bound worth reporting if the window dies before rep 1
    t0 = time.perf_counter()
    ok = col.flush(rlc=rlc)
    warm_s = time.perf_counter() - t0
    assert ok.all(), "epoch warmup verification failed"
    if emit_partial is not None:
        emit_partial(
            result(
                n_sigs / warm_s,
                stage="warmup (compile-inclusive)",
                epoch_seconds=round(warm_s, 3),
                setup_seconds=round(setup_s, 1),
            )
        )

    rep_times = []
    for r in range(reps):
        t0 = time.perf_counter()
        ok = col.flush(rlc=rlc)
        dt = time.perf_counter() - t0
        assert ok.all(), "epoch verification failed"
        rep_times.append(dt)
        # partial lines report best-so-far (their `stage` key marks them);
        # the FINAL value below is the median of reps, matching committee
        # mode and prior rounds
        if emit_partial is not None:
            best_so_far = min(rep_times)
            emit_partial(
                result(
                    n_sigs / best_so_far,
                    stage=f"rep {r + 1}/{reps}",
                    epoch_seconds=round(best_so_far, 3),
                    setup_seconds=round(setup_s, 1),
                )
            )
    rep_times.sort()
    best = rep_times[len(rep_times) // 2] if rep_times else warm_s

    return result(
        n_sigs / best,
        epoch_seconds=round(best, 3),
        setup_seconds=round(setup_s, 1),
    )
