"""Epoch-replay benchmark: a mainnet-shaped epoch of signature checks
through the batched device pipeline (BASELINE config #4).

Workload shape (reference protocol constants, BASELINE.md):
  SLOTS x COMMITTEES FastAggregateVerify items of K_att signers each
  (the process_attestation hot loop, reference
  specs/phase0/beacon-chain.md:1742-1756, :719-735),
  + SLOTS sync-aggregate verifies of K_sync=512
  (altair process_sync_aggregate, specs/altair/beacon-chain.md:535-565),
  + SLOTS block-proposer verifies of K=1
  (verify_block_signature, specs/phase0/beacon-chain.md:1253-1258).

Mainnet defaults 32 x 64 x 146 cover ~300k attesting validators. Setup cost
is kept linear in the number of CHECKS, not signatures: an aggregate of
same-message signatures from keys {sk_i} equals Sign(sum sk_i mod r), so
each committee costs one G2 multiply to construct.

Env: BENCH_EPOCH_SLOTS, BENCH_EPOCH_COMMITTEES, BENCH_EPOCH_K,
BENCH_EPOCH_POOL (pubkey pool size), BENCH_REPS.
"""
import os
import time

from ..batch_verify import SignatureCollector
from ..utils import bls
from ..utils.bls12_381 import R

TARGET_PER_CHIP = 150_000 / 8


def _env_int(name, default):
    return int(os.environ.get(name, str(default)))


def build_epoch_checks(slots, committees, k_att, k_sync, pool_size):
    """Synthesize the epoch's checks into a SignatureCollector (as if a
    32-block replay had just been collected)."""
    pool_size = max(pool_size, k_att, k_sync)
    privkeys = list(range(1, pool_size + 1))
    pubkeys = [bls.SkToPk(sk) for sk in privkeys]

    col = SignatureCollector()
    for slot in range(slots):
        # attestation committees: distinct message per (slot, committee)
        for c in range(committees):
            start = (slot * committees + c) % (pool_size - k_att + 1)
            ks = privkeys[start:start + k_att]
            pks = pubkeys[start:start + k_att]
            msg = b"att" + slot.to_bytes(8, "little") + c.to_bytes(8, "little") + b"\x00" * 13
            agg_sk = sum(ks) % R
            sig = bls.Sign(agg_sk, msg)
            col._fast_aggregate_verify(pks, msg, sig)
        # one sync aggregate per slot
        if k_sync > 0:
            ks = privkeys[:k_sync]
            msg = b"sync" + slot.to_bytes(8, "little") + b"\x00" * 20
            sig = bls.Sign(sum(ks) % R, msg)
            col._fast_aggregate_verify(pubkeys[:k_sync], msg, sig)
        # one proposer signature per slot
        proposer = slot % pool_size
        msg = b"blk" + slot.to_bytes(8, "little") + b"\x00" * 21
        col._fast_aggregate_verify(
            [pubkeys[proposer]], msg, bls.Sign(privkeys[proposer], msg)
        )
    return col


def run_epoch_replay() -> dict:
    import jax

    platform = jax.default_backend()
    on_cpu = platform == "cpu"

    # CPU fallback keeps the epoch SHAPE but shrinks the axes so a number
    # still lands within the bench deadline; the TPU run uses mainnet scale
    slots = _env_int("BENCH_EPOCH_SLOTS", 2 if on_cpu else 32)
    committees = _env_int("BENCH_EPOCH_COMMITTEES", 2 if on_cpu else 64)
    k_att = _env_int("BENCH_EPOCH_K", 8 if on_cpu else 146)
    k_sync = _env_int("BENCH_EPOCH_K_SYNC", 16 if on_cpu else 512)
    pool = _env_int("BENCH_EPOCH_POOL", max(k_att, k_sync))
    reps = _env_int("BENCH_REPS", 2)

    t0 = time.perf_counter()
    col = build_epoch_checks(slots, committees, k_att, k_sync, pool)
    setup_s = time.perf_counter() - t0

    n_sigs = slots * (committees * k_att + k_sync + 1)

    # warmup compile of each bucket
    ok = col.flush()
    assert ok.all(), "epoch warmup verification failed"

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        ok = col.flush()
        dt = time.perf_counter() - t0
        assert ok.all(), "epoch verification failed"
        times.append(dt)
    times.sort()
    best = times[len(times) // 2]

    sigs_per_sec = n_sigs / best
    return dict(
        value=sigs_per_sec,
        vs_baseline=sigs_per_sec / TARGET_PER_CHIP,
        platform=platform,
        mode="epoch",
        slots=slots,
        committees=committees,
        k=k_att,
        signatures=n_sigs,
        epoch_seconds=round(best, 3),
        setup_seconds=round(setup_s, 1),
    )
