"""`bench.py --mode sim` / `make sim-bench`: the scenario-matrix run.

Drives every named simnet scenario (consensus_specs_tpu/sim/scenarios.py)
through the deterministic discrete-event runner and reports the matrix:
per-scenario convergence (the differential gate's verdict, non-strict —
a diverging scenario is recorded, the bench line still lands), partition
heal-to-convergence latency, per-node ``get_head`` serving rates, fault
mix, and fabric traffic counters. Per-node flight-recorder journals dump
to ``CONSENSUS_SPECS_TPU_SIM_FLIGHT_DIR`` when set (the CI failure
artifact).

The JSON line's ``value`` is total gossip deliveries/sec of wall time
across the matrix (the throughput of the whole simulated cluster —
every delivery runs the real validate/verify/apply pipeline on its
node); ``vs_baseline`` is the converged share of the matrix (1.0 = every
scenario's gate green — the acceptance bar). The ``sim`` section
(scenario -> converged + heal latency) is what ``tools/bench_compare.py``
gates round over round: a previously-converging scenario that stops
converging fails the round outright.

Env knobs: CONSENSUS_SPECS_TPU_SIM_SCENARIOS (csv filter, default all),
CONSENSUS_SPECS_TPU_SIM_NODES (default 4), CONSENSUS_SPECS_TPU_SIM_SEED
(default 7), CONSENSUS_SPECS_TPU_SIM_EVENTS (attestation aggregates per
epoch), CONSENSUS_SPECS_TPU_SIM_FLIGHT_DIR (journal directory).
"""
import os
import time
from typing import Dict, Optional

from ..sim.runner import (
    FLIGHT_DIR_ENV,
    NODES_ENV,
    SCENARIOS_ENV,
    SEED_ENV,
    build_world,
    run_scenario,
)
from ..sim.scenarios import SCENARIOS, get_scenario


def _selected_scenarios():
    raw = (os.environ.get(SCENARIOS_ENV) or "").strip()
    if not raw:
        return list(SCENARIOS.values())
    return [get_scenario(name.strip()) for name in raw.split(",")
            if name.strip()]


def run_sim_bench() -> dict:
    """Run the matrix; returns bench.py's result dict (ready for
    ``_emit_result``)."""
    from ..obs import programs as obs_programs
    from ..ops import profiling

    profiling.reset()
    obs_programs.export_gauges()

    nodes = int(os.environ.get(NODES_ENV, "4"))
    seed = int(os.environ.get(SEED_ENV, "7"))
    flight_dir: Optional[str] = (os.environ.get(FLIGHT_DIR_ENV)
                                 or "").strip() or None
    scenarios = _selected_scenarios()

    spec, anchor_state, anchor_block = build_world()
    matrix: Dict[str, dict] = {}
    sim_section: Dict[str, dict] = {}
    total_deliveries = 0
    total_wall = 0.0
    converged = 0
    t0 = time.perf_counter()
    for scenario in scenarios:
        report = run_scenario(
            scenario, spec=spec, anchor_state=anchor_state,
            anchor_block=anchor_block, seed=seed, nodes=nodes,
            strict=False, flight_dir=flight_dir)
        entry = report.to_dict()
        matrix[scenario.name] = entry
        sim_section[scenario.name] = {
            "converged": report.converged,
            "heal_to_convergence_s": report.heal_to_convergence_s,
            "nodes": report.nodes,
            "deliveries": report.deliveries,
        }
        total_deliveries += report.deliveries
        total_wall += report.wall_s
        converged += bool(report.converged)
    elapsed = time.perf_counter() - t0

    value = total_deliveries / total_wall if total_wall > 0 else 0.0
    per_mode_best = {
        f"sim[{name}]": round(
            entry["deliveries"] / matrix[name]["wall_s"], 2)
        for name, entry in sim_section.items()
        if matrix[name]["wall_s"] > 0
    }
    result = dict(
        metric="simnet gossip deliveries/sec across the scenario matrix",
        value=round(value, 2),
        # the acceptance bar is the matrix itself: 1.0 == every scenario
        # converged through the differential gate
        vs_baseline=round(converged / len(scenarios), 4) if scenarios else 0.0,
        unit="deliveries/sec",
        mode="sim",
        nodes=nodes,
        seed=seed,
        scenarios=len(scenarios),
        converged=converged,
        diverged=[name for name, e in sim_section.items()
                  if not e["converged"]],
        deliveries=total_deliveries,
        elapsed_s=round(elapsed, 3),
        heads_per_sec_min=min(
            (m["heads_per_sec_min"] for m in matrix.values()), default=0.0),
        sim=sim_section,
        matrix=matrix,
        per_mode_best=per_mode_best,
        profile=profiling.summary(),
    )
    if flight_dir:
        result["flight_dir"] = flight_dir
    return result
