"""`bench.py --mode soak` / `make soak-bench`: the long-horizon telemetry
soak (ISSUE 19).

Every bench so far measures minutes of behavior; the failure modes the
telemetry plane exists for — participation decay, finality-lag growth,
deferral-buffer creep, reorg churn — only show up over HOURS of slots.
This mode runs a thousand-plus-slot simnet scenario against the REAL
fleet deployment shape (`sim/fleet_replay.py` wiring: every node's
signature checks cross a process boundary to verdict-mode workers) and
records the whole telemetry plane while it runs:

- a per-node `chain/health.py` ledger observes every simulated slot past
  a short warm-up (the runner's ``slot_hook`` fires once per crossed
  slot boundary, quiet stretches included);
- a sim-clock `obs/timeseries.py` store samples the live gauge surface
  (the ``health[<node>].*`` family among them) once per slot at base
  resolution, downsampling into the coarser rings exactly as the
  wall-clock stores do;
- the workers' own wall-clock TSDBs and span rings ship home through
  the snapshot protocol and merge in the router's aggregator — the
  stitched Chrome trace at the end carries spans from every worker pid
  joined to router-side flows by matching flow ids.

The health verdict is `chain/health.evaluate_gate` over the worst-case
aggregate across nodes; `tools/bench_compare.py` turns a green round
that later reports red into "HEALTH DIVERGED". One honesty note on the
finality bound: the simnet imports blocks by crafted-state ingress
(`import_block_unchecked` — no per-block state transitions), so the
finalized checkpoint stays at the genesis anchor and the lag grows one
slot per slot BY CONSTRUCTION. The bound passed here is therefore the
horizon itself: it asserts the lag never exceeds the clock (monotone,
rate <= 1 slot/slot — a regression or clock runaway still fails), while
participation and unexplained reorgs are the live gates. The soak
scenario keeps the canonical chain linear (``fork_rate=0``) so "zero
unexplained reorgs" is a REAL claim: any reorg in a fork-free run is a
fork-choice bug, not noise.

Scheduling honors the scenario library's invariant: every periodic
partition forms early in epoch ``e`` and heals early in epoch ``e+1``,
so no node ever ages an aggregate past the fork-choice's two-epoch
acceptance window.

Env knobs: CONSENSUS_SPECS_TPU_SOAK_EPOCHS (default 128 — 1023 slots on
the minimal preset's 8-slot epochs; `make soak-smoke` sets 26),
CONSENSUS_SPECS_TPU_SOAK_WORKERS (default 2),
CONSENSUS_SPECS_TPU_SOAK_DIR (artifact directory, default
``soak_artifacts``), plus the simnet's NODES/SEED envs.
"""
import json
import os
import sys
import time
from dataclasses import replace
from typing import Dict, List, Optional

from ..chain import health
from ..obs import timeseries, tracing
from ..sim.fabric import PartitionWindow
from ..sim.fleet_replay import FleetVerdictBackend
from ..sim.runner import NODES_ENV, SEED_ENV, build_world, run_scenario
from ..sim.scenarios import get_scenario

EPOCHS_ENV = "CONSENSUS_SPECS_TPU_SOAK_EPOCHS"
WORKERS_ENV = "CONSENSUS_SPECS_TPU_SOAK_WORKERS"
DIR_ENV = "CONSENSUS_SPECS_TPU_SOAK_DIR"

# health rows start after the vote tables warm up: proto-array
# participation counts validators with a latest message, which takes the
# first committees a couple of epochs to cover — gating those ramp slots
# would fail every run on an artifact of "the chain just started"
WARMUP_EPOCHS = 2


def soak_scenario(epochs: int, *, nodes: int = 4,
                  slots_per_epoch: int = 8):
    """The long-horizon scenario: `partition_heal`'s shape repeated.

    A two-way split forms early in epoch ``e`` and heals early in epoch
    ``e+1`` every eighth epoch (first at epoch 3, past the warm-up), on
    top of a steady 5% invalid-signature and 5% censored-aggregate diet.
    ``fork_rate=0`` keeps the canonical chain linear — see the module
    docstring for why that makes the zero-reorg gate meaningful."""
    spe = int(slots_per_epoch)
    half = nodes // 2
    windows = tuple(
        PartitionWindow(
            form_slot=float(e * spe + 2),
            heal_slot=float((e + 1) * spe + 1),
            groups=(tuple(range(half)), tuple(range(half, nodes))),
        )
        for e in range(3, epochs - 1, 8)
    )
    base = get_scenario("partition_heal")
    return replace(
        base,
        name="telemetry_soak",
        description="long-horizon soak: periodic two-way partitions over "
                    "a linear canonical chain with invalid and censored "
                    "aggregates; the health ledger observes every slot",
        nodes=nodes,
        epochs=int(epochs),
        fork_rate=0.0,
        partitions=windows,
        invalid_rate=0.05,
        censor_rate=0.05,
    )


def _trace_join_stats(path: str) -> Dict:
    """Read the stitched Chrome trace back and count the acceptance
    evidence: worker pids carrying spans, and flow ids that appear both
    as a worker-side START ("s" on a worker pid) and a router-side
    FINISH ("f")."""
    with open(path) as f:
        doc = json.load(f)
    worker_pids = set()
    starts_by_pid: Dict[int, set] = {}
    finishes = set()
    for ev in doc.get("traceEvents", ()):
        pid = int(ev.get("pid", 0))
        if pid >= tracing.WORKER_PID_BASE and ev.get("ph") == "X":
            worker_pids.add(pid)
        if ev.get("ph") == "s":
            starts_by_pid.setdefault(pid, set()).add(int(ev["id"]))
        elif ev.get("ph") == "f":
            finishes.add(int(ev["id"]))
    worker_starts = set()
    for pid, ids in starts_by_pid.items():
        if pid >= tracing.WORKER_PID_BASE:
            worker_starts |= ids
    return {
        "worker_pids": sorted(worker_pids),
        "worker_flow_starts": len(worker_starts),
        "flow_joins": len(worker_starts & finishes),
    }


def run_soak_bench(epochs: Optional[int] = None,
                  workers: Optional[int] = None) -> dict:
    """Run the soak; returns bench.py's result dict (ready for
    ``_emit_result``)."""
    from ..obs import programs as obs_programs
    from ..ops import profiling
    from ..serve.fleet import FleetRouter

    # the telemetry plane under test must be ON: the TSDB env arms the
    # worker samplers (inherited through spawn), the trace env arms the
    # node-side and worker-side tracers whose spans the stitch joins
    os.environ.setdefault(timeseries.TS_ENV, "1")
    os.environ.setdefault(tracing.TRACE_ENV, "1")
    profiling.reset()
    obs_programs.export_gauges()

    epochs = int(os.environ.get(EPOCHS_ENV, "128") if epochs is None
                 else epochs)
    workers = int(os.environ.get(WORKERS_ENV, "2") if workers is None
                  else workers)
    nodes = int(os.environ.get(NODES_ENV, "4"))
    seed = int(os.environ.get(SEED_ENV, "7"))
    out_dir = (os.environ.get(DIR_ENV) or "soak_artifacts").strip()
    os.makedirs(out_dir, exist_ok=True)

    spec, anchor_state, anchor_block = build_world()
    sps = int(spec.config.SECONDS_PER_SLOT)
    spe = int(spec.SLOTS_PER_EPOCH)
    scenario = soak_scenario(epochs, nodes=nodes, slots_per_epoch=spe)
    total_slots = spe * epochs - 1
    warmup_slots = WARMUP_EPOCHS * spe
    # the spec's fork choice (`filter_block_tree`, `get_ancestor`) recurses
    # once per block of tree depth, and the simnet anchors finality at
    # genesis so the store never prunes: by the end of the horizon the
    # tree is `total_slots` deep and the interpreter's default 1000-frame
    # limit dies mid-soak. ~3 frames per recursion level (call + the two
    # comprehensions), plus headroom for the caller stack.
    needed = 4 * (total_slots + 4 * spe) + 2000
    if sys.getrecursionlimit() < needed:
        sys.setrecursionlimit(needed)
    disruption = [(w.form_slot, w.heal_slot + 2.0)
                  for w in scenario.partitions]

    # the health time series lives on the SIMULATED clock: one base
    # sample per slot (interval = the slot time), capacity sized so the
    # whole horizon is retained at base resolution — the soak artifact
    # is the full history, not the trailing window
    store = timeseries.TimeSeriesStore(
        interval_s=float(sps), capacity=total_slots + 256)
    ledgers: Dict[str, health.HealthLedger] = {}
    hook_slots = [0]

    def slot_hook(slot: int, sim_nodes: List) -> None:
        hook_slots[0] = slot
        if not ledgers:
            for node in sim_nodes:
                ledgers[node.name] = health.HealthLedger(
                    node.head, node=node.name)
        if slot > warmup_slots:
            expect = any(a <= slot <= b for a, b in disruption)
            for node in sim_nodes:
                ledgers[node.name].observe_slot(
                    slot=slot, expect_reorgs=expect)
        store.export_gauges()
        store.sample(now=float(slot) * sps)

    router = FleetRouter(
        workers=workers, backend="verdict",
        env={"SERVE_MAX_WAIT_MS": "2",
             timeseries.TS_ENV: "1",
             tracing.TRACE_ENV: "1"})
    t0 = time.perf_counter()
    try:
        report = run_scenario(
            scenario, spec=spec, anchor_state=anchor_state,
            anchor_block=anchor_block, seed=seed, nodes=nodes,
            strict=False,
            backend_factory=lambda name: FleetVerdictBackend(router, name),
            slot_hook=slot_hook)
        snaps = router.poll_snapshots()
        per_worker = {
            label: {
                "pid": snap.get("pid"),
                "submits": snap["extra"]["serve"]["submits"],
                "cache_hits": snap["extra"]["serve"]["cache_hits"],
                "batches": snap["extra"]["serve"]["batches"],
            }
            for label, snap in sorted(snaps.items())
        }
        trace_path = os.path.join(out_dir, "soak_trace.json")
        router.dump_trace(trace_path)
        fleet_ts_path = os.path.join(out_dir, "fleet_timeseries.json")
        with open(fleet_ts_path, "w") as f:
            json.dump(router.timeseries_doc(), f, sort_keys=True)
    finally:
        router.close()
    wall_s = time.perf_counter() - t0

    ts_path = os.path.join(out_dir, "soak_timeseries.jsonl")
    store.dump_jsonl(ts_path)
    joins = _trace_join_stats(trace_path)

    per_node = {name: led.summary() for name, led in sorted(ledgers.items())}
    aggregate = health.aggregate_summaries(list(per_node.values()))
    gate = health.evaluate_gate(
        aggregate,
        participation_floor=health.DEFAULT_PARTICIPATION_FLOOR,
        # see the module docstring: the simnet anchors finality at
        # genesis, so the bound is the horizon — lag must never exceed
        # the clock (the final ticks run the hook a few slots past the
        # last scripted slot, hence the epoch of margin)
        finality_lag_max_slots=total_slots + 4 * spe,
        max_unexplained_reorgs=0)

    slots = hook_slots[0]
    value = slots / wall_s if wall_s > 0 else 0.0
    return dict(
        metric="simulated slots soaked per second of wall time "
               "(health ledger + TSDB sampling every slot, fleet-routed "
               "verification)",
        value=round(value, 2),
        # the acceptance bar: 1.0 == the health gate held over the whole
        # horizon on every node
        vs_baseline=1.0 if gate["ok"] else 0.0,
        unit="slots/sec",
        mode="soak",
        nodes=nodes,
        seed=seed,
        epochs=epochs,
        slots=slots,
        warmup_slots=warmup_slots,
        converged=report.converged,
        deliveries=report.deliveries,
        elapsed_s=round(wall_s, 3),
        health=dict(
            gate=gate,
            aggregate=aggregate,
            per_node=per_node,
            slots_observed=aggregate["slots_observed"],
            warmup_slots=warmup_slots,
        ),
        soak=dict(
            scenario=scenario.name,
            partitions=len(scenario.partitions),
            timeseries=dict(
                samples=store.samples,
                evicted=store.evicted,
                interval_s=float(sps),
                path=ts_path,
            ),
            trace=dict(path=trace_path, **joins),
            fleet_timeseries_path=fleet_ts_path,
            fleet=dict(
                workers=sorted(snaps),
                routed=router.requests,
                per_worker=per_worker,
            ),
        ),
        per_mode_best={"soak[slots]": float(slots)},
        profile=profiling.summary(),
    )
