"""Prep-only microbenchmark: batched input codec vs per-item host prep.

Measures exactly the front-door cost the codec plane (ops/codec.py) was
built to kill: decode+KeyValidate of N pubkeys, decode+subgroup-check of
N signatures, and hash-to-G2 of N messages — once through the per-item
pure-Python compute functions (`ops/bls_backend._*_limbs_compute`, the
cache-miss fallback) and once through the batched codec entry points
(`codec.pubkey_limbs_batch` / `signature_limbs_batch` /
`message_limbs_batch`). No pairing work on either side: this isolates the
codec win that `bench.py --mode serve` reports as prep_ms_per_flush.

Setup (constructing N valid points via oracle scalar multiplies) is
excluded from the timed regions. Knobs: CODEC_ITEMS (default 64),
CODEC_SEED. Run via `make codec-bench` (CPU-forced, so the codec's
raw-int host fallback is what gets measured — the acceptance bar is
beating the per-item path at >= 64-item batches on plain CPU).
"""
import os
import time
from typing import Dict, List


def _build_inputs(n: int, seed: int):
    """N distinct pubkeys / signatures / messages (one scalar multiply
    each — setup stays linear and outside the timed window)."""
    import hashlib

    from ..utils import bls12_381 as O

    pks: List[bytes] = []
    sigs: List[bytes] = []
    msgs: List[bytes] = []
    for i in range(n):
        k = (
            int.from_bytes(
                hashlib.sha256(b"codec-bench%d:%d" % (seed, i)).digest(),
                "big",
            )
            % O.R
        ) or 1
        pks.append(O.g1_to_bytes(O.ec_mul(O.G1_GEN, k)))
        sigs.append(O.g2_to_bytes(O.ec_mul(O.G2_GEN, k)))
        msgs.append(hashlib.sha256(b"codec-msg%d:%d" % (seed, i)).digest())
    return pks, sigs, msgs


def run_codec_bench() -> dict:
    """Returns bench.py's result dict; value is batched-codec items/sec
    over all three kinds, vs_baseline is the speedup over the per-item
    path (>1 means the codec wins)."""
    from ..ops import bls_backend, codec

    n = int(os.environ.get("CODEC_ITEMS", "64"))
    seed = int(os.environ.get("CODEC_SEED", "7"))
    pks, sigs, msgs = _build_inputs(n, seed)

    # per-item path (the cache-miss fallback the codec replaces)
    per_item: Dict[str, float] = {}
    t0 = time.perf_counter()
    for pk in pks:
        bls_backend._pubkey_limbs_compute(pk)
    per_item["pk"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    for s in sigs:
        bls_backend._signature_limbs_compute(s)
    per_item["sig"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    for m in msgs:
        bls_backend._message_limbs_compute(m)
    per_item["msg"] = time.perf_counter() - t0

    batched: Dict[str, float] = {}
    t0 = time.perf_counter()
    codec.pubkey_limbs_batch(pks)
    batched["pk"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    codec.signature_limbs_batch(sigs)
    batched["sig"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    codec.message_limbs_batch(msgs, bls_backend.DST)
    batched["msg"] = time.perf_counter() - t0

    total_items = 3 * n
    per_item_s = sum(per_item.values())
    batched_s = sum(batched.values())
    speedup = per_item_s / batched_s if batched_s else 0.0
    return dict(
        metric="codec prep items/sec (batched input codec, all kinds)",
        value=total_items / batched_s if batched_s else 0.0,
        vs_baseline=round(speedup, 4),  # here: speedup over per-item prep
        mode="codec",
        items_per_kind=n,
        device_path=codec._use_device(),
        per_item_items_per_sec=round(
            total_items / per_item_s if per_item_s else 0.0, 2
        ),
        speedup=round(speedup, 4),
        per_kind_speedup={
            k: round(per_item[k] / batched[k], 4) if batched[k] else 0.0
            for k in per_item
        },
        per_item_ms={k: round(1e3 * v, 2) for k, v in per_item.items()},
        batched_ms={k: round(1e3 * v, 2) for k, v in batched.items()},
    )
