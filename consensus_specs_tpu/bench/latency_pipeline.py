"""`bench.py --mode latency`: the end-to-end gossip→head latency matrix.

ROADMAP item 5's acceptance run: the ``gossip_to_head_p99`` number must
be measured ADVERSARIALLY — under simnet's ``latency_skew`` (one laggard
node on ~20x links, heavy deferral churn) and ``lossy_links`` (15%
i.i.d. loss with anti-entropy recovery) scenarios — and the
deadline-aware flush scheduler must demonstrably lower it against the
classic size-OR-deadline baseline. Each scenario therefore runs three
times through the full per-node HeadService+VerificationService stacks:

- **baseline**: the classic flush rule (``max_wait_ms`` alone bounds the
  batching wait — every lone gossip item eats the full window);
- **deadline**: one shared :class:`~..serve.service.SlotClock` arms the
  slot-budget rule on every node — a flush fires as soon as the most
  urgent queued item's remaining slot budget minus the live downstream
  p99 (``obs/latency.downstream_p99_s``) would otherwise be blown;
- **speculative**: deadline flushing PLUS speculative head application
  (``CONSENSUS_SPECS_TPU_SPECULATE`` semantics): the head reflects a
  batch before its verdicts return, so gossip→head additionally stops
  paying the signature wait; invalid-signature traffic in the scenarios
  exercises the rollback path for real.

The JSON line's ``latency`` section carries one row per scenario —
``ok`` (converged AND the deadline-mode p99 meets the declared
``gossip_to_head_p99`` objective), the three p99s, and the improvement
flag — which ``tools/bench_compare.py`` gates round over round
("LATENCY SLO VIOLATED" when a previously-ok scenario flips). The
``slo`` section evaluates the declared objective over the EXACT merge of
the deadline-mode histograms (the same merge algebra the fleet uses).

Env knobs: LATENCY_SCENARIOS (csv, default "latency_skew,lossy_links"),
LATENCY_MAX_WAIT_MS (40), LATENCY_SLOT_MS (20), LATENCY_NODES,
LATENCY_SEED, LATENCY_EVENTS (events/epoch override).
"""
import os
import time
from typing import Dict, Optional

from ..obs import latency as obs_latency
from ..obs import slo
from ..ops import profiling
from ..serve.service import SlotClock
from ..sim.runner import FLIGHT_DIR_ENV, build_world, run_scenario
from ..sim.scenarios import get_scenario

MODES = ("baseline", "deadline", "speculative")


def _run_one(scenario_name: str, mode: str, *, world, seed: int,
             nodes: Optional[int], events: Optional[int],
             wait_ms: float, slot_ms: float,
             flight_dir: Optional[str]) -> Dict:
    """One (scenario, mode) run from a clean metric slate; returns the
    per-run row plus the detached gossip_to_head histogram snapshot (so
    the caller can merge across runs without re-observing)."""
    spec, anchor_state, anchor_block = world
    profiling.reset()
    obs_latency.reset()

    service_kwargs: Dict = {"max_wait_ms": wait_ms, "max_batch": 8}
    head_kwargs: Dict = {}
    if mode != "baseline":
        # ONE slot grid shared by every node — the network-wide slot
        # boundary a real deployment schedules against
        service_kwargs["slot_clock"] = SlotClock(slot_ms / 1e3)
    if mode == "speculative":
        head_kwargs["speculative"] = True

    t0 = time.perf_counter()
    report = run_scenario(
        get_scenario(scenario_name), spec=spec, anchor_state=anchor_state,
        anchor_block=anchor_block, seed=seed, nodes=nodes,
        events_per_epoch=events, strict=False,
        flight_dir=flight_dir, query_rounds=32,
        service_kwargs=service_kwargs, head_kwargs=head_kwargs)
    wall_s = time.perf_counter() - t0

    hists = profiling.latency_histograms()
    h = hists.get(obs_latency.GOSSIP_TO_HEAD_LABEL)
    summary = h.summary() if h is not None else {}
    per_node = report.per_node or {}
    row = {
        "converged": bool(report.converged),
        "error": report.error,
        "n": int(summary.get("n", 0)),
        "p50_ms": summary.get("p50_ms", 0.0),
        "p99_ms": summary.get("p99_ms", 0.0),
        "max_ms": summary.get("max_ms", 0.0),
        "deadline_flushes": sum(
            int(v.get("deadline_flushes", 0)) for v in per_node.values()),
        "speculative_applied": sum(
            int(v.get("speculative_applied", 0)) for v in per_node.values()),
        "rollbacks": sum(
            int(v.get("rollbacks", 0)) for v in per_node.values()),
        "applied": sum(int(v.get("applied", 0)) for v in per_node.values()),
        "wall_s": round(wall_s, 3),
    }
    return {"row": row, "hist": h}


def run_latency_bench() -> dict:
    """The scenario × flush-policy matrix; returns bench.py's result dict
    (ready for ``_emit_result``)."""
    from ..obs import programs as obs_programs

    profiling.reset()
    obs_programs.export_gauges()
    slo.reset_global()

    scenario_names = [
        tok.strip() for tok in os.environ.get(
            "LATENCY_SCENARIOS", "latency_skew,lossy_links").split(",")
        if tok.strip()
    ]
    wait_ms = float(os.environ.get("LATENCY_MAX_WAIT_MS", "40"))
    slot_ms = float(os.environ.get("LATENCY_SLOT_MS", "20"))
    nodes = int(os.environ.get("LATENCY_NODES", "0")) or None
    seed = int(os.environ.get("LATENCY_SEED", "7"))
    events = int(os.environ.get("LATENCY_EVENTS", "0")) or None
    flight_dir = (os.environ.get(FLIGHT_DIR_ENV) or "").strip() or None

    objective_ms = next(
        (obj["threshold_s"] * 1e3 for obj in slo.declared_objectives()
         if obj["name"] == "gossip_to_head_p99"), 1_000.0)

    world = build_world()
    detail: Dict[str, Dict] = {}
    section: Dict[str, Dict] = {}
    deadline_hists = []
    for name in scenario_names:
        rows = {}
        for mode in MODES:
            out = _run_one(name, mode, world=world, seed=seed, nodes=nodes,
                           events=events, wait_ms=wait_ms, slot_ms=slot_ms,
                           flight_dir=flight_dir)
            rows[mode] = out["row"]
            if mode == "deadline" and out["hist"] is not None:
                deadline_hists.append(out["hist"])
        detail[name] = rows
        base, dl, spec_row = (rows["baseline"], rows["deadline"],
                              rows["speculative"])
        section[name] = {
            # the gated state: the scenario converged under every flush
            # policy, the end-to-end histogram actually filled, and the
            # deadline-mode p99 meets the declared per-slot objective
            "ok": bool(
                all(r["converged"] for r in rows.values())
                and dl["n"] > 0
                and dl["p99_ms"] <= objective_ms),
            "converged": bool(all(r["converged"] for r in rows.values())),
            "n": dl["n"],
            "p99_ms": dl["p99_ms"],
            "baseline_p99_ms": base["p99_ms"],
            "speculative_p99_ms": spec_row["p99_ms"],
            "improved": bool(dl["p99_ms"] < base["p99_ms"]),
            "deadline_flushes": dl["deadline_flushes"],
            "rollbacks": spec_row["rollbacks"],
        }

    # the declared-objective evaluation over the EXACT merge of the
    # deadline-mode histograms (the fleet merge algebra: bucket mass sums)
    merged = None
    for h in deadline_hists:
        merged = h if merged is None else merged.merge(h)
    slo_section: Dict[str, Dict] = {}
    if merged is not None:
        tracker = slo.SloTracker([
            obj for obj in slo.declared_objectives()
            if obj["name"] == "gossip_to_head_p99"])
        evaluated = tracker.evaluate(
            hists={obs_latency.GOSSIP_TO_HEAD_LABEL: merged}, export=False)
        for obj_name, e in evaluated.items():
            row = {"ok": bool(e["ok"]), "n": e["n"],
                   "objective_ms": e["objective_ms"],
                   "attained_ms": e["attained_ms"],
                   "burn_rate": e["burn_rate"]}
            if "margin" in e:
                row["margin"] = e["margin"]
            slo_section[obj_name] = row

    # the worst scenario BY DEADLINE p99, and that same scenario's
    # baseline — both numbers must come from one scenario or the ratio
    # can pair scenario A's baseline with scenario B's deadline tail
    worst_row = max(
        (row for row in section.values() if row["n"]),
        key=lambda row: row["p99_ms"], default=None)
    worst_deadline = worst_row["p99_ms"] if worst_row else 0.0
    worst_baseline = worst_row["baseline_p99_ms"] if worst_row else 0.0
    value = 1e3 / worst_deadline if worst_deadline > 0 else 0.0
    return dict(
        metric="worst-scenario gossip→head p99 under deadline-aware "
               "flushing, as 1/p99 (latency pipeline)",
        value=round(value, 2),
        # the deadline-flush win itself: baseline p99 over deadline p99
        # at the worst scenario (> 1 == the scheduler lowered the tail)
        vs_baseline=round(worst_baseline / worst_deadline, 4)
        if worst_deadline > 0 else 0.0,
        unit="1/s",
        platform="cpu",
        mode="latency",
        scenarios=scenario_names,
        max_wait_ms=wait_ms,
        slot_ms=slot_ms,
        objective_ms=objective_ms,
        worst_deadline_p99_ms=round(worst_deadline, 3),
        worst_baseline_p99_ms=round(worst_baseline, 3),
        latency=section,
        latency_detail=detail,
        slo=slo_section,
    )
