"""`bench.py --mode mainnet` / `make mainnet-bench`: the mainnet-scale
workload replay (ISSUE 20 / ROADMAP item 1).

Replays full mainnet-shape slots end-to-end over a synthetic
million-validator registry: mainnet-preset committee shuffling (64
committees/slot, ~n/2048 validators each), real index-derived pubkeys,
per-committee aggregate signatures, hierarchical aggregate-of-
aggregates verification (per-committee aggregates via the RLC combine,
committee verdicts folded to ONE final exp per slot), the pubkey plane
holding the decompressed working set under a byte budget.

Sections (the ``mainnet`` dict; ``ok`` flags feed bench_compare's
"MAINNET DIVERGED" state gate, throughput numbers are report-only):

- ``mainnet[slot_replay]``   — warm-round attestations/sec +
  final_exps_per_slot + pubkey-plane hit rate + peak RSS vs budget.
- ``mainnet[bad_committee]`` — a forced bad committee at full fan-out,
  localized exactly by bisection.
- ``mainnet[censored_sim]``  — simnet's ``censored_aggregates`` at
  mainnet committee fan-out (64 committees/slot via a scaled minimal
  world) through the STRICT convergence gate, censorship evidence
  asserted.
- ``mainnet[affinity]``      — the slot's committees routed twice
  through a real 2-worker fleet on committee-index affinity: stable
  assignment, zero moves.
"""
import os
import time

VALIDATORS_ENV = "CONSENSUS_SPECS_TPU_SCALE_VALIDATORS"
SLOTS_ENV = "CONSENSUS_SPECS_TPU_SCALE_SLOTS"
RSS_BUDGET_ENV = "CONSENSUS_SPECS_TPU_SCALE_RSS_MB"
SIM_VALIDATORS_ENV = "CONSENSUS_SPECS_TPU_SCALE_SIM_VALIDATORS"
FLEET_WORKERS_ENV = "CONSENSUS_SPECS_TPU_SCALE_FLEET_WORKERS"

_DEFAULT_VALIDATORS = 1 << 20
_DEFAULT_RSS_MB = 8192
# 2048 minimal-preset validators -> 2048/8/4 = 64 committees per slot:
# the TRUE mainnet fan-out (MAX_COMMITTEES_PER_SLOT) at sim scale
_DEFAULT_SIM_VALIDATORS = 2048


def run_mainnet_bench() -> dict:
    from ..obs import latency
    from ..ops import bls_backend, profiling
    from ..scale import hierarchy, routing
    from ..scale.pubkeys import PubkeyPlane, peak_rss_bytes
    from ..scale.registry import Registry

    profiling.reset()
    latency.reset()
    bls_backend.reset_call_counts()

    n = int(os.environ.get(VALIDATORS_ENV, str(_DEFAULT_VALIDATORS)))
    n_slots = max(1, int(os.environ.get(SLOTS_ENV, "1")))
    rss_budget_mb = float(os.environ.get(RSS_BUDGET_ENV,
                                         str(_DEFAULT_RSS_MB)))
    sim_validators = int(os.environ.get(SIM_VALIDATORS_ENV,
                                        str(_DEFAULT_SIM_VALIDATORS)))
    fleet_workers = int(os.environ.get(FLEET_WORKERS_ENV, "2"))

    sections = {}
    all_ok = True

    # -- registry + slot traffic ------------------------------------------
    t0 = time.perf_counter()
    reg = Registry(n, seed=20)
    per_slot = reg.committees_per_slot()
    committees = [reg.committees_at_slot(s) for s in range(n_slots)]
    shuffle_s = time.perf_counter() - t0
    committee_size = len(committees[0][0])

    t0 = time.perf_counter()
    slot_items = [hierarchy.committee_items(reg, slot=s)
                  for s in range(n_slots)]
    derive_s = time.perf_counter() - t0

    plane = PubkeyPlane()

    # -- mainnet[slot_replay]: cold round warms, warm round is timed ------
    cold_s = 0.0
    cold_reports = []
    for s, items in enumerate(slot_items):
        rep = hierarchy.verify_slot(items, slot=s, plane=plane)
        cold_reports.append(rep)
        cold_s += rep.verify_s
    plane_hits0, plane_misses0 = plane.hits, plane.misses

    warm_reports = []
    warm_s = 0.0
    for s, items in enumerate(slot_items):
        rep = hierarchy.verify_slot(items, slot=s, plane=plane)
        warm_reports.append(rep)
        warm_s += rep.verify_s
    atts = sum(r.attestations for r in warm_reports)
    atts_per_sec = atts / warm_s if warm_s > 0 else 0.0
    warm_hits = plane.hits - plane_hits0
    warm_misses = plane.misses - plane_misses0
    warm_hit_rate = (warm_hits / (warm_hits + warm_misses)
                     if (warm_hits + warm_misses) else 0.0)
    final_exps_per_slot = (sum(r.final_exps for r in warm_reports)
                           / len(warm_reports))
    peak_rss_mb = peak_rss_bytes() / (1 << 20)

    replay_ok = (all(r.all_valid for r in cold_reports + warm_reports)
                 and final_exps_per_slot == 1.0
                 and warm_hit_rate == 1.0
                 and plane.bytes <= plane.budget_bytes
                 and peak_rss_mb <= rss_budget_mb)
    all_ok &= replay_ok
    sections["slot_replay"] = {
        "ok": bool(replay_ok),
        "validators": n,
        "slots": n_slots,
        "committees_per_slot": per_slot,
        "committee_size": committee_size,
        "attestations_per_slot": atts // n_slots,
        "atts_per_sec": round(atts_per_sec, 1),
        "verify_s_per_slot": round(warm_s / n_slots, 3),
        "cold_verify_s_per_slot": round(cold_s / n_slots, 3),
        "final_exps_per_slot": round(final_exps_per_slot, 3),
        "pubkey_hit_rate": round(warm_hit_rate, 4),
        "pubkey_plane_mb": round(plane.bytes / (1 << 20), 1),
        "pubkey_budget_mb": round(plane.budget_bytes / (1 << 20), 1),
        "peak_rss_mb": round(peak_rss_mb, 1),
        "rss_budget_mb": rss_budget_mb,
        "registry_shuffle_s": round(shuffle_s, 3),
        "pubkey_derive_s": round(derive_s, 3),
    }

    # -- mainnet[bad_committee]: bisection localization at full fan-out ---
    bad_ci = per_slot // 2
    items_b = list(slot_items[0])
    items_b[bad_ci] = hierarchy.corrupt_item(items_b[bad_ci])
    rep_b = hierarchy.verify_slot(items_b, slot=0, plane=plane)
    bad_ok = (rep_b.bad_committees == [bad_ci] and rep_b.bisections >= 1)
    all_ok &= bad_ok
    sections["bad_committee"] = {
        "ok": bool(bad_ok),
        "planted": bad_ci,
        "localized": rep_b.bad_committees,
        "bisections": rep_b.bisections,
        "extra_final_exps": rep_b.final_exps - 1,
        "verify_s": round(rep_b.verify_s, 3),
    }

    # -- mainnet[censored_sim]: censorship resilience, strictly gated -----
    from ..sim.runner import SimDivergence, build_world, run_scenario
    from ..sim.scenarios import get_scenario

    spec, anchor_state, anchor_block = build_world(
        validators=sim_validators)
    sim_fanout = int(spec.get_committee_count_per_slot(
        anchor_state, spec.get_current_epoch(anchor_state)))
    try:
        sim_report = run_scenario(
            get_scenario("censored_aggregates"), spec=spec,
            anchor_state=anchor_state, anchor_block=anchor_block,
            strict=True)
        sim_error = None
    except SimDivergence as e:
        sim_report = None
        sim_error = str(e)
    sim_ok = (sim_report is not None and sim_report.converged
              and sim_report.censored > 0)
    all_ok &= sim_ok
    sections["censored_sim"] = {
        "ok": bool(sim_ok),
        "sim_validators": sim_validators,
        "committees_per_slot": sim_fanout,
        "censored_validators": (sim_report.censored if sim_report else 0),
        "converged": bool(sim_report.converged) if sim_report else False,
        "error": sim_error,
        "digest": sim_report.digest if sim_report else "",
    }

    # -- mainnet[affinity]: committee-affinity fleet routing --------------
    if fleet_workers > 0:
        with routing.CommitteeFleet(workers=fleet_workers,
                                    backend="verdict") as fleet:
            assign = fleet.assignment(range(per_slot))
            verdict_items = [
                ("fast_aggregate", [b"\x22" * 48],
                 b"mn%06d" % ci + b"\x00" * 24, b"\x11" * 96)
                for ci in range(per_slot)]
            rounds_ok = True
            for _ in range(2):
                rounds_ok &= all(fleet.submit_slot(verdict_items))
            aff_ok = (rounds_ok
                      and fleet.assignment(range(per_slot)) == assign
                      and fleet.affinity_moves == 0)
            spread = len(set(assign.values()))
        all_ok &= aff_ok
        sections["affinity"] = {
            "ok": bool(aff_ok),
            "workers": fleet_workers,
            "committees": per_slot,
            "workers_covered": spread,
            "moves": 0 if aff_ok else -1,
        }

    return dict(
        metric="mainnet attestations/sec (hierarchical slot fold, warm)",
        value=sections["slot_replay"]["atts_per_sec"],
        vs_baseline=sections["slot_replay"]["final_exps_per_slot"],
        unit="attestations/sec",
        mode="mainnet",
        platform="cpu",
        validators=n,
        ok=bool(all_ok),
        atts_per_sec=sections["slot_replay"]["atts_per_sec"],
        final_exps_per_slot=sections["slot_replay"]["final_exps_per_slot"],
        pubkey_hit_rate=sections["slot_replay"]["pubkey_hit_rate"],
        peak_rss_mb=sections["slot_replay"]["peak_rss_mb"],
        mainnet=sections,
        rlc_stats=dict(bls_backend.RLC_STATS),
        profile=profiling.summary(),
    )
