"""VM execution-backend microbenchmark (`make vmexec-bench`, ISSUE 13).

Races the scan INTERPRETER against the FUSED straight-line lowering
(ops/vm_compile.py) on identical assembled programs and identical random
field inputs, per (program kind, rows) cell:

  vmexec[kind,rows] -> {
    ok              fused outputs bit-identical to interpreted outputs
                    (full limb identity on every named output),
    interp_ms_row   warm interpreter wall ms / row,
    fused_ms_row    warm fused wall ms / row,
    fused_compile_s trace + XLA-compile wall seconds the fused pipeline
                    paid for this batch shape (0.0 in-process warm;
                    ~persistent-cache-hit cost on later processes),
    speedup         interp_ms_row / fused_ms_row,
  }

Cells are state-gated round over round by tools/bench_compare.py
("VMEXEC ERRORED", mirror of FINALEXP ERRORED — a kind losing its fused
backend, or the two backends disagreeing bitwise, fails the round);
the ms/row and speedup movement is report-only.

Because every warm fused cell ALSO persists its measured ms/row pair
into the program's `.vm_cache` lowering plan, running this bench is what
teaches `CONSENSUS_SPECS_TPU_VM_EXEC=auto` processes on the same machine
which backend wins each program — a later process serves fused for any
shape it warms (`vm_compile.warm_fused`/a pinned-`fused` call) without
re-measuring the interpreter first.

COLD-START CELLS (ISSUE 15). After the warm race, the bench measures
fresh-process time-to-fused-ready by spawning one CHILD per arm
(consensus_specs_tpu/bench/vmexec_cold.py), each against a FRESH
persistent-XLA-cache dir: ``cold,<kind>`` (structural dedup on; its
``ok`` additionally requires ready_s within VMEXEC_COLD_BUDGET_S —
default 180 s — so the seconds-scale claim is STATE-gated round over
round like every other vmexec cell) and ``cold_nodedup,<kind>`` (the
PR 13 one-compile-per-chunk baseline, ok = reached + bit-identical).
The headline ``cold_speedup`` is their ready_s ratio — the ISSUE 15
acceptance number (>= 5x for the 955-level g2_subgroup ladder).

Env: VMEXEC_KINDS (default "g2_subgroup,h2g_finish,hard_part_frobenius"
— a full-registry sweep costs one XLA compile per kind per rows value;
pass a comma list to resize), VMEXEC_ROWS (default "1,8"), VMEXEC_REPS
(default 2), VMEXEC_K (per-item size for the k-carrying kinds, default
2), VMEXEC_SEED (default 7), VMEXEC_COLD (1 = both cold arms, "dedup" =
skip the minutes-scale baseline arm, 0 = skip cold cells),
VMEXEC_COLD_KIND / VMEXEC_COLD_BUDGET_S for the cold probe.
"""
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

from .finalexp import _timed

DEFAULT_KINDS = "g2_subgroup,h2g_finish,hard_part_frobenius"


def _run_cold_arm(dedup: bool, timeout_s: float = None) -> dict:
    """One fresh child process against fresh persistent-XLA-cache AND
    `.vm_cache` dirs (deleted afterwards — the point is a genuinely
    cold runner for BOTH arms, assembly and plan derivation included);
    returns the child's VMEXEC_COLD_JSON payload (or an error cell).
    VMEXEC_COLD_TIMEOUT_S bounds the child (default 1800 — raise it
    along with VMEXEC_COLD_KIND for the aperiodic heavy kinds, whose
    per-chunk baseline arm can exceed half an hour)."""
    import shutil

    if timeout_s is None:
        timeout_s = float(os.environ.get("VMEXEC_COLD_TIMEOUT_S", "1800"))
    env = dict(os.environ)
    cache_dir = tempfile.mkdtemp(prefix="vmexec_cold_xla_")
    env["CONSENSUS_SPECS_TPU_XLA_CACHE"] = cache_dir
    env["CONSENSUS_SPECS_TPU_VM_CACHE"] = os.path.join(cache_dir, "vm")
    env["CONSENSUS_SPECS_TPU_VM_DEDUP"] = "1" if dedup else "0"
    env.pop("CONSENSUS_SPECS_TPU_VM_EXEC", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "consensus_specs_tpu.bench.vmexec_cold"],
            capture_output=True, text=True, env=env, timeout=timeout_s)
        for line in proc.stdout.splitlines():
            if line.startswith("VMEXEC_COLD_JSON "):
                return json.loads(line[len("VMEXEC_COLD_JSON "):])
        return {"ok": False,
                "error": f"no cold JSON (rc={proc.returncode}): "
                         f"{proc.stderr[-200:]}"}
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"timeout after {timeout_s:.0f}s"}
    except Exception as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"[:200]}
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def run_vmexec_bench() -> dict:
    import random

    from ..ops import bls_backend as bb, fq, vm, vm_compile

    kinds = [
        k for k in os.environ.get("VMEXEC_KINDS", DEFAULT_KINDS).split(",")
        if k
    ]
    rows_list = [
        int(x) for x in os.environ.get("VMEXEC_ROWS", "1,8").split(",")
        if x.strip()
    ]
    reps = max(1, int(os.environ.get("VMEXEC_REPS", "2")))
    k_items = int(os.environ.get("VMEXEC_K", "2"))
    seed = int(os.environ.get("VMEXEC_SEED", "7"))
    rng = random.Random(seed)

    from ..utils import bls12_381 as O

    section = {}
    best_speedup = 0.0
    prev_mode = os.environ.get("CONSENSUS_SPECS_TPU_VM_EXEC")
    try:
        for kind in kinds:
            try:
                k = k_items if kind in ("miller_product", "aggregate_verify",
                                        "rlc_combine") else 0
                program, _fold = bb._program(kind, k, 1)
            except Exception as e:
                for r in rows_list:
                    section[f"{kind},{r}"] = {
                        "ok": False,
                        "error": f"build: {type(e).__name__}: {e}"[:200],
                    }
                continue
            for r in rows_list:
                cell = {"ok": False}
                section[f"{kind},{r}"] = cell
                try:
                    ins = {
                        name: np.stack([
                            fq.to_mont_int(rng.randrange(O.P))
                            for _ in range(r)
                        ]) for name in program.input_names
                    }
                    bs = (r,)

                    os.environ["CONSENSUS_SPECS_TPU_VM_EXEC"] = "interp"
                    out_i = vm.execute(program, ins, batch_shape=bs)  # warm
                    interp_s = min(
                        _timed(lambda: vm.execute(program, ins,
                                                  batch_shape=bs))
                        for _ in range(reps))

                    os.environ["CONSENSUS_SPECS_TPU_VM_EXEC"] = "fused"
                    compile_s = vm_compile.warm_fused(program, bs)
                    out_f = vm.execute(program, ins, batch_shape=bs)
                    fused_s = min(
                        _timed(lambda: vm.execute(program, ins,
                                                  batch_shape=bs))
                        for _ in range(reps))

                    identical = set(out_i) == set(out_f) and all(
                        np.array_equal(np.asarray(out_i[name]),
                                       np.asarray(out_f[name]))
                        for name in out_i)
                    cell.update(
                        ok=bool(identical),
                        interp_ms_row=round(interp_s * 1e3 / r, 3),
                        fused_ms_row=round(fused_s * 1e3 / r, 3),
                        fused_compile_s=round(compile_s, 2),
                        speedup=round(interp_s / fused_s, 2)
                        if fused_s else None,
                    )
                    if not identical:
                        cell["error"] = "fused != interp (bitwise)"
                    elif fused_s:
                        best_speedup = max(best_speedup,
                                           interp_s / fused_s)
                except Exception as e:
                    cell["error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        if prev_mode is None:
            os.environ.pop("CONSENSUS_SPECS_TPU_VM_EXEC", None)
        else:
            os.environ["CONSENSUS_SPECS_TPU_VM_EXEC"] = prev_mode

    # cold-start arms (ISSUE 15): fresh child processes, fresh XLA caches
    cold_mode = os.environ.get("VMEXEC_COLD", "1")
    cold_speedup = None
    if cold_mode != "0":
        cold_kind = os.environ.get("VMEXEC_COLD_KIND", "g2_subgroup")
        dedup_cell = _run_cold_arm(dedup=True)
        # the seconds-scale budget rides the cell's ok STATE — a round
        # whose cold arm stops fitting the budget fails bench_compare
        dedup_cell["ok"] = bool(
            dedup_cell.get("ok") and dedup_cell.get("within_budget"))
        section[f"cold,{cold_kind}"] = dedup_cell
        if cold_mode != "dedup":
            base_cell = _run_cold_arm(dedup=False)
            section[f"cold_nodedup,{cold_kind}"] = base_cell
            if (dedup_cell.get("ready_s") and base_cell.get("ready_s")):
                cold_speedup = round(
                    base_cell["ready_s"] / dedup_cell["ready_s"], 2)

    return dict(
        metric="best fused-over-interp VM execution speedup (warm ms/row)",
        value=round(best_speedup, 2),
        vs_baseline=round(best_speedup, 2),
        mode="vmexec",
        kinds=kinds,
        rows=rows_list,
        reps=reps,
        chunk_steps=vm_compile.chunk_steps(),
        cold_speedup=cold_speedup,
        vmexec=section,
    )
