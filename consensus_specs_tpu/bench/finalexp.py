"""Final-exponentiation hard-part microbenchmark (`make finalexp-bench`).

Races the host-oracle HHT against every VM hard-part variant on identical
unitary rows, at rows in {1, 2, 4, 8} (FINALEXP_ROWS):

  host        exact-int oracle HHT, one element at a time (~20 ms/row on
              CPU — the route `CONSENSUS_SPECS_TPU_RLC_FINAL=auto` picks
              there);
  bit_serial  the legacy depth-bound chain (4864 padded steps at any
              fold — ISSUE 10's "~1.3 s/row" motivation);
  windowed    HHT with sliding-window ladders over depth-lean component
              cyclotomic squarings (crit ~2109);
  frobenius   the lambda-decomposed spine variant (crit ~1840, the
              width-for-depth flagship) — rows >= 2 fold onto the program
              row, so ms/row drops with pipelining.

Plus the ISSUE 13 execution-backend cells: "frobenius_fused,<rows>" re-
runs the frobenius variant under CONSENSUS_SPECS_TPU_VM_EXEC=fused (the
straight-line lowering of ops/vm_compile.py, fold-1 + batch rows) at
FINALEXP_FUSED_ROWS (default "1,8"); the `bars` gain fused_3x_<rows> —
fused must beat the interpreted frobenius cell at the same rows >= 3x.

Every VM execution's verdict must be True on the valid rows (an errored
or wrong-verdict variant marks its cells ok=false — tools/bench_compare.py
fails the round on a variant that worked last round, mirror of MESH
ERRORED; a device cell merely slower than host is report-only).

The JSON line also carries:
  crit_path   vmlint critical-path depths per variant + the ratio vs the
              legacy 4864-step chain (the >=2.5x acceptance bar);
  assembler   the bucketed-vs-legacy scheduler race on the chunk-16
              rlc_combine (ops/sec both ways, cold-assembly seconds, the
              >=4x / <=2s acceptance bars, whether the native kernel ran);
  bars        every ISSUE 10 acceptance predicate, pre-evaluated.

Env: FINALEXP_ROWS (default "1,2,4,8"), FINALEXP_REPS (default 1),
FINALEXP_SEED (default 7).
"""
import os
import time

import numpy as np


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _build_g_rows(seed: int, n: int) -> "tuple":
    """(n, 12, L) Montgomery rows of VALID unitary hard-part inputs (post
    easy part of real verification f's) + their exact flat coefficients.
    Valid rows make every variant's verdict True, so a wrong formula is an
    immediate ok=false, not a silent slow cell."""
    from ..ops import bls_backend as bb, fq
    from .rlc_final import _build_f_rows

    fs = _build_f_rows(seed)
    rows = []
    coeffs = []
    for i in range(n):
        f = [fq.from_mont_limbs(fs[i % fs.shape[0], j]) for j in range(12)]
        g = bb._easy_part_flat(f)
        assert g is not None
        coeffs.append(g)
        rows.append(np.stack([fq.to_mont_int(c) for c in g]))
    return np.stack(rows), coeffs


def run_finalexp_bench() -> dict:
    from ..ops import bls_backend as bb, vm_analysis, vmlib

    rows_list = [
        int(x)
        for x in os.environ.get("FINALEXP_ROWS", "1,2,4,8").split(",")
    ]
    reps = max(1, int(os.environ.get("FINALEXP_REPS", "1")))
    seed = int(os.environ.get("FINALEXP_SEED", "7"))

    max_rows = max(rows_list)
    g_rows, g_coeffs = _build_g_rows(seed, max_rows)

    section = {}

    def put(variant, rows, ms, ok=True, err=None):
        cell = {"ok": bool(ok), "ms_per_row": round(ms / rows, 2) if ms else None}
        if err:
            cell["error"] = str(err)[:200]
        section[f"{variant},{rows}"] = cell

    # host oracle: one exact-int HHT per row
    for r in rows_list:
        def host_all():
            for c in g_coeffs[:r]:
                assert bb._hard_part_is_one_oracle(c)
        host_all()  # warm (pure python; also validates)
        dt = min(_timed(host_all) for _ in range(reps))
        put("host", r, dt * 1e3)

    # the one canonical variant-name -> program-kind map (bls_backend owns
    # routing; the bench races exactly what production can serve)
    variants = dict(bb._HARD_PART_KINDS)
    for variant, kind in variants.items():
        for r in rows_list:
            sub = g_rows[:r]
            try:
                ok = bb._run_hard_part(sub, kind=kind)  # warm + verdict
                if not ok.all():
                    put(variant, r, 0.0, ok=False,
                        err="wrong verdict on valid rows")
                    continue
                dt = min(
                    _timed(lambda: bb._run_hard_part(sub, kind=kind))
                    for _ in range(reps)
                )
                put(variant, r, dt * 1e3)
            except Exception as e:
                put(variant, r, 0.0, ok=False, err=f"{type(e).__name__}: {e}")

    # fused-lowering race cells (ISSUE 13): the frobenius hard part run
    # as a BACKEND race on the identical fold-1 program — the scan
    # interpreter ("frobenius_interp1,<rows>") vs the fused straight-line
    # lowering ("frobenius_fused,<rows>"), rows riding the batch axis
    # both ways (under pinned `fused`, _fold_for collapses to 1: the
    # straight-line stream has no idle lanes for folding to reclaim).
    # The >=3x acceptance bars below compare this pair; the production-
    # route comparison (fused vs the FOLDED interp cells above, the
    # _FinalExpBatcher shape) is reported as fused_vs_pipelined — on the
    # 2-core container the fold-8 interpreter keeps a 1.6x edge at 8
    # rows, which is exactly why `auto` routes on measured ms/row per
    # machine instead of pinning a winner. First fused call per shape
    # pays the one-time trace+XLA compile (persistent-cached across
    # processes) outside the timed reps.
    fused_rows = [
        int(x)
        for x in os.environ.get("FINALEXP_FUSED_ROWS", "1,8").split(",")
        if x and int(x) <= max_rows
    ]
    # these cells DECIDE the fused_3x bars, so the warm-floor estimate
    # needs a tighter min than the report-only cells above: single-row
    # fused wall time jitters ~25% on the 2-core container (min-of-1
    # measured 2.86x on a program whose min-of-5 ratio is 3.7x) —
    # FINALEXP_REPS still raises it further
    race_reps = max(3, reps)
    prev_exec = os.environ.get("CONSENSUS_SPECS_TPU_VM_EXEC")
    try:
        for variant, mode in (("frobenius_interp1", "interp"),
                              ("frobenius_fused", "fused")):
            os.environ["CONSENSUS_SPECS_TPU_VM_EXEC"] = mode
            for r in fused_rows:
                sub = g_rows[:r]
                try:
                    ok = bb._run_hard_part(
                        sub, kind=variants["frobenius"], fold=1)
                    if not ok.all():
                        put(variant, r, 0.0, ok=False,
                            err="wrong verdict on valid rows")
                        continue
                    dt = min(
                        _timed(lambda: bb._run_hard_part(
                            sub, kind=variants["frobenius"], fold=1))
                        for _ in range(race_reps)
                    )
                    put(variant, r, dt * 1e3)
                except Exception as e:
                    put(variant, r, 0.0, ok=False,
                        err=f"{type(e).__name__}: {e}")
    finally:
        if prev_exec is None:
            os.environ.pop("CONSENSUS_SPECS_TPU_VM_EXEC", None)
        else:
            os.environ["CONSENSUS_SPECS_TPU_VM_EXEC"] = prev_exec

    # vmlint critical paths (fold-1 shapes), vs the legacy padded chain
    legacy_padded = 4864
    crit = {}
    for variant, kind in variants.items():
        rep = vm_analysis.analyze_prog(
            vmlib.BUILDERS[kind](0, 1), name=kind,
            w_mul=bb.W_MUL, w_lin=bb.W_LIN,
            pad_steps_to=bb.PAD_STEPS, pad_regs_to=bb._pow2(64))
        crit[variant] = rep["cost"]["critical_path"]
    best_crit = min(crit["windowed"], crit["frobenius"])
    crit_section = dict(crit, legacy_padded=legacy_padded,
                        best_ratio=round(legacy_padded / best_crit, 2))

    # assembler race: bucketed (+ native kernel when built) vs legacy list
    # scheduling on the chunk-16 rlc_combine — the .vm_cache-miss stall
    from ..ops import vm as vm_mod

    prog = vmlib.build_rlc_combine(16, 1)
    n_ops = len(prog.ops)
    shape = dict(w_mul=bb.W_MUL, w_lin=bb.W_LIN,
                 pad_steps_to=bb.PAD_STEPS, pad_regs_to=bb._pow2(64))
    new_s = min(
        _timed(lambda: prog.assemble(annotate=False, **shape))
        for _ in range(2)
    )
    legacy_s = _timed(lambda: prog.assemble_legacy(**shape))
    assembler = {
        "ops": n_ops,
        "new_s": round(new_s, 3),
        "legacy_s": round(legacy_s, 3),
        "new_ops_per_s": round(n_ops / new_s, 0),
        "legacy_ops_per_s": round(n_ops / legacy_s, 0),
        "speedup": round(legacy_s / new_s, 2),
        "native_kernel": vm_mod._NATIVE_SCHED is not None,
    }

    # acceptance predicates (ISSUE 10)
    def ms(variant, r):
        cell = section.get(f"{variant},{r}")
        return cell["ms_per_row"] if cell and cell["ok"] else None

    base_1row = ms("bit_serial", 1)
    pipelined = [
        ms(v, r)
        for v in ("windowed", "frobenius")
        for r in rows_list
        if r >= 2 and ms(v, r)
    ]
    best_pipelined = min(pipelined) if pipelined else None
    bars = {
        "depth_2_5x": legacy_padded >= 2.5 * best_crit,
        "ms_per_row_3x": bool(
            base_1row and best_pipelined
            and base_1row >= 3.0 * best_pipelined),
        "assembler_4x": assembler["speedup"] >= 4.0,
        "cold_assembly_2s": new_s <= 2.0,
    }
    # ISSUE 13 acceptance: the fused lowering must beat the interpreter
    # on the IDENTICAL fold-1 program at the same rows by >= 3x (the
    # backend race — same program, same inputs, bit-identical outputs).
    # fused_vs_pipelined reports the production-route ratio against the
    # folded interp cells (report-only: the fold-8 interpreter is a
    # different program the auto route keeps available).
    fused_vs_pipelined = {}
    for r in fused_rows:
        bars[f"fused_3x_{r}"] = bool(
            ms("frobenius_interp1", r) and ms("frobenius_fused", r)
            and ms("frobenius_interp1", r)
            >= 3.0 * ms("frobenius_fused", r))
        if ms("frobenius", r) and ms("frobenius_fused", r):
            fused_vs_pipelined[str(r)] = round(
                ms("frobenius", r) / ms("frobenius_fused", r), 2)

    best_rows = max(
        (r for r in rows_list
         if any(ms(v, r) for v in ("windowed", "frobenius"))),
        default=max_rows)
    best_ms = min(
        (ms(v, best_rows) for v in ("windowed", "frobenius")
         if ms(v, best_rows)),
        default=None)
    value = 1e3 / best_ms if best_ms else 0.0  # rows/sec, higher-better
    return dict(
        metric="hard-part finalization rows/sec (best VM variant, "
               f"{best_rows} pipelined rows)",
        value=round(value, 2),
        vs_baseline=round(
            (base_1row / best_pipelined) / 3.0, 3
        ) if (base_1row and best_pipelined) else 0.0,
        mode="finalexp",
        rows=rows_list,
        reps=reps,
        final=bb._rlc_final_mode(),
        finalexp=section,
        crit_path=crit_section,
        assembler=assembler,
        bars=bars,
    )
