"""`bench.py --mode proofs` / `make proof-bench`: the read-path bench.

Replays 10^4-10^6 simulated light clients against the proof plane: R
distinct per-slot artifacts (R = CONSENSUS_SPECS_TPU_PROOF_SLOTS head
slots in one altair ``ProofWorld``) behind one ``ProofService``, hit by
N = CONSENSUS_SPECS_TPU_PROOF_CLIENTS client requests round-robin over
the slots from CONSENSUS_SPECS_TPU_PROOF_WORKERS request threads. The
content address ``(slot, state_root)`` makes exactly R requests builds
and every other request a cache hit or in-flight join, so the steady-
state hit rate is (N - R) / N — the >= 0.99 acceptance bar at N >= 10^4.

Every artifact is FULLY verified before the timed window: the spec's
``validate_light_client_update`` (both branches, period math, and the
sync-committee FastAggregateVerify), the combined multiproof, and the
finality branch against an independently re-Merkleized state root
(fresh ``decode_bytes`` round trip — no warm-cache reuse on the verify
side). Inside the window every request still pays the client-side
``is_valid_merkle_branch`` finality check on the artifact it received —
served bytes are never trusted unchecked.

The signature verdict routes through the real ``VerificationService``
(CONSENSUS_SPECS_TPU_PROOF_BACKEND: "oracle" = pure-python pairing per
update — real crypto, no XLA compiles; "verdict" = the crypto-free
``VerdictBackend`` for quick runs). The ``proofs`` JSON section
(per-shape ``verified`` + proofs/sec + hit rate + p99) is what
``tools/bench_compare.py`` state-gates round over round ("PROOFS
DIVERGED" when a previously-verified shape stops verifying).
"""
import os
import time
from concurrent.futures import ThreadPoolExecutor

CLIENTS_ENV = "CONSENSUS_SPECS_TPU_PROOF_CLIENTS"
SLOTS_ENV = "CONSENSUS_SPECS_TPU_PROOF_SLOTS"
WORKERS_ENV = "CONSENSUS_SPECS_TPU_PROOF_WORKERS"
BACKEND_ENV = "CONSENSUS_SPECS_TPU_PROOF_BACKEND"
# validator-registry depth of the proved states: gives artifact build a
# realistically deep Merkle tree so the build+sign phase times the
# Merkleization plane, not an empty state
VALIDATORS_ENV = "CONSENSUS_SPECS_TPU_PROOF_VALIDATORS"


class _OracleBackend:
    """Per-item pure-python FastAggregateVerify — real pairings with no
    XLA compile bill (the PR 12 tier-budget pattern); only the R distinct
    artifact builds ever reach it."""

    def __init__(self):
        self.calls = 0
        self.items = 0

    def batch_fast_aggregate_verify(self, pubkey_sets, messages, signatures):
        from ..utils import bls

        self.calls += 1
        self.items += len(signatures)
        return [
            bool(bls.FastAggregateVerify(list(pks), bytes(msg), bytes(sig)))
            for pks, msg, sig in zip(pubkey_sets, messages, signatures)
        ]

    def batch_aggregate_verify(self, pubkey_sets, message_sets, signatures):
        from ..utils import bls

        self.calls += 1
        self.items += len(signatures)
        return [
            bool(bls.AggregateVerify(list(pks), [bytes(m) for m in msgs],
                                     bytes(sig)))
            for pks, msgs, sig in zip(pubkey_sets, message_sets, signatures)
        ]


def run_proofs_bench() -> dict:
    """Run the proof-serving replay; returns bench.py's result dict."""
    from ..builder import build_spec_module
    from ..lightclient.proof_tree import (
        ProofWorld, build_update_artifact, floorlog2, subtree_index,
        verify_artifact,
    )
    from ..lightclient.serve_proofs import ProofService
    from ..obs import latency
    from ..ops import profiling
    from ..serve.service import VerificationService

    profiling.reset()
    latency.reset()

    n_clients = int(os.environ.get(CLIENTS_ENV, "20000"))
    n_slots = max(1, int(os.environ.get(SLOTS_ENV, "8")))
    n_workers = max(1, int(os.environ.get(WORKERS_ENV, "4")))
    backend_kind = os.environ.get(BACKEND_ENV, "oracle").strip() or "oracle"
    n_validators = int(os.environ.get(VALIDATORS_ENV, "16384"))

    spec = build_spec_module("altair", "minimal")
    world = ProofWorld(spec, validators=n_validators)
    if backend_kind == "verdict":
        from ..serve.load import VerdictBackend

        backend = VerdictBackend()
    else:
        backend = _OracleBackend()
    verifier = VerificationService(backend, max_batch=8, max_wait_ms=1.0)
    service = ProofService(verifier=verifier)

    head_slots = [world.finalized_slot + 1 + i for i in range(n_slots)]
    states = {s: world.head_state(s) for s in head_slots}
    roots = {s: bytes(states[s].hash_tree_root()) for s in head_slots}

    def build(slot):
        return build_update_artifact(
            spec, states[slot], world.finalized_state,
            genesis_validators_root=world.genesis_validators_root,
            sign=world.sign)

    all_verified = True
    try:
        # -- the artifact build+sign phase (the Merkleization plane's
        # consumer-facing number): per-slot build_update_artifact timing
        # on COLD states (fresh decode, no warm caches), native vs the
        # forced pure-python oracle in the same round -----------------------
        from ..merkle import levels as _merkle_levels

        enc_fin = world.finalized_state.encode_bytes()

        def timed_build_sign(mode: str, slot: int) -> float:
            st = spec.BeaconState.decode_bytes(states[slot].encode_bytes())
            fin = spec.BeaconState.decode_bytes(enc_fin)
            with _merkle_levels.forced_mode(mode):
                t0 = time.perf_counter()
                build_update_artifact(
                    spec, st, fin,
                    genesis_validators_root=world.genesis_validators_root,
                    sign=world.sign)
                return time.perf_counter() - t0

        bs_native = min(timed_build_sign("native", s) for s in head_slots)
        bs_python = min(timed_build_sign("python", s) for s in head_slots)

        # -- warm + full verification of every distinct artifact ----------
        t_build = time.perf_counter()
        for s in head_slots:
            artifact = service.serve(s, roots[s], lambda s=s: build(s))
            # service-side verdict (VerificationService BLS fast path)
            all_verified &= artifact.verified is True
            # client-side: the whole spec check against an independently
            # re-Merkleized root (fresh deserialization, cold caches)
            fresh = spec.BeaconState.decode_bytes(states[s].encode_bytes())
            verify_artifact(
                spec, artifact, world.snapshot,
                world.genesis_validators_root,
                state_root=bytes(fresh.hash_tree_root()))
        build_s = time.perf_counter() - t_build

        # -- the timed client replay --------------------------------------
        def one_request(i: int) -> bool:
            slot = head_slots[i % n_slots]
            artifact = service.serve(slot, roots[slot],
                                     lambda: build(slot))
            # every served proof is checked, not trusted: the finality
            # branch must re-hash to the requested state root
            g = artifact.finality_gindex
            ok = artifact.verified is True and spec.is_valid_merkle_branch(
                spec.Root(artifact.finalized_root),
                [spec.Bytes32(b) for b in artifact.finality_branch],
                floorlog2(g), subtree_index(g),
                spec.Root(bytes(roots[slot])))
            return bool(ok)

        t0 = time.perf_counter()
        if n_workers == 1:
            checked = sum(one_request(i) for i in range(n_clients))
        else:
            with ThreadPoolExecutor(max_workers=n_workers) as pool:
                checked = sum(pool.map(one_request, range(n_clients),
                                       chunksize=256))
        elapsed = time.perf_counter() - t0
        all_verified &= checked == n_clients
    finally:
        verifier.close(timeout=30)

    pps = n_clients / elapsed if elapsed > 0 else 0.0
    hit_rate = service.metrics.hit_rate
    service.export_gauges()
    lat = latency.snapshot()
    serve_summary = lat.get(latency.stage_label("proof_serve"), {})
    p99_ms = float(serve_summary.get("p99_ms", 0.0))

    shape = f"clients={n_clients}"
    proofs_section = {
        shape: {
            "verified": bool(all_verified),
            "proofs_per_sec": round(pps, 2),
            "hit_rate": round(hit_rate, 6),
            "p99_ms": round(p99_ms, 4),
            "clients": n_clients,
            "slots": n_slots,
            "workers": n_workers,
            "backend": backend_kind,
            "validators": n_validators,
            # per-slot artifact build+sign on cold states: the native
            # Merkleization plane vs the forced pure-python oracle
            "build_sign_s_per_slot": round(bs_native, 4),
            "build_sign_s_per_slot_python": round(bs_python, 4),
        }
    }
    return dict(
        metric="light-client proofs served/sec",
        value=round(pps, 2),
        # the acceptance bar: content-addressed steady-state hit rate
        vs_baseline=round(hit_rate, 4),
        unit="proofs/sec",
        mode="proofs",
        platform="cpu",
        clients=n_clients,
        slots=n_slots,
        workers=n_workers,
        backend=backend_kind,
        distinct_artifacts=n_slots,
        verified=bool(all_verified),
        checked_requests=int(checked),
        hit_rate=round(hit_rate, 6),
        p99_ms=round(p99_ms, 4),
        build_s=round(build_s, 3),
        build_sign_s_per_slot=round(bs_native, 4),
        build_sign_s_per_slot_python=round(bs_python, 4),
        validators=n_validators,
        elapsed_s=round(elapsed, 3),
        proofs=proofs_section,
        per_mode_best={f"proofs[{shape}]": round(pps, 2)},
        stage_latency=lat,
        service=service.snapshot(),
        profile=profiling.summary(),
    )
