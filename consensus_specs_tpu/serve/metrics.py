"""Serve-plane observability: queue depth, batch occupancy, cache hit
rate, and submit->result latency percentiles.

Everything is exported through ``ops/profiling`` (gauges +
``record_latency``) so ``profiling.summary()`` — and therefore every
bench JSON line that attaches it — carries the serving SLO numbers
without bench needing to know the service's internals.
"""
import sys
import threading
from typing import Dict, Optional

from ..obs.registry import node_label
from ..ops import profiling

# resolved lazily through sys.modules: a service wrapping a lightweight
# test/oracle backend must never pay the real backend's (jax-importing)
# module load just to read its process-global counters — if the module
# is absent, the counters are necessarily still zero
_BACKEND_MOD = __package__.rsplit(".", 1)[0] + ".ops.bls_backend"


def _backend_module():
    return sys.modules.get(_BACKEND_MOD)

LATENCY_LABEL = "serve.submit_to_result"
BATCH_LABEL = "serve.batch_flush"
PREP_LABEL = "serve.prep_flush"


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


class ServeMetrics:
    """Counters for one VerificationService instance.

    Occupancy is tracked on two axes, both of which cost real device time
    when wasted:
    - ROW occupancy: filled batch rows / padded rows (the backend rounds
      the batch axis up to a power of two);
    - LANE occupancy: actual committee keys / (rows * K bucket) (each item
      pads its key axis up to its bucket).

    ``node`` labels every exported metric (``serve[<node>].<name>``, the
    ``serve[`` dynamic family) so N service instances — one per simnet
    node — publish side by side instead of overwriting shared gauges.
    """

    def __init__(self, node: Optional[str] = None):
        self.node = node
        self._latency_label = node_label(LATENCY_LABEL, node)
        self._batch_label = node_label(BATCH_LABEL, node)
        self._prep_label = node_label(PREP_LABEL, node)
        self._queue_depth_label = node_label("serve.queue_depth", node)
        self._hit_rate_label = node_label("serve.cache_hit_rate", node)
        self._occ_rows_label = node_label("serve.occupancy_rows", node)
        self._occ_lanes_label = node_label("serve.occupancy_lanes", node)
        self._mesh_devices_label = node_label("serve.mesh_devices", node)
        self._mesh_fallbacks_label = node_label("serve.mesh_fallbacks", node)
        self._ladder_rung_label = node_label("serve.ladder_rung", node)
        self._deadline_flushes_label = node_label("serve.deadline_flushes",
                                                  node)
        self._deadline_budget_label = node_label("serve.deadline_budget_ms",
                                                 node)
        self._lock = threading.Lock()
        self.submits = 0
        self.eager = 0  # resolved at submit time by the reference's own rules
        self.cache_hits = 0
        self.inflight_joins = 0
        self.enqueued = 0
        self.batches = 0
        self.rows_filled = 0
        self.rows_padded = 0
        self.lanes_filled = 0
        self.lanes_padded = 0
        self.backend_retries = 0
        self.fallback_batches = 0
        self.fallback_items = 0
        self.queue_depth_peak = 0
        # mesh plane (ISSUE 9): devices the service's verify mesh spans
        # (0 = single-device) and how many sharded attempts fell back to
        # the single-device path (degradation-ladder rung 0)
        self.mesh_devices = 0
        self.mesh_fallbacks = 0
        # commanded degradation-ladder rung (ISSUE 11 load shedding)
        self.ladder_rung = 0
        # deadline-aware flush scheduling (ISSUE 12): flushes fired by
        # the slot-budget rule instead of size-or-deadline, and the slot
        # budget remaining (post-downstream-p99) at the latest one
        self.deadline_flushes = 0
        self.last_deadline_budget_ms = 0.0
        # prep-vs-device time split (the two pipeline stages): where a
        # flush's wall time goes — host codec prep or the device hard
        # part. device_flushes counts whole flushes (like prep_batches)
        # so the two per-flush means share a denominator shape; `batches`
        # above counts (kind, K-bucket) GROUPS, of which a flush has >= 1
        self.prep_batches = 0
        self.prep_s = 0.0
        self.device_flushes = 0
        self.device_s = 0.0
        # RLC amortization baseline: the backend's combine/bisection/
        # final-exp counters are process-global, so snapshot() reports
        # THIS service's deltas (final-exps-per-item is the headline the
        # serve bench gates on). Backend not imported yet == counters at
        # zero, so the empty baseline is exact, not an approximation.
        mod = _backend_module()
        self._rlc_base = dict(mod.RLC_STATS) if mod is not None else {}

    # -- recording hooks (service.py) --------------------------------------

    def note_submit(self) -> None:
        with self._lock:
            self.submits += 1

    def note_eager(self) -> None:
        with self._lock:
            self.eager += 1

    def note_cache_hit(self) -> None:
        with self._lock:
            self.cache_hits += 1

    def note_inflight_join(self) -> None:
        with self._lock:
            self.inflight_joins += 1

    def note_enqueued(self, queue_depth: int) -> None:
        with self._lock:
            self.enqueued += 1
            self.queue_depth_peak = max(self.queue_depth_peak, queue_depth)
        profiling.set_gauge(self._queue_depth_label, queue_depth)

    def note_prep(self, seconds: float) -> None:
        with self._lock:
            self.prep_batches += 1
            self.prep_s += seconds
        profiling.record(self._prep_label, seconds)

    def note_batch(self, n_items: int, sum_k: int, bucket: int,
                   seconds: float) -> None:
        rows = _pow2(max(1, n_items))
        with self._lock:
            self.batches += 1
            self.rows_filled += n_items
            self.rows_padded += rows
            self.lanes_filled += sum_k
            self.lanes_padded += rows * bucket
        profiling.record(self._batch_label, seconds)

    def note_device_flush(self, seconds: float) -> None:
        with self._lock:
            self.device_flushes += 1
            self.device_s += seconds

    def note_retry(self) -> None:
        with self._lock:
            self.backend_retries += 1

    def note_mesh(self, n_devices: int) -> None:
        """Record the verify mesh's device count at service construction."""
        with self._lock:
            self.mesh_devices = n_devices
        profiling.set_gauge(self._mesh_devices_label, n_devices)

    def note_ladder(self, rung: int) -> None:
        """Record the commanded degradation-ladder rung (shed control)."""
        with self._lock:
            self.ladder_rung = rung
        profiling.set_gauge(self._ladder_rung_label, rung)

    def note_deadline_flush(self, budget_ms: float) -> None:
        """One flush fired early by the slot-budget rule; ``budget_ms``
        is the slot time that remained after subtracting the observed
        downstream p99 (how close the deadline actually was)."""
        with self._lock:
            self.deadline_flushes += 1
            self.last_deadline_budget_ms = budget_ms
            count = self.deadline_flushes
        profiling.set_gauge(self._deadline_flushes_label, count)
        profiling.set_gauge(self._deadline_budget_label, round(budget_ms, 3))

    def note_mesh_fallback(self) -> None:
        with self._lock:
            self.mesh_fallbacks += 1
            count = self.mesh_fallbacks
        profiling.set_gauge(self._mesh_fallbacks_label, count)

    def note_fallback(self, n_items: int) -> None:
        with self._lock:
            self.fallback_batches += 1
            self.fallback_items += n_items

    def note_result(self, latency_s: float) -> None:
        profiling.record_latency(self._latency_label, latency_s)

    # -- derived views ------------------------------------------------------

    @property
    def hit_rate(self) -> float:
        """Share of non-eager submits answered without new backend work
        (completed-result cache hits + in-flight dedup joins)."""
        served = self.submits - self.eager
        return (self.cache_hits + self.inflight_joins) / served if served else 0.0

    @property
    def row_occupancy(self) -> float:
        return self.rows_filled / self.rows_padded if self.rows_padded else 0.0

    @property
    def lane_occupancy(self) -> float:
        return self.lanes_filled / self.lanes_padded if self.lanes_padded else 0.0

    def export_gauges(self) -> None:
        """Publish the derived ratios into profiling.summary()."""
        profiling.set_gauge(self._hit_rate_label, self.hit_rate)
        profiling.set_gauge(self._occ_rows_label, self.row_occupancy)
        profiling.set_gauge(self._occ_lanes_label, self.lane_occupancy)

    def snapshot(self) -> Dict[str, float]:
        self.export_gauges()
        lat = profiling.latency_summary().get(self._latency_label, {})
        # backend prep-plane counters (which path warmed the caches, how
        # many items degraded to serial per-item prep, pool-broken latch)
        # — process-global like the caches they describe
        bls_backend = _backend_module()
        try:
            prep_stats = dict(bls_backend.PREP_STATS)
            prep_stats["pool_broken"] = bool(bls_backend._POOL_BROKEN)
            # a counter BELOW its baseline means bls_backend.reset_rlc_stats()
            # rewound the process-global ledger after this service was
            # constructed — the delta since that reset is then exactly the
            # current value (never report negative combine counts, never
            # hide real post-reset activity)
            rlc_stats = {
                k: (cur if cur < self._rlc_base.get(k, 0)
                    else cur - self._rlc_base.get(k, 0))
                for k, cur in bls_backend.RLC_STATS.items()
            }
        except AttributeError:  # backend never imported in this process
            prep_stats = {}
            rlc_stats = {}
        with self._lock:
            prep_ms = (
                1e3 * self.prep_s / self.prep_batches
                if self.prep_batches else 0.0
            )
            device_ms = (
                1e3 * self.device_s / self.device_flushes
                if self.device_flushes else 0.0
            )
            # final exponentiations per SERVED request (non-eager submits:
            # everything the crypto plane answered, cache hits included —
            # the RLC combine AND the dedup layer both amortize, and this
            # is the number that shows it; < 0.2 at steady state is the
            # serve-bench acceptance bar)
            served = self.submits - self.eager
            final_exps_per_item = (
                rlc_stats.get("final_exps", 0) / served if served > 0 else 0.0
            )
            return {
                "submits": self.submits,
                "eager": self.eager,
                "enqueued": self.enqueued,
                "cache_hits": self.cache_hits,
                "inflight_joins": self.inflight_joins,
                "cache_hit_rate": round(self.hit_rate, 4),
                "batches": self.batches,
                "occupancy_rows": round(self.row_occupancy, 4),
                "occupancy_lanes": round(self.lane_occupancy, 4),
                "backend_retries": self.backend_retries,
                "fallback_batches": self.fallback_batches,
                "fallback_items": self.fallback_items,
                "mesh_devices": self.mesh_devices,
                "mesh_fallbacks": self.mesh_fallbacks,
                "ladder_rung": self.ladder_rung,
                "deadline_flushes": self.deadline_flushes,
                "last_deadline_budget_ms": round(
                    self.last_deadline_budget_ms, 3),
                "queue_depth_peak": self.queue_depth_peak,
                "prep_batches": self.prep_batches,
                "device_flushes": self.device_flushes,
                "prep_ms_per_flush": round(prep_ms, 3),
                "prep_ms_total": round(1e3 * self.prep_s, 3),
                "device_ms_per_flush": round(device_ms, 3),
                "device_ms_total": round(1e3 * self.device_s, 3),
                "prep": prep_stats,
                "rlc": rlc_stats,
                "final_exps_per_item": round(final_exps_per_item, 4),
                # rows the last device finalization window coalesced
                # (ISSUE 10 pipelined multi-row route; 0 = host route or
                # no device finalization yet this process) — gauge read
                # via stats_and_gauges: one lock-protected dict copy, no
                # latency-histogram merge under this snapshot's lock
                "final_exp_rows_inflight": int(
                    profiling.stats_and_gauges()[1]
                    .get("bls.final_exp_rows_inflight", 0)
                ),
                "latency": lat,
            }
