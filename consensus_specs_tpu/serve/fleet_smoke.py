"""Fleet control-plane canary (`make fleet-smoke`, CI; mesh-smoke's
fleet sibling).

Two phases, both against a REAL 2-worker fleet (`serve/worker.py`
processes, real bls backend):

1. **Verdict identity**: a batch exercising every input class — valid
   committees, a corrupted message (RLC bisection), a malformed
   signature, an infinity pubkey — submitted through the fleet router
   must answer bit-identically to (a) a single-process
   ``VerificationService`` over the same backend and (b) the pure-Python
   host oracle. The merged ``/metrics`` scrape must be the exact merge
   of the per-worker snapshots. Every worker snapshot must additionally
   report ``extra["warm_bg"]`` true — background VM warming
   (``CONSENSUS_SPECS_TPU_VM_WARM_BG``) is the fleet-worker default.

2. **Forced worker fault -> SLO-burn-driven decision**: one worker's
   backend is armed to fail, distinct committees routed to THAT worker
   are pushed under load (every flush degrades down the ladder to the
   sequential oracle — slow but correct), and the router's control loop
   must reach a shed/drain decision from the burn rates on the MERGED
   histograms. The gate demands the full reconstruction from the merged
   flight journal: the fleet decision event (worker provenance + burn
   evidence), the worker's own ``shed_rung`` ladder transition, and a
   merged-scrape delta (``fleet.sheds``/``fleet.drains`` moved, merged
   observation counts grew).

The merged journal always dumps to ``fleet_flight.jsonl`` (uploaded as a
CI artifact on failure). Out of tier-1: the workers pay real-backend
compiles (~minutes cold). Exit 0 on pass, 1 with a diagnosis otherwise.
"""
import json
import os
import sys
import time

WORKERS = 2
JOURNAL_PATH = "fleet_flight.jsonl"
# the smoke's objective: tight enough that the fault phase's full
# degradation cascade (two failed RLC attempts + two failed group
# attempts + the sequential pure-Python oracle, ~1-2 s/item even with
# warm host caches) blows it deterministically. No clean traffic exists
# after the baseline checkpoint — phase A's compile-heavy latencies are
# baselined out by the post-identity control tick, and the burn windows
# diff against that checkpoint — so only fault-phase mass can burn and
# the tightness has no false-positive surface.
SLO_OVERRIDE = "serve_p99_ms=500"


def _scrape_gauge(text: str, name: str) -> float:
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def _scrape_hist_count(text: str) -> int:
    fam = ("consensus_specs_tpu_serve_submit_to_result_"
           "latency_hist_seconds_count")
    return int(_scrape_gauge(text, fam))


def main() -> int:
    os.environ["CONSENSUS_SPECS_TPU_FLIGHT"] = "1"
    os.environ.setdefault("CONSENSUS_SPECS_TPU_FLIGHT_DUMP", JOURNAL_PATH)
    os.environ.setdefault("CONSENSUS_SPECS_TPU_SLO", SLO_OVERRIDE)
    from ..utils.jax_env import force_cpu

    force_cpu()

    from ..obs.slo import ShedPolicy
    from ..utils import bls
    from ..utils.bls12_381 import R
    from .cache import check_key
    from .fleet import FleetRouter
    from .service import VerificationService

    def committee(tag, k=1, good=True):
        sks = [7000 * tag + j + 1 for j in range(k)]
        pks = [bls.SkToPk(sk) for sk in sks]
        msg = (b"flt%03d" % tag) + b"\x00" * 26
        sig = bls.Sign(sum(sks) % R, msg)
        if not good:
            msg = b"\xff" + msg[1:]
        return ("fast_aggregate", pks, msg, sig)

    items = [
        committee(1, k=2),
        committee(2),
        committee(3, good=False),                      # corrupted: bisection
        ("fast_aggregate", [bls.SkToPk(7)], b"m" * 32,
         b"\xa0" + b"\x01" * 95),                      # undecodable signature
        ("fast_aggregate", [b"\xc0" + b"\x00" * 47],
         b"p" * 32, bls.Sign(9, b"p" * 32)),           # infinity pubkey
    ]
    want = [True, True, False, False, False]

    router = None
    try:
        # host-oracle truth (the reference's exception-swallowing rules)
        def oracle_one(kind, pks, msg, sig):
            try:
                return bool(bls.FastAggregateVerify(pks, msg, sig))
            except Exception:
                return False

        oracle = [oracle_one(*it) for it in items]
        assert oracle == want, (
            f"oracle drifted from the pinned pattern: {oracle} != {want}")

        router = FleetRouter(
            workers=WORKERS, backend="bls",
            env={"SERVE_MAX_WAIT_MS": "300",
                 "CONSENSUS_SPECS_TPU_FLIGHT": "1",
                 "CONSENSUS_SPECS_TPU_SLO":
                     os.environ["CONSENSUS_SPECS_TPU_SLO"]},
            policy=ShedPolicy(),  # stock thresholds: shed 4x, drain 32x
        )

        # -- phase 1: verdict identity ----------------------------------------
        fleet_futs = [router.submit(*it) for it in items]
        got_fleet = [bool(f.result(timeout=600)) for f in fleet_futs]

        svc = VerificationService(max_wait_ms=300.0)
        try:
            single_futs = [svc.submit(*it) for it in items]
            got_single = [bool(f.result(timeout=600)) for f in single_futs]
        finally:
            svc.close(timeout=60)
        assert got_fleet == got_single == oracle == want, (
            f"verdict identity violated: fleet={got_fleet} "
            f"single={got_single} oracle={oracle} want={want}")

        # -- background-warm default (ISSUE 20 satellite) ---------------------
        # every worker must report warm_bg armed in its snapshot extra:
        # the fleet's fresh processes background-compile cold shapes off
        # the serving path by default (worker main() setdefaults
        # CONSENSUS_SPECS_TPU_VM_WARM_BG=1; a regression here silently
        # returns the fleet to interpreter-only cold starts)
        snaps = router.poll_snapshots()
        warm_flags = {label: snap.get("extra", {}).get("warm_bg")
                      for label, snap in snaps.items()}
        assert len(warm_flags) == WORKERS and all(warm_flags.values()), (
            f"background VM warming not armed on every worker: "
            f"{warm_flags}")

        # baseline: merge the identity-phase state and checkpoint the
        # burn windows — only fault-phase mass can burn from here
        router.control_tick()
        before = router.scrape_text()
        n_before = _scrape_hist_count(before)
        assert n_before >= len(items), (
            f"merged scrape lost observations: {n_before} < {len(items)}")
        acts_before = (_scrape_gauge(before, "consensus_specs_tpu_fleet_sheds")
                       + _scrape_gauge(before,
                                       "consensus_specs_tpu_fleet_drains"))

        # -- phase 2: forced worker fault -> burn -> decision ------------------
        # distinct valid committees that all consistent-hash to ONE worker
        target, fault_items, tag = None, [], 100
        while len(fault_items) < 5 and tag < 400:
            it = committee(tag, k=1)
            label = router.route_label(check_key(*it))
            if target is None:
                target = label
            if label == target:
                fault_items.append(it)
            tag += 1
        assert len(fault_items) >= 5, "could not craft affine fault traffic"
        router.handle(target).inject_fault(calls=64, mode="fail")

        fault_futs = [router.submit(*it) for it in fault_items]
        got_fault = [bool(f.result(timeout=600)) for f in fault_futs]
        assert all(got_fault), (
            f"fault-phase verdicts wrong (oracle fallback must stay "
            f"correct): {got_fault}")

        time.sleep(1.1)  # burn-tracker checkpoint spacing
        decisions = []
        for _ in range(20):
            decisions = router.control_tick()["decisions"]
            if decisions:
                break
            time.sleep(0.5)
        assert decisions, (
            "no shed/drain decision: the burn on the merged histograms "
            f"never crossed the policy ({router.healthz()['slo']})")
        decision = decisions[0]
        assert decision["worker"] == target, (
            f"decision hit {decision['worker']}, the fault was on {target}")

        # -- reconstruction from the merged journal ---------------------------
        router.poll_snapshots()  # absorb the worker's post-shed journal
        journal = router.journal_jsonl(reason="fleet_smoke")
        events = [json.loads(line) for line in journal.splitlines()[1:]]
        fleet_decisions = [e for e in events if e["plane"] == "fleet"
                           and e["kind"] in ("shed", "drain")]
        assert fleet_decisions, "decision missing from the merged journal"
        devt = fleet_decisions[0]
        assert devt["data"].get("worker") == target
        assert devt["data"].get("burn", 0) > 0
        if devt["kind"] == "shed":
            transitions = [e for e in events if e["kind"] == "shed_rung"
                           and e.get("worker") == target]
            assert transitions, (
                "worker ladder transition missing from the merged journal")
        ladder_evidence = [e for e in events if e.get("worker") == target
                           and e["kind"].startswith("degraded")]
        assert ladder_evidence, (
            "the faulted worker's own degradation events missing from "
            "the merged journal")

        # -- merged-scrape delta ----------------------------------------------
        after = router.scrape_text()
        n_after = _scrape_hist_count(after)
        acts_after = (_scrape_gauge(after, "consensus_specs_tpu_fleet_sheds")
                      + _scrape_gauge(after,
                                      "consensus_specs_tpu_fleet_drains"))
        assert n_after >= n_before + len(fault_items), (
            f"merged scrape missed the fault traffic: {n_before} -> "
            f"{n_after}")
        assert acts_after > acts_before, (
            "fleet.sheds/fleet.drains did not move on the merged scrape")

        with open(JOURNAL_PATH, "w") as fh:
            fh.write(journal)
        print(
            f"fleet-smoke OK: {WORKERS} workers, verdicts == single-process "
            f"== oracle, fault on {target} -> {devt['kind']} "
            f"(burn {devt['data'].get('burn'):.1f}x "
            f"{devt['data'].get('objective')}/{devt['data'].get('window')}), "
            f"merged scrape {n_before} -> {n_after} observations, "
            f"journal {JOURNAL_PATH} ({len(events)} events)"
        )
        return 0
    except Exception as e:
        print(f"fleet-smoke FAIL: {type(e).__name__}: {e}")
        if router is not None:
            try:
                with open(JOURNAL_PATH, "w") as fh:
                    fh.write(router.journal_jsonl(reason="fleet_smoke_fail"))
                print(f"fleet-smoke: merged journal dumped to {JOURNAL_PATH}")
            except Exception:
                pass
        return 1
    finally:
        if router is not None:
            router.close()


if __name__ == "__main__":
    sys.exit(main())
