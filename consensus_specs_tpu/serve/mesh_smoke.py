"""Mesh-plane convergence canary (`make mesh-smoke`, CI).

One serve-plane flush on a 4-virtual-device CPU mesh, held to the STRICT
verdict-identity gate: every verdict the mesh-sharded service returns must
be bit-identical to (a) the single-device RLC path and (b) the
pure-Python host oracle, over a batch that exercises every input class —
valid committees, a corrupted message (which forces a bisection through
the failed SHARDED combine), a malformed signature, and an infinity
pubkey. The flight recorder is armed for the whole run; on failure the
journal dumps to ``mesh_flight.jsonl`` (uploaded as a CI artifact) so the
divergence post-mortem exists without a rerun, and on success the journal
must show ZERO degradation-ladder transitions — a mesh smoke that only
passes because it silently fell back to the single-device path is a fail.

Exit 0 on pass; nonzero with a diagnosis line otherwise. Kept out of
tier-1 (the sharded XLA compiles cost tens of seconds); the pytest-side
mesh coverage lives in tests/test_mesh_rlc.py.
"""
import os
import sys

MESH_DEVICES = 4


def main() -> int:
    os.environ["CONSENSUS_SPECS_TPU_MESH"] = str(MESH_DEVICES)
    os.environ["CONSENSUS_SPECS_TPU_FLIGHT"] = "1"
    os.environ.setdefault("CONSENSUS_SPECS_TPU_FLIGHT_DUMP",
                          "mesh_flight.jsonl")
    from ..utils.jax_env import force_cpu

    force_cpu(n_devices=MESH_DEVICES)

    from ..obs import flight
    from ..ops import bls_backend
    from ..utils import bls
    from ..utils.bls12_381 import R
    from .service import VerificationService

    def committee(tag, k=1, good=True):
        sks = [9000 * tag + j + 1 for j in range(k)]
        pks = [bls.SkToPk(sk) for sk in sks]
        msg = (b"smk%03d" % tag) + b"\x00" * 26
        sig = bls.Sign(sum(sks) % R, msg)
        if not good:
            msg = b"\xff" + msg[1:]
        return ("fast_aggregate", pks, msg, sig)

    items = [
        committee(1, k=2),
        committee(2),
        committee(3, good=False),                      # corrupted: bisection
        ("fast_aggregate", [bls.SkToPk(7)], b"m" * 32,
         b"\xa0" + b"\x01" * 95),                      # undecodable signature
        ("fast_aggregate", [b"\xc0" + b"\x00" * 47],
         b"p" * 32, bls.Sign(9, b"p" * 32)),           # infinity pubkey
    ]
    want = [True, True, False, False, False]

    rec = flight.global_recorder()
    try:
        # host-oracle truth (the reference's exception-swallowing rules)
        def oracle_one(kind, pks, msg, sig):
            try:
                return bool(bls.FastAggregateVerify(pks, msg, sig))
            except Exception:
                return False

        oracle = [oracle_one(*it) for it in items]
        assert oracle == want, f"oracle drifted from the pinned pattern: " \
            f"{oracle} != {want}"

        # max_wait sized so all five submits join ONE flush even on a
        # slow CI runner — a flush narrower than the mesh would route to
        # the single-device path (service._flush_mesh) and the smoke
        # would no longer exercise the sharded combine at all
        svc = VerificationService(max_wait_ms=300.0)
        assert svc.mesh_devices == MESH_DEVICES, (
            f"mesh not armed: service spans {svc.mesh_devices} devices "
            f"(CONSENSUS_SPECS_TPU_MESH={os.environ['CONSENSUS_SPECS_TPU_MESH']})"
        )
        stats_before = dict(bls_backend.RLC_STATS)
        try:
            futures = [svc.submit(*it) for it in items]
            got = [bool(f.result(timeout=600)) for f in futures]
        finally:
            svc.close(timeout=60)
        # the SERVICE flush's own counters (captured before the
        # single-device reference run below, which also bisects)
        svc_bisections = (bls_backend.RLC_STATS["bisections"]
                          - stats_before["bisections"])
        # direct evidence the flush ran SHARDED: the VM executions it
        # paid must carry sharded=True labels (narrow flushes would have
        # routed single-device and still produced matching verdicts)
        from ..ops import profiling

        stats, _gauges = profiling.stats_and_gauges()
        sharded_execs = [k for k in stats if "sharded=True" in k]

        single = [bool(r) for r in bls_backend.batch_verify_rlc(items)]
        assert got == single == oracle == want, (
            f"verdict identity violated: mesh={got} single={single} "
            f"oracle={oracle} want={want}"
        )
        assert svc.metrics.mesh_fallbacks == 0, (
            f"{svc.metrics.mesh_fallbacks} mesh fallback(s): the smoke "
            "only passed on the single-device path"
        )
        # "zero SILENT fallbacks" covers both rungs: the serve-level
        # degraded_* transitions AND the combine's host-multiply escape
        # hatch (vm/mesh_reduce_fallback — verdicts stay right, but the
        # cross-replica butterfly this smoke gates would be dead)
        degraded = [e for e in rec.events()
                    if e["kind"].startswith("degraded")
                    or e["kind"] == "mesh_reduce_fallback"]
        assert not degraded, f"degradation transitions on clean traffic: " \
            f"{[e['kind'] for e in degraded]}"
        assert svc_bisections > 0, (
            "the service flush never bisected — the corrupted item did "
            "not exercise the failed-sharded-combine path"
        )
        assert sharded_execs, (
            "no sharded VM executions recorded — the flush routed "
            "single-device and the mesh path was never exercised"
        )
        print(
            f"mesh-smoke OK: {len(items)} checks on {MESH_DEVICES} virtual "
            f"devices, verdicts == single-device == oracle, "
            f"{svc_bisections} bisection(s) through the sharded combine "
            f"({len(sharded_execs)} sharded VM execution shapes), "
            "0 fallbacks"
        )
        return 0
    except Exception as e:
        path = rec.dump(reason="mesh_smoke_failure")
        print(f"mesh-smoke FAIL: {type(e).__name__}: {e}")
        print(f"mesh-smoke: flight journal dumped to {path}")
        return 1


if __name__ == "__main__":
    sys.exit(main())
